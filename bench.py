"""Benchmark: tpu_hist booster training throughput on Higgs-like data.

Measures the north-star config (BASELINE.json configs[2]): XGBoost-style
``tree_method=tpu_hist`` training rows/sec/chip on a synthetic Higgs-shaped
dataset (28 numeric features, binary response — the real Higgs-11M is not
bundled in this zero-egress image, so shapes/statistics are simulated).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is relative to the previous round's recorded value when a
BENCH_r*.json exists, else 1.0 (the reference repo publishes no numbers —
SURVEY.md §6).
"""

import glob
import json
import os
import re
import sys
import time

import numpy as np

# Persistent compilation cache (same settings the test tier uses,
# tests/conftest.py): the unrolled boosting-block programs are large, and a
# transient tunnel hiccup during a 30s+ remote compile is the #1 way this
# bench has died.  A warm cache makes retries nearly free.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")


def _retry(fn, attempts: int = 3, label: str = "", attempt_timeout: int = 1500):
    """Run fn(), retrying on transient runtime/compile errors.

    The driver records rc=1 if the process dies; a single remote_compile
    "response body closed" blip must not turn a real 2.7M rows/sec result
    into an official crash (VERDICT r2 item 1). A SIGALRM bounds each
    attempt: a WEDGED remote backend (init that never returns) must raise
    and retry instead of silently eating the driver's whole window.
    """
    import signal

    last = None
    for i in range(attempts):
        def _alarm(signum, frame):
            raise TimeoutError(f"{label} attempt exceeded {attempt_timeout}s")

        old = signal.signal(signal.SIGALRM, _alarm)
        signal.alarm(attempt_timeout)
        try:
            return fn()
        except Exception as e:  # includes jaxlib XlaRuntimeError
            signal.alarm(0)  # disarm BEFORE the backoff sleep
            last = e
            print(f"# bench retry {i + 1}/{attempts} after {label} error: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            time.sleep(5.0 * (i + 1))
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    raise last


def synth_higgs(n_rows: int, n_feat: int = 28, seed: int = 0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    w = rng.normal(size=n_feat) / np.sqrt(n_feat)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    return X, y


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000_000))
    ntrees = int(os.environ.get("BENCH_TREES", 10))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))

    import jax

    from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
    from h2o3_tpu.models.tree.common import init_margin

    X, y = synth_higgs(n_rows)
    params = TreeParams(
        ntrees=ntrees, max_depth=max_depth, learn_rate=0.1, nbins=256,
        min_rows=1.0, reg_lambda=1.0, seed=0,
    )
    f0 = init_margin("bernoulli", y, 1)

    # warmup run at full shape: compiles the training-block executable(s);
    # the timed run below hits the jit cache
    _retry(lambda: train_boosted(X, "bernoulli", y, 1, f0, params),
           label="warmup")

    # steady-state training throughput: the timings hook separates one-time
    # host prep (binning + device transfer over the tunnel) from the on-chip
    # boosting loop, the same split the reference's benchmarks use (DMatrix
    # build excluded from the gpu_hist training timer)
    timings = {}

    def _timed():
        timings.clear()
        return train_boosted(X, "bernoulli", y, 1, f0, params, timings=timings)

    booster = _retry(_timed, label="timed-run")
    dt = timings["train_s"]

    rows_per_sec = n_rows * ntrees / dt  # row-scans per second per chip

    vs = 1.0
    for path in sorted(glob.glob("BENCH_r*.json"), reverse=True):
        try:
            with open(path) as f:
                prev = json.load(f)
            parsed = prev.get("parsed") or prev  # driver wraps under "parsed"
            if parsed.get("value"):  # skip rounds that recorded a crash
                vs = rows_per_sec / float(parsed["value"])
                break
        except Exception:
            continue

    print(json.dumps({
        "metric": "tpu_hist_train_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec (n_rows*ntrees/train_time, Higgs-shaped 28f)",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
