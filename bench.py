"""Benchmark: tpu_hist booster training throughput on Higgs-like data.

Measures the north-star config (BASELINE.json configs[2]): XGBoost-style
``tree_method=tpu_hist`` training rows/sec/chip on a synthetic Higgs-shaped
dataset (28 numeric features, binary response — the real Higgs-11M is not
bundled in this zero-egress image, so shapes/statistics are simulated).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is relative to the previous round's recorded value when a
BENCH_r*.json exists, else 1.0 (the reference repo publishes no numbers —
SURVEY.md §6).
"""

import glob
import json
import os
import re
import sys
import time

import numpy as np


def synth_higgs(n_rows: int, n_feat: int = 28, seed: int = 0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    w = rng.normal(size=n_feat) / np.sqrt(n_feat)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    return X, y


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000_000))
    ntrees = int(os.environ.get("BENCH_TREES", 10))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))

    import jax

    from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
    from h2o3_tpu.models.tree.common import init_margin

    X, y = synth_higgs(n_rows)
    params = TreeParams(
        ntrees=ntrees, max_depth=max_depth, learn_rate=0.1, nbins=256,
        min_rows=1.0, reg_lambda=1.0, seed=0,
    )
    f0 = init_margin("bernoulli", y, 1)

    # warmup run at full shape: compiles the training-block executable(s);
    # the timed run below hits the jit cache
    train_boosted(X, "bernoulli", y, 1, f0, params)

    # steady-state training throughput: the timings hook separates one-time
    # host prep (binning + device transfer over the tunnel) from the on-chip
    # boosting loop, the same split the reference's benchmarks use (DMatrix
    # build excluded from the gpu_hist training timer)
    timings = {}
    booster = train_boosted(X, "bernoulli", y, 1, f0, params, timings=timings)
    dt = timings["train_s"]

    rows_per_sec = n_rows * ntrees / dt  # row-scans per second per chip

    vs = 1.0
    prior = sorted(glob.glob("BENCH_r*.json"))
    if prior:
        try:
            with open(prior[-1]) as f:
                prev = json.load(f)
            if prev.get("value"):
                vs = rows_per_sec / float(prev["value"])
        except Exception:
            pass

    print(json.dumps({
        "metric": "tpu_hist_train_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec (n_rows*ntrees/train_time, Higgs-shaped 28f)",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
