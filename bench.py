"""Benchmark: tpu_hist booster training throughput on Higgs-like data.

Measures the north-star config (BASELINE.json configs[2]): XGBoost-style
``tree_method=tpu_hist`` training rows/sec/chip on a synthetic Higgs-shaped
dataset (28 numeric features, binary response — the real Higgs-11M is not
bundled in this zero-egress image, so shapes/statistics are simulated).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is relative to the previous round's recorded value when a
BENCH_r*.json exists, else 1.0 (the reference repo publishes no numbers —
SURVEY.md §6).

Architecture (round 4): orchestrator + worker subprocesses.

A wedged remote TPU backend blocks *inside a C call holding the GIL*, so
an in-process SIGALRM never fires (measured: a 90s alarm around
``import jax; jax.devices()`` never ran its handler).  Rounds 2 and 3 both
lost their official number to exactly this.  The only robust envelope is
external: this file, run with no args, is a pure-Python orchestrator that
never imports jax.  It

1. pre-flights the backend in a subprocess (``--probe``) under a hard
   60s wall clock — a wedged backend yields a structured
   ``{"error": "backend unreachable"}`` JSON line and exit 0, so the
   driver records a diagnosis instead of rc=124;
2. runs the real measurement in a subprocess (``--worker``) under a
   ~500s wall clock, retrying up to 3 times.  The persistent XLA compile
   cache makes each killed attempt's compilation progress durable, so
   retries resume where the last attempt died;
3. mirrors any successful result to BENCH_PARTIAL.json immediately, so a
   later crash cannot erase it.

Worst case budget: 2*60 probe + 600 + 2*500 + sleeps ≈ 29 min, inside
any plausible driver window (round 3's single 1500s attempt was not).
"""

import json
import os
import subprocess
import sys
import time

# Persistent compilation cache: the unrolled boosting-block programs are
# large, and a transient tunnel hiccup during a 30s+ remote compile is the
# #1 way this bench has died.  A warm cache makes retries nearly free, and
# makes *partial* compilation progress survive a killed attempt.
_CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": "/tmp/h2o3_tpu_jax_cache",
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.5",
    "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
}
# BENCH_FORCE_CPU (the test hook) must NOT touch the persistent cache:
# XLA:CPU AOT entries both contaminate the TPU cache and intermittently
# SIGSEGV at load (tests/conftest.py documents the hazard).  Removal (not
# just skipping the setdefault) so an externally exported cache dir can't
# reach CPU children either.
if os.environ.get("BENCH_FORCE_CPU") or "--cache-bench" in sys.argv \
        or "--parse-bench" in sys.argv or "--cluster-bench" in sys.argv \
        or "--chaos-bench" in sys.argv or "--serve-bench" in sys.argv \
        or "--rapids-bench" in sys.argv or "--hist-bench" in sys.argv \
        or "--obs-bench" in sys.argv or "--codec-bench" in sys.argv:
    # --cache-bench / --parse-bench / --cluster-bench / --chaos-bench /
    # --serve-bench / --rapids-bench / --hist-bench / --obs-bench /
    # --codec-bench are CPU-only by construction: same hazard
    for _k in _CACHE_ENV:
        os.environ.pop(_k, None)
else:
    for _k, _v in _CACHE_ENV.items():
        os.environ.setdefault(_k, _v)

_HERE = os.path.dirname(os.path.abspath(__file__))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", 60))
# First attempt gets a longer budget: if the largest single executable's
# compile exceeds the per-attempt bound, the cache checkpoints nothing and
# no number of retries helps.  Observed compiles split into many cacheable
# executables, so 600/500 is a hedge, not a requirement.
ATTEMPT1_TIMEOUT = int(os.environ.get("BENCH_ATTEMPT1_TIMEOUT", 600))
ATTEMPT_TIMEOUT = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 500))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", 3))
PARTIAL_PATH = os.path.join(_HERE, "BENCH_PARTIAL.json")


def _current_round():
    """Round number from the driver's PROGRESS.jsonl (None if unknown)."""
    try:
        with open(os.path.join(_HERE, "PROGRESS.jsonl")) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        return json.loads(lines[-1]).get("round")
    except Exception:
        return None


def _fail(stage: str, detail: str) -> None:
    """Print a structured single-line diagnosis and exit 0.

    Exit 0 is deliberate: the driver records stdout either way, and a
    parseable diagnosis beats rc=124 with a truncated log (VERDICT r3
    item 1).

    If a mirrored partial result from a successful run EARLIER IN THIS
    ROUND exists (BENCH_PARTIAL.json — written only by a worker that
    completed a real measurement, stamped with the round it ran in),
    report that value with explicit provenance instead of 0.0: the round
    then records the verified number plus the diagnosis, not just the
    outage. A partial from a PREVIOUS round is never reported — that
    would fabricate a number for a round in which nothing ran.
    """
    err = f"{stage}: {detail}"
    try:
        if os.environ.get("BENCH_FORCE_CPU"):
            partial = None  # CPU smoke runs must not report the TPU artifact
        else:
            with open(PARTIAL_PATH) as f:
                partial = json.load(f)
    except (OSError, json.JSONDecodeError):
        partial = None
    rnd = _current_round()
    if partial and (rnd is None or partial.get("round") != rnd):
        partial = None  # stale cross-round artifact (or unknowable round)
    if partial and partial.get("value"):
        partial["error"] = err
        # explicit machine-readable flag so a consumer parsing only a few
        # fields cannot mistake a mirrored value for a live measurement
        partial["value_is_mirrored"] = True
        partial["source"] = (
            "BENCH_PARTIAL.json — mirrored from a successful measurement "
            "earlier this round; the TPU backend was unreachable at bench "
            "time (see error)"
        )
        print(json.dumps(partial))
        sys.exit(0)
    print(json.dumps({
        "metric": "tpu_hist_train_rows_per_sec_per_chip",
        "value": 0.0,
        "unit": "rows/sec",
        "vs_baseline": 0.0,
        "error": err,
    }))
    sys.exit(0)


def synth_higgs(n_rows: int, n_feat: int = 28, seed: int = 0):
    import numpy as np
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    w = rng.normal(size=n_feat) / np.sqrt(n_feat)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    return X, y


def _probe() -> None:
    """Child: touch the backend, print device count, exit."""
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps({"devices": len(jax.devices()),
                      "platform": jax.devices()[0].platform}))


def _worker() -> None:
    """Child: the real measurement.  Prints the result JSON as its last
    stdout line; the orchestrator relays it."""
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000_000))
    ntrees = int(os.environ.get("BENCH_TREES", 10))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))

    if os.environ.get("BENCH_FORCE_CPU"):
        # test hook: exercise the worker logic without the TPU tunnel.
        # Env vars alone don't switch platforms here (sitecustomize pins
        # the axon backend); the config update before first backend use is
        # authoritative, same as tests/conftest.py.
        import jax
        jax.config.update("jax_platforms", "cpu")

    from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
    from h2o3_tpu.models.tree.common import init_margin
    from h2o3_tpu.util import telemetry

    # count XLA compiles from the first warmup program on, so the artifact
    # records how much of this run was compilation vs steady-state training
    telemetry.install_jax_compile_listener()

    X, y = synth_higgs(n_rows)
    params = TreeParams(
        ntrees=ntrees, max_depth=max_depth, learn_rate=0.1, nbins=256,
        min_rows=1.0, reg_lambda=1.0, seed=0,
    )
    f0 = init_margin("bernoulli", y, 1)

    # warmup run at full shape: compiles the training-block executable(s);
    # the timed run below hits the jit cache.  A DIFFERENT seed keeps every
    # warmup device execution's input values distinct from the timed run's
    # (the axon relay must never be able to serve a timed step from any
    # cache of already-executed identical computations).
    from dataclasses import replace as _dc_replace
    t0 = time.time()
    train_boosted(X, "bernoulli", y, 1, f0, _dc_replace(params, seed=12345))
    warmup_s = time.time() - t0
    print(f"# warmup done in {warmup_s:.1f}s", file=sys.stderr)

    # steady-state training throughput: the timings hook separates one-time
    # host prep (binning + device transfer over the tunnel) from the on-chip
    # boosting loop, the same split the reference's benchmarks use (DMatrix
    # build excluded from the gpu_hist training timer)
    timings = {}
    train_boosted(X, "bernoulli", y, 1, f0, params, timings=timings)
    dt = timings["train_s"]

    # record which level flow produced this number (the orchestrator's
    # final attempt pins subtraction off; the artifact must say so)
    from h2o3_tpu.models.tree.booster import _tree_subtract_enabled
    _subtract_on = _tree_subtract_enabled()

    rows_per_sec = n_rows * ntrees / dt  # row-scans per second per chip

    # MFU accounting (VERDICT r4 item 2): the histogram build is the FLOP
    # budget — per level the node-matmul kernel contracts
    # one_hot(bins)[R, F*B1] against node-masked vals [R, K*C] (C=4
    # channels), so FLOPs = 2*R*F*B1*K*C summed over levels (K = 2**d
    # nodes; subtraction builds only the smaller child, ~halving K past
    # the root).  Achieved TFLOP/s over bf16 peak gives MFU on one v5e
    # core (197 TFLOP/s; override BENCH_PEAK_TFLOPS for other parts).
    n_bins1, chans, n_feat = 257, 4, X.shape[1]
    level_nodes = sum(
        max(1, 2 ** d // (2 if _subtract_on and d > 0 else 1))
        for d in range(max_depth)
    )
    flops = 2.0 * n_rows * n_feat * n_bins1 * chans * level_nodes * ntrees
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS", 197.0))
    tflops = flops / dt / 1e12
    # re-based denominators (VERDICT r4 weak 7): the 8M round target and
    # the 25M north star, not round 1's broken floor
    target = 8_000_000.0

    # telemetry ride-along: jit-miss / dispatch / shard-byte totals travel
    # inside every BENCH_*.json so regressions in compile count or dispatch
    # volume are visible in the same trend line as the throughput number
    try:
        tel = {k: (round(v, 3) if isinstance(v, float) else v)
               for k, v in telemetry.REGISTRY.summary().items() if v}
    except Exception:  # the measurement must never die on its meters
        tel = {}

    print(json.dumps({
        "metric": "tpu_hist_train_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec (n_rows*ntrees/train_time, Higgs-shaped 28f)",
        "vs_baseline": round(rows_per_sec / target, 3),
        "detail": {"n_rows": n_rows, "ntrees": ntrees,
                   "max_depth": max_depth, "train_s": round(dt, 3),
                   "warmup_s": round(warmup_s, 1),
                   "subtract": _subtract_on,
                   "vs_baseline_is": "value / 8M rows/sec round target",
                   "vs_north_star_25M": round(rows_per_sec / 25e6, 3),
                   "achieved_tflops": round(tflops, 2),
                   "mfu_vs_bf16_peak": round(tflops / peak, 4)},
        "telemetry": tel,
    }))


def _cache_bench() -> None:
    """CPU-runnable warm-vs-cold devcache microbench (PR 3 acceptance).

    N repeat fits of GLM+GBM on ONE frame plus repeat map_reduce
    dispatches; reports upload bytes and dispatch/fit wall cold vs warm,
    and the ``mapreduce_jit_cache_total`` hit ratio. Prints ONE JSON line.
    Runs entirely on the host CPU backend — the caching win is provable
    without TPU access (`python bench.py --cache-bench`).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.compute.mapreduce import FrameTable, map_reduce
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.util import telemetry

    telemetry.install_jax_compile_listener()
    n_rows = int(os.environ.get("BENCH_CACHE_ROWS", 100_000))
    n_fits = int(os.environ.get("BENCH_CACHE_FITS", 3))

    import numpy as np
    X, y = synth_higgs(n_rows, n_feat=8)
    fr = Frame.from_dict(
        {f"f{i}": X[:, i].astype(np.float64) for i in range(X.shape[1])}
        | {"y": y}
    )
    shard_bytes = telemetry.REGISTRY.get("shard_bytes_total")
    jit_cache = telemetry.REGISTRY.get("mapreduce_jit_cache_total")

    def fit_once():
        t0 = time.time()
        GLM(response_column="y", family="binomial", lambda_=0.0).train(fr)
        GBM(response_column="y", ntrees=3, max_depth=3, seed=0).train(fr)
        return time.time() - t0

    def dispatch_once():
        tbl = FrameTable.from_frame(fr, columns=["f0", "f1"])
        t0 = time.time()
        map_reduce(
            _cache_bench_stat, tbl)
        return time.time() - t0

    b0 = shard_bytes.total()
    cold_fit = fit_once()
    cold_dispatch = dispatch_once()
    cold_bytes = shard_bytes.total() - b0

    warm_fit, warm_dispatch, warm_bytes = [], [], []
    for _ in range(max(1, n_fits - 1)):
        b1 = shard_bytes.total()
        warm_fit.append(fit_once())
        warm_dispatch.append(dispatch_once())
        warm_bytes.append(shard_bytes.total() - b1)

    hits = sum(
        s["value"] for s in jit_cache.snapshot()["series"]
        if s["labels"]["result"] == "hit"
    )
    total = sum(s["value"] for s in jit_cache.snapshot()["series"])
    summary = {
        k: v for k, v in telemetry.REGISTRY.summary().items()
        if k.startswith(("devcache", "mapreduce", "shard"))
    }
    print(json.dumps({
        "metric": "devcache_warm_vs_cold",
        "unit": "seconds / bytes (lower warm is the win)",
        "n_rows": n_rows,
        "fits_per_phase": "1 GLM + 1 GBM",
        "cold": {"fit_s": round(cold_fit, 3),
                 "dispatch_s": round(cold_dispatch, 4),
                 "upload_bytes": cold_bytes},
        "warm": {"fit_s": round(min(warm_fit), 3),
                 "dispatch_s": round(min(warm_dispatch), 4),
                 "upload_bytes": max(warm_bytes)},
        "warm_beats_cold": bool(
            min(warm_fit) < cold_fit
            and min(warm_dispatch) < cold_dispatch
            and max(warm_bytes) < cold_bytes
        ),
        "jit_cache_hit_ratio": round(hits / total, 3) if total else None,
        "telemetry": summary,
    }))


def _dist_rapids_cell() -> dict:
    """The distributed-Rapids cell of ``--rapids-bench``: one fused
    ``:=``/filter/reduce pipeline run caller-local over a materialized
    frame (1-node, the bit-identity reference) and again over a
    chunk-homed ``DistFrame`` on a 3-node in-process cloud
    (``rapids/dist_exec.py``), where each region ships as a canonical
    sexpr and the derived/filtered columns stay home-resident.  Reports
    warm pipeline wall and per-op wall for both modes, the bytes that
    actually moved (dtask payloads + ring reads, pinned so gossip noise
    cannot pollute the cell) vs the f64 frame body a gather would move,
    and asserts ``bit_identical`` + ``partials_only`` + a
    zero-plan-compile warm path in-run."""
    import numpy as np

    from h2o3_tpu.cluster import dkv as cdkv
    from h2o3_tpu.cluster import tasks as ctasks
    from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
    from h2o3_tpu.frame.parse import _iter_body_chunks, parse_csv, \
        parse_setup
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.rapids.runtime import Session, exec_rapids
    from h2o3_tpu.util import telemetry

    n = int(os.environ.get("BENCH_DIST_RAPIDS_ROWS", 30_000))
    reps = 3

    def _meter(name, **labels):
        c = telemetry.REGISTRY.get(name)
        if c is None:
            return 0.0
        return sum(s["value"] for s in c.snapshot()["series"]
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    # integer-valued floats: reducer partials are exact in f64 under
    # any chunk partitioning, so merge order cannot move bits
    xs = np.arange(n) % 97
    ys = (np.arange(n) * 7) % 31
    text = "x,y\n" + "".join(f"{xs[i]},{ys[i]}\n" for i in range(n))

    clouds = []
    for i in range(3):
        c = Cloud("rapbench", f"rb{i}", hb_interval=0.05)
        cdkv.install(c, KeyedStore())
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and not all(
            c.size() == 3 for c in clouds):
        time.sleep(0.02)

    saved = os.environ.get("H2O3_TPU_RAPIDS_FUSION")
    try:
        set_local_cloud(clouds[0])
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 16384, setup.header,
            setup.skip_blank_lines))
        fr = ctasks.distributed_parse_chunks(
            chunks, setup, cloud=clouds[0], key="bench_dist_rapids_df")
        n_homes = len({g["home_name"]
                       for g in fr.chunk_layout["groups"]})

        session = Session()
        session.assign("db", fr)
        session.assign("lb", parse_csv(text))

        # :=-derive onto the homes, filter through a shipped mask,
        # reduce to partials — three regions, ~4 fused prims
        n_ops = 4

        def _pipeline(v):
            exec_rapids(
                f"(tmp= {v}d (:= {v}b (* (cols_py {v}b 0) 2) 1 _))",
                session)
            exec_rapids(
                f"(tmp= {v}f (rows {v}d (< (cols_py {v}d 0) 48)))",
                session)
            out = exec_rapids(
                f"(sum (* (cols_py {v}f 0) (cols_py {v}f 1)))", session)
            return int(np.float64(out.value).view(np.uint64))

        def _timed(v):
            sig = _pipeline(v)  # cold: compiles, probes, caches
            w0 = _meter("rpc_payload_bytes_total",
                        direction="sent", method="dtask")
            g0 = _meter("rpc_payload_bytes_total", method="dkv_get")
            pb0 = _meter("rapids_dist_partial_bytes_total")
            dd0 = _meter("rapids_dist_total", result="dist")
            pm0 = (_meter("mapreduce_plan_cache_total",
                          op="rapids_dist", result="miss")
                   + _meter("mapreduce_plan_cache_total",
                            op="rapids_fusion", result="miss"))
            t = time.perf_counter()
            sig = _pipeline(v)
            wall = time.perf_counter() - t
            meters = {
                "moved_bytes": (
                    _meter("rpc_payload_bytes_total",
                           direction="sent", method="dtask") - w0
                    + _meter("rpc_payload_bytes_total",
                             method="dkv_get") - g0),
                "partial_bytes": (
                    _meter("rapids_dist_partial_bytes_total") - pb0),
                "dist_regions": (
                    _meter("rapids_dist_total", result="dist") - dd0),
                "plan_misses": (
                    _meter("mapreduce_plan_cache_total",
                           op="rapids_dist", result="miss")
                    + _meter("mapreduce_plan_cache_total",
                             op="rapids_fusion", result="miss") - pm0),
            }
            for _ in range(reps - 1):
                t = time.perf_counter()
                _pipeline(v)
                wall = min(wall, time.perf_counter() - t)
            return {"sig": sig, "wall": wall, **meters}

        local = _timed("l")
        dist = _timed("d")

        frame_bytes = 8 * n * 2
        partials_only = dist["moved_bytes"] < frame_bytes / 4
        return {
            "rows": n,
            "homes": n_homes,
            "pipeline": ":= derive -> mask filter -> sum reduce",
            "pipeline_ops": n_ops,
            "warm_wall_1node_ms": round(local["wall"] * 1e3, 2),
            "warm_wall_3node_ms": round(dist["wall"] * 1e3, 2),
            "warm_per_op_ms_1node": round(
                local["wall"] * 1e3 / n_ops, 3),
            "warm_per_op_ms_3node": round(
                dist["wall"] * 1e3 / n_ops, 3),
            "dist_regions_per_run": int(dist["dist_regions"]),
            "wire_moved_bytes": int(dist["moved_bytes"]),
            "partial_bytes": int(dist["partial_bytes"]),
            "frame_body_bytes": frame_bytes,
            "wire_vs_frame_ratio": round(
                dist["moved_bytes"] / max(frame_bytes, 1), 4),
            "bit_identical": local["sig"] == dist["sig"],
            "partials_only": bool(partials_only),
            "warm_zero_plan_compile": dist["plan_misses"] == 0.0,
        }
    finally:
        if saved is None:
            os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        else:
            os.environ["H2O3_TPU_RAPIDS_FUSION"] = saved
        set_local_cloud(None)
        for c in clouds:
            try:
                c.stop()
            except Exception:
                pass


def _rapids_bench() -> None:
    """CPU-runnable rapids query-fusion bench (fusion PR acceptance).

    One ~20-op munging pipeline (column selects, scale, abs-clip via
    ifelse, sqrt, floor, modulo, compare, sum reduce) over a generated
    2-column frame, three ways: op-at-a-time interpreter
    (H2O3_TPU_RAPIDS_FUSION=0), fused cold (first dispatch: lowering +
    trace + compile + upload), fused warm (plan cache + devcache hits).
    Asserts fused/interpreted bit-identity in-run and a zero-recompile,
    zero-upload warm path; a second pipeline with a non-fusible log1p in
    the middle pins fallback-at-the-boundary parity. Writes
    RAPIDS_BENCH.json and prints the same JSON (`--rapids-bench`)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from h2o3_tpu.frame.frame import Column, ColType, Frame
    from h2o3_tpu.rapids.runtime import Session, exec_rapids
    from h2o3_tpu.util import telemetry

    n_rows = int(os.environ.get("BENCH_RAPIDS_ROWS", 2_000_000))
    reps = int(os.environ.get("BENCH_RAPIDS_REPS", 5))
    rng = np.random.default_rng(7)
    session = Session()
    fr = Frame([
        Column("x", rng.standard_normal(n_rows), ColType.NUM),
        Column("y", rng.standard_normal(n_rows), ColType.NUM),
    ])
    session.assign("rb", fr)

    # all-fusible ~20-op pipeline, one scalar out (sum-reduce root)
    pipeline = (
        "(sum (* (+ (sqrt (abs (+ (cols_py rb 0) (cols_py rb 1)))) "
        "(ifelse (> (cols_py rb 0) 0) (cols_py rb 0) (- 0 (cols_py rb 0)))) "
        "(+ (* (floor (cols_py rb 1)) 0.25) (% (cols_py rb 0) 3))))"
    )
    # same shape with a non-fusible log1p inside: the region fractures at
    # the boundary and must still be bit-identical
    mixed = (
        "(sum (* (log1p (abs (+ (cols_py rb 0) (cols_py rb 1)))) "
        "(+ (* (floor (cols_py rb 1)) 0.25) (% (cols_py rb 0) 3))))"
    )

    def bits(v: float) -> int:
        return int(np.float64(v).view(np.uint64))

    def run(expr, fusion: bool) -> tuple:
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1" if fusion else "0"
        t0 = time.perf_counter()
        out = exec_rapids(expr, session)
        return time.perf_counter() - t0, float(out.value)

    def counters():
        def val(name, **labels):
            c = telemetry.REGISTRY.get(name)
            return float(c.value(**labels)) if c is not None else 0.0

        return {
            "jit_miss": val("mapreduce_jit_cache_total",
                            op="map_batches", result="miss"),
            "plan_miss": val("mapreduce_plan_cache_total",
                             op="rapids_fusion", result="miss"),
            "upload_bytes": val("shard_bytes_total"),
            "devcache_miss": val("devcache_requests_total",
                                 kind="frame_table", result="miss"),
        }

    interp_s, interp_v = zip(*(run(pipeline, fusion=False)
                               for _ in range(reps)))
    cold_s, cold_v = run(pipeline, fusion=True)
    snap = counters()
    warm = [run(pipeline, fusion=True) for _ in range(reps)]
    warm_s = [t for t, _ in warm]
    warm_deltas = {k: counters()[k] - snap[k] for k in snap}

    mixed_interp = run(mixed, fusion=False)[1]
    mixed_fused = run(mixed, fusion=True)[1]

    values = {interp_v[0], cold_v} | {v for _, v in warm}
    bit_identical = len({bits(v) for v in values}) == 1
    mixed_identical = bits(mixed_interp) == bits(mixed_fused)
    warm_clean = all(v == 0.0 for v in warm_deltas.values())

    interp_best = min(interp_s)
    warm_best = min(warm_s)
    fusion_counter = telemetry.REGISTRY.get("rapids_fusion_total")
    result = {
        "metric": "rapids_fusion_warm_speedup",
        "unit": "x (interpreted wall / fused warm wall, same pipeline)",
        "n_rows": n_rows,
        "pipeline_ops": 20,
        "interpreted_s": round(interp_best, 4),
        "fused_cold_s": round(cold_s, 4),
        "fused_warm_s": round(warm_best, 4),
        "speedup_warm": round(interp_best / warm_best, 2),
        "rows_per_sec": {
            "interpreted": int(n_rows / interp_best),
            "fused_warm": int(n_rows / warm_best),
        },
        "bit_identical": bit_identical,
        "mixed_fallback_bit_identical": mixed_identical,
        "warm_zero_recompile_zero_upload": warm_clean,
        "warm_deltas": warm_deltas,
        "fused_regions": fusion_counter.value(result="fused"),
        "fallback_regions": fusion_counter.value(result="fallback"),
    }
    dist_cell = _dist_rapids_cell()
    result["dist_rapids"] = dist_cell
    with open(os.path.join(_HERE, "RAPIDS_BENCH.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    if not (bit_identical and mixed_identical and warm_clean
            and dist_cell["bit_identical"] and dist_cell["partials_only"]
            and dist_cell["warm_zero_plan_compile"]):
        sys.exit(1)


def _parse_bench_csv(target_mb: float) -> str:
    """Deterministic mixed NUM/CAT/TIME/STR/NUM CSV of ~target_mb MB —
    the column mix routes every chunk through every native primitive
    (float parse, dict encode, time parse, string gather)."""
    cats = ("alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta")
    row_bytes = 62  # measured mean of the format below
    n = max(1000, int(target_mb * 1e6 / row_bytes))
    rows = ["num,cat,time,str,count"]
    for i in range(n):
        num = "NA" if i % 97 == 0 else f"{i * 0.75 - 17.0:.4f}"
        tim = (f"2021-{(i % 12) + 1:02d}-{(i % 27) + 1:02d}"
               f" 10:{i % 60:02d}:{(i * 7) % 60:02d}")
        rows.append(f"{num},{cats[i % 7]},{tim},free text {i % 5000},{i}")
    rows.append("")
    return "\n".join(rows)


def _frames_identical(a, b) -> bool:
    import numpy as np

    if a.names != b.names or a.nrows != b.nrows:
        return False
    for n in a.names:
        ca, cb = a.col(n), b.col(n)
        if ca.type != cb.type or ca.domain != cb.domain:
            return False
        if ca.data.dtype == object:
            if list(ca.data) != list(cb.data):
                return False
        elif not np.array_equal(ca.data, cb.data, equal_nan=True):
            return False
    return True


def _parse_bench() -> None:
    """CPU parse-pipeline bench (chunk-parallel two-phase ingest).

    Rows/sec at 1/2/4/8 workers on a generated mixed NUM/CAT/TIME/STR
    CSV (~BENCH_PARSE_MB, default 100), plain and gzipped, with scaling
    efficiency and a serial-vs-parallel bit-identity check in the same
    run.  Prints ONE JSON line and mirrors it to PARSE_BENCH.json.
    Worker scaling is an OS-scheduling property: on a single-core host
    (host_cpus=1) throughput is flat across worker counts by physics —
    the pipeline-vs-serial-python speedup is the portable number there.
    """
    import gzip
    import io

    from h2o3_tpu.frame.parse import parse_csv, parse_csv_stream
    from h2o3_tpu.frame.ingest import parse_bytes
    from h2o3_tpu.util import telemetry

    size_mb = float(os.environ.get("BENCH_PARSE_MB", 100))
    repeats = int(os.environ.get("BENCH_PARSE_REPEATS", 2))
    worker_counts = (1, 2, 4, 8)
    # 2 MiB chunks: ~50 chunks at 100 MB, enough scheduling granularity
    # for 8 workers without drowning in per-chunk overhead
    os.environ.setdefault("H2O3_TPU_PARSE_CHUNK_BYTES", str(2 << 20))
    chunk_bytes = int(os.environ["H2O3_TPU_PARSE_CHUNK_BYTES"])

    t0 = time.time()
    text = _parse_bench_csv(size_mb)
    raw = text.encode("utf-8")
    nbytes = len(raw)
    gen_s = time.time() - t0
    print(f"# generated {nbytes / 1e6:.1f} MB csv in {gen_s:.1f}s",
          file=sys.stderr)

    def timed_parse(workers):
        best, fr = None, None
        for _ in range(max(1, repeats)):
            t = time.time()
            f = parse_csv_stream(io.BytesIO(raw), workers=workers)
            dt = time.time() - t
            if best is None or dt < best:
                best, fr = dt, f
        return best, fr

    # warmup outside the timers: builds/loads the native lib (a stale
    # .so recompiles on first use) and faults the page cache
    w_end = raw.find(b"\n", min(3 << 20, len(raw) // 2)) + 1
    parse_csv_stream(io.BytesIO(raw[:w_end] if w_end > 0 else raw),
                     workers=2)

    plain = {}
    frames = {}
    for w in worker_counts:
        dt, fr = timed_parse(w)
        plain[w] = {"seconds": round(dt, 3),
                    "rows_per_sec": round(fr.nrows / dt, 1),
                    "mb_per_sec": round(nbytes / 1e6 / dt, 1)}
        if w in (1, worker_counts[-1]):
            frames[w] = fr
        print(f"# workers={w}: {dt:.2f}s "
              f"({fr.nrows / dt / 1e6:.2f}M rows/s)", file=sys.stderr)
    nrows = frames[1].nrows

    # gzipped source through the streamed-decompression ingest path
    gz = gzip.compress(raw, compresslevel=1)
    gz_res = {}
    gz_identical = True
    for w in (1, worker_counts[-1]):
        best, fr = None, None
        for _ in range(max(1, repeats)):
            t = time.time()
            f = parse_bytes("bench.csv.gz", gz, workers=w)
            dt = time.time() - t
            if best is None or dt < best:
                best, fr = dt, f
        gz_res[w] = {"seconds": round(best, 3),
                     "rows_per_sec": round(fr.nrows / best, 1)}
        gz_identical = gz_identical and _frames_identical(frames[1], fr)
        print(f"# gz workers={w}: {best:.2f}s", file=sys.stderr)

    # bit-identity, same run: parallel vs workers=1 on the full input,
    # plus the serial whole-text oracle on a record-aligned prefix small
    # enough to take the serial path (it is pure-python and ~25x slower)
    wmax = worker_counts[-1]
    identical = _frames_identical(frames[1], frames[wmax])
    serial_mb = float(os.environ.get("BENCH_PARSE_SERIAL_MB", 8))
    cut = raw.rfind(b"\n", 0, int(serial_mb * 1e6)) + 1
    slice_text = raw[:cut].decode()
    # chunk threshold above the slice size forces the true serial
    # whole-text path (parse_csv routes anything larger to the pipeline)
    os.environ["H2O3_TPU_PARSE_CHUNK_BYTES"] = str(1 << 28)
    t = time.time()
    serial_fr = parse_csv(slice_text)
    serial_s = time.time() - t
    os.environ["H2O3_TPU_PARSE_CHUNK_BYTES"] = str(256 << 10)
    par_slice = parse_csv(slice_text, workers=wmax)
    os.environ["H2O3_TPU_PARSE_CHUNK_BYTES"] = str(chunk_bytes)
    serial_identical = _frames_identical(serial_fr, par_slice)
    serial_rps = serial_fr.nrows / serial_s

    rps1, rpsN = plain[1]["rows_per_sec"], plain[wmax]["rows_per_sec"]
    tel = {
        k: v for k, v in telemetry.REGISTRY.summary().items()
        if k.startswith("parse")
    }
    result = {
        "metric": "parse_rows_per_sec",
        "value": rpsN,
        "unit": f"rows/sec ({wmax} workers, mixed NUM/CAT/TIME/STR csv)",
        "vs_baseline": round(rpsN / serial_rps, 2),
        "detail": {
            "csv_mb": round(nbytes / 1e6, 1),
            "n_rows": nrows,
            "chunk_bytes": chunk_bytes,
            "host_cpus": os.cpu_count(),
            "workers": plain,
            "gz": gz_res,
            "scaling_efficiency": {
                w: round(plain[w]["rows_per_sec"] / (w * rps1), 3)
                for w in worker_counts
            },
            "speedup_8w_vs_1w": round(rpsN / rps1, 2),
            "serial_python_rows_per_sec": round(serial_rps, 1),
            "speedup_pipeline_vs_serial_python": round(rpsN / serial_rps, 2),
            "bit_identical_1w_vs_8w_full": identical,
            "bit_identical_serial_vs_parallel_slice": serial_identical,
            "bit_identical_gz_vs_plain": gz_identical,
            "vs_baseline_is": "pipeline rows/sec / serial-python rows/sec",
        },
        "telemetry": {k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in tel.items()},
    }
    with open(os.path.join(_HERE, "PARSE_BENCH.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


def _cache_bench_stat(cols, mask):
    """Module-level map fn so repeat dispatches share one plan-cache key."""
    import jax.numpy as jnp

    return jnp.sum(jnp.where(mask, cols["f0"] * cols["f1"], 0.0))


def _run_child(arg: str, timeout: int, extra_env=None):
    """Run this file with `arg` in a subprocess under a hard timeout.

    Returns (ok, last_json_line_or_None, note).  The child is killed on
    timeout — over the axon tunnel that is the only way to bound a
    backend-init hang (in-process signals never fire; see module doc).
    """
    cmd = [sys.executable, os.path.abspath(__file__), arg]
    env = None
    if extra_env:
        env = dict(os.environ)
        env.update(extra_env)
    try:
        proc = subprocess.run(
            cmd, timeout=timeout, capture_output=True, text=True, cwd=_HERE,
            env=env)
    except subprocess.TimeoutExpired as e:
        def _text(b):
            return b.decode(errors="replace") if isinstance(b, bytes) \
                else (b or "")
        # a worker can finish the measurement and then wedge in backend
        # teardown at interpreter exit — a result line already on stdout
        # must count as success, not burn the remaining attempts
        for line in reversed(_text(e.stdout).strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return True, json.loads(line), "result before teardown hang"
                except json.JSONDecodeError:
                    continue
        # the stderr captured before the kill is the only evidence of
        # where the worker hung (e.g. a "# warmup done" progress line
        # distinguishes init-hang from timed-run-hang)
        tail = ""
        err = _text(e.stderr)
        if err:
            sys.stderr.write(err[-4000:])
            tail = "; last stderr: " + " | ".join(
                err.strip().splitlines()[-2:])
        return False, None, f"killed after {timeout}s (backend hang){tail}"
    if proc.stderr:
        sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return False, None, f"rc={proc.returncode}: {' | '.join(tail)}"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return True, json.loads(line), ""
            except json.JSONDecodeError:
                continue
    return False, None, "no JSON line in child stdout"


def _dist_hist_cell() -> dict:
    """The distributed-training cell of ``--hist-bench``: one GBM fit run
    1-node (``H2O3_TPU_DIST_HIST=local`` — the same engine with every
    histogram op executed caller-side, the bit-identity reference) and
    again against a 3-node in-process cloud with the frame parsed onto
    chunk homes (``models/tree/dist_hist.py``).  Reports fit wall and
    mean per-level wall for both modes, the partials-vs-rows wire ratio
    — histogram-partial bytes actually shipped vs the f64 frame body the
    move-the-data path would ship — and the bit-identity flag.  The
    partials bound is asserted in-run (``partials_bounded``): per level
    at most ``n_nodes x n_features x (nbins+1) x 3 x 8`` bytes per home.
    Fit walls are min-of-3 warm repeats (scheduler jitter at the ~200ms
    scale otherwise swamps mode deltas); wire/cache meters are deltas
    around the first warm repeat only.
    """
    import pickle

    import numpy as np

    from h2o3_tpu.cluster import dkv as cdkv
    from h2o3_tpu.cluster import tasks as ctasks
    from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
    from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.models.grid import metric_value
    from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
    from h2o3_tpu.util import telemetry

    n = int(os.environ.get("BENCH_DIST_HIST_ROWS", 30_000))
    nbins, depth, ntrees = 16, 3, 4

    def _meter(name, **labels):
        c = telemetry.REGISTRY.get(name)
        if c is None:
            return 0.0
        return sum(s["value"] for s in c.snapshot()["series"]
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    cats = ("lo", "mid", "hi")
    yes_no = ("no", "yes")
    lines = ["x,y,z,c,resp"]
    for i in range(n):
        x, y, z = i % 97, (i * 7) % 31, (i * 13) % 53
        lines.append(f"{x},{y},{z},{cats[i % 3]},"
                     f"{yes_no[int((x * 3 + y) % 11 < 5)]}")
    text = "\n".join(lines) + "\n"

    clouds = []
    for i in range(3):
        c = Cloud("histbench", f"hb{i}", hb_interval=0.05)
        cdkv.install(c, KeyedStore())
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and not all(
            c.size() == 3 for c in clouds):
        time.sleep(0.02)

    saved = os.environ.get("H2O3_TPU_DIST_HIST")
    try:
        set_local_cloud(clouds[0])
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 32768, setup.header, setup.skip_blank_lines))
        fr = ctasks.distributed_parse_chunks(
            chunks, setup, cloud=clouds[0], key="bench_dist_hist_df")
        n_homes = len({g["home_name"]
                       for g in fr.chunk_layout["groups"]})

        def _fit():
            m = GBM(GBMParameters(
                response_column="resp", ntrees=ntrees, max_depth=depth,
                nbins=nbins, min_rows=1.0, seed=17)).train(fr)
            arrays = [
                np.stack(getattr(t, f))
                for t in m.booster.trees_per_class
                for f in ("feat", "split_bin", "default_left",
                          "is_split", "leaf")]
            return pickle.dumps([arrays,
                                 np.asarray(m.booster.init_margin),
                                 metric_value(m, "auto")[0]])

        def _timed_fit(mode):
            os.environ["H2O3_TPU_DIST_HIST"] = mode
            _fit()  # warms the mode's jit / binned contexts
            lv0 = _meter("dist_hist_levels_total")
            pb0 = _meter("dist_hist_partial_bytes_total")
            w0 = _meter("rpc_payload_bytes_total", direction="sent")
            # the timed fit IS the warm repeat fit on the unmutated
            # DistFrame: its hist_bind rounds must serve every group's
            # binned codes from the device cache — a miss is the only
            # path that decodes (apply_bins) or uploads, so miss == 0
            # is the zero-decode / zero-upload-bytes proof
            bm0 = _meter("dist_hist_bind_cache_total", result="miss")
            bh0 = _meter("dist_hist_bind_cache_total", result="hit")
            dm0 = _meter("devcache_requests_total",
                         kind="hist_bins_home", result="miss")
            t = time.perf_counter()
            sig = _fit()
            wall = time.perf_counter() - t
            meters = {
                "levels": _meter("dist_hist_levels_total") - lv0,
                "partial_bytes": (
                    _meter("dist_hist_partial_bytes_total") - pb0),
                "sent_bytes": (
                    _meter("rpc_payload_bytes_total",
                           direction="sent") - w0),
                "bind_decodes": (
                    _meter("dist_hist_bind_cache_total", result="miss")
                    - bm0),
                "bind_cache_hits": (
                    _meter("dist_hist_bind_cache_total", result="hit")
                    - bh0),
                "bind_upload_misses": (
                    _meter("devcache_requests_total",
                           kind="hist_bins_home", result="miss") - dm0),
            }
            # min-of-k warm walls (same rationale as the level rows):
            # one fit is ~200ms of mostly-idle RPC turnarounds, exactly
            # the scale at which scheduler jitter swamps a real delta
            for _ in range(2):
                t = time.perf_counter()
                _fit()
                wall = min(wall, time.perf_counter() - t)
            return {
                "sig": sig,
                "wall": wall,
                **meters,
            }

        local = _timed_fit("local")
        dist = _timed_fit("1")

        # the per-level arithmetic from the README: worst case
        # 2^(depth-1) sibling nodes x F features x (nbins + 1 NA
        # bucket) x {sum_g, sum_h, sum_w} x f64, per home
        F, n_bins1 = 4, nbins + 1
        per_level_cap = (1 << max(depth - 1, 0)) * F * n_bins1 * 3 * 8
        frame_bytes = 8 * n * 5
        partials_bounded = (
            dist["levels"] > 0
            and dist["partial_bytes"]
            <= dist["levels"] * per_level_cap * n_homes)
        return {
            "rows": n,
            "homes": n_homes,
            "ntrees": ntrees,
            "max_depth": depth,
            "nbins": nbins,
            "fit_wall_1node_ms": round(local["wall"] * 1e3, 1),
            "fit_wall_3node_ms": round(dist["wall"] * 1e3, 1),
            "level_ops_3node": int(dist["levels"]),
            "mean_level_ms_1node": round(
                local["wall"] * 1e3 / max(local["levels"], 1), 2),
            "mean_level_ms_3node": round(
                dist["wall"] * 1e3 / max(dist["levels"], 1), 2),
            "partial_bytes": int(dist["partial_bytes"]),
            "frame_body_bytes": frame_bytes,
            "partials_vs_rows_ratio": round(
                dist["partial_bytes"] / max(frame_bytes, 1), 4),
            "wire_sent_bytes": int(dist["sent_bytes"]),
            "partials_bounded": bool(partials_bounded),
            "wire_under_frame": bool(dist["sent_bytes"] < frame_bytes),
            "bit_identical": local["sig"] == dist["sig"],
            "warm_bind_decodes": int(dist["bind_decodes"]),
            "warm_bind_cache_hits": int(dist["bind_cache_hits"]),
            "warm_binned_upload_zero": bool(
                dist["bind_upload_misses"] == 0
                and dist["bind_cache_hits"] > 0),
        }
    finally:
        if saved is None:
            os.environ.pop("H2O3_TPU_DIST_HIST", None)
        else:
            os.environ["H2O3_TPU_DIST_HIST"] = saved
        set_local_cloud(None)
        for c in clouds:
            try:
                c.stop()
            except Exception:
                pass


def _hist_bench() -> None:
    """CPU booster-histogram microbench (the XLA scatter path).

    Times ``build_histogram_sharded`` — the per-level inner loop of the
    tree booster — on synthetic Higgs-shaped data quantized once with
    ``make_bins``/``apply_bins``, at node counts matching tree levels
    0..depth (2^level histogram nodes).  Per level it reports the cold
    wall (first call; plan compile included only when the node-bucket
    ladder misses), the warm wall (min of repeat calls on the cached plan
    — min-of-k, not median: the compile question is "is there a plan", so
    the best warm rep is the signal and the rest is scheduler noise), the
    warm-plan delta between them, rows/s from the warm wall, and the
    plan-cache hit/miss counts (``hist_plan_cache_total``) so compile-free
    warm levels are asserted, not inferred from walls.  The ``plan_churn``
    cell aggregates those per-level compile deltas and bucket hits; the
    run FAILS if any warm rep misses the plan cache.  The ``dist_hist``
    cell then prices map-side training over chunk homes (see
    :func:`_dist_hist_cell`).
    Prints ONE JSON line and mirrors it
    to HIST_BENCH.json.  CPU-only by construction: ``H2O3_TPU_HIST_IMPL``
    is pinned to ``scatter`` so numbers compare across hosts without a
    TPU in the loop (the Pallas kernel tier is scripts/bench_hist_kernel
    on real hardware)."""
    import platform

    os.environ["H2O3_TPU_HIST_IMPL"] = "scatter"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from h2o3_tpu.ops.histogram import (
        apply_bins,
        build_histogram_sharded,
        make_bins,
    )

    n = int(os.environ.get("BENCH_HIST_ROWS", 200_000))
    nfeat = int(os.environ.get("BENCH_HIST_FEATS", 28))
    nbins = int(os.environ.get("BENCH_HIST_BINS", 64))
    depth = int(os.environ.get("BENCH_HIST_DEPTH", 6))
    reps = int(os.environ.get("BENCH_HIST_REPS", 5))

    X, _y = synth_higgs(n, nfeat, seed=0)
    t = time.perf_counter()
    edges = make_bins(X, nbins=nbins, seed=0)
    make_bins_ms = (time.perf_counter() - t) * 1e3
    t = time.perf_counter()
    codes = apply_bins(X, edges)
    apply_bins_ms = (time.perf_counter() - t) * 1e3

    bins = jnp.asarray(codes, dtype=jnp.int32)
    rng = np.random.default_rng(1)
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    n_bins1 = nbins + 1  # + the NA bucket at the end

    from h2o3_tpu.ops.histogram import node_buckets, pad_nodes
    from h2o3_tpu.util import telemetry

    def _plan(result):
        c = telemetry.REGISTRY.get("hist_plan_cache_total")
        if c is None:
            return 0.0
        return sum(s["value"] for s in c.snapshot()["series"]
                   if s["labels"].get("result") == result)

    levels = []
    for lvl in range(depth + 1):
        k = 2 ** lvl
        nodes = jnp.asarray(rng.integers(0, k, size=n).astype(np.int32))
        m0 = _plan("miss")
        t = time.perf_counter()
        jax.block_until_ready(build_histogram_sharded(
            bins, nodes, g, h, k, n_bins1))
        cold = time.perf_counter() - t
        cold_miss = int(_plan("miss") - m0)
        m1, h1 = _plan("miss"), _plan("hit")
        walls = []
        for _ in range(reps):
            t = time.perf_counter()
            jax.block_until_ready(build_histogram_sharded(
                bins, nodes, g, h, k, n_bins1))
            walls.append(time.perf_counter() - t)
        warm = min(walls)  # min-of-k: any rep on the cached plan is proof
        warm_miss = int(_plan("miss") - m1)
        warm_hits = int(_plan("hit") - h1)
        levels.append({
            "level": lvl,
            "n_nodes": k,
            "node_bucket": pad_nodes(k),
            "cold_ms": round(cold * 1e3, 2),
            "warm_ms": round(warm * 1e3, 2),
            "warm_plan_delta_ms": round((cold - warm) * 1e3, 2),
            "rows_per_sec": round(n / max(warm, 1e-9), 1),
            "plan_cache": {"cold_miss": cold_miss,
                           "warm_hits": warm_hits,
                           "warm_miss": warm_miss},
        })
    # warm tree levels must compile nothing: every warm rep a plan-cache
    # hit, and within a node bucket only the FIRST level's cold call may
    # compile — asserted on the counters, not inferred from wall noise
    compile_free = all(lv["plan_cache"]["warm_miss"] == 0 for lv in levels)
    bucket_first = {}
    for lv in levels:
        bucket_first.setdefault(lv["node_bucket"], lv["level"])
    warm_bucket_levels = [lv for lv in levels
                          if bucket_first[lv["node_bucket"]] != lv["level"]]
    bucket_hits = all(lv["plan_cache"]["cold_miss"] == 0
                      for lv in warm_bucket_levels)
    if not (compile_free and bucket_hits):
        raise AssertionError(
            f"plan churn on warm levels: {[lv['plan_cache'] for lv in levels]}")
    plan_churn = {
        "node_buckets": list(node_buckets()),
        "plan_misses": sum(lv["plan_cache"]["cold_miss"] for lv in levels),
        "bucket_hit_levels": len(warm_bucket_levels),
        "per_level": [
            {"level": lv["level"], "n_nodes": lv["n_nodes"],
             "node_bucket": lv["node_bucket"],
             "compile_delta_ms": (lv["warm_plan_delta_ms"]
                                  if lv["plan_cache"]["cold_miss"] else 0.0),
             "plan_cache": lv["plan_cache"]}
            for lv in levels],
        "warm_levels_compile_free": bool(compile_free and bucket_hits),
    }
    deepest = levels[-1]
    dist_cell = _dist_hist_cell()
    result = {
        "metric": "cpu_hist_scatter_rows_per_sec",
        "value": deepest["rows_per_sec"],
        "unit": (f"rows/sec (warm scatter histogram, level {depth}: "
                 f"{deepest['n_nodes']} nodes, {nfeat} features, "
                 f"{nbins} bins)"),
        "vs_baseline": round(
            levels[0]["rows_per_sec"]
            / max(deepest["rows_per_sec"], 1e-9), 2),
        "detail": {
            "host_cpus": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
            "impl": "scatter",
            "rows": n,
            "features": nfeat,
            "nbins": nbins,
            "make_bins_ms": round(make_bins_ms, 1),
            "apply_bins_ms": round(apply_bins_ms, 1),
            "per_level": levels,
            "plan_churn": plan_churn,
            "dist_hist": dist_cell,
            "vs_baseline_is": "level-0 rows/s / deepest-level rows/s",
        },
    }
    with open(os.path.join(_HERE, "HIST_BENCH.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


def _cluster_bench() -> None:
    """2-node localhost cloud microbench (application-plane cluster).

    Boots this process as node 0 and a ``h2o3_tpu.cluster.nodeproc``
    subprocess as node 1 (port 0 + address-file rendezvous, exactly the
    multi-process tests' harness), then measures the control plane: RPC
    round-trip latency percentiles, RPC throughput by payload size, and
    DKV put/get on keys homed locally vs on the remote node, plus a
    ``dist_frame`` cell: chunk-homed parse wall, chunk-homed vs local
    ``map_reduce`` wall, and partials-vs-frame bytes on the wire.  Prints
    ONE JSON line and mirrors it to CLUSTER_BENCH.json.  The control
    plane itself stays jax-free; only the dist_frame cell jits.
    """
    import platform
    import tempfile

    from h2o3_tpu.cluster.membership import boot_node, set_local_cloud
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.util import telemetry

    rounds = int(os.environ.get("BENCH_CLUSTER_ROUNDS", 300))
    store = KeyedStore()
    cloud = boot_node("cluster-bench", "bench-n0",
                      hb_interval=0.2, store=store)
    router = store.router
    tmp = tempfile.mkdtemp(prefix="cluster_bench_")
    flat = os.path.join(tmp, "flatfile")
    addr1 = os.path.join(tmp, "n1.addr")
    with open(flat, "w") as f:
        f.write(f"{cloud.info.host}:{cloud.info.port}\n")
    child = subprocess.Popen(
        [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
         "--cluster-name", "cluster-bench", "--node-name", "bench-n1",
         "--flatfile", flat, "--address-file", addr1,
         "--hb-interval", "0.2"],
        stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT, cwd=_HERE,
    )
    try:
        t0 = time.time()
        while time.time() - t0 < 30:
            if cloud.size() == 2 and cloud.consensus():
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("2-node bench cloud never formed")
        peer = next(m for m in cloud.members_sorted()
                    if m.info.name == "bench-n1")

        def _pct(samples, q):
            s = sorted(samples)
            return s[min(len(s) - 1, int(q * len(s)))]

        # RPC round-trip latency (echo, tiny payload) — interleaved with
        # the traced variant in alternating blocks so scheduler/cache
        # drift between sections cancels out of the comparison
        lat = []
        lat_traced = []
        block = max(1, rounds // 4)
        for _ in range(4):
            for _ in range(block):
                t = time.perf_counter()
                cloud.client.call(peer.info.addr, "echo", b"x", timeout=5.0,
                                  target=peer.info.ident)
                lat.append(time.perf_counter() - t)
            with telemetry.Span("cluster_bench_traced"):
                for _ in range(block):
                    t = time.perf_counter()
                    cloud.client.call(peer.info.addr, "echo", b"x",
                                      timeout=5.0, target=peer.info.ident)
                    lat_traced.append(time.perf_counter() - t)
        rtt = {
            "p50_us": round(_pct(lat, 0.50) * 1e6, 1),
            "p90_us": round(_pct(lat, 0.90) * 1e6, 1),
            "p99_us": round(_pct(lat, 0.99) * 1e6, 1),
            "rounds": len(lat),
        }
        # telemetry overhead: the same echo RTT with tracing ACTIVE (an
        # open span makes the client inject trace context, open an
        # rpc_client span, and the server open its dispatch span) vs the
        # untraced blocks above.  Documented budget: <5% p50 regression on
        # a production control plane — operationalized at a 500us
        # reference RTT (cross-host LAN), i.e. <=25us absolute per traced
        # call.  The loopback percentage is also reported but is
        # pessimistic by construction: a sub-100us loopback RTT amplifies
        # a fixed ~20us span cost into a large-looking ratio.
        on_p50 = _pct(lat_traced, 0.50) * 1e6
        off_p50 = rtt["p50_us"]
        overhead_us = on_p50 - off_p50
        ref_rtt_us = 500.0
        budget_us = ref_rtt_us * 0.05
        trace_overhead = {
            "tracing_off_p50_us": off_p50,
            "tracing_on_p50_us": round(on_p50, 1),
            "overhead_us_p50": round(overhead_us, 1),
            "overhead_pct_p50_loopback": round(
                overhead_us / max(off_p50, 1e-9) * 100, 1),
            "budget": {
                "pct_p50": 5.0,
                "reference_rtt_us": ref_rtt_us,
                "overhead_budget_us": budget_us,
            },
            "within_budget": overhead_us <= budget_us,
        }
        # throughput by payload size (echo both ways: 2x bytes per RTT)
        thru = {}
        for sz in (64 << 10, 1 << 20, 4 << 20):
            payload = b"\0" * sz
            n = max(8, min(64, (64 << 20) // sz))
            t = time.perf_counter()
            for _ in range(n):
                cloud.client.call(peer.info.addr, "echo", payload,
                                  timeout=30.0, target=peer.info.ident)
            dt = time.perf_counter() - t
            thru[sz] = {"mb_per_sec": round(2 * sz * n / dt / 1e6, 1),
                        "calls": n}
        # DKV put/get: one key homed here, one homed on the peer
        local_key = next(k for k in (f"bench_local_{i}" for i in range(4096))
                         if router.home_name(k) == "bench-n0")
        remote_key = next(k for k in (f"bench_remote_{i}" for i in range(4096))
                          if router.home_name(k) == "bench-n1")
        value = list(range(1000))
        dkv = {}
        for label, key in (("local", local_key), ("remote", remote_key)):
            puts, gets = [], []
            for _ in range(rounds):
                t = time.perf_counter()
                store.put(key, value)
                puts.append(time.perf_counter() - t)
                t = time.perf_counter()
                got = store.get(key)
                gets.append(time.perf_counter() - t)
            assert got == value, f"{label} DKV roundtrip corrupted"
            store.remove(key)
            dkv[label] = {
                "put_p50_us": round(_pct(puts, 0.5) * 1e6, 1),
                "get_p50_us": round(_pct(gets, 0.5) * 1e6, 1),
            }
        # chunk-homed distributed Frame: parse-to-homes wall, chunk-homed
        # vs local map_reduce wall, and partials-vs-frame bytes on the
        # wire (the one jax user in this bench: the map side jits on
        # both members)
        import numpy as np

        from h2o3_tpu.cluster import frames as cframes
        from h2o3_tpu.cluster import tasks as ctasks
        from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup

        n = 60000
        xs = np.arange(n) % 97
        ys = (np.arange(n) * 7) % 31
        text = "x,y\n" + "".join(f"{xs[i]},{ys[i]}\n" for i in range(n))
        setup = parse_setup(text)
        chunks_in = list(_iter_body_chunks(
            [text.encode()], 32768, setup.header, setup.skip_blank_lines))
        t = time.perf_counter()
        fr = ctasks.distributed_parse_chunks(
            chunks_in, setup, cloud=cloud, key="bench_dist_frame")
        parse_wall = time.perf_counter() - t
        host = {"x": xs.astype(np.float64), "y": ys.astype(np.float64)}
        local_mr = ctasks.distributed_map_reduce(
            cframes.mr_sum_xy, host, cloud=None)  # warms the local jit
        t = time.perf_counter()
        ctasks.distributed_map_reduce(cframes.mr_sum_xy, host, cloud=None)
        local_wall = time.perf_counter() - t

        def _sent_bytes():
            c = telemetry.REGISTRY.get("rpc_payload_bytes_total")
            if c is None:
                return 0.0
            # sum over the method label: this cell wants total egress
            return sum(s["value"] for s in c.snapshot()["series"]
                       if s["labels"].get("direction") == "sent")

        ctasks.distributed_map_reduce(
            cframes.mr_sum_xy, fr, cloud=cloud)  # warms the remote jit
        s0 = _sent_bytes()
        t = time.perf_counter()
        dist_mr = ctasks.distributed_map_reduce(
            cframes.mr_sum_xy, fr, cloud=cloud)
        homed_wall = time.perf_counter() - t
        mr_sent = _sent_bytes() - s0
        frame_bytes = 2 * 8 * n
        import jax as _jax

        bit_identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(_jax.tree.leaves(local_mr),
                            _jax.tree.leaves(dist_mr)))
        # distributed model search: the same 6-cell GLM grid walked
        # single-node vs fanned across both members (cluster/search.py).
        # Each path runs once untimed to warm its jit caches, then once
        # timed; the leaderboards must be bit-identical either way (the
        # subsystem's determinism contract).  Runs BEFORE the dead-home
        # cell below: it needs the peer alive.
        from h2o3_tpu.frame.frame import ColType, Column, Frame
        from h2o3_tpu.models.glm import GLM, GLMParameters
        from h2o3_tpu.models.grid import GridSearch, cell_key, metric_value

        srng = np.random.default_rng(5)
        sn = 400
        sX = srng.normal(size=(sn, 3))
        slogit = sX @ np.array([1.0, -2.0, 0.5])
        sy = (srng.random(sn)
              < 1.0 / (1.0 + np.exp(-slogit))).astype(np.float64)
        scols = [Column(f"x{i}", sX[:, i]) for i in range(3)]
        scols.append(Column("y", sy, ColType.CAT, ["n", "p"]))
        sfr = Frame(scols)

        def _grid():
            return GridSearch(
                GLM,
                GLMParameters(response_column="y", family="binomial",
                              seed=7, nfolds=2),
                {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.01, 0.1]})

        def _srows(grid):
            return [(cell_key(hp), metric_value(m, "auto")[0])
                    for hp, m in zip(grid.hyper_params, grid.models)]

        os.environ["H2O3_TPU_SEARCH_DIST"] = "0"
        try:
            _grid().train(sfr)  # warms the local jit
            t = time.perf_counter()
            sg1 = _grid().train(sfr)
            search_1node = time.perf_counter() - t
        finally:
            os.environ.pop("H2O3_TPU_SEARCH_DIST", None)
        _grid().train(sfr)  # warms the peer's jit + its frame transfer
        t = time.perf_counter()
        sg2 = _grid().train(sfr)
        search_2node = time.perf_counter() - t
        search_speedup = search_1node / max(search_2node, 1e-9)
        dist_search = {
            "cells": 6,
            "grid_wall_1node_ms": round(search_1node * 1e3, 1),
            "grid_wall_2node_ms": round(search_2node * 1e3, 1),
            "speedup": round(search_speedup, 2),
            "scaling_efficiency": round(search_speedup / 2.0, 2),
            "leaderboard_bit_identical": _srows(sg1) == _srows(sg2),
        }
        # one-home-dead recovery wall: SIGKILL the peer (this cell runs
        # last, nothing downstream needs it) and re-run the chunk-homed
        # map_reduce — the caller holds the dead home's replica chunks,
        # so the ladder recovers path=replica without a re-parse
        rec = telemetry.REGISTRY.get("cluster_fanout_recovered_total")
        rep0 = rec.value(path="replica") if rec is not None else 0.0
        child.kill()
        t = time.perf_counter()
        dead_mr = ctasks.distributed_map_reduce(
            cframes.mr_sum_xy, fr, cloud=cloud)
        dead_wall = time.perf_counter() - t
        dead_identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(_jax.tree.leaves(local_mr),
                            _jax.tree.leaves(dead_mr)))
        rep1 = rec.value(path="replica") if rec is not None else 0.0
        lay = getattr(fr, "chunk_layout", None) or {}
        dist_frame = {
            "rows": n,
            "chunks": len(chunks_in),
            "groups": len(lay.get("groups", ())),
            "parse_to_homes_ms": round(parse_wall * 1e3, 1),
            "map_reduce_local_ms": round(local_wall * 1e3, 1),
            "map_reduce_chunk_homed_ms": round(homed_wall * 1e3, 1),
            "map_reduce_one_home_dead_ms": round(dead_wall * 1e3, 1),
            "recovered_path_replica": int(rep1 - rep0),
            "mr_sent_bytes": int(mr_sent),
            "frame_bytes": frame_bytes,
            "partials_only": bool(mr_sent < frame_bytes / 4),
            "bit_identical": bit_identical and dead_identical,
        }
        tel = {k: v for k, v in telemetry.REGISTRY.summary().items()
               if k.startswith(("rpc_", "cluster_"))}
        result = {
            "metric": "rpc_roundtrip_p50_us",
            "value": rtt["p50_us"],
            "unit": "microseconds (2-node localhost cloud, echo RPC)",
            "vs_baseline": round(
                dkv["remote"]["get_p50_us"]
                / max(dkv["local"]["get_p50_us"], 1e-9), 2),
            "detail": {
                "host_cpus": os.cpu_count(),
                "platform": platform.platform(),
                "python": platform.python_version(),
                "rpc_roundtrip": rtt,
                "telemetry_overhead": trace_overhead,
                "rpc_throughput_by_bytes": thru,
                "dkv": dkv,
                "dist_frame": dist_frame,
                "dist_search": dist_search,
                "vs_baseline_is": "remote get p50 / local get p50",
            },
            "telemetry": {k: (round(v, 3) if isinstance(v, float) else v)
                          for k, v in tel.items()},
        }
        with open(os.path.join(_HERE, "CLUSTER_BENCH.json"), "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result))
    finally:
        try:
            child.stdin.close()
            child.wait(timeout=10)
        except Exception:
            child.kill()
        cloud.stop()
        set_local_cloud(None)


def _obs_bench() -> None:
    """Cost-ledger overhead + end-to-end attribution bench (--obs-bench).

    Two A/B cells, ledger charging ON vs OFF in alternating blocks (so
    scheduler/cache drift cancels out of the comparison):

    * **warm fused Rapids dispatch** — plan-cache + devcache hits, the
      hot serving path; the ledger's design puts zero charge events on
      it, and this cell is the proof
    * **traced RPC echo** on a 2-node in-process cloud — every call pays
      two real charge events (sent + received bytes), the worst per-call
      ledger tax in the system.  Like the --cluster-bench telemetry
      cell, the <5% p50 budget is operationalized at a 500us reference
      RTT (the loopback percentage is reported but pessimistic: a
      sub-100us RTT amplifies a ~2us fixed cost)

    The same two workloads are re-run as ``flight`` cells with the
    flight recorder ON vs OFF (ledger left on in both arms), proving the
    always-on ring stays inside the same <5% p50 budget.

    Then the in-run attribution assertion: a REST request (bench-local
    route) whose handler runs ``distributed_map_reduce`` must leave a
    ledger on its trace carrying BOTH client-side categories (RPC bytes)
    and remote-side categories (the peer's shard wall).  Writes
    OBS_BENCH.json and prints the same JSON; exits 1 when over budget or
    when attribution came back empty.
    """
    import urllib.request

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from h2o3_tpu.api import start_server
    from h2o3_tpu.cluster import frames as cframes
    from h2o3_tpu.cluster import tasks as ctasks
    from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
    from h2o3_tpu.frame.frame import Column, ColType, Frame
    from h2o3_tpu.rapids.runtime import Session, exec_rapids
    from h2o3_tpu.util import flight as flight_mod
    from h2o3_tpu.util import ledger as ledger_mod
    from h2o3_tpu.util import telemetry

    n_rows = int(os.environ.get("BENCH_OBS_ROWS", 200_000))
    reps = int(os.environ.get("BENCH_OBS_REPS", 40))

    def _pct(samples, q):
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * len(s)))]

    def _ab(fn, n, warmup=3, toggle=None):
        """Alternating-block A/B: returns (on_samples, off_samples).
        ``toggle`` flips the subsystem under test (default: the cost
        ledger; the flight cells pass the recorder's switch)."""
        toggle = toggle or ledger_mod.set_enabled
        for _ in range(warmup):
            fn()
        on, off = [], []
        block = max(1, n // 4)
        for _ in range(4):
            for enabled, sink in ((True, on), (False, off)):
                toggle(enabled)
                for _ in range(block):
                    t = time.perf_counter()
                    fn()
                    sink.append(time.perf_counter() - t)
        toggle(True)
        return on, off

    # -- cell 1: warm fused Rapids dispatch --------------------------------
    rng = np.random.default_rng(7)
    session = Session()
    session.assign("ob", Frame([
        Column("x", rng.standard_normal(n_rows), ColType.NUM),
        Column("y", rng.standard_normal(n_rows), ColType.NUM),
    ]))
    expr = ("(sum (* (sqrt (abs (+ (cols_py ob 0) (cols_py ob 1)))) "
            "(+ (* (floor (cols_py ob 1)) 0.25) (% (cols_py ob 0) 3))))")
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
    rap_on, rap_off = _ab(lambda: exec_rapids(expr, session), reps)
    rap_on_ms = _pct(rap_on, 0.5) * 1e3
    rap_off_ms = _pct(rap_off, 0.5) * 1e3
    rap_pct = (rap_on_ms - rap_off_ms) / max(rap_off_ms, 1e-9) * 100
    rapids_cell = {
        "ledger_off_p50_ms": round(rap_off_ms, 3),
        "ledger_on_p50_ms": round(rap_on_ms, 3),
        "overhead_pct_p50": round(rap_pct, 2),
        "budget": {"pct_p50": 5.0},
        "within_budget": rap_pct <= 5.0,
    }

    # -- flight cell 1: same warm dispatch, recorder ON vs OFF ------------
    # the hot serving path has no flight choke points (only evictions and
    # shed record), so this cell proves the always-on default costs nothing
    # where latency matters most
    frap_on, frap_off = _ab(lambda: exec_rapids(expr, session), reps,
                            toggle=flight_mod.set_enabled)
    frap_on_ms = _pct(frap_on, 0.5) * 1e3
    frap_off_ms = _pct(frap_off, 0.5) * 1e3
    frap_pct = (frap_on_ms - frap_off_ms) / max(frap_off_ms, 1e-9) * 100
    flight_rapids_cell = {
        "flight_off_p50_ms": round(frap_off_ms, 3),
        "flight_on_p50_ms": round(frap_on_ms, 3),
        "overhead_pct_p50": round(frap_pct, 2),
        "budget": {"pct_p50": 5.0},
        "within_budget": frap_pct <= 5.0,
    }

    # -- cell 2 + attribution: 2-node cloud, REST front -------------------
    a = Cloud("obs-bench", "obs-n0", hb_interval=0.2)
    b = Cloud("obs-bench", "obs-n1", hb_interval=0.2)
    srv = None
    try:
        a.start([])
        b.start([a.info.addr])
        t0 = time.time()
        while time.time() - t0 < 30:
            if a.size() == 2 and a.consensus() and b.consensus():
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("2-node obs-bench cloud never formed")
        ctasks.install(a)
        ctasks.install(b)
        peer = next(m for m in a.members_sorted()
                    if m.info.name == "obs-n1")

        def _echo():
            with telemetry.Span("obs_bench_echo"):
                a.client.call(peer.info.addr, "echo", b"x", timeout=5.0,
                              target=peer.info.ident)

        echo_on, echo_off = _ab(_echo, reps * 4)
        on_us = _pct(echo_on, 0.5) * 1e6
        off_us = _pct(echo_off, 0.5) * 1e6
        overhead_us = on_us - off_us
        ref_rtt_us, budget_us = 500.0, 500.0 * 0.05
        echo_cell = {
            "ledger_off_p50_us": round(off_us, 1),
            "ledger_on_p50_us": round(on_us, 1),
            "overhead_us_p50": round(overhead_us, 1),
            "overhead_pct_p50_loopback": round(
                overhead_us / max(off_us, 1e-9) * 100, 1),
            "budget": {
                "pct_p50": 5.0,
                "reference_rtt_us": ref_rtt_us,
                "overhead_budget_us": budget_us,
            },
            "within_budget": overhead_us <= budget_us,
        }

        # -- flight cell 2: traced echo, recorder ON vs OFF ---------------
        # every successful non-heartbeat call appends one structured event
        # to the ring — the recorder's worst per-call tax; same 500us
        # reference-RTT budget as the ledger cell
        fecho_on, fecho_off = _ab(_echo, reps * 4,
                                  toggle=flight_mod.set_enabled)
        f_on_us = _pct(fecho_on, 0.5) * 1e6
        f_off_us = _pct(fecho_off, 0.5) * 1e6
        f_overhead_us = f_on_us - f_off_us
        flight_echo_cell = {
            "flight_off_p50_us": round(f_off_us, 1),
            "flight_on_p50_us": round(f_on_us, 1),
            "overhead_us_p50": round(f_overhead_us, 1),
            "overhead_pct_p50_loopback": round(
                f_overhead_us / max(f_off_us, 1e-9) * 100, 1),
            "budget": {
                "pct_p50": 5.0,
                "reference_rtt_us": ref_rtt_us,
                "overhead_budget_us": budget_us,
            },
            "within_budget": f_overhead_us <= budget_us,
        }

        # REST -> distributed_map_reduce attribution, through the full
        # middleware (the REST span is the trace root the remote shard
        # execution must fold back into)
        set_local_cloud(a)
        srv = start_server(port=0)
        host = {"x": np.arange(50_000, dtype=np.float64),
                "y": (np.arange(50_000, dtype=np.float64) * 3) % 17}

        def bench_dmr(params):
            out = ctasks.distributed_map_reduce(
                cframes.mr_sum_xy, host, cloud=a)
            return {"leaves": [float(v) for v in jax.tree.leaves(out)]}

        srv.registry.register("GET", "/3/BenchDMR", bench_dmr,
                              "bench-only: REST-rooted distributed mr")
        with urllib.request.urlopen(srv.url + "/3/BenchDMR") as resp:
            assert resp.status == 200
            tid = resp.headers["X-H2O3-Trace-Id"]
        entry = ledger_mod.LEDGER.get(tid)
        assert entry is not None, "REST dmr trace has no ledger entry"
        total = entry["total"]
        client_ok = (total.get(ledger_mod.RPC_SENT_BYTES, 0) > 0
                     and total.get(ledger_mod.RPC_RECV_BYTES, 0) > 0)
        remote = entry["nodes"].get("obs-n1", {})
        remote_ok = remote.get(ledger_mod.SHARD_WALL_SECONDS, 0) > 0
        attribution = {
            "trace_id": tid,
            "client_categories_nonempty": client_ok,
            "remote_categories_nonempty": remote_ok,
            "nodes": sorted(entry["nodes"]),
            "total": {k: round(v, 6) for k, v in sorted(total.items())},
        }
    finally:
        if srv is not None:
            srv.stop()
        set_local_cloud(None)
        a.stop()
        b.stop()

    ok = (rapids_cell["within_budget"] and echo_cell["within_budget"]
          and flight_rapids_cell["within_budget"]
          and flight_echo_cell["within_budget"]
          and client_ok and remote_ok)
    result = {
        "metric": "ledger_overhead_pct_p50_warm_rapids",
        "value": rapids_cell["overhead_pct_p50"],
        "unit": "% (ledger on vs off, warm fused Rapids dispatch p50)",
        "detail": {
            "n_rows": n_rows,
            "rapids_warm_dispatch": rapids_cell,
            "rpc_echo_traced": echo_cell,
            "flight": {
                "rapids_warm_dispatch": flight_rapids_cell,
                "rpc_echo_traced": flight_echo_cell,
            },
            "rest_dmr_attribution": attribution,
        },
    }
    with open(os.path.join(_HERE, "OBS_BENCH.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    if not ok:
        sys.exit(1)


def _chaos_bench() -> None:
    """Chaos recovery microbench (the failure model's price tags).

    Boots a 3-node localhost cloud (this process + two nodeproc
    children), replicates keys across it, then SIGKILLs one child and
    measures what recovery actually costs: how long until the first
    replica-served read of a key the victim homed (the read-repair
    path), what fraction of replicated keys stay readable through the
    death, distributed map_reduce wall clock with a rescheduled range
    vs healthy, and the time for membership to reconverge on the
    survivors.  Prints ONE JSON line and mirrors it to
    CHAOS_BENCH.json.  CPU-only: the fan-out payloads are tiny."""
    import platform
    import signal as _signal
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from h2o3_tpu.cluster import tasks as ctasks
    from h2o3_tpu.cluster.membership import boot_node, set_local_cloud
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.util import telemetry

    import numpy as np

    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    with open(os.path.join(tmp, "chaos_bench_mrfns.py"), "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "def stat(cols, mask):\n"
            "    return {'s': jnp.sum(jnp.where(mask, cols['x'], 0.0)),\n"
            "            'n': jnp.sum(mask.astype(jnp.float32))}\n")
    sys.path.insert(0, tmp)
    import chaos_bench_mrfns as mrfns

    store = KeyedStore()
    cloud = boot_node("chaos-bench", "cb-n0", hb_interval=0.1, store=store)
    router = store.router
    flat = os.path.join(tmp, "flatfile")
    with open(flat, "w") as f:
        f.write(f"{cloud.info.host}:{cloud.info.port}\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = tmp + os.pathsep + _HERE + os.pathsep + \
        env.get("PYTHONPATH", "")
    children = {}
    for name in ("cb-n1", "cb-n2"):
        children[name] = subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
             "--cluster-name", "chaos-bench", "--node-name", name,
             "--flatfile", flat, "--hb-interval", "0.1"],
            stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT, cwd=tmp, env=env)
    try:
        t_form = time.perf_counter()
        t0 = time.time()
        while time.time() - t0 < 60:
            if cloud.size() == 3 and cloud.consensus():
                break
            time.sleep(0.02)
        else:
            raise RuntimeError("3-node chaos-bench cloud never formed")
        formation_s = time.perf_counter() - t_form

        victim = "cb-n2"
        keys = {f"chaos-bench/k{i}": [i, i * 2] for i in range(32)}
        for k, v in sorted(keys.items()):
            store.put(k, v, replicas=3)
        victim_keys = [k for k in sorted(keys)
                       if router.home_name(k) == victim]

        cols = {"x": (np.arange(30011) % 97).astype(np.float32)}
        baseline = ctasks.distributed_map_reduce(
            mrfns.stat, cols, cloud=None)
        healthy = []
        for _ in range(3):
            t = time.perf_counter()
            out = ctasks.distributed_map_reduce(mrfns.stat, cols,
                                                cloud=cloud)
            healthy.append(time.perf_counter() - t)
        assert float(out["s"]) == float(baseline["s"])
        healthy_s = sorted(healthy)[1]  # median of 3

        # -- nemesis: SIGKILL one child, then price the recovery paths
        children[victim].send_signal(_signal.SIGKILL)
        children[victim].wait(timeout=10)
        t_kill = time.perf_counter()

        first_read_us = None
        readable = 0
        for k in victim_keys + [k for k in sorted(keys)
                                if k not in victim_keys]:
            t = time.perf_counter()
            ok = store.get(k) == keys[k]
            dt = time.perf_counter() - t
            readable += bool(ok)
            if first_read_us is None and k in victim_keys:
                first_read_us = round(dt * 1e6, 1)

        t = time.perf_counter()
        recovered = ctasks.distributed_map_reduce(mrfns.stat, cols,
                                                  cloud=cloud)
        recovered_s = time.perf_counter() - t
        bit_identical = (float(recovered["s"]) == float(baseline["s"])
                         and float(recovered["n"]) == float(baseline["n"]))

        while time.time() - t0 < 120:
            if cloud.size() == 2:
                break
            time.sleep(0.02)
        reconverge_s = time.perf_counter() - t_kill

        tel = {k: v for k, v in telemetry.REGISTRY.summary().items()
               if k.startswith(("cluster_fanout", "cluster_dkv",
                                "cluster_removals", "rpc_retries"))}
        result = {
            "metric": "chaos_reconverge_seconds",
            "value": round(reconverge_s, 3),
            "unit": ("seconds from SIGKILL to survivor membership "
                     "(3->2 nodes, hb 0.1s)"),
            "vs_baseline": round(recovered_s / max(healthy_s, 1e-9), 2),
            "detail": {
                "host_cpus": os.cpu_count(),
                "platform": platform.platform(),
                "formation_s": round(formation_s, 3),
                "mr_healthy_p50_s": round(healthy_s, 4),
                "mr_recovered_s": round(recovered_s, 4),
                "mr_recovered_bit_identical": bit_identical,
                "keys_replicated": len(keys),
                "keys_homed_on_victim": len(victim_keys),
                "keys_readable_after_kill": readable,
                "first_victim_key_read_us": first_read_us,
                "vs_baseline_is": "recovered map_reduce / healthy p50",
            },
            "telemetry": {k: (round(v, 3) if isinstance(v, float) else v)
                          for k, v in tel.items()},
        }
        with open(os.path.join(_HERE, "CHAOS_BENCH.json"), "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result))
    finally:
        for child in children.values():
            try:
                child.stdin.close()
                child.wait(timeout=10)
            except Exception:
                child.kill()
        cloud.stop()
        set_local_cloud(None)


def _serve_bench_multinode(model, score_fr, smoke, *, client,
                           read_response):
    """The cluster-wide serving cell: three REAL node processes (REST +
    cluster plane each), the model imported on ONE of them and homed
    onto the DKV ring by the serving plane.  Measures

    * ``one_door_rps`` — every client through the single door that holds
      the model (the 1-node serving baseline);
    * ``three_door_rps`` — the same closed-loop load spread across ALL
      front doors: two of them forward over ``predict_remote`` to the
      model's ring home, where bundles coalesce (dispatches < forwarded
      requests, proven from the home's ``/3/Metrics``);
    * ``replica_spill_rps`` — a second topology whose ring home is
      spawned with ``H2O3_TPU_SERVE_BUDGET=0``: every forwarded request
      sheds 429 at the home and must SPILL to the ring replica.

    ``overload_clean`` (nothing outside 2xx/408/413/429 anywhere) and
    ``bit_identical`` (a forwarded/spilled prediction CSV byte-equal to
    the home-door's local one) are asserted IN-RUN — a violation raises
    and fails the bench."""
    import asyncio
    import shutil
    import socket
    import tempfile
    import urllib.parse
    import urllib.request

    from h2o3_tpu.cluster.dkv import HashRing
    from h2o3_tpu.frame.persist import save_frame
    from h2o3_tpu.models.persist import save_model

    mn_duration = 0.35 if smoke else 2.0
    one_door_clients = 6 if smoke else 24
    three_door_clients = 6 if smoke else 24
    overload_total = 0 if smoke else 384
    spill_clients = 4 if smoke else 16

    mkey, fkey = "sb_multi", "sb_score.hex"
    mpath = f"/3/Predictions/models/{mkey}/frames/{fkey}"
    tmp = tempfile.mkdtemp(prefix="serve-bench-mn-")
    frame_path = save_frame(score_fr, os.path.join(tmp, "score.h2f"))
    model_path = save_model(model, os.path.join(tmp, "model.bin"))

    def _ctl(base, method, path, data=None, retries=40):
        body = json.dumps(data).encode() if data is not None else None
        hdrs = {"Content-Type": "application/json"} if body else {}
        last = None
        for _ in range(retries):
            req = urllib.request.Request(
                base + path, data=body, headers=hdrs, method=method)
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read())
            except Exception as e:  # noqa: BLE001  (node still booting)
                last = e
                time.sleep(0.25)
        raise RuntimeError(f"{method} {path} on {base} failed: {last}")

    def _metric(base, name, **labels):
        fam = _ctl(base, "GET", "/3/Metrics")["metrics"].get(name)
        if not fam:
            return 0.0
        return sum(s["value"] for s in fam["series"]
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    def _hist(base, name):
        fam = _ctl(base, "GET", "/3/Metrics")["metrics"].get(name)
        if not fam:
            return 0.0, 0.0
        return (float(sum(s["count"] for s in fam["series"])),
                float(sum(s["sum"] for s in fam["series"])))

    def _csv(base, frame_id):
        url = (base + "/3/DownloadDataset?frame_id="
               + urllib.parse.quote(frame_id))
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.read()

    def _free_ports(n):
        socks = [socket.socket() for _ in range(n)]
        try:
            for s in socks:
                s.bind(("127.0.0.1", 0))
            return [s.getsockname()[1] for s in socks]
        finally:
            for s in socks:
                s.close()

    def _boot(tag, home_env=None):
        """Spawn + form a 3-node cloud; returns (procs, REST bases,
        home door index for ``mkey``).  Ports are parent-picked so ring
        idents — and therefore the model's home — are known up front."""
        rpc, rest = _free_ports(3), _free_ports(3)
        names = [f"sb{tag}{i}" for i in range(3)]
        idents = [f"{names[i]}@127.0.0.1:{rpc[i]}" for i in range(3)]
        home_i = idents.index(HashRing(idents).homes(mkey, 1)[0])
        procs = []
        for i in range(3):
            ff = os.path.join(tmp, f"flatfile_{tag}{i}")
            with open(ff, "w") as f:
                f.write("".join(f"127.0.0.1:{p}\n"
                                for j, p in enumerate(rpc) if j != i))
            env = dict(os.environ)
            env.pop("BENCH_SERVE_SMOKE", None)
            env.update(JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                       H2O3_TPU_HB_INTERVAL="0.1",
                       H2O3_TPU_SERVE_REPLICAS="1",
                       H2O3_TPU_BATCH_WINDOW_MS="6.0")
            if home_env and i == home_i:
                env.update(home_env)
            log = open(os.path.join(tmp, f"{names[i]}.log"), "wb")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "h2o3_tpu",
                 "--name", names[i], "--port", str(rest[i]),
                 "--cluster-name", f"sbench{tag}",
                 "--node-name", names[i],
                 "--cluster-port", str(rpc[i]), "--flatfile", ff],
                stdout=log, stderr=log, env=env, cwd=_HERE))
        bases = [f"http://127.0.0.1:{p}" for p in rest]
        deadline = time.time() + 90
        sizes = []
        while time.time() < deadline:
            sizes = [len(_ctl(b, "GET", "/3/Cloud").get("nodes", []))
                     for b in bases]
            if sizes == [3, 3, 3]:
                return procs, bases, home_i
            time.sleep(0.2)
        raise RuntimeError(f"multinode cloud never formed: {sizes}")

    def _seed(bases, import_door):
        for b in bases:
            _ctl(b, "POST", "/3/Frames/load",
                 {"dir": frame_path, "frame_id": fkey})
        _ctl(bases[import_door], "POST", "/99/Models.bin",
             {"dir": model_path, "model_id": mkey})

    def _halt(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except Exception:
                p.kill()

    def _mn_req(door, i):
        body = json.dumps(
            {"predictions_frame": f"sb_pred_{door}_{i % 8}"}).encode()
        return (f"POST {mpath} HTTP/1.1\r\nHost: localhost\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n").encode() + body

    async def _mn_cell(doors, n_clients):
        """doors: list of (host, port, door index); clients round-robin
        across them, closed-loop for ``mn_duration``."""
        for host, port, d in doors:
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(_mn_req(d, 0))
            await writer.drain()
            st, _ = await read_response(reader)
            writer.close()
            if st != 200:
                raise RuntimeError(
                    f"multinode cold request on door {d} answered {st}")
        lat, statuses, errors = [], {}, [0]
        stop_t = time.perf_counter() + mn_duration + 0.25
        await asyncio.gather(*(
            client(doors[i % len(doors)][0], doors[i % len(doors)][1],
                   _mn_req(doors[i % len(doors)][2], i), stop_t, lat,
                   statuses, errors, stagger=0.25 * i / n_clients)
            for i in range(n_clients)))
        lat.sort()
        n_ok = len(lat)
        return {
            "p50_ms": round(lat[n_ok // 2] * 1e3, 3) if n_ok else None,
            "rps": round(n_ok / mn_duration, 1),
            "statuses": {str(k): v for k, v in sorted(statuses.items())},
            "conn_errors": errors[0],
        }

    def _doors(bases, idx):
        out = []
        for i in idx:
            host, port = bases[i][len("http://"):].split(":")
            out.append((host, int(port), i))
        return out

    cells = {}
    all_statuses = []
    try:
        # -- topology A: normal budgets.  Import the model on a door
        # that is NOT the ring home: the importing door scores its own
        # copy locally (the 1-node baseline), while the OTHER doors miss
        # in DKV (the model object is node-local and the ring home holds
        # only the serving blob) and must forward through the ring -----
        procs, bases, home_i = _boot("a")
        imp = (home_i + 1) % 3
        third = 3 - home_i - imp
        try:
            _seed(bases, import_door=imp)
            cells["one_door"] = asyncio.run(
                _mn_cell(_doors(bases, [imp]), one_door_clients))
            fwd0 = sum(_metric(b, "serve_forward_total") for b in bases)
            disp0, req0 = _hist(bases[home_i], "predict_batch_size")
            cells["three_door"] = asyncio.run(
                _mn_cell(_doors(bases, [0, 1, 2]), three_door_clients))
            forwarded = sum(_metric(b, "serve_forward_total")
                            for b in bases) - fwd0
            disp1, req1 = _hist(bases[home_i], "predict_batch_size")
            dispatches, coalesced = disp1 - disp0, req1 - req0
            if overload_total:
                cells["three_door_overload"] = asyncio.run(
                    _mn_cell(_doors(bases, [0, 1, 2]), overload_total))
            # bit-identity: a forwarded door's prediction CSV byte-equal
            # to the model-holding door's locally scored one
            ref_csv = _csv(bases[imp], f"sb_pred_{imp}_0")
            fwd_csv = _csv(bases[third], f"sb_pred_{third}_0")
        finally:
            _halt(procs)

        # -- topology B: the ring home sheds EVERYTHING; forwarded load
        # must spill to the ring replica.  Import on a non-home door
        # again and aim the client load at the THIRD door, which holds
        # nothing locally — every request must forward, shed, spill ----
        procs, bases, home_i = _boot(
            "b", home_env={"H2O3_TPU_SERVE_BUDGET": "0"})
        imp = (home_i + 1) % 3
        front = 3 - home_i - imp
        try:
            _seed(bases, import_door=imp)
            spill0 = sum(_metric(b, "serve_replica_spill_total")
                         for b in bases)
            cells["replica_spill"] = asyncio.run(
                _mn_cell(_doors(bases, [front]), spill_clients))
            spilled = sum(_metric(b, "serve_replica_spill_total")
                          for b in bases) - spill0
            spill_csv = _csv(bases[front], f"sb_pred_{front}_0")
        finally:
            _halt(procs)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    for c in cells.values():
        all_statuses.extend(c["statuses"])
    overload_clean = not [
        s for s in all_statuses
        if not (200 <= int(s) < 300 or int(s) in (408, 413, 429))]
    bit_identical = bool(ref_csv) and ref_csv == fwd_csv == spill_csv
    out = {
        "nodes": 3,
        "one_door_rps": cells["one_door"]["rps"],
        "three_door_rps": cells["three_door"]["rps"],
        "three_vs_one": round(
            cells["three_door"]["rps"] / cells["one_door"]["rps"], 2)
        if cells["one_door"]["rps"] else 0.0,
        "replica_spill_rps": cells["replica_spill"]["rps"],
        "forwarded_requests": forwarded,
        "home_dispatches": dispatches,
        "home_coalesced_requests": coalesced,
        "replica_spilled": spilled,
        "overload_clean": overload_clean,
        "bit_identical": bit_identical,
        "cells": cells,
    }
    # the in-run contract: violations FAIL the bench, they don't just
    # dent a number in the JSON
    if not overload_clean:
        raise RuntimeError(f"multinode serving answered outside "
                           f"2xx/408/413/429: {out}")
    if not bit_identical:
        raise RuntimeError("forwarded/spilled predictions are not "
                           "byte-identical to home-door scoring")
    if not (forwarded > 0 and spilled > 0):
        raise RuntimeError(f"serving ring never exercised: {out}")
    if not dispatches < coalesced:
        raise RuntimeError(
            f"forwarded requests did not coalesce at the home: "
            f"{dispatches} dispatches for {coalesced} requests")
    return out


def _serve_bench():
    """Serving-plane microbench (the async front-end's price tags).

    Trains one GBM in-process, parks a scoring frame in DKV, then runs
    closed-loop keep-alive HTTP clients (asyncio, one loop — 4096 real
    client threads would measure the client, not the server) against three
    transports: the thread-per-connection baseline (server_threaded.py),
    the event loop with coalescing off, and the event loop with the
    scoring coalescer on.  Per cell: first-request (cold) latency, warm
    p50/p99, RPS, status mix.  The headline is warm scoring RPS of the
    coalescing event loop vs the threaded baseline at the reference
    client count; the overload cell (4096 clients) must answer with
    nothing outside 2xx/408/413/429.  Prints ONE JSON line and mirrors
    it to SERVE_BENCH.json.  CPU-only: scoring programs are tiny.
    BENCH_SERVE_SMOKE=1 shrinks everything for the tier-1 test."""
    import asyncio
    import platform
    import threading  # noqa: F401  (server machinery: imported for clarity)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from h2o3_tpu import Frame
    from h2o3_tpu.api.server import H2OServer
    from h2o3_tpu.api.server_threaded import ThreadedH2OServer
    from h2o3_tpu.keyed import DKV
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.util import telemetry

    smoke = bool(os.environ.get("BENCH_SERVE_SMOKE"))
    n_train = 1500 if smoke else 20000
    n_score = 256 if smoke else 2048
    ntrees = 3 if smoke else 8
    duration = 0.4 if smoke else 3.0
    client_counts = [4] if smoke else [16, 256, 4096]
    ref_clients = 4 if smoke else 256
    overload_clients = 4 if smoke else 4096
    # thread-per-connection cannot field the overload cell: 4096 clients
    # would need 4096 server threads on this host
    threaded_max_clients = 256
    pred_keyspace = 64  # predictions_frame targets cycle: DKV stays bounded

    Xtr, ytr = synth_higgs(n_train, seed=1)
    names = [f"x{i}" for i in range(Xtr.shape[1])]
    train_fr = Frame.from_dict(
        {n: Xtr[:, i] for i, n in enumerate(names)} | {"y": ytr})
    model = GBM(response_column="y", ntrees=ntrees, max_depth=4,
                seed=7).train(train_fr)
    Xs, _ = synth_higgs(n_score, seed=2)
    score_fr = Frame.from_dict({n: Xs[:, i] for i, n in enumerate(names)})
    score_fr.key = "serve_bench.hex"
    DKV.put(score_fr.key, score_fr)
    path = f"/3/Predictions/models/{model.key}/frames/{score_fr.key}"

    def _request_bytes(i):
        body = json.dumps(
            {"predictions_frame": f"serve_bench_pred_{i % pred_keyspace}"}
        ).encode()
        return (f"POST {path} HTTP/1.1\r\nHost: localhost\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
                ).encode() + body

    async def _read_response(reader):
        line = await reader.readline()
        if not line:
            raise ConnectionError("server closed")
        parts = line.split()
        status = int(parts[1])
        # the threaded baseline answers HTTP/1.0: close-per-response
        # unless it says keep-alive (reconnect cost is part of its price)
        length, keep = 0, parts[0] != b"HTTP/1.0"
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin-1").partition(":")
            k = k.strip().lower()
            if k == "content-length":
                length = int(v)
            elif k == "connection" and "close" in v.lower():
                keep = False
        if length:
            await reader.readexactly(length)
        return status, keep

    async def _client(host, port, req, stop_t, lat, statuses, errors,
                      stagger):
        await asyncio.sleep(stagger)
        reader = writer = None
        try:
            while time.perf_counter() < stop_t:
                if writer is None:
                    try:
                        reader, writer = await asyncio.open_connection(
                            host, port)
                    except OSError:
                        errors[0] += 1
                        await asyncio.sleep(0.01)
                        continue
                t0 = time.perf_counter()
                try:
                    writer.write(req)
                    await writer.drain()
                    status, keep = await _read_response(reader)
                except (OSError, ConnectionError,
                        asyncio.IncompleteReadError):
                    errors[0] += 1
                    writer.close()
                    writer = None
                    continue
                lat.append(time.perf_counter() - t0)
                statuses[status] = statuses.get(status, 0) + 1
                if status < 200 or status >= 300:
                    lat.pop()  # RPS/latency count successes only
                if not keep:
                    writer.close()
                    writer = None
                if status == 429:
                    await asyncio.sleep(0.005)  # shed: back off, retry
        finally:
            if writer is not None:
                writer.close()

    async def _run_cell(host, port, n_clients):
        # cold: the first request a fresh transport serves (process-wide
        # jit caches persist across cells, so only the first cell pays
        # the compile — recorded as-is, the matrix shows it)
        t0 = time.perf_counter()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(_request_bytes(0))
        await writer.drain()
        st, _ = await _read_response(reader)
        cold_ms = (time.perf_counter() - t0) * 1e3
        writer.close()
        if st != 200:
            raise RuntimeError(f"cold request answered {st}")
        lat, statuses, errors = [], {}, [0]
        stop_t = time.perf_counter() + duration + 0.25
        await asyncio.gather(*(
            _client(host, port, _request_bytes(i), stop_t, lat, statuses,
                    errors, stagger=0.25 * i / n_clients)
            for i in range(n_clients)))
        lat.sort()
        n_ok = len(lat)
        return {
            "cold_ms": round(cold_ms, 2),
            "p50_ms": round(lat[n_ok // 2] * 1e3, 3) if n_ok else None,
            "p99_ms": round(lat[min(n_ok - 1, int(n_ok * 0.99))] * 1e3,
                            3) if n_ok else None,
            "rps": round(n_ok / duration, 1),
            "statuses": {str(k): v for k, v in sorted(statuses.items())},
            "conn_errors": errors[0],
        }

    servers = [
        ("threaded", lambda: ThreadedH2OServer(port=0)),
        ("event_loop", lambda: H2OServer(
            port=0, http=dict(batch_window_ms=0))),
        ("event_loop_coalesce", lambda: H2OServer(
            port=0, http=dict(batch_window_ms=4.0))),
    ]
    cells = []
    warm_rps = {}
    try:
        for sname, mk in servers:
            for n_clients in client_counts:
                if sname == "threaded" and n_clients > threaded_max_clients:
                    cells.append({"server": sname, "clients": n_clients,
                                  "skipped": "thread-per-connection "
                                             "cannot field this load"})
                    continue
                srv = mk().start()
                try:
                    cell = asyncio.run(
                        _run_cell("127.0.0.1", srv.port, n_clients))
                finally:
                    srv.stop()
                cell.update(server=sname, clients=n_clients)
                cells.append(cell)
                warm_rps[(sname, n_clients)] = cell["rps"]

        # bit-identity: what the coalesced path left in DKV == serial
        serial = model.predict(score_fr)
        got = DKV.get(f"serve_bench_pred_{0}")
        bit_identical = bool(got is not None and all(
            np.array_equal(np.asarray(a.data, dtype=np.float64),
                           np.asarray(b.data, dtype=np.float64))
            for a, b in zip(serial.columns, got.columns)))

        overload = next(
            (c for c in cells if c.get("server") == "event_loop_coalesce"
             and c.get("clients") == overload_clients), None)
        overload_clean = overload is not None and not [
            s for s in overload["statuses"]
            if not (200 <= int(s) < 300 or int(s) in (408, 413, 429))]

        multinode = _serve_bench_multinode(
            model, score_fr, smoke,
            client=_client, read_response=_read_response)
        base = warm_rps.get(("threaded", ref_clients), 0.0)
        coal = warm_rps.get(("event_loop_coalesce", ref_clients), 0.0)
        speedup = round(coal / base, 2) if base else 0.0
        tel = {k: v for k, v in telemetry.REGISTRY.summary().items()
               if k.startswith(("http_", "predict_batch_size"))}
        result = {
            "metric": "serve_warm_rps_speedup",
            "value": speedup,
            "unit": (f"x warm scoring RPS at {ref_clients} clients, "
                     "coalescing event loop vs thread-per-connection"),
            "vs_baseline": speedup,
            "detail": {
                "host_cpus": os.cpu_count(),
                "platform": platform.platform(),
                "model": f"GBM ntrees={ntrees} depth=4 on "
                         f"{n_train}x28 synth-higgs",
                "score_rows": n_score,
                "duration_s": duration,
                "matrix": cells,
                "bit_identical": bit_identical,
                "overload_clean": overload_clean,
                "multinode": multinode,
                "smoke": smoke,
            },
            "telemetry": {k: (round(v, 3) if isinstance(v, float) else v)
                          for k, v in tel.items()},
        }
        if not smoke:
            with open(os.path.join(_HERE, "SERVE_BENCH.json"), "w") as f:
                json.dump(result, f, indent=1)
        print(json.dumps(result))
        return result
    finally:
        DKV.remove(score_fr.key)
        for i in range(pred_keyspace):
            try:
                DKV.remove(f"serve_bench_pred_{i}")
            except Exception:
                pass
        try:
            DKV.remove(model.key)
        except Exception:
            pass


def main() -> None:
    t_start = time.time()
    # two probe attempts: a single transient tunnel blip (one-off
    # XlaRuntimeError during init) must not turn a healthy backend into
    # an official 0.0 — only a repeatable failure is a diagnosis
    ok, info, note = _run_child("--probe", PROBE_TIMEOUT)
    if not ok:
        time.sleep(3.0)
        ok, info, note = _run_child("--probe", PROBE_TIMEOUT)
    if not ok:
        _fail("backend unreachable (pre-flight probe, 2 attempts)", note)
    print(f"# probe ok: {info} in {time.time() - t_start:.1f}s",
          file=sys.stderr)

    last_note = ""
    for i in range(ATTEMPTS):
        # the final attempt pins the training program to the direct
        # (non-subtraction) level flow — the configuration every prior
        # official number was measured with — so a regression in a newer
        # default can never turn the whole bench into a zero
        extra = ({"H2O3_TPU_TREE_SUBTRACT": "0"}
                 if i == ATTEMPTS - 1 and
                 "H2O3_TPU_TREE_SUBTRACT" not in os.environ else None)
        ok, result, note = _run_child(
            "--worker", ATTEMPT1_TIMEOUT if i == 0 else ATTEMPT_TIMEOUT,
            extra_env=extra)
        if ok and result and result.get("value"):
            # mirror immediately so a later crash can't erase the number —
            # but never let the CPU test hook clobber a real TPU artifact
            if not os.environ.get("BENCH_FORCE_CPU"):
                try:
                    mirrored = dict(result)
                    mirrored["round"] = _current_round()
                    with open(PARTIAL_PATH, "w") as f:
                        json.dump(mirrored, f)
                except OSError:
                    pass
            print(json.dumps(result))
            return
        last_note = note or "worker returned no result"
        print(f"# bench attempt {i + 1}/{ATTEMPTS} failed: {last_note}",
              file=sys.stderr)
        if i < ATTEMPTS - 1:
            time.sleep(3.0 * (i + 1))
    _fail(f"all {ATTEMPTS} attempts failed", last_note)


def _codec_bench() -> None:
    """CPU chunk-codec bench (codec-layer PR acceptance).

    Parses the mixed NUM/CAT/TIME/STR/NUM CSV (~BENCH_CODEC_MB, default
    96) onto a 2-node in-process cloud twice — codecs on (the default
    data plane) and ``H2O3_TPU_CODECS=0`` — and prices the layer:
    resident (ring wire) bytes/row and replica fan-out bytes encoded vs
    dense, the warm fused Rapids pipeline wall over encoded vs dense
    chunks, and the parse→fit working set (frame wire bytes + decoded
    devcache bytes by kind + peak RSS) for a distributed tree fit on the
    encoded frame.  Asserts IN-RUN that both parses materialize every
    column bit-identically (uint64 views) and that the encoded resident
    footprint is at most half the dense one.  Prints ONE JSON line and
    mirrors it to CODEC_BENCH.json (`--codec-bench`).
    """
    import resource

    import numpy as np

    from h2o3_tpu.cluster import dkv as cdkv
    from h2o3_tpu.cluster import tasks as ctasks
    from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
    from h2o3_tpu.frame import codecs as _codecs  # noqa: F401  registers
    from h2o3_tpu.frame import devcache as _devcache  # the codec meters
    from h2o3_tpu.frame.frame import ColType
    from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
    from h2o3_tpu.rapids.runtime import Session, exec_rapids
    from h2o3_tpu.util import telemetry

    size_mb = float(os.environ.get("BENCH_CODEC_MB", 96))
    reps = int(os.environ.get("BENCH_CODEC_REPS", 3))
    chunk_bytes = int(os.environ.get("H2O3_TPU_PARSE_CHUNK_BYTES",
                                     2 << 20))

    def _meter(name, **labels):
        c = telemetry.REGISTRY.get(name)
        if c is None:
            return 0.0
        return sum(s["value"] for s in c.snapshot()["series"]
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    t0 = time.time()
    text = _parse_bench_csv(size_mb)
    raw_mb = len(text.encode()) / 1e6
    print(f"# generated {raw_mb:.1f} MB csv in {time.time() - t0:.1f}s",
          file=sys.stderr)

    clouds = []
    for i in range(2):
        c = Cloud("codecbench", f"cb{i}", hb_interval=0.1)
        cdkv.install(c, KeyedStore())
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and not all(
            c.size() == 2 for c in clouds):
        time.sleep(0.02)

    saved = os.environ.get("H2O3_TPU_CODECS")
    try:
        set_local_cloud(clouds[0])
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], chunk_bytes, setup.header,
            setup.skip_blank_lines))

        def _parse(key, codecs_on):
            os.environ["H2O3_TPU_CODECS"] = "1" if codecs_on else "0"
            mix0 = {s["labels"]["codec"]: s["value"] for s in
                    telemetry.REGISTRY.get("chunk_codec_total")
                    .snapshot()["series"]} if codecs_on else {}
            r0 = _meter("cluster_chunk_replica_bytes")
            t = time.perf_counter()
            fr = ctasks.distributed_parse_chunks(
                chunks, setup, cloud=clouds[0], key=key)
            wall = time.perf_counter() - t
            mix = {}
            if codecs_on:
                for s in (telemetry.REGISTRY.get("chunk_codec_total")
                          .snapshot()["series"]):
                    codec = s["labels"]["codec"]
                    d = s["value"] - mix0.get(codec, 0.0)
                    if d:
                        mix[codec] = int(d)
            return fr, wall, _meter("cluster_chunk_replica_bytes") - r0, mix

        enc, enc_wall, enc_replica, codec_mix = _parse("codec_enc", True)
        dense, dense_wall, dense_replica, _ = _parse("codec_dense", False)
        os.environ["H2O3_TPU_CODECS"] = "1"
        nrows = enc.nrows

        # bit-identity: both chunk-homed parses must materialize every
        # column to the same bits (uint64 views for numeric, exact codes
        # + domains for CAT, element equality for STR)
        bit_identical = True
        for name in enc.names:
            a, b = enc.col(name), dense.col(name)
            if a.type != b.type or a.domain != b.domain:
                bit_identical = False
            elif a.data.dtype == object:
                bit_identical &= all(
                    x == y for x, y in zip(a.data, b.data))
            elif a.type in (ColType.NUM, ColType.TIME):
                bit_identical &= bool(np.array_equal(
                    a.numeric_view().view(np.uint64),
                    b.numeric_view().view(np.uint64)))
            else:
                bit_identical &= bool(np.array_equal(a.data, b.data))

        # warm fused pipeline over encoded vs dense chunks: drop the
        # materialized copies so the dist path (group reps + in-program
        # decode) is what actually runs
        session = Session()
        session.assign("ce", enc)
        session.assign("cd", dense)

        def _pipeline(v):
            out = exec_rapids(
                f"(sumNA (* (cols_py {v} 0) (cols_py {v} 4)))", session)
            return int(np.float64(out.value).view(np.uint64))

        def _warm(v, fr):
            fr._materialized = None
            sig = _pipeline(v)  # cold: compiles + uploads + caches
            best = None
            for _ in range(max(1, reps)):
                t = time.perf_counter()
                assert _pipeline(v) == sig
                dt = time.perf_counter() - t
                best = dt if best is None else min(best, dt)
            return best, sig

        warm_enc_s, sig_enc = _warm("ce", enc)
        warm_dense_s, sig_dense = _warm("cd", dense)
        pipeline_identical = sig_enc == sig_dense

        # parse→fit working set: a distributed tree fit straight off the
        # encoded chunks — what stays resident is the encoded ring copy
        # plus the byte-budgeted devcache entries, not a dense frame
        enc._materialized = None
        t = time.perf_counter()
        model = GBM(GBMParameters(
            nbins=16, response_column="count", ntrees=2, max_depth=3,
            min_rows=10.0, seed=11,
            ignored_columns=["str"])).train(enc)
        fit_wall = time.perf_counter() - t
        assert model is not None
        fit_cell = {
            "fit_wall_s": round(fit_wall, 3),
            "frame_wire_bytes": int(enc.nbytes_wire),
            "devcache_bytes_by_kind": {
                k: int(v) for k, v in sorted(
                    _devcache.DEVCACHE.kind_bytes().items())},
            "peak_rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
                1),
        }

        resident_ratio = enc.nbytes_wire / max(dense.nbytes_wire, 1)
        replica_ratio = enc_replica / max(dense_replica, 1.0)
        result = {
            "metric": "chunk_codec_resident_ratio",
            "unit": "x (encoded ring bytes / dense ring bytes, same frame)",
            "csv_mb": round(raw_mb, 1),
            "n_rows": nrows,
            "n_cols": len(enc.names),
            "resident": {
                "encoded_bytes_per_row": round(enc.nbytes_wire / nrows, 2),
                "dense_bytes_per_row": round(dense.nbytes_wire / nrows, 2),
                "ratio": round(resident_ratio, 4),
            },
            "replicas": {
                "encoded_replica_bytes": int(enc_replica),
                "dense_replica_bytes": int(dense_replica),
                "ratio": round(replica_ratio, 4),
            },
            "codec_mix": codec_mix,
            "parse_wall": {"encoded_s": round(enc_wall, 3),
                           "dense_s": round(dense_wall, 3)},
            "fused_pipeline": {
                "warm_encoded_s": round(warm_enc_s, 4),
                "warm_dense_s": round(warm_dense_s, 4),
                "bit_identical": pipeline_identical,
            },
            "fit_working_set": fit_cell,
            "bit_identical": bit_identical and pipeline_identical,
            "resident_ratio_within_half": resident_ratio <= 0.5,
        }
        with open(os.path.join(_HERE, "CODEC_BENCH.json"), "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result))
        if not (result["bit_identical"]
                and result["resident_ratio_within_half"]):
            sys.exit(1)
    finally:
        if saved is None:
            os.environ.pop("H2O3_TPU_CODECS", None)
        else:
            os.environ["H2O3_TPU_CODECS"] = saved
        set_local_cloud(None)
        for c in clouds:
            try:
                c.stop()
            except Exception:
                pass


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe()
    elif "--worker" in sys.argv:
        _worker()
    elif "--cache-bench" in sys.argv:
        _cache_bench()
    elif "--parse-bench" in sys.argv:
        _parse_bench()
    elif "--cluster-bench" in sys.argv:
        _cluster_bench()
    elif "--chaos-bench" in sys.argv:
        _chaos_bench()
    elif "--serve-bench" in sys.argv:
        _serve_bench()
    elif "--rapids-bench" in sys.argv:
        _rapids_bench()
    elif "--hist-bench" in sys.argv:
        _hist_bench()
    elif "--obs-bench" in sys.argv:
        _obs_bench()
    elif "--codec-bench" in sys.argv:
        _codec_bench()
    else:
        main()
