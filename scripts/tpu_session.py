"""One-shot TPU measurement session: every round-4 perf artifact in a
single backend claim.

The axon remote backend serializes sessions and a killed process wedges
it for ~25+ minutes (see .claude/skills/verify/SKILL.md), so when a
window opens the safest plan is ONE process that produces everything:

  1. KERNEL_PROBE_r05.json    — per-K kernel evidence (VERDICT r3 1d)
  2. KERNEL_LAB.json          — production vs rt1024 vs factorized per K
  3. SUBTRACT_AB_r05.json     — end-to-end A/B of the subtraction flow
  4. BENCH_PARTIAL.json       — refreshed flagship number via the fastest
                               measured configuration

Each stage is wrapped so a failure records a diagnostic and the session
moves on; artifacts are written as soon as each stage completes.

Usage: python scripts/tpu_session.py      (never under `timeout`!)
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _HERE)


def _stage(name, fn):
    t0 = time.time()
    print(f"### stage {name} start", flush=True)
    try:
        out = fn()
        print(f"### stage {name} OK in {time.time() - t0:.1f}s", flush=True)
        return out
    except Exception as e:
        print(f"### stage {name} FAILED: {type(e).__name__}: {e}",
              flush=True)
        return None


_SMOKE = os.environ.get("TPU_SESSION_SMOKE") == "1"
# smoke mode shrinks the training config too, not just the kernel stages
_ROWS = int(os.environ.get("TPU_SESSION_ROWS",
                           20_000 if _SMOKE else 2_000_000))
_TREES = int(os.environ.get("TPU_SESSION_TREES", 3 if _SMOKE else 10))


def _train_once(subtract: str, seed: int, n_rows: int = None,
                ntrees: int = None):
    """One full training run at the bench config; returns train_s."""
    from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
    from h2o3_tpu.models.tree.common import init_margin

    # NO cache_clear: `subtract` is part of _make_block_fn's cache key
    # (booster re-reads the env per train call), so each mode's warmup
    # block survives for its timed run — clearing would put a re-trace
    # inside the timed window and bias the number low
    os.environ["H2O3_TPU_TREE_SUBTRACT"] = subtract
    n_rows = n_rows or _ROWS
    ntrees = ntrees or _TREES
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, 28)).astype(np.float32)
    w = rng.normal(size=28) / np.sqrt(28)
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float64)
    params = TreeParams(ntrees=ntrees, max_depth=6, nbins=256, seed=seed)
    f0 = init_margin("bernoulli", y, 1)
    timings = {}
    train_boosted(X, "bernoulli", y, 1, f0, params, timings=timings)
    return timings["train_s"]


def main() -> None:
    import jax

    os.chdir(_HERE)  # CWD-relative outputs (KERNEL_LAB.json) land in-repo
    print("devices:", jax.devices(), flush=True)

    # 1. kernel probe (writes KERNEL_PROBE_r05.json itself)
    def probe():
        import runpy

        sys.argv = ["bench_hist_kernel",
                    os.path.join(_HERE, "KERNEL_PROBE_r05.json")]
        runpy.run_path(
            os.path.join(_HERE, "scripts", "bench_hist_kernel.py"),
            run_name="__main__")

    if not _SMOKE:
        _stage("kernel_probe", probe)

    # 2. kernel lab variant sweep (writes KERNEL_LAB.json)
    def lab():
        import runpy

        sys.argv = ["kernel_lab"]
        runpy.run_path(os.path.join(_HERE, "scripts", "kernel_lab.py"),
                       run_name="__main__")

    if not _SMOKE:
        _stage("kernel_lab", lab)

    # 3. subtraction A/B at the flagship config. Warmup each mode once
    #    (different seed), then time. The persistent cache keeps later
    #    rounds cheap.
    def ab():
        results = {}
        for mode in ("0", "1"):
            _train_once(mode, seed=12345)  # warmup/compile
            dt = _train_once(mode, seed=0)
            results[f"subtract_{mode}_train_s"] = round(dt, 3)
            results[f"subtract_{mode}_rows_per_sec"] = round(
                _ROWS * _TREES / dt, 1)
            print(results, flush=True)
        if not _SMOKE:  # a CPU smoke run must not write TPU artifacts
            with open(os.path.join(_HERE, "SUBTRACT_AB_r05.json"), "w") as f:
                json.dump(results, f, indent=1)
        return results

    ab_res = _stage("subtract_ab", ab)

    # 4. refresh the flagship partial with the best measured mode
    def refresh():
        best_mode = min(("0", "1"),
                        key=lambda m: ab_res[f"subtract_{m}_train_s"])
        dt = ab_res[f"subtract_{best_mode}_train_s"]
        value = round(_ROWS * _TREES / dt, 1)
        try:
            with open(os.path.join(_HERE, "PROGRESS.jsonl")) as f:
                rnd = json.loads(f.read().splitlines()[-1]).get("round")
        except Exception:
            rnd = None
        # vs_baseline means "vs the 8M rows/sec round target", exactly
        # like bench.py's worker — the two writers of BENCH_PARTIAL.json
        # must agree on the metric's meaning
        partial = {
            "metric": "tpu_hist_train_rows_per_sec_per_chip",
            "value": value,
            "unit": "rows/sec (n_rows*ntrees/train_time, Higgs-shaped 28f)",
            "vs_baseline": round(value / 8e6, 3),
            "detail": {"n_rows": _ROWS, "ntrees": _TREES, "max_depth": 6,
                       "train_s": dt,
                       "subtract": best_mode == "1"},
            "round": rnd,
        }
        with open(os.path.join(_HERE, "BENCH_PARTIAL.json"), "w") as f:
            json.dump(partial, f)
        print("refreshed BENCH_PARTIAL.json:", json.dumps(partial),
              flush=True)

    if ab_res and not _SMOKE:  # never let a smoke run touch the artifact
        _stage("refresh_partial", refresh)

    # 5. device-munging crossover sweep (VERDICT r4 item 7): host vs
    #    device sort and groupby at 64k..4M rows, so DIST_SORT_MIN is
    #    set from data instead of a guess.
    def crossover():
        from h2o3_tpu.rapids import dist

        sizes = ((65_536, 262_144, 1_048_576, 4_194_304)
                 if not _SMOKE else (8_192, 16_384))
        out = {"sizes": []}
        for n in sizes:
            rng = np.random.default_rng(n)
            vals = rng.normal(size=n)
            keys = dist.encode_f64(vals)
            codes = rng.integers(0, 1024, size=n).astype(np.int64)
            entry = {"n": n}
            dist.device_argsort_u64(keys)  # compile warmup
            t0 = time.time()
            dist.device_argsort_u64(keys)
            entry["device_sort_s"] = round(time.time() - t0, 4)
            t0 = time.time()
            np.argsort(keys, kind="stable")
            entry["host_sort_s"] = round(time.time() - t0, 4)
            dist.device_group_aggregate(codes, vals, 1024)  # warmup
            t0 = time.time()
            dist.device_group_aggregate(codes, vals, 1024)
            entry["device_groupby_s"] = round(time.time() - t0, 4)
            t0 = time.time()
            np.bincount(codes, minlength=1024)
            np.bincount(codes, weights=vals, minlength=1024)
            np.bincount(codes, weights=vals * vals, minlength=1024)
            entry["host_groupby_s"] = round(time.time() - t0, 4)
            out["sizes"].append(entry)
            print(entry, flush=True)
        # first size where the device sort beats host = measured crossover
        xs = [e["n"] for e in out["sizes"]
              if e["device_sort_s"] < e["host_sort_s"]]
        out["sort_crossover_rows"] = min(xs) if xs else None
        if not _SMOKE:
            with open(os.path.join(_HERE, "MUNGE_CROSSOVER_r05.json"),
                      "w") as f:
                json.dump(out, f, indent=1)
        return out

    _stage("munge_crossover", crossover)

    print("### session complete", flush=True)


if __name__ == "__main__":
    main()
