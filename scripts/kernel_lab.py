"""Kernel experiment lab: time tpu_hist variants and isolate per-level cost.

Measures, per tree level K in (1, 2, 4, 8, 16, 32):
  * the production node-matmul kernel (h2o3_tpu/ops/pallas_histogram.py);
  * a full "level step" (hist + split search + routing) to expose the glue
    residual between the kernel and the end-to-end tree time;
  * candidate variants (row-tile 1024, factorized hi/lo one-hot) before
    they are promoted into the production kernel.

Timing uses the same methodology as scripts/bench_hist_kernel.py (scan-chain
REPS applications, checksum readback, RTT subtracted — block_until_ready is
a no-op over the axon tunnel; see that file's module doc).

Usage:
  python scripts/kernel_lab.py                # full lab on TPU
  python scripts/kernel_lab.py --parity       # interpreter-mode parity (CPU)
"""

import json
import os
import sys
import time
import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PARITY = "--parity" in sys.argv
if PARITY:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if PARITY:
    # env vars alone don't switch platforms here: the axon sitecustomize
    # pins the remote backend; the config update before first backend use
    # is authoritative (same as tests/conftest.py and bench.py)
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
from h2o3_tpu.ops.pallas_histogram import (  # noqa: E402
    build_histogram_pallas,
    _build_histogram_nodematmul,
    _resolve_hist_dtype,
)

N = 2_000_000 if not PARITY else 4096
F, B1 = 28, 257
REPS = 4
LEVEL_KS = (1, 2, 4, 8, 16, 32)


# ---------------------------------------------------------------------------
# timing helpers (bench_hist_kernel methodology)


def _measure_rtt() -> float:
    tiny = jax.device_put(np.ones(8, np.float32))
    float(tiny.sum())
    t0 = time.perf_counter()
    for _ in range(10):
        float(tiny.sum())
    return (time.perf_counter() - t0) / 10


def _timed_chain(make_fn, gs_warm, gs_timed, rtt: float, tries: int = 3):
    @jax.jit
    def chained(gs):
        def body(tot, g):
            return tot + make_fn(g).sum(), None
        tot, _ = jax.lax.scan(body, jnp.float32(0.0), gs)
        return tot

    last = None
    for i in range(tries):
        try:
            gt = gs_timed * np.float32(1.0 + i * 2.0**-10)
            float(gt.sum())
            float(chained(gs_warm))
            t0 = time.perf_counter()
            float(chained(gt))
            dt = (time.perf_counter() - t0 - rtt) / gs_timed.shape[0]
            return max(dt, 1e-9)
        except Exception as e:
            last = e
            time.sleep(3.0)
    raise last


# ---------------------------------------------------------------------------


def parity_main():
    rng = np.random.default_rng(0)
    bins = rng.integers(0, B1, size=(N, F)).astype(np.int32)
    nodes = rng.integers(-1, 8, size=N).astype(np.int32)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.random(N).astype(np.float32)

    from h2o3_tpu.ops.histogram import _shard_histogram

    want = np.asarray(_shard_histogram(
        jnp.asarray(bins), jnp.asarray(nodes), jnp.asarray(g),
        jnp.asarray(h), 8, B1))

    fb = 4
    Fp = F + (-F) % fb
    bfm = np.zeros((Fp, N), np.int32)
    bfm[:F] = bins.T
    got = np.asarray(build_histogram_pallas(
        jnp.asarray(bins), jnp.asarray(nodes), jnp.asarray(g),
        jnp.asarray(h), 8, B1, row_tile=512, interpret=True,
        kernel="factorized"))
    err = np.max(np.abs(want - got))
    print(f"factorized parity max_abs_err = {err:.3e}")
    assert err < 1e-2, err
    print("PARITY OK")


def lab_main():
    rng = np.random.default_rng(0)
    bins = rng.integers(0, B1, size=(N, F)).astype(np.int32)
    fb = 8
    Fp = F + (-F) % fb
    bfm_host = np.zeros((Fp, N), np.int32)
    bfm_host[:F] = bins.T
    bins_d = jax.device_put(bins)
    bfm = jax.device_put(bfm_host)
    gs_warm = jnp.stack([jax.device_put(rng.normal(size=N).astype(np.float32))
                         for _ in range(REPS)])
    gs = jnp.stack([jax.device_put(rng.normal(size=N).astype(np.float32))
                    for _ in range(REPS)])
    h = jax.device_put(rng.random(N).astype(np.float32))

    rtt = _measure_rtt()
    print(f"rtt {rtt*1e3:.1f} ms", flush=True)
    rows = []

    dt_bf16 = jnp.bfloat16 if _resolve_hist_dtype("auto") == jnp.bfloat16 \
        else jnp.float32

    for K in LEVEL_KS:
        nodes = jax.device_put(rng.integers(0, K, size=N).astype(np.int32))
        row = {"K": K}

        # production kernel (row_tile 512)
        row["prod_ms"] = round(_timed_chain(
            lambda g: build_histogram_pallas(
                bins_d, nodes, g, h, K, B1, bins_fm=bfm),
            gs_warm, gs, rtt) * 1e3, 2)

        # row-tile 1024 variant of the production kernel
        try:
            row["rt1024_ms"] = round(_timed_chain(
                lambda g: _build_histogram_nodematmul(
                    bins_d, nodes, g, h, K, B1, row_tile=1024, feat_block=fb,
                    interpret=False, vma=(), bins_fm=None, dtype=dt_bf16),
                gs_warm, gs, rtt) * 1e3, 2)
        except Exception as e:
            row["rt1024_ms"] = f"ERR {type(e).__name__}"

        # factorized hi/lo variant (production kernel)
        try:
            row["fact_ms"] = round(_timed_chain(
                lambda g: build_histogram_pallas(
                    bins_d, nodes, g, h, K, B1, bins_fm=bfm,
                    kernel="factorized"),
                gs_warm, gs, rtt) * 1e3, 2)
        except Exception as e:
            row["fact_ms"] = f"ERR {type(e).__name__}"

        # factorized at row-tile 1024
        try:
            row["fact1024_ms"] = round(_timed_chain(
                lambda g: build_histogram_pallas(
                    bins_d, nodes, g, h, K, B1, row_tile=1024,
                    kernel="factorized"),
                gs_warm, gs, rtt) * 1e3, 2)
        except Exception as e:
            row["fact1024_ms"] = f"ERR {type(e).__name__}"

        rows.append(row)
        print(row, flush=True)

    # glue residual: one full level step (hist + split search + route)
    from h2o3_tpu.models.tree.booster import _split_search, _sel_tables, _sel_cols

    K = 32
    nodes_l = jax.device_put(rng.integers(0, K, size=N).astype(np.int32))

    def level_step(g):
        hist = build_histogram_pallas(bins_d, nodes_l, g, h, K, B1, bins_fm=bfm)
        out = _split_search(
            hist, jnp.float32(1.0), jnp.float32(0.0), jnp.float32(0.0),
            jnp.float32(0.1), jnp.ones((F,), bool), min_rows=1.0, n_bins1=B1)
        bf, bb, dl, gain, leaf = out
        f, sb, dlk, cank = _sel_tables(
            (bf, bb, dl, gain > 0), jnp.clip(nodes_l, 0, K - 1))
        b = _sel_cols(bins_d, f)
        go_left = jnp.where(b >= B1 - 1, dlk, b <= sb)
        child = 2 * nodes_l + jnp.where(go_left, 1, 2)
        return child.astype(jnp.float32).sum() + leaf.sum()

    t = _timed_chain(level_step, gs_warm, gs, rtt)
    print({"level_step_K32_ms": round(t * 1e3, 2)}, flush=True)
    rows.append({"level_step_K32_ms": round(t * 1e3, 2)})

    with open("KERNEL_LAB.json", "w") as f:
        json.dump(rows, f, indent=1)
    print("wrote KERNEL_LAB.json")


if __name__ == "__main__":
    if PARITY:
        parity_main()
    else:
        lab_main()
