#!/usr/bin/env python
"""Render span trees from a saved ``/3/Timeline`` JSON snapshot.

Pure stdlib, no repo imports — point it at anything the timeline surface
produced: ``GET /3/Timeline``, ``GET /3/Timeline?cluster=true`` (merged,
node-tagged), or ``GET /3/Timeline/nodes/{i}`` (one member's ring).

    curl -s localhost:54321/3/Timeline?cluster=true > snap.json
    python scripts/trace_view.py snap.json
    python scripts/trace_view.py snap.json --trace 1a2b3c4d5e6f7788
    curl -s localhost:54321/3/Timeline | python scripts/trace_view.py -

Output: one tree per trace, spans indented under their parents with
durations and node ids, e.g. ::

    trace 83f1d2... (4 spans, 1 event)
    rest GET /3/DKV/k1 4.2ms ok [node-a]
      rpc_client dkv_put 3.1ms ok [node-a]
        rpc_server dkv_put 0.4ms ok [node-b]
        rpc_attempt #1 0.9ms ok [node-a]   <- only when the ladder retried

A timeline event is a *span end* when it carries a ``parent_id`` key (the
Span contract: every span records parent_id, None for roots); other events
under the same trace (plain ``timeline.record`` calls, ``timed`` blocks)
attach beneath the span that was open when they were recorded.  Spans whose
parent fell off the ring render as roots, flagged ``(orphan)``.

When the snapshot was saved with ``?ledgers=true`` (a top-level
``ledgers`` map of trace_id -> cost breakdown), each span line gains the
cost columns the ledger attributed to it — ``$ compile 0.123s``,
``upload 1.2KB`` (devcache bytes), ``wire 3.4KB`` (RPC bytes both
directions), ``hist 0.5s`` (distributed tree-level histogram wall) —
and the trace header line shows the cross-node totals.
Snapshots without ledger data render exactly as before.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

#: event fields that are structural, not descriptive — everything else a
#: span carries is shown as key=value detail
_STRUCTURAL = {
    "ns", "seq", "kind", "trace_id", "span_id", "parent_id",
    "duration_ms", "ok", "node",
}


def _events_of(payload: Any) -> List[Dict[str, Any]]:
    """Accept a raw event list or any /3/Timeline response shape."""
    if isinstance(payload, list):
        return [e for e in payload if isinstance(e, dict)]
    if isinstance(payload, dict) and isinstance(payload.get("events"), list):
        return [e for e in payload["events"] if isinstance(e, dict)]
    raise ValueError(
        "unrecognized snapshot shape: want a /3/Timeline response "
        "(an object with 'events') or a bare event list")


def _is_span(ev: Dict[str, Any]) -> bool:
    return "span_id" in ev and "parent_id" in ev


def _start_ns(ev: Dict[str, Any]) -> float:
    """Spans record at END; sort children by their start instant."""
    return float(ev.get("ns", 0)) - float(ev.get("duration_ms", 0.0)) * 1e6


def _label(ev: Dict[str, Any]) -> str:
    parts = [str(ev.get("kind", "?"))]
    for key in ("method", "route", "op", "task", "member", "target"):
        if key in ev:
            parts.append(str(ev[key]))
            break
    if "attempt" in ev:
        parts.append(f"#{ev['attempt']}")
    if "duration_ms" in ev:
        parts.append(f"{float(ev['duration_ms']):.1f}ms")
    if "ok" in ev:
        parts.append("ok" if ev["ok"] else "FAILED")
    node = ev.get("node")
    if node:
        parts.append(f"[{node}]")
    detail = ",".join(
        f"{k}={ev[k]}" for k in sorted(ev)
        if k not in _STRUCTURAL
        and k not in ("method", "route", "op", "task", "member", "target",
                      "attempt")
    )
    if detail:
        parts.append(f"({detail})")
    return " ".join(parts)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    if n < 1024:
        return f"{int(n)}B"
    for unit in ("KB", "MB", "GB"):
        n /= 1024.0
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}"
    return f"{n:.1f}GB"


def _cost_suffix(costs: Optional[Dict[str, Any]]) -> str:
    """The per-span cost columns: compile s / upload B / wire B (other
    charged categories show as key=value so nothing is hidden)."""
    if not costs:
        return ""
    shown = set()
    parts = []
    c = float(costs.get("compile_seconds", 0.0))
    if c:
        parts.append(f"compile {c:.3f}s")
    shown.add("compile_seconds")
    u = float(costs.get("devcache_upload_bytes", 0.0))
    if u:
        parts.append(f"upload {_fmt_bytes(u)}")
    shown.add("devcache_upload_bytes")
    w = (float(costs.get("rpc_sent_bytes", 0.0))
         + float(costs.get("rpc_recv_bytes", 0.0)))
    if w:
        parts.append(f"wire {_fmt_bytes(w)}")
    shown.update(("rpc_sent_bytes", "rpc_recv_bytes"))
    hl = float(costs.get("hist_level_wall", 0.0))
    if hl:
        parts.append(f"hist {hl:.3f}s")
    shown.add("hist_level_wall")
    for k in sorted(costs):
        if k not in shown and costs[k]:
            v = costs[k]
            parts.append(f"{k}={v:.3f}" if isinstance(v, float)
                         else f"{k}={v}")
    return "  $ " + " ".join(parts) if parts else ""


def render(events: List[Dict[str, Any]],
           trace_id: Optional[str] = None,
           ledgers: Optional[Dict[str, Any]] = None) -> str:
    """The trace trees of ``events`` as indented text, one per trace,
    newest trace last.  ``trace_id`` narrows to one trace; ``ledgers``
    (trace_id -> cost breakdown, the ``?ledgers=true`` attachment) adds
    per-span cost columns and per-trace totals."""
    ledgers = ledgers or {}
    traces: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for ev in events:
        tid = ev.get("trace_id")
        if not tid or (trace_id and tid != trace_id):
            continue
        if tid not in traces:
            traces[tid] = []
            order.append(tid)
        traces[tid].append(ev)

    lines: List[str] = []
    for tid in order:
        evs = traces[tid]
        spans = [e for e in evs if _is_span(e)]
        plain = [e for e in evs if not _is_span(e)]
        by_id = {e["span_id"]: e for e in spans}
        children: Dict[Optional[str], List[Dict[str, Any]]] = {}
        for e in spans:
            parent = e.get("parent_id")
            if parent is not None and parent not in by_id:
                parent = None  # parent fell off the ring: orphan root
                e = {**e, "_orphan": True}
            children.setdefault(parent, []).append(e)
        notes: Dict[str, List[Dict[str, Any]]] = {}
        loose: List[Dict[str, Any]] = []
        for e in plain:
            sid = e.get("span_id")
            (notes.setdefault(sid, []) if sid in by_id else loose).append(e)

        ledger = ledgers.get(tid) if isinstance(ledgers, dict) else None
        span_costs = (ledger or {}).get("spans") or {}
        total_suffix = _cost_suffix((ledger or {}).get("total"))

        lines.append(
            f"trace {tid} ({len(spans)} span{'s' if len(spans) != 1 else ''}"
            + (f", {len(plain)} event{'s' if len(plain) != 1 else ''}"
               if plain else "") + ")" + total_suffix)

        def _walk(span: Dict[str, Any], depth: int) -> None:
            flag = " (orphan)" if span.get("_orphan") else ""
            lines.append("  " * depth + _label(span) + flag
                         + _cost_suffix(span_costs.get(span.get("span_id"))))
            for note in sorted(notes.get(span["span_id"], []),
                               key=lambda e: e.get("ns", 0)):
                lines.append("  " * (depth + 1) + "- " + _label(note))
            for child in sorted(children.get(span["span_id"], []),
                                key=_start_ns):
                _walk(child, depth + 1)

        for root in sorted(children.get(None, []), key=_start_ns):
            _walk(root, 0)
        for note in sorted(loose, key=lambda e: e.get("ns", 0)):
            lines.append("  - " + _label(note))
        lines.append("")
    if not lines:
        lines = ["no traced events in snapshot", ""]
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render span trees from a /3/Timeline JSON snapshot")
    ap.add_argument("snapshot",
                    help="path to the saved JSON, or '-' for stdin")
    ap.add_argument("--trace", default=None,
                    help="show only this trace_id")
    args = ap.parse_args(argv)
    try:
        if args.snapshot == "-":
            payload = json.load(sys.stdin)
        else:
            with open(args.snapshot) as f:
                payload = json.load(f)
        events = _events_of(payload)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_view: {e}", file=sys.stderr)
        return 1
    ledgers = (payload.get("ledgers")
               if isinstance(payload, dict) else None)
    sys.stdout.write(render(events, trace_id=args.trace, ledgers=ledgers))
    return 0


if __name__ == "__main__":
    sys.exit(main())
