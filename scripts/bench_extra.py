"""Secondary benchmarks: GLM / DeepLearning / KMeans training throughput.

BASELINE.json's to-measure configs go beyond the flagship tpu_hist number
(GLM prostate-shaped smoke, DL MNIST-shaped, AutoML airlines-shaped —
SURVEY.md §6). This runner measures the single-chip training throughput of
the three dense-algebra algos on synthetic data of those shapes and writes
BENCH_EXTRA_r04.json. Run it whenever the TPU is reachable; it is
independent of the driver's bench.py envelope.

Timing: warmup run (compiles; different seed so the axon relay can't serve
the timed run from a result cache), then a timed run, per algo. Each
train's own device-sync boundaries make per-train wall time honest (the
host blocks on fetching the fitted parameters).

Usage:  python scripts/bench_extra.py [out.json]
(BENCH_EXTRA_SCALE=0.01 shrinks every config for a CPU smoke run.)
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SCALE = float(os.environ.get("BENCH_EXTRA_SCALE", "1.0"))


def _n(base: int) -> int:
    return max(1000, int(base * _SCALE))


def _bench_glm():
    """Binomial IRLSM on a prostate-shaped but larger design (1M x 16)."""
    from h2o3_tpu.frame.frame import Column, ColType, Frame
    from h2o3_tpu.models.glm import GLM, GLMParameters

    rng = np.random.default_rng(0)
    n, d = _n(1_000_000), 16
    X = rng.normal(size=(n, d)).astype(np.float64)
    w = rng.normal(size=d) / np.sqrt(d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.int32)

    def make_frame(seed_shift):
        cols = [Column(f"x{i}", X[:, i] + seed_shift) for i in range(d)]
        cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
        return Frame(cols)

    GLM(GLMParameters(response_column="y", family="binomial")).train(
        make_frame(1e-6))  # warmup/compile
    fr = make_frame(0.0)
    t0 = time.time()
    m = GLM(GLMParameters(response_column="y", family="binomial")).train(fr)
    dt = time.time() - t0
    return {
        "metric": "glm_binomial_train_rows_per_sec",
        "value": round(n * m.iterations / dt, 1),
        "unit": f"row-passes/sec ({n} rows x {m.iterations} IRLSM iters)",
        "train_s": round(dt, 3),
    }


def _bench_dl():
    """MNIST-shaped MLP (60k x 784, 128-64 hidden, 10 classes)."""
    from h2o3_tpu.frame.frame import Column, ColType, Frame
    from h2o3_tpu.models.deeplearning import DeepLearning

    rng = np.random.default_rng(0)
    n, d, C = _n(60_000), 784 if _SCALE >= 1 else 64, 10
    X = rng.random((n, d)).astype(np.float32)
    y = rng.integers(0, C, n).astype(np.int32)
    epochs = 2

    def make_frame(shift):
        cols = [Column(f"p{i}", X[:, i].astype(np.float64) + shift)
                for i in range(d)]
        cols.append(Column("y", y, ColType.CAT, [str(c) for c in range(C)]))
        return Frame(cols)

    DeepLearning(hidden=[128, 64], epochs=epochs, response_column="y",
                 seed=1).train(make_frame(1e-6))
    fr = make_frame(0.0)
    t0 = time.time()
    DeepLearning(hidden=[128, 64], epochs=epochs, response_column="y",
                 seed=2).train(fr)
    dt = time.time() - t0
    return {
        "metric": "dl_mnist_shape_train_samples_per_sec",
        "value": round(n * epochs / dt, 1),
        "unit": f"sample-passes/sec ({n} rows x {epochs} epochs, "
                f"{d}-128-64-10)",
        "train_s": round(dt, 3),
    }


def _bench_kmeans():
    """Lloyd iterations on 2M x 16, k=8."""
    from h2o3_tpu.frame.frame import Column, Frame
    from h2o3_tpu.models.kmeans import KMeans

    rng = np.random.default_rng(0)
    n, d, k = _n(2_000_000), 16, 8
    X = rng.normal(size=(n, d)).astype(np.float64)
    X[: n // 8] += 3.0

    def make_frame(shift):
        return Frame([Column(f"x{i}", X[:, i] + shift) for i in range(d)])

    KMeans(k=k, max_iterations=5, seed=1).train(make_frame(1e-6))
    fr = make_frame(0.0)
    t0 = time.time()
    m = KMeans(k=k, max_iterations=5, seed=2).train(fr)
    dt = time.time() - t0
    iters = getattr(m, "iterations", 5) or 5
    return {
        "metric": "kmeans_train_rows_per_sec",
        "value": round(n * iters / dt, 1),
        "unit": f"row-iterations/sec ({n} rows x {iters} Lloyd iters, k={k})",
        "train_s": round(dt, 3),
    }


def main() -> None:
    results = []
    for fn in (_bench_glm, _bench_kmeans, _bench_dl):
        try:
            r = fn()
        except Exception as e:  # record the failure, keep going
            r = {"metric": fn.__name__, "error": f"{type(e).__name__}: {e}"}
        results.append(r)
        print(json.dumps(r), flush=True)
    import jax

    artifact = {
        "device": str(jax.devices()[0]),
        "results": results,
    }
    out = sys.argv[1] if len(sys.argv) > 1 else "BENCH_EXTRA_r04.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
