"""Client-bindings code generator.

Reference: ``h2o-bindings/bin/gen_python.py:140,354`` — the h2o-py estimator
classes are GENERATED from the server's live parameter schemas (via
``/3/Metadata/schemas``), so the client surface can never drift from the
server. This generator does the same: point it at a running server (or let
it import the registry in-process) and it emits a static module of
estimator classes with explicit keyword signatures and docstrings.

Usage:
    python scripts/gen_bindings.py out.py                # in-process registry
    python scripts/gen_bindings.py out.py http://host:port  # over REST
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

# runnable from anywhere: the repo root hosts the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HEADER = '''"""GENERATED client estimator bindings — do not edit by hand.

Regenerate with scripts/gen_bindings.py (the h2o-bindings/bin/gen_python.py
analogue): the kwargs below are exactly the server-side Parameters
dataclass fields at generation time.
"""

from h2o3_tpu.client.estimators import H2OEstimator

'''

CLASS_NAMES = {
    "gbm": "H2OGradientBoostingEstimator",
    "drf": "H2ORandomForestEstimator",
    "xgboost": "H2OXGBoostEstimator",
    "glm": "H2OGeneralizedLinearEstimator",
    "gam": "H2OGeneralizedAdditiveEstimator",
    "deeplearning": "H2ODeepLearningEstimator",
    "kmeans": "H2OKMeansEstimator",
    "naivebayes": "H2ONaiveBayesEstimator",
    "pca": "H2OPrincipalComponentAnalysisEstimator",
    "svd": "H2OSingularValueDecompositionEstimator",
    "isolationforest": "H2OIsolationForestEstimator",
    "extendedisolationforest": "H2OExtendedIsolationForestEstimator",
    "coxph": "H2OCoxProportionalHazardsEstimator",
    "glrm": "H2OGeneralizedLowRankEstimator",
    "psvm": "H2OPSVMEstimator",
    "rulefit": "H2ORuleFitEstimator",
    "stackedensemble": "H2OStackedEnsembleEstimator",
    "word2vec": "H2OWord2vecEstimator",
    "aggregator": "H2OAggregatorEstimator",
    "targetencoder": "H2OTargetEncoderEstimator",
    "generic": "H2OGenericEstimator",
}


def schemas_from_registry():
    import dataclasses

    from h2o3_tpu.api.registry import algo_map

    out = []
    for algo, (_, pcls) in algo_map().items():
        out.append({
            "algo": algo,
            "name": pcls.__name__,
            "fields": [
                {
                    "name": f.name,
                    "type": str(f.type),
                    "default_value": (
                        f.default
                        if f.default is not dataclasses.MISSING
                        and isinstance(f.default, (int, float, str, bool, type(None)))
                        else None
                    ),
                }
                for f in dataclasses.fields(pcls)
            ],
        })
    return out


def schemas_from_server(base_url: str):
    with urllib.request.urlopen(base_url + "/3/Metadata/schemas") as resp:
        schemas = json.loads(resp.read())["schemas"]
    # map schema name back to algo via /3/ModelBuilders
    with urllib.request.urlopen(base_url + "/3/ModelBuilders") as resp:
        algos = list(json.loads(resp.read())["model_builders"])
    by_name = {s["name"]: s for s in schemas}
    out = []
    for algo in algos:
        for s in schemas:
            stem = s["name"].replace("Parameters", "").lower()
            if stem == algo.replace("_", ""):
                out.append({**s, "algo": algo})
                break
    return out or [dict(s, algo=s["name"].replace("Parameters", "").lower())
                   for s in by_name.values()]


def generate(schemas) -> str:
    chunks = [HEADER]
    for s in sorted(schemas, key=lambda s: s["algo"]):
        cls = CLASS_NAMES.get(s["algo"])
        if cls is None:
            continue
        sig_parts = []
        for f in s["fields"]:
            d = f["default_value"]
            sig_parts.append(f"        {f['name']}={d!r},  # {f['type']}")
        sig = "\n".join(sig_parts)
        chunks.append(
            f'''class {cls}(H2OEstimator):
    """Estimator for the {s["algo"]!r} algo ({s["name"]})."""

    algo = "{s["algo"]}"

    def __init__(
        self,
        *,
{sig}
        model_id=None,
        **extra,
    ):
        kw = {{k: v for k, v in locals().items()
              if k not in ("self", "extra", "__class__")}}
        kw.update(extra)
        # only non-default values travel to the server
        defaults = {{{", ".join(f"{f['name']!r}: {f['default_value']!r}" for f in s["fields"])}, "model_id": None}}
        kw = {{k: v for k, v in kw.items() if defaults.get(k, object()) != v}}
        super().__init__(**kw)


''')
    return "".join(chunks)


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "generated_estimators.py"
    if len(sys.argv) > 2:
        schemas = schemas_from_server(sys.argv[2].rstrip("/"))
    else:
        schemas = schemas_from_registry()
    code = generate(schemas)
    with open(out_path, "w") as f:
        f.write(code)
    print(f"wrote {out_path}: {code.count('class ')} estimator classes")


if __name__ == "__main__":
    main()
