"""Client-bindings code generator.

Reference: ``h2o-bindings/bin/gen_python.py:140,354`` — the h2o-py estimator
classes are GENERATED from the server's live parameter schemas (via
``/3/Metadata/schemas``), so the client surface can never drift from the
server. This generator does the same: point it at a running server (or let
it import the registry in-process) and it emits a static module of
estimator classes with explicit keyword signatures and docstrings.

Usage:
    python scripts/gen_bindings.py out.py                # in-process registry
    python scripts/gen_bindings.py out.py http://host:port  # over REST
    python scripts/gen_bindings.py --r h2o3r/R/estimators_gen.R  # R emitter

The R emitter is the ``h2o-bindings/bin/gen_R.py`` analogue: it emits one
``h2o.<algo>`` wrapper per registered algorithm (h2o-r naming), each with
the full keyword surface of the server-side Parameters dataclass.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

# runnable from anywhere: the repo root hosts the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HEADER = '''"""GENERATED client estimator bindings — do not edit by hand.

Regenerate with scripts/gen_bindings.py (the h2o-bindings/bin/gen_python.py
analogue): the kwargs below are exactly the server-side Parameters
dataclass fields at generation time.
"""

from h2o3_tpu.client.estimators import H2OEstimator

'''

CLASS_NAMES = {
    "gbm": "H2OGradientBoostingEstimator",
    "drf": "H2ORandomForestEstimator",
    "xgboost": "H2OXGBoostEstimator",
    "glm": "H2OGeneralizedLinearEstimator",
    "gam": "H2OGeneralizedAdditiveEstimator",
    "deeplearning": "H2ODeepLearningEstimator",
    "kmeans": "H2OKMeansEstimator",
    "naivebayes": "H2ONaiveBayesEstimator",
    "pca": "H2OPrincipalComponentAnalysisEstimator",
    "svd": "H2OSingularValueDecompositionEstimator",
    "isolationforest": "H2OIsolationForestEstimator",
    "extendedisolationforest": "H2OExtendedIsolationForestEstimator",
    "coxph": "H2OCoxProportionalHazardsEstimator",
    "glrm": "H2OGeneralizedLowRankEstimator",
    "psvm": "H2OPSVMEstimator",
    "rulefit": "H2ORuleFitEstimator",
    "stackedensemble": "H2OStackedEnsembleEstimator",
    "word2vec": "H2OWord2vecEstimator",
    "aggregator": "H2OAggregatorEstimator",
    "targetencoder": "H2OTargetEncoderEstimator",
    "generic": "H2OGenericEstimator",
}


def schemas_from_registry():
    import dataclasses

    from h2o3_tpu.api.registry import algo_map

    out = []
    for algo, (_, pcls) in algo_map().items():
        out.append({
            "algo": algo,
            "name": pcls.__name__,
            "fields": [
                {
                    "name": f.name,
                    "type": str(f.type),
                    "default_value": (
                        f.default
                        if f.default is not dataclasses.MISSING
                        and isinstance(f.default, (int, float, str, bool, type(None)))
                        else None
                    ),
                }
                for f in dataclasses.fields(pcls)
            ],
        })
    return out


def schemas_from_server(base_url: str):
    with urllib.request.urlopen(base_url + "/3/Metadata/schemas") as resp:
        schemas = json.loads(resp.read())["schemas"]
    # map schema name back to algo via /3/ModelBuilders
    with urllib.request.urlopen(base_url + "/3/ModelBuilders") as resp:
        algos = list(json.loads(resp.read())["model_builders"])
    by_name = {s["name"]: s for s in schemas}
    out = []
    for algo in algos:
        for s in schemas:
            stem = s["name"].replace("Parameters", "").lower()
            if stem == algo.replace("_", ""):
                out.append({**s, "algo": algo})
                break
    return out or [dict(s, algo=s["name"].replace("Parameters", "").lower())
                   for s in by_name.values()]


def generate(schemas) -> str:
    chunks = [HEADER]
    for s in sorted(schemas, key=lambda s: s["algo"]):
        cls = CLASS_NAMES.get(s["algo"])
        if cls is None:
            continue
        sig_parts = []
        for f in s["fields"]:
            d = f["default_value"]
            sig_parts.append(f"        {f['name']}={d!r},  # {f['type']}")
        sig = "\n".join(sig_parts)
        chunks.append(
            f'''class {cls}(H2OEstimator):
    """Estimator for the {s["algo"]!r} algo ({s["name"]})."""

    algo = "{s["algo"]}"

    def __init__(
        self,
        *,
{sig}
        model_id=None,
        **extra,
    ):
        kw = {{k: v for k, v in locals().items()
              if k not in ("self", "extra", "__class__")}}
        kw.update(extra)
        # only non-default values travel to the server
        defaults = {{{", ".join(f"{f['name']!r}: {f['default_value']!r}" for f in s["fields"])}, "model_id": None}}
        kw = {{k: v for k, v in kw.items() if defaults.get(k, object()) != v}}
        super().__init__(**kw)


''')
    return "".join(chunks)


R_FUNC_NAMES = {
    "gbm": "h2o.gbm",
    "drf": "h2o.randomForest",
    "xgboost": "h2o.xgboost",
    "glm": "h2o.glm",
    "gam": "h2o.gam",
    "deeplearning": "h2o.deeplearning",
    "kmeans": "h2o.kmeans",
    "naivebayes": "h2o.naiveBayes",
    "pca": "h2o.prcomp",
    "svd": "h2o.svd",
    "isolationforest": "h2o.isolationForest",
    "extendedisolationforest": "h2o.extendedIsolationForest",
    "coxph": "h2o.coxph",
    "glrm": "h2o.glrm",
    "psvm": "h2o.psvm",
    "rulefit": "h2o.rulefit",
    "stackedensemble": "h2o.stackedEnsemble",
    "word2vec": "h2o.word2vec",
    "aggregator": "h2o.aggregator",
    "targetencoder": "h2o.targetencoder",
    "generic": "h2o.genericModel",
}

R_HEADER = """# GENERATED estimator wrappers -- do not edit by hand.
#
# Regenerate with: python scripts/gen_bindings.py --r h2o3r/R/estimators_gen.R
# (the h2o-bindings/bin/gen_R.py analogue). Each wrapper's arguments are
# exactly the server-side Parameters dataclass fields at generation time;
# non-NULL arguments travel to POST /3/ModelBuilders/{algo}.

"""


def _r_default(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return '"' + v.replace('"', '\\"') + '"'
    if isinstance(v, float) and v != v:
        return "NaN"
    if isinstance(v, float) and v in (float("inf"), float("-inf")):
        return "Inf" if v > 0 else "-Inf"
    return repr(v)


def _r_name(py_name: str) -> str:
    # trailing-underscore python names (lambda_) keep the h2o-r spelling
    return py_name.rstrip("_") if py_name.endswith("_") else py_name


def generate_r(schemas) -> str:
    chunks = [R_HEADER]
    for s in sorted(schemas, key=lambda s: s["algo"]):
        fn = R_FUNC_NAMES.get(s["algo"])
        if fn is None:
            continue
        args, body = [], []
        args.append("training_frame")
        args.append("validation_frame = NULL")
        body.append('  params <- list()')
        body.append('  params$training_frame <- training_frame')
        body.append('  params$validation_frame <- validation_frame')
        seen = {"training_frame", "validation_frame"}
        for f in s["fields"]:
            rn = _r_name(f["name"])
            if rn in seen:
                continue
            seen.add(rn)
            args.append(f"{rn} = {_r_default(f['default_value'])}")
            body.append(f'  params${f["name"]} <- {rn}')
        args.append("model_id = NULL")
        body.append('  params$model_id <- model_id')
        sep = ",\n                "  # hoisted: pre-3.12 f-strings reject \n
        chunks.append(
            f"{fn} <- function({sep.join(args)}) {{\n"
            + "\n".join(body)
            + f'\n  .h2o.train("{s["algo"]}", params)\n}}\n\n'
        )
    return "".join(chunks)


def main() -> None:
    argv = list(sys.argv[1:])
    r_mode = "--r" in argv
    if r_mode:
        argv.remove("--r")
    out_path = argv[0] if argv else (
        "h2o3r/R/estimators_gen.R" if r_mode else "generated_estimators.py")
    if len(argv) > 1:
        schemas = schemas_from_server(argv[1].rstrip("/"))
    else:
        schemas = schemas_from_registry()
    code = generate_r(schemas) if r_mode else generate(schemas)
    with open(out_path, "w") as f:
        f.write(code)
    unit = "wrappers" if r_mode else "estimator classes"
    n = code.count("<- function(") if r_mode else code.count("class ")
    print(f"wrote {out_path}: {n} {unit}")


if __name__ == "__main__":
    main()
