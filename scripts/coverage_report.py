"""Print the framework's coverage numbers, derived live from the code.

Every figure the round notes claim should be re-derivable by running
this (CPU-only, no TPU needed):

    python scripts/coverage_report.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    from h2o3_tpu.api.registry import algo_map
    from h2o3_tpu.api.server import H2OServer
    from h2o3_tpu.models import mojo_ref
    from h2o3_tpu.rapids.prims import PRIMS

    s = H2OServer()
    print(f"REST routes:            {len(s.registry.routes)}"
          f"  (reference RegisterV3Api: 125)")
    print(f"Registered algos:       {len(algo_map())}")
    print(f"Rapids primitives:      {len(PRIMS)}"
          f"  (reference ast/prims: ~200 incl. bases)")

    # reference-format MOJO families = tree writers + the dispatch table
    import inspect

    src = inspect.getsource(mojo_ref.write_mojo)
    table = [ln.split('"')[1] for ln in src.splitlines() if '": _write' in ln]
    families = sorted(set(table) | {"gbm", "drf"})
    print(f"Reference-MOJO families: {len(families)}  {families}")

    import subprocess

    n_tests = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only", "-q"],
        capture_output=True, text=True,
    ).stdout.strip().splitlines()[-1]
    print(f"Test collection:        {n_tests}")


if __name__ == "__main__":
    main()
