#!/usr/bin/env python
"""Lint: observability docs must match the live REST route registry.

Two checks, both cheap enough for tier-1 (CPU-only, no server socket):

1. Every *observability* route registered on the server (anything under the
   prefixes below) must appear in README.md's "## Observability" route
   table. A new metrics/logging/profiling route that nobody documented
   fails the build.
2. Every algo in ``h2o3_tpu/api/registry.py``'s ``algo_map`` must be
   servable through the registered ``/3/ModelBuilders/{algo}`` train route
   — the registry and the route table cannot drift apart.

Exit 0 = in sync; exit 1 prints what is missing.
"""

from __future__ import annotations

import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

#: route prefixes that constitute the observability surface
OBS_PREFIXES = (
    "/3/Logs",
    "/3/Timeline",
    "/3/Metrics",
    "/3/Profiler",
    "/3/JStack",
    "/3/WaterMeterCpuTicks",
    "/3/Ping",
)


def readme_documented_routes(readme_path: str) -> set:
    """Route strings out of the Observability section's markdown table."""
    with open(readme_path) as f:
        text = f.read()
    m = re.search(r"^## Observability$(.*?)(?=^## |\Z)", text,
                  re.MULTILINE | re.DOTALL)
    if not m:
        return set()
    routes = set()
    for line in m.group(1).splitlines():
        if not line.startswith("|"):
            continue
        cell = line.split("|")[1].strip().strip("`")
        parts = cell.split()
        if len(parts) == 2 and parts[0] in ("GET", "POST", "DELETE"):
            # table escapes | inside parameter hints; the route is parts[1]
            routes.add((parts[0], parts[1]))
    return routes


#: backticked tokens with one of these suffixes (optionally carrying a
#: ``{label,...}`` hint) are treated as metric references the registry
#: must actually contain
_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_entries", "_workers",
                    "_inflight", "_depth", "_batch_size", "_connections",
                    "_homes")


#: README sections whose backticked metric references the registry must
#: actually contain (Clustering documents cluster_*/rpc_*, Failure
#: model the chaos-plane meters, Distributed Frames the chunk-home
#: meters, Serving plane the http_*/batching meters)
_METRIC_SECTIONS = ("Observability", "Clustering", "Distributed Frames",
                    "Failure model", "Serving plane")


def readme_documented_metrics(readme_path: str) -> set:
    """Metric names referenced in the metric-documenting sections' prose."""
    with open(readme_path) as f:
        text = f.read()
    names = set()
    for section in _METRIC_SECTIONS:
        m = re.search(rf"^## {section}$(.*?)(?=^## |\Z)", text,
                      re.MULTILINE | re.DOTALL)
        if not m:
            continue
        for tok in re.findall(r"`([a-z][a-z0-9_]*)(?:\{[a-z0-9_,]+\})?`",
                              m.group(1)):
            if tok.endswith(_METRIC_SUFFIXES):
                names.add(tok)
    return names


def live_metrics() -> set:
    """Registry names after importing every metric-declaring module the
    server pulls in (parse/ingest/devcache/mapreduce come via the server
    import below; list the frame layer explicitly so the lint cannot go
    vacuous if a route stops importing it)."""
    import h2o3_tpu.frame.ingest     # noqa: F401  parse_* / ingest_* meters
    import h2o3_tpu.frame.devcache   # noqa: F401  devcache_* meters
    import h2o3_tpu.compute.mapreduce  # noqa: F401  mapreduce_* meters
    import h2o3_tpu.models.framework  # noqa: F401  model_fit_seconds
    import h2o3_tpu.cluster.rpc      # noqa: F401  rpc_* meters
    import h2o3_tpu.cluster.membership  # noqa: F401  cluster_* meters
    import h2o3_tpu.cluster.dkv      # noqa: F401  cluster_dkv_* meters
    import h2o3_tpu.cluster.tasks    # noqa: F401  cluster_tasks_* meters
    import h2o3_tpu.cluster.faults   # noqa: F401  cluster_faults_* meters
    import h2o3_tpu.cluster.frames   # noqa: F401  cluster_chunk_* meters
    import h2o3_tpu.api.coalesce     # noqa: F401  predict_batch_size
    import h2o3_tpu.rapids.fusion    # noqa: F401  rapids_fusion_* meters
    from h2o3_tpu.util import telemetry

    return set(telemetry.REGISTRY.names())


def live_routes():
    """(method, template) pairs off a constructed (not started) server."""
    from h2o3_tpu.api.server import H2OServer

    return H2OServer(port=0).registry.templates()


def main() -> int:
    failures = []

    routes = live_routes()
    documented = readme_documented_routes(os.path.join(_ROOT, "README.md"))
    if not documented:
        failures.append(
            "README.md has no '## Observability' route table at all")
    obs = [
        (m, t) for m, t in routes
        if any(t.startswith(p) for p in OBS_PREFIXES)
    ]
    for m, t in sorted(obs):
        if (m, t) not in documented:
            failures.append(
                f"observability route {m} {t} is registered but missing "
                f"from README.md's Observability table"
            )
    stale = {
        (m, t) for m, t in documented
        if any(t.startswith(p) for p in OBS_PREFIXES)
        and (m, t) not in set(routes)
    }
    for m, t in sorted(stale):
        failures.append(
            f"README.md documents {m} {t} but no such route is registered"
        )

    registered = live_metrics()
    ghost = readme_documented_metrics(os.path.join(_ROOT, "README.md")) \
        - registered
    for name in sorted(ghost):
        failures.append(
            f"README.md's {'/'.join(_METRIC_SECTIONS)} sections document "
            f"metric {name!r} but the telemetry registry never declares it"
        )

    # fusion registry lint: a prim flagged fusible without an emitter would
    # silently fall back on every query (binop/uniop/ifelse kinds), and a
    # fusible prim with no parity test case is an unverified bit-identity
    # claim — both fail the build
    from h2o3_tpu.rapids.prims import FUSIBLE

    emit_kinds = ("binop", "uniop", "ifelse")
    for name, spec in sorted(FUSIBLE.items()):
        if spec.kind in emit_kinds and spec.emit is None:
            failures.append(
                f"fusible prim {name!r} (kind={spec.kind}) has no emitter")
    parity_path = os.path.join(_ROOT, "tests", "test_rapids_fusion.py")
    try:
        with open(parity_path) as f:
            parity_src = f.read()
    except OSError:
        parity_src = ""
        failures.append("tests/test_rapids_fusion.py is missing — every "
                        "fusible prim needs a fused-vs-interpreted parity case")
    untested = [
        name for name in sorted(FUSIBLE)
        if f'"{name}"' not in parity_src and f"'{name}'" not in parity_src
    ]
    for name in untested:
        failures.append(
            f"fusible prim {name!r} has no parity case in "
            f"tests/test_rapids_fusion.py"
        )

    from h2o3_tpu.api.registry import algo_map

    train_routes = {t for m, t in routes if m == "POST"}
    if "/3/ModelBuilders/{algo}" not in train_routes:
        failures.append("train route /3/ModelBuilders/{algo} not registered")
    else:
        # every registry algo name must be a clean single path segment,
        # so the train route's {algo} placeholder can actually match it
        for algo in algo_map():
            if not re.match(r"^[a-z0-9_]+$", algo):
                failures.append(
                    f"algo {algo!r} in api/registry.py cannot be a "
                    f"URL path segment of /3/ModelBuilders/{{algo}}"
                )

    if failures:
        for f in failures:
            print(f"check_telemetry: {f}", file=sys.stderr)
        return 1
    n_doc_metrics = len(
        readme_documented_metrics(os.path.join(_ROOT, "README.md")))
    print(
        f"check_telemetry: OK — {len(obs)} observability routes documented, "
        f"{n_doc_metrics} documented metrics registered, "
        f"{len(algo_map())} algos registered, "
        f"{len(FUSIBLE)} fusible prims emitter+parity checked"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
