#!/usr/bin/env python
"""Lint: observability docs must match the live REST route registry.

Thin shim: the checks now live in the static-analysis framework as the
``telemetry-drift`` pass (``h2o3_tpu/analysis/passes/telemetry_drift.py``)
and also run via ``scripts/analyze.py``. This entry point keeps the
original contract — exit 0 and a ``check_telemetry: OK`` summary when
in sync, exit 1 with one ``check_telemetry: <problem>`` line per drift
on stderr — so existing tier-1 wiring and docs stay valid.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)


def main() -> int:
    from h2o3_tpu.analysis.passes.telemetry_drift import collect

    failures, summary = collect(_ROOT, os.path.join(_ROOT, "README.md"))
    if failures:
        for _rule, _file, _symbol, message in failures:
            print(f"check_telemetry: {message}", file=sys.stderr)
        return 1
    print(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
