#!/usr/bin/env python
"""Render a saved ``/3/Diagnostics`` bundle (or a flight crash file).

Pure stdlib, no repo imports — point it at anything the health plane
persists:

    curl -s localhost:54321/3/Diagnostics > diag.json
    python scripts/diag_view.py diag.json
    curl -s 'localhost:54321/3/Diagnostics?cluster=true' \
        | python scripts/diag_view.py -
    python scripts/diag_view.py /var/crash/flight-node-a-1234.json

Accepted shapes (distinguished by the top-level ``kind`` field):

``diagnostics``          one node's bundle (identity + knobs, watchdog
                         verdicts, flight ring tail, worst SlowOps,
                         membership view, thread stacks)
``diagnostics_cluster``  the federated ``?cluster=true`` shape — one
                         bundle per reachable node plus a ``partial``
                         flag and per-node errors
``flight_crash``         the atexit/fatal-path crash file: the flight
                         ring as it stood at death, plus whatever the
                         crash-extras hook attached (health verdicts)

Output: one section per node — health verdicts first (a support bundle
answers "is it sick" before "what happened"), then the flight events
oldest-first with severity flags, then slow ops and membership.
``--events N`` bounds the flight tail, ``--stacks`` adds thread dumps.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional

_SEV_MARK = {"info": " ", "warn": "!", "error": "E", "critical": "C"}

#: flight-event fields that are structural; everything else prints as
#: key=value payload detail
_STRUCTURAL = {"ts_ms", "seq", "category", "severity", "node", "msg",
               "trace_id"}


def _fmt_ts(ms: Any) -> str:
    try:
        return time.strftime("%H:%M:%S", time.gmtime(float(ms) / 1000.0)) \
            + f".{int(float(ms)) % 1000:03d}"
    except (TypeError, ValueError):
        return "--:--:--"


def _event_line(ev: Dict[str, Any]) -> str:
    sev = str(ev.get("severity", "info"))
    parts = [
        _SEV_MARK.get(sev, "?"),
        _fmt_ts(ev.get("ts_ms")),
        f"{ev.get('category', '?')}/{ev.get('msg', '')}",
    ]
    tid = ev.get("trace_id")
    if tid:
        parts.append(f"trace={tid}")
    detail = " ".join(
        f"{k}={ev[k]}" for k in sorted(ev) if k not in _STRUCTURAL)
    if detail:
        parts.append(detail)
    return " ".join(parts)


def _render_health(health: Optional[Dict[str, Any]], out: List[str]) -> None:
    if not isinstance(health, dict):
        return
    summary = health.get("summary") or {}
    verdicts = health.get("verdicts") or {}
    state = summary.get("state", "unknown")
    out.append(f"  health: {state}"
               + ("" if summary.get("running", True) else " (monitor stopped)"))
    for check in sorted(verdicts):
        v = verdicts[check] or {}
        detail = v.get("detail") or ""
        out.append(f"    {check:<20} {v.get('state', '?'):<9}"
                   + (f" {detail}" if detail else ""))


def _render_flight(events: Any, limit: int, out: List[str]) -> None:
    if not isinstance(events, list) or not events:
        out.append("  flight: (empty ring)")
        return
    tail = events[-limit:] if limit else events
    skipped = len(events) - len(tail)
    out.append(f"  flight ({len(events)} events"
               + (f", showing last {len(tail)}" if skipped else "") + "):")
    for ev in tail:
        if isinstance(ev, dict):
            out.append("    " + _event_line(ev))


def _render_slowops(slowops: Any, out: List[str]) -> None:
    routes = (slowops or {}).get("routes") if isinstance(slowops, dict) else None
    if not routes:
        return
    out.append("  slow ops:")
    for route in sorted(routes):
        for entry in routes[route] or []:
            ms = entry.get("duration_ms", entry.get("ms", "?"))
            out.append(f"    {route} {ms}ms trace={entry.get('trace_id', '-')}")


def _render_members(members: Any, out: List[str]) -> None:
    if not isinstance(members, list) or not members:
        return
    out.append("  members:")
    for m in members:
        if isinstance(m, dict):
            name = m.get("name", m.get("node", "?"))
            state = m.get("state", m.get("status", ""))
            out.append(f"    {name} {state}".rstrip())


def _render_stacks(threads: Any, out: List[str]) -> None:
    if not isinstance(threads, list):
        return
    out.append(f"  threads ({len(threads)}):")
    for t in threads:
        if not isinstance(t, dict):
            continue
        out.append(f"    -- {t.get('thread', '?')}")
        for frame in t.get("frames") or []:
            for line in str(frame).rstrip().splitlines():
                out.append("       " + line)


def _render_bundle(b: Dict[str, Any], events: int, stacks: bool,
                   out: List[str]) -> None:
    out.append(f"node {b.get('node', '?')} (pid {b.get('pid', '?')})")
    _render_health(b.get("health"), out)
    _render_flight(b.get("flight"), events, out)
    _render_slowops(b.get("slowops"), out)
    _render_members(b.get("members"), out)
    if stacks:
        _render_stacks(b.get("threads"), out)
    out.append("")


def _render_crash(c: Dict[str, Any], events: int, out: List[str]) -> None:
    out.append(f"flight crash file: node {c.get('node', '?')} "
               f"(pid {c.get('pid', '?')}) reason={c.get('reason', '?')} "
               f"at {_fmt_ts(c.get('ts_ms'))}")
    health = c.get("health")
    if isinstance(health, dict):
        # crash extras store bare verdicts; reuse the bundle renderer shape
        _render_health({"summary": {"state": "at-death"},
                        "verdicts": health}, out)
    _render_flight(c.get("events"), events, out)
    out.append("")


def render(payload: Any, events: int = 50, stacks: bool = False) -> str:
    """The bundle as indented text; raises ValueError on unknown shapes."""
    if not isinstance(payload, dict):
        raise ValueError("unrecognized snapshot shape: want a JSON object")
    kind = payload.get("kind")
    out: List[str] = []
    if kind == "diagnostics":
        _render_bundle(payload, events, stacks, out)
    elif kind == "diagnostics_cluster":
        nodes = payload.get("nodes") or {}
        errors = payload.get("errors") or {}
        out.append(f"cluster diagnostics: {len(nodes)} node(s)"
                   + (", PARTIAL" if payload.get("partial") else ""))
        out.append("")
        for name in sorted(nodes):
            if isinstance(nodes[name], dict):
                _render_bundle(nodes[name], events, stacks, out)
        for name in sorted(errors):
            out.append(f"node {name}: UNREACHABLE ({errors[name]})")
        if errors:
            out.append("")
    elif kind == "flight_crash":
        _render_crash(payload, events, out)
    else:
        raise ValueError(
            f"unrecognized snapshot kind {kind!r}: want 'diagnostics', "
            f"'diagnostics_cluster' or 'flight_crash'")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render a /3/Diagnostics bundle or flight crash file")
    ap.add_argument("snapshot",
                    help="path to the saved JSON, or '-' for stdin")
    ap.add_argument("--events", type=int, default=50,
                    help="flight events shown per node (default 50, 0=all)")
    ap.add_argument("--stacks", action="store_true",
                    help="include per-thread stack dumps")
    args = ap.parse_args(argv)
    try:
        if args.snapshot == "-":
            payload = json.load(sys.stdin)
        else:
            with open(args.snapshot) as f:
                payload = json.load(f)
        text = render(payload, events=args.events, stacks=args.stacks)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"diag_view: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(text + ("\n" if not text.endswith("\n") else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
