#!/usr/bin/env python
"""Run the h2o3_tpu static analyzer (see ``h2o3_tpu/analysis/``).

Exit 0 when every finding is either suppressed inline
(``# h2o3: noqa[RULE]``) or accepted in the checked-in baseline
(``analysis_baseline.json``); exit 1 and print the new findings
otherwise. Tier-1 invokes this via ``tests/test_analysis.py``.

Flags:
  --json            machine-readable output (schema version 1)
  --changed-only    analyze only files changed since ``git merge-base
                    HEAD main`` (plus worktree/untracked changes); the
                    runtime-importing telemetry-drift pass is skipped
                    unless a telemetry-relevant file changed, so
                    incremental runs stay fast (<2s, no jax import)
  --passes A,B      run only the named passes
  --baseline PATH   alternate baseline file
  --update-baseline rewrite the baseline to accept all current findings
                    (existing justifications are preserved)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import types

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

# Import the analysis package without executing h2o3_tpu/__init__.py:
# the package init pulls the frame layer (and therefore jax), which
# would put multiple seconds of import time on every --changed-only
# run. A stub parent with the real __path__ lets submodule imports
# (including the telemetry-drift pass's lazy runtime imports) work
# normally.
if "h2o3_tpu" not in sys.modules:
    _pkg = types.ModuleType("h2o3_tpu")
    _pkg.__path__ = [os.path.join(_ROOT, "h2o3_tpu")]
    with open(os.path.join(_ROOT, "h2o3_tpu", "__init__.py")) as _f:
        _m = re.search(r'__version__ = "([^"]+)"', _f.read())
    _pkg.__version__ = _m.group(1) if _m else "0"
    sys.modules["h2o3_tpu"] = _pkg

from h2o3_tpu.analysis import core  # noqa: E402

#: changed paths matching these prefixes re-arm the telemetry-drift
#: pass in --changed-only mode (it imports the runtime, so it is
#: skipped when nothing it checks can have moved)
TDRIFT_TRIGGERS = (
    "README.md",
    "h2o3_tpu/api/",
    "h2o3_tpu/rapids/",
    "h2o3_tpu/util/telemetry.py",
    "tests/test_rapids_fusion.py",
    "scripts/check_telemetry.py",
)


def _git(*args: str) -> str:
    try:
        return subprocess.run(
            ["git", *args], cwd=_ROOT, capture_output=True, text=True,
            timeout=30, check=False).stdout
    except OSError:
        return ""


def changed_files() -> list:
    """Paths changed vs merge-base with main, plus worktree/untracked."""
    base = _git("merge-base", "HEAD", "main").strip() or "HEAD"
    out = set()
    out.update(_git("diff", "--name-only", base).splitlines())
    out.update(_git("ls-files", "--others", "--exclude-standard")
               .splitlines())
    return sorted(p for p in out if p)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--changed-only", action="store_true")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of passes to run")
    ap.add_argument("--baseline",
                    default=os.path.join(_ROOT, "analysis_baseline.json"))
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to analyze (default: all)")
    args = ap.parse_args(argv)

    pass_names = ([p.strip() for p in args.passes.split(",") if p.strip()]
                  if args.passes else None)

    files = None
    if args.paths:
        files = [os.path.relpath(os.path.abspath(p), _ROOT)
                 .replace(os.sep, "/") for p in args.paths]
    elif args.changed_only:
        changed = changed_files()
        surface = set(core.iter_source_files(_ROOT))
        files = [p for p in changed if p in surface]
        if pass_names is None:
            pass_names = [n for n in core.default_passes()
                          if n != "telemetry-drift"]
            if any(p.startswith(TDRIFT_TRIGGERS) for p in changed):
                pass_names.append("telemetry-drift")
        if not files and "telemetry-drift" not in pass_names:
            print("analyze: OK — no analyzable files changed")
            return 0

    findings = core.analyze(_ROOT, files=files, pass_names=pass_names)
    baseline = core.load_baseline(args.baseline)
    new, accepted = core.split_baselined(findings, baseline)

    if args.update_baseline:
        justifications = {fp: e.get("justification", "")
                          for fp, e in baseline.items()
                          if e.get("justification")}
        core.save_baseline(args.baseline, findings, justifications)
        print(f"analyze: baseline updated — {len(findings)} accepted "
              f"finding(s) in {args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "findings": [f.to_dict() for f in new],
            "baselined": len(accepted),
            "passes": pass_names or sorted(core.default_passes()),
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if new:
        print(f"analyze: {len(new)} new finding(s) "
              f"({len(accepted)} baselined). Fix them, add "
              f"'# h2o3: noqa[RULE]' with a reason, or re-baseline via "
              f"--update-baseline with a justification.", file=sys.stderr)
        return 1
    scanned = len(files) if files is not None \
        else len(core.iter_source_files(_ROOT))
    print(f"analyze: OK — {scanned} file(s), "
          f"{len(pass_names or core.default_passes())} pass(es), "
          f"{len(accepted)} baselined finding(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
