"""Probe the Pallas tpu_hist kernel vs the XLA scatter path on real TPU.

Writes KERNEL_PROBE_r04.json (per-K ms, rows/sec, achieved-vs-peak MXU
FLOPs) so kernel-level evidence lands on disk the moment the TPU is
reachable, independent of the end-to-end bench (VERDICT r3 item 1d).
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

from h2o3_tpu.ops.histogram import _shard_histogram  # noqa: E402
from h2o3_tpu.ops.pallas_histogram import _C, build_histogram_pallas  # noqa: E402

N, F, B1 = 2_000_000, 28, 257
#: f32 MXU peak per chip generation (bf16 peak / 2); pct_of_peak is
#: omitted when the device string matches none of these
PEAK_F32_TFLOPS_BY_DEVICE = {
    "v6": 459.0,   # bf16 ~918
    "v5p": 229.5,  # bf16 ~459
    "v5": 98.5,    # v5e/lite: bf16 ~197
    "v4": 137.5,   # bf16 ~275
}


def _peak_for(device: str):
    d = device.lower()
    for key, peak in PEAK_F32_TFLOPS_BY_DEVICE.items():
        if key in d:
            return peak
    return None


def main() -> None:
    rng = np.random.default_rng(0)
    bins = jax.device_put(rng.integers(0, B1, size=(N, F)).astype(np.int32))
    g = jax.device_put(rng.normal(size=N).astype(np.float32))
    h = jax.device_put(rng.random(N).astype(np.float32))
    scatter = jax.jit(_shard_histogram, static_argnums=(4, 5))

    results = []
    for K in (1, 8, 64):
        nodes = jax.device_put(rng.integers(0, K, size=N).astype(np.int32))

        def timeit(fn, reps=5):
            fn().block_until_ready()  # compile+warm
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn()
            out.block_until_ready()
            return (time.perf_counter() - t0) / reps, out

        t_x, out_x = timeit(lambda: scatter(bins, nodes, g, h, K, B1))
        t_p, out_p = timeit(
            lambda: build_histogram_pallas(bins, nodes, g, h, K, B1))
        err = float(np.max(np.abs(np.asarray(out_x) - np.asarray(out_p))))
        # dense-matmul FLOPs actually ISSUED: the kernel pads features to
        # a _FEAT_BLOCK multiple and rows to a _ROW_TILE multiple
        from h2o3_tpu.ops.pallas_histogram import _FEAT_BLOCK, _ROW_TILE

        f_pad = F + (-F) % _FEAT_BLOCK
        n_pad = N + (-N) % _ROW_TILE
        flops = 2.0 * n_pad * (f_pad * B1) * (K * _C)
        achieved = flops / t_p / 1e12
        peak = _peak_for(str(jax.devices()[0]))
        row = {
            "K": K,
            "xla_scatter_ms": round(t_x * 1e3, 2),
            "pallas_ms": round(t_p * 1e3, 2),
            "speedup": round(t_x / t_p, 2),
            "pallas_rows_per_sec": round(N / t_p, 0),
            "achieved_tflops_f32": round(achieved, 2),
            "max_abs_err": err,
        }
        if peak is not None:
            row["pct_of_peak_f32"] = round(100 * achieved / peak, 1)
        results.append(row)
        print(row, flush=True)

    artifact = {
        "config": {"n_rows": N, "n_feat": F, "n_bins1": B1,
                   "device": str(jax.devices()[0])},
        "results": results,
    }
    out_path = sys.argv[1] if len(sys.argv) > 1 else "KERNEL_PROBE_r04.json"
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
