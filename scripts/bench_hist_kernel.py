"""Probe the Pallas tpu_hist kernel vs the XLA scatter path on real TPU.

Writes KERNEL_PROBE_r04.json (per-K ms, rows/sec, achieved-vs-peak MXU
FLOPs) so kernel-level evidence lands on disk the moment the TPU is
reachable, independent of the end-to-end bench (VERDICT r3 item 1d).

Timing methodology (measured hazards of the axon remote tunnel):

* ``block_until_ready()`` does NOT wait for device execution over the
  tunnel — naive per-call timing reads 0.02-0.8 ms for kernels whose VMEM
  write traffic alone bounds them to ~25 ms.  Every timed region here ends
  in a FORCED HOST READBACK of a checksum (``float(out)``), which cannot
  complete before the computation does.
* repeated calls with identical arguments are served from a cache
  somewhere in the relay; each rep therefore consumes a DIFFERENT gradient
  vector, pre-uploaded outside the timed region.
* a scalar readback costs ~65 ms round-trip, same order as one kernel; the
  probe measures that RTT explicitly, folds REPS kernel applications into
  ONE program (``lax.scan``) with a single checksum readback, and reports
  (elapsed - rtt) / REPS.
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/h2o3_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from h2o3_tpu.ops.histogram import _shard_histogram  # noqa: E402
from h2o3_tpu.ops.pallas_histogram import _C, build_histogram_pallas  # noqa: E402

N, F, B1 = 2_000_000, 28, 257
#: the XLA scatter baseline runs on this many rows and is scaled linearly
#: to N — TPU scatter-adds are serialized per element, so a full-N baseline
#: both risks the probe's time budget and adds nothing (it is the *slow*
#: side of the comparison)
N_SCATTER = 200_000
REPS = 4
#: f32 MXU peak per chip generation (bf16 peak / 2); pct_of_peak is
#: omitted when the device string matches none of these
PEAK_F32_TFLOPS_BY_DEVICE = {
    "v6": 459.0,   # bf16 ~918
    "v5p": 229.5,  # bf16 ~459
    "v5": 98.5,    # v5e/lite: bf16 ~197
    "v4": 137.5,   # bf16 ~275
}


def _peak_for(device: str):
    d = device.lower()
    for key, peak in PEAK_F32_TFLOPS_BY_DEVICE.items():
        if key in d:
            return peak
    return None


def _measure_rtt() -> float:
    """Scalar round-trip time of the tunnel (compute ~0)."""
    tiny = jax.device_put(np.ones(8, np.float32))
    float(tiny.sum())  # warm
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        float(tiny.sum())
    return (time.perf_counter() - t0) / reps


def _timed_chain(make_fn, gs_warm, gs_timed, rtt: float, tries: int = 3):
    """Compile sum-of-checksums over a scan of REPS kernel applications,
    run once, force the scalar back, subtract RTT.  Retries transient
    tunnel errors (remote_compile connection drops observed in the wild).

    gs_warm and gs_timed hold DIFFERENT values: re-running the program on
    the warm-up arguments would be served from the relay's result cache.
    """
    @jax.jit
    def chained(gs):
        def body(tot, g):
            return tot + make_fn(g).sum(), None
        tot, _ = jax.lax.scan(body, jnp.float32(0.0), gs)
        return tot

    last = None
    for i in range(tries):
        try:
            # per-try scaling keeps every try's timed inputs distinct: a
            # retry after a failure mid-readback must not re-time a
            # computation the relay may already have executed
            gt = gs_timed * np.float32(1.0 + i * 2.0**-10)
            float(gt.sum())  # materialize outside the timed region
            float(chained(gs_warm))  # warm: compile + first run
            t0 = time.perf_counter()
            chk = float(chained(gt))
            dt = (time.perf_counter() - t0 - rtt) / gs_timed.shape[0]
            return max(dt, 1e-9), chk
        except Exception as e:  # transient tunnel failures
            last = e
            time.sleep(3.0)
    raise last


def main() -> None:
    rng = np.random.default_rng(0)
    bins = jax.device_put(rng.integers(0, B1, size=(N, F)).astype(np.int32))
    gs_warm = jnp.stack([
        jax.device_put(rng.normal(size=N).astype(np.float32))
        for _ in range(REPS)
    ])
    gs = jnp.stack([
        jax.device_put(rng.normal(size=N).astype(np.float32))
        for _ in range(REPS)
    ])
    h = jax.device_put(rng.random(N).astype(np.float32))
    scatter = jax.jit(_shard_histogram, static_argnums=(4, 5))

    rtt = _measure_rtt()
    print(f"scalar rtt: {rtt * 1e3:.1f} ms", flush=True)

    results = []
    for K in (1, 8, 64):
        nodes = jax.device_put(rng.integers(0, K, size=N).astype(np.int32))

        t_p, _ = _timed_chain(
            lambda g: build_histogram_pallas(bins, nodes, g, h, K, B1),
            gs_warm, gs, rtt)
        t_xs, _ = _timed_chain(
            lambda g: scatter(bins[:N_SCATTER], nodes[:N_SCATTER],
                              g[:N_SCATTER], h[:N_SCATTER], K, B1),
            gs_warm[:, :N_SCATTER], gs[:, :N_SCATTER], rtt)
        t_x = t_xs * (N / N_SCATTER)  # scatter cost is linear in rows

        # parity at the subsample size (full-size oracle OOMs: its scatter
        # operand lane-pads 3 -> 128); dtype pinned to f32 so this measures
        # kernel correctness, not bf16 input rounding — note the TPU MXU's
        # DEFAULT precision still multiplies in bf16 either way
        out_x = scatter(bins[:N_SCATTER], nodes[:N_SCATTER],
                        gs[0, :N_SCATTER], h[:N_SCATTER], K, B1)
        out_p = build_histogram_pallas(
            bins[:N_SCATTER], nodes[:N_SCATTER], gs[0, :N_SCATTER],
            h[:N_SCATTER], K, B1, dtype="f32")
        err = float(np.max(np.abs(np.asarray(out_x) - np.asarray(out_p))))

        # dense-matmul FLOPs actually ISSUED: the kernel pads features to
        # a _FEAT_BLOCK multiple and rows to a _ROW_TILE multiple
        from h2o3_tpu.ops.pallas_histogram import _FEAT_BLOCK, _ROW_TILE

        f_pad = F + (-F) % _FEAT_BLOCK
        n_pad = N + (-N) % _ROW_TILE
        flops = 2.0 * n_pad * (f_pad * B1) * (K * _C)
        achieved = flops / t_p / 1e12
        from h2o3_tpu.ops.pallas_histogram import _resolve_hist_dtype
        import jax.numpy as _jnp

        is_bf16 = _resolve_hist_dtype("auto") == _jnp.bfloat16
        peak = _peak_for(str(jax.devices()[0]))
        if peak is not None and is_bf16:
            peak *= 2.0  # bf16 MXU rate is 2x the f32 table entries
        row = {
            "K": K,
            "xla_scatter_ms": round(t_x * 1e3, 2),
            "xla_scatter_n": N_SCATTER,  # measured rows; ms scaled to N
            "pallas_ms": round(t_p * 1e3, 2),
            "speedup": round(t_x / t_p, 2),
            "pallas_rows_per_sec": round(N / t_p, 0),
            "achieved_tflops": round(achieved, 2),
            "max_abs_err": err,
        }
        if peak is not None:
            # peak matches the dtype the kernel actually ran in (the
            # artifact's hist_dtype field)
            row["pct_of_peak"] = round(100 * achieved / peak, 1)
        results.append(row)
        print(row, flush=True)

    from h2o3_tpu.ops.pallas_histogram import _resolve_hist_dtype

    artifact = {
        "config": {"n_rows": N, "n_feat": F, "n_bins1": B1,
                   "device": str(jax.devices()[0]),
                   "hist_dtype": (
                       "bf16" if _resolve_hist_dtype("auto") == jnp.bfloat16
                       else "f32"),
                   "reps": REPS, "rtt_ms": round(rtt * 1e3, 1),
                   "method": "scan-chained kernel apps, checksum readback "
                             "forced, rtt subtracted (block_until_ready is "
                             "a no-op over the axon tunnel)"},
        "results": results,
    }
    out_path = sys.argv[1] if len(sys.argv) > 1 else "KERNEL_PROBE_r04.json"
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
