"""Compare the Pallas tpu_hist kernel vs the XLA scatter path on real TPU."""
import time
import numpy as np
import jax

from h2o3_tpu.ops.histogram import _shard_histogram
from h2o3_tpu.ops.pallas_histogram import build_histogram_pallas

N, F, B1 = 2_000_000, 28, 257
rng = np.random.default_rng(0)
bins = jax.device_put(rng.integers(0, B1, size=(N, F)).astype(np.int32))
g = jax.device_put(rng.normal(size=N).astype(np.float32))
h = jax.device_put(rng.random(N).astype(np.float32))

scatter = jax.jit(_shard_histogram, static_argnums=(4, 5))

for K in (1, 8, 64):
    nodes = jax.device_put(rng.integers(0, K, size=N).astype(np.int32))

    def timeit(fn, reps=3):
        fn().block_until_ready()  # compile+warm
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps, out

    t_x, out_x = timeit(lambda: scatter(bins, nodes, g, h, K, B1))
    t_p, out_p = timeit(lambda: build_histogram_pallas(bins, nodes, g, h, K, B1))
    err = float(np.max(np.abs(np.asarray(out_x) - np.asarray(out_p))))
    print(f"K={K:3d}  xla_scatter={t_x*1e3:8.2f}ms  pallas={t_p*1e3:8.2f}ms  "
          f"speedup={t_x/t_p:6.2f}x  max_abs_err={err:.3e}")
