#!/usr/bin/env python
"""Jepsen-style chaos runner: workload + nemesis + invariant checks.

Each scenario composes three pieces over the real cluster stack (no
mocks — the same transport/RPC/gossip/DKV code production runs):

* a **workload** — replicated DKV puts/gets, distributed map_reduce,
  grid search — generating state whose correct value is known up front;
* a **nemesis** — a seeded :mod:`h2o3_tpu.cluster.faults` plan (drops,
  delays, duplicates, partitions) or a real ``SIGKILL`` on a child
  process, driven through the test-only fault RPC surface;
* **invariants** — boolean checks (bit-exact results, no false
  removals, reconvergence, telemetry proof of the recovery path)
  asserted after the dust settles.

Verdicts are dicts of booleans ONLY — no timings, no counts — so two
runs with the same ``--seed`` must produce byte-identical verdicts
(the determinism contract ``tests/test_chaos.py`` enforces).

Fast scenarios (``dup_reorder``, ``slow_node``, ``partition_gossip``)
build multiple Cloud instances in-process and finish in seconds; slow
scenarios (``kill_fanout``, ``kill_grid``) spawn real node processes
and kill -9 them mid-work.

Usage::

    python scripts/chaos.py --scenario all  --seed 42   # everything
    python scripts/chaos.py --scenario fast --seed 42   # in-process only
    python scripts/chaos.py --scenario kill_fanout
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# chaos clouds gossip fast so suspicion windows stay sub-second
os.environ.setdefault("H2O3_TPU_HB_INTERVAL", "0.1")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import numpy as np  # noqa: E402

#: name -> (fn(seed) -> verdict dict, is_slow)
SCENARIOS: Dict[str, Tuple[Callable[[int], Dict[str, bool]], bool]] = {}


def scenario(name: str, slow: bool = False):
    def _reg(fn):
        SCENARIOS[name] = (fn, slow)
        return fn
    return _reg


# ---------------------------------------------------------------------------
# shared harness


def _wait(pred: Callable[[], bool], deadline_s: float,
          every: float = 0.02) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _mini_cloud(n: int, hb: float, prefix: str):
    """``n`` full in-process nodes (gossip + DKV + DTask), formed."""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.membership import Cloud
    from h2o3_tpu.keyed import KeyedStore

    clouds, stores = [], []
    for i in range(n):
        c = Cloud("chaos", f"{prefix}{i}", hb_interval=hb)
        s = KeyedStore()
        _dkv.install(c, s)
        _tasks.install(c)
        clouds.append(c)
        stores.append(s)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    formed = _wait(lambda: all(c.size() == n for c in clouds), 15.0)
    return clouds, stores, formed


def _teardown(clouds) -> None:
    from h2o3_tpu.cluster import faults

    faults.clear_plan()
    for c in clouds:
        try:
            c.stop()
        except Exception:
            pass


def _counter_value(name: str, **labels) -> float:
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return c.value(**labels) if labels else c.total()


def _counter_sum(name: str, **labels) -> float:
    """Sum every series of ``name`` matching the given label subset —
    for families with more labels than the caller pins (e.g.
    ``rpc_payload_bytes_total{direction,method}`` summed over method)."""
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return sum(
        s["value"] for s in c.snapshot()["series"]
        if all(s["labels"].get(k) == v for k, v in labels.items()))


def mr_stat(cols, mask):
    """Module-level MR fn (crosses the wire by module reference)."""
    import jax.numpy as jnp

    x = cols["x"]
    y = cols["y"]
    return {
        "n": jnp.sum(mask.astype(jnp.float32)),
        "sx": jnp.sum(jnp.where(mask, x, 0.0)),
        "sy": jnp.sum(jnp.where(mask, y, 0.0)),
        "sxy": jnp.sum(jnp.where(mask, x * y, 0.0)),
    }


def _mr_columns(n: int = 3000) -> Dict[str, np.ndarray]:
    # integer-valued floats: every partial sum is exactly representable
    # in float32, so k-way split order cannot perturb the reduction
    x = np.arange(n, dtype=np.float64) % 97.0
    y = (np.arange(n, dtype=np.float64) * 7.0) % 31.0
    return {"x": x, "y": y}


def _tree_bytes(t: Any) -> bytes:
    import jax

    return b"".join(np.asarray(v).tobytes()
                    for v in jax.tree.leaves(t))


# ---------------------------------------------------------------------------
# fast scenarios (in-process clouds, seeded fault plans)


@scenario("dup_reorder")
def s_dup_reorder(seed: int) -> Dict[str, bool]:
    """Duplicated + reordered mutation frames: every dkv_put frame is
    sent twice and dkv_get frames land after a random delay, from both
    nodes concurrently.  Invariants: all values bit-exact from both
    sides, both fault rules actually fired, and the idempotency-token
    dedup provably collapsed duplicated executions (a counted RPC
    method under the duplicate rule executes exactly once per call)."""
    from h2o3_tpu.cluster import faults

    clouds, stores, formed = _mini_cloud(2, hb=0.1, prefix="dr")
    v: Dict[str, bool] = {"formed": formed}
    try:
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "duplicate", "method": "dkv_put"},
            {"action": "reorder", "method": "dkv_get", "delay_ms": 15},
            {"action": "duplicate", "method": "chaos_count"},
        ]})
        faults.set_plan(plan)

        executions: List[int] = []
        clouds[1].rpc_server.register(
            "chaos_count", lambda p: executions.append(1) or {"ok": True})

        keys = {f"chaos/dup-{i}": [i, i * i, f"v{i}"] for i in range(24)}
        items = sorted(keys.items())

        def _put(store, half):
            for k, val in half:
                store.put(k, val, replicas=2)

        t0 = threading.Thread(target=_put, args=(stores[0], items[:12]))
        t1 = threading.Thread(target=_put, args=(stores[1], items[12:]))
        t0.start(); t1.start(); t0.join(); t1.join()

        v["values_exact"] = all(
            stores[0].get(k) == val and stores[1].get(k) == val
            for k, val in keys.items())

        n_calls = 10
        for i in range(n_calls):
            clouds[0].client.call(clouds[1].info.addr, "chaos_count",
                                  {"i": i}, timeout=5.0,
                                  target=clouds[1].info.ident)
        hits = plan.hits()
        v["duplicates_injected"] = hits[0] > 0 and hits[2] > 0
        v["reorders_injected"] = hits[1] > 0
        # dedup proof: every frame was sent twice, yet each logical call
        # executed exactly once — the duplicate parked on the memo
        v["dedup_exact"] = len(executions) == n_calls
    finally:
        _teardown(clouds)
    return v


@scenario("slow_node")
def s_slow_node(seed: int) -> Dict[str, bool]:
    """Delay ladder against one slow member under DKV + map_reduce
    load: every frame TO node 2 is held ~40ms (well inside the beat
    timeout).  Invariants: no false suspicion/removal, replicated
    values exact through the slow path, distributed map_reduce
    bit-identical to the local run."""
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster.tasks import distributed_map_reduce

    removals0 = _counter_value("cluster_removals_total")
    clouds, stores, formed = _mini_cloud(3, hb=0.15, prefix="sn")
    v: Dict[str, bool] = {"formed": formed}
    try:
        slow_port = clouds[2].info.port
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "delay", "side": "client",
             "dst": f"*:{slow_port}", "delay_ms": 40},
        ]})
        faults.set_plan(plan)

        keys = {f"chaos/slow-{i}": {"i": i, "p": i ** 2} for i in range(12)}
        for k, val in sorted(keys.items()):
            stores[0].put(k, val, replicas=2)
        v["values_exact"] = all(
            stores[j].get(k) == val
            for j in range(3) for k, val in keys.items())

        cols = _mr_columns()
        local = distributed_map_reduce(mr_stat, cols, cloud=None)
        dist = distributed_map_reduce(mr_stat, cols, cloud=clouds[0])
        v["mr_bit_identical"] = _tree_bytes(local) == _tree_bytes(dist)

        v["delays_injected"] = plan.hits()[0] > 0
        v["no_false_removal"] = (
            all(c.size() == 3 for c in clouds)
            and _counter_value("cluster_removals_total") == removals0)
    finally:
        _teardown(clouds)
    return v


@scenario("partition_gossip")
def s_partition_gossip(seed: int) -> Dict[str, bool]:
    """Asymmetric then symmetric partition during gossip.  Phase 1
    drops only a->c heartbeats: c still beats a, so nobody may be
    removed.  Phase 2 isolates c in both directions past the removal
    window: a/b must drop to a 2-cloud and c to a 1-cloud, while a
    replicated key stays readable from the majority side.  Healing the
    partition must reconverge all three with hash consensus (no fence:
    the isolated node's cloud version survived).  A final RESTART
    drill then stops c and boots a fresh process-equivalent in its
    place: the newcomer reuses the name with a reset version, so it
    must be fenced (410), rejoin, and the restarted node's keys must
    re-home onto it — observable as read-repair or a sweep re-home."""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.membership import Cloud
    from h2o3_tpu.keyed import KeyedStore

    rejoins0 = _counter_value("cluster_rejoins_total")
    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="pg")
    a, b, c = clouds
    c2 = None
    v: Dict[str, bool] = {"formed": formed}
    try:
        key, val = "chaos/part-key", {"payload": list(range(8))}
        stores[0].put(key, val, replicas=3)
        keys = {f"chaos/part-{i}": [i, i + 0.5] for i in range(40)}
        for k2, val2 in sorted(keys.items()):
            stores[0].put(k2, val2, replicas=3)

        c_port = c.info.port
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "client",
             "src": a.info.name, "dst": f"*:{c_port}"},
        ]})
        faults.set_plan(plan)
        time.sleep(1.0)  # ~4x the removal window
        v["asymmetric_hits"] = plan.hits()[0] > 0
        v["no_removal_asymmetric"] = all(cl.size() == 3 for cl in clouds)

        plan2 = faults.plan_from_dict({"seed": seed + 1, "rules": [
            {"action": "drop", "side": "client", "dst": f"*:{c_port}"},
            {"action": "drop", "side": "client", "src": c.info.name},
        ]})
        faults.set_plan(plan2)
        v["partition_detected"] = _wait(
            lambda: a.size() == 2 and b.size() == 2 and c.size() == 1, 15.0)
        v["readable_during_partition"] = (
            stores[0].get(key) == val and stores[1].get(key) == val)

        faults.clear_plan()
        v["reconverged"] = _wait(
            lambda: all(cl.size() == 3 for cl in clouds)
            and len({cl.cloud_hash() for cl in clouds}) == 1
            and all(cl.consensus() for cl in clouds), 20.0)
        v["readable_after_heal"] = all(
            stores[j].get(key) == val for j in range(3))

        # -- restart drill: stop c, boot a fresh same-name node --------
        c.stop()
        v["death_detected"] = _wait(
            lambda: a.size() == 2 and b.size() == 2, 15.0)
        repairs0 = _counter_value("cluster_dkv_read_repair_total")
        rehomes0 = _counter_value("cluster_dkv_replica_sweep_total",
                                  action="rehomed")
        restores0 = _counter_value("cluster_dkv_replica_sweep_total",
                                   action="restored")
        c2 = Cloud("chaos", c.info.name, hb_interval=0.05)
        store_c2 = KeyedStore()
        _dkv.install(c2, store_c2)
        _tasks.install(c2)
        c2.start([a.info.addr, b.info.addr])
        v["restart_rejoined"] = _wait(
            lambda: a.size() == 3 and b.size() == 3 and c2.size() == 3,
            20.0)
        # the fresh node's version reset to 1, so re-admission MUST have
        # gone through the 410 fence -> rejoin path
        v["rejoin_counted"] = (
            _counter_value("cluster_rejoins_total") > rejoins0)
        # every key is readable from the restarted (empty) node; keys
        # whose arc it owns re-home onto it via read-repair, keys the
        # survivors tracked re-home via the sweep — either path must
        # surface in telemetry
        v["readable_after_restart"] = all(
            store_c2.get(k2) == val2 for k2, val2 in sorted(keys.items()))
        v["rehome_observable"] = (
            _counter_value("cluster_dkv_read_repair_total") > repairs0
            or _counter_value("cluster_dkv_replica_sweep_total",
                              action="rehomed") > rehomes0
            or _counter_value("cluster_dkv_replica_sweep_total",
                              action="restored") > restores0)
    finally:
        if c2 is not None:
            try:
                c2.stop()
            except Exception:
                pass
        _teardown(clouds)
    return v


@scenario("wedged_member")
def s_wedged_member(seed: int) -> Dict[str, bool]:
    """One member wedges — dtask frames TO it are held seconds (the call
    stays in flight), its gossip is black-holed both ways — and the
    HEALTH PLANE must see it first: the caller's watchdog flags
    ``rpc_stuck`` strictly before heartbeat suspicion fires, the flight
    recorder holds the retry-ladder trail against the victim, and a
    federated diagnostics poll from a survivor degrades to partial
    (never raises) with the victim in ``errors``.  Verdicts are
    booleans only, so two runs with one seed must match byte-for-byte
    (the fresh per-run HealthMonitor and flight seq-delta filtering
    keep run 2 blind to run 1's residue)."""
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import health as _health
    from h2o3_tpu.util import flight as _flight

    # per-run knobs: rpc_stuck at 1x the ladder budget (1.2s for a
    # 0.3s x 4-attempt call), suspicion at 8 x 0.4s = 3.2s silent —
    # a 2s window between watchdog and suspicion even on a loaded box
    env0 = {k: os.environ.get(k) for k in
            ("H2O3_TPU_HEALTH_RPC_FACTOR", "H2O3_TPU_HB_SUSPECT")}
    os.environ["H2O3_TPU_HEALTH_RPC_FACTOR"] = "1.0"
    os.environ["H2O3_TPU_HB_SUSPECT"] = "8"
    clouds, stores, formed = _mini_cloud(3, hb=0.4, prefix="wm")
    a, b, victim = clouds
    v: Dict[str, bool] = {"formed": formed}
    mon = _health.HealthMonitor(node=a.info.name, interval_s=0.05)
    try:
        vport = victim.info.port
        vident = victim.info.ident
        seq0 = _flight.RECORDER.seq  # run 2 ignores run 1's events

        plan = faults.plan_from_dict({"seed": seed, "rules": [
            # the wedge: dtask frames to the victim held 4s — the call
            # ages IN FLIGHT (delay, unlike black_hole, consumes wall)
            {"action": "delay", "side": "client", "method": "dtask",
             "dst": f"*:{vport}", "delay_ms": 4000},
            # gossip blackout both ways: the suspicion clock runs
            {"action": "black_hole", "side": "client",
             "method": "heartbeat", "dst": f"*:{vport}"},
            {"action": "black_hole", "side": "client",
             "method": "heartbeat", "src": victim.info.name},
            # the victim cannot answer a diagnostics poll either
            {"action": "black_hole", "side": "client",
             "method": "diagnostics_snapshot", "dst": f"*:{vport}"},
        ]})
        faults.set_plan(plan)
        mon.start()

        def _wedged_call() -> None:
            try:
                a.client.call(victim.info.addr, "dtask",
                              {"task": "echo", "payload": {"i": seed}},
                              timeout=0.3, target=vident)
            except Exception:
                pass  # outcome immaterial — the in-flight AGE is the test

        caller = threading.Thread(target=_wedged_call, daemon=True,
                                  name="wedged-dtask")
        caller.start()

        def _suspected() -> bool:
            return any(
                ev["category"] == _flight.MEMBERSHIP
                and ev["msg"] in ("suspect", "tombstone")
                and vident in str(ev.get("member", ""))
                for ev in _flight.RECORDER.snapshot(min_seq=seq0))

        flagged = _wait(
            lambda: (mon.verdicts().get("rpc_stuck") or {}).get(
                "state") in ("degraded", "critical"), 2.6)
        v["wedge_flagged"] = flagged
        # the whole point: the watchdog saw the wedge while membership
        # still considered the victim healthy
        v["wedge_flagged_before_suspicion"] = flagged and not _suspected()
        g = _health._HEALTH_STATE.value(node=a.info.name, check="rpc_stuck")
        v["gauge_degraded"] = g >= 1.0
        # the transition landed in the flight ring as a health event
        v["stall_explained"] = any(
            ev["category"] == _flight.HEALTH
            and ev.get("check") == "rpc_stuck"
            and ev.get("state") in ("degraded", "critical")
            for ev in _flight.RECORDER.snapshot(min_seq=seq0))

        # federated diagnostics from the survivor: the victim lands in
        # errors, the answer degrades to partial — it never raises
        try:
            results, errors = a.poll_members(
                "diagnostics_snapshot", {"events": 50}, timeout=1.0)
            v["diagnostics_partial"] = (
                victim.info.name in errors
                and a.info.name in results
                and b.info.name in results)
        except Exception:
            v["diagnostics_partial"] = False
        # the ladder's attempts against the wedged node are in the ring
        v["retry_trail_in_flight"] = any(
            ev["category"] == _flight.RPC
            and ev["msg"] in ("retry", "timeout", "connect_error")
            and str(ev.get("target", "")).endswith(f":{vport}")
            for ev in _flight.RECORDER.snapshot(min_seq=seq0))

        # suspicion DOES eventually fire — the watchdog was early, not
        # a replacement for the failure detector
        v["suspicion_eventually"] = _wait(_suspected, 12.0)
        caller.join(timeout=8.0)
    finally:
        mon.stop()
        for k, old in env0.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        _teardown(clouds)
    return v


@scenario("kill_chunk_home")
def s_kill_chunk_home(seed: int) -> Dict[str, bool]:
    """Chunk-homed distributed Frame through a home's death.  A CSV
    parses ONTO the ring (``distributed_parse_chunks`` with a live DKV
    lands tokenized chunks on their chunk-group homes, replicated to
    ring successors), then ``distributed_map_reduce`` over the resulting
    DistFrame runs map-side with only partials crossing the wire —
    proven by the RPC byte meter.  The nemesis then makes one home
    (never the caller) refuse its ``mr_chunks`` task and stops it
    mid-fan-out: the group must re-execute from REPLICA chunks on the
    ring successors (``path=replica``), never by caller re-parse
    (``path=local`` stays zero), bit-identical to the local run.  A
    fresh same-name node then boots empty in the victim's place: every
    one of the dead home's chunks must read back through the ring walk
    and the re-home must surface in repair/sweep telemetry.  (A real
    SIGKILL mid-flight on child processes is the multiprocess tier —
    ``TestSigkillChunkHome``; in-process ``stop()`` drains in-flight
    dispatches gracefully, so the refusal rule is what makes the death
    observable at task granularity here.)"""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.frames import DistFrame, chunk_key
    from h2o3_tpu.cluster.membership import Cloud
    from h2o3_tpu.frame.parse import (
        _iter_body_chunks, parse_csv, parse_setup,
    )
    from h2o3_tpu.keyed import KeyedStore

    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="ch")
    a = clouds[0]
    c2 = None
    v: Dict[str, bool] = {"formed": formed}
    try:
        # integer-valued floats (exact float32 partials under any
        # partitioning) + a CAT column so domain merging is on the line
        n = 24000
        xs = np.arange(n) % 97
        ys = (np.arange(n) * 7) % 31
        cats = ("lo", "mid", "hi")
        text = "x,y,c\n" + "".join(
            f"{xs[i]},{ys[i]},{cats[i % 3]}\n" for i in range(n))
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 16384, setup.header, setup.skip_blank_lines))
        serial = parse_csv(text)

        fr = _tasks.distributed_parse_chunks(
            chunks, setup, cloud=a, key=f"chaos_df_{seed}")
        lay = getattr(fr, "chunk_layout", None)
        v["parsed_chunk_homed"] = isinstance(fr, DistFrame) and bool(lay)
        if not v["parsed_chunk_homed"]:
            return v
        v["chunks_spread"] = len(
            {g["home_name"] for g in lay["groups"]}) >= 2

        host = {nm: serial.col(nm).numeric_view() for nm in ("x", "y")}
        local = _tasks.distributed_map_reduce(mr_stat, host, cloud=None)
        frame_bytes = sum(
            serial.col(nm).numeric_view().nbytes for nm in serial.names)

        sent0 = _counter_sum("rpc_payload_bytes_total", direction="sent")
        dist = _tasks.distributed_map_reduce(mr_stat, fr, cloud=a)
        sent_mr = _counter_sum(
            "rpc_payload_bytes_total", direction="sent") - sent0
        v["mr_bit_identical"] = _tree_bytes(local) == _tree_bytes(dist)
        # map-side execution ships partials (plus gossip noise), never
        # the columns — a host-dict fan-out would ship ~2/3 of the frame
        v["partials_only"] = sent_mr < frame_bytes / 4

        # -- nemesis: one home (never the caller) refuses its group and
        # dies mid-fan-out ---------------------------------------------
        victim_name = next(g["home_name"] for g in lay["groups"]
                           if g["home_name"] != a.info.name)
        victim = next(c for c in clouds if c.info.name == victim_name)
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "server", "src": victim_name,
             "method": "dtask:mr_chunks"},
        ]})
        faults.set_plan(plan)
        rep0 = _counter_value("cluster_fanout_recovered_total",
                              path="replica")
        loc0 = _counter_value("cluster_fanout_recovered_total",
                              path="local")
        box: Dict[str, Any] = {}

        def _dmr():
            try:
                box["out"] = _tasks.distributed_map_reduce(
                    mr_stat, fr, cloud=a, timeout=60.0)
            except Exception as e:  # invariant failure, not a crash
                box["err"] = e

        th = threading.Thread(target=_dmr, daemon=True)
        th.start()
        time.sleep(0.3)
        victim.stop()
        th.join(timeout=90.0)
        v["refusal_injected"] = plan.hits()[0] > 0
        v["killed_mr_completed"] = "out" in box
        v["killed_mr_bit_identical"] = (
            "out" in box and _tree_bytes(local) == _tree_bytes(box["out"]))
        v["replica_recovered"] = _counter_value(
            "cluster_fanout_recovered_total", path="replica") > rep0
        v["no_caller_reparse"] = _counter_value(
            "cluster_fanout_recovered_total", path="local") == loc0
        faults.clear_plan()

        # -- restart drill: a fresh same-name EMPTY node re-adopts the
        # dead home's chunks through the ring walk ---------------------
        v["death_detected"] = _wait(
            lambda: all(c.size() == 2 for c in clouds
                        if c.info.name != victim_name), 15.0)
        repairs0 = _counter_value("cluster_dkv_read_repair_total")
        sweep0 = {
            act: _counter_value("cluster_dkv_replica_sweep_total",
                                action=act)
            for act in ("restored", "reseeded", "rehomed", "promoted")
        }
        c2 = Cloud("chaos", victim_name, hb_interval=0.05)
        store_c2 = KeyedStore()
        _dkv.install(c2, store_c2)
        _tasks.install(c2)
        c2.start([c.info.addr for c in clouds
                  if c.info.name != victim_name])
        v["restart_rejoined"] = _wait(
            lambda: c2.size() == 3 and a.size() == 3, 20.0)
        vgrp = next(g for g in lay["groups"]
                    if g["home_name"] == victim_name)
        v["chunks_readback"] = all(
            store_c2.get(chunk_key(vgrp["anchor"], i)) is not None
            for i in range(vgrp["lo"], vgrp["hi"]))
        dist2 = _tasks.distributed_map_reduce(mr_stat, fr, cloud=a)
        v["post_restart_mr_bit_identical"] = (
            _tree_bytes(local) == _tree_bytes(dist2))

        # -- codec plane: chunks (and their replicas) rest ENCODED on
        # the ring, and a full materialization — here necessarily read
        # through replica/ring-walk bytes after the home died — decodes
        # bit-identically to the serial parse ---------------------------
        from h2o3_tpu.frame import codecs as _codecs

        grp0 = lay["groups"][0]
        enc_val = stores[0].get(chunk_key(grp0["anchor"], int(grp0["lo"])))
        v["chunks_landed_encoded"] = (
            _codecs.codecs_enabled() and enc_val is not None
            and _codecs.is_encoded_chunk(enc_val))
        v["replica_decode_bit_identical"] = all(
            np.array_equal(
                fr.col(nm).numeric_view().view(np.uint64),
                serial.col(nm).numeric_view().view(np.uint64))
            for nm in ("x", "y", "c"))
        v["rehome_observable"] = _wait(
            lambda: (
                _counter_value("cluster_dkv_read_repair_total") > repairs0
                or any(
                    _counter_value("cluster_dkv_replica_sweep_total",
                                   action=act) > sweep0[act]
                    for act in sweep0)), 10.0)
    finally:
        if c2 is not None:
            try:
                c2.stop()
            except Exception:
                pass
        _teardown(clouds)
    return v


@scenario("kill_serving_replica")
def s_kill_serving_replica(seed: int) -> Dict[str, bool]:
    """Serving plane through a serving member's death mid-storm.  A GLM
    trains on one node and its blob homes onto the ring (home + one
    successor); a front door that holds neither model nor blob storms
    ``forward_predict``.  Phase one saturates the home's serving budget:
    every request must SPILL to the replica (429 at the home, 2xx from
    the replica, bit-identical to the builder's own predict).  Phase two
    makes the home refuse its ``predict_remote`` task and stops it
    mid-storm: the remaining requests must degrade down the ladder to
    the surviving replica with nothing but 2xx/429 — never a 5xx, never
    a wrong answer.  (As with ``kill_chunk_home``, in-process ``stop()``
    drains in-flight dispatches gracefully and pooled connections can
    outlive the listener, so the refusal rule is what makes the death
    observable at task granularity.)"""
    from h2o3_tpu.api.server import RestError
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import serving
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM

    saved_reps = os.environ.get("H2O3_TPU_SERVE_REPLICAS")
    os.environ["H2O3_TPU_SERVE_REPLICAS"] = "1"
    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="sv")
    v: Dict[str, bool] = {"formed": formed}
    try:
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(500, 4))
        logit = X @ np.array([1.1, -0.7, 0.4, 0.0]) - 0.1
        y = rng.random(500) < 1.0 / (1.0 + np.exp(-logit))
        fr = Frame.from_dict(
            {f"x{i}": X[:, i] for i in range(4)}
            | {"y": np.where(y, "yes", "no").astype(object)})
        m = GLM(family="binomial", response_column="y",
                lambda_=0.0, seed=seed).train(fr)
        v["homed"] = serving.home_model(
            m, cloud=clouds[0], store=stores[0])
        members = serving.serving_members(m.key, stores[0])
        names = [mm.info.name for mm in members]
        v["replicated"] = len(names) == 2 and _wait(
            lambda: all(isinstance(
                s.peek(serving.serve_key(m.key)), (bytes, bytearray))
                for c, s in zip(clouds, stores) if c.info.name in names),
            10.0)
        if not (v["homed"] and v["replicated"]):
            return v
        by_name = {c.info.name: (c, s)
                   for c, s in zip(clouds, stores)}
        front_c, front_s = next(
            (c, s) for c, s in zip(clouds, stores)
            if c.info.name not in names)
        Xs = rng.normal(size=(60, 4))
        sf = Frame.from_dict({f"x{i}": Xs[:, i] for i in range(4)})
        front_s.put("chaos_serve_df", sf)
        ref = [np.asarray(c.data, dtype=np.float64)
               for c in m.predict(sf).columns]

        def _shot() -> str:
            """One forwarded request: '2xx' only if the answer is
            bit-identical to the builder's predict, '429' on a clean
            shed, '5xx' on anything else."""
            try:
                outs = serving.forward_predict(
                    [({}, {"model_id": m.key,
                           "frame_id": "chaos_serve_df"})],
                    m.key, cloud=front_c, store=front_s)
            except Exception as e:
                return "429" if getattr(e, "status", 0) == 429 else "5xx"
            if outs is None:
                return "5xx"
            out = outs[0]
            if isinstance(out, BaseException):
                return ("429" if isinstance(out, RestError)
                        and out.status == 429 else "5xx")
            dest = out["model_metrics"][0]["predictions_frame"]["name"]
            pred = front_s.get(dest)
            got = [np.asarray(c.data, dtype=np.float64)
                   for c in pred.columns]
            same = len(got) == len(ref) and all(
                np.array_equal(g, r) for g, r in zip(got, ref))
            return "2xx" if same else "5xx"

        # -- phase one: saturated home spills to the replica -----------
        home_c, home_s = by_name[names[0]]
        spill0 = _counter_value("serve_replica_spill_total")
        home_s._serve_budget = 0
        spill_outcomes = [_shot() for _ in range(3)]
        home_s._serve_budget = None
        v["spill_served"] = spill_outcomes == ["2xx"] * 3
        v["spill_observable"] = (
            _counter_value("serve_replica_spill_total") >= spill0 + 3)

        # -- phase two: the home refuses predict_remote and dies -------
        rep0 = _counter_value("cluster_fanout_recovered_total",
                              path="replica")
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "server", "src": names[0],
             "method": "dtask:predict_remote"},
        ]})
        faults.set_plan(plan)
        outcomes = [_shot() for _ in range(2)]
        home_c.stop()
        outcomes += [_shot() for _ in range(5)]
        v["refusal_injected"] = plan.hits()[0] > 0
        v["overload_clean"] = all(o in ("2xx", "429") for o in outcomes)
        v["no_5xx"] = "5xx" not in outcomes
        v["killed_storm_served"] = outcomes.count("2xx") >= 5
        v["replica_recovered"] = _counter_value(
            "cluster_fanout_recovered_total", path="replica") > rep0
    finally:
        faults.clear_plan()
        if saved_reps is None:
            os.environ.pop("H2O3_TPU_SERVE_REPLICAS", None)
        else:
            os.environ["H2O3_TPU_SERVE_REPLICAS"] = saved_reps
        _teardown(clouds)
    return v


@scenario("kill_rapids_home")
def s_kill_rapids_home(seed: int) -> Dict[str, bool]:
    """Distributed Rapids through a home's death.  A CSV parses ONTO
    the ring, then a fused reduce region (``(sum (* x y))``) ships as
    ``rapids_exec`` ctx-DTasks to the chunk homes — only the canonical
    sexpr goes out and only ``{v,n}`` reducer partials come back,
    proven by the payload meter against the frame bytes.  The nemesis
    makes one home (never the caller) refuse every ``rapids_exec`` and
    stops it mid-fan-out: the group must re-execute from REPLICA
    chunks on the ring successors (``path=replica``), never by caller
    gather (``path=local`` stays zero), bit-identical to the fusion-off
    interpreter on a serial twin.  A fresh same-name node then boots
    empty in the victim's place: the dead home's chunks must read back
    through the ring walk and the same eval must stay bit-identical —
    with the source DistFrame never materializing caller-side at any
    point in the drill."""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.frames import DistFrame, chunk_key
    from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
    from h2o3_tpu.frame.parse import (
        _iter_body_chunks, parse_csv, parse_setup,
    )
    from h2o3_tpu.keyed import KeyedStore
    from h2o3_tpu.rapids.runtime import Session, exec_rapids

    def _bits(val) -> bytes:
        return np.asarray(
            val.value, dtype=np.float64).view(np.uint64).tobytes()

    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="rh")
    a = clouds[0]
    c2 = None
    v: Dict[str, bool] = {"formed": formed}
    fus_prev = os.environ.get("H2O3_TPU_RAPIDS_FUSION")
    # the rapids dist path resolves the caller's cloud via active_cloud()
    set_local_cloud(a)
    try:
        # integer-valued floats: reducer partials are exact in f64
        # under any chunk partitioning, so Σ merge order cannot move bits
        n = 24000
        xs = np.arange(n) % 97
        ys = (np.arange(n) * 7) % 31
        text = "x,y\n" + "".join(
            f"{xs[i]},{ys[i]}\n" for i in range(n))
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 16384, setup.header, setup.skip_blank_lines))
        serial = parse_csv(text)

        fr = _tasks.distributed_parse_chunks(
            chunks, setup, cloud=a, key=f"chaos_rap_{seed}")
        lay = getattr(fr, "chunk_layout", None)
        v["parsed_chunk_homed"] = isinstance(fr, DistFrame) and bool(lay)
        if not v["parsed_chunk_homed"]:
            return v

        sess = Session()
        sess.assign("rd", fr)
        sess.assign("rl", serial)
        expr_d = "(sum (* (cols_py rd 0) (cols_py rd 1)))"
        expr_l = "(sum (* (cols_py rl 0) (cols_py rl 1)))"

        # fusion-off interpreter on the serial twin: the bit reference
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
        ref = _bits(exec_rapids(expr_l, sess))
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"

        # healthy dist eval: wire discipline.  Pin the meter to the
        # classes data motion would ride — dtask payloads (fan-out +
        # partials) and dkv_get (a gather's ring walk) — so gossip and
        # replica-sweep noise in the window cannot flip the verdict.
        frame_bytes = 8 * int(lay["espc"][-1]) * len(lay["column_names"])
        d0 = _counter_value("rapids_dist_total", result="dist")
        sent0 = _counter_sum("rpc_payload_bytes_total",
                             direction="sent", method="dtask")
        get0 = _counter_sum("rpc_payload_bytes_total", method="dkv_get")
        got = exec_rapids(expr_d, sess)
        moved = (
            _counter_sum("rpc_payload_bytes_total",
                         direction="sent", method="dtask") - sent0
            + _counter_sum("rpc_payload_bytes_total",
                           method="dkv_get") - get0)
        v["dist_path_taken"] = _counter_value(
            "rapids_dist_total", result="dist") - d0 >= 1
        v["healthy_bit_identical"] = _bits(got) == ref
        v["partials_only"] = moved < frame_bytes / 4

        # -- nemesis: one home (never the caller) refuses rapids_exec
        # and dies mid-fan-out -----------------------------------------
        victim_name = next(g["home_name"] for g in lay["groups"]
                           if g["home_name"] != a.info.name)
        victim = next(c for c in clouds if c.info.name == victim_name)
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "server", "src": victim_name,
             "method": "dtask:rapids_exec"},
        ]})
        faults.set_plan(plan)
        rep0 = _counter_value("cluster_fanout_recovered_total",
                              path="replica")
        loc0 = _counter_value("cluster_fanout_recovered_total",
                              path="local")
        box: Dict[str, Any] = {}

        def _eval():
            try:
                box["bits"] = _bits(exec_rapids(expr_d, sess))
            except Exception as e:  # invariant failure, not a crash
                box["err"] = e

        th = threading.Thread(target=_eval, daemon=True)
        th.start()
        time.sleep(0.3)
        victim.stop()
        th.join(timeout=90.0)
        v["refusal_injected"] = plan.hits()[0] > 0
        v["killed_eval_completed"] = "bits" in box
        v["killed_eval_bit_identical"] = box.get("bits") == ref
        v["replica_recovered"] = _counter_value(
            "cluster_fanout_recovered_total", path="replica") > rep0
        v["no_caller_reparse"] = _counter_value(
            "cluster_fanout_recovered_total", path="local") == loc0
        faults.clear_plan()

        # -- restart drill: a fresh same-name EMPTY node boots in the
        # victim's place; chunks read back through the ring walk -------
        v["death_detected"] = _wait(
            lambda: all(c.size() == 2 for c in clouds
                        if c.info.name != victim_name), 15.0)
        c2 = Cloud("chaos", victim_name, hb_interval=0.05)
        store_c2 = KeyedStore()
        _dkv.install(c2, store_c2)
        _tasks.install(c2)
        c2.start([c.info.addr for c in clouds
                  if c.info.name != victim_name])
        v["restart_rejoined"] = _wait(
            lambda: c2.size() == 3 and a.size() == 3, 20.0)
        vgrp = next(g for g in lay["groups"]
                    if g["home_name"] == victim_name)
        v["chunks_readback"] = all(
            store_c2.get(chunk_key(vgrp["anchor"], i)) is not None
            for i in range(vgrp["lo"], vgrp["hi"]))
        v["post_restart_bit_identical"] = (
            _bits(exec_rapids(expr_d, sess)) == ref)
        # the whole drill must have run map-side: a single gather would
        # have parked the materialized frame on the caller
        v["never_gathered"] = fr._materialized is None
    finally:
        if fus_prev is None:
            os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        else:
            os.environ["H2O3_TPU_RAPIDS_FUSION"] = fus_prev
        set_local_cloud(None)
        if c2 is not None:
            try:
                c2.stop()
            except Exception:
                pass
        _teardown(clouds)
    return v


@scenario("kill_hist_home")
def s_kill_hist_home(seed: int) -> Dict[str, bool]:
    """Map-side distributed tree training through a home's death.  A CSV
    parses ONTO the ring, a GBM reference fit runs with the same engine
    forced caller-local (``H2O3_TPU_DIST_HIST=local``), then the
    distributed fit fans ``hist_level`` ctx-DTasks to the chunk homes —
    only ``(feature, bin, {Σg,Σh,Σw})`` partials cross the wire, proven
    by the payload meter against the level arithmetic
    ``n_nodes x F x (nbins+1) x 3 x 8 x n_homes``.  The nemesis makes
    one home (never the caller) refuse every ``hist_level`` and stops
    it mid-fit: the fit must finish down the replica rung of the ladder
    (``cluster_fanout_recovered_total{path=replica}``), applying each
    refused op exactly once (drops fault BEFORE the handler, so no
    double-counted rows are possible), and the final trees + training
    metric must be BIT-IDENTICAL to the pre-kill local reference."""
    import pickle

    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.frames import DistFrame
    from h2o3_tpu.cluster.membership import set_local_cloud
    from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup
    from h2o3_tpu.models.grid import metric_value
    from h2o3_tpu.models.tree.gbm import GBM, GBMParameters

    n = 12000
    xs = np.arange(n) % 97
    ys = (np.arange(n) * 7) % 31
    zs = (np.arange(n) * 13) % 53
    cats = ("lo", "mid", "hi")
    bins = ("no", "yes")
    text = "x,y,z,c,resp\n" + "".join(
        f"{xs[i]},{ys[i]},{zs[i]},{cats[i % 3]},"
        f"{bins[int((xs[i] * 3 + ys[i]) % 11 < 5)]}\n" for i in range(n))
    setup = parse_setup(text)
    chunks = list(_iter_body_chunks(
        [text.encode()], 16384, setup.header, setup.skip_blank_lines))

    def _fit():
        m = GBM(GBMParameters(
            response_column="resp", ntrees=6, max_depth=3, nbins=16,
            min_rows=1.0, seed=seed)).train(fr)
        bt = m.booster
        arrays = [np.stack(getattr(t, f)) for t in bt.trees_per_class
                  for f in ("feat", "split_bin", "default_left",
                            "is_split", "leaf")]
        return pickle.dumps([arrays, np.asarray(bt.init_margin),
                             metric_value(m, "auto")[0]])

    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="hh")
    a = clouds[0]
    v: Dict[str, bool] = {"formed": formed}
    mode_prev = os.environ.get("H2O3_TPU_DIST_HIST")
    set_local_cloud(a)
    try:
        fr = _tasks.distributed_parse_chunks(
            chunks, setup, cloud=a, key=f"chaos_hist_{seed}")
        lay = getattr(fr, "chunk_layout", None)
        v["parsed_chunk_homed"] = isinstance(fr, DistFrame) and bool(lay)
        if not v["parsed_chunk_homed"]:
            return v

        os.environ["H2O3_TPU_DIST_HIST"] = "local"
        ref = _fit()
        os.environ["H2O3_TPU_DIST_HIST"] = "1"

        # healthy distributed fit: wire discipline
        frame_bytes = 8 * int(lay["espc"][-1]) * len(lay["column_names"])
        wire0 = _counter_sum("rpc_payload_bytes_total")
        lv0 = _counter_value("dist_hist_levels_total")
        pb0 = _counter_value("dist_hist_partial_bytes_total")
        v["healthy_bit_identical"] = _fit() == ref
        wire = _counter_sum("rpc_payload_bytes_total") - wire0
        levels = _counter_value("dist_hist_levels_total") - lv0
        partial = _counter_value("dist_hist_partial_bytes_total") - pb0
        # per level each home ships <= n_nodes x F x n_bins1 x 3 x 8
        per_level_cap = 4 * 4 * 17 * 3 * 8 * len(lay["groups"])
        v["partials_bounded"] = (
            levels > 0 and partial <= levels * per_level_cap)
        v["wire_under_frame"] = wire < frame_bytes

        # -- nemesis: one home refuses hist_level and dies mid-fit ------
        victim_name = next(g["home_name"] for g in lay["groups"]
                           if g["home_name"] != a.info.name)
        victim = next(c for c in clouds if c.info.name == victim_name)
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "server", "src": victim_name,
             "method": "dtask:hist_level"},
        ]})
        faults.set_plan(plan)
        rep0 = _counter_value("cluster_fanout_recovered_total",
                              path="replica")
        box: Dict[str, Any] = {}

        def _train():
            try:
                box["sig"] = _fit()
            except Exception as e:  # invariant failure, not a crash
                box["err"] = e

        th = threading.Thread(target=_train, daemon=True)
        th.start()
        time.sleep(0.3)
        victim.stop()
        th.join(timeout=120.0)
        v["refusal_injected"] = plan.hits()[0] > 0
        v["killed_fit_completed"] = "sig" in box
        v["killed_fit_bit_identical"] = box.get("sig") == ref
        v["replica_recovered"] = _counter_value(
            "cluster_fanout_recovered_total", path="replica") > rep0
    finally:
        if mode_prev is None:
            os.environ.pop("H2O3_TPU_DIST_HIST", None)
        else:
            os.environ["H2O3_TPU_DIST_HIST"] = mode_prev
        set_local_cloud(None)
        _teardown(clouds)
    return v


@scenario("kill_search_member")
def s_kill_search_member(seed: int) -> Dict[str, bool]:
    """Distributed grid search through a member's death, then a
    cancel -> ``auto_recover`` resume drill.  Phase 1: a 6-cell GLM
    grid fans out over a 3-node cloud; a fault rule lets the victim
    train exactly ONE cell then refuse every later ``search_cell``
    (``after: 1``), and the nemesis stops it mid-search.  Invariants:
    the search completes with the full model count, the leaderboard is
    bit-identical to the single-node baseline in canonical walk order,
    survivors re-claimed the victim's cells (``path=survivor``
    metered), and the global cell meter moved by exactly the cell
    count — no cell trained twice (dropped dispatches 503 BEFORE the
    handler, so the victim never half-trains).  Phase 2: the same grid
    with ``recovery_dir`` is cancelled via its Job once >=2 cells
    stream ``done`` progress; the snapshot must survive the cancel and
    ``auto_recover`` must finish the grid WITHOUT retraining finished
    cells (total cells across cancel+resume == 6), hp-sorted rows
    bit-identical to the baseline (resume inserts snapshot models
    first, so canonical-order comparison does not apply)."""
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster.membership import set_local_cloud
    from h2o3_tpu.frame.frame import ColType, Column, Frame
    from h2o3_tpu.models.framework import Job
    from h2o3_tpu.models.glm import GLM, GLMParameters
    from h2o3_tpu.models.grid import GridSearch, cell_key, metric_value
    from h2o3_tpu.recovery import auto_recover

    rng = np.random.default_rng(seed)
    n = 400
    X = rng.normal(size=(n, 3))
    logit = X @ np.array([1.0, -2.0, 0.5])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    cols = [Column(f"x{i}", X[:, i]) for i in range(3)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    fr = Frame(cols)

    # nonzero lambdas: at lambda_=0 alpha is inert, metrics tie, and the
    # leaderboard sort order would depend on insertion order
    hyper = {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.01, 0.1]}
    n_cells = 6

    def _gs(rec_dir=None):
        return GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial",
                          seed=7, nfolds=2),
            hyper, recovery_dir=rec_dir)

    def _rows(grid):
        return [(cell_key(hp), metric_value(m, "auto")[0])
                for hp, m in zip(grid.hyper_params, grid.models)]

    # single-node baseline BEFORE any cloud exists (no local cloud set,
    # so the walk runs in-process): the bit-identity reference
    base = _rows(_gs().train(fr))

    clouds, stores, formed = _mini_cloud(3, hb=0.05, prefix="ks")
    a = clouds[0]
    victim = clouds[1]
    v: Dict[str, bool] = {"formed": formed}
    set_local_cloud(a)
    try:
        # -- phase 1: victim trains one cell, refuses the rest, dies --
        plan = faults.plan_from_dict({"seed": seed, "rules": [
            {"action": "drop", "side": "server",
             "src": victim.info.name, "method": "dtask:search_cell",
             "after": 1},
        ]})
        faults.set_plan(plan)
        cells0 = _counter_value("cluster_search_cells_total")
        surv0 = _counter_value("cluster_search_recovered_total",
                               path="survivor")
        box: Dict[str, Any] = {}

        def _train():
            try:
                box["grid"] = _gs().train(fr)
            except Exception as e:  # invariant failure, not a crash
                box["err"] = e

        th = threading.Thread(target=_train, daemon=True)
        th.start()
        time.sleep(0.5)
        victim.stop()
        th.join(timeout=180.0)
        faults.clear_plan()

        v["search_completed"] = "grid" in box
        grid1 = box.get("grid")
        v["full_model_count"] = (grid1 is not None
                                 and len(grid1.models) == n_cells)
        # distributed recording is canonical walk order: compare directly
        v["leaderboard_bit_identical"] = (grid1 is not None
                                          and _rows(grid1) == base)
        v["refusal_injected"] = plan.hits()[0] > 0
        v["survivor_recovered"] = _counter_value(
            "cluster_search_recovered_total", path="survivor") > surv0
        # in-process clouds share one meter: exactly n_cells training
        # runs happened ANYWHERE — the victim's dropped dispatches were
        # refused before the handler, never half-trained
        v["no_cell_trained_twice"] = (
            _counter_value("cluster_search_cells_total") - cells0
            == float(n_cells))

        # survivors must notice the death before the resume drill so
        # phase 2 never dispatches into the corpse
        v["death_detected"] = _wait(
            lambda: all(c.size() == 2 for c in clouds
                        if c.info.name != victim.info.name), 15.0)

        # -- phase 2: cancel mid-search, resume from the snapshot ------
        tmp = tempfile.mkdtemp(prefix="chaos-search-")
        rec_dir = os.path.join(tmp, "rec")
        meta_path = os.path.join(rec_dir, "recovery.json")
        cells1 = _counter_value("cluster_search_cells_total")
        done0 = _counter_value("cluster_search_progress_total",
                               status="done")
        job = Job("chaos distributed search").start()
        watcher_saw = {"two_done": False}

        def _watch():
            if _wait(lambda: _counter_value(
                    "cluster_search_progress_total",
                    status="done") - done0 >= 2.0, 120.0):
                watcher_saw["two_done"] = True
                job.cancel()

        wth = threading.Thread(target=_watch, daemon=True)
        wth.start()
        grid2 = _gs(rec_dir=rec_dir).train(fr, job=job)
        wth.join(timeout=130.0)
        v["cancel_landed"] = watcher_saw["two_done"]

        partial = len(grid2.models) + len(grid2.failures)
        # the cancel races completion: when it interrupted the search
        # the snapshot MUST survive; when every cell finished first the
        # snapshot was legitimately cleaned and there is nothing to test
        interrupted = partial < n_cells
        v["snapshot_kept_when_partial"] = (
            os.path.exists(meta_path) if interrupted else True)
        if os.path.exists(meta_path):
            grid3 = auto_recover(rec_dir)
        else:
            grid3 = grid2
        v["resumed_complete"] = (grid3 is not None
                                 and len(grid3.models) == n_cells)
        # resume inserts snapshot models before walk-order ones, so
        # compare hp-sorted rows (floats still bit-exact)
        v["resume_rows_bit_identical"] = (
            grid3 is not None and sorted(_rows(grid3)) == sorted(base))
        # cancel + resume together trained each cell exactly once
        v["no_retrain_after_resume"] = (
            _counter_value("cluster_search_cells_total") - cells1
            == float(n_cells))
    finally:
        set_local_cloud(None)
        _teardown(clouds)
    return v


# ---------------------------------------------------------------------------
# slow scenarios (real child processes, SIGKILL nemesis)


def _env(extra_path: str = "") -> Dict[str, str]:
    env = dict(os.environ)
    path = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if extra_path:
        path = extra_path + os.pathsep + path
    env["PYTHONPATH"] = path
    env["PYTHONUNBUFFERED"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["H2O3_TPU_HB_INTERVAL"] = "0.2"
    env["H2O3_TPU_FAULTS"] = "1"  # nemesis RPC surface on every node
    return env


def _spawn_node(name: str, addr_file: str,
                flatfile: Optional[str] = None,
                extra_path: str = "") -> subprocess.Popen:
    cmd = [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
           "--cluster-name", "chaoskill", "--node-name", name,
           "--port", "0", "--address-file", addr_file]
    if flatfile:
        cmd += ["--flatfile", flatfile]
    return subprocess.Popen(
        cmd, stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, env=_env(extra_path), cwd=_ROOT)


def _read_addr(path: str, deadline_s: float = 30.0) -> Tuple[str, int]:
    ok = _wait(lambda: os.path.exists(path)
               and os.path.getsize(path) > 0, deadline_s)
    if not ok:
        raise RuntimeError(f"address file {path} never appeared")
    host, port = open(path).read().strip().rsplit(":", 1)
    return host, int(port)


@scenario("kill_fanout", slow=True)
def s_kill_fanout(seed: int) -> Dict[str, bool]:
    """SIGKILL a member mid-``distributed_map_reduce``.  A fault plan
    installed over the nemesis RPC surface slows the victim's dtask
    handling so the kill provably lands mid-shard.  Invariants: the
    result is bit-identical to the local run, the range re-ran on the
    SURVIVOR (``path=survivor`` metered here, the survivor's own
    ``mr_shard`` meter moved — remote-side proof), and membership
    reconverges to the survivors."""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import faults
    from h2o3_tpu.cluster import rpc as _rpc
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.cluster.membership import Cloud
    from h2o3_tpu.cluster.tasks import distributed_map_reduce
    from h2o3_tpu.keyed import KeyedStore

    tmp = tempfile.mkdtemp(prefix="chaos-kill-")
    # the MR fn must be importable by the SAME module path on every
    # node — a tmp module on everyone's PYTHONPATH, never __main__
    mrfns = os.path.join(tmp, "chaos_mrfns.py")
    with open(mrfns, "w") as f:
        f.write(
            "import jax.numpy as jnp\n\n\n"
            "def stat(cols, mask):\n"
            "    x = cols['x']\n"
            "    y = cols['y']\n"
            "    return {'n': jnp.sum(mask.astype(jnp.float32)),\n"
            "            'sx': jnp.sum(jnp.where(mask, x, 0.0)),\n"
            "            'sy': jnp.sum(jnp.where(mask, y, 0.0)),\n"
            "            'sxy': jnp.sum(jnp.where(mask, x * y, 0.0))}\n")
    sys.path.insert(0, tmp)
    import chaos_mrfns  # noqa: E402  (the tmp module written above)

    victim = _spawn_node("victim", os.path.join(tmp, "victim.addr"),
                         extra_path=tmp)
    surv = None
    cloud = None
    v: Dict[str, bool] = {}
    try:
        victim_addr = _read_addr(os.path.join(tmp, "victim.addr"))
        flatfile = os.path.join(tmp, "flatfile")
        with open(flatfile, "w") as f:
            f.write(f"{victim_addr[0]}:{victim_addr[1]}\n")
        surv = _spawn_node("survivor", os.path.join(tmp, "surv.addr"),
                           flatfile=flatfile, extra_path=tmp)
        surv_addr = _read_addr(os.path.join(tmp, "surv.addr"))

        cloud = Cloud("chaoskill", "driver", hb_interval=0.2)
        _dkv.install(cloud, KeyedStore())
        _tasks.install(cloud)
        cloud.start([victim_addr, surv_addr])
        v["formed"] = _wait(lambda: cloud.size() == 3, 30.0)

        # nemesis: hold the victim's dtask handling long enough that
        # SIGKILL lands while its shard is provably in flight
        cloud.client.call(victim_addr, "fault_plan_set", {
            "seed": seed,
            "rules": [{"action": "delay", "side": "server",
                       "method": "dtask", "delay_ms": 2500}],
        }, timeout=5.0)

        cols = _mr_columns(4001)
        local = distributed_map_reduce(chaos_mrfns.stat, cols, cloud=None)
        rec0 = _counter_value("cluster_fanout_recovered_total",
                              path="survivor")
        box: Dict[str, Any] = {}

        def _dmr():
            try:
                box["out"] = distributed_map_reduce(
                    chaos_mrfns.stat, cols, cloud=cloud, timeout=60.0)
            except Exception as e:  # invariant failure, not a crash
                box["err"] = e

        th = threading.Thread(target=_dmr, daemon=True)
        th.start()
        time.sleep(0.8)  # fan-out is in flight, victim is mid-delay
        victim.kill()
        th.join(timeout=90.0)

        v["mr_completed"] = "out" in box
        v["mr_bit_identical"] = (
            "out" in box
            and _tree_bytes(local) == _tree_bytes(box["out"]))
        v["survivor_rescheduled"] = _counter_value(
            "cluster_fanout_recovered_total", path="survivor") > rec0

        # remote-side proof: the survivor's OWN mr_shard meter moved
        try:
            snap = cloud.client.call(surv_addr, "metrics", None, timeout=5.0)
            served = snap.get("cluster_tasks_total", 0)
            v["survivor_metered"] = served >= 2
        except _rpc.RPCError:
            v["survivor_metered"] = False

        v["membership_reconverged"] = _wait(lambda: cloud.size() == 2, 20.0)
    finally:
        for p in (victim, surv):
            if p is not None:
                try:
                    p.kill()
                except OSError:
                    pass
        if cloud is not None:
            cloud.stop()
        faults.clear_plan()
        if tmp in sys.path:
            sys.path.remove(tmp)
    return v


@scenario("kill_grid", slow=True)
def s_kill_grid(seed: int) -> Dict[str, bool]:
    """SIGKILL a grid search mid-run, then resume it from its recovery
    snapshots.  The child process builds a 4-model GLM grid with
    ``recovery_dir`` set and SIGKILLs ITSELF on entry to the third
    build — a real ``kill -9`` at a deterministic point (exactly 2
    models checkpointed).  ``auto_recover`` in this process must then
    finish exactly the remaining models from the snapshot."""
    tmp = tempfile.mkdtemp(prefix="chaos-grid-")
    rec_dir = os.path.join(tmp, "rec")
    script = os.path.join(tmp, "grid_child.py")
    with open(script, "w") as f:
        f.write(f"""
import os, signal
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.glm import GLM, GLMParameters
from h2o3_tpu.models.grid import GridSearch

rng = np.random.default_rng({seed})
n = 300
X = rng.normal(size=(n, 3))
y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(np.int32)
cols = [Column(f"x{{i}}", X[:, i]) for i in range(3)]
cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
fr = Frame(cols)

built = {{"n": 0}}


class KillGLM(GLM):
    def _fit(self, frame, valid=None):
        built["n"] += 1
        if built["n"] == 3:
            # nemesis: a REAL kill -9, mid-third-build, with exactly
            # two models checkpointed (resume re-instantiates plain
            # GLM from the snapshot's algo name, not this subclass)
            os.kill(os.getpid(), signal.SIGKILL)
        return super()._fit(frame, valid)


GridSearch(KillGLM,
           GLMParameters(response_column="y", family="binomial", seed=1),
           {{"lambda_": [0.0, 0.01, 0.1, 1.0]}},
           recovery_dir={rec_dir!r}).train(fr)
""")
    child = subprocess.Popen([sys.executable, script],
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL, env=_env(), cwd=_ROOT)
    v: Dict[str, bool] = {}
    try:
        child.wait(timeout=180.0)
        meta_path = os.path.join(rec_dir, "recovery.json")

        def _models_done() -> int:
            try:
                with open(meta_path) as f:
                    return len(json.load(f).get("models", []))
            except (OSError, ValueError):
                return 0

        v["killed_midway"] = (child.returncode == -signal.SIGKILL
                              and _models_done() == 2)

        from h2o3_tpu.recovery import auto_recover

        grid = auto_recover(rec_dir)
        v["resumed_complete"] = (grid is not None
                                 and len(grid.models) == 4)
        # on_done cleaned the snapshot up — the resume COMPLETED the
        # grid rather than leaving a half-recovered state behind
        v["snapshot_cleaned"] = not os.path.exists(meta_path)
    finally:
        try:
            child.kill()
        except OSError:
            pass
    return v


# ---------------------------------------------------------------------------
# runner


def run_scenario(name: str, seed: int) -> Dict[str, bool]:
    fn, _slow = SCENARIOS[name]
    return fn(seed)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="fast",
                    help="all | fast | " + " | ".join(SCENARIOS))
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--json", default="",
                    help="also write verdicts to this path")
    args = ap.parse_args(argv)

    if args.scenario == "all":
        names = list(SCENARIOS)
    elif args.scenario == "fast":
        names = [n for n, (_f, slow) in SCENARIOS.items() if not slow]
    elif args.scenario in SCENARIOS:
        names = [args.scenario]
    else:
        ap.error(f"unknown scenario {args.scenario!r}")

    verdicts: Dict[str, Dict[str, bool]] = {}
    ok = True
    for name in names:
        print(f"== chaos scenario {name} (seed={args.seed}) ==", flush=True)
        verdicts[name] = run_scenario(name, args.seed)
        for inv, passed in verdicts[name].items():
            print(f"   {'PASS' if passed else 'FAIL'}  {inv}", flush=True)
        ok = ok and all(verdicts[name].values())

    if args.json:
        with open(args.json, "w") as f:
            json.dump({"seed": args.seed, "verdicts": verdicts}, f, indent=2)
    print("chaos:", "ALL PASS" if ok else "FAILURES", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
