# Golden-transcript parity: the R munging surface must emit EXACTLY the
# rapids text the python client emits for the same operations
# (tests/golden/r_python_rapids_parity.json, authored from the python
# client's ExprNode emission and pinned on the python side by
# tests/test_r_client.py::TestRapidsParity).
#
# Emission is pure string composition — no server, no connection needed.
# Run: Rscript h2o3r/tests/test_munging.R   (exit 0 = all parity holds)

args <- commandArgs(trailingOnly = FALSE)
this <- sub("--file=", "", args[grepl("^--file=", args)])
root <- normalizePath(file.path(dirname(this), "..", ".."))

for (f in c("json.R", "connection.R", "rapids.R", "frame.R", "models.R"))
  source(file.path(root, "h2o3r", "R", f))

golden <- .h2o.fromJSON(paste(readLines(
  file.path(root, "tests", "golden", "r_python_rapids_parity.json"),
  warn = FALSE), collapse = "\n"))

mk <- function(key, names) {
  structure(list(key = key, ast = NULL, nrows = 100L,
                 ncols = length(names), names = names),
            class = "H2OFrame")
}
frA <- mk("frA", c("a", "b", "g"))
frB <- mk("frB", c("a", "c"))

ast <- function(x) if (inherits(x, "H2OFrame")) .h2o.ast.of(x) else x

got <- list(
  col_by_name = ast(frA$a),
  cols_by_list = ast(frA[, c("a", "b")]),
  row_slice = ast(frA[1:5, ]),
  mask_rows = ast(frA[frA$a > 6L, ]),
  arith = ast(frA$a * 2 + 1),
  rmul = ast(2 * frA$a),
  compare_and = ast((frA$a > 1) & (frA$b < 2)),
  "not" = ast(!frA$a),
  mean = .h2o.op("mean", frA$a, TRUE, 0),
  sum = .h2o.op("sum", frA$a, TRUE),
  unique = ast(h2o.unique(frA$g)),
  table = ast(h2o.table(frA$g)),
  asfactor = ast(h2o.asfactor(frA$g)),
  cbind = ast(h2o.cbind(frA, frB)),
  rbind = ast(h2o.rbind(frA, frA)),
  colnames_assign = ast(h2o.setNames(frA, c("x", "y", "z"))),
  sort = ast(h2o.arrange(frA, "a")),
  sort_desc_multi = ast(h2o.arrange(frA, "a", "b", ascending = FALSE)),
  merge = ast(h2o.merge(frA, frB)),
  merge_all_x = ast(h2o.merge(frA, frB, all.x = TRUE)),
  groupby = ast(h2o.group_by(frA, "g", sum = "a", mean = "b")),
  groupby_count = ast(h2o.group_by(frA, "g", nrow = TRUE)),
  ifelse = ast(h2o.ifelse(frA$a > 0L, 1, 0)),
  log = ast(log(frA$a)),
  perfect_auc = .h2o.op("perfectAUC", frA$a, frA$b),
  quantile = ast(h2o.quantile(frA$a, c(0.25, 0.5, 0.75))),
  impute = ast(h2o.impute(frA, 0, "median")),
  cor = ast(h2o.cor(frA[, c("a", "b")])),
  scale = ast(h2o.scale(frA[, c("a", "b")])),
  cumsum = ast(h2o.cumsum(frA$a)),
  tolower = ast(h2o.tolower(frA$g)),
  gsub = ast(h2o.gsub("x", "y", frA$g)),
  strsplit = ast(h2o.strsplit(frA$g, "-")),
  substring = ast(h2o.substring(frA$g, 1, 3)),
  nchar = ast(h2o.nchar(frA$g)),
  year = ast(h2o.year(frA$b))
)

fails <- 0L
for (name in names(golden)) {
  want <- golden[[name]]
  have <- got[[name]]
  if (is.null(have)) {
    cat("MISSING scenario:", name, "\n")
    fails <- fails + 1L
  } else if (!identical(have, want)) {
    cat("MISMATCH", name, "\n  R:      ", have, "\n  python: ", want, "\n")
    fails <- fails + 1L
  }
}
# every R scenario must also exist in the golden file (no dead entries)
extra <- setdiff(names(got), names(golden))
if (length(extra) > 0) {
  cat("scenarios absent from golden file:", paste(extra, collapse = ", "),
      "\n")
  fails <- fails + 1L
}
cat(length(golden) - fails, "of", length(golden), "parity scenarios OK\n")
quit(status = if (fails > 0) 1L else 0L)
