# Connection + raw HTTP layer.
#
# Reference: h2o-r/h2o-package/R/connection.R (h2o.init / h2o.connect)
# and communication.R (.h2o.doRawREST). The transport here is a minimal
# HTTP/1.1 client over base-R socketConnection — no RCurl/httr — which
# is all a localhost control-plane needs.

.h2o.env <- new.env(parent = emptyenv())

h2o.connect <- function(ip = "127.0.0.1", port = 54321, https = FALSE) {
  if (https) stop("h2o3r speaks plain HTTP; front TLS with a proxy")
  .h2o.env$ip <- ip
  .h2o.env$port <- as.integer(port)
  about <- .h2o.GET("/3/About")
  invisible(structure(list(ip = ip, port = port, about = about),
                      class = "H2OConnection"))
}

h2o.init <- function(ip = "127.0.0.1", port = 54321, ...) {
  # h2o-r's h2o.init launches a JVM when none is running; here the
  # server is a python process the operator owns, so init == connect
  h2o.connect(ip = ip, port = port)
}

h2o.clusterStatus <- function() .h2o.GET("/3/Cloud")

.h2o.url <- function() {
  if (is.null(.h2o.env$ip)) stop("no connection: call h2o.init() first")
  paste0(.h2o.env$ip, ":", .h2o.env$port)
}

.h2o.request <- function(method, path, body = NULL,
                         content_type = "application/json") {
  con <- socketConnection(.h2o.env$ip, .h2o.env$port, blocking = TRUE,
                          open = "r+b", timeout = 120)
  on.exit(close(con), add = TRUE)
  payload <- if (is.null(body)) raw(0) else charToRaw(body)
  head <- paste0(
    method, " ", path, " HTTP/1.1\r\n",
    "Host: ", .h2o.url(), "\r\n",
    "Content-Type: ", content_type, "\r\n",
    "Content-Length: ", length(payload), "\r\n",
    "Connection: close\r\n\r\n")
  writeBin(c(charToRaw(head), payload), con)
  flush(con)
  # status line + headers
  status_line <- readLines(con, n = 1L)
  status <- as.integer(strsplit(status_line, " ")[[1]][2])
  clen <- -1L
  repeat {
    h <- readLines(con, n = 1L)
    if (length(h) == 0 || h == "") break
    kv <- strsplit(h, ": ?")[[1]]
    if (tolower(kv[1]) == "content-length") clen <- as.integer(kv[2])
  }
  body_raw <- if (clen >= 0) readBin(con, what = "raw", n = clen) else {
    acc <- raw(0)
    repeat {
      chunk <- readBin(con, what = "raw", n = 65536L)
      if (length(chunk) == 0) break
      acc <- c(acc, chunk)
    }
    acc
  }
  list(status = status, body = rawToChar(body_raw))
}

.h2o.check <- function(resp) {
  if (resp$status >= 400) {
    msg <- tryCatch(.h2o.fromJSON(resp$body)$msg, error = function(e) resp$body)
    stop("HTTP ", resp$status, ": ", msg)
  }
  resp
}

.h2o.GET <- function(path) {
  .h2o.fromJSON(.h2o.check(.h2o.request("GET", path))$body)
}

.h2o.DELETE <- function(path) {
  .h2o.fromJSON(.h2o.check(.h2o.request("DELETE", path))$body)
}

.h2o.POST <- function(path, params = NULL) {
  body <- if (is.null(params)) "{}" else .h2o.toJSON(params)
  .h2o.fromJSON(.h2o.check(.h2o.request("POST", path, body))$body)
}

.h2o.GETraw <- function(path) {
  .h2o.check(.h2o.request("GET", path))$body
}

h2o.shutdown <- function(prompt = FALSE) {
  invisible(.h2o.POST("/3/Shutdown"))
}

h2o.logAndEcho <- function(message) {
  .h2o.POST("/3/LogAndEcho", list(message = message))$message
}
