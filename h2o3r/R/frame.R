# H2OFrame: a handle to a server-side frame.
#
# Reference: h2o-r/h2o-package/R/frame.R (~10k LoC of lazy AST builders).
# This client keeps frames as thin key handles and ships munging to the
# server as Rapids text — the same wire contract, a fraction of the
# surface; as.data.frame round-trips through the CSV download route.

.h2o.frameHandle <- function(key) {
  info <- .h2o.GET(paste0("/3/Frames/", utils::URLencode(key, reserved = TRUE),
                          "/light"))$frames[[1]]
  structure(list(key = key,
                 nrows = info$rows,
                 ncols = info$num_columns,
                 names = unlist(info$column_names)),
            class = "H2OFrame")
}

h2o.getFrame <- function(id) .h2o.frameHandle(id)

h2o.uploadFile <- function(path, destination_frame = NULL, header = TRUE) {
  text <- paste(readLines(path, warn = FALSE), collapse = "\n")
  h2o.uploadText(text, destination_frame)
}

h2o.uploadText <- function(text, destination_frame = NULL) {
  up <- .h2o.POST("/3/PostFile", list(data = text))
  dest <- if (is.null(destination_frame))
    paste0("frame_", format(as.numeric(Sys.time()) * 1000, scientific = FALSE))
  else destination_frame
  .h2o.POST("/3/Parse", list(
    source_frames = list(up$destination_frame),
    destination_frame = dest))
  .h2o.frameHandle(dest)
}

h2o.importFile <- function(path, destination_frame = NULL) {
  imp <- .h2o.POST("/3/ImportFiles", list(path = path))
  dest <- if (is.null(destination_frame))
    paste0("frame_", format(as.numeric(Sys.time()) * 1000, scientific = FALSE))
  else destination_frame
  .h2o.POST("/3/Parse", list(
    source_frames = as.list(unlist(imp$destination_frames)),
    destination_frame = dest))
  .h2o.frameHandle(dest)
}

as.data.frame.H2OFrame <- function(x, ...) {
  x <- .h2o.eval(x)  # lazy rapids frames materialize first
  csv <- .h2o.GETraw(paste0("/3/DownloadDataset?frame_id=",
                            utils::URLencode(x$key, reserved = TRUE)))
  utils::read.csv(text = csv, stringsAsFactors = FALSE)
}

print.H2OFrame <- function(x, ...) {
  if (is.null(x$key)) {
    cat("H2OFrame (lazy):", x$ast, "\n")
    return(invisible(x))
  }
  cat("H2OFrame", x$key, ":", x$nrows, "rows x", x$ncols, "cols\n")
  cat("columns:", paste(x$names, collapse = ", "), "\n")
  invisible(x)
}

dim.H2OFrame <- function(x) { x <- .h2o.eval(x); c(x$nrows, x$ncols) }

h2o.nrow <- function(fr) .h2o.eval(fr)$nrows
h2o.ncol <- function(fr) .h2o.eval(fr)$ncols
h2o.colnames <- function(fr) .h2o.names.of(fr)

h2o.ls <- function() {
  frames <- .h2o.GET("/3/Frames")$frames
  data.frame(key = vapply(frames, function(f) f$frame_id$name, character(1)),
             rows = vapply(frames, function(f) as.numeric(f$rows), numeric(1)),
             stringsAsFactors = FALSE)
}

h2o.rm <- function(x) {
  key <- if (inherits(x, "H2OFrame") || inherits(x, "H2OModel")) x$key else x
  invisible(.h2o.DELETE(paste0("/3/DKV/",
                               utils::URLencode(key, reserved = TRUE))))
}

h2o.removeAll <- function() invisible(.h2o.DELETE("/3/DKV"))

h2o.splitFrame <- function(fr, ratios = 0.75, destination_frames = NULL,
                           seed = -1) {
  fr <- .h2o.eval(fr)
  params <- list(dataset = fr$key, ratios = as.list(ratios), seed = seed)
  if (!is.null(destination_frames))
    params$destination_frames <- as.list(destination_frames)
  out <- .h2o.POST("/3/SplitFrame", params)
  lapply(out$destination_frames, function(d) .h2o.frameHandle(d$name))
}

h2o.rapids <- function(ast) .h2o.POST("/99/Rapids", list(ast = ast))

h2o.describe <- function(fr) {
  fr <- .h2o.eval(fr)
  .h2o.GET(paste0("/3/Frames/", utils::URLencode(fr$key, reserved = TRUE),
                  "/summary"))$frames[[1]]$columns
}

# h2o.group_by and the rest of the munging surface live in rapids.R
