# Lazy munging surface: R expressions compose Rapids ASTs.
#
# Reference: h2o-r/h2o-package/R/frame.R (.newExpr and the `[`/`$`/Ops
# methods that build the lazy AST client-side). The emission here is
# pinned to the PYTHON client's wire text: for every op below, the
# rendered rapids string must equal what h2o3_tpu/client/expr.py emits
# for the same operation. tests/golden/r_python_rapids_parity.json holds
# the golden transcripts; tests/test_r_client.py checks the python side
# against them (no Rscript needed) and h2o3r/tests/test_munging.R checks
# this side when an R runtime exists.

# -- value rendering (mirror of client/expr.py _to_ast) ----------------------

.h2o.rapids.quote <- function(s) {
  s <- gsub("\\\\", "\\\\\\\\", s)
  s <- gsub("\"", "\\\\\"", s)
  paste0("\"", s, "\"")
}

.h2o.rapids.num <- function(x) {
  # integers render bare ("3"), fractions as decimals ("0.75") — the
  # same strings python's repr() produces for int/float args
  format(x, scientific = FALSE, trim = TRUE, digits = 15)
}

.h2o.rapids.val <- function(x) {
  if (inherits(x, "H2OFrame")) return(.h2o.ast.of(x))
  if (is.null(x)) return("\"\"")
  if (is.logical(x) && length(x) == 1) return(if (x) "1" else "0")
  if (is.numeric(x)) {
    if (length(x) == 1) return(.h2o.rapids.num(x))
    return(paste0("[", paste(vapply(x, .h2o.rapids.num, character(1)),
                             collapse = " "), "]"))
  }
  if (is.character(x)) {
    if (length(x) == 1) return(.h2o.rapids.quote(x))
    return(paste0("[", paste(vapply(x, .h2o.rapids.quote, character(1)),
                             collapse = " "), "]"))
  }
  stop("cannot render a ", class(x)[1], " into a rapids ast")
}

.h2o.rapids.strlist <- function(xs) {
  # a character vector ALWAYS renders as a list (python list-of-str),
  # even when length 1
  paste0("[", paste(vapply(xs, .h2o.rapids.quote, character(1)),
                    collapse = " "), "]")
}

.h2o.rapids.numlist <- function(xs) {
  paste0("[", paste(vapply(xs, .h2o.rapids.num, character(1)),
                    collapse = " "), "]")
}

.h2o.ast.of <- function(fr) {
  if (!is.null(fr$ast)) fr$ast else fr$key
}

.h2o.op <- function(op, ...) {
  args <- list(...)
  rendered <- vapply(args, .h2o.rapids.val, character(1))
  paste0("(", op, paste0(" ", rendered, collapse = ""), ")")
}

# a pre-rendered fragment that .h2o.rapids.val must splice verbatim
.h2o.raw <- function(text) structure(list(ast = text), class = "H2OFrame")

# -- lazy frames -------------------------------------------------------------

.h2o.expr <- function(ast) {
  structure(list(key = NULL, ast = ast, nrows = NA_integer_,
                 ncols = NA_integer_, names = NULL),
            class = "H2OFrame")
}

.h2o.session <- function() {
  if (is.null(.h2o.env$session_id))
    .h2o.env$session_id <- .h2o.POST("/4/sessions")$session_key
  .h2o.env$session_id
}

.h2o.tmp.counter <- function() {
  n <- if (is.null(.h2o.env$tmp_n)) 0L else .h2o.env$tmp_n
  .h2o.env$tmp_n <- n + 1L
  n
}

.h2o.eval <- function(fr) {
  # materialize a lazy frame under a session temp key (the python
  # client's refresh(): (tmp= {sid}_tmp_{n} <ast>)).  R lists copy by
  # value, so the evaluated handle can't be cached on fr itself; a
  # per-session cache keyed by the AST text keeps repeated metadata
  # calls from re-executing the expression and leaking temp keys.
  if (!is.null(fr$key)) return(fr)
  if (is.null(.h2o.env$eval_cache))
    .h2o.env$eval_cache <- new.env(parent = emptyenv())
  hit <- .h2o.env$eval_cache[[fr$ast]]
  if (!is.null(hit)) return(hit)
  sid <- .h2o.session()
  tmp <- paste0(sid, "_r_tmp_", .h2o.tmp.counter())
  out <- .h2o.POST("/99/Rapids",
                   list(ast = paste0("(tmp= ", tmp, " ", fr$ast, ")"),
                        session_id = sid))
  ev <- structure(list(key = out$key$name, ast = NULL, nrows = out$num_rows,
                       ncols = out$num_cols,
                       names = unlist(lapply(out$col_names, identity))),
                  class = "H2OFrame")
  .h2o.env$eval_cache[[fr$ast]] <- ev
  ev
}

.h2o.scalar <- function(ast) {
  sid <- .h2o.session()
  out <- .h2o.POST("/99/Rapids", list(ast = ast, session_id = sid))
  if (!is.null(out$scalar)) return(out$scalar)
  if (!is.null(out$string)) return(out$string)
  .h2o.frameHandle(out$key$name)
}

.h2o.names.of <- function(fr) {
  if (!is.null(fr$names)) return(fr$names)
  .h2o.eval(fr)$names
}

.h2o.colidx <- function(fr, cols) {
  nm <- .h2o.names.of(fr)
  idx <- match(cols, nm)
  if (anyNA(idx)) stop("unknown column(s): ",
                       paste(cols[is.na(idx)], collapse = ", "))
  idx - 1L
}

# -- slicing / selection -----------------------------------------------------

"$.H2OFrame" <- function(x, name) {
  # handle fields win over columns; warn if that shadows a real column
  if (name %in% c("key", "ast", "nrows", "ncols", "names")) {
    nm <- .subset2(x, "names")
    if (!is.null(nm) && name %in% nm)
      warning("frame has a column named '", name, "' shadowed by the ",
              "handle field; use fr[, \"", name, "\"] to select it")
    return(.subset2(x, name))
  }
  .h2o.expr(.h2o.op("cols_py", x, name))
}

"[.H2OFrame" <- function(x, i, j, ...) {
  base <- x
  if (!missing(j)) {
    if (is.logical(j)) j <- which(j)
    if (is.numeric(j) && any(j < 0))
      stop("negative (exclusion) column indices are not supported; ",
           "select the columns to keep")
    if (is.character(j)) {
      sel <- if (length(j) == 1) .h2o.rapids.quote(j) else
        .h2o.rapids.strlist(j)
    } else {
      sel <- if (length(j) == 1) .h2o.rapids.num(j - 1) else
        .h2o.rapids.numlist(j - 1)
    }
    base <- .h2o.expr(paste0("(cols_py ", .h2o.ast.of(x), " ", sel, ")"))
  }
  if (missing(i)) return(base)
  if (inherits(i, "H2OFrame"))  # boolean mask frame
    return(.h2o.expr(.h2o.op("rows", base, i)))
  if (is.logical(i)) i <- which(i)
  if (any(i < 0))
    stop("negative (exclusion) row indices are not supported; ",
         "select the rows to keep")
  i <- as.integer(i)
  lo <- min(i) - 1L
  n <- length(i)
  if (identical(as.integer(i), seq.int(min(i), max(i))))  # contiguous 1-based
    return(.h2o.expr(paste0("(rows ", .h2o.ast.of(base),
                            " [", lo, ":", n, "])")))
  .h2o.expr(.h2o.op("rows", base, i - 1))
}

# -- operators (Ops group generic: + - * / ^ %% == != < <= > >= & |) ---------

Ops.H2OFrame <- function(e1, e2) {
  op <- switch(.Generic, "%%" = "%", .Generic)
  if (missing(e2)) {  # unary ! / -
    if (.Generic == "!") return(.h2o.expr(.h2o.op("not", e1)))
    if (.Generic == "-") return(.h2o.expr(.h2o.op("-", 0, e1)))
    stop("unsupported unary op ", .Generic)
  }
  .h2o.expr(.h2o.op(op, e1, e2))
}

"!.H2OFrame" <- function(x) .h2o.expr(.h2o.op("not", x))

Math.H2OFrame <- function(x, ...) {
  # log/exp/sqrt/abs/floor/ceiling/trunc/cos/sin/tan/...: rapids uses the
  # same names (prims/mathops.py)
  .h2o.expr(.h2o.op(.Generic, x))
}

# -- reducers (eager scalars, python's H2OFrame.mean/... emission) -----------

mean.H2OFrame <- function(x, na.rm = TRUE, ...)
  .h2o.scalar(.h2o.op("mean", x, na.rm, 0))

sum.H2OFrame <- function(..., na.rm = TRUE) {
  if (length(list(...)) != 1) stop("sum over one H2OFrame at a time")
  .h2o.scalar(.h2o.op("sum", ..1, na.rm))
}

min.H2OFrame <- function(..., na.rm = TRUE) {
  if (length(list(...)) != 1) stop("min over one H2OFrame at a time")
  .h2o.scalar(.h2o.op("min", ..1, na.rm))
}

max.H2OFrame <- function(..., na.rm = TRUE) {
  if (length(list(...)) != 1) stop("max over one H2OFrame at a time")
  .h2o.scalar(.h2o.op("max", ..1, na.rm))
}

h2o.sd <- function(fr) .h2o.scalar(.h2o.op("sd", fr, TRUE))
h2o.var <- function(fr) .h2o.scalar(.h2o.op("var", fr, TRUE, "everything"))
h2o.median <- function(fr, na.rm = TRUE)
  .h2o.scalar(.h2o.op("median", fr, na.rm))
h2o.nacnt <- function(fr) .h2o.scalar(.h2o.op("naCnt", fr))

# -- munging -----------------------------------------------------------------

h2o.unique <- function(fr) .h2o.expr(.h2o.op("unique", fr))
h2o.table <- function(fr) .h2o.expr(.h2o.op("table", fr, FALSE))
h2o.asfactor <- function(fr) .h2o.expr(.h2o.op("as.factor", fr))
h2o.asnumeric <- function(fr) .h2o.expr(.h2o.op("as.numeric", fr))
h2o.ascharacter <- function(fr) .h2o.expr(.h2o.op("as.character", fr))
h2o.cbind <- function(a, b) .h2o.expr(.h2o.op("cbind", a, b))
h2o.rbind <- function(a, b) .h2o.expr(.h2o.op("rbind", a, b))
h2o.ifelse <- function(test, yes, no)
  .h2o.expr(.h2o.op("ifelse", test, yes, no))

h2o.setNames <- function(fr, names) {
  .h2o.expr(paste0("(colnames= ", .h2o.ast.of(fr), " ",
                   .h2o.rapids.numlist(seq_along(names) - 1), " ",
                   .h2o.rapids.strlist(names), ")"))
}

h2o.arrange <- function(fr, ..., ascending = TRUE) {
  cols <- c(...)
  idxs <- .h2o.colidx(fr, cols)
  flags <- rep(if (ascending) 1 else 0, length(idxs))
  .h2o.expr(paste0("(sort ", .h2o.ast.of(fr), " ",
                   .h2o.rapids.numlist(idxs), " ",
                   .h2o.rapids.numlist(flags), ")"))
}

h2o.merge <- function(x, y, all.x = FALSE, all.y = FALSE) {
  .h2o.expr(paste0("(merge ", .h2o.ast.of(x), " ", .h2o.ast.of(y), " ",
                   if (all.x) "1" else "0", " ", if (all.y) "1" else "0",
                   " [] [] \"auto\")"))
}

h2o.group_by <- function(fr, by, nrow = NULL, sum = NULL, mean = NULL,
                         min = NULL, max = NULL, sd = NULL, var = NULL,
                         median = NULL, mode = NULL, na = "all") {
  # (GB fr [by-idxs] agg colidx na ...) — AstGroup's multi-agg form,
  # the exact emission of the python client's fluent H2OGroupBy
  aggs <- character(0)
  if (!is.null(nrow))
    aggs <- c(aggs, paste0("\"nrow\" ", .h2o.colidx(fr, by[1]),
                           " ", .h2o.rapids.quote(na)))
  for (agg in c("sum", "mean", "min", "max", "sd", "var", "median",
                "mode")) {
    cols <- get(agg)
    if (is.null(cols)) next
    for (ci in .h2o.colidx(fr, cols))
      aggs <- c(aggs, paste0(.h2o.rapids.quote(agg), " ", ci, " ",
                             .h2o.rapids.quote(na)))
  }
  if (length(aggs) == 0) stop("add at least one aggregation")
  .h2o.expr(paste0("(GB ", .h2o.ast.of(fr), " ",
                   .h2o.rapids.numlist(.h2o.colidx(fr, by)), " ",
                   paste(aggs, collapse = " "), ")"))
}

h2o.perfAUC <- function(probs, acts)
  .h2o.scalar(.h2o.op("flatten", .h2o.raw(.h2o.op("perfectAUC", probs,
                                                  acts))))

h2o.reset_threshold <- function(model, threshold) {
  key <- if (inherits(model, "H2OModel")) model$key else model
  .h2o.scalar(.h2o.op("flatten",
                      .h2o.raw(paste0("(model.reset.threshold ", key, " ",
                                      .h2o.rapids.num(threshold), ")"))))
}

h2o.permutation_importance <- function(model, fr, metric = "AUTO",
                                       n_samples = 10000, n_repeats = 1,
                                       features = NULL, seed = -1) {
  key <- if (inherits(model, "H2OModel")) model$key else model
  feats <- if (is.null(features)) "\"\"" else .h2o.rapids.strlist(features)
  .h2o.eval(.h2o.expr(paste0(
    "(PermutationVarImp ", key, " ", .h2o.ast.of(fr), " ",
    .h2o.rapids.quote(metric), " ", .h2o.rapids.num(n_samples), " ",
    .h2o.rapids.num(n_repeats), " ", feats, " ",
    .h2o.rapids.num(seed), ")")))
}

# -- round-5 widening: quantiles, imputation, correlation, strings, time ----

h2o.quantile <- function(fr, probs = c(0.001, 0.01, 0.1, 0.25, 0.333, 0.5,
                                       0.667, 0.75, 0.9, 0.99, 0.999),
                         combine_method = "interpolate") {
  .h2o.expr(paste0("(quantile ", .h2o.ast.of(fr), " ",
                   .h2o.rapids.numlist(probs), " ",
                   .h2o.rapids.quote(combine_method), ")"))
}

h2o.impute <- function(fr, column = -1, method = "mean",
                       combine_method = "interpolate", by = NULL) {
  byl <- if (is.null(by)) "[]" else .h2o.rapids.numlist(by)
  .h2o.expr(paste0("(h2o.impute ", .h2o.ast.of(fr), " ",
                   .h2o.rapids.num(column), " ",
                   .h2o.rapids.quote(method), " ",
                   .h2o.rapids.quote(combine_method), " ", byl, ")"))
}

h2o.cor <- function(x, y = NULL, use = "everything", method = "Pearson") {
  .h2o.expr(.h2o.op("cor", x, if (is.null(y)) x else y, use, method))
}

h2o.scale <- function(fr, center = TRUE, scale = TRUE)
  .h2o.expr(.h2o.op("scale", fr, center, scale))

h2o.cumsum <- function(fr, axis = 0) .h2o.expr(.h2o.op("cumsum", fr, axis))
h2o.cumprod <- function(fr, axis = 0) .h2o.expr(.h2o.op("cumprod", fr, axis))
h2o.tolower <- function(fr) .h2o.expr(.h2o.op("tolower", fr))
h2o.toupper <- function(fr) .h2o.expr(.h2o.op("toupper", fr))
h2o.trim <- function(fr) .h2o.expr(.h2o.op("trim", fr))
h2o.gsub <- function(pattern, replacement, fr, ignore.case = FALSE)
  .h2o.expr(.h2o.op("replaceall", fr, pattern, replacement, ignore.case))
h2o.strsplit <- function(fr, split) .h2o.expr(.h2o.op("strsplit", fr, split))
h2o.substring <- function(fr, start, end = -1)
  .h2o.expr(.h2o.op("substring", fr, start, end))
h2o.nchar <- function(fr) .h2o.expr(.h2o.op("length", fr))
h2o.year <- function(fr) .h2o.expr(.h2o.op("year", fr))
h2o.month <- function(fr) .h2o.expr(.h2o.op("month", fr))
h2o.day <- function(fr) .h2o.expr(.h2o.op("day", fr))
h2o.hour <- function(fr) .h2o.expr(.h2o.op("hour", fr))
