# Minimal JSON codec in base R — no jsonlite dependency.
#
# Reference: h2o-r leans on jsonlite/RCurl (h2o-r/h2o-package/R/
# communication.R); this package stays dependency-free so it loads on a
# bare R, which is also why the server keeps its responses to plain
# objects/arrays/scalars.

.h2o.toJSON <- function(x) {
  if (is.null(x)) return("null")
  if (is.list(x) && !is.null(names(x)) && length(x) > 0) {
    parts <- vapply(seq_along(x), function(i) {
      paste0(.h2o.jsonString(names(x)[i]), ":", .h2o.toJSON(x[[i]]))
    }, character(1))
    return(paste0("{", paste(parts, collapse = ","), "}"))
  }
  if (is.list(x) || length(x) > 1) {
    return(paste0("[", paste(vapply(as.list(x), .h2o.toJSON, character(1)),
                             collapse = ","), "]"))
  }
  if (length(x) == 0) return("[]")
  if (is.character(x)) return(.h2o.jsonString(x))
  if (is.logical(x)) return(if (is.na(x)) "null" else if (x) "true" else "false")
  if (is.na(x)) return("null")
  if (is.numeric(x)) {
    if (is.infinite(x) || is.nan(x)) return("null")
    return(format(x, scientific = FALSE, digits = 17, trim = TRUE))
  }
  stop("cannot serialize type: ", class(x)[1])
}

.h2o.jsonString <- function(s) {
  s <- gsub("\\\\", "\\\\\\\\", s)
  s <- gsub('"', '\\\\"', s)
  s <- gsub("\n", "\\\\n", s)
  s <- gsub("\r", "\\\\r", s)
  s <- gsub("\t", "\\\\t", s)
  paste0('"', s, '"')
}

# -- parser ------------------------------------------------------------------

.h2o.fromJSON <- function(txt) {
  st <- new.env(parent = emptyenv())
  st$s <- txt
  st$i <- 1L
  st$n <- nchar(txt)
  v <- .h2o.jsParseValue(st)
  v
}

.h2o.jsPeek <- function(st) substr(st$s, st$i, st$i)

.h2o.jsSkipWs <- function(st) {
  while (st$i <= st$n && .h2o.jsPeek(st) %in% c(" ", "\n", "\t", "\r"))
    st$i <- st$i + 1L
}

.h2o.jsParseValue <- function(st) {
  .h2o.jsSkipWs(st)
  ch <- .h2o.jsPeek(st)
  if (ch == "{") return(.h2o.jsParseObject(st))
  if (ch == "[") return(.h2o.jsParseArray(st))
  if (ch == '"') return(.h2o.jsParseString(st))
  rest <- substr(st$s, st$i, min(st$n, st$i + 4L))
  if (startsWith(rest, "true"))  { st$i <- st$i + 4L; return(TRUE) }
  if (startsWith(rest, "false")) { st$i <- st$i + 5L; return(FALSE) }
  if (startsWith(rest, "null"))  { st$i <- st$i + 4L; return(NULL) }
  .h2o.jsParseNumber(st)
}

.h2o.jsParseObject <- function(st) {
  st$i <- st$i + 1L  # {
  out <- list()
  .h2o.jsSkipWs(st)
  if (.h2o.jsPeek(st) == "}") { st$i <- st$i + 1L; return(out) }
  repeat {
    .h2o.jsSkipWs(st)
    key <- .h2o.jsParseString(st)
    .h2o.jsSkipWs(st)
    if (.h2o.jsPeek(st) != ":") stop("JSON: expected ':' at ", st$i)
    st$i <- st$i + 1L
    val <- .h2o.jsParseValue(st)
    out[[key]] <- if (is.null(val)) NA else val
    .h2o.jsSkipWs(st)
    ch <- .h2o.jsPeek(st)
    st$i <- st$i + 1L
    if (ch == "}") return(out)
    if (ch != ",") stop("JSON: expected ',' or '}' at ", st$i)
  }
}

.h2o.jsParseArray <- function(st) {
  st$i <- st$i + 1L  # [
  out <- list()
  .h2o.jsSkipWs(st)
  if (.h2o.jsPeek(st) == "]") { st$i <- st$i + 1L; return(out) }
  repeat {
    val <- .h2o.jsParseValue(st)
    out[[length(out) + 1L]] <- if (is.null(val)) NA else val
    .h2o.jsSkipWs(st)
    ch <- .h2o.jsPeek(st)
    st$i <- st$i + 1L
    if (ch == "]") return(out)
    if (ch != ",") stop("JSON: expected ',' or ']' at ", st$i)
  }
}

.h2o.jsParseString <- function(st) {
  if (.h2o.jsPeek(st) != '"') stop("JSON: expected string at ", st$i)
  st$i <- st$i + 1L
  out <- character(0)
  buf_start <- st$i
  while (st$i <= st$n) {
    ch <- .h2o.jsPeek(st)
    if (ch == '"') {
      out <- c(out, substr(st$s, buf_start, st$i - 1L))
      st$i <- st$i + 1L
      return(paste0(out, collapse = ""))
    }
    if (ch == "\\") {
      out <- c(out, substr(st$s, buf_start, st$i - 1L))
      esc <- substr(st$s, st$i + 1L, st$i + 1L)
      rep <- switch(esc, n = "\n", t = "\t", r = "\r", b = "\b", f = "\f",
                    "u" = NA, esc)
      if (identical(rep, NA)) {
        code <- strtoi(substr(st$s, st$i + 2L, st$i + 5L), 16L)
        rep <- intToUtf8(code)
        st$i <- st$i + 6L
      } else {
        st$i <- st$i + 2L
      }
      out <- c(out, rep)
      buf_start <- st$i
    } else {
      st$i <- st$i + 1L
    }
  }
  stop("JSON: unterminated string")
}

.h2o.jsParseNumber <- function(st) {
  j <- st$i
  while (j <= st$n && substr(st$s, j, j) %in%
         c("-", "+", ".", "e", "E", as.character(0:9)))
    j <- j + 1L
  num <- as.numeric(substr(st$s, st$i, j - 1L))
  st$i <- j
  num
}
