# Model training / scoring / metrics.
#
# Reference: h2o-r/h2o-package/R/models.R (.h2o.modelJob / h2o.performance /
# h2o.predict and the metric accessors). Estimator wrappers (h2o.gbm,
# h2o.glm, ...) are GENERATED into estimators_gen.R from the server's
# parameter schemas by scripts/gen_bindings.py --r.

.h2o.train <- function(algo, params) {
  params <- Filter(function(v) !is.null(v), params)
  # frames travel as keys
  for (k in c("training_frame", "validation_frame")) {
    if (!is.null(params[[k]]) && inherits(params[[k]], "H2OFrame"))
      params[[k]] <- .h2o.eval(params[[k]])$key
  }
  out <- .h2o.POST(paste0("/3/ModelBuilders/", algo), params)
  key <- out$models[[1]]$model_id$name
  h2o.getModel(key)
}

h2o.getModel <- function(model_id) {
  out <- .h2o.GET(paste0("/3/Models/",
                         utils::URLencode(model_id, reserved = TRUE)))
  m <- out$models[[1]]
  structure(list(key = model_id, algo = m$algo,
                 parameters = m$parameters, output = m$output),
            class = "H2OModel")
}

print.H2OModel <- function(x, ...) {
  cat("H2OModel", x$key, "(", x$algo, ")\n")
  tm <- x$output$training_metrics
  if (!is.null(tm) && !identical(tm, NA)) {
    for (k in names(tm)) cat(" ", k, "=", format(tm[[k]]), "\n")
  }
  invisible(x)
}

h2o.predict <- function(object, newdata, predictions_frame = NULL) {
  params <- list()
  if (!is.null(predictions_frame)) params$predictions_frame <- predictions_frame
  out <- .h2o.POST(paste0(
    "/3/Predictions/models/", utils::URLencode(object$key, reserved = TRUE),
    "/frames/", utils::URLencode(.h2o.eval(newdata)$key,
                                 reserved = TRUE)), params)
  .h2o.frameHandle(out$model_metrics[[1]]$predictions_frame$name)
}

h2o.performance <- function(model, newdata = NULL) {
  if (is.null(newdata)) return(model$output$training_metrics)
  out <- .h2o.POST(paste0(
    "/3/ModelMetrics/models/", utils::URLencode(model$key, reserved = TRUE),
    "/frames/", utils::URLencode(newdata$key, reserved = TRUE)),
    list(force = TRUE))
  out$model_metrics[[1]]
}

h2o.make_metrics <- function(predicted, actuals, domain = NULL,
                             distribution = "gaussian") {
  params <- list(distribution = distribution)
  if (!is.null(domain)) params$domain <- as.list(domain)
  out <- .h2o.POST(paste0(
    "/3/ModelMetrics/predictions_frame/",
    utils::URLencode(predicted$key, reserved = TRUE),
    "/actuals_frame/", utils::URLencode(actuals$key, reserved = TRUE)),
    params)
  out$model_metrics[[1]]
}

.h2o.metric <- function(mm, name) {
  if (inherits(mm, "H2OModel")) mm <- mm$output$training_metrics
  v <- mm[[name]]
  if (is.null(v)) NA_real_ else as.numeric(v)
}

h2o.auc     <- function(mm) .h2o.metric(mm, "auc")
h2o.aucpr   <- function(mm) .h2o.metric(mm, "pr_auc")
h2o.logloss <- function(mm) .h2o.metric(mm, "logloss")
h2o.rmse    <- function(mm) .h2o.metric(mm, "rmse")
h2o.mse     <- function(mm) .h2o.metric(mm, "mse")
h2o.mae     <- function(mm) .h2o.metric(mm, "mae")
h2o.r2      <- function(mm) .h2o.metric(mm, "r2")
h2o.giniCoef <- function(mm) .h2o.metric(mm, "gini")
h2o.mean_per_class_error <- function(mm) .h2o.metric(mm, "mean_per_class_error")

h2o.varimp <- function(model) {
  out <- .h2o.GET(paste0("/3/Models/",
                         utils::URLencode(model$key, reserved = TRUE),
                         "/varimp"))
  out$varimp
}

h2o.saveModel <- function(object, path, force = TRUE) {
  out <- .h2o.GET(paste0("/99/Models.bin/",
                         utils::URLencode(object$key, reserved = TRUE),
                         "?dir=", utils::URLencode(path, reserved = TRUE),
                         "&force=", tolower(as.character(force))))
  out$dir
}

h2o.loadModel <- function(path, model_id = NULL) {
  id <- if (is.null(model_id))
    paste0("model_", format(as.numeric(Sys.time()) * 1000,
                            scientific = FALSE))
  else model_id
  out <- .h2o.POST(paste0("/99/Models.bin/",
                          utils::URLencode(id, reserved = TRUE),
                          "?dir=", utils::URLencode(path, reserved = TRUE)))
  h2o.getModel(out$models[[1]]$model_id$name)
}

h2o.listModels <- function() {
  models <- .h2o.GET("/3/Models")$models
  vapply(models, function(m) m$model_id$name, character(1))
}

h2o.getGrid <- function(grid_id) {
  .h2o.GET(paste0("/99/Grids/", utils::URLencode(grid_id, reserved = TRUE)))
}

h2o.grid <- function(algo, hyper_params, grid_id = NULL, ...) {
  params <- list(...)
  for (k in c("training_frame", "validation_frame")) {
    if (!is.null(params[[k]]) && inherits(params[[k]], "H2OFrame"))
      params[[k]] <- .h2o.eval(params[[k]])$key
  }
  params$hyper_parameters <- hyper_params
  if (!is.null(grid_id)) params$grid_id <- grid_id
  .h2o.POST(paste0("/99/Grid/", algo), params)
}

h2o.automl <- function(training_frame, y, max_models = NULL,
                       max_runtime_secs = NULL, ...) {
  params <- list(...)
  params$training_frame <- if (inherits(training_frame, "H2OFrame"))
    training_frame$key else training_frame
  params$response_column <- y
  if (!is.null(max_models)) params$max_models <- max_models
  if (!is.null(max_runtime_secs)) params$max_runtime_secs <- max_runtime_secs
  .h2o.POST("/99/AutoMLBuilder", params)
}
