// Columnar chunk codecs — compressed column storage + spill support.
//
// Reference: the ~23 Chunk codecs in water/fvec/C*.java chosen by
// NewChunk.close() (Chunk.java:35-43): constants (C0DChunk/C0LChunk),
// biased small ints (C1/C2/C4Chunk), scaled decimals (C1S/C2S/C4SChunk),
// floats (C4F/C8DChunk), sparse (CXI/CXFChunk), and the Cleaner's
// user-mode swap of cold chunks (water/Cleaner.java:10-12).
//
// The TPU build stores host-canonical columns as float64; this codec picks
// the cheapest lossless encoding per chunk:
//   0 RAW64    raw little-endian doubles (fallback)
//   1 CONST    one double (+NA bitmap if mixed)
//   2 INT8/3 INT16/4 INT32: bias + small ints, NA = sentinel min
//   5 SCALED16 decimal: bias + scale, int16 mantissa (C2SChunk analogue)
//   6 SPARSE   nonzero (idx,value) pairs (CXFChunk analogue)
// Encoded layout: [u8 tag][i64 n][payload]. All lossless: decode == input
// bit-for-bit on the values (NaN canonicalized to one quiet NaN pattern).

#include <cmath>
#include <cstdint>
#include <cstring>

static const double kNaN = __builtin_nan("");

extern "C" {
// Worst-case encoded size for n doubles (RAW64 + header).
int64_t h2o3_codec_bound(int64_t n) { return 9 + n * 8 + 16; }
}

namespace {

static inline bool is_na(double v) { return std::isnan(v); }

struct Stats {
  bool any_na = false, all_na = true, all_int = true, constant = true;
  double first = kNaN;
  double minv = INFINITY, maxv = -INFINITY;
  int64_t nonzero = 0;
  bool scaled16_ok = true;  // value*100 fits int16 after bias
};

static Stats scan(const double* x, int64_t n) {
  Stats s;
  bool seen = false;
  for (int64_t i = 0; i < n; ++i) {
    double v = x[i];
    if (is_na(v)) {
      s.any_na = true;
      continue;
    }
    s.all_na = false;
    if (!seen) {
      s.first = v;
      seen = true;
    } else if (v != s.first) {
      s.constant = false;
    }
    if (v != 0.0) ++s.nonzero;
    if (v < s.minv) s.minv = v;
    if (v > s.maxv) s.maxv = v;
    if (s.all_int && (v != std::floor(v) || std::fabs(v) > 9.2e18))
      s.all_int = false;
    if (s.scaled16_ok) {
      double c = v * 100.0;
      double r = std::nearbyint(c);
      if (std::fabs(c - r) > 1e-9 || std::fabs(c) > 3.2e6) s.scaled16_ok = false;
    }
  }
  return s;
}

template <typename T>
static int64_t enc_int(const double* x, int64_t n, double bias, uint8_t tag,
                       uint8_t* out) {
  out[0] = tag;
  memcpy(out + 1, &n, 8);
  memcpy(out + 9, &bias, 8);
  T* p = (T*)(out + 17);
  const T sentinel = (T)((T)1 << (sizeof(T) * 8 - 1));  // min value = NA
  for (int64_t i = 0; i < n; ++i)
    p[i] = is_na(x[i]) ? sentinel : (T)(int64_t)(x[i] - bias);
  return 17 + n * (int64_t)sizeof(T);
}

template <typename T>
static void dec_int(const uint8_t* in, double* out) {
  int64_t n;
  double bias;
  memcpy(&n, in + 1, 8);
  memcpy(&bias, in + 9, 8);
  const T* p = (const T*)(in + 17);
  const T sentinel = (T)((T)1 << (sizeof(T) * 8 - 1));
  for (int64_t i = 0; i < n; ++i)
    out[i] = (p[i] == sentinel) ? kNaN : bias + (double)p[i];
}

}  // namespace

extern "C" {

// Encode n doubles; returns encoded byte length.
int64_t h2o3_codec_encode(const double* x, int64_t n, uint8_t* out) {
  Stats s = scan(x, n);

  if (s.all_na || (s.constant && !s.any_na)) {  // CONST (C0DChunk)
    out[0] = 1;
    memcpy(out + 1, &n, 8);
    double v = s.all_na ? kNaN : s.first;
    memcpy(out + 9, &v, 8);
    return 17;
  }

  if (s.all_int && !s.all_na) {  // biased ints (C1/C2/C4Chunk)
    double span = s.maxv - s.minv;
    if (span <= 254.0)
      return enc_int<int8_t>(x, n, s.minv + 127.0, 2, out);
    if (span <= 65534.0)
      return enc_int<int16_t>(x, n, s.minv + 32767.0, 3, out);
    if (span <= 4294967294.0)
      return enc_int<int32_t>(x, n, s.minv + 2147483647.0, 4, out);
  }

  if (s.scaled16_ok && !s.all_na) {  // SCALED16 (C2SChunk: mantissa*10^-2)
    double bias = std::nearbyint(((s.minv + s.maxv) / 2) * 100.0);
    bool fits = true;
    for (int64_t i = 0; i < n && fits; ++i)
      if (!is_na(x[i])) {
        double c = std::nearbyint(x[i] * 100.0) - bias;
        if (c < -32767.0 || c > 32767.0) fits = false;
        // exact round-trip required: the scan's epsilon test admits values
        // like 0.1+0.2 whose decode would differ in the last ulp — lossless
        // means decode == input bit-for-bit, so re-verify exactly
        else if ((bias + c) / 100.0 != x[i]) fits = false;
      }
    if (fits) {
      out[0] = 5;
      memcpy(out + 1, &n, 8);
      memcpy(out + 9, &bias, 8);
      int16_t* p = (int16_t*)(out + 17);
      for (int64_t i = 0; i < n; ++i)
        p[i] = is_na(x[i])
                   ? (int16_t)-32768
                   : (int16_t)(std::nearbyint(x[i] * 100.0) - bias);
      return 17 + n * 2;
    }
  }

  if (!s.any_na && s.nonzero * 12 + 25 < n * 8) {  // SPARSE (CXFChunk)
    out[0] = 6;
    memcpy(out + 1, &n, 8);
    memcpy(out + 9, &s.nonzero, 8);
    uint8_t* p = out + 17;
    for (int64_t i = 0; i < n; ++i)
      if (x[i] != 0.0) {
        int32_t ii = (int32_t)i;
        memcpy(p, &ii, 4);
        memcpy(p + 4, &x[i], 8);
        p += 12;
      }
    return (int64_t)(p - out);
  }

  out[0] = 0;  // RAW64 (C8DChunk)
  memcpy(out + 1, &n, 8);
  memcpy(out + 9, x, (size_t)n * 8);
  return 9 + n * 8;
}

// Decode into out (length from header). Returns n, or -1 on bad tag.
int64_t h2o3_codec_decode(const uint8_t* in, double* out) {
  int64_t n;
  memcpy(&n, in + 1, 8);
  switch (in[0]) {
    case 0:
      memcpy(out, in + 9, (size_t)n * 8);
      return n;
    case 1: {
      double v;
      memcpy(&v, in + 9, 8);
      for (int64_t i = 0; i < n; ++i) out[i] = v;
      return n;
    }
    case 2: dec_int<int8_t>(in, out); return n;
    case 3: dec_int<int16_t>(in, out); return n;
    case 4: dec_int<int32_t>(in, out); return n;
    case 5: {
      double bias;
      memcpy(&bias, in + 9, 8);
      const int16_t* p = (const int16_t*)(in + 17);
      for (int64_t i = 0; i < n; ++i)
        out[i] = (p[i] == -32768) ? kNaN : (bias + p[i]) / 100.0;
      return n;
    }
    case 6: {
      int64_t nz;
      memcpy(&nz, in + 9, 8);
      memset(out, 0, (size_t)n * 8);
      const uint8_t* p = in + 17;
      for (int64_t k = 0; k < nz; ++k) {
        int32_t i;
        double v;
        memcpy(&i, p, 4);
        memcpy(&v, p + 4, 8);
        out[i] = v;
        p += 12;
      }
      return n;
    }
    default:
      return -1;
  }
}

// LSD radix argsort of uint64 keys (order-transformed by caller for
// signed/float ordering). Powers rapids sort/merge
// (water/rapids/RadixOrder.java:20 — MSB radix there; LSD here, same O(n)).
void h2o3_radix_argsort_u64(const uint64_t* keys, int64_t n, int64_t* order) {
  int64_t* cur = order;
  int64_t* tmp = new int64_t[n];
  for (int64_t i = 0; i < n; ++i) cur[i] = i;
  int64_t count[256];
  for (int pass = 0; pass < 8; ++pass) {
    int shift = pass * 8;
    memset(count, 0, sizeof(count));
    for (int64_t i = 0; i < n; ++i)
      ++count[(keys[cur[i]] >> shift) & 0xff];
    if (count[0] == n) continue;  // all zero in this byte: skip pass
    int64_t off[256], acc = 0;
    for (int b = 0; b < 256; ++b) {
      off[b] = acc;
      acc += count[b];
    }
    for (int64_t i = 0; i < n; ++i)
      tmp[off[(keys[cur[i]] >> shift) & 0xff]++] = cur[i];
    int64_t* t = cur;
    cur = tmp;
    tmp = t;
  }
  if (cur != order) memcpy(order, cur, (size_t)n * 8);
  delete[] (cur == order ? tmp : cur);
}

}  // extern "C"
