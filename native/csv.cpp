// Fast CSV tokenizer / numeric parser — the data-loader hot path.
//
// Reference: water/parser/CsvParser.java (byte->token->NewChunk append per
// row, driven chunk-parallel by MultiFileParseTask, ParseDataset.java:623).
// The TPU build keeps type guessing in Python (sampled, cheap) and moves the
// bulk byte scanning here: one pass over the buffer, branch-light float
// parsing, NA -> quiet NaN.  Rows are split across threads on newline
// boundaries (the chunk-parallel structure of the reference's parse).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// Count logical rows (newlines, ignoring a trailing unterminated line's
// absence) so Python can preallocate.
int64_t h2o3_count_rows(const char* buf, int64_t len) {
  int64_t n = 0;
  for (int64_t i = 0; i < len; ++i)
    if (buf[i] == '\n') ++n;
  if (len > 0 && buf[len - 1] != '\n') ++n;
  return n;
}

namespace {

// strtod-free fast path for plain decimal numbers; falls back to strtod for
// exponents/specials. Returns NaN for non-numeric tokens.
//
// Bit-exactness contract: for any token this function resolves without
// strtod, the result must equal Python's float(token) exactly. At <= 15
// total digits ip*scale+fp is an exact int64 below 2^53, so the single
// division is the one correctly-rounded step — identical to CPython's
// correctly-rounded decimal->binary conversion. (The old ip + fp/scale
// form rounded twice and could drift 1 ulp at 16-18 digits.)
static inline double parse_token(const char* s, const char* e) {
  while (s < e && (*s == ' ' || *s == '\t')) ++s;
  while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r')) --e;
  if (s == e) return NAN;
  bool neg = false;
  const char* p = s;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') ++p;
  int64_t ip = 0;
  int digits = 0;
  while (p < e && *p >= '0' && *p <= '9' && digits < 15) {
    ip = ip * 10 + (*p - '0');
    ++p; ++digits;
  }
  if (p < e && *p == '.') {
    ++p;
    int64_t fp = 0, scale = 1;
    while (p < e && *p >= '0' && *p <= '9' && digits < 15) {
      fp = fp * 10 + (*p - '0');
      scale *= 10;
      ++p; ++digits;
    }
    if (p == e && digits > 0) {
      double v = (double)(ip * scale + fp) / (double)scale;
      return neg ? -v : v;
    }
  } else if (p == e && digits > 0) {
    double v = (double)ip;
    return neg ? -v : v;
  }
  // strtod accepts hex floats (0x1A) that python's float() rejects; any
  // token containing x/X is junk (NaN) on the python path, so match that
  for (const char* q = s; q < e; ++q)
    if (*q == 'x' || *q == 'X') return NAN;
  // exponent / >15 digits / inf / nan / junk: defer to strtod
  char tmp[64];
  size_t n = (size_t)(e - s);
  if (n >= sizeof(tmp)) return NAN;
  memcpy(tmp, s, n);
  tmp[n] = 0;
  char* endp = nullptr;
  double v = strtod(tmp, &endp);
  if (endp == tmp || (endp && *endp != 0)) return NAN;
  return v;
}

struct Shard {
  const char* buf;
  int64_t begin, end;       // byte range, begin at a row start
  int64_t row0;             // first row index in this shard
  double* out;              // [nrows, ncols] row-major
  int32_t ncols;
  char sep;
};

static void parse_shard(const Shard sh) {
  const char* p = sh.buf + sh.begin;
  const char* lim = sh.buf + sh.end;
  int64_t row = sh.row0;
  while (p < lim) {
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(lim - p));
    if (!line_end) line_end = lim;
    double* dst = sh.out + row * sh.ncols;
    const char* tok = p;
    int32_t col = 0;
    for (const char* q = p; q <= line_end && col < sh.ncols; ++q) {
      if (q == line_end || *q == sh.sep) {
        dst[col++] = parse_token(tok, q);
        tok = q + 1;
      }
    }
    while (col < sh.ncols) dst[col++] = NAN;  // short row: missing -> NA
    ++row;
    p = line_end + 1;
  }
}

}  // namespace

// Parse `nrows` x `ncols` numerics from buf into out (row-major doubles).
// start: byte offset of the first data row (header skipped by caller).
// Returns rows parsed. Threads split on newline boundaries.
int64_t h2o3_parse_numeric_csv(const char* buf, int64_t len, int64_t start,
                               char sep, int32_t ncols, double* out,
                               int64_t nrows, int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  // find shard boundaries: nthreads byte-ranges snapped to line starts
  std::vector<int64_t> begins;
  begins.push_back(start);
  for (int t = 1; t < nthreads; ++t) {
    int64_t target = start + (len - start) * t / nthreads;
    const char* nl =
        (const char*)memchr(buf + target, '\n', (size_t)(len - target));
    int64_t b = nl ? (nl - buf) + 1 : len;
    if (b > begins.back()) begins.push_back(b);
  }
  begins.push_back(len);

  // row offsets per shard (prefix newline counts)
  std::vector<int64_t> row0(begins.size() - 1, 0);
  {
    int64_t acc = 0;
    for (size_t s = 0; s + 1 < begins.size(); ++s) {
      row0[s] = acc;
      const char* b = buf + begins[s];
      const char* e = buf + begins[s + 1];
      int64_t cnt = 0;
      for (const char* q = b; q < e; ++q)
        if (*q == '\n') ++cnt;
      if (s + 2 == begins.size() && e > b && e[-1] != '\n') ++cnt;
      acc += cnt;
    }
    if (acc > nrows) return -1;  // caller's preallocation too small
  }

  std::vector<std::thread> threads;
  for (size_t s = 0; s + 1 < begins.size(); ++s) {
    Shard sh{buf, begins[s], begins[s + 1], row0[s], out, ncols, sep};
    threads.emplace_back(parse_shard, sh);
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  {
    const char* b = buf + start;
    const char* e = buf + len;
    for (const char* q = b; q < e; ++q)
      if (*q == '\n') ++total;
    if (e > b && e[-1] != '\n') ++total;
  }
  return total;
}

// ---------------------------------------------------------------------------
// Chunk-parallel two-phase parse primitives (ParseDataset.java:623 chunk
// tokenization, driven from Python's ThreadPoolExecutor).  Each call is a
// GIL-released ctypes invocation over one newline-aligned body chunk, so N
// Python worker threads tokenize N chunks genuinely concurrently.  The
// caller guarantees (frame/parse.py eligibility gate): no quote bytes, no
// lone '\r', ASCII-only, single-byte separator.

// Tokenize a body chunk into a [rows, ncols] cell grid of byte offsets
// (start/end per cell, whitespace-stripped; missing trailing cells become
// the empty range; extra cells beyond ncols are ignored, matching the
// python tokenizer).  Whitespace-only records are skipped when skip_blanks.
// Returns rows written, or -1 if cap_rows would overflow.
int64_t h2o3_csv_index_chunk(const char* buf, int64_t len, char sep,
                             int32_t ncols, int32_t skip_blanks,
                             int32_t* starts, int32_t* ends,
                             int64_t cap_rows) {
  int64_t row = 0;
  const char* p = buf;
  const char* lim = buf + len;
  while (p < lim) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(lim - p));
    const char* le = nl ? nl : lim;
    const char* re = le;
    if (re > p && re[-1] == '\r') --re;  // CRLF terminator
    if (skip_blanks) {
      const char* q = p;
      while (q < re && (*q == ' ' || *q == '\t')) ++q;
      if (q == re) { p = nl ? nl + 1 : lim; continue; }
    }
    if (row >= cap_rows) return -1;
    int32_t* rs = starts + row * ncols;
    int32_t* rr = ends + row * ncols;
    int32_t col = 0;
    const char* tok = p;
    for (const char* q = p; q <= re && col < ncols; ++q) {
      if (q == re || *q == sep) {
        const char* a = tok;
        const char* b = q;
        while (a < b && (*a == ' ' || *a == '\t')) ++a;
        while (b > a && (b[-1] == ' ' || b[-1] == '\t')) --b;
        rs[col] = (int32_t)(a - buf);
        rr[col] = (int32_t)(b - buf);
        ++col;
        tok = q + 1;
      }
    }
    for (; col < ncols; ++col) { rs[col] = 0; rr[col] = 0; }
    ++row;
    p = nl ? nl + 1 : lim;
  }
  return row;
}

// Parse one column's cells (by index grid offsets) into float64; NA/junk
// tokens become quiet NaN, same as the python builder's float() fallback.
void h2o3_parse_cells_f64(const char* buf, const int32_t* starts,
                          const int32_t* ends, int64_t n, double* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = parse_token(buf + starts[i], buf + ends[i]);
}

namespace {

// days since 1970-01-01 for a proleptic-Gregorian civil date
// (Howard Hinnant's days_from_civil; exact over datetime's year range)
static inline int64_t days_from_civil(int y, int m, int d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153u * (unsigned)(m + (m > 2 ? -3 : 9)) + 2u) / 5u +
                       (unsigned)d - 1u;
  const unsigned doe = yoe * 365u + yoe / 4u - yoe / 100u + doy;
  return era * 146097 + (int64_t)doe - 719468;
}

static inline bool rd_digits(const char* s, int k, int* v) {
  int acc = 0;
  for (int i = 0; i < k; ++i) {
    if (s[i] < '0' || s[i] > '9') return false;
    acc = acc * 10 + (s[i] - '0');
  }
  *v = acc;
  return true;
}

static const int kMonthDays[13] = {0, 31, 28, 31, 30, 31, 30,
                                   31, 31, 30, 31, 30, 31};

static inline bool valid_ymd(int y, int m, int d) {
  if (y < 1 || y > 9999 || m < 1 || m > 12 || d < 1) return false;
  int md = kMonthDays[m];
  if (m == 2 && (y % 4 == 0 && (y % 100 != 0 || y % 400 == 0))) md = 29;
  return d <= md;
}

}  // namespace

// Parse one column's cells as the canonical TIME formats
// (yyyy-MM-dd[{ |T}HH:mm:ss[.f{1,6}]] and MM/dd/yyyy) into fractional
// epoch milliseconds, computed exactly as CPython does:
// (total_microseconds / 1e6) * 1000.0 — bit-identical to
// (datetime.strptime(t) - epoch).total_seconds() * 1000.0.
// Any cell not strictly matching (including NA tokens and out-of-range
// fields) is flagged for the python fallback.  Returns the flagged count.
int64_t h2o3_parse_cells_time(const char* buf, const int32_t* starts,
                              const int32_t* ends, int64_t n, double* out,
                              uint8_t* flags) {
  int64_t nflag = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = buf + starts[i];
    const int len = ends[i] - starts[i];
    int y = 0, mo = 0, d = 0, h = 0, mi = 0, sec = 0;
    int64_t us = 0;
    bool ok = false;
    if (len >= 10 && s[4] == '-' && s[7] == '-') {
      ok = rd_digits(s, 4, &y) && rd_digits(s + 5, 2, &mo) &&
           rd_digits(s + 8, 2, &d);
      if (ok && len > 10) {
        ok = len >= 19 && (s[10] == ' ' || s[10] == 'T') && s[13] == ':' &&
             s[16] == ':' && rd_digits(s + 11, 2, &h) &&
             rd_digits(s + 14, 2, &mi) && rd_digits(s + 17, 2, &sec);
        if (ok && len > 19) {
          const int nf = len - 20;  // fractional digits after '.'
          ok = s[19] == '.' && nf >= 1 && nf <= 6;
          if (ok) {
            int frac = 0;
            ok = rd_digits(s + 20, nf, &frac);
            if (ok) {
              int64_t f = frac;
              for (int k = nf; k < 6; ++k) f *= 10;  // strptime %f pads
              us = f;
            }
          }
        }
      }
    } else if (len == 10 && s[2] == '/' && s[5] == '/') {
      ok = rd_digits(s, 2, &mo) && rd_digits(s + 3, 2, &d) &&
           rd_digits(s + 6, 4, &y);
    }
    if (ok)
      ok = valid_ymd(y, mo, d) && h <= 23 && mi <= 59 && sec <= 59;
    if (!ok) {
      out[i] = NAN;
      flags[i] = 1;
      ++nflag;
      continue;
    }
    flags[i] = 0;
    const int64_t total_us =
        (days_from_civil(y, mo, d) * 86400 + h * 3600 + mi * 60 + sec) *
            1000000LL +
        us;
    out[i] = ((double)total_us / 1e6) * 1000.0;
  }
  return nflag;
}

// Dictionary-encode one column's cells: int32 codes in first-appearance
// order plus the unique tokens as (start,end) offsets into buf (the local
// categorical dictionary; Categorical.java's per-chunk map).  Cells equal
// to an NA token (packed blob + offsets) get code -1.  uniq_starts /
// uniq_ends must hold n entries.  Returns the dictionary size.
int64_t h2o3_dict_encode_cells(const char* buf, const int32_t* starts,
                               const int32_t* ends, int64_t n,
                               const char* na_buf, const int32_t* na_starts,
                               const int32_t* na_ends, int32_t n_na,
                               int32_t* codes, int32_t* uniq_starts,
                               int32_t* uniq_ends) {
  std::unordered_map<std::string_view, int32_t> dict;
  dict.reserve(256);
  for (int64_t i = 0; i < n; ++i) {
    const std::string_view sv(buf + starts[i],
                              (size_t)(ends[i] - starts[i]));
    bool is_na = false;
    for (int32_t k = 0; k < n_na; ++k) {
      const size_t l = (size_t)(na_ends[k] - na_starts[k]);
      if (sv.size() == l &&
          (l == 0 || memcmp(sv.data(), na_buf + na_starts[k], l) == 0)) {
        is_na = true;
        break;
      }
    }
    if (is_na) {
      codes[i] = -1;
      continue;
    }
    auto it = dict.find(sv);
    if (it == dict.end()) {
      const int32_t c = (int32_t)dict.size();
      dict.emplace(sv, c);
      uniq_starts[c] = starts[i];
      uniq_ends[c] = ends[i];
      codes[i] = c;
    } else {
      codes[i] = it->second;
    }
  }
  return (int64_t)dict.size();
}

// Gather one column's cells into a single '\n'-joined buffer (cells never
// contain newlines — records were split on them) with an NA mask, so
// Python can materialize a STR/UUID column with ONE decode + split instead
// of n per-cell slices.  out must hold sum(ends-starts) + n bytes.
// Returns bytes written.
int64_t h2o3_gather_cells(const char* buf, const int32_t* starts,
                          const int32_t* ends, int64_t n, const char* na_buf,
                          const int32_t* na_starts, const int32_t* na_ends,
                          int32_t n_na, char* out, uint8_t* na_mask) {
  char* w = out;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = buf + starts[i];
    const size_t l = (size_t)(ends[i] - starts[i]);
    bool is_na = false;
    for (int32_t k = 0; k < n_na; ++k) {
      const size_t nl = (size_t)(na_ends[k] - na_starts[k]);
      if (l == nl && (l == 0 || memcmp(s, na_buf + na_starts[k], l) == 0)) {
        is_na = true;
        break;
      }
    }
    na_mask[i] = is_na ? 1 : 0;
    if (!is_na && l) {
      memcpy(w, s, l);
      w += l;
    }
    if (i + 1 < n) *w++ = '\n';
  }
  return (int64_t)(w - out);
}

}  // extern "C"
