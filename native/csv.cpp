// Fast CSV tokenizer / numeric parser — the data-loader hot path.
//
// Reference: water/parser/CsvParser.java (byte->token->NewChunk append per
// row, driven chunk-parallel by MultiFileParseTask, ParseDataset.java:623).
// The TPU build keeps type guessing in Python (sampled, cheap) and moves the
// bulk byte scanning here: one pass over the buffer, branch-light float
// parsing, NA -> quiet NaN.  Rows are split across threads on newline
// boundaries (the chunk-parallel structure of the reference's parse).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Count logical rows (newlines, ignoring a trailing unterminated line's
// absence) so Python can preallocate.
int64_t h2o3_count_rows(const char* buf, int64_t len) {
  int64_t n = 0;
  for (int64_t i = 0; i < len; ++i)
    if (buf[i] == '\n') ++n;
  if (len > 0 && buf[len - 1] != '\n') ++n;
  return n;
}

namespace {

// strtod-free fast path for plain decimal numbers; falls back to strtod for
// exponents/specials. Returns NaN for non-numeric tokens.
static inline double parse_token(const char* s, const char* e) {
  while (s < e && (*s == ' ' || *s == '\t')) ++s;
  while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r')) --e;
  if (s == e) return NAN;
  bool neg = false;
  const char* p = s;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') ++p;
  int64_t ip = 0;
  int digits = 0;
  while (p < e && *p >= '0' && *p <= '9' && digits < 18) {
    ip = ip * 10 + (*p - '0');
    ++p; ++digits;
  }
  if (p < e && *p == '.') {
    ++p;
    int64_t fp = 0, scale = 1;
    while (p < e && *p >= '0' && *p <= '9' && digits < 18) {
      fp = fp * 10 + (*p - '0');
      scale *= 10;
      ++p; ++digits;
    }
    if (p == e && digits > 0) {
      double v = (double)ip + (double)fp / (double)scale;
      return neg ? -v : v;
    }
  } else if (p == e && digits > 0) {
    double v = (double)ip;
    return neg ? -v : v;
  }
  // exponent / >18 digits / inf / nan / junk: defer to strtod
  char tmp[64];
  size_t n = (size_t)(e - s);
  if (n >= sizeof(tmp)) return NAN;
  memcpy(tmp, s, n);
  tmp[n] = 0;
  char* endp = nullptr;
  double v = strtod(tmp, &endp);
  if (endp == tmp || (endp && *endp != 0)) return NAN;
  return v;
}

struct Shard {
  const char* buf;
  int64_t begin, end;       // byte range, begin at a row start
  int64_t row0;             // first row index in this shard
  double* out;              // [nrows, ncols] row-major
  int32_t ncols;
  char sep;
};

static void parse_shard(const Shard sh) {
  const char* p = sh.buf + sh.begin;
  const char* lim = sh.buf + sh.end;
  int64_t row = sh.row0;
  while (p < lim) {
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(lim - p));
    if (!line_end) line_end = lim;
    double* dst = sh.out + row * sh.ncols;
    const char* tok = p;
    int32_t col = 0;
    for (const char* q = p; q <= line_end && col < sh.ncols; ++q) {
      if (q == line_end || *q == sh.sep) {
        dst[col++] = parse_token(tok, q);
        tok = q + 1;
      }
    }
    while (col < sh.ncols) dst[col++] = NAN;  // short row: missing -> NA
    ++row;
    p = line_end + 1;
  }
}

}  // namespace

// Parse `nrows` x `ncols` numerics from buf into out (row-major doubles).
// start: byte offset of the first data row (header skipped by caller).
// Returns rows parsed. Threads split on newline boundaries.
int64_t h2o3_parse_numeric_csv(const char* buf, int64_t len, int64_t start,
                               char sep, int32_t ncols, double* out,
                               int64_t nrows, int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  // find shard boundaries: nthreads byte-ranges snapped to line starts
  std::vector<int64_t> begins;
  begins.push_back(start);
  for (int t = 1; t < nthreads; ++t) {
    int64_t target = start + (len - start) * t / nthreads;
    const char* nl =
        (const char*)memchr(buf + target, '\n', (size_t)(len - target));
    int64_t b = nl ? (nl - buf) + 1 : len;
    if (b > begins.back()) begins.push_back(b);
  }
  begins.push_back(len);

  // row offsets per shard (prefix newline counts)
  std::vector<int64_t> row0(begins.size() - 1, 0);
  {
    int64_t acc = 0;
    for (size_t s = 0; s + 1 < begins.size(); ++s) {
      row0[s] = acc;
      const char* b = buf + begins[s];
      const char* e = buf + begins[s + 1];
      int64_t cnt = 0;
      for (const char* q = b; q < e; ++q)
        if (*q == '\n') ++cnt;
      if (s + 2 == begins.size() && e > b && e[-1] != '\n') ++cnt;
      acc += cnt;
    }
    if (acc > nrows) return -1;  // caller's preallocation too small
  }

  std::vector<std::thread> threads;
  for (size_t s = 0; s + 1 < begins.size(); ++s) {
    Shard sh{buf, begins[s], begins[s + 1], row0[s], out, ncols, sep};
    threads.emplace_back(parse_shard, sh);
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  {
    const char* b = buf + start;
    const char* e = buf + len;
    for (const char* q = b; q < e; ++q)
      if (*q == '\n') ++total;
    if (e > b && e[-1] != '\n') ++total;
  }
  return total;
}

}  // extern "C"
