"""Scoring coalescer: same-key requests collected for a short window,
executed as ONE batched dispatch.

Reference: the TensorFlow-Serving batching layer the system paper points
at — the serving front-end owns batching, the runtime only sees one
warm-cache dispatch.  Here the event-loop server submits every coalescable
REST request (POST /3/Predictions, keyed by model) through a Coalescer;
the first entry arms a window timer, later entries ride along, and the
batch closes on window expiry or when a bound trips.  Followers never
occupy worker threads — a whole batch is one job on the bounded pool — so
batch size is limited by admission control, not by worker count.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

#: requests per batched dispatch; total_count is the number of dispatches,
#: sum the number of coalesced requests — the coalescer tests assert on
#: exactly that ratio
_BATCH_SIZE = telemetry.histogram(
    "predict_batch_size",
    "coalesced scoring requests per batched dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)


def thread_dispatch(fn: Callable[[], None]) -> None:
    """Dispatcher for pool-less Coalescer owners (the cluster serving
    plane coalesces forwarded bundles outside any REST worker pool):
    each closed batch runs on its own daemon thread."""
    threading.Thread(target=fn, daemon=True, name="coalesce-batch").start()


class _Batch:
    __slots__ = ("key", "fn", "entries", "groups", "rows", "closed", "timer")

    def __init__(self, key: Any, fn: Callable[[List[Any]], List[Any]]) -> None:
        self.key = key
        self.fn = fn
        #: (payload, future, trace_id-or-None) per coalesced request
        self.entries: List[Tuple[Any, Future, Optional[str]]] = []
        self.groups: set = set()
        self.rows = 0
        self.closed = False
        self.timer: Optional[threading.Timer] = None


class Coalescer:
    """Collects submissions against one key for ``window_s``, then runs the
    batch function ONCE on the worker pool; per-entry futures resolve with
    its aligned results.

    The window is a bounded latency floor traded for one dispatch instead
    of N.  A batch closes early when ``max_requests`` entries accumulate or
    the row total crosses ``max_rows`` — rows are summed over DISTINCT row
    groups only (identical frames dedup to one scoring pass, so a thousand
    callers of the same frame cost its rows once, not a thousand times).
    """

    def __init__(
        self,
        dispatch: Callable[[Callable[[], None]], None],
        window_s: float,
        max_rows: int,
        max_requests: int,
    ) -> None:
        self._dispatch = dispatch
        self.window_s = float(window_s)
        self.max_rows = int(max_rows)
        self.max_requests = int(max_requests)
        self._lock = threading.Lock()
        self._open: Dict[Any, _Batch] = {}

    def submit(
        self,
        fn: Callable[[List[Any]], List[Any]],
        key: Any,
        payload: Any,
        rows_hint: int = 0,
        group: Any = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Queue ``payload`` into the open batch for ``key`` (creating one
        if needed).  ``fn(payloads)`` runs once per batch and must return
        one result per payload, aligned; the returned Future resolves with
        this payload's result.  ``trace_id`` names the submitting
        request's trace so the batch's dispatch cost can split across
        every rider (cost ledger)."""
        fut: Future = Future()
        full = False
        opened = False
        with self._lock:
            b = self._open.get(key)
            if b is None:
                b = _Batch(key, fn)
                self._open[key] = b
                opened = True
                b.timer = threading.Timer(self.window_s, self._close, (b,))
                b.timer.daemon = True
                b.timer.start()
            b.entries.append((payload, fut, trace_id))
            g = group if group is not None else object()
            if g not in b.groups:
                b.groups.add(g)
                b.rows += int(rows_hint)
            full = (len(b.entries) >= self.max_requests
                    or b.rows > self.max_rows)
            if full:
                self._detach(b)
        if opened:  # flight events after the leaf lock releases
            _flight.record(_flight.COALESCE, "info", "batch_open",
                           trace_id=trace_id)
        if full:
            self._fire(b)
        return fut

    def flush(self) -> None:
        """Close every open batch immediately (server drain: queued
        scoring requests finish instead of waiting out their window)."""
        with self._lock:
            batches = [b for b in self._open.values() if not b.closed]
            for b in batches:
                self._detach(b)
        for b in batches:
            self._fire(b)

    # -- internals -----------------------------------------------------------
    def _detach(self, b: _Batch) -> None:
        # caller holds the lock; after this no submit can join the batch
        b.closed = True
        if self._open.get(b.key) is b:
            del self._open[b.key]

    def _close(self, b: _Batch) -> None:
        # the window timer path
        with self._lock:
            if b.closed:
                return
            self._detach(b)
        self._fire(b)

    def _fire(self, b: _Batch) -> None:
        if b.timer is not None:
            b.timer.cancel()
        self._dispatch(lambda: self._run(b))

    def _run(self, b: _Batch) -> None:
        _BATCH_SIZE.observe(len(b.entries))
        _flight.record(_flight.COALESCE, "info", "batch_close",
                       entries=len(b.entries), rows=int(b.rows))
        t0 = time.perf_counter()
        try:
            results = b.fn([p for p, _, _ in b.entries])
            if len(results) != len(b.entries):
                raise RuntimeError(
                    f"batch fn returned {len(results)} results for "
                    f"{len(b.entries)} entries"
                )
        except BaseException as e:  # noqa: BLE001
            self._charge_shares(b, time.perf_counter() - t0)
            for _, fut, _ in b.entries:
                try:
                    fut.set_exception(e)
                except Exception:
                    pass  # drained/cancelled caller: response abandoned
            return
        self._charge_shares(b, time.perf_counter() - t0)
        for (_, fut, _), res in zip(b.entries, results):
            try:
                fut.set_result(res)
            except Exception:
                pass  # drained/cancelled caller: response abandoned

    @staticmethod
    def _charge_shares(b: _Batch, wall_s: float) -> None:
        """Split one dispatch's wall equally across every rider's trace:
        the shares of the K coalesced requests sum to the batch cost, so
        followers are never free and the leader is never blamed for K."""
        share = wall_s / len(b.entries) if b.entries else 0.0
        for _, _, tid in b.entries:
            if tid:
                _ledger.charge(
                    _ledger.COALESCE_SHARE_SECONDS, share, trace_id=tid)
