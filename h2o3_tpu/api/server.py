"""The REST server: route registry + event-loop HTTP front-end.

Reference: ``water/api/RequestServer.java:56-80,157-192,241`` (route table,
{placeholder} path params, fallback per-algo routes), ``RegisterV3Api.java``
(endpoint registration), ``water/api/Handler.java`` (schema in/out),
``water/api/H2OErrorV3`` (error payloads).

Design notes (TPU-native): the REST layer is pure control plane — every
handler manipulates host-side objects (frames, model keys, jobs) and the
device work happens inside the models' jitted programs.  The front-end is
an asyncio event loop in one thread (replacing both Jetty and the earlier
thread-per-connection stand-in, preserved in ``server_threaded.py`` as the
bench baseline): keep-alive connections, a global connection cap, per-route
concurrency budgets and a bounded request queue.  Overload sheds with
429 + ``Retry-After`` — never a hang, never an unbounded thread pile.
Handlers stay synchronous: admitted requests run on a bounded worker pool
off the loop, so all registered routes work unchanged.  Coalescable routes
(POST /3/Predictions) route through ``api/coalesce.py`` instead — same-model
requests collect for ``H2O3_TPU_BATCH_WINDOW_MS`` and execute as ONE
devcache-warm batched score, bit-identical to serial execution.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import queue
import re
import struct
import threading
import time
import traceback
import urllib.parse
from concurrent.futures import Future as _CFuture
from http.client import responses as _HTTP_REASONS
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu import __version__
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

Route = Tuple[str, "re.Pattern[str]", List[str], Callable, str]

#: REST traffic meters. The route label is the registered *pattern*
#: (/3/Models/{model_id}), never the raw path — raw paths would explode the
#: label cardinality with every model key ever scored.
_REST_REQUESTS = telemetry.counter(
    "rest_requests_total", "REST requests served",
    labels=("method", "route", "status"),
)
_REST_SECONDS = telemetry.histogram(
    "rest_request_seconds", "REST request wall seconds",
    labels=("method", "route"),
)
#: serving-plane meters: what admission control is doing right now
#: (in-flight = admitted, not yet responded; queue depth = waiting for a
#: worker) and what it refused (sheds answer 429 + Retry-After)
_HTTP_INFLIGHT = telemetry.gauge(
    "http_inflight", "REST requests admitted and not yet responded")
_HTTP_QUEUE_DEPTH = telemetry.gauge(
    "http_queue_depth", "REST requests waiting for a worker thread")
_HTTP_SHED = telemetry.counter(
    "http_shed_total", "REST requests shed by admission control (429)",
    labels=("route",),
)
_HTTP_CONNS = telemetry.gauge(
    "http_open_connections", "open REST client connections")


class RestError(Exception):
    """``headers`` ride the error response verbatim — the serving plane
    uses them to propagate a remote home's ``Retry-After`` through the
    front door unchanged (the front door's own admission meters never
    tick for a shed that happened elsewhere)."""

    def __init__(self, status: int, msg: str,
                 headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        super().__init__(msg)
        self.status = status
        self.headers = tuple(headers)


class RequestServer:
    """Route registry (RequestServer.java:56-80)."""

    def __init__(self) -> None:
        self.routes: List[Route] = []
        #: compiled pattern text -> the original {name} path template; the
        #: request meters and the docs lint both label routes with this
        self._templates: Dict[str, str] = {}
        #: (method, raw path) -> match result; scoring traffic hits the
        #: same few concrete paths thousands of times, so a linear scan of
        #: ~150 regexes per request would dominate the loop thread
        self._match_cache: Dict[Tuple[str, str],
                                Tuple[Callable, Dict[str, str], str]] = {}

    def register(self, method: str, path: str, handler: Callable, summary: str = "") -> None:
        """path uses {name} placeholders, e.g. /3/Models/{model_id}."""
        names = re.findall(r"\{(\w+)\}", path)
        pattern = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", path) + "$"
        )
        self.routes.append((method.upper(), pattern, names, handler, summary))
        self._templates[pattern.pattern] = path
        self._match_cache.clear()

    def templates(self) -> List[Tuple[str, str]]:
        """(method, {name}-template) of every registered route."""
        return [
            (m, self._templates.get(p.pattern, p.pattern[1:-1]))
            for m, p, _names, _handler, _summary in self.routes
        ]

    def match(
        self, method: str, path: str
    ) -> Optional[Tuple[Callable, Dict[str, str], str]]:
        """(handler, path_kwargs, route_pattern) of the first matching route;
        the pattern string is the stable low-cardinality label the request
        meters use."""
        hit = self._match_cache.get((method, path))
        if hit is not None:
            handler, kw, route = hit
            return handler, dict(kw), route
        for m, pattern, _names, handler, _ in self.routes:
            if m != method:
                continue
            mt = pattern.match(path)
            if mt:
                kw = {
                    k: urllib.parse.unquote(v)
                    for k, v in mt.groupdict().items()
                }
                # label with the {name} template the route was registered
                # under, not the compiled (?P<name>...) regex
                route = self._templates.get(
                    pattern.pattern, pattern.pattern[1:-1])
                # hits only — caching misses would let a path scanner grow
                # the dict without bound
                if len(self._match_cache) < 4096:
                    self._match_cache[(method, path)] = (
                        handler, dict(kw), route)
                return handler, kw, route
        return None

    def dispatch(self, method: str, path: str, params: Dict[str, Any]) -> Any:
        found = self.match(method, path)
        if found is None:
            raise RestError(404, f"no route for {method} {path}")
        handler, kw, _route = found
        return handler(params, **kw)

    def endpoints(self) -> List[Dict[str, str]]:
        return [
            {"method": m, "url_pattern": p.pattern[1:-1], "summary": s}
            for m, p, _, _, s in self.routes
        ]


#: inbound trace-context headers must look like the ids we mint (hex, 8-32
#: chars): the value is echoed back as a response header and recorded into
#: every timeline event and log line of the request, so an unvalidated
#: value would be a response-header-injection (CRLF) primitive and a
#: timeline-pollution vector
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")


def _trace_header(value: Optional[str]) -> Optional[str]:
    if value and _TRACE_ID_RE.match(value):
        return value
    return None


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return None if np.isnan(v) else v
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, float) and np.isnan(o):
        return None
    raise TypeError(f"not JSON serializable: {type(o)}")


#: URLs of servers CURRENTLY running in this process (start() adds,
#: stop() removes — a dead server's port may be reused by anything)
_LIVE_URLS: set = set()


def served_from_this_process(url: str) -> bool:
    """True if `url` is served by a live H2OServer in this process RIGHT
    NOW. Callers that need "was this endpoint ours?" later (e.g. after
    the server stops) must evaluate this at connection time and remember
    the answer — a stopped server's port can be reused by an unrelated
    external service."""
    return url.rstrip("/") in _LIVE_URLS


# -- serving-plane knobs ------------------------------------------------------

#: field -> (env var, default, cast).  Env sets the process default; the
#: H2OServer(http={...}) constructor arg overrides per server (tests run
#: tiny queues, the bench flips the batch window).
_KNOBS: Dict[str, Tuple[str, Any, Callable[[Any], Any]]] = {
    "workers": ("H2O3_TPU_HTTP_WORKERS", 16, int),
    "queue": ("H2O3_TPU_HTTP_QUEUE", 512, int),
    "max_conns": ("H2O3_TPU_HTTP_MAX_CONNS", 8192, int),
    "route_budget": ("H2O3_TPU_HTTP_ROUTE_BUDGET", 256, int),
    "max_header_bytes": ("H2O3_TPU_HTTP_MAX_HEADER_BYTES", 64 * 1024, int),
    "max_body_bytes": ("H2O3_TPU_HTTP_MAX_BODY_BYTES", 256 << 20, int),
    "read_timeout_s": ("H2O3_TPU_HTTP_READ_TIMEOUT_S", 30.0, float),
    "idle_timeout_s": ("H2O3_TPU_HTTP_IDLE_TIMEOUT_S", 120.0, float),
    "drain_s": ("H2O3_TPU_HTTP_DRAIN_S", 5.0, float),
    "batch_window_ms": ("H2O3_TPU_BATCH_WINDOW_MS", 2.0, float),
    "batch_max_rows": ("H2O3_TPU_BATCH_MAX_ROWS", 262144, int),
    "batch_max_requests": ("H2O3_TPU_BATCH_MAX_REQUESTS", 256, int),
}


class HttpOptions:
    """Resolved serving-plane configuration (see ``_KNOBS`` for the env
    names and defaults)."""

    __slots__ = tuple(_KNOBS) + ("route_budgets",)

    def __init__(self, **overrides: Any) -> None:
        budgets = overrides.pop("route_budgets", None) or {}
        for fld, (env, default, cast) in _KNOBS.items():
            if fld in overrides:
                v = overrides.pop(fld)
            else:
                raw = os.environ.get(env)
                v = raw if raw is not None else default
            setattr(self, fld, cast(v))
        if overrides:
            raise TypeError(f"unknown http option(s): {sorted(overrides)}")
        #: route pattern -> per-route in-flight budget override
        self.route_budgets: Dict[str, int] = {
            k: int(v) for k, v in budgets.items()}

    def budget_for(self, route: str) -> int:
        return self.route_budgets.get(route, self.route_budget)


# -- request/response plumbing ------------------------------------------------

def _body_bytes(status: int, msg: str) -> bytes:
    """A loop-built error payload (water/api/schemas3/H2OErrorV3 shape)."""
    return json.dumps({
        "http_status": status,
        "msg": msg,
        "dev_msg": msg,
        "exception_type": "RestError",
    }).encode()


def _error_body(e: BaseException) -> Tuple[int, bytes]:
    if isinstance(e, RestError):
        return e.status, json.dumps({
            "http_status": e.status,
            "msg": str(e),
            "dev_msg": str(e),
            "exception_type": "RestError",
        }).encode()
    return 500, json.dumps({
        "http_status": 500,
        "msg": f"{type(e).__name__}: {e}",
        "dev_msg": "".join(
            traceback.format_exception(type(e), e, e.__traceback__)),
        "exception_type": type(e).__name__,
    }).encode()


def _encode_out(out: Any) -> Tuple[bytes, str]:
    if (isinstance(out, tuple) and len(out) == 2
            and isinstance(out[0], (bytes, bytearray))):
        return bytes(out[0]), out[1]
    if isinstance(out, (bytes, bytearray)):
        return bytes(out), "application/octet-stream"
    return json.dumps(out, default=_json_default).encode(), "application/json"


def _build_params(query: str, body: bytes, ctype: str) -> Dict[str, Any]:
    params: Dict[str, Any] = {
        k: v[0] if len(v) == 1 else v
        for k, v in urllib.parse.parse_qs(query).items()
    }
    if body:
        if "json" in ctype:
            params.update(json.loads(body))
        elif "octet-stream" in ctype:
            # binary upload (model files, NPS blobs): handlers read the
            # bytes under _raw_body
            params["_raw_body"] = body
        else:  # h2o-py posts urlencoded forms
            try:
                params.update({
                    k: v[0] if len(v) == 1 else v
                    for k, v in urllib.parse.parse_qs(body.decode()).items()
                })
            except UnicodeDecodeError:
                params["_raw_body"] = body
    return params


def _render_head(status: int, length: int, ctype: str,
                 extra: Tuple[Tuple[str, str], ...] = (),
                 close: bool = False) -> bytes:
    head = [f"HTTP/1.1 {status} {_HTTP_REASONS.get(status, '')}"]
    for k, v in extra:
        head.append(f"{k}: {v}")
    head.append(f"Server: h2o3-tpu/{__version__}")
    head.append(f"Content-Type: {ctype}")
    head.append(f"Content-Length: {length}")
    if close:
        head.append("Connection: close")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")


async def _write_response(writer: asyncio.StreamWriter, status: int,
                          payload: bytes, ctype: str = "application/json",
                          extra: Tuple[Tuple[str, str], ...] = (),
                          close: bool = False) -> bool:
    data = _render_head(status, len(payload), ctype, extra, close) + payload
    try:
        writer.write(data)
        await writer.drain()
    except (ConnectionError, RuntimeError):
        return False
    return True


def _keep_alive(version: str, headers: Dict[str, str]) -> bool:
    conn = headers.get("connection", "").lower()
    if "close" in conn:
        return False
    if version == "HTTP/1.0":
        return "keep-alive" in conn
    return True


#: what the event-loop side resolves a request future to
#: (status, payload, content-type, trace id to echo[, extra headers]) —
#: the optional fifth element carries handler-supplied response headers
#: (RestError.headers, e.g. a forwarded Retry-After)
_Resp = Tuple[int, bytes, str, Optional[str]]
_DRAIN_RESP: _Resp = (
    503, _body_bytes(503, "server draining"), "application/json", None)


class _Job:
    """One admitted request travelling loop -> worker -> loop."""

    __slots__ = ("method", "path", "query", "ctype", "body", "handler",
                 "path_kw", "route", "trace_id", "parent_id", "future")

    def __init__(self, method: str, path: str, query: str, ctype: str,
                 body: bytes, handler: Callable, path_kw: Dict[str, str],
                 route: str, trace_id: Optional[str],
                 parent_id: Optional[str]) -> None:
        self.method = method
        self.path = path
        self.query = query
        self.ctype = ctype
        self.body = body
        self.handler = handler
        self.path_kw = path_kw
        self.route = route
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.future: _CFuture = _CFuture()


def _resolve(fut: _CFuture, resp: _Resp) -> None:
    try:
        fut.set_result(resp)
    except Exception:
        pass  # cancelled/drained: the connection already got an answer


def _run_job(job: _Job) -> None:
    """Worker-side execution of one non-coalesced request: params build,
    Span, handler, encode — everything that may block or compute."""
    from h2o3_tpu.util.log import get_logger

    status, ctype = 200, "application/json"
    # a proxied/forwarded request may carry its caller's trace: honor the
    # headers (id-shaped values only) so one trace threads client -> this
    # REST span -> any node RPC it fans out
    span = telemetry.Span(
        "rest", method=job.method, route=job.route, path=job.path,
        trace_id=job.trace_id, parent_id=job.parent_id,
    )
    t0 = time.perf_counter()
    hdrs: Tuple[Tuple[str, str], ...] = ()
    try:
        with span:
            # logged INSIDE the span so the /3/Logs line carries this
            # request's trace/span ids
            get_logger("rest").info("%s %s", job.method, job.path)
            out = job.handler(
                _build_params(job.query, job.body, job.ctype), **job.path_kw)
        payload, ctype = _encode_out(out)
    except BaseException as e:  # noqa: BLE001
        status, payload = _error_body(e)
        ctype = "application/json"
        hdrs = tuple(getattr(e, "headers", ()) or ())
    # cost accounting BEFORE the future resolves: a client reading its
    # response can immediately GET /3/Traces/{id} and see route/wall meta
    wall_ms = (time.perf_counter() - t0) * 1e3
    _ledger.LEDGER.annotate(span.trace_id, route=job.route,
                            wall_ms=round(wall_ms, 3), status=status)
    _ledger.SLOWOPS.record(job.route, wall_ms, span.trace_id, status)
    _resolve(job.future, (status, payload, ctype, span.trace_id, hdrs))


def _run_batch(route: str, batch_fn: Callable, jobs: List[_Job]) -> List[_Resp]:
    """Worker-side execution of one coalesced batch: build params per
    entry, ONE batch-handler call, encode per entry.  A bad entry (params
    or handler error) gets its own error response; the rest proceed."""
    from h2o3_tpu.util.log import get_logger

    log = get_logger("rest")
    built: List[Optional[BaseException]] = []
    live: List[Tuple[Dict[str, Any], Dict[str, str]]] = []
    for job in jobs:
        try:
            live.append((
                _build_params(job.query, job.body, job.ctype), job.path_kw))
            built.append(None)
        except BaseException as e:  # noqa: BLE001
            built.append(e)
    # the batch span runs under the LEADER's trace (jobs[0]); each rider
    # keeps its own trace id for its response header and its ledger share
    # (the coalescer splits the dispatch wall across riders)
    span = telemetry.Span(
        "rest", method=jobs[0].method, route=route, batch=len(jobs),
        trace_id=jobs[0].trace_id, parent_id=jobs[0].parent_id,
    )
    outs: List[Any]
    t0 = time.perf_counter()
    with span:
        for job in jobs:
            log.info("%s %s (coalesced x%d)", job.method, job.path, len(jobs))
        try:
            outs = list(batch_fn(live))
            if len(outs) != len(live):
                raise RuntimeError(
                    f"batch handler returned {len(outs)} results "
                    f"for {len(live)} requests")
        except BaseException as e:  # noqa: BLE001
            outs = [e] * len(live)
    wall_ms = (time.perf_counter() - t0) * 1e3
    results: List[_Resp] = []
    it = iter(outs)
    for job, err in zip(jobs, built):
        res = err if err is not None else next(it)
        hdrs: Tuple[Tuple[str, str], ...] = ()
        if isinstance(res, BaseException):
            status, payload = _error_body(res)
            ctype = "application/json"
            hdrs = tuple(getattr(res, "headers", ()) or ())
        else:
            try:
                payload, ctype = _encode_out(res)
                status = 200
            except BaseException as e:  # noqa: BLE001
                status, payload = _error_body(e)
                ctype = "application/json"
        tid = job.trace_id or span.trace_id
        _ledger.LEDGER.annotate(tid, route=route,
                                wall_ms=round(wall_ms, 3), status=status,
                                batch=len(jobs))
        _ledger.SLOWOPS.record(route, wall_ms, tid, status)
        results.append((status, payload, ctype, tid, hdrs))
    return results


class _WorkerPool:
    """Bounded handler execution off the event loop.  The queue object is
    unbounded (SimpleQueue); boundedness is enforced up front by the
    loop-side admission counters — an explicit 429 at admission beats the
    implicit unbounded backlog a ThreadPoolExecutor would hide."""

    def __init__(self, n: int) -> None:
        self._q: "queue.SimpleQueue[Optional[Callable[[], None]]]" = (
            queue.SimpleQueue())
        self._threads: List[threading.Thread] = []
        for i in range(n):
            t = threading.Thread(
                target=self._run, daemon=True,
                name=f"http-worker-{i}",  # /3/Profiler's "^http" filter
            )
            t.start()
            self._threads.append(t)

    def submit(self, fn: Callable[[], None]) -> None:
        self._q.put(fn)

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(None)

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except BaseException:  # noqa: BLE001
                from h2o3_tpu.util.log import get_logger

                get_logger("rest").error(
                    "worker job crashed: %s", traceback.format_exc())


class H2OServer:
    """The server facade (h2o-webserver-iface HttpServerFacade analogue).

    Security (water/network + LoginType hash-file auth): ``ssl_cert``/
    ``ssl_key`` wrap the listener in TLS (the reference's jetty SSL config;
    asyncio handshakes per connection without blocking the accept path);
    ``auth_file`` — lines of ``user:sha256(password)`` — enables HTTP Basic
    auth on every route (LoginType.HASH_FILE).

    ``http`` overrides serving-plane knobs (see ``_KNOBS``), e.g.
    ``http=dict(workers=2, queue=8, batch_window_ms=0)``."""

    def __init__(
        self,
        port: int = 54321,
        name: str = "h2o3-tpu",
        ssl_cert: Optional[str] = None,
        ssl_key: Optional[str] = None,
        auth_file: Optional[str] = None,
        auth_backend=None,
        ip: str = "127.0.0.1",
        http: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        #: bind address (-ip / web_ip OptArg); 0.0.0.0 for pod/container
        #: serving where probes and clients arrive on the pod IP
        self.ip = ip
        self.start_time = time.time()
        self.registry = RequestServer()
        from h2o3_tpu.api import handlers

        handlers.register_all(self.registry, self)
        self.port = port
        self.ssl_cert = ssl_cert
        self.ssl_key = ssl_key
        self.http = HttpOptions(**(http or {}))
        #: the auth SPI (api/auth.py LoginBackend); auth_file builds the
        #: hash-file backend for back-compat, auth_backend wins when given
        self._auth = auth_backend
        if self._auth is None and auth_file:
            from h2o3_tpu.api.auth import HashFileBackend

            self._auth = HashFileBackend(auth_file)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._listener: Optional[asyncio.AbstractServer] = None
        self._pool: Optional[_WorkerPool] = None
        self._coalescer = None
        # loop-confined connection/request accounting (single-threaded
        # event loop => no locks); stop() only READS _inflight cross-thread
        self._conns: set = set()
        self._nconns = 0
        self._inflight = 0
        self._route_inflight: Dict[str, int] = {}
        # queue depth is written from both sides (loop enqueues, workers
        # dequeue), so it gets a lock
        self._qlock = threading.Lock()
        self._queued = 0
        self._draining = False
        self._stop_lock = threading.Lock()
        self._stopped = False

    def _check_auth(self, header: Optional[str]) -> bool:
        if self._auth is None:
            return True
        if not header or not header.startswith("Basic "):
            return False
        import base64

        try:
            user, _, password = (
                base64.b64decode(header[6:]).decode().partition(":")
            )
        except Exception:
            return False
        return self._auth.authenticate(user, password)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "H2OServer":
        # the /3/Logs ring must be live from the first request, whether or
        # not any module logged before the server came up (satellite of the
        # telemetry PR: init() is idempotent, dir comes from H2O3_TPU_LOG_DIR)
        from h2o3_tpu.util import log as _log

        _log.init()
        # standalone REST nodes (no boot_node) still get a watchdog +
        # crash hooks; on a clustered node boot_node already started it
        from h2o3_tpu.cluster import health as _health

        _health.start()
        opts = self.http
        self._pool = _WorkerPool(opts.workers)
        if opts.batch_window_ms > 0:
            from h2o3_tpu.api.coalesce import Coalescer

            self._coalescer = Coalescer(
                dispatch=self._pool.submit,
                window_s=opts.batch_window_ms / 1000.0,
                max_rows=opts.batch_max_rows,
                max_requests=opts.batch_max_requests,
            )
        ctx = None
        if self.ssl_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.ssl_cert, self.ssl_key)
        self._loop = asyncio.new_event_loop()
        bound: _CFuture = _CFuture()

        async def _serve() -> None:
            try:
                srv = await asyncio.start_server(
                    self._handle_conn, self.ip, self.port, ssl=ctx,
                    # the stream limit backs the header-size cap: an
                    # overlong line surfaces as LimitOverrunError -> 413
                    limit=max(opts.max_header_bytes, 64 * 1024),
                    backlog=1024,
                )
            except BaseException as e:  # noqa: BLE001
                bound.set_exception(e)
                return
            self._listener = srv
            bound.set_result(srv.sockets[0].getsockname()[1])

        def _loop_main() -> None:
            loop = self._loop
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(_serve())
                if bound.exception() is None:
                    loop.run_forever()
            finally:
                try:
                    pending = asyncio.all_tasks(loop)
                    for t in pending:
                        t.cancel()
                    if pending:
                        loop.run_until_complete(asyncio.gather(
                            *pending, return_exceptions=True))
                finally:
                    loop.close()

        self._thread = threading.Thread(
            target=_loop_main, daemon=True,
            name="http-loop",  # matches /3/Profiler's "^http" filter
        )
        self._thread.start()
        try:
            self.port = int(bound.result(timeout=30))
        except BaseException:
            self.stop()
            raise
        # a live application-plane cloud learns where this node's REST
        # surface landed (OS-assigned ports resolve only here); gossip
        # then carries it to every member's /3/Cloud listing
        from h2o3_tpu import cluster

        _cloud = cluster.local_cloud()
        if _cloud is not None:
            _cloud.advertise_rest_port(self.port)
        # registry of live in-process servers: lets clients answer "is
        # this endpoint one of ours?" exactly at connect time, instead
        # of guessing from the address (a port-forwarded remote can
        # look like loopback)
        _LIVE_URLS.add(self.url)
        return self

    def stop(self) -> None:
        # idempotent + thread-safe: /3/Shutdown schedules a delayed stop
        # that may race the owner's own stop() call.  Shutdown is a
        # bounded drain: close the listener, let in-flight requests finish
        # for up to drain_s, then 503 what's still queued and cut the
        # connections — a lingering keep-alive client can never wedge a
        # test teardown or a chaos restart.
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        _LIVE_URLS.discard(self.url)
        loop, thread = self._loop, self._thread
        self._draining = True
        if loop is not None and thread is not None and thread.is_alive():
            async def _begin() -> None:
                if self._listener is not None:
                    self._listener.close()
                if self._coalescer is not None:
                    self._coalescer.flush()

            try:
                asyncio.run_coroutine_threadsafe(
                    _begin(), loop).result(timeout=5)
            except Exception:
                pass
            deadline = time.monotonic() + self.http.drain_s
            while time.monotonic() < deadline and self._inflight > 0:
                time.sleep(0.01)

            async def _finish() -> None:
                for t in list(self._conns):
                    t.cancel()

            try:
                asyncio.run_coroutine_threadsafe(
                    _finish(), loop).result(timeout=5)
            except Exception:
                pass
            loop.call_soon_threadsafe(loop.stop)
            thread.join(timeout=10)
        if self._pool is not None:
            self._pool.stop()

    @property
    def url(self) -> str:
        scheme = "https" if self.ssl_cert else "http"
        # a wildcard bind is reachable via loopback for local clients
        host = "127.0.0.1" if self.ip in ("0.0.0.0", "::") else self.ip
        return f"{scheme}://{host}:{self.port}"

    # -- connection handling (event-loop side) -------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        self._nconns += 1
        try:
            with _HTTP_CONNS.track():
                if self._nconns > self.http.max_conns:
                    _HTTP_SHED.inc(route="(connection_limit)")
                    _flight.record(_flight.COALESCE, "warn", "shed",
                                   route="(connection_limit)",
                                   conns=self._nconns)
                    await _write_response(
                        writer, 429,
                        _body_bytes(429, "connection limit reached"),
                        extra=(("Retry-After", "1"),), close=True)
                    return
                await self._conn_loop(reader, writer)
        except (asyncio.CancelledError, ConnectionError):
            pass
        except Exception:  # noqa: BLE001
            from h2o3_tpu.util.log import get_logger

            get_logger("rest").error(
                "connection handler crashed: %s", traceback.format_exc())
        finally:
            self._conns.discard(task)
            self._nconns -= 1
            try:
                writer.close()
            except Exception:
                pass

    async def _conn_loop(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        opts = self.http
        loop = self._loop
        while not self._draining:
            # request line: wait out keep-alive idleness, then put the
            # rest of the head under a read deadline — a slow-loris client
            # gets 408, it never pins anything
            try:
                line = await asyncio.wait_for(
                    reader.readline(), opts.idle_timeout_s)
            except asyncio.TimeoutError:
                return  # idle keep-alive expired: close silently
            except (ValueError, asyncio.LimitOverrunError):
                await _write_response(
                    writer, 413,
                    _body_bytes(413, "request line too long"), close=True)
                return
            if not line:
                return
            if line in (b"\r\n", b"\n"):
                continue  # tolerate stray blank lines between requests
            try:
                method, target, version = (
                    line.decode("latin-1").rstrip("\r\n").split(" ", 2))
            except ValueError:
                await _write_response(
                    writer, 400,
                    _body_bytes(400, "malformed request line"), close=True)
                return
            deadline = loop.time() + opts.read_timeout_s
            headers: Dict[str, str] = {}
            hbytes = len(line)
            bad: Optional[Tuple[int, str]] = None
            while True:
                try:
                    h = await asyncio.wait_for(
                        reader.readline(),
                        max(0.001, deadline - loop.time()))
                except asyncio.TimeoutError:
                    bad = (408, "request header read deadline exceeded")
                    break
                except (ValueError, asyncio.LimitOverrunError):
                    bad = (413, "request header line too long")
                    break
                if not h:
                    return  # client went away mid-header
                if h in (b"\r\n", b"\n"):
                    break
                hbytes += len(h)
                if hbytes > opts.max_header_bytes:
                    bad = (413, f"request headers exceed "
                                f"{opts.max_header_bytes} bytes")
                    break
                k, sep, v = h.decode("latin-1").partition(":")
                if sep:
                    headers[k.strip().lower()] = v.strip()
            if bad is not None:
                await _write_response(
                    writer, bad[0], _body_bytes(*bad), close=True)
                return
            path = urllib.parse.urlsplit(target).path
            if (method == "GET" and path == "/3/Steam.web"
                    and "websocket" in headers.get("upgrade", "").lower()):
                await self._serve_websocket(reader, writer, headers)
                return
            # body: Content-Length only (the clients we serve — h2o-py,
            # the R client, curl uploads — all send it)
            if "chunked" in headers.get("transfer-encoding", "").lower():
                await _write_response(
                    writer, 411,
                    _body_bytes(411, "chunked transfer encoding not "
                                     "supported; send Content-Length"),
                    close=True)
                return
            try:
                length = int(headers.get("content-length") or 0)
            except ValueError:
                await _write_response(
                    writer, 400,
                    _body_bytes(400, "bad Content-Length"), close=True)
                return
            if length > opts.max_body_bytes:
                await _write_response(
                    writer, 413,
                    _body_bytes(413, f"request body exceeds "
                                     f"{opts.max_body_bytes} bytes"),
                    close=True)
                return
            body = b""
            if length:
                if "100-continue" in headers.get("expect", "").lower():
                    writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                try:
                    body = await asyncio.wait_for(
                        reader.readexactly(length), opts.read_timeout_s)
                except asyncio.TimeoutError:
                    await _write_response(
                        writer, 408,
                        _body_bytes(408, "request body read deadline "
                                         "exceeded"),
                        close=True)
                    return
                except asyncio.IncompleteReadError:
                    return
            keep = _keep_alive(version, headers)
            ok = await self._serve_request(
                writer, method, target, headers, body, keep)
            if not ok or not keep:
                return

    async def _serve_request(self, writer: asyncio.StreamWriter, method: str,
                             target: str, headers: Dict[str, str],
                             body: bytes, keep: bool) -> bool:
        """Route + admission + response for one parsed request.  Returns
        False when the connection should close."""
        from h2o3_tpu.util.log import get_logger

        t0 = time.perf_counter()
        parsed = urllib.parse.urlsplit(target)
        path = parsed.path
        if method not in ("GET", "POST", "DELETE"):
            return await _write_response(
                writer, 501,
                _body_bytes(501, f"unsupported method {method}"),
                close=not keep) and keep
        # the request meters label by registered route pattern; an
        # unmatched path collapses into one "(unmatched)" series so
        # scanners can't mint unbounded label values
        found = self.registry.match(method, path)
        route = found[2] if found else "(unmatched)"
        if not self._check_auth(headers.get("authorization")):
            get_logger("rest").info("%s %s", method, path)
            resp: _Resp = (
                401,
                json.dumps({"http_status": 401,
                            "msg": "authentication required"}).encode(),
                "application/json", None)
            return await self._finish_request(
                writer, method, route, t0, resp, keep,
                extra=(("WWW-Authenticate", 'Basic realm="h2o3-tpu"'),))
        if found is None:
            with telemetry.Span("rest", method=method,
                                route=route, path=path) as span:
                get_logger("rest").info("%s %s", method, path)
            status, payload = _error_body(
                RestError(404, f"no route for {method} {path}"))
            return await self._finish_request(
                writer, method, route, t0,
                (status, payload, "application/json", span.trace_id), keep)
        handler, path_kw, route = found
        # -- admission control ------------------------------------------------
        budget = self.http.budget_for(route)
        if self._route_inflight.get(route, 0) >= budget:
            _HTTP_SHED.inc(route=route)
            _flight.record(_flight.COALESCE, "warn", "shed", route=route,
                           why="route_budget")
            resp = (429,
                    _body_bytes(429, f"route {route} concurrency budget "
                                     f"({budget}) exhausted"),
                    "application/json", None)
            return await self._finish_request(
                writer, method, route, t0, resp, keep,
                extra=(("Retry-After", "1"),))
        batch_fn = getattr(handler, "_h2o3_batch", None)
        coalesce = (self._coalescer is not None and batch_fn is not None
                    and not self._draining)
        if not coalesce and self._queued >= self.http.queue:
            _HTTP_SHED.inc(route=route)
            _flight.record(_flight.COALESCE, "warn", "shed", route=route,
                           why="queue_full")
            resp = (429,
                    _body_bytes(429, f"request queue full "
                                     f"({self.http.queue})"),
                    "application/json", None)
            return await self._finish_request(
                writer, method, route, t0, resp, keep,
                extra=(("Retry-After", "1"),))
        # -- admitted ---------------------------------------------------------
        self._route_inflight[route] = self._route_inflight.get(route, 0) + 1
        self._inflight += 1
        _HTTP_INFLIGHT.inc()
        try:
            job = _Job(method, path, parsed.query,
                       headers.get("content-type", ""), body, handler,
                       path_kw, route,
                       _trace_header(headers.get("x-h2o3-trace-id")),
                       _trace_header(headers.get("x-h2o3-span-id")))
            if coalesce:
                if job.trace_id is None:
                    # every coalesced rider gets its own trace identity up
                    # front (not just the leader's batch span), so the
                    # dispatch cost splits across rider traces and each
                    # response echoes an id /3/Traces/{id} can resolve
                    job.trace_id = telemetry._new_id()
                key = (route, handler._h2o3_batch_key(path_kw))
                group_fn = getattr(handler, "_h2o3_batch_group", None)
                rows_fn = getattr(handler, "_h2o3_batch_rows", None)
                cfut = self._coalescer.submit(
                    functools.partial(_run_batch, route, batch_fn),
                    key, job,
                    rows_hint=rows_fn(path_kw) if rows_fn else 0,
                    group=(key, group_fn(path_kw)) if group_fn else None,
                    trace_id=job.trace_id,
                )
            else:
                cfut = job.future
                with self._qlock:
                    self._queued += 1
                _HTTP_QUEUE_DEPTH.inc()
                self._pool.submit(functools.partial(self._exec_job, job))
            try:
                resp = await asyncio.wrap_future(cfut)
            except asyncio.CancelledError:
                # drain deadline expired with this request still queued:
                # best-effort 503 (buffered, no drain — the loop is
                # stopping) before the connection is cut
                _resolve(cfut, _DRAIN_RESP)
                try:
                    writer.write(_render_head(
                        503, len(_DRAIN_RESP[1]), "application/json",
                        close=True) + _DRAIN_RESP[1])
                except Exception:
                    pass
                raise
            except BaseException as e:  # noqa: BLE001
                status, payload = _error_body(e)
                resp = (status, payload, "application/json", None,
                        tuple(getattr(e, "headers", ()) or ()))
            return await self._finish_request(
                writer, method, route, t0, resp, keep)
        finally:
            self._route_inflight[route] = (
                self._route_inflight.get(route, 1) - 1)
            self._inflight -= 1
            _HTTP_INFLIGHT.dec()

    async def _finish_request(self, writer: asyncio.StreamWriter, method: str,
                              route: str, t0: float, resp: _Resp, keep: bool,
                              extra: Tuple[Tuple[str, str], ...] = ()) -> bool:
        status, payload, ctype, trace_id, *rest = resp
        if rest and rest[0]:
            # handler-supplied headers (RestError.headers): e.g. the
            # serving plane forwarding a remote home's Retry-After
            extra = extra + tuple(rest[0])
        # account BEFORE the response flushes: a client that has read its
        # response can immediately see the request in /3/Metrics
        # (read-your-writes for the meters)
        _REST_REQUESTS.inc(method=method, route=route, status=str(status))
        _REST_SECONDS.observe(
            time.perf_counter() - t0, method=method, route=route)
        if trace_id:
            # clients correlate their request with /3/Timeline
            extra = extra + (("X-H2O3-Trace-Id", trace_id),)
        return await _write_response(
            writer, status, payload, ctype=ctype, extra=extra,
            close=not keep) and keep

    def _exec_job(self, job: _Job) -> None:
        with self._qlock:
            self._queued -= 1
        _HTTP_QUEUE_DEPTH.dec()
        if job.future.done():
            return  # drained/cancelled while queued: nobody is listening
        _run_job(job)

    def _in_worker(self, fn: Callable, *args: Any) -> "asyncio.Future":
        """Run fn on the bounded worker pool, awaitable from the loop."""
        fut: _CFuture = _CFuture()

        def run() -> None:
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001
                try:
                    fut.set_exception(e)
                except Exception:
                    pass

        self._pool.submit(run)
        return asyncio.wrap_future(fut)

    # -- websocket (Steam) ---------------------------------------------------
    async def _serve_websocket(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter,
                               headers: Dict[str, str]) -> None:
        """RFC 6455 server endpoint for /3/Steam.web (async reimplementation
        of steam.serve_websocket's frame loop; the handshake/encode/dispatch
        pieces are steam's pure helpers)."""
        from h2o3_tpu.api import steam

        if not self._check_auth(headers.get("authorization")):
            await _write_response(writer, 401, b"", close=True)
            return
        key = headers.get("sec-websocket-key", "")
        if not key:
            await _write_response(writer, 400, b"", close=True)
            return
        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\n"
            b"Connection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + steam.accept_key(key).encode()
            + b"\r\n\r\n")
        await writer.drain()
        try:
            while True:
                head = await reader.readexactly(2)
                opcode = head[0] & 0x0F
                masked = head[1] & 0x80
                n = head[1] & 0x7F
                if n == 126:
                    n = struct.unpack(">H", await reader.readexactly(2))[0]
                elif n == 127:
                    n = struct.unpack(">Q", await reader.readexactly(8))[0]
                if n > (1 << 22):
                    return  # oversized control-plane frame: drop
                mask = await reader.readexactly(4) if masked else b""
                payload = await reader.readexactly(n) if n else b""
                if masked:
                    payload = bytes(
                        b ^ mask[i % 4] for i, b in enumerate(payload))
                if opcode == 0x8:  # close: echo and stop
                    writer.write(steam.encode_frame(payload, 0x8))
                    await writer.drain()
                    return
                if opcode == 0x9:  # ping -> pong
                    writer.write(steam.encode_frame(payload, 0xA))
                    await writer.drain()
                    continue
                if opcode != 0x1:
                    continue  # binary/continuation: the exchange is text-only
                try:
                    message = json.loads(payload.decode())
                except (ValueError, UnicodeDecodeError):
                    continue
                # messengers may import/compute (hello touches the device
                # mesh), so the dispatch runs off-loop
                for resp in await self._in_worker(steam.dispatch, message):
                    writer.write(
                        steam.encode_frame(json.dumps(resp).encode()))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            return


def start_server(port: int = 0, name: str = "h2o3-tpu", **kw) -> H2OServer:
    """Start a server on localhost (port 0 = OS-assigned). Keyword args
    pass through to H2OServer (ssl_cert/ssl_key/auth_file/http)."""
    return H2OServer(port=port, name=name, **kw).start()
