"""The REST server: route registry + threaded HTTP dispatch.

Reference: ``water/api/RequestServer.java:56-80,157-192,241`` (route table,
{placeholder} path params, fallback per-algo routes), ``RegisterV3Api.java``
(endpoint registration), ``water/api/Handler.java`` (schema in/out),
``water/api/H2OErrorV3`` (error payloads).

Design notes (TPU-native): the REST layer is pure control plane — every
handler manipulates host-side objects (frames, model keys, jobs) and the
device work happens inside the models' jitted programs.  A
ThreadingHTTPServer replaces Jetty; one process is one "cloud" (the
reference's multi-JVM cloud maps to the device mesh, not to processes).
"""

from __future__ import annotations

import json
import re
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu import __version__
from h2o3_tpu.keyed import DKV
from h2o3_tpu.util import telemetry

Route = Tuple[str, "re.Pattern[str]", List[str], Callable, str]

#: REST traffic meters. The route label is the registered *pattern*
#: (/3/Models/{model_id}), never the raw path — raw paths would explode the
#: label cardinality with every model key ever scored.
_REST_REQUESTS = telemetry.counter(
    "rest_requests_total", "REST requests served",
    labels=("method", "route", "status"),
)
_REST_SECONDS = telemetry.histogram(
    "rest_request_seconds", "REST request wall seconds",
    labels=("method", "route"),
)


class RestError(Exception):
    def __init__(self, status: int, msg: str) -> None:
        super().__init__(msg)
        self.status = status


class RequestServer:
    """Route registry (RequestServer.java:56-80)."""

    def __init__(self) -> None:
        self.routes: List[Route] = []
        #: compiled pattern text -> the original {name} path template; the
        #: request meters and the docs lint both label routes with this
        self._templates: Dict[str, str] = {}

    def register(self, method: str, path: str, handler: Callable, summary: str = "") -> None:
        """path uses {name} placeholders, e.g. /3/Models/{model_id}."""
        names = re.findall(r"\{(\w+)\}", path)
        pattern = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", path) + "$"
        )
        self.routes.append((method.upper(), pattern, names, handler, summary))
        self._templates[pattern.pattern] = path

    def templates(self) -> List[Tuple[str, str]]:
        """(method, {name}-template) of every registered route."""
        return [
            (m, self._templates.get(p.pattern, p.pattern[1:-1]))
            for m, p, _names, _handler, _summary in self.routes
        ]

    def match(
        self, method: str, path: str
    ) -> Optional[Tuple[Callable, Dict[str, str], str]]:
        """(handler, path_kwargs, route_pattern) of the first matching route;
        the pattern string is the stable low-cardinality label the request
        meters use."""
        for m, pattern, _names, handler, _ in self.routes:
            if m != method:
                continue
            mt = pattern.match(path)
            if mt:
                kw = {
                    k: urllib.parse.unquote(v)
                    for k, v in mt.groupdict().items()
                }
                # label with the {name} template the route was registered
                # under, not the compiled (?P<name>...) regex
                route = self._templates.get(
                    pattern.pattern, pattern.pattern[1:-1])
                return handler, kw, route
        return None

    def dispatch(self, method: str, path: str, params: Dict[str, Any]) -> Any:
        found = self.match(method, path)
        if found is None:
            raise RestError(404, f"no route for {method} {path}")
        handler, kw, _route = found
        return handler(params, **kw)

    def endpoints(self) -> List[Dict[str, str]]:
        return [
            {"method": m, "url_pattern": p.pattern[1:-1], "summary": s}
            for m, p, _, _, s in self.routes
        ]


#: inbound trace-context headers must look like the ids we mint (hex, 8-32
#: chars): the value is echoed back as a response header and recorded into
#: every timeline event and log line of the request, so an unvalidated
#: value would be a response-header-injection (CRLF) primitive and a
#: timeline-pollution vector
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")


def _trace_header(value: Optional[str]) -> Optional[str]:
    if value and _TRACE_ID_RE.match(value):
        return value
    return None


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return None if np.isnan(v) else v
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, float) and np.isnan(o):
        return None
    raise TypeError(f"not JSON serializable: {type(o)}")


#: URLs of servers CURRENTLY running in this process (start() adds,
#: stop() removes — a dead server's port may be reused by anything)
_LIVE_URLS: set = set()


def served_from_this_process(url: str) -> bool:
    """True if `url` is served by a live H2OServer in this process RIGHT
    NOW. Callers that need "was this endpoint ours?" later (e.g. after
    the server stops) must evaluate this at connection time and remember
    the answer — a stopped server's port can be reused by an unrelated
    external service."""
    return url.rstrip("/") in _LIVE_URLS


class H2OServer:
    """The server facade (h2o-webserver-iface HttpServerFacade analogue).

    Security (water/network + LoginType hash-file auth): ``ssl_cert``/
    ``ssl_key`` wrap the listening socket in TLS (the reference's jetty SSL
    config); ``auth_file`` — lines of ``user:sha256(password)`` — enables
    HTTP Basic auth on every route (LoginType.HASH_FILE)."""

    def __init__(
        self,
        port: int = 54321,
        name: str = "h2o3-tpu",
        ssl_cert: Optional[str] = None,
        ssl_key: Optional[str] = None,
        auth_file: Optional[str] = None,
        auth_backend=None,
        ip: str = "127.0.0.1",
    ) -> None:
        self.name = name
        #: bind address (-ip / web_ip OptArg); 0.0.0.0 for pod/container
        #: serving where probes and clients arrive on the pod IP
        self.ip = ip
        self.start_time = time.time()
        self.registry = RequestServer()
        from h2o3_tpu.api import handlers

        handlers.register_all(self.registry, self)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port = port
        self.ssl_cert = ssl_cert
        self.ssl_key = ssl_key
        #: the auth SPI (api/auth.py LoginBackend); auth_file builds the
        #: hash-file backend for back-compat, auth_backend wins when given
        self._auth = auth_backend
        if self._auth is None and auth_file:
            from h2o3_tpu.api.auth import HashFileBackend

            self._auth = HashFileBackend(auth_file)

    def _check_auth(self, header: Optional[str]) -> bool:
        if self._auth is None:
            return True
        if not header or not header.startswith("Basic "):
            return False
        import base64

        try:
            user, _, password = (
                base64.b64decode(header[6:]).decode().partition(":")
            )
        except Exception:
            return False
        return self._auth.authenticate(user, password)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "H2OServer":
        # the /3/Logs ring must be live from the first request, whether or
        # not any module logged before the server came up (satellite of the
        # telemetry PR: init() is idempotent, dir comes from H2O3_TPU_LOG_DIR)
        from h2o3_tpu.util import log as _log

        _log.init()
        registry = self.registry
        srv = self

        class Handler(BaseHTTPRequestHandler):
            server_version = f"h2o3-tpu/{__version__}"
            timeout = 120  # a dead client must not pin its thread forever

            def log_message(self, *a):  # quiet; the Log subsystem records
                pass

            def _params(self) -> Dict[str, Any]:
                parsed = urllib.parse.urlparse(self.path)
                params: Dict[str, Any] = {
                    k: v[0] if len(v) == 1 else v
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    body = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype:
                        params.update(json.loads(body))
                    elif "octet-stream" in ctype:
                        # binary upload (model files, NPS blobs): handlers
                        # read the bytes under _raw_body
                        params["_raw_body"] = body
                    else:  # h2o-py posts urlencoded forms
                        try:
                            params.update(
                                {
                                    k: v[0] if len(v) == 1 else v
                                    for k, v in urllib.parse.parse_qs(
                                        body.decode()
                                    ).items()
                                }
                            )
                        except UnicodeDecodeError:
                            params["_raw_body"] = body
                return params

            def _respond(self, method: str) -> None:
                from h2o3_tpu.util.log import get_logger

                # claim the default "Thread-N" name for this worker so the
                # profiler's housekeeping filter ("^http[-_]") can target
                # server threads precisely without hiding unnamed
                # application threads that happen to share the default name
                cur = threading.current_thread()
                if cur.name.startswith("Thread-"):
                    cur.name = "http-worker"
                parsed = urllib.parse.urlparse(self.path)
                # the request meters label by registered route pattern; an
                # unmatched path collapses into one "(unmatched)" series so
                # scanners can't mint unbounded label values
                found = registry.match(method, parsed.path)
                route = found[2] if found else "(unmatched)"
                status = 200
                ctype = "application/json"
                extra_headers: List[Tuple[str, str]] = []
                span: Optional[telemetry.Span] = None
                t0 = time.perf_counter()
                if not srv._check_auth(self.headers.get("Authorization")):
                    get_logger("rest").info("%s %s", method, parsed.path)
                    status = 401
                    payload = json.dumps(
                        {"http_status": 401, "msg": "authentication required"}
                    ).encode()
                    extra_headers.append(
                        ("WWW-Authenticate", 'Basic realm="h2o3-tpu"'))
                else:
                    # a proxied/forwarded request may carry its caller's
                    # trace: honor the headers (id-shaped values only) so
                    # one trace threads client -> this REST span -> any
                    # node RPC it fans out
                    span = telemetry.Span(
                        "rest", method=method, route=route,
                        path=parsed.path,
                        trace_id=_trace_header(
                            self.headers.get("X-H2O3-Trace-Id")),
                        parent_id=_trace_header(
                            self.headers.get("X-H2O3-Span-Id")),
                    )
                    try:
                        with span:
                            # logged INSIDE the span so the /3/Logs line
                            # carries this request's trace/span ids
                            get_logger("rest").info(
                                "%s %s", method, parsed.path)
                            if found is None:
                                raise RestError(
                                    404,
                                    f"no route for {method} {parsed.path}",
                                )
                            handler, path_kw, _ = found
                            out = handler(self._params(), **path_kw)
                        if (
                            isinstance(out, tuple) and len(out) == 2
                            and isinstance(out[0], (bytes, bytearray))
                        ):
                            payload, ctype = out
                        elif isinstance(out, (bytes, bytearray)):
                            payload, ctype = out, "application/octet-stream"
                        else:
                            payload = json.dumps(
                                out, default=_json_default).encode()
                    except RestError as e:
                        status = e.status
                        payload = json.dumps(
                            {  # water/api/schemas3/H2OErrorV3 shape
                                "http_status": e.status,
                                "msg": str(e),
                                "dev_msg": str(e),
                                "exception_type": "RestError",
                            }
                        ).encode()
                        ctype = "application/json"
                    except Exception as e:  # noqa: BLE001
                        status = 500
                        payload = json.dumps(
                            {
                                "http_status": 500,
                                "msg": f"{type(e).__name__}: {e}",
                                "dev_msg": traceback.format_exc(),
                                "exception_type": type(e).__name__,
                            }
                        ).encode()
                        ctype = "application/json"
                # account BEFORE the response flushes: a client that has
                # read its response can immediately see the request in
                # /3/Metrics (read-your-writes for the meters)
                _REST_REQUESTS.inc(
                    method=method, route=route, status=str(status))
                _REST_SECONDS.observe(
                    time.perf_counter() - t0, method=method, route=route)
                if span is not None and span.trace_id:
                    # clients correlate their request with /3/Timeline
                    extra_headers.append(("X-H2O3-Trace-Id", span.trace_id))
                self.send_response(status)
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if (urllib.parse.urlparse(self.path).path == "/3/Steam.web"
                        and "websocket" in
                        (self.headers.get("Upgrade") or "").lower()):
                    if not srv._check_auth(
                            self.headers.get("Authorization")):
                        self.send_response(401)
                        self.end_headers()
                        return
                    from h2o3_tpu.api import steam

                    steam.serve_websocket(self)
                    return
                self._respond("GET")

            def do_POST(self):
                self._respond("POST")

            def do_DELETE(self):
                self._respond("DELETE")

        self._httpd = ThreadingHTTPServer((self.ip, self.port), Handler)
        if self.ssl_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.ssl_cert, self.ssl_key)
            # lazy handshake: with do_handshake_on_connect the handshake
            # would run inside accept(), letting one stalled client block
            # the accept loop for everyone; deferred, it happens on first
            # read inside the per-connection handler thread
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
        self.port = self._httpd.server_address[1]
        # a live application-plane cloud learns where this node's REST
        # surface landed (OS-assigned ports resolve only here); gossip
        # then carries it to every member's /3/Cloud listing
        from h2o3_tpu import cluster

        _cloud = cluster.local_cloud()
        if _cloud is not None:
            _cloud.advertise_rest_port(self.port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="http-accept",  # matches /3/Profiler's "^http" filter
        )
        self._thread.start()
        # registry of live in-process servers: lets clients answer "is
        # this endpoint one of ours?" exactly at connect time, instead
        # of guessing from the address (a port-forwarded remote can
        # look like loopback)
        _LIVE_URLS.add(self.url)
        return self

    def stop(self) -> None:
        # idempotent + thread-safe: /3/Shutdown schedules a delayed stop
        # that may race the owner's own stop() call
        httpd, self._httpd = self._httpd, None
        if httpd:
            _LIVE_URLS.discard(self.url)
            httpd.shutdown()
            httpd.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self.ssl_cert else "http"
        # a wildcard bind is reachable via loopback for local clients
        host = "127.0.0.1" if self.ip in ("0.0.0.0", "::") else self.ip
        return f"{scheme}://{host}:{self.port}"


def start_server(port: int = 0, name: str = "h2o3-tpu", **kw) -> H2OServer:
    """Start a server on localhost (port 0 = OS-assigned). Keyword args
    pass through to H2OServer (ssl_cert/ssl_key/auth_file)."""
    return H2OServer(port=port, name=name, **kw).start()
