"""Steam integration: the websocket message exchange.

Reference: ``h2o-extensions/steam`` — ``SteamWebsocketServlet`` accepts
ONE websocket connection from the Steam orchestrator at ``/3/Steam.web``
and fans every parsed JSON message out to registered ``SteamMessenger``s;
``SteamHelloMessenger`` answers ``{"_type": "hello"}`` with version and
cloud facts. The transport here is a from-scratch RFC 6455 server-side
endpoint (stdlib only): handshake (Sec-WebSocket-Accept), client-masked
frame decode, text/ping/close handling.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
from typing import Callable, Dict, List, Optional

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

#: messenger registry (SteamMessenger SPI): fn(message) -> response|None
MESSENGERS: List[Callable[[Dict[str, str]], Optional[Dict[str, str]]]] = []


def messenger(fn):
    MESSENGERS.append(fn)
    return fn


@messenger
def hello_messenger(message: Dict[str, str]) -> Optional[Dict[str, str]]:
    """SteamHelloMessenger: hello -> hello_response with build facts."""
    if message.get("_type") != "hello":
        return None
    from h2o3_tpu import __version__ as _ver
    from h2o3_tpu.parallel.mesh import default_mesh

    try:
        cloud = default_mesh().devices.size
    except Exception:
        cloud = 1
    return {
        "_type": "hello_response",
        "_id": str(message.get("_id", "")) + "_response",
        "version": _ver,
        "branch": "main",
        "hash": "0" * 7,
        "cloud_size": str(cloud),
    }


def dispatch(message: Dict[str, str]) -> List[Dict[str, str]]:
    """All messengers see every message (SteamMessageExchange
    .distributeMessage); non-None returns are sent back."""
    out = []
    for fn in MESSENGERS:
        resp = fn(message)
        if resp is not None:
            out.append(resp)
    return out


def accept_key(client_key: str) -> str:
    """Sec-WebSocket-Accept for a client's Sec-WebSocket-Key (RFC 6455
    §4.2.2)."""
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(payload: bytes, opcode: int = 0x1) -> bytes:
    """One server->client frame (FIN set, unmasked)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < (1 << 16):
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


def read_frame(rfile) -> Optional[tuple]:
    """One client frame -> (opcode, payload bytes); None on EOF.
    Client frames MUST be masked (§5.1)."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    opcode = head[0] & 0x0F
    masked = head[1] & 0x80
    n = head[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", rfile.read(2))[0]
    elif n == 127:
        n = struct.unpack(">Q", rfile.read(8))[0]
    if n > (1 << 22):
        return None  # oversized control-plane frame: drop the connection
    mask = rfile.read(4) if masked else b""
    payload = rfile.read(n)
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload


def serve_websocket(handler) -> None:
    """Upgrade an http.server request to a websocket and run the Steam
    message loop until close (SteamWebsocketServlet.onWebSocketText)."""
    key = handler.headers.get("Sec-WebSocket-Key", "")
    if not key:
        handler.send_response(400)
        handler.end_headers()
        return
    handler.send_response_only(101, "Switching Protocols")
    handler.send_header("Upgrade", "websocket")
    handler.send_header("Connection", "Upgrade")
    handler.send_header("Sec-WebSocket-Accept", accept_key(key))
    handler.end_headers()
    handler.wfile.flush()
    while True:
        frame = read_frame(handler.rfile)
        if frame is None:
            break
        opcode, payload = frame
        if opcode == 0x8:  # close: echo and stop
            handler.wfile.write(encode_frame(payload, 0x8))
            break
        if opcode == 0x9:  # ping -> pong
            handler.wfile.write(encode_frame(payload, 0xA))
            continue
        if opcode != 0x1:
            continue  # binary/continuation: the exchange is text-only
        try:
            message = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        for resp in dispatch(message):
            handler.wfile.write(
                encode_frame(json.dumps(resp).encode()))
        handler.wfile.flush()
    handler.close_connection = True
