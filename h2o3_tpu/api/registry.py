"""Algorithm registry: algo name -> (ModelBuilder, Parameters).

Reference: ``hex/api/RegisterAlgos.java:16-34`` — the authoritative list of
algos exposed over REST (per-algo train routes are registered dynamically
from this list), plus the extension registrations (xgboost, targetencoder).
"""

from __future__ import annotations

from typing import Dict, Tuple, Type


def algo_map() -> Dict[str, Tuple[type, type]]:
    from h2o3_tpu.models.aggregator import Aggregator, AggregatorParameters
    from h2o3_tpu.models.coxph import CoxPH, CoxPHParameters
    from h2o3_tpu.models.deeplearning import DeepLearning, DeepLearningParameters
    from h2o3_tpu.models.ext_isolation_forest import (
        ExtendedIsolationForest,
        ExtendedIsolationForestParameters,
    )
    from h2o3_tpu.models.gam import GAM, GAMParameters
    from h2o3_tpu.models.generic import Generic, GenericParameters
    from h2o3_tpu.models.glm import GLM, GLMParameters
    from h2o3_tpu.models.glrm import GLRM, GLRMParameters
    from h2o3_tpu.models.isolation_forest import (
        IsolationForest,
        IsolationForestParameters,
    )
    from h2o3_tpu.models.kmeans import KMeans, KMeansParameters
    from h2o3_tpu.models.naive_bayes import NaiveBayes, NaiveBayesParameters
    from h2o3_tpu.models.pca import PCA, PCAParameters, SVD, SVDParameters
    from h2o3_tpu.models.psvm import PSVM, PSVMParameters
    from h2o3_tpu.models.rulefit import RuleFit, RuleFitParameters
    from h2o3_tpu.models.stacked_ensemble import (
        StackedEnsemble,
        StackedEnsembleParameters,
    )
    from h2o3_tpu.models.target_encoder import TargetEncoder, TargetEncoderParameters
    from h2o3_tpu.models.tree.drf import DRF, DRFParameters
    from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
    from h2o3_tpu.models.tree.xgboost import XGBoost, XGBoostParameters
    from h2o3_tpu.models.word2vec import Word2Vec, Word2VecParameters

    return {
        # hex/api/RegisterAlgos.java order
        "coxph": (CoxPH, CoxPHParameters),
        "deeplearning": (DeepLearning, DeepLearningParameters),
        "drf": (DRF, DRFParameters),
        "glm": (GLM, GLMParameters),
        "glrm": (GLRM, GLRMParameters),
        "kmeans": (KMeans, KMeansParameters),
        "naivebayes": (NaiveBayes, NaiveBayesParameters),
        "pca": (PCA, PCAParameters),
        "svd": (SVD, SVDParameters),
        "gbm": (GBM, GBMParameters),
        "isolationforest": (IsolationForest, IsolationForestParameters),
        "extendedisolationforest": (
            ExtendedIsolationForest,
            ExtendedIsolationForestParameters,
        ),
        "aggregator": (Aggregator, AggregatorParameters),
        "word2vec": (Word2Vec, Word2VecParameters),
        "stackedensemble": (StackedEnsemble, StackedEnsembleParameters),
        "psvm": (PSVM, PSVMParameters),
        "gam": (GAM, GAMParameters),
        "rulefit": (RuleFit, RuleFitParameters),
        "generic": (Generic, GenericParameters),
        # extensions
        "xgboost": (XGBoost, XGBoostParameters),
        "targetencoder": (TargetEncoder, TargetEncoderParameters),
    }
