"""REST route groups added in round 4 — ModelMetrics CRUD, model
import/export by URI, NPS, munging utilities, diagnostics.

Reference: ``water/api/RegisterV3Api.java`` (the route inventory),
``ModelMetricsHandler.java`` (fetch/delete/score/make),
``ModelsHandler.java`` (importModel/exportModel/uploadModel),
``NodePersistentStorageHandler.java``, ``water/util/Tabulate.java``,
``hex/Interaction.java``, ``DCTTransformer``, ``TypeaheadHandler``,
``ProfileCollectorTask`` and friends. Split from handlers.py to keep
each registration file readable; ``handlers.register_all`` calls
``register(r, server)`` here last, so these routes see the same DKV and
server facade.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.api.server import H2OServer, RequestServer, RestError
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.framework import Job, Model
from h2o3_tpu.models.metrics import ScoringRecord, make_metrics


def _schema_of_record(rec: ScoringRecord) -> Dict[str, Any]:
    from h2o3_tpu.api.handlers import _metrics_schema

    out = {
        "model": {"name": rec.model_id},
        "frame": {"name": rec.frame_id},
        "model_category": rec.model_category,
        "scoring_time": int(rec.scoring_time * 1000),
    }
    out.update(_metrics_schema(rec.metrics) or {})
    return out


def record_scoring(model: Model, frame_id: str, metrics: Any) -> None:
    """Cache a scoring result in the DKV (hex/ModelMetrics.buildKey)."""
    cat = ("Binomial" if model.nclasses == 2 else
           "Multinomial" if model.nclasses > 2 else "Regression")
    rec = ScoringRecord(model.key, frame_id, metrics, cat, time.time())
    DKV.put(ScoringRecord.key_for(model.key, frame_id), rec)


def register(r: RequestServer, server: H2OServer) -> None:  # noqa: C901
    from h2o3_tpu.api.handlers import (
        _frame_schema,
        _get_frame,
        _get_model,
        _model_schema,
    )

    # ---- ModelMetrics CRUD + score + make (ModelMetricsHandler.java) ------
    def _records(model: Optional[str] = None,
                 frame: Optional[str] = None) -> List[ScoringRecord]:
        out = []
        for k in DKV.keys_of_type(ScoringRecord):
            rec = DKV.get(k)
            if not isinstance(rec, ScoringRecord):
                continue
            if model and rec.model_id != model:
                continue
            if frame and rec.frame_id != frame:
                continue
            out.append(rec)
        return sorted(out, key=lambda rec: rec.scoring_time)

    def mm_fetch(params, model=None, frame=None):
        return {"model_metrics": [
            _schema_of_record(rec) for rec in _records(model, frame)
        ]}

    def mm_delete(params, model=None, frame=None):
        removed = []
        for rec in _records(model, frame):
            DKV.remove(ScoringRecord.key_for(rec.model_id, rec.frame_id))
            removed.append({"model": rec.model_id, "frame": rec.frame_id})
        return {"deleted": removed}

    def mm_score(params, model, frame):
        m = _get_model(model)
        fr = _get_frame(frame)
        key = ScoringRecord.key_for(model, frame)
        cached = DKV.get(key)
        force = str(params.get("force", "false")).lower() in ("true", "1")
        if not isinstance(cached, ScoringRecord) or force:
            record_scoring(m, frame, m.model_performance(fr))
            cached = DKV.get(key)
        return {"model_metrics": [_schema_of_record(cached)]}

    def mm_make(params, predictions_frame, actuals_frame):
        pf = _get_frame(predictions_frame)
        af = _get_frame(actuals_frame)
        domain = params.get("domain")
        if isinstance(domain, str):
            s = domain.strip()
            domain = (json.loads(s.replace("'", '"')) if s.startswith("[")
                      else [x for x in s.split(",") if x])
        dist = params.get("distribution") or "gaussian"
        P = np.column_stack([
            c.numeric_view() if c.type is not ColType.CAT else c.data
            for c in pf.columns
        ])
        # predictions frames from /3/Predictions lead with the label
        # column for classifiers; make_metrics handles the K+1 shape
        ac = af.columns[0]
        if ac.type is ColType.CAT:
            actual = np.asarray(ac.data, dtype=np.int64)
            if domain is None:
                domain = list(ac.domain)
        else:
            actual = ac.numeric_view()
            if domain is not None and len(domain) > 2:
                # numeric actuals under a domain are class ids; NA rows
                # must drop BEFORE the int cast (int64(NaN) is garbage,
                # not a missing marker)
                ok = ~np.isnan(actual)
                actual = actual[ok].astype(np.int64)
                P = P[ok]
            # binomial keeps float64: binomial_metrics masks NaN itself
        mm = make_metrics(P, actual, domain=domain, distribution=dist)
        from h2o3_tpu.api.handlers import _metrics_schema

        out = {"model_category": ("Binomial" if domain and len(domain) == 2
                                  else "Multinomial" if domain
                                  else "Regression")}
        out.update(_metrics_schema(mm) or {})
        return {"model_metrics": [out]}

    r.register("GET", "/3/ModelMetrics", mm_fetch, "all scoring records")
    r.register("GET", "/3/ModelMetrics/models/{model}", mm_fetch,
               "scoring records for a model")
    r.register("GET", "/3/ModelMetrics/frames/{frame}", mm_fetch,
               "scoring records for a frame")
    r.register("GET", "/3/ModelMetrics/models/{model}/frames/{frame}",
               mm_fetch, "scoring record for (model, frame)")
    r.register("GET", "/3/ModelMetrics/frames/{frame}/models/{model}",
               mm_fetch, "scoring record for (model, frame)")
    r.register("DELETE", "/3/ModelMetrics", mm_delete, "delete all records")
    r.register("DELETE", "/3/ModelMetrics/models/{model}", mm_delete,
               "delete records for a model")
    r.register("DELETE", "/3/ModelMetrics/frames/{frame}", mm_delete,
               "delete records for a frame")
    r.register("DELETE", "/3/ModelMetrics/models/{model}/frames/{frame}",
               mm_delete, "delete one record")
    r.register("DELETE", "/3/ModelMetrics/frames/{frame}/models/{model}",
               mm_delete, "delete one record")
    r.register("POST", "/3/ModelMetrics/models/{model}/frames/{frame}",
               mm_score, "score a frame, cache + return metrics")
    r.register(
        "POST",
        "/3/ModelMetrics/predictions_frame/{predictions_frame}"
        "/actuals_frame/{actuals_frame}",
        mm_make, "metrics from raw predictions + actuals (makeMetrics)")

    # ---- async predictions (POST /4/Predictions..., predictAsync) ---------
    def predict_async(params, model, frame):
        m = _get_model(model)
        fr = _get_frame(frame)
        dest = params.get("predictions_frame") or DKV.make_key("pred")
        job = Job(f"predict {model} on {frame}").start()

        def run():
            try:
                pred = m.predict(fr)
                DKV.put(dest, pred)
                try:
                    record_scoring(m, frame, m.model_performance(fr))
                except Exception:
                    pass  # response-less frames still score
                job.done()
            except Exception as e:  # noqa: BLE001
                job.fail(e)

        # named so a scoring thread reads as work in /3/Profiler and
        # /3/JStack, not as an anonymous Thread-N
        threading.Thread(
            target=run, daemon=True, name=f"job-{job.key}").start()
        return {"job": {"key": {"name": job.key}},
                "predictions_frame": {"name": dest}}

    r.register("POST", "/4/Predictions/models/{model}/frames/{frame}",
               predict_async, "async scoring job")

    # ---- model import/export by URI (ModelsHandler.java) ------------------
    def model_export(params, model_id):
        from h2o3_tpu.models.persist import save_model

        m = _get_model(model_id)
        d = os.path.expanduser(params.get("dir") or ".")
        if os.path.splitext(d)[1] != ".bin":
            os.makedirs(d, exist_ok=True)
            d = os.path.join(d, model_id)
        force = str(params.get("force", "true")).lower() in ("true", "1")
        if os.path.exists(d) and not force:
            raise RestError(409, f"{d} exists and force is false")
        return {"dir": save_model(m, d)}

    def model_import(params, model_id):
        from h2o3_tpu.models.persist import load_model

        d = os.path.expanduser(params.get("dir") or ".")
        if os.path.isdir(d):
            d = os.path.join(d, model_id)
        try:
            m = load_model(d, register=False)
        except FileNotFoundError:
            raise RestError(404, f"no model file at {d!r}")
        except Exception as e:  # corrupt / non-model file: client error
            raise RestError(400, f"model load failed: {type(e).__name__}: {e}")
        if not isinstance(m, Model):
            raise RestError(400, f"{d!r} is not a model export")
        m.key = model_id
        DKV.put(m.key, m)
        return {"models": [_model_schema(m)]}

    def model_upload(params, model_id):
        from h2o3_tpu.models.persist import load_model

        body = params.get("_raw_body")
        if not body:
            raise RestError(400, "binary model body required "
                                 "(Content-Type: application/octet-stream)")
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
            f.write(body)
            tmp = f.name
        try:
            m = load_model(tmp, register=False)
        except Exception as e:
            raise RestError(400, f"model load failed: {type(e).__name__}: {e}")
        finally:
            os.unlink(tmp)
        if not isinstance(m, Model):
            raise RestError(400, "uploaded bytes are not a model export")
        m.key = model_id
        DKV.put(m.key, m)
        return {"models": [_model_schema(m)]}

    def model_details(params, model_id):
        return {"models": [_model_schema(_get_model(model_id))]}

    def new_model_id(params, algo):
        return {"model_id": {"name": DKV.make_key(f"{algo}_model")}}

    def _pojo_lang(params) -> str:
        # one normalizer for both routes: preview must preview exactly
        # what the full route serves
        return "c" if str(params.get("lang", "java")).lower() == "c" \
            else "java"

    def model_java(params, model_id):
        m = _get_model(model_id)
        try:
            src = m.pojo(_pojo_lang(params))
        except ValueError as e:
            raise RestError(400, str(e))
        return src.encode(), "text/plain; charset=utf-8"

    def model_preview(params, model_id):
        m = _get_model(model_id)
        try:
            src = m.pojo(_pojo_lang(params))
        except ValueError as e:
            raise RestError(400, str(e))
        head = "\n".join(src.splitlines()[:60])
        return head.encode(), "text/plain; charset=utf-8"

    r.register("GET", "/3/Models.java/{model_id}", model_java,
               "POJO scoring source (java; ?lang=c for the C emitter)")
    r.register("GET", "/3/Models.java/{model_id}/preview", model_preview,
               "POJO source preview")
    r.register("GET", "/99/Models.bin/{model_id}", model_export,
               "export model binary to a server path")
    r.register("POST", "/99/Models.bin/{model_id}", model_import,
               "import model binary from a server path")
    r.register("POST", "/99/Models.upload.bin/{model_id}", model_upload,
               "upload model binary in the request body")
    r.register("GET", "/99/Models/{model_id}/json", model_details,
               "full model details as JSON")
    r.register("POST", "/3/ModelBuilders/{algo}/model_id", new_model_id,
               "mint a fresh model id")

    # ---- munging utilities: Tabulate / Interaction / DCT ------------------
    def _col_bins(c: Column, nbins: int):
        """(bin_index_per_row, labels) for a column — levels for cats,
        equal-width bins for numerics (water/util/Tabulate.java)."""
        if c.type is ColType.CAT:
            return np.asarray(c.data, dtype=np.int64), list(c.domain)
        x = c.numeric_view()
        finite = x[~np.isnan(x)]
        if finite.size == 0:
            return np.zeros(len(x), dtype=np.int64), ["NA"]
        lo, hi = float(finite.min()), float(finite.max())
        span = (hi - lo) or 1.0
        idx = np.clip(((x - lo) / span * nbins).astype(np.int64), 0,
                      nbins - 1)
        idx = np.where(np.isnan(x), -1, idx)
        labels = [f"[{lo + span * i / nbins:.4g}, "
                  f"{lo + span * (i + 1) / nbins:.4g})"
                  for i in range(nbins)]
        return idx, labels

    def tabulate(params):
        fr = _get_frame(params.get("dataset") or params.get("frame_id", ""))
        pred = params.get("predictor")
        resp = params.get("response")
        if not pred or not resp:
            raise RestError(400, "predictor and response are required")
        try:
            pc, rc = fr.col(pred), fr.col(resp)
        except KeyError as e:
            raise RestError(404, str(e))
        w = (fr.col(params["weight"]).numeric_view()
             if params.get("weight") else np.ones(fr.nrows))
        nb_p = int(params.get("nbins_predictor", 20))
        nb_r = int(params.get("nbins_response", 10))
        pi, pl = _col_bins(pc, nb_p)
        ri, rl = _col_bins(rc, nb_r)
        ok = (pi >= 0) & (ri >= 0)
        counts = np.zeros((len(pl), len(rl)))
        np.add.at(counts, (pi[ok], ri[ok]), w[ok])
        # per-predictor-bin mean response (the "response chart")
        rv = (rc.numeric_view() if rc.type is not ColType.CAT
              else np.asarray(rc.data, dtype=np.float64))
        sums = np.zeros(len(pl))
        wsum = np.zeros(len(pl))
        np.add.at(sums, pi[ok], (w * np.nan_to_num(rv))[ok])
        np.add.at(wsum, pi[ok], w[ok])
        mean_resp = np.where(wsum > 0, sums / np.maximum(wsum, 1e-300),
                             np.nan)
        return {
            "count_table": {"predictor_labels": pl, "response_labels": rl,
                            "counts": counts.tolist()},
            "response_table": {"predictor_labels": pl,
                               "mean_response": mean_resp.tolist(),
                               "counts": wsum.tolist()},
        }

    def interaction(params):
        """Categorical interaction features (hex/Interaction.java): a new
        frame with the concatenated-level column(s), trimmed to the
        most frequent ``max_factors`` levels."""
        fr = _get_frame(params.get("source_frame")
                        or params.get("dataset", ""))
        cols = params.get("factor_columns") or params.get("factors")
        if isinstance(cols, str):
            s = cols.strip()
            cols = (json.loads(s.replace("'", '"')) if s.startswith("[")
                    else [x for x in s.split(",") if x])
        if not cols or len(cols) < 2:
            raise RestError(400, "need >= 2 factor_columns")
        pairwise = str(params.get("pairwise", "false")).lower() in (
            "true", "1")
        max_factors = int(params.get("max_factors", 100))
        min_occ = int(params.get("min_occurrence", 1))

        def combine(names: List[str]) -> Column:
            srcs = []
            for n in names:
                try:
                    c = fr.col(n)
                except KeyError as e:
                    raise RestError(404, str(e))
                if c.type is not ColType.CAT:
                    raise RestError(400, f"{n!r} is not categorical")
                srcs.append(c)
            labels = []
            for i in range(fr.nrows):
                parts = [
                    (c.domain[c.data[i]] if c.data[i] >= 0 else "NA")
                    for c in srcs
                ]
                labels.append("_".join(parts))
            vals, counts = np.unique(labels, return_counts=True)
            keep = [v for v, n in sorted(zip(vals, counts),
                                         key=lambda t: (-t[1], t[0]))
                    if n >= min_occ][:max_factors]
            keep_set = set(keep)
            domain = sorted(keep_set) + ["other"]
            lut = {v: i for i, v in enumerate(domain)}
            other = lut["other"]
            ids = np.array([lut.get(s, other) for s in labels],
                           dtype=np.int64)
            return Column("_".join(names), ids, ColType.CAT, domain)

        out_cols = []
        if pairwise:
            for i in range(len(cols)):
                for j in range(i + 1, len(cols)):
                    out_cols.append(combine([cols[i], cols[j]]))
        else:
            out_cols.append(combine(cols))
        dest = (params.get("dest") or params.get("destination_frame")
                or DKV.make_key("interaction"))
        out = Frame(out_cols)
        out.key = dest
        DKV.put(dest, out)
        job = Job(f"interaction {dest}").start()
        job.done()
        return {"job": {"key": {"name": job.key}},
                "destination_frame": {"name": dest},
                "domains": [c.domain for c in out_cols]}

    def dct_transform(params):
        """Orthonormal DCT-II over each row reshaped to (h, w, depth) —
        the reference's MXNet-backed DCTTransformer, here via scipy."""
        fr = _get_frame(params.get("dataset") or params.get("frame_id", ""))
        dims = params.get("dimensions", "[0,0,0]")
        if isinstance(dims, str):
            dims = json.loads(dims)
        dims = [int(x) for x in dims]
        while len(dims) < 3:
            dims.append(1)
        h, w, d = (x or 1 for x in dims[:3])
        need = h * w * d
        if need != fr.ncols:
            raise RestError(
                400, f"dimensions {h}x{w}x{d}={need} != ncols {fr.ncols}")
        from scipy.fft import dctn

        X = np.column_stack([c.numeric_view() for c in fr.columns])
        tens = X.reshape(fr.nrows, h, w, d)
        out = dctn(tens, axes=(1, 2, 3), norm="ortho").reshape(
            fr.nrows, need)
        dest = (params.get("destination_frame")
                or DKV.make_key("dct"))
        cols = [Column(f"DCT_{i}", out[:, i].astype(np.float64))
                for i in range(need)]
        of = Frame(cols)
        of.key = dest
        DKV.put(dest, of)
        return {"destination_frame": {"name": dest}}

    r.register("POST", "/99/Tabulate", tabulate, "co-occurrence tables")
    r.register("POST", "/3/Interaction", interaction,
               "categorical interaction column(s)")
    r.register("POST", "/99/DCTTransformer", dct_transform,
               "row-wise orthonormal DCT")

    # ---- node-persistent storage (8 routes) -------------------------------
    from h2o3_tpu.util import nps

    def nps_put_named(params, category, name):
        body = params.get("_raw_body")
        if body is None:
            body = (params.get("value") or "").encode()
        return nps.put(category, name, body)

    def nps_put(params, category):
        name = nps.new_name()
        out = nps_put_named(params, category, name)
        return out

    def nps_get(params, category, name):
        try:
            return nps.get(category, name), "application/octet-stream"
        except FileNotFoundError:
            raise RestError(404, f"no NPS value {category}/{name}")

    r.register("GET", "/3/NodePersistentStorage/configured",
               lambda p: {"configured": nps.configured()}, "NPS configured?")
    r.register("GET",
               "/3/NodePersistentStorage/categories/{category}/exists",
               lambda p, category: {"exists": nps.exists(category)},
               "NPS category exists?")
    r.register(
        "GET",
        "/3/NodePersistentStorage/categories/{category}/names/{name}/exists",
        lambda p, category, name: {"exists": nps.exists(category, name)},
        "NPS entry exists?")
    r.register("POST", "/3/NodePersistentStorage/{category}/{name}",
               nps_put_named, "store a named NPS value")
    r.register("POST", "/3/NodePersistentStorage/{category}", nps_put,
               "store an NPS value under a fresh name")
    r.register("GET", "/3/NodePersistentStorage/{category}/{name}", nps_get,
               "read an NPS value")
    r.register("DELETE", "/3/NodePersistentStorage/{category}/{name}",
               lambda p, category, name: {
                   "deleted": nps.delete(category, name)},
               "delete an NPS value")
    r.register("GET", "/3/NodePersistentStorage/{category}",
               lambda p, category: {"entries": nps.list_entries(category)},
               "list NPS entries")

    # ---- frame drill-down -------------------------------------------------
    def _find_col(fr: Frame, column: str) -> Column:
        try:
            return fr.col(column)
        except KeyError as e:
            raise RestError(404, str(e))

    def frame_column(params, frame_id, column):
        fr = _get_frame(frame_id)
        _find_col(fr, column)  # 404 before paging
        off = int(params.get("row_offset", 0))
        n = int(params.get("row_count", 100))
        sub = fr.rows(np.arange(off, min(off + n, fr.nrows))).cols([column])
        return _frame_schema(sub, frame_id, rows=n)

    def frame_column_summary(params, frame_id, column):
        c = _find_col(_get_frame(frame_id), column)
        out: Dict[str, Any] = {"label": c.name,
                               "type": c.type.name.lower(),
                               "missing_count": int(c.na_count())}
        if c.type in (ColType.NUM, ColType.TIME):
            x = c.numeric_view()
            fin = x[~np.isnan(x)]
            if fin.size:
                qs = np.percentile(
                    fin, [0.1, 1, 10, 25, 33.3, 50, 66.7, 75, 90, 99, 99.9])
                out.update({
                    "mins": np.sort(fin)[:5].tolist(),
                    "maxs": np.sort(fin)[-5:][::-1].tolist(),
                    "mean": float(fin.mean()),
                    "sigma": float(fin.std(ddof=1)) if fin.size > 1 else 0.0,
                    "percentiles": qs.tolist(),
                    "histogram_bins": np.histogram(fin, bins=20)[0].tolist(),
                })
        elif c.type is ColType.CAT:
            ids = np.asarray(c.data)
            counts = np.bincount(ids[ids >= 0], minlength=len(c.domain))
            out["domain"] = c.domain
            out["domain_counts"] = counts.tolist()
        return {"frames": [{"frame_id": {"name": frame_id},
                            "columns": [out]}]}

    def frame_column_domain(params, frame_id, column):
        c = _find_col(_get_frame(frame_id), column)
        if c.type is not ColType.CAT:
            raise RestError(400, f"{column!r} is not categorical")
        return {"domain": [c.domain], "map_keys": {"string": c.domain}}

    def frame_light(params, frame_id):
        fr = _get_frame(frame_id)
        return {"frames": [{
            "frame_id": {"name": frame_id},
            "rows": fr.nrows, "num_columns": fr.ncols,
            "column_names": fr.names,
            "byte_size": sum(getattr(c.data, "nbytes", 0)
                             for c in fr.columns),
            "is_text": False,
        }]}

    def frame_chunks(params, frame_id):
        fr = _get_frame(frame_id)
        # host-resident columnar layout: one logical chunk per column
        # (device sharding is owned by FrameTable/Mesh, not the catalog)
        return {"chunks": [
            {"column": c.name,
             "chunk_count": 1,
             "byte_size": int(getattr(c.data, "nbytes", 0))}
            for c in fr.columns
        ]}

    def find_in_frame(params):
        fr = _get_frame(params.get("key", ""))
        col = params.get("column")
        row = int(params.get("row", 0))
        match = params.get("match")
        cols = [_find_col(fr, col)] if col else fr.columns
        prev_hit, next_hit = -1, -1
        for c in cols:
            if c.type is ColType.CAT:
                try:
                    want = c.domain.index(match)
                except ValueError:
                    continue
                hits = np.flatnonzero(np.asarray(c.data) == want)
            elif c.type is ColType.STR:
                hits = np.array([i for i, v in enumerate(c.data)
                                 if v is not None and str(v) == match])
            else:
                if match is None:
                    continue
                try:
                    want_f = float(match)
                except ValueError:
                    continue
                hits = np.flatnonzero(c.numeric_view() == want_f)
            before = hits[hits < row]
            after = hits[hits >= row]
            if before.size:
                prev_hit = max(prev_hit, int(before[-1]))
            if after.size:
                next_hit = (int(after[0]) if next_hit < 0
                            else min(next_hit, int(after[0])))
        return {"prev": prev_hit, "next": next_hit}

    def download_bin(params):
        out = r.dispatch("GET", "/3/DownloadDataset", params)
        return out, "application/octet-stream"

    r.register("GET", "/3/Frames/{frame_id}/columns/{column}", frame_column,
               "one column with a data page")
    r.register("GET", "/3/Frames/{frame_id}/columns/{column}/summary",
               frame_column_summary, "column rollups + percentiles")
    r.register("GET", "/3/Frames/{frame_id}/columns/{column}/domain",
               frame_column_domain, "categorical levels")
    r.register("GET", "/3/Frames/{frame_id}/light", frame_light,
               "frame header without data")
    r.register("GET", "/3/FrameChunks/{frame_id}", frame_chunks,
               "chunk layout")
    r.register("GET", "/3/Find", find_in_frame, "find a value in a frame")
    r.register("GET", "/3/DownloadDataset.bin", download_bin,
               "frame as csv (binary endpoint)")

    # ---- cluster ops ------------------------------------------------------
    def dkv_delete(params, key):
        # existence through the ROUTED get (not local __contains__): on a
        # multi-node cloud the key may live on its remote home — the same
        # node that GET /3/DKV/{key} would happily answer from
        sentinel = object()
        if DKV.get(key, sentinel) is sentinel:
            raise RestError(404, f"no key {key!r}")
        DKV.remove(key)
        return {"key": {"name": key}}

    def dkv_delete_all(params):
        skipped = []
        for k in list(DKV.keys()):
            try:
                DKV.remove(k)
            except ValueError:
                skipped.append(k)
        return {"skipped_locked": skipped}

    def _dkv_home(key):
        """The key's home node name when a multi-node cloud is live."""
        router = DKV.router
        if router is not None and router.active():
            return router.home_name(key)
        return None

    def dkv_get(params, key):
        """Read one key THROUGH the distributed router: on a multi-node
        cloud this answers identically from every member, wherever the
        key is homed."""
        sentinel = object()
        v = DKV.get(key, sentinel)
        if v is sentinel:
            raise RestError(404, f"no key {key!r}")
        if not isinstance(v, (str, int, float, bool, list, dict, type(None))):
            v = repr(v)  # frames/models: identity, not payload
        return {"key": {"name": key}, "value": v, "home": _dkv_home(key)}

    def dkv_put(params, key):
        """Store a JSON value under a key — routed to its home node when
        a multi-node cloud is live (``replicas`` fans copies to the ring
        successors)."""
        if "value" not in params:
            raise RestError(400, "missing 'value'")
        try:
            replicas = int(params.get("replicas", 1))
        except (TypeError, ValueError):
            raise RestError(400, "replicas must be an integer")
        DKV.put(key, params["value"], replicas=replicas)
        return {"key": {"name": key}, "home": _dkv_home(key)}

    def dkv_home(params, key):
        """Where a key lives (home + replica candidates) — the Key.home()
        introspection the multi-node tests steer by."""
        router = DKV.router
        if router is None or not router.active():
            return {"key": {"name": key}, "home": None, "replicas": [],
                    "local": True}
        homes = [m.info.name for m in router.home_members(key, 3)]
        return {"key": {"name": key},
                "home": homes[0] if homes else None,
                "replicas": homes[1:],
                "local": router.is_home(key)}

    def _faults_mod():
        """The chaos plane's REST half — 403 unless the process opted in
        (H2O3_TPU_FAULTS=1 / a fault-plan env): nemesis scripts steer a
        test node, production nodes refuse the surface outright."""
        from h2o3_tpu.cluster import faults

        if not faults.surface_enabled():
            raise RestError(
                403, "fault injection disabled (set H2O3_TPU_FAULTS=1)")
        return faults

    def faults_get(params):
        faults = _faults_mod()
        plan = faults.active_plan()
        return {"plan": plan.to_dict() if plan is not None else None,
                "hits": plan.hits() if plan is not None else []}

    def faults_set(params):
        faults = _faults_mod()
        try:
            plan = faults.plan_from_dict(params or {})
        except (TypeError, ValueError) as e:
            raise RestError(400, f"bad fault plan: {e}")
        faults.set_plan(plan)
        return {"installed": True, "seed": plan.seed,
                "rules": len(plan.rules)}

    def faults_clear(params):
        _faults_mod().clear_plan()
        return {"cleared": True}

    def log_and_echo(params):
        from h2o3_tpu.util.log import get_logger

        msg = params.get("message", "")
        get_logger("echo").info("%s", msg)
        return {"message": msg}

    def kill_minus_3(params):
        # the reference sends SIGQUIT to itself to dump stacks to stdout;
        # here the dump goes to the Log subsystem
        from h2o3_tpu.util.log import get_logger

        log = get_logger("jstack")
        for t in r.dispatch("GET", "/3/JStack", params)["traces"]:
            log.info("thread %s%s", t["thread"],
                     " (daemon)" if t["daemon"] else "")
            for chunk in t["stack"]:
                for ln in chunk.rstrip().splitlines():
                    log.info("%s", ln)
        return {}

    def unlock_keys(params):
        DKV.unlock_all()
        return {}

    def cloud_lock(params):
        # single-control-plane cloud: the membership set is fixed at mesh
        # init, so the cloud is ALWAYS locked; record the caller's reason
        from h2o3_tpu.util.log import get_logger

        get_logger("cloud").info(
            "cloud lock requested: %s", params.get("reason", ""))
        return {"locked": True}

    def network_test(params):
        # ICI/DCN byte-moving lives in XLA collectives; the REST-visible
        # network is the loopback control plane — measure that honestly
        import socket

        sizes = [1, 1024, 1024 * 1024]
        n_round = 10
        results = []
        for sz in sizes:
            payload = b"x" * sz
            a, b = socket.socketpair()

            # drain concurrently: sendall on a full socketpair buffer
            # would deadlock a single-threaded echo loop
            def drain(sock=b, total=sz * n_round):
                got = 0
                while got < total:
                    data = sock.recv(1 << 20)
                    if not data:
                        break
                    got += len(data)

            t = threading.Thread(target=drain, daemon=True)
            t.start()
            t0 = time.perf_counter()
            for _ in range(n_round):
                a.sendall(payload)
            t.join(timeout=10)
            dt = (time.perf_counter() - t0) / n_round
            a.close(); b.close()
            results.append({"bytes": sz,
                            "microseconds": round(dt * 1e6, 1),
                            "mb_per_sec": round(sz / max(dt, 1e-9) / 1e6, 1)})
        return {"table": results, "nodes": ["localhost"]}

    def watermeter_io(params, nodeidx=None):
        try:
            with open("/proc/self/io") as f:
                kv = dict(line.strip().split(": ") for line in f)
            return {"persist_stats": [{
                "read_bytes": int(kv.get("read_bytes", 0)),
                "write_bytes": int(kv.get("write_bytes", 0)),
                "syscr": int(kv.get("syscr", 0)),
                "syscw": int(kv.get("syscw", 0)),
            }], "available": True}
        except OSError:
            return {"persist_stats": [], "available": False}

    def _cluster_node(nodeidx):
        """(cloud, member) for a node-addressed route, or (None, None)
        when no multi-node cloud is live (single-node: index 0 is us).
        The index addresses the canonical sorted member list — the same
        order /3/Cloud's ``nodes`` array reports."""
        from h2o3_tpu import cluster

        c = cluster.active_cloud()
        try:
            idx = int(nodeidx)
        except (TypeError, ValueError):
            # the route pattern matches any non-slash segment: a
            # non-numeric index is a 404, not an int() 500
            raise RestError(404, f"no node {nodeidx!r}")
        if c is None:
            if idx != 0 and idx != -1:
                raise RestError(404, f"no node {idx} (cloud of 1)")
            return None, None
        members = c.members_sorted()
        if not (-1 <= idx < len(members)):
            raise RestError(
                404, f"no node {idx} (cloud has {len(members)} members)")
        member = members[idx] if idx >= 0 else c.local_member()
        return c, member

    def _node_rpc(c, member, method, payload=None):
        """Proxy one built-in RPC to an addressed member, mapping
        transport failures onto REST status codes (502: the member is
        there but unreachable — exactly what the caller asked about)."""
        from h2o3_tpu.cluster import RPCError, RemoteError

        try:
            # retries=1: an HTTP worker is waiting — bound the worst
            # case near the timeout instead of 4x it
            return c.client.call(
                member.info.addr, method, payload,
                timeout=10.0, target=member.info.ident, retries=1)
        except RemoteError as e:
            raise RestError(e.code if e.code >= 400 else 500, e.msg)
        except RPCError as e:
            raise RestError(
                502, f"node {member.info.ident} unreachable: {e}")

    def watermeter_cpu_node(params, nodeidx):
        c, member = _cluster_node(nodeidx)
        if c is None or member.info.name == c.info.name:
            return r.dispatch("GET", "/3/WaterMeterCpuTicks", params)
        return _node_rpc(c, member, "cpu_ticks")

    def logs_node_file(params, nodeidx, name):
        c, member = _cluster_node(nodeidx)
        if c is None or member.info.name == c.info.name:
            from h2o3_tpu.util import log as L

            L.init()
            return ("\n".join(L.recent(10000)) + "\n").encode(), "text/plain"
        got = _node_rpc(c, member, "logs", {"count": 10000})
        return ("\n".join(got.get("lines", [])) + "\n").encode(), "text/plain"

    def timeline_node(params, nodeidx):
        """One member's event ring, unmerged and on its own clock (the
        per-node drill-down under the merged /3/Timeline?cluster=true) —
        proxied over node RPC exactly like /3/Logs/nodes/{i}.  The self
        index answers in-process with the SAME shape the RPC returns
        (events/total_events/now_ns/node), so clients can compare clocks
        across members without special-casing the serving node."""
        from h2o3_tpu.util import telemetry, timeline

        c, member = _cluster_node(nodeidx)
        n = int(params.get("count", params.get("n", 1000)))
        if c is None or member.info.name == c.info.name:
            out = timeline.snapshot_payload(n)
            out["node"] = (c.info.name if c is not None
                           else telemetry.node_name() or "localhost")
            return out
        return _node_rpc(c, member, "timeline_snapshot", {"count": n})

    r.register("DELETE", "/3/DKV/{key}", dkv_delete, "remove one key")
    r.register("DELETE", "/3/DKV", dkv_delete_all, "remove all keys")
    r.register("GET", "/3/DKV/{key}", dkv_get,
               "read one key (routed to its home node)")
    r.register("POST", "/3/DKV/{key}", dkv_put,
               "store a JSON value (routed to its home node)")
    r.register("GET", "/3/DKV/{key}/home", dkv_home,
               "key home + replica placement")
    r.register("GET", "/3/Faults", faults_get,
               "active fault plan + per-rule hit counts (test-only)")
    r.register("POST", "/3/Faults", faults_set,
               "install a fault plan on this node (test-only)")
    r.register("DELETE", "/3/Faults", faults_clear,
               "clear the active fault plan (test-only)")
    r.register("POST", "/3/LogAndEcho", log_and_echo, "log a message")
    r.register("GET", "/3/KillMinus3", kill_minus_3,
               "dump thread stacks to the log")
    r.register("POST", "/3/UnlockKeys", unlock_keys, "drop all read locks")
    r.register("POST", "/3/CloudLock", cloud_lock, "lock cloud membership")
    r.register("GET", "/3/NetworkTest", network_test,
               "loopback control-plane throughput")
    r.register("GET", "/3/WaterMeterIo", watermeter_io, "io counters")
    r.register("GET", "/3/WaterMeterIo/{nodeidx}", watermeter_io,
               "io counters (node)")
    r.register("GET", "/3/WaterMeterCpuTicks/{nodeidx}", watermeter_cpu_node,
               "cpu ticks (node)")
    r.register("GET", "/3/Logs/nodes/{nodeidx}/files/{name}", logs_node_file,
               "log file for a node")
    r.register("GET", "/3/Timeline/nodes/{nodeidx}", timeline_node,
               "event timeline of one addressed member (node RPC proxy)")

    # ---- typeahead / rapids help / capabilities / misc --------------------
    def typeahead_files(params):
        import glob as _glob

        src = os.path.expanduser(params.get("src", ""))
        limit = int(params.get("limit", 100))
        if os.path.isdir(src):
            pattern = os.path.join(src, "*")
        else:
            pattern = src + "*"
        matches = sorted(_glob.glob(pattern))[:max(limit, 0)]
        return {"src": src, "matches": matches}

    def rapids_help(params):
        from h2o3_tpu.rapids.prims import PRIMS

        sigs = []
        for name in sorted(PRIMS):
            fn = PRIMS[name]
            doc = (fn.__doc__ or "").strip().splitlines()
            sigs.append({"name": name,
                         "description": doc[0] if doc else ""})
        return {"syntaxes": sigs}

    def capabilities_core(params):
        return {"capabilities": [
            {"name": n} for n in
            ("frames", "rapids", "models", "grid", "automl", "persist",
             "recovery", "timeline", "mesh-sharding", "pallas-kernels",
             "parse-xls-biff")
        ]}

    def capabilities_api(params):
        return {"capabilities": [
            {"name": f"{m} {p.pattern[1:-1]}"}
            for m, p, _n, _h, _s in r.routes
        ]}

    r.register("GET", "/3/Typeahead/files", typeahead_files,
               "file path suggestions")
    r.register("GET", "/99/Rapids/help", rapids_help, "rapids primitives")
    r.register("GET", "/3/Capabilities/Core", capabilities_core,
               "core capabilities")
    r.register("GET", "/3/Capabilities/API", capabilities_api,
               "REST capabilities")
    r.register("GET", "/99/Sample", lambda p: {
        "status": "experimental example endpoint"}, "sample endpoint")
    r.register("GET", "/3/SteamMetrics", lambda p: {
        "malloced_bytes": DKV.resident_frame_bytes()}, "steam metrics")

    # ---- grid import/export by reference URI ------------------------------
    def grid_bin_export(params, grid_id):
        return r.dispatch("POST", f"/99/Grids/{grid_id}/export", params)

    def grid_bin_import(params):
        return r.dispatch("POST", "/99/Grids/import", params)

    r.register("POST", "/3/Grid.bin/{grid_id}/export", grid_bin_export,
               "export grid (reference URI)")
    r.register("POST", "/3/Grid.bin/import", grid_bin_import,
               "import grid (reference URI)")

    # ---- metadata drill-down ----------------------------------------------
    def endpoint_meta(params, path):
        eps = r.endpoints()
        try:
            num = int(path)
            if not 0 <= num < len(eps):
                raise RestError(404, f"no endpoint #{num}")
            return {"routes": [eps[num]]}
        except ValueError:
            hits = [e for e in eps if path in e["url_pattern"]]
            if not hits:
                raise RestError(404, f"no endpoint matching {path!r}")
            return {"routes": hits}

    def schema_class_meta(params, classname):
        return r.dispatch(
            "GET", f"/3/Metadata/schemas/{classname}", params)

    r.register("GET", "/3/Metadata/endpoints/{path}", endpoint_meta,
               "endpoint metadata by number or substring")
    r.register("GET", "/3/Metadata/schemaclasses/{classname}",
               schema_class_meta, "schema metadata by class name")

    # ---- profiler (ProfileCollectorTask -> /3/Profiler; TPU half:
    # jax.profiler trace toggle) --------------------------------------------
    def profiler_ep(params):
        from h2o3_tpu.util import profiler, telemetry

        # default filter drops ONLY the server's own threads — the accept
        # loop ("http-accept") and request workers ("http-worker", named by
        # the handler); application threads, even unnamed ones, stay
        # visible. exclude="" disables, any other value is a name regex;
        # the applied filter is echoed so nothing is hidden silently
        exclude = params.get("exclude", r"^http[-_]")
        duration = float(params.get("duration", 0.25))
        depth = int(params.get("depth", 10))
        cluster_q = str(params.get("cluster", "")).lower() in (
            "1", "true", "yes", "on")
        if cluster_q:
            from h2o3_tpu import cluster as _cluster

            c = _cluster.active_cloud()
            if c is not None:
                return _profiler_cluster(c, duration, depth, exclude)
            # no live cloud: the single-node answer, flagged complete
        from h2o3_tpu.cluster import health as _health

        return {"nodes": [{
            "node_name": telemetry.node_name() or "localhost",
            "exclude": exclude,
            "health": _health.summary(),
            "profile": profiler.collect(
                duration_s=duration, depth=depth, exclude=exclude or None),
        }]}

    def _profiler_cluster(c, duration, depth, exclude):
        """Federate the sampling profiler exactly the way /3/Metrics was:
        scrape every member (profiler_snapshot RPC — each samples for
        ``duration``), node-tag the collapsed stacks, append a
        ``_cluster`` aggregate, and degrade to ``partial: true`` — never
        5xx — when a member is unreachable."""
        results, errors = c.poll_members(
            "profiler_snapshot",
            {"duration": duration, "depth": depth, "exclude": exclude},
            timeout=duration + 5.0,
        )
        nodes = []
        agg: Dict[tuple, int] = {}
        for name in sorted(results):
            snap = results[name] or {}
            prof = snap.get("profile") or []
            nodes.append({
                "node_name": name, "exclude": exclude,
                # each member's watchdog verdict rode the profiler_snapshot
                # payload — no second RPC to answer "is this node ok?"
                "health": snap.get("health"), "profile": prof})
            for entry in prof:
                key = tuple(entry.get("stacktrace") or ())
                agg[key] = agg.get(key, 0) + int(entry.get("count", 0))
        total = sum(agg.values())
        # per-node pct is sweeps-presence and cannot merge exactly, so
        # the aggregate's pct is each stack's share of cluster samples
        merged = [
            {"stacktrace": list(k), "count": v,
             "pct": round(100.0 * v / total, 1) if total else 0.0}
            for k, v in sorted(agg.items(), key=lambda kv: -kv[1])[:50]
        ]
        nodes.append({
            "node_name": "_cluster", "exclude": exclude, "profile": merged})
        return {
            "nodes": nodes,
            "partial": bool(errors),
            "errors": {k: errors[k] for k in sorted(errors)},
        }

    def profiler_trace(params):
        from h2o3_tpu.util.profiler import TRACE

        action = params.get("action", "")
        try:
            if action == "start":
                d = params.get("dir") or os.path.join(
                    os.environ.get("H2O3_TPU_ICE_ROOT", "/tmp"),
                    f"jax_trace_{int(time.time())}")
                return TRACE.start(d)
            if action == "stop":
                return TRACE.stop()
        except RuntimeError as e:
            raise RestError(409, str(e))
        raise RestError(400, "action must be 'start' or 'stop'")

    r.register("GET", "/3/Profiler", profiler_ep, "sampled python stacks")
    r.register("POST", "/3/Profiler/trace", profiler_trace,
               "toggle jax.profiler trace capture")
