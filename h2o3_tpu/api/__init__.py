"""REST API v3 — the wire surface clients speak.

Reference: ``water/api/`` (~25k LoC: RequestServer route registry +
dispatch, RequestServer.java:56-80,241; 125 v3 endpoints registered in
RegisterV3Api.java; schema/handler pattern under api/schemas3/), served by
the ``h2o-webserver-iface`` facade over Jetty.

TPU-native: a threaded stdlib HTTP server (the cluster control plane is
host-side Python; device compute stays in jitted programs), the same
versioned route layout (/3/..., /99/Rapids), JSON responses shaped like the
reference's schema objects so h2o-py-style clients port over.
"""

from h2o3_tpu.api.server import H2OServer, start_server

__all__ = ["H2OServer", "start_server"]
