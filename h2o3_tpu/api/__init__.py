"""REST API v3 — the wire surface clients speak.

Reference: ``water/api/`` (~25k LoC: RequestServer route registry +
dispatch, RequestServer.java:56-80,241; 125 v3 endpoints registered in
RegisterV3Api.java; schema/handler pattern under api/schemas3/), served by
the ``h2o-webserver-iface`` facade over Jetty.

TPU-native: an asyncio event-loop front-end (``server.py``) with admission
control (connection cap, per-route budgets, bounded queue — overload sheds
429 + Retry-After) and coalesced batched scoring (``coalesce.py``: same-model
predictions collect for a window and execute as one devcache-warm dispatch);
the cluster control plane is host-side Python, device compute stays in
jitted programs.  The same versioned route layout (/3/..., /99/Rapids) and
JSON responses shaped like the reference's schema objects so h2o-py-style
clients port over.  The earlier thread-per-connection transport survives in
``server_threaded.py`` as the serving-bench baseline.
"""

from h2o3_tpu.api.server import H2OServer, start_server

__all__ = ["H2OServer", "start_server"]
