"""Round-4 batch 2: model analysis (feature interactions, Friedman–
Popescu H, fetchable PDPs), frame export by URI, remaining ingest route
forms, and the Assembly pipeline.

Reference: ``ModelsHandler.makeFeatureInteraction`` (/3/FeatureInteraction),
``makeFriedmansPopescusH`` (/3/FriedmansPopescusH), ``fetchPartialDependence``
(GET /3/PartialDependence/{name}), ``FramesHandler.export``,
``ImportFilesHandler`` multi/GET forms, ``ParseSVMLight``,
``DecryptionSetup``/Hive (module-gated), ``AssemblyHandler``.
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Any, Dict, List

import numpy as np

from h2o3_tpu.api.server import H2OServer, RequestServer, RestError
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import DKV


class PDPResult:
    """DKV-resident partial-dependence result (fetchable by name)."""

    def __init__(self, payload: Dict[str, Any]) -> None:
        self.payload = payload


def _parse_list(value) -> List[str]:
    """Query/body value -> list of strings: proper JSON first, the
    python-repr fallback second, else comma split (one shared parser —
    the quote-swap-only variant corrupts legitimate apostrophes)."""
    if isinstance(value, (list, tuple)):
        return list(value)
    s = (value or "").strip()
    if s.startswith("["):
        try:
            return json.loads(s)
        except json.JSONDecodeError:
            try:
                return json.loads(s.replace("'", '"'))
            except json.JSONDecodeError:
                raise RestError(
                    400, f"cannot parse list value {s[:80]!r}: use JSON "
                         f"or comma-separated tokens")
    return [x for x in s.split(",") if x]


def register(r: RequestServer, server: H2OServer) -> None:  # noqa: C901
    from h2o3_tpu.api.handlers import _get_frame, _get_model

    # ---- fetchable PDP ----------------------------------------------------
    def fetch_pdp(params, name):
        v = DKV.get(name)
        if not isinstance(v, PDPResult):
            raise RestError(404, f"no partial dependence under {name!r}")
        return v.payload

    r.register("GET", "/3/PartialDependence/{name}", fetch_pdp,
               "fetch a stored PDP by name")

    # ---- feature interactions (tree models) -------------------------------
    def feature_interaction(params):
        """Pairwise split-adjacency interaction counts: a parent split on
        f1 whose child splits on f2 is one (f1, f2) interaction
        (FeatureInteraction.java's depth-1 path statistic)."""
        from h2o3_tpu.models.tree.common import (
            TreeModelBase,
            tree_feature_names,
        )

        m = _get_model(params.get("model_id", ""))
        if not isinstance(m, TreeModelBase):
            raise RestError(400, f"{m.algo_name} is not a tree model")
        # this statistic is depth-1 adjacency only, so the reference's
        # max_interaction_depth (path length) does not apply; top_n caps
        # the RESPONSE size explicitly instead of overloading it
        top_n = int(params.get("top_n", 100))
        names = tree_feature_names(m.data_info, m.tree_encoding)
        pair_counts: Dict[tuple, int] = {}
        single_counts: Dict[int, int] = {}
        for trees in m.booster.trees_per_class:
            for t in range(trees.ntrees):
                feat = trees.feat[t]
                sp = trees.is_split[t]
                M = len(feat)
                for i in range(M):
                    if not sp[i]:
                        continue
                    f1 = int(feat[i])
                    single_counts[f1] = single_counts.get(f1, 0) + 1
                    for child in (2 * i + 1, 2 * i + 2):
                        if child < M and sp[child]:
                            pair = tuple(sorted((f1, int(feat[child]))))
                            pair_counts[pair] = pair_counts.get(pair, 0) + 1
        ranked = sorted(pair_counts.items(), key=lambda kv: -kv[1])[:top_n]
        return {
            "feature_interaction": [
                {"feature_pair": f"{names[a]}|{names[b]}",
                 "interaction_count": n}
                for (a, b), n in ranked
            ],
            "split_counts": {names[f]: n for f, n in sorted(
                single_counts.items(), key=lambda kv: -kv[1])},
        }

    r.register("POST", "/3/FeatureInteraction", feature_interaction,
               "pairwise split interactions")

    # ---- Friedman–Popescu H -----------------------------------------------
    def friedmans_h(params):
        """H² statistic for a variable pair: the variance of the joint
        partial dependence not explained by the additive parts
        (hex/tree/FriedmanPopescusH.java), estimated over a row sample."""
        m = _get_model(params.get("model_id", ""))
        fr = _get_frame(params.get("frame", params.get("frame_id", "")))
        variables = _parse_list(params.get("variables") or [])
        if len(variables) != 2:
            raise RestError(400, "variables must name exactly 2 columns")
        va, vb = variables
        for v in variables:
            if v not in fr.names:
                raise RestError(404, f"column {v!r} not in frame")
        n_sample = min(int(params.get("n_sample", 50)), fr.nrows)
        rng = np.random.default_rng(42)
        rows = rng.choice(fr.nrows, size=n_sample, replace=False)
        sub = fr.rows(np.sort(rows))

        if getattr(m, "nclasses", 1) > 2:
            # per-class H needs the reference's full per-class sweep;
            # narrowing to one class silently would mislead
            raise RestError(
                400, "FriedmansPopescusH supports regression and binomial "
                     "models only in this build")

        def raw_margin(frame: Frame) -> np.ndarray:
            p = m._predict_raw(frame)
            return p[:, -1] if p.ndim == 2 else p

        def pd_over(cols_fixed: List[str]) -> np.ndarray:
            """PD(x_S) at each sample point in ONE prediction: block i of
            an [n_sample², ...] frame pins the S-columns to sample i's
            values over a full copy of the sample; the block mean is
            PD(x_S = sample_i)."""
            n2 = n_sample * n_sample
            cols = []
            for c in sub.columns:
                if c.name in cols_fixed:
                    data = np.repeat(c.data, n_sample)  # [i..i..] blocks
                else:
                    data = np.tile(c.data, n_sample)
                cols.append(Column(c.name, data, c.type, c.domain))
            margins = raw_margin(Frame(cols)).reshape(n_sample, n_sample)
            assert margins.size == n2
            return np.nanmean(margins, axis=1)

        pd_ab = pd_over([va, vb])
        pd_a = pd_over([va])
        pd_b = pd_over([vb])
        pd_ab -= pd_ab.mean()
        pd_a -= pd_a.mean()
        pd_b -= pd_b.mean()
        denom = float((pd_ab ** 2).sum())
        h2 = (float(((pd_ab - pd_a - pd_b) ** 2).sum()) / denom
              if denom > 0 else 0.0)
        return {"h": float(np.sqrt(max(h2, 0.0))), "h_squared": h2,
                "variables": [va, vb], "n_sample": n_sample}

    r.register("POST", "/3/FriedmansPopescusH", friedmans_h,
               "Friedman-Popescu H statistic for a variable pair")

    # ---- frame export by URI ----------------------------------------------
    def _export_frame(frame_id: str, path: str,
                      force: bool) -> Dict[str, Any]:
        _get_frame(frame_id)  # 404 before touching the filesystem
        path = os.path.expanduser(path)
        if os.path.exists(path) and not force:
            raise RestError(409, f"{path} exists and force is false")
        csv = r.dispatch("GET", "/3/DownloadDataset",
                         {"frame_id": frame_id})
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(csv)
        return {"path": path, "bytes": len(csv)}

    def frame_export_post(params, frame_id):
        path = params.get("path")
        if not path:
            raise RestError(400, "path required")
        # force defaults FALSE: silent overwrite is the reference's
        # opt-in, not its default (FramesHandler.export)
        force = str(params.get("force", "false")).lower() in ("true", "1")
        return _export_frame(frame_id, path, force)

    def frame_export_get(params, frame_id, path, force):
        return _export_frame(frame_id, path,
                             str(force).lower() in ("true", "1"))

    r.register("POST", "/3/Frames/{frame_id}/export", frame_export_post,
               "export frame as csv to a server path")
    r.register("GET", "/3/Frames/{frame_id}/export/{path}/overwrite/{force}",
               frame_export_get, "export frame (URI form)")

    # ---- remaining ingest route forms -------------------------------------
    def import_files_multi(params):
        paths = _parse_list(params.get("paths") or [])
        if not paths:
            raise RestError(400, "paths required")
        outs = [r.dispatch("POST", "/3/ImportFiles", {"path": p})
                for p in paths]
        return {"destination_frames": [
            k for o in outs
            for k in (o.get("destination_frames") or
                      [o.get("destination_frame")])
        ]}

    def import_files_get(params):
        return r.dispatch("POST", "/3/ImportFiles", params)

    def parse_svmlight_ep(params):
        params = dict(params)
        params["format"] = "svmlight"
        return r.dispatch("POST", "/3/Parse", params)

    def decryption_setup(params):
        raise RestError(
            400,
            "encrypted-archive ingest (DecryptionSetup / AES zip) is not "
            "available in this build; decrypt before import (reference: "
            "water/parser/DecryptionTool.java)")

    def import_hive_table_ep(params):
        """ImportHiveTableHandler: reads over a HiveServer2 DB-API
        connection (pyhive) instead of the reference's metastore-direct
        loads; errors are actionable when the driver is absent."""
        from h2o3_tpu.frame.ingest import import_hive_table

        parts = params.get("partitions")
        if isinstance(parts, str) and parts:
            parts = json.loads(parts)
        try:
            fr = import_hive_table(
                database=params.get("database") or "default",
                table=params.get("table") or "",
                partitions=parts or None,
                connection_url=params.get("connection_url"))
        except ValueError as e:
            raise RestError(400, str(e))
        key = params.get("destination_frame") or DKV.make_key("hive")
        fr.key = key
        DKV.put(key, fr)
        return {"key": {"name": key}, "destination_frame": {"name": key},
                "num_rows": fr.nrows, "num_cols": fr.ncols}

    def hive_unavailable(params):
        raise RestError(
            400,
            "Hive export needs the Hive metastore client, which is "
            "not available in this build (reference: h2o-ext-hive / "
            "water/hive/HiveTableImporter.java); export the table to "
            "parquet/orc/csv and import that")

    r.register("POST", "/3/ImportFilesMulti", import_files_multi,
               "import several paths")
    r.register("GET", "/3/ImportFiles", import_files_get,
               "import a file (GET form)")
    r.register("POST", "/3/ParseSVMLight", parse_svmlight_ep,
               "parse svmlight sources")
    r.register("POST", "/3/DecryptionSetup", decryption_setup,
               "encrypted ingest (unavailable, actionable error)")
    r.register("POST", "/3/ImportHiveTable", import_hive_table_ep,
               "hive table import over HiveServer2 (pyhive)")
    r.register("POST", "/3/SaveToHiveTable", hive_unavailable,
               "hive export (unavailable, actionable error)")

    # ---- assembly ----------------------------------------------------------
    def assembly_fit(params):
        from h2o3_tpu.models.assembly import fit_assembly

        fr = _get_frame(params.get("frame", params.get("frame_id", "")))
        steps = params.get("steps")
        if isinstance(steps, str):
            steps = json.loads(steps)
        if not isinstance(steps, list) or not steps:
            raise RestError(400, "steps (non-empty list) required")
        try:
            asm, out = fit_assembly(steps, fr)
        except (ValueError, KeyError) as e:
            raise RestError(400, str(e))
        dest = params.get("destination_frame") or DKV.make_key("assembly_out")
        out.key = dest
        DKV.put(dest, out)
        return {"assembly": {"name": asm.key},
                "result": {"name": dest},
                "out_names": asm.out_names}

    def assembly_java(params, assembly_id, pojo_name):
        from h2o3_tpu.models.assembly import Assembly

        asm = DKV.get(assembly_id)
        if not isinstance(asm, Assembly):
            raise RestError(404, f"no assembly {assembly_id!r}")
        return asm.to_java(pojo_name).encode(), "text/plain; charset=utf-8"

    r.register("POST", "/99/Assembly", assembly_fit,
               "fit a munging pipeline")
    r.register("GET", "/99/Assembly.java/{assembly_id}/{pojo_name}",
               assembly_java, "assembly as standalone java munger")

    # ---- scoring pipeline (mojo-pipeline extension analogue) ---------------
    def pipeline_build(params):
        """Assemble a ScoringPipeline from a trained model and/or a fitted
        Assembly (hex/mojopipeline — ours builds its own artifact instead
        of loading DriverlessAI MOJO2)."""
        from h2o3_tpu.models.assembly import Assembly
        from h2o3_tpu.models.pipeline import build_pipeline

        model = None
        if params.get("model"):
            model = _get_model(params["model"])
        asm = None
        if params.get("assembly"):
            asm = DKV.get(params["assembly"])
            if not isinstance(asm, Assembly):
                raise RestError(404, f"no assembly {params['assembly']!r}")
        try:
            pipe = build_pipeline(model=model, assembly=asm)
        except ValueError as e:
            raise RestError(400, str(e))
        return {"pipeline": {"name": pipe.key},
                "in_names": pipe.in_names,
                "has_model": pipe.mojo_bytes is not None}

    def _get_pipeline(key: str):
        from h2o3_tpu.models.pipeline import ScoringPipeline

        pipe = DKV.get(key)
        if not isinstance(pipe, ScoringPipeline):
            raise RestError(404, f"no pipeline {key!r}")
        return pipe

    def pipeline_fetch(params, pipeline_id):
        """Download the pipeline artifact zip."""
        return _get_pipeline(pipeline_id).to_bytes()

    def pipeline_import(params):
        """Import an artifact from a server-side path or a base64 body."""
        import base64

        from h2o3_tpu.models.pipeline import ScoringPipeline

        if params.get("path"):
            try:
                with open(params["path"], "rb") as f:
                    data = f.read()
            except OSError as e:
                raise RestError(400, f"cannot read {params['path']!r}: {e}")
        elif params.get("data"):
            try:
                data = base64.b64decode(params["data"])
            except Exception:
                raise RestError(400, "data is not valid base64")
        else:
            raise RestError(400, "path or data (base64 zip) required")
        try:
            pipe = ScoringPipeline.from_bytes(data)
        except (ValueError, zipfile.BadZipFile, json.JSONDecodeError) as e:
            raise RestError(400, f"bad pipeline artifact: {e}")
        pipe.key = params.get("destination_key") or DKV.make_key("pipeline")
        DKV.put(pipe.key, pipe)
        return {"pipeline": {"name": pipe.key}, "in_names": pipe.in_names,
                "has_model": pipe.mojo_bytes is not None}

    def pipeline_transform(params):
        """Run the pipeline on a frame (MojoPipeline.transform)."""
        pipe = _get_pipeline(params.get("pipeline", ""))
        fr = _get_frame(params.get("frame", params.get("frame_id", "")))
        try:
            out = pipe.transform(fr)
        except ValueError as e:
            raise RestError(400, str(e))
        dest = params.get("destination_frame") or DKV.make_key("pipe_out")
        out.key = dest
        DKV.put(dest, out)
        return {"result": {"name": dest}, "names": out.names}

    r.register("POST", "/99/PipelineMojo", pipeline_build,
               "build a scoring pipeline from model + assembly")
    r.register("GET", "/99/PipelineMojo.fetch/{pipeline_id}", pipeline_fetch,
               "download the pipeline artifact")
    r.register("POST", "/99/PipelineMojo.import", pipeline_import,
               "import a pipeline artifact")
    r.register("POST", "/99/PipelineMojo.transform", pipeline_transform,
               "transform a frame through a pipeline")
