"""The previous thread-per-connection REST front-end, kept as the serving
bench baseline.

This is the stdlib ``ThreadingHTTPServer`` implementation ``api/server.py``
shipped before the event-loop rewrite: one thread per connection, no
admission control, no batching, a blanket 120s socket timeout as the only
slow-client guard.  ``bench.py --serve-bench`` runs it head-to-head against
the event-loop server (with and without the scoring coalescer) so
SERVE_BENCH.json carries the before/after; nothing else should use it.

It shares the route registry, auth, error shapes and request meters with
the event-loop server — only the transport differs.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu import __version__
from h2o3_tpu.api.server import (
    _LIVE_URLS,
    _REST_REQUESTS,
    _REST_SECONDS,
    H2OServer,
    RestError,
    _json_default,
    _trace_header,
)
from h2o3_tpu.util import telemetry


class ThreadedH2OServer(H2OServer):
    """Thread-per-connection H2OServer (the pre-event-loop transport)."""

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ThreadedH2OServer":
        from h2o3_tpu.util import log as _log

        _log.init()
        registry = self.registry
        srv = self

        class Handler(BaseHTTPRequestHandler):
            server_version = f"h2o3-tpu/{__version__}"
            timeout = 120  # a dead client must not pin its thread forever

            def log_message(self, *a):  # quiet; the Log subsystem records
                pass

            def _params(self) -> Dict[str, Any]:
                parsed = urllib.parse.urlparse(self.path)
                params: Dict[str, Any] = {
                    k: v[0] if len(v) == 1 else v
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    body = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype:
                        params.update(json.loads(body))
                    elif "octet-stream" in ctype:
                        params["_raw_body"] = body
                    else:  # h2o-py posts urlencoded forms
                        try:
                            params.update(
                                {
                                    k: v[0] if len(v) == 1 else v
                                    for k, v in urllib.parse.parse_qs(
                                        body.decode()
                                    ).items()
                                }
                            )
                        except UnicodeDecodeError:
                            params["_raw_body"] = body
                return params

            def _respond(self, method: str) -> None:
                from h2o3_tpu.util.log import get_logger

                cur = threading.current_thread()
                if cur.name.startswith("Thread-"):
                    cur.name = "http-worker"
                parsed = urllib.parse.urlparse(self.path)
                found = registry.match(method, parsed.path)
                route = found[2] if found else "(unmatched)"
                status = 200
                ctype = "application/json"
                extra_headers: List[Tuple[str, str]] = []
                span: Optional[telemetry.Span] = None
                t0 = time.perf_counter()
                if not srv._check_auth(self.headers.get("Authorization")):
                    get_logger("rest").info("%s %s", method, parsed.path)
                    status = 401
                    payload = json.dumps(
                        {"http_status": 401, "msg": "authentication required"}
                    ).encode()
                    extra_headers.append(
                        ("WWW-Authenticate", 'Basic realm="h2o3-tpu"'))
                else:
                    span = telemetry.Span(
                        "rest", method=method, route=route,
                        path=parsed.path,
                        trace_id=_trace_header(
                            self.headers.get("X-H2O3-Trace-Id")),
                        parent_id=_trace_header(
                            self.headers.get("X-H2O3-Span-Id")),
                    )
                    try:
                        with span:
                            get_logger("rest").info(
                                "%s %s", method, parsed.path)
                            if found is None:
                                raise RestError(
                                    404,
                                    f"no route for {method} {parsed.path}",
                                )
                            handler, path_kw, _ = found
                            out = handler(self._params(), **path_kw)
                        if (
                            isinstance(out, tuple) and len(out) == 2
                            and isinstance(out[0], (bytes, bytearray))
                        ):
                            payload, ctype = out
                        elif isinstance(out, (bytes, bytearray)):
                            payload, ctype = out, "application/octet-stream"
                        else:
                            payload = json.dumps(
                                out, default=_json_default).encode()
                    except RestError as e:
                        status = e.status
                        payload = json.dumps(
                            {
                                "http_status": e.status,
                                "msg": str(e),
                                "dev_msg": str(e),
                                "exception_type": "RestError",
                            }
                        ).encode()
                        ctype = "application/json"
                    except Exception as e:  # noqa: BLE001
                        status = 500
                        payload = json.dumps(
                            {
                                "http_status": 500,
                                "msg": f"{type(e).__name__}: {e}",
                                "dev_msg": traceback.format_exc(),
                                "exception_type": type(e).__name__,
                            }
                        ).encode()
                        ctype = "application/json"
                _REST_REQUESTS.inc(
                    method=method, route=route, status=str(status))
                _REST_SECONDS.observe(
                    time.perf_counter() - t0, method=method, route=route)
                if span is not None and span.trace_id:
                    extra_headers.append(("X-H2O3-Trace-Id", span.trace_id))
                self.send_response(status)
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if (urllib.parse.urlparse(self.path).path == "/3/Steam.web"
                        and "websocket" in
                        (self.headers.get("Upgrade") or "").lower()):
                    if not srv._check_auth(
                            self.headers.get("Authorization")):
                        self.send_response(401)
                        self.end_headers()
                        return
                    from h2o3_tpu.api import steam

                    steam.serve_websocket(self)
                    return
                self._respond("GET")

            def do_POST(self):
                self._respond("POST")

            def do_DELETE(self):
                self._respond("DELETE")

        self._httpd: Optional[ThreadingHTTPServer] = ThreadingHTTPServer(
            (self.ip, self.port), Handler)
        if self.ssl_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.ssl_cert, self.ssl_key)
            # lazy handshake: with do_handshake_on_connect the handshake
            # would run inside accept(), letting one stalled client block
            # the accept loop for everyone; deferred, it happens on first
            # read inside the per-connection handler thread
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
        self.port = self._httpd.server_address[1]
        from h2o3_tpu import cluster

        _cloud = cluster.local_cloud()
        if _cloud is not None:
            _cloud.advertise_rest_port(self.port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="http-accept",
        )
        self._thread.start()
        _LIVE_URLS.add(self.url)
        return self

    def stop(self) -> None:
        httpd, self._httpd = getattr(self, "_httpd", None), None
        if httpd:
            _LIVE_URLS.discard(self.url)
            httpd.shutdown()
            httpd.server_close()
