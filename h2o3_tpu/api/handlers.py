"""v3 endpoint implementations.

Reference: ``water/api/RegisterV3Api.java`` route inventory (SURVEY.md
Appendix B) and the per-group handlers (``FramesHandler``,
``ParseHandler``, ``ModelBuilderHandler``, ``RapidsHandler``,
``JobsHandler``, ``GridSearchHandler``, ``CloudHandler`` ...).  Response
shapes follow the ``api/schemas3`` objects (FrameV3, ModelSchemaV3, JobV3,
CloudV3, H2OErrorV3) closely enough for thin clients to port.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu import __version__
from h2o3_tpu.api.registry import algo_map
from h2o3_tpu.api.server import H2OServer, RequestServer, RestError
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.frame.parse import parse_csv, parse_setup
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.framework import Job, Model
from h2o3_tpu.models.grid import Grid, GridSearch, SearchCriteria
from h2o3_tpu.rapids import Session, exec_rapids


class _RawFile:
    """An imported-but-unparsed source (reference: raw ByteVec under a key).
    Keeps the ORIGINAL bytes (a multi-entry zip must reach the parser
    whole); name/data expose the first decompressed part for sniffing."""

    def __init__(self, path: str, text: Optional[str] = None,
                 data: Optional[bytes] = None) -> None:
        from h2o3_tpu.frame.ingest import _decompress

        self.path = path
        if text is not None:
            self.raw_name, self.raw_data = path, text.encode()
        else:
            self.raw_name = os.path.basename(path) or path
            self.raw_data = data or b""
        self.name, self.data = _decompress(self.raw_name, self.raw_data)

    @property
    def text(self) -> str:
        return self.data.decode("utf-8", errors="replace")


_SESSIONS: Dict[str, Session] = {}


# ---------------------------------------------------------------------------
# helpers


def _get_frame(frame_id: str) -> Frame:
    fr = DKV.get(frame_id)
    if not isinstance(fr, Frame):
        fr = _dist_frame_from_ring(frame_id)
        if fr is None:
            raise RestError(404, f"frame {frame_id!r} not found")
    return fr


def _dist_frame_from_ring(frame_id: str) -> Optional[Frame]:
    """A chunk-homed frame resolved from the DKV ring: any member whose
    local registry misses the key can still serve (or fit against) a
    frame parsed to homes elsewhere in the cloud — the layout and parse
    setup live beside the chunks at MAX_REPLICAS depth."""
    from h2o3_tpu.cluster import active_cloud

    cloud = active_cloud()
    store = getattr(cloud, "dkv_store", None) if cloud is not None else None
    if store is None:
        return None
    from h2o3_tpu.cluster import frames as _frames

    try:
        layout = store.get(_frames.layout_key(frame_id))
        if not isinstance(layout, dict):
            return None
        setup = store.get(_frames.setup_key(frame_id))
        if setup is None:
            return None
        return _frames.DistFrame(
            layout, _frames.setup_from_payload(setup), store)
    except Exception:
        return None


def _get_model(model_id: str) -> Model:
    m = DKV.get(model_id)
    if not isinstance(m, Model):
        raise RestError(404, f"model {model_id!r} not found")
    return m


def _frame_schema(fr: Frame, key: str, rows: int = 10) -> Dict[str, Any]:
    """FrameV3 / FrameBaseV3 (api/schemas3/FrameV3.java)."""
    cols = []
    for c in fr.columns:
        r = c.rollups if c.type in (ColType.NUM, ColType.TIME, ColType.CAT) else None
        head = c.data[:rows]
        if c.type is ColType.CAT:
            data = [c.domain[v] if v >= 0 else None for v in head]
        elif c.type is ColType.STR:
            data = [None if v is None else str(v) for v in head]
        else:
            data = [None if np.isnan(v) else float(v) for v in head]
        cols.append(
            {
                "label": c.name,
                "type": c.type.name.lower(),
                "domain": c.domain,
                "domain_cardinality": len(c.domain) if c.domain else 0,
                "missing_count": int(r.na_count) if r else int(c.na_count()),
                "mins": [r.min] if r else [],
                "maxs": [r.max] if r else [],
                "mean": r.mean if r else None,
                "sigma": r.sigma if r else None,
                "data": data,
            }
        )
    return {
        "frame_id": {"name": key},
        "rows": fr.nrows,
        "num_columns": fr.ncols,
        "column_names": fr.names,
        "columns": cols,
    }


def _job_schema(job: Job) -> Dict[str, Any]:
    """JobV3 (api/schemas3/JobV3.java)."""
    return {
        "key": {"name": job.key},
        "description": job.description,
        "status": job.status,
        "progress": job.progress,
        "progress_msg": getattr(job, "progress_msg", None),
        "msec": int(job.run_time * 1000),
        "exception": str(job.exception) if job.exception else None,
        "dest": getattr(job, "dest", None),
    }


def _metrics_schema(mm: Any) -> Optional[Dict[str, Any]]:
    if mm is None:
        return None
    if isinstance(mm, dict):  # e.g. isolation forest's {mean_score, max_score}
        return {k: (None if isinstance(v, float) and np.isnan(v) else v)
                for k, v in mm.items() if np.isscalar(v)}
    out = {}
    for k in (
        "mse rmse mae rmsle r2 mean_residual_deviance auc pr_auc gini logloss "
        "mean_per_class_error max_f1_threshold nobs"
    ).split():
        v = getattr(mm, k, None)
        if v is not None and np.isscalar(v):
            out[k] = None if isinstance(v, float) and np.isnan(v) else v
    return out


def _model_schema(m: Model) -> Dict[str, Any]:
    """ModelSchemaV3: model_id + algo + parameters + output."""
    params = {}
    for f in dataclasses.fields(m.params):
        v = getattr(m.params, f.name)
        if isinstance(v, (int, float, str, bool, type(None), list)):
            params[f.name] = v
    output: Dict[str, Any] = {
        "model_category": (
            "Binomial" if m.nclasses == 2 else
            "Multinomial" if m.nclasses > 2 else "Regression"
        ),
        "training_metrics": _metrics_schema(m.training_metrics),
        "validation_metrics": _metrics_schema(m.validation_metrics),
        "cross_validation_metrics": _metrics_schema(m.cross_validation_metrics),
        "names": list(m.data_info.predictor_names),
        "domains": m.data_info.response_domain,
        "run_time": m.run_time,
    }
    for attr in ("coefficients", "exp_coef", "std_errors", "p_values", "iterations"):
        v = getattr(m, attr, None)
        if v is not None:
            output[attr] = v
    vi = getattr(m, "variable_importances", None)
    if callable(vi):
        try:
            output["variable_importances"] = vi()
        except Exception:
            pass
    return {
        "model_id": {"name": m.key},
        "algo": m.algo_name,
        "parameters": params,
        "output": output,
    }


def _coerce_params(params_cls, raw: Dict[str, Any]):
    """Form/JSON values -> typed Parameters dataclass (the schema-filling
    that api/Handler.fillFromParms does via schema metadata)."""
    fields = {f.name: f for f in dataclasses.fields(params_cls)}
    kw: Dict[str, Any] = {}
    for k, v in raw.items():
        if k not in fields:
            continue
        f = fields[k]
        ftype = f.type if isinstance(f.type, str) else getattr(f.type, "__name__", "")
        if isinstance(v, str):
            t = str(ftype)
            if "bool" in t:
                v = v.lower() in ("true", "1", "yes")
            elif "int" in t and "List" not in t:
                v = int(float(v))
            elif "float" in t:
                v = float(v)
            elif "List" in t or "list" in t:
                s = v.strip()
                if s.startswith("["):
                    v = json.loads(s.replace("'", '"'))
                else:
                    v = [x for x in s.split(",") if x]
        kw[k] = v
    try:
        return params_cls(**kw)
    except TypeError as e:
        raise RestError(400, f"bad parameters: {e}")


# ---------------------------------------------------------------------------
# registration


def register_all(r: RequestServer, server: H2OServer) -> None:
    algos = algo_map()

    # ---- cloud / ops ------------------------------------------------------
    def cloud(params):
        """CloudV3 (api/schemas3/CloudV3.java) — real members with
        heartbeat ages when an application-plane cloud is live
        (h2o3_tpu/cluster/), the single-node shape otherwise."""
        import jax

        from h2o3_tpu import cluster
        from h2o3_tpu.util import telemetry

        try:
            devices = [str(d) for d in jax.devices()]
        except Exception:
            devices = []
        out = {
            "version": __version__,
            "cloud_name": server.name,
            "cloud_size": 1,
            "cloud_healthy": True,
            "cloud_uptime_millis": int((time.time() - server.start_time) * 1000),
            "consensus": True,
            "locked": True,
            # compact process-wide totals; the full registry is /3/Metrics
            "telemetry": telemetry.REGISTRY.summary(),
            "nodes": [
                {
                    "h2o": f"127.0.0.1:{server.port}",
                    "healthy": True,
                    "num_cpus": os.cpu_count(),
                    "devices": devices,
                }
            ],
        }
        c = cluster.local_cloud()
        if c is not None:
            nodes = c.member_schemas()
            for nd in nodes:
                if nd["name"] == c.info.name:  # only the local node can
                    nd["devices"] = devices    # name its own devices
                    nd["num_cpus"] = os.cpu_count()
            out.update({
                "cloud_name": c.cloud_name,
                "node_name": c.info.name,
                "cloud_size": sum(1 for nd in nodes if not nd["client"]),
                "cloud_healthy": all(nd["healthy"] for nd in nodes),
                "consensus": c.consensus(),
                "cloud_hash": c.cloud_hash(),
                "cloud_version": c.version,
                "bad_nodes": sum(1 for nd in nodes if not nd["healthy"]),
                "nodes": nodes,
            })
        return out

    r.register("GET", "/3/Cloud", cloud, "cloud status")
    r.register("GET", "/3/Cloud/status", cloud, "cloud status (minimal)")
    r.register("GET", "/3/About", lambda p: {
        "entries": [
            {"name": "Build version", "value": __version__},
            {"name": "Backend", "value": "jax/XLA (TPU-native)"},
        ]
    }, "about")
    r.register("GET", "/3/Capabilities", lambda p: {
        "capabilities": [{"name": a} for a in sorted(algos)]
    }, "capabilities")
    r.register("GET", "/3/Metadata/endpoints", lambda p: {
        "routes": r.endpoints()
    }, "endpoint metadata")
    def shutdown(params):
        # stop the HTTP server for real (ShutdownHandler) — delayed so
        # this response still reaches the client; the hosting process
        # stays alive (it owns the TPU runtime), matching h2o.shutdown()
        # semantics of "the cluster stops answering"
        import threading as _threading

        _threading.Timer(0.3, server.stop).start()
        return {"result": "shutting down"}

    r.register("POST", "/3/Shutdown", shutdown, "stop the REST server")
    r.register("POST", "/3/GarbageCollect", lambda p: (__import__("gc").collect(), {})[1],
               "gc")

    # ---- jobs -------------------------------------------------------------
    def jobs_list(params):
        return {"jobs": [_job_schema(DKV.get(k)) for k in DKV.keys_of_type(Job)]}

    def job_get(params, job_id):
        j = DKV.get(job_id)
        if not isinstance(j, Job):
            raise RestError(404, f"job {job_id!r} not found")
        return {"jobs": [_job_schema(j)]}

    def job_cancel(params, job_id):
        j = DKV.get(job_id)
        if not isinstance(j, Job):
            raise RestError(404, f"job {job_id!r} not found")
        j.cancel()
        return {"jobs": [_job_schema(j)]}

    r.register("GET", "/3/Jobs", jobs_list, "list jobs")
    r.register("GET", "/3/Jobs/{job_id}", job_get, "job status")
    r.register("POST", "/3/Jobs/{job_id}/cancel", job_cancel, "cancel job")

    # ---- import / parse ---------------------------------------------------
    def import_files(params):
        """Path / glob / directory / URI -> raw sources (ImportFilesHandler
        + PersistManager scheme dispatch; water/persist/)."""
        from h2o3_tpu.frame.ingest import list_sources, resolve_persist

        path = params.get("path")
        if not path:
            raise RestError(400, "path required")
        try:
            sources = list_sources(path)
        except FileNotFoundError as e:
            raise RestError(404, f"path {e} not found")
        except ValueError as e:
            raise RestError(400, str(e))
        keys: List[str] = []
        fails: List[str] = []
        for src in sources:
            try:
                backend, p = resolve_persist(src)
                key = DKV.make_key("nfs:" + os.path.basename(p))
                DKV.put(key, _RawFile(p, data=backend.read_bytes(p)))
                keys.append(key)
            except Exception:
                fails.append(src)
        if not keys:
            raise RestError(
                400, f"no readable sources among {sources!r} (failed: {fails})"
            )
        return {
            "files": sources,
            "destination_frames": keys,
            "fails": fails,
            "dels": [],
        }

    def post_file(params):
        # upload_file: raw body was stashed under 'file' by the client;
        # our client sends {"data": csv_text}
        text = params.get("data")
        if text is None:
            raise RestError(400, "no file data")
        key = params.get("destination_frame") or DKV.make_key("upload")
        DKV.put(key, _RawFile("<upload>", text))
        return {"destination_frame": key, "total_bytes": len(text)}

    def _raw_of(key: str) -> _RawFile:
        v = DKV.get(key)
        if not isinstance(v, _RawFile):
            raise RestError(404, f"no raw file under {key!r}")
        return v

    def parse_setup_ep(params):
        from h2o3_tpu.frame.ingest import sniff_format

        srcs = params.get("source_frames")
        if isinstance(srcs, str):
            srcs = json.loads(srcs.replace("'", '"')) if srcs.startswith("[") else [srcs]
        raw = _raw_of(srcs[0])
        fmt = sniff_format(raw.name, raw.data)
        out = {
            "source_frames": [{"name": s} for s in srcs],
            "destination_frame": srcs[0].rsplit(":", 1)[-1] + ".hex",
            "parse_type": fmt.upper(),
        }
        if fmt == "csv":
            setup = parse_setup(raw.text)
            out.update(
                separator=ord(setup.separator),
                check_header=1 if setup.header else -1,
                column_names=setup.column_names,
                column_types=[t.name.lower() for t in setup.column_types],
                number_columns=len(setup.column_names),
            )
        return out

    def parse_ep(params):
        srcs = params.get("source_frames")
        if isinstance(srcs, str):
            srcs = json.loads(srcs.replace("'", '"')) if srcs.startswith("[") else [srcs]
        raw = _raw_of(srcs[0])
        dest = params.get("destination_frame") or DKV.make_key("parse")
        kw: Dict[str, Any] = {}
        if params.get("separator"):
            kw["separator"] = chr(int(params["separator"]))
        if params.get("check_header"):
            kw["header"] = int(params["check_header"]) == 1
        # chunk-parallel tokenization width (frame/parse.py two-phase
        # pipeline); absent -> H2O3_TPU_PARSE_WORKERS / host cores
        if params.get("parse_workers"):
            try:
                kw["workers"] = max(1, int(params["parse_workers"]))
            except (TypeError, ValueError):
                raise RestError(400, "parse_workers must be an integer")
        # forced types from ParseSetup must survive Parse (the reference's
        # two-phase parse honors the client-edited setup)
        names = params.get("column_names")
        types = params.get("column_types")
        if isinstance(names, str):
            names = json.loads(names.replace("'", '"'))
        if isinstance(types, str):
            types = json.loads(types.replace("'", '"'))
        if types:
            if not names:
                names = parse_setup(raw.text).column_names
            kw["column_types"] = {
                n: t for n, t in zip(names, types) if t
            }
        job = Job(f"parse {dest}").start()
        try:
            from h2o3_tpu.frame.ingest import parse_bytes, rbind_all

            # multi-file: parse each + rbind (ParseDataset parseAllKeys)
            fr = rbind_all(
                [
                    parse_bytes(_raw_of(s).raw_name, _raw_of(s).raw_data, **kw)
                    for s in srcs
                ]
            )
            DKV.put(dest, fr)
            job.dest = dest
            job.done()
        except Exception as e:
            job.fail(e)
            raise RestError(400, f"parse failed: {e}")
        return {"job": _job_schema(job), "destination_frame": {"name": dest}}

    def import_sql(params):
        """/3/ImportSQLTable (water/jdbc/SQLManager.java; sqlite here)."""
        from h2o3_tpu.frame.ingest import import_sql_table

        url = params.get("connection_url")
        if not url:
            raise RestError(400, "connection_url required")
        cols = params.get("columns")
        if isinstance(cols, str) and cols:
            cols = [c for c in cols.split(",") if c]
        try:
            fr = import_sql_table(
                url,
                table=params.get("table"),
                select_query=params.get("select_query"),
                columns=cols or None,
                partition_column=params.get("partition_column"),
                num_partitions=int(params.get("num_partitions") or 1),
            )
        except FileNotFoundError as e:
            raise RestError(404, f"database not found: {e}")
        except ValueError as e:
            raise RestError(400, str(e))
        dest = params.get("destination_frame") or DKV.make_key("sql")
        fr.key = dest
        DKV.put(dest, fr)
        return {"destination_frame": {"name": dest},
                "rows": fr.nrows, "cols": fr.ncols}

    r.register("POST", "/3/ImportSQLTable", import_sql, "import a SQL table")
    r.register("POST", "/3/ImportFiles", import_files, "import a file")
    r.register("POST", "/3/PostFile", post_file, "upload a file body")
    r.register("POST", "/3/ParseSetup", parse_setup_ep, "guess parse setup")
    r.register("POST", "/3/Parse", parse_ep, "parse to frame")

    # ---- frames -----------------------------------------------------------
    def _chunk_homes(v):
        """Chunk layout + replica health for a ring-homed frame; None
        for an ordinary node-local frame (the common case: one getattr)."""
        if getattr(v, "chunk_layout", None) is None:
            return None
        from h2o3_tpu.cluster.frames import layout_health

        return layout_health(v)

    def frames_list(params):
        out = []
        for k in DKV.keys_of_type(Frame):
            # peek: listing a spilled frame must not fault it back in
            v = DKV.peek(k)
            if v is None:
                continue
            row = {"frame_id": {"name": k}, "rows": v.nrows,
                   "num_columns": v.ncols}
            homes = _chunk_homes(v)
            if homes is not None:
                row["chunk_homes"] = homes
            out.append(row)
        return {"frames": out}

    def frame_get(params, frame_id):
        rows = int(params.get("row_count", 10))
        fr = _get_frame(frame_id)
        schema = _frame_schema(fr, frame_id, rows)
        homes = _chunk_homes(fr)
        if homes is not None:
            schema["chunk_homes"] = homes
        return {"frames": [schema]}

    def frame_summary(params, frame_id):
        return frame_get(params, frame_id)

    def frame_columns(params, frame_id):
        fr = _get_frame(frame_id)
        return {"columns": _frame_schema(fr, frame_id)["columns"]}

    def frame_delete(params, frame_id):
        _get_frame(frame_id)
        try:
            DKV.remove(frame_id)
        except ValueError as e:  # Lockable: in use by a running job
            raise RestError(409, str(e))
        return {"frame_id": {"name": frame_id}}

    def frames_delete_all(params):
        skipped = []
        for k in DKV.keys_of_type(Frame):
            try:
                DKV.remove(k)
            except ValueError:  # locked by a running job: skip, not fail
                skipped.append(k)
        return {"skipped_locked": skipped}

    def download_dataset(params):
        """CSV straight from the columns — no pandas: the pandas/pyarrow
        string-index path is not thread-safe under ThreadingHTTPServer and
        segfaulted the server in testing."""
        import csv as _csv

        fr = _get_frame(params.get("frame_id", ""))
        buf = io.StringIO()
        w = _csv.writer(buf, lineterminator="\n")
        w.writerow(fr.names)
        rendered = []
        for c in fr.columns:
            if c.type is ColType.CAT:
                dom = c.domain
                rendered.append(
                    [dom[v] if v >= 0 else "" for v in c.data]
                )
            elif c.type is ColType.STR:
                rendered.append(["" if v is None else str(v) for v in c.data])
            else:
                rendered.append([
                    "" if np.isnan(v) else (repr(int(v)) if float(v).is_integer() else repr(float(v)))
                    for v in c.data
                ])
        for row in zip(*rendered):
            w.writerow(row)
        return buf.getvalue().encode()

    def split_frame(params):
        fr = _get_frame(params.get("dataset", params.get("frame_id", "")))
        ratios = params.get("ratios", "[0.75]")
        if isinstance(ratios, str):
            ratios = json.loads(ratios)
        ratios = [float(x) for x in np.atleast_1d(ratios)]
        seed = int(params.get("seed", -1))
        rng = np.random.default_rng(None if seed == -1 else seed)
        u = rng.random(fr.nrows)
        bounds = np.cumsum(ratios)
        dests = params.get("destination_frames")
        if isinstance(dests, str):
            dests = json.loads(dests.replace("'", '"'))
        keys = []
        lo = 0.0
        all_bounds = list(bounds)
        if not all_bounds or all_bounds[-1] < 1.0 - 1e-12:
            all_bounds.append(1.0)  # remainder split only if ratios < 1
        for i, hi in enumerate(all_bounds):
            mask = (u >= lo) & (u < hi)
            lo = hi
            sub = fr.rows(mask)
            key = (dests[i] if dests and i < len(dests)
                   else DKV.make_key("split"))
            DKV.put(key, sub)
            keys.append(key)
        return {"destination_frames": [{"name": k} for k in keys]}

    r.register("GET", "/3/Frames", frames_list, "list frames")
    r.register("GET", "/3/Frames/{frame_id}", frame_get, "frame + preview")
    r.register("GET", "/3/Frames/{frame_id}/summary", frame_summary, "frame summary")
    r.register("GET", "/3/Frames/{frame_id}/columns", frame_columns, "frame columns")
    r.register("DELETE", "/3/Frames/{frame_id}", frame_delete, "delete frame")
    r.register("DELETE", "/3/Frames", frames_delete_all, "delete all frames")
    r.register("GET", "/3/DownloadDataset", download_dataset, "frame as csv")
    r.register("POST", "/3/SplitFrame", split_frame, "split a frame")

    # ---- rapids / sessions ------------------------------------------------
    def new_session(params):
        s = Session()
        _SESSIONS[s.id] = s
        return {"session_key": s.id}

    def end_session(params, session_id):
        s = _SESSIONS.pop(session_id, None)
        n = s.end() if s else 0
        return {"session_key": session_id, "frames_removed": n}

    def rapids_exec_ep(params):
        ast = params.get("ast")
        if not ast:
            raise RestError(400, "ast required")
        sid = params.get("session_id")
        session = _SESSIONS.get(sid) if sid else None
        if sid and session is None:
            session = _SESSIONS[sid] = Session(sid)
        try:
            val = exec_rapids(ast, session=session)
        except Exception as e:
            raise RestError(400, f"rapids error: {e}")
        # RapidsSchemaV3 family: scalar / string / frame
        if val.is_frame():
            fr = val.as_frame()
            key = getattr(fr, "key", None) or DKV.make_key("rapids")
            DKV.put(key, fr)
            out = {
                "key": {"name": key},
                "num_rows": fr.nrows,
                "num_cols": fr.ncols,
            }
            # a chunk-homed result stays on the ring: report the layout
            # (shape answers come off it — nothing here gathers chunks)
            lay = getattr(fr, "chunk_layout", None)
            if lay is not None:
                out["chunk_homed"] = True
                out["chunk_groups"] = len(lay["groups"])
            return out
        if val.is_num():
            return {"scalar": val.as_num()}
        if val.is_str():
            return {"string": val.as_str()}
        try:
            return {"scalar": val.as_nums().tolist()}
        except Exception:
            return {"string": repr(val)}

    r.register("POST", "/4/sessions", new_session, "new rapids session")
    r.register("DELETE", "/4/sessions/{session_id}", end_session, "end session")
    r.register("POST", "/99/Rapids", rapids_exec_ep, "execute a rapids ast")

    def flow_replay(params, name):
        """Load a notebook document saved under NPS category "notebook"
        (the reference Flow's own save location, NodePersistentStorage)
        and execute its cells in order server-side — the h2o-web flow
        replay, minus the browser."""
        import json as _json

        from h2o3_tpu.util import nps

        try:
            raw = nps.get("notebook", name)
        except FileNotFoundError:
            raise RestError(404, f"no saved flow {name!r}")
        try:
            doc = _json.loads(raw.decode())
        except Exception:
            raise RestError(400, f"flow {name!r} is not a JSON document")
        out = []
        for cell in doc.get("cells", []):
            ast = cell.get("input") if isinstance(cell, dict) else None
            if not ast:
                continue
            try:
                res = rapids_exec_ep(
                    {"ast": ast, "session_id": params.get("session_id")})
                out.append({"input": ast, "ok": True, "result": res})
            except RestError as e:
                out.append({"input": ast, "ok": False, "error": str(e)})
        return {"name": name, "cells": out}

    r.register("POST", "/99/Flow/{name}/run", flow_replay,
               "replay a saved flow document")

    # ---- model builders ---------------------------------------------------
    def builders_list(params):
        return {
            "model_builders": {
                a: {"algo": a, "visibility": "Stable"} for a in sorted(algos)
            }
        }

    def _default_of(f: dataclasses.Field):
        if f.default is not dataclasses.MISSING and isinstance(
            f.default, (int, float, str, bool, type(None))
        ):
            return f.default
        return None  # default_factory or non-scalar default

    def builder_get(params, algo):
        if algo not in algos:
            raise RestError(404, f"unknown algo {algo!r}")
        _, pcls = algos[algo]
        return {
            "model_builders": {
                algo: {
                    "algo": algo,
                    "parameters": [
                        {"name": f.name, "default_value": _default_of(f)}
                        for f in dataclasses.fields(pcls)
                    ],
                }
            }
        }

    #: request fields consumed by the route itself, not the algo params
    _TRAIN_EXTRA = frozenset({"training_frame", "validation_frame", "model_id"})

    def train(params, algo):
        if algo not in algos:
            raise RestError(404, f"unknown algo {algo!r}")
        bcls, pcls = algos[algo]
        # an unknown param must 400, not silently drop (the REST face of
        # the no-silent-param guard; reference: ModelBuilderHandler rejects
        # unknown schema fields)
        unknown = set(params) - {f.name for f in dataclasses.fields(pcls)} - _TRAIN_EXTRA
        if unknown:
            raise RestError(
                400, f"unknown parameters for {algo}: {sorted(unknown)}"
            )
        # generic "trains" from an artifact, not a frame (hex/generic)
        fr = (
            _get_frame(params.get("training_frame", ""))
            if algo != "generic"
            else None
        )
        valid = (
            _get_frame(params["validation_frame"])
            if params.get("validation_frame")
            else None
        )
        p = _coerce_params(pcls, params)
        builder = bcls(p)
        try:
            model = builder.train(fr, valid)
        except RestError:
            raise
        except Exception as e:
            raise RestError(400, f"{algo} train failed: {type(e).__name__}: {e}")
        if params.get("model_id"):
            DKV.rekey(model, params["model_id"])
        job = builder.job  # ModelBuilder.train always creates one
        if job is None:  # defensive: synthesize a finished job
            job = Job(f"{algo} train").start()
            job.done()
        job.dest = model.key
        return {"job": _job_schema(job), "model_id": {"name": model.key}}

    r.register("GET", "/3/ModelBuilders", builders_list, "list algos")
    r.register("GET", "/3/ModelBuilders/{algo}", builder_get, "algo parameters")
    r.register("POST", "/3/ModelBuilders/{algo}", train, "train a model")

    # ---- models -----------------------------------------------------------
    def models_list(params):
        out = []
        for k in DKV.keys_of_type(Model):
            out.append({"model_id": {"name": k}, "algo": DKV.get(k).algo_name})
        return {"models": out}

    def model_get(params, model_id):
        return {"models": [_model_schema(_get_model(model_id))]}

    def model_delete(params, model_id):
        _get_model(model_id)
        DKV.remove(model_id)
        return {}

    def models_delete_all(params):
        for k in DKV.keys_of_type(Model):
            DKV.remove(k)
        return {}

    def model_mojo(params, model_id):
        m = _get_model(model_id)
        with tempfile.NamedTemporaryFile(suffix=".mojo", delete=False) as f:
            path = f.name
        try:
            fmt = str(params.get("format", "")).strip().lower()
            if fmt == "reference":
                # the actual H2O-3 MOJO zip layout (models/mojo_ref.py)
                from h2o3_tpu.models.mojo_ref import write_mojo as _write_ref

                try:
                    _write_ref(m, path)
                except ValueError as e:
                    raise RestError(400, str(e))
            elif fmt in ("", "native"):
                m.download_mojo(path)
            else:
                # an explicit unknown format must not silently fall back:
                # the client would feed the wrong artifact downstream
                raise RestError(400, f"unknown mojo format {fmt!r} "
                                     f"(use 'native' or 'reference')")
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)

    def mojo_pipeline(params):
        """Compose trained models into ONE reference-layout pipeline MOJO
        (hex/genmodel/MojoPipelineWriter — h2o.make_mojo_pipeline's
        role): body {models: {alias: model_id}, input_mapping:
        {generated_col: "alias:pred_idx"}, main_model: alias}; returns
        the zip bytes."""
        from h2o3_tpu.models.mojo_ref import write_pipeline_mojo

        models_spec = params.get("models")
        if isinstance(models_spec, str):
            models_spec = json.loads(models_spec)
        mapping = params.get("input_mapping") or {}
        if isinstance(mapping, str):
            mapping = json.loads(mapping)
        main = params.get("main_model")
        if not models_spec or not main:
            raise RestError(400, "models (alias->model_id) and main_model "
                                 "are required")
        models = {alias: _get_model(mid)
                  for alias, mid in models_spec.items()}
        with tempfile.NamedTemporaryFile(suffix=".zip",
                                         delete=False) as f:
            path = f.name
        try:
            try:
                write_pipeline_mojo(models, mapping, main, path)
            except ValueError as e:
                raise RestError(400, str(e))
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)

    def _predict_out(m, model_id, frame_id, params, pred, metrics_fn):
        """Assemble one /3/Predictions response: register the predictions
        frame, best-effort metrics + the DKV scoring record."""
        dest = params.get("predictions_frame") or DKV.make_key("pred")
        DKV.put(dest, pred)
        out: Dict[str, Any] = {
            "model_metrics": [
                {
                    "frame": {"name": frame_id},
                    "model": {"name": model_id},
                    "predictions_frame": {"name": dest},
                }
            ]
        }
        try:
            mm = metrics_fn()
            out["model_metrics"][0].update(_metrics_schema(mm) or {})
            # leave the DKV-resident scoring record the /3/ModelMetrics
            # routes fetch/delete (hex/ModelMetrics.buildKey)
            from h2o3_tpu.api.handlers_ops import record_scoring

            record_scoring(m, frame_id, mm)
        except Exception:
            pass  # frames without a response can still be scored
        return out

    def predict_batch(requests):
        """Batched /3/Predictions body: the serving coalescer keys batches
        on model_id, so every entry here shares one model and the whole
        batch costs ONE raw-score dispatch (Model.predict_raw_batched) —
        identical frames score once and share the result, distinct frames
        row-stack.  Returns one result-or-exception per entry, aligned;
        exceptions map to the same status the serial handler produces."""
        results: List[Any] = [None] * len(requests)
        try:
            m = _get_model(requests[0][1]["model_id"])
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, RestError) and e.status == 404:
                # not local: a multi-node cloud can still serve it — the
                # serving ring forwards the whole batch to the model's
                # home (or its replicas), cluster/serving.py
                from h2o3_tpu.cluster import serving as _serving

                try:
                    fwd = _serving.forward_predict(
                        requests, requests[0][1]["model_id"])
                except BaseException as fe:  # noqa: BLE001
                    return [fe] * len(requests)
                if fwd is not None:
                    return fwd
            return [e] * len(requests)
        # models with a bespoke predict()/score shape (PCA names PC
        # columns, aggregator has no row scoring) can't share a raw pass:
        # serial per entry, exactly the pre-coalescer behavior
        if type(m).predict is not Model.predict:
            for i, (params, kw) in enumerate(requests):
                try:
                    fr = _get_frame(kw["frame_id"])
                    results[i] = _predict_out(
                        m, kw["model_id"], kw["frame_id"], params,
                        m.predict(fr), lambda fr=fr: m.model_performance(fr))
                except BaseException as e:  # noqa: BLE001
                    results[i] = e
            return results
        frames: List[Any] = [None] * len(requests)
        for i, (_params, kw) in enumerate(requests):
            try:
                frames[i] = _get_frame(kw["frame_id"])
            except BaseException as e:  # noqa: BLE001
                results[i] = e
        live = [i for i in range(len(requests)) if results[i] is None]
        try:
            scored: List[Any] = m.predict_raw_batched(
                [frames[i] for i in live])
        except BaseException:  # noqa: BLE001
            # one bad frame must not poison the batch: retry serially so
            # only the offender fails
            scored = []
            for i in live:
                try:
                    pre = m._apply_preprocessors(frames[i])
                    scored.append((m._predict_raw(pre), pre))
                except BaseException as e:  # noqa: BLE001
                    scored.append(e)
        own_perf = type(m).model_performance is Model.model_performance
        for i, s in zip(live, scored):
            params, kw = requests[i]
            if isinstance(s, BaseException):
                results[i] = s
                continue
            try:
                raw, pre = s
                results[i] = _predict_out(
                    m, kw["model_id"], kw["frame_id"], params,
                    m.prediction_from_raw(raw),
                    # reuse the batch's raw scores for the metrics instead
                    # of scoring again (unless the model overrides
                    # model_performance with stored stats of its own)
                    (lambda raw=raw, pre=pre: m._metrics_from_raw(pre, raw))
                    if own_perf
                    else (lambda fr=frames[i]: m.model_performance(fr)))
            except BaseException as e:  # noqa: BLE001
                results[i] = e
        return results

    def predict(params, model_id, frame_id):
        # a single request IS a batch of one — serial and coalesced
        # scoring share every line of code, which is what makes the
        # batched results bit-identical by construction
        out = predict_batch(
            [(params, {"model_id": model_id, "frame_id": frame_id})])[0]
        if isinstance(out, BaseException):
            raise out
        return out

    def _predict_rows_hint(kw):
        fr = DKV.peek(kw.get("frame_id", ""))
        try:
            return int(getattr(fr, "nrows", 0) or 0)
        except Exception:
            return 0

    # coalescing contract with the event-loop server: batch same-model
    # requests (key), bound batches by summed rows over distinct frames
    # (group/rows)
    predict._h2o3_batch = predict_batch
    predict._h2o3_batch_key = lambda kw: kw.get("model_id")
    predict._h2o3_batch_group = lambda kw: kw.get("frame_id")
    predict._h2o3_batch_rows = _predict_rows_hint

    # ---- binary persistence (Model.exportBinaryModel / importBinaryModel,
    # /3/Models/.../save + /99/Models.bin; FramePersist save/load) ----------
    def _server_path(params, default_name: str) -> str:
        """'dir' is a target DIRECTORY (the h2o-py save_model contract) —
        created if missing — unless it names a file explicitly via a known
        artifact extension."""
        d = params.get("dir")
        if not d:
            raise RestError(400, "missing 'dir' (server-side target path)")
        d = os.path.expanduser(d)
        if os.path.splitext(d)[1] in (".bin", ".h2f", ".mojo", ".zip"):
            os.makedirs(os.path.dirname(d) or ".", exist_ok=True)
            return d
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, default_name)

    def model_save(params, model_id):
        from h2o3_tpu.models.persist import save_model as _save_model

        m = _get_model(model_id)
        path = _server_path(params, f"{model_id}.bin")
        force = str(params.get("force", "true")).lower() in ("true", "1", "yes")
        if os.path.exists(path) and not force:
            raise RestError(409, f"{path} exists and force is false")
        return {"dir": _save_model(m, path)}

    def model_load(params):
        from h2o3_tpu.models.persist import load_model as _load_model

        d = params.get("dir")
        if not d:
            raise RestError(400, "missing 'dir' (server-side model file)")
        try:
            # decode without touching the DKV so a non-model file (e.g. a
            # grid export) can be rejected with no side effects
            m = _load_model(os.path.expanduser(d), register=False)
        except FileNotFoundError:
            raise RestError(404, f"no model file at {d!r}")
        except Exception as e:
            raise RestError(400, f"model load failed: {type(e).__name__}: {e}")
        if not isinstance(m, Model):
            raise RestError(400, f"{d!r} is not a model export")
        if params.get("model_id"):
            # new key only — the file's saved key stays untouched so a live
            # model sharing it is never clobbered
            m.key = params["model_id"]
        DKV.put(m.key, m)
        # an imported model joins the serving ring exactly like a trained
        # one: on a multi-node cloud its blob homes (+ replicates) so ANY
        # member's /3/Predictions can reach it (cluster/serving.py)
        from h2o3_tpu.cluster import serving as _serving

        _serving.home_model(m)
        return {"models": [{"model_id": {"name": m.key}, "algo": m.algo_name}]}

    def frame_save(params, frame_id):
        from h2o3_tpu.frame.persist import save_frame as _save_frame

        fr = _get_frame(frame_id)
        path = _server_path(params, f"{frame_id}.h2f")
        return {"dir": _save_frame(fr, path)}

    def frame_load(params):
        from h2o3_tpu.frame.persist import load_frame as _load_frame

        d = params.get("dir")
        if not d:
            raise RestError(400, "missing 'dir' (server-side frame file)")
        try:
            fr = _load_frame(os.path.expanduser(d))
        except FileNotFoundError:
            raise RestError(404, f"no frame file at {d!r}")
        key = params.get("frame_id") or fr.key or DKV.make_key("frame")
        fr.key = key
        DKV.put(key, fr)
        return {"frames": [{"frame_id": {"name": key}, "rows": fr.nrows,
                            "num_columns": fr.ncols}]}

    def mojo_import(params):
        """Import a MOJO archive as a servable Generic model (hex/generic)."""
        from h2o3_tpu.models.generic import import_mojo as _import_mojo

        path = params.get("dir") or params.get("path")
        if not path:
            raise RestError(400, "missing 'dir' (server-side mojo path)")
        try:
            m = _import_mojo(os.path.expanduser(path), params.get("model_id"))
        except FileNotFoundError:
            raise RestError(404, f"no mojo at {path!r}")
        except Exception as e:
            raise RestError(400, f"mojo import failed: {type(e).__name__}: {e}")
        return {"models": [{"model_id": {"name": m.key}, "algo": m.algo_name,
                            "source_algo": m.source_algo}]}

    r.register("GET", "/3/Models", models_list, "list models")
    r.register("GET", "/3/Models/{model_id}", model_get, "model details")
    r.register("DELETE", "/3/Models/{model_id}", model_delete, "delete model")
    r.register("DELETE", "/3/Models", models_delete_all, "delete all models")
    r.register("GET", "/3/Models/{model_id}/mojo", model_mojo, "download mojo")
    r.register("POST", "/3/Models/{model_id}/save", model_save,
               "save model binary server-side")
    r.register("POST", "/99/Models.bin", model_load, "load model binary")
    r.register("POST", "/3/Frames/{frame_id}/save", frame_save,
               "save frame server-side")
    r.register("POST", "/3/Frames/load", frame_load, "load a saved frame")
    r.register("POST", "/99/MojoPipeline", mojo_pipeline,
               "compose models into a reference pipeline MOJO")
    r.register("POST", "/99/Models.mojo", mojo_import,
               "import a MOJO as a Generic model")
    r.register(
        "POST", "/3/Predictions/models/{model_id}/frames/{frame_id}", predict,
        "score a frame",
    )

    # ---- grids ------------------------------------------------------------
    def grid_train(params, algo):
        if algo not in algos:
            raise RestError(404, f"unknown algo {algo!r}")
        bcls, pcls = algos[algo]
        fr = _get_frame(params.get("training_frame", ""))
        hyper = params.get("hyper_parameters")
        if isinstance(hyper, str):
            hyper = json.loads(hyper)
        if not isinstance(hyper, dict) or not hyper:
            raise RestError(400, "hyper_parameters (dict) required")
        crit_raw = params.get("search_criteria") or {}
        if isinstance(crit_raw, str):
            crit_raw = json.loads(crit_raw)
        crit = SearchCriteria(**{
            k: v for k, v in crit_raw.items()
            if k in {f.name for f in dataclasses.fields(SearchCriteria)}
        })
        base = _coerce_params(pcls, params)
        gs = GridSearch(bcls, base, hyper, crit)
        # the search runs under a Job so /3/Jobs shows live cluster-wide
        # completion while members stream search_progress events into it
        job = Job(f"grid search ({algo})").start()
        try:
            grid = gs.train(fr, job=job)
        except Exception as e:
            job.fail(e)
            raise
        job.dest = grid.grid_id
        job.done()
        want = params.get("grid_id")
        if want and want != grid.grid_id:
            # client-chosen grid id (GridSearchHandler honors grid_id)
            old = grid.grid_id
            grid.grid_id = want
            DKV.put(want, grid)
            if old in DKV:
                DKV.remove(old)
        return {
            "grid_id": {"name": grid.grid_id},
            "model_ids": [{"name": k} for k in grid.model_ids],
            "failure_details": [msg for _, msg in grid.failures],
            "job": _job_schema(job),
        }

    def grids_list(params):
        out = []
        for k in DKV.keys_of_type(Grid):
            out.append({"grid_id": {"name": k}, "model_count": len(DKV.get(k).models)})
        return {"grids": out}

    def grid_get(params, grid_id):
        g = DKV.get(grid_id)
        if not isinstance(g, Grid):
            raise RestError(404, f"grid {grid_id!r} not found")
        sort_by = params.get("sort_by", "auto")
        gs = g.get_grid(sort_by)
        out = {
            "grid_id": {"name": grid_id},
            "model_ids": [{"name": k} for k in gs.model_ids],
            "hyper_params": gs.hyper_params,
            "failure_details": [msg for _, msg in gs.failures],
        }
        # live cluster-wide completion while a distributed search runs
        # (members stream per-model search_progress events to the caller)
        try:
            from h2o3_tpu.cluster.search import search_progress

            prog = search_progress(grid_id)
        except Exception:
            prog = None
        if prog is not None:
            out["progress"] = prog
        return out

    def grid_export(params, grid_id):
        """export_grid (hex/grid Grid.exportBinary): pickle-free archive."""
        g = DKV.get(grid_id)
        if not isinstance(g, Grid):
            raise RestError(404, f"grid {grid_id!r} not found")
        path = _server_path(params, f"{grid_id}.bin")
        return {"dir": g.save(path)}

    def grid_import(params):
        d = params.get("dir")
        if not d:
            raise RestError(400, "missing 'dir' (server-side grid file)")
        try:
            g = Grid.load(os.path.expanduser(d))
        except FileNotFoundError:
            raise RestError(404, f"no grid file at {d!r}")
        except Exception as e:
            raise RestError(400, f"grid import failed: {type(e).__name__}: {e}")
        return {"grid_id": {"name": g.grid_id}, "model_ids": g.model_ids}

    def recovery_resume(params):
        """/3/Recovery/resume (hex/faulttolerance autoRecover): resume an
        interrupted Recoverable from its auto-recovery directory."""
        from h2o3_tpu.recovery import Recovery, auto_recover

        d = params.get("dir") or params.get("recovery_dir")
        if not d:
            raise RestError(400, "missing 'dir' (auto-recovery directory)")
        if not Recovery.present(d):
            raise RestError(404, f"no recovery snapshot in {d!r}")
        try:
            result = auto_recover(d)
        except Exception as e:
            raise RestError(400, f"recovery failed: {type(e).__name__}: {e}")
        out: Dict[str, Any] = {"resumed": True}
        if isinstance(result, Grid):
            out["grid_id"] = {"name": result.grid_id}
            out["model_ids"] = result.model_ids
        return out

    def udf_upload(params):
        """/3/CustomMetric upload (water/udf CFuncRef; gated by
        H2O3_TPU_ENABLE_UDF=1 — uploaded code is code execution)."""
        from h2o3_tpu import udf

        name = params.get("name")
        source = params.get("source")
        if not name or not source:
            raise RestError(400, "name and source required")
        try:
            udf.compile_metric(name, source)
        except PermissionError as e:
            raise RestError(403, str(e))
        except Exception as e:
            raise RestError(400, f"bad UDF: {type(e).__name__}: {e}")
        return {"name": name}

    def udf_eval(params):
        """Evaluate a registered custom metric on (model, frame)."""
        from h2o3_tpu import udf

        m = _get_model(params.get("model_id", ""))
        fr = _get_frame(params.get("frame_id", ""))
        name = params.get("name")
        if not name:
            raise RestError(400, "name required")
        try:
            fn = udf.get_metric(name)
        except KeyError as e:
            raise RestError(404, str(e))
        try:
            value = udf.custom_metric(m, fr, fn)
        except Exception as e:  # data errors are the caller's 400, not 404
            raise RestError(
                400, f"metric evaluation failed: {type(e).__name__}: {e}"
            )
        return {"name": name, "value": value}

    r.register("POST", "/3/CustomMetric", udf_upload, "upload a metric UDF")
    r.register("POST", "/3/CustomMetric/eval", udf_eval, "evaluate a metric UDF")
    r.register("POST", "/3/Recovery/resume", recovery_resume,
               "resume from auto-recovery snapshot")
    r.register("POST", "/99/Grid/{algo}", grid_train, "grid search")
    r.register("GET", "/99/Grids", grids_list, "list grids")
    r.register("GET", "/99/Grids/{grid_id}", grid_get, "grid details")
    r.register("POST", "/99/Grids/{grid_id}/export", grid_export, "export grid")
    r.register("POST", "/99/Grids/import", grid_import, "import grid")

    # ---- automl (h2o-automl REST: /99/AutoMLBuilder, leaderboard) ---------
    def automl_build(params):
        from h2o3_tpu.automl import AutoML

        fr = _get_frame(params.get("training_frame", ""))
        y = params.get("response_column")
        if not y:
            raise RestError(400, "response_column required")
        kw: Dict[str, Any] = {}
        for k, cast in (
            ("max_models", int), ("max_runtime_secs", float), ("seed", int),
            ("nfolds", int), ("sort_metric", str),
        ):
            if params.get(k) is not None:
                kw[k] = cast(params[k])
        for k in ("include_algos", "exclude_algos"):
            v = params.get(k)
            if isinstance(v, str):
                v = json.loads(v.replace("'", '"'))
            if v:
                kw[k] = v
        aml = AutoML(**kw)
        x = params.get("x")
        if isinstance(x, str):
            x = json.loads(x.replace("'", '"'))
        try:
            aml.train(y=y, training_frame=fr, x=x)
        except Exception as e:
            raise RestError(400, f"automl failed: {type(e).__name__}: {e}")
        return {
            "automl_id": {"name": aml.project_key},
            "leader": {"name": aml.leader.key},
            "leaderboard": aml.leaderboard.as_table(),
        }

    def automl_get(params, automl_id):
        from h2o3_tpu.automl import AutoML

        aml = DKV.get(automl_id)
        if not isinstance(aml, AutoML):
            raise RestError(404, f"automl {automl_id!r} not found")
        return {
            "automl_id": {"name": aml.project_key},
            "leader": {"name": aml.leader.key} if aml.leader else None,
            "leaderboard": aml.leaderboard.as_table(),
            "event_log": aml.event_log.events,
        }

    r.register("POST", "/99/AutoMLBuilder", automl_build, "run automl")
    r.register("GET", "/99/AutoML/{automl_id}", automl_get, "automl results")

    # ---- diagnostics (TimeLine / logs / jstack analogues) -----------------
    # ---- observability (water/TimeLine.java, util/Log.java, JStack) -------
    def _truthy(v) -> bool:
        return str(v).lower() in ("1", "true", "yes")

    def _active_cloud():
        from h2o3_tpu import cluster

        return cluster.active_cloud()

    def timeline_ep(params):
        """Real event ring: compiles, training blocks, REST requests
        (water/TimeLine.java:22,75 snapshot semantics).  With
        ``?cluster=true`` on a multi-node cloud: every member's ring is
        collected over RPC, each remote event is tagged ``node=`` and its
        wall clock shifted by the heartbeat-derived skew estimate, and the
        merged stream comes back sorted — the reference's cluster-snapshot
        TimeLine (init/TimelineSnapshot.java), minus the UDP packet log."""
        from h2o3_tpu.util import timeline

        # `count` is the documented name; `n` is the short alias thin
        # clients use (both untested before the telemetry PR)
        n = int(params.get("count", params.get("n", 1000)))
        cloud = _active_cloud() if _truthy(params.get("cluster")) else None
        if cloud is None:
            return _attach_ledgers({
                "events": timeline.snapshot(n),
                "total_events": timeline.total_events(),
                "now": int(time.time() * 1000),
            }, params)
        results, errors = cloud.poll_members(
            "timeline_snapshot", {"count": n})
        members = {m.info.name: m for m in cloud.members_sorted()}
        events = []
        nodes_meta = []
        for name in sorted(results):
            snap = results[name] or {}
            m = members.get(name)
            is_self = name == cloud.info.name
            skew_ms = 0.0
            if not is_self and m is not None and m.clock_skew_ms is not None:
                skew_ms = float(m.clock_skew_ms)
            for ev in snap.get("events", []):
                ev = dict(ev)
                ev.setdefault("node", name)
                # a remote clock ahead of ours by skew_ms reads skew_ms
                # too late: shift its events back onto our clock
                ev["ns"] = int(ev.get("ns", 0) - skew_ms * 1e6)
                events.append(ev)
            nodes_meta.append({
                "name": name,
                "skew_ms": round(skew_ms, 3),
                "rtt_ms": (None if is_self or m is None or m.rtt_ms is None
                           else round(m.rtt_ms, 3)),
                "events": len(snap.get("events", [])),
                "total_events": snap.get("total_events", 0),
            })
        for name in sorted(errors):
            nodes_meta.append({"name": name, "error": errors[name]})
        events.sort(key=lambda e: e.get("ns", 0))
        return _attach_ledgers({
            "events": events,
            "nodes": nodes_meta,
            "partial": bool(errors),
            "total_events": sum(nm.get("total_events", 0)
                                for nm in nodes_meta),
            "now": int(time.time() * 1000),
        }, params)

    def _attach_ledgers(resp, params):
        """``?ledgers=true``: attach this node's cost-ledger entries for
        every trace id present in the returned events, so a saved
        timeline snapshot carries the data trace_view.py needs to render
        per-span cost columns."""
        if not _truthy(params.get("ledgers")):
            return resp
        from h2o3_tpu.util import ledger as ledger_mod

        tids = [e.get("trace_id") for e in resp.get("events", [])
                if e.get("trace_id")]
        resp["ledgers"] = ledger_mod.LEDGER.snapshot_many(tids)
        return resp

    def traces_ep(params, trace_id):
        """Per-trace cost breakdown (node x category), federated: every
        member is asked for its ledger entry over the trace_ledger RPC
        and the per-node maps merge — 404 only when NO reachable member
        knows the trace; an unreachable member degrades the answer to
        ``partial: true``, never a 5xx."""
        from h2o3_tpu.util import ledger as ledger_mod

        cloud = _active_cloud()
        if cloud is None:
            entry = ledger_mod.LEDGER.get(trace_id)
            if entry is None:
                raise RestError(
                    404, f"no cost ledger for trace {trace_id!r}")
            entry["partial"] = False
            return entry
        results, errors = cloud.poll_members(
            "trace_ledger", {"trace_id": trace_id})
        nodes: Dict[str, Any] = {}
        spans: Dict[str, Any] = {}
        meta: Dict[str, Any] = {}
        known = False
        for name in sorted(results):
            led = (results[name] or {}).get("ledger")
            if not led:
                continue
            known = True
            # merge by OVERWRITING per-node keys, never summing: each
            # node's charges live under its own name (disjoint in a real
            # multi-process cloud), and in-process test clouds share one
            # process-wide ledger — every member returns the same entry,
            # so summing would multiply every cost by the member count
            for node, cats in (led.get("nodes") or {}).items():
                nodes[node] = dict(cats)
            for sid, cats in (led.get("spans") or {}).items():
                spans[sid] = dict(cats)
            for k, v in led.items():
                if k not in ("trace_id", "nodes", "spans", "total"):
                    meta.setdefault(k, v)
        if not known:
            raise RestError(404, f"no cost ledger for trace {trace_id!r}")
        total: Dict[str, float] = {}
        for cats in nodes.values():
            for k, v in cats.items():
                total[k] = total.get(k, 0.0) + v
        out = {"trace_id": trace_id, "nodes": nodes, "spans": spans,
               "total": total, "partial": bool(errors)}
        if errors:
            out["errors"] = {k: errors[k] for k in sorted(errors)}
        for k, v in meta.items():
            out.setdefault(k, v)
        return out

    def slowops_ep(params):
        """The slow-op exemplar log: the N worst traces per route above
        the threshold, each with its ledger snapshot attached.
        ``?route=`` filters to one route.  The serving node's watchdog
        summary rides along so one scrape answers "slow AND sick?"."""
        from h2o3_tpu.cluster import health as health_mod
        from h2o3_tpu.util import ledger as ledger_mod

        out = ledger_mod.SLOWOPS.snapshot(route=params.get("route") or None)
        out["health"] = health_mod.summary()
        return out

    def diagnostics_ep(params):
        """One-call support bundle: identity + knobs, watchdog verdicts,
        the last-K flight events, worst SlowOps, membership view and
        thread stacks.  ``?cluster=true`` federates over the
        diagnostics_snapshot RPC — an unreachable member degrades the
        answer to ``partial: true``, never a 5xx."""
        from h2o3_tpu.cluster import health as health_mod

        n = int(params.get("events", params.get("count", 200)))
        if not _truthy(params.get("cluster")):
            return health_mod.diagnostics_snapshot(
                cloud=_active_cloud(), events=n)
        cloud = _active_cloud()
        if cloud is None:
            bundle = health_mod.diagnostics_snapshot(events=n)
            return {"kind": "diagnostics_cluster",
                    "nodes": {bundle["node"]: bundle},
                    "partial": False, "errors": {},
                    "now": int(time.time() * 1000)}
        results, errors = cloud.poll_members(
            "diagnostics_snapshot", {"events": n})
        return {
            "kind": "diagnostics_cluster",
            "nodes": {k: results[k] for k in sorted(results)},
            "partial": bool(errors),
            "errors": {k: errors[k] for k in sorted(errors)},
            "now": int(time.time() * 1000),
        }

    def jstack(params):
        """Real per-thread stack dump (util/JStackCollectorTask.java)."""
        import threading
        import traceback as tb

        frames = __import__("sys")._current_frames()
        traces = []
        for t in threading.enumerate():
            stack = tb.format_stack(frames[t.ident]) if t.ident in frames else []
            traces.append({"thread": t.name, "alive": t.is_alive(),
                           "daemon": t.daemon, "stack": stack})
        return {"traces": traces}

    def logs_ep(params):
        from h2o3_tpu.util import log as L

        L.init()
        return {
            "lines": L.recent(int(params.get("count", 1000))),
            "log_file": L.log_file(),
        }

    def logs_download(params):
        from h2o3_tpu.util import log as L

        L.init()
        return ("\n".join(L.recent(100000)) + "\n").encode()

    def watermeter(params):
        """CPU tick counters (api/WaterMeterCpuTicksHandler.java:6); the
        tick reader lives with the cluster heartbeat so the local route,
        the HeartBeat payload and the cross-node proxy report one shape."""
        from h2o3_tpu.cluster.membership import cpu_ticks_payload

        return cpu_ticks_payload()

    def _federated_metrics():
        """(merged_snapshot, nodes, errors) across the live cloud — or the
        local registry labelled under this node's name when no multi-node
        cloud is up, so ``?cluster=true`` has ONE response shape."""
        from h2o3_tpu import cluster
        from h2o3_tpu.util import telemetry

        cloud = _active_cloud()
        if cloud is None:
            local = cluster.local_cloud()
            node = local.info.name if local is not None else (
                telemetry.node_name() or "localhost")
            merged = telemetry.merge_snapshots(
                {node: telemetry.REGISTRY.snapshot()})
            return merged, [node], {}
        results, errors = cloud.poll_members("metrics_snapshot")
        merged = telemetry.merge_snapshots({
            name: (r or {}).get("metrics", {})
            for name, r in results.items()
        })
        return merged, sorted(results), errors

    def metrics_ep(params):
        """Full registry snapshot as JSON (the quantitative face of
        /3/Timeline — counts where the timeline has events).  With
        ``?cluster=true``: every member's registry is scraped over RPC and
        merged with a ``node=`` label (counters also sum into a
        ``node="_cluster"`` aggregate, histogram buckets merge, gauges stay
        per-node); an unreachable member degrades the answer to
        ``partial: true`` — never a 5xx."""
        from h2o3_tpu.util import telemetry

        if not _truthy(params.get("cluster")):
            return {
                "metrics": telemetry.REGISTRY.snapshot(),
                "now": int(time.time() * 1000),
            }
        merged, nodes, errors = _federated_metrics()
        return {
            "metrics": merged,
            "nodes": nodes,
            "errors": errors,
            "partial": bool(errors),
            "now": int(time.time() * 1000),
        }

    def metrics_prometheus(params):
        """Prometheus text exposition v0.0.4 — point a scraper at it.
        ``?cluster=true`` serves the federated merge (node= labels on every
        series) with a comment header naming unreachable members."""
        from h2o3_tpu.util import telemetry

        if not _truthy(params.get("cluster")):
            return (
                telemetry.REGISTRY.prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        merged, nodes, errors = _federated_metrics()
        text = telemetry.snapshot_prometheus(merged)
        if errors:
            head = "".join(
                f"# partial scrape: {name} unreachable ({msg})\n"
                for name, msg in sorted(errors.items()))
            text = head + text
        return text.encode(), "text/plain; version=0.0.4; charset=utf-8"

    r.register("GET", "/3/Metrics", metrics_ep, "telemetry registry (JSON)")
    r.register("GET", "/3/Metrics/prometheus", metrics_prometheus,
               "telemetry registry (Prometheus text exposition)")
    r.register("GET", "/3/Timeline", timeline_ep, "event timeline")
    r.register("GET", "/3/Traces/{trace_id}", traces_ep,
               "per-trace cost ledger (node x category)")
    r.register("GET", "/3/SlowOps", slowops_ep, "slow-op exemplar log")
    r.register("GET", "/3/Diagnostics", diagnostics_ep,
               "support bundle (health, flight ring, slowops, stacks)")
    r.register("GET", "/3/JStack", jstack, "thread dump")
    r.register("GET", "/3/Logs", logs_ep, "recent log lines")
    r.register("GET", "/3/Logs/download", logs_download, "full log download")
    r.register("GET", "/3/WaterMeterCpuTicks", watermeter, "cpu tick meter")
    r.register("GET", "/3/Ping", lambda p: {"ok": True, "now": int(time.time() * 1000)},
               "liveness probe")

    # ---- model introspection (varimp / PDP / trees / word2vec) ------------
    def model_varimp(params, model_id):
        """Variable importances (ModelOutput varimp + /3/Models makeFI)."""
        m = _get_model(model_id)
        fn = getattr(m, "variable_importances", None)
        if fn is None:
            raise RestError(400, f"{m.algo_name} has no variable importances")
        try:
            vi = fn()
        except NotImplementedError as e:
            raise RestError(400, str(e))
        ordered = sorted(vi.items(), key=lambda kv: -kv[1])
        total = sum(v for _, v in ordered) or 1.0
        return {
            "varimp": [
                {"variable": k, "relative_importance": v,
                 "scaled_importance": v / (ordered[0][1] or 1.0),
                 "percentage": v / total}
                for k, v in ordered
            ]
        }

    def partial_dependence(params):
        """Synchronous PDP (api/ModelBuilders makePDP/fetchPDP): for each
        requested column, sweep a grid and average the model's predictions
        over the frame with that column overridden."""
        m = _get_model(params.get("model_id", ""))
        fr = _get_frame(params.get("frame_id", ""))
        cols = params.get("cols") or []
        if isinstance(cols, str):
            if cols.startswith("["):
                try:  # proper JSON first; python-repr fallback second
                    cols = json.loads(cols)
                except json.JSONDecodeError:
                    cols = json.loads(cols.replace("'", '"'))
            else:
                cols = [cols]
        if not cols:
            raise RestError(400, "cols required")
        nbins = int(params.get("nbins", 20))
        out_tables = []
        for col in cols:
            if col not in fr.names:
                raise RestError(404, f"column {col!r} not in frame")
            c = fr.col(col)
            if c.type is ColType.CAT:
                values: List[Any] = list(range(len(c.domain)))
                labels = list(c.domain)
            else:
                v = c.numeric_view()
                lo, hi = float(np.nanmin(v)), float(np.nanmax(v))
                values = list(np.linspace(lo, hi, nbins))
                labels = [f"{x:.6g}" for x in values]
            dom = m.data_info.response_domain if m.is_classifier else None
            mean_resp: List[Any] = []
            per_class: Dict[str, List[float]] = {lv: [] for lv in (dom or [])}
            for val in values:
                cols_copy = []
                for cc in fr.columns:
                    if cc.name == col:
                        if cc.type is ColType.CAT:
                            data = np.full(fr.nrows, val, dtype=np.int32)
                            cols_copy.append(Column(cc.name, data, ColType.CAT, cc.domain))
                        else:
                            data = np.full(fr.nrows, float(val))
                            cols_copy.append(Column(cc.name, data, ColType.NUM))
                    else:
                        cols_copy.append(cc)
                pred = m.predict(Frame(cols_copy))
                if m.is_classifier:
                    # per-class probability curves (the reference's PDP is
                    # per class; averaging one arbitrary column would be
                    # silently wrong for multinomial)
                    for lv in dom:
                        per_class[lv].append(
                            float(np.nanmean(pred.col(f"p{lv}").numeric_view()))
                        )
                else:
                    mean_resp.append(
                        float(np.nanmean(pred.col("predict").numeric_view()))
                    )
            table = {"column": col, "values": labels}
            if m.is_classifier:
                table["classes"] = dom
                table["mean_response_per_class"] = per_class
                # convenience: positive-class curve for binomial
                table["mean_response"] = per_class[dom[-1]]
            else:
                table["mean_response"] = mean_resp
            out_tables.append(table)
        payload = {"partial_dependence_data": out_tables}
        # store for GET /3/PartialDependence/{name} (fetchPDP)
        from h2o3_tpu.api.handlers_ext import PDPResult

        dest = params.get("destination_key") or DKV.make_key("pdp")
        DKV.put(dest, PDPResult(payload))
        payload["destination_key"] = {"name": dest}
        return payload

    def tree_inspect(params, model_id, tree_number):
        """Tree inspection (hex/schemas TreeV3 / h2o-py h2o.tree): node
        arrays of one tree in heap layout."""
        from h2o3_tpu.models.tree.common import TreeModelBase, tree_feature_names

        m = _get_model(model_id)
        if not isinstance(m, TreeModelBase):
            raise RestError(400, f"{m.algo_name} is not a tree model")
        t = int(tree_number)
        cls = int(params.get("tree_class", 0))
        b = m.booster
        if not 0 <= cls < len(b.trees_per_class):
            raise RestError(404, f"tree_class {cls} out of range")
        trees = b.trees_per_class[cls]
        if not 0 <= t < trees.ntrees:
            raise RestError(404, f"tree {t} out of range (ntrees={trees.ntrees})")
        names = tree_feature_names(m.data_info, m.tree_encoding)
        feat = trees.feat[t]
        is_split = trees.is_split[t]
        edges = trees.edges
        import math

        thresholds = []
        for i in range(len(feat)):
            if is_split[i]:
                f, sb = int(feat[i]), int(trees.split_bin[t][i])
                # split 'bins <= sb go left' -> raw threshold = edge[sb];
                # sb == nbins-1 separates non-NA from NA only (no finite
                # threshold), and inf edge padding (low-cardinality
                # features) is not valid JSON — both report null
                if sb >= edges.shape[1]:
                    thresholds.append(None)
                else:
                    e = float(edges[f][sb])
                    thresholds.append(e if math.isfinite(e) else None)
            else:
                thresholds.append(None)
        return {
            "model_id": {"name": model_id},
            "tree_number": t,
            "tree_class": cls,
            "features": [names[int(f)] if is_split[i] else None
                         for i, f in enumerate(feat)],
            "thresholds": thresholds,
            "is_split": [bool(x) for x in is_split],
            "default_left": [bool(x) for x in trees.default_left[t]],
            "predictions": [float(x) for x in trees.leaf[t]],
            "layout": "heap: children of node i are 2i+1 (left) / 2i+2",
        }

    def w2v_synonyms(params):
        """/3/Word2VecSynonyms (word2vec REST extension)."""
        from h2o3_tpu.models.word2vec import Word2VecModel

        m = _get_model(params.get("model_id", ""))
        if not isinstance(m, Word2VecModel):
            raise RestError(400, f"{m.algo_name} is not a word2vec model")
        word = params.get("word")
        if not word:
            raise RestError(400, "word required")
        count = int(params.get("count", 10))
        syn = m.find_synonyms(word, count)
        return {"synonyms": list(syn.keys()), "scores": list(syn.values())}

    def w2v_transform(params):
        """/3/Word2VecTransform: words frame -> embedding frame."""
        from h2o3_tpu.models.word2vec import Word2VecModel

        m = _get_model(params.get("model_id", ""))
        if not isinstance(m, Word2VecModel):
            raise RestError(400, f"{m.algo_name} is not a word2vec model")
        fr = _get_frame(params.get("words_frame", ""))
        agg = params.get("aggregate_method", "none").lower()
        vecs = m.transform(fr, aggregate_method=agg)
        dest = params.get("destination_frame") or DKV.make_key("w2v")
        DKV.put(dest, vecs)
        return {"vectors_frame": {"name": dest}}

    def predict_contribs(params, model_id, frame_id):
        """SHAP contributions over REST (the predict_contributions flag of
        /3/Predictions in the reference)."""
        m = _get_model(model_id)
        fr = _get_frame(frame_id)
        fn = getattr(m, "predict_contributions", None)
        if fn is None:
            raise RestError(400, f"{m.algo_name} has no SHAP contributions")
        try:
            contribs = fn(fr)
        except ValueError as e:
            raise RestError(400, str(e))
        dest = params.get("predictions_frame") or DKV.make_key("contrib")
        contribs.key = dest
        DKV.put(dest, contribs)
        return {"predictions_frame": {"name": dest},
                "columns": contribs.names}

    r.register(
        "POST", "/3/PredictContributions/models/{model_id}/frames/{frame_id}",
        predict_contribs, "SHAP prediction contributions",
    )
    r.register("GET", "/3/Models/{model_id}/varimp", model_varimp,
               "variable importances")
    r.register("POST", "/3/PartialDependence", partial_dependence,
               "partial dependence plot data")
    r.register("GET", "/3/Trees/{model_id}/{tree_number}", tree_inspect,
               "tree node inspection")
    r.register("POST", "/3/Word2VecSynonyms", w2v_synonyms, "word synonyms")
    r.register("POST", "/3/Word2VecTransform", w2v_transform,
               "words -> embeddings")

    # ---- synthetic data + munging utilities -------------------------------
    def create_frame(params):
        """/3/CreateFrame (hex/createframe recipes, simplified)."""
        rows = int(params.get("rows", 10000))
        cols = int(params.get("cols", 10))
        seed = int(params.get("seed", -1))
        rng = np.random.default_rng(None if seed == -1 else seed)
        cat_frac = float(params.get("categorical_fraction", 0.2))
        int_frac = float(params.get("integer_fraction", 0.2))
        bin_frac = float(params.get("binary_fraction", 0.1))
        missing_frac = float(params.get("missing_fraction", 0.0))
        factors = int(params.get("factors", 5))
        real_range = float(params.get("real_range", 100.0))
        has_response = str(params.get("has_response", "false")).lower() in (
            "true", "1", "yes")
        response_factors = int(params.get("response_factors", 2))

        n_cat = int(round(cols * cat_frac))
        n_int = int(round(cols * int_frac))
        n_bin = int(round(cols * bin_frac))
        n_real = max(cols - n_cat - n_int - n_bin, 0)
        out_cols: List[Column] = []
        i = 0
        for _ in range(n_real):
            i += 1
            data = rng.uniform(-real_range, real_range, rows)
            if missing_frac:
                data[rng.random(rows) < missing_frac] = np.nan
            out_cols.append(Column(f"C{i}", data, ColType.NUM))
        for _ in range(n_int):
            i += 1
            data = rng.integers(-100, 100, rows).astype(np.float64)
            if missing_frac:
                data[rng.random(rows) < missing_frac] = np.nan
            out_cols.append(Column(f"C{i}", data, ColType.NUM))
        for _ in range(n_bin):
            i += 1
            data = (rng.random(rows) < 0.5).astype(np.float64)
            if missing_frac:
                data[rng.random(rows) < missing_frac] = np.nan
            out_cols.append(Column(f"C{i}", data, ColType.NUM))
        for _ in range(n_cat):
            i += 1
            dom = [f"c{i}.l{j}" for j in range(factors)]
            codes = rng.integers(0, factors, rows).astype(np.int32)
            if missing_frac:
                codes[rng.random(rows) < missing_frac] = -1
            out_cols.append(Column(f"C{i}", codes, ColType.CAT, dom))
        if has_response:
            if response_factors > 1:
                dom = [f"r{j}" for j in range(response_factors)]
                codes = rng.integers(0, response_factors, rows).astype(np.int32)
                out_cols.insert(0, Column("response", codes, ColType.CAT, dom))
            else:
                out_cols.insert(
                    0, Column("response", rng.normal(size=rows), ColType.NUM)
                )
        dest = params.get("dest") or params.get("destination_frame") or DKV.make_key("frame")
        fr = Frame(out_cols)
        fr.key = dest
        DKV.put(dest, fr)
        return {"destination_frame": {"name": dest},
                "rows": fr.nrows, "cols": fr.ncols}

    def missing_inserter(params):
        """/3/MissingInserter: punch NAs into a frame in place."""
        key = params.get("dataset") or params.get("frame_id") or ""
        fr = _get_frame(key)
        frac = float(params.get("fraction", 0.1))
        seed = int(params.get("seed", -1))
        rng = np.random.default_rng(None if seed == -1 else seed)
        new_cols = []
        for c in fr.columns:
            mask = rng.random(fr.nrows) < frac
            if c.type is ColType.CAT:
                data = np.where(mask, -1, c.data).astype(np.int32)
                new_cols.append(Column(c.name, data, ColType.CAT, c.domain))
            elif c.type in (ColType.NUM, ColType.TIME):
                data = np.where(mask, np.nan, c.data.astype(np.float64))
                new_cols.append(Column(c.name, data, c.type))
            else:
                data = c.data.copy()
                data[mask] = None
                new_cols.append(Column(c.name, data, c.type))
        out = Frame(new_cols)
        out.key = key
        DKV.put(key, out)
        return {"frame_id": {"name": key}}

    r.register("POST", "/3/CreateFrame", create_frame, "synthetic frame")
    r.register("POST", "/3/MissingInserter", missing_inserter, "insert NAs")

    # ---- schema metadata (water/api/SchemaMetadata -> bindings codegen) ---
    def _schema_of(pcls) -> Dict[str, Any]:
        return {
            "name": pcls.__name__,
            "fields": [
                {
                    "name": f.name,
                    "type": str(f.type),
                    "default_value": _default_of(f),
                }
                for f in dataclasses.fields(pcls)
            ],
        }

    def schemas_list(params):
        return {"schemas": [
            _schema_of(pcls) for _, pcls in sorted(
                (a, p) for a, (_, p) in algos.items()
            )
        ]}

    def schema_get(params, name):
        for a, (_, pcls) in algos.items():
            if pcls.__name__ == name or a == name.lower():
                return {"schemas": [_schema_of(pcls)]}
        raise RestError(404, f"no schema {name!r}")

    r.register("GET", "/3/Metadata/schemas", schemas_list, "parameter schemas")
    r.register("GET", "/3/Metadata/schemas/{name}", schema_get, "one schema")

    # ---- Flow-lite (h2o-web: the notebook UI, here a minimal live console)
    _FLOW_HTML = """<!DOCTYPE html>
<html><head><title>h2o3-tpu Flow</title>
<style>
 body{font-family:monospace;margin:2em;background:#fafafa;color:#222}
 h1{font-size:1.3em} h2{font-size:1.05em;margin-top:1.4em}
 table{border-collapse:collapse;margin:.5em 0}
 td,th{border:1px solid #ccc;padding:.25em .6em;text-align:left}
 .muted{color:#888}
</style></head>
<body>
<h1>h2o3-tpu <span class=muted>Flow-lite</span></h1>
<div id=cloud class=muted>loading&hellip;</div>
<h2>Notebook <span class=muted>(Rapids cells — see /99/Rapids/help)</span></h2>
<div id=history></div>
<div><textarea id=cell rows=3 cols=80
 placeholder="(sort frame_id [0] [1])"></textarea><br>
<button id=run>Run</button>
<input id=fname size=18 placeholder="flow name">
<button id=fsave>Save flow</button>
<select id=flist></select>
<button id=fload>Load</button>
<button id=freplay>Load + replay</button>
<span class=muted>flows persist server-side under
 /3/NodePersistentStorage/notebook</span></div>
<pre id=cellout class=muted></pre>
<h2>Import <span class=muted>(path/glob/URI on the server)</span></h2>
<div><input id=ipath size=60 placeholder="/data/train.csv">
<input id=iname size=20 placeholder="frame name (optional)">
<button id=imp>Import &amp; parse</button></div>
<pre id=impout class=muted></pre>
<h2>Train</h2>
<div><select id=talgo></select>
<input id=tframe size=20 placeholder="training frame">
<input id=tresp size=14 placeholder="response col">
<input id=tparams size=40 placeholder='extra params JSON, e.g. {"ntrees":20}'>
<button id=train>Train</button></div>
<pre id=trainout class=muted></pre>
<h2>Frames</h2><table id=frames></table>
<h2>Models</h2><table id=models></table>
<h2>Jobs</h2><table id=jobs></table>
<script>
async function j(p){const r=await fetch(p);return r.json()}
async function post(p,body){const r=await fetch(p,{method:'POST',
 headers:{'Content-Type':'application/json'},body:JSON.stringify(body)});
 return r.json()}
function show(id,v){document.getElementById(id).textContent=
 typeof v==='string'?v:JSON.stringify(v,null,1)}
let cells=[];
function renderHistory(){
 const h=document.getElementById('history');h.innerHTML='';
 cells.forEach((c,i)=>{
  const d=document.createElement('div');
  const inp=document.createElement('pre');
  inp.textContent='['+(i+1)+'] '+c.input;d.appendChild(inp);
  const out=document.createElement('pre');out.className='muted';
  out.textContent=typeof c.output==='string'?c.output:
   JSON.stringify(c.output,null,1);d.appendChild(out);
  h.appendChild(d)})}
async function runCell(ast){
 const out=await post('/99/Rapids',{ast});
 cells.push({input:ast,output:out});renderHistory();return out}
async function refreshFlows(){
 const sel=document.getElementById('flist');sel.innerHTML='';
 const ls=await j('/3/NodePersistentStorage/notebook');
 for(const e of (ls.entries||[])){
  const o=document.createElement('option');o.value=e.name;
  o.textContent=e.name;sel.appendChild(o)}}
async function loadFlow(replay){
 const name=document.getElementById('flist').value;if(!name)return;
 const r=await fetch('/3/NodePersistentStorage/notebook/'+
  encodeURIComponent(name));
 const doc=JSON.parse(await r.text());
 if(replay){cells=[];renderHistory();
  for(const c of (doc.cells||[]))await runCell(c.input)}
 else{cells=doc.cells||[];renderHistory()}
 document.getElementById('fname').value=name;refresh()}
document.addEventListener('DOMContentLoaded',()=>{
 document.getElementById('run').onclick=async()=>{
  const ast=document.getElementById('cell').value.trim();
  if(!ast)return;
  show('cellout',await runCell(ast));
  document.getElementById('cell').value='';refresh()};
 document.getElementById('fsave').onclick=async()=>{
  const name=document.getElementById('fname').value.trim();
  if(!name){show('cellout','name the flow first');return}
  await post('/3/NodePersistentStorage/notebook/'+
   encodeURIComponent(name),
   {value:JSON.stringify({version:1,cells})});
  show('cellout','saved flow '+name);refreshFlows()};
 document.getElementById('fload').onclick=()=>loadFlow(false);
 document.getElementById('freplay').onclick=()=>loadFlow(true);
 refreshFlows();
 document.getElementById('imp').onclick=async()=>{
  const path=document.getElementById('ipath').value.trim();
  if(!path)return;
  const up=await post('/3/ImportFiles',{path});
  if(up.http_status){show('impout',up);return}
  const dest=document.getElementById('iname').value.trim()||undefined;
  const srcs=up.destination_frames?up.destination_frames:[up.destination_frame];
  show('impout',await post('/3/Parse',
   {source_frames:srcs,destination_frame:dest}));refresh()};
 document.getElementById('train').onclick=async()=>{
  const algo=document.getElementById('talgo').value;
  let extra={};
  const t=document.getElementById('tparams').value.trim();
  if(t){try{extra=JSON.parse(t)}catch(e){show('trainout','bad JSON: '+e);return}}
  const body=Object.assign({
   training_frame:document.getElementById('tframe').value.trim(),
   response_column:document.getElementById('tresp').value.trim()||undefined},
   extra);
  show('trainout','training…');
  show('trainout',await post('/3/ModelBuilders/'+algo,body));refresh()};
 j('/3/ModelBuilders').then(b=>{
  const sel=document.getElementById('talgo');
  for(const a of Object.keys(b.model_builders).sort()){
   const o=document.createElement('option');o.value=a;o.textContent=a;
   sel.appendChild(o)}});
});
function row(t,cells,th){const tr=document.createElement('tr');
 for(const c of cells){const td=document.createElement(th?'th':'td');
  td.textContent=c;tr.appendChild(td)} t.appendChild(tr)}
async function refresh(){
 const c=await j('/3/Cloud');
 document.getElementById('cloud').textContent=
  c.cloud_name+' — '+c.version+' — devices: '+(c.devices||[]).join(', ');
 const f=document.getElementById('frames');f.innerHTML='';
 row(f,['frame','rows','cols'],true);
 for(const fr of (await j('/3/Frames')).frames)
  row(f,[fr.frame_id.name,fr.rows,fr.num_columns]);
 const m=document.getElementById('models');m.innerHTML='';
 row(m,['model','algo'],true);
 for(const mo of (await j('/3/Models')).models)
  row(m,[mo.model_id.name,mo.algo]);
 const jb=document.getElementById('jobs');jb.innerHTML='';
 row(jb,['job','status','progress','description'],true);
 for(const job of (await j('/3/Jobs')).jobs)
  row(jb,[job.key.name,job.status,Math.round(job.progress*100)+'%',job.description]);
}
refresh();setInterval(refresh,5000);
</script></body></html>"""

    def flow_page(params):
        # (bytes, content-type): the server renders it as HTML, not a
        # download (the plain-bytes branch is octet-stream for models)
        return (_FLOW_HTML.encode(), "text/html; charset=utf-8")

    r.register("GET", "/", flow_page, "Flow-lite console")
    r.register("GET", "/flow/index.html", flow_page, "Flow-lite console")

    # ---- round-4 route groups (ModelMetrics CRUD, model io by URI, NPS,
    # munging utilities, diagnostics) — registered last so they see the
    # fully-populated registry for dispatch-based reuse ----------------------
    from h2o3_tpu.api import handlers_ext, handlers_ops

    handlers_ops.register(r, server)
    handlers_ext.register(r, server)
