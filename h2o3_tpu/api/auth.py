"""Pluggable login backends (the auth SPI).

Reference: ``h2o-webserver-iface/.../LoginType.java`` — NONE / HASH /
LDAP / KERBEROS / SPNEGO / PAM, each a JAAS realm behind jetty's Basic
auth. Here the SPI is a ``LoginBackend`` with ``authenticate(user,
password)``; the server's Basic-auth gate delegates to whichever backend
is configured:

* ``HashFileBackend`` — LoginType.HASH's realm.properties analogue.
  Accepts BOTH entry formats:
    - legacy: ``user:<sha256-hex>``  (single-round, kept for existing
      files)
    - salted: ``user:pbkdf2:<iterations>:<salt-hex>:<hash-hex>``
      (PBKDF2-HMAC-SHA256; generate with ``hash_entry()``)
  All comparisons are constant-time (``hmac.compare_digest``).
* ``LdapBackend`` — LoginType.LDAP, via ``ldap3`` when importable: a
  simple-bind against the configured server with a DN template. The
  image has no ldap3 (and no LDAP server), so construction raises a
  clear error unless the module is present; the SPI seam is what tests
  pin (a stub ldap3 exercises the flow).

KERBEROS / SPNEGO / PAM remain honest refusals (``make_backend`` says
so) — they need system daemons this runtime does not ship.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Dict, Optional


class LoginBackend:
    """SPI: one method, constant-time semantics required of impls."""

    name = "none"

    def authenticate(self, user: str, password: str) -> bool:
        raise NotImplementedError


def hash_entry(user: str, password: str, iterations: int = 120_000,
               salt: Optional[bytes] = None) -> str:
    """One salted hash-file line: ``user:pbkdf2:<iters>:<salt>:<hash>``."""
    salt = salt if salt is not None else os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iterations)
    return f"{user}:pbkdf2:{iterations}:{salt.hex()}:{dk.hex()}"


class HashFileBackend(LoginBackend):
    name = "hash_file"

    def __init__(self, path: str) -> None:
        self._entries: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and ":" in line and not line.startswith("#"):
                    user, spec = line.split(":", 1)
                    self._entries[user] = spec

    def __len__(self) -> int:
        return len(self._entries)

    def authenticate(self, user: str, password: str) -> bool:
        spec = self._entries.get(user)
        if spec is None:
            return False
        if spec.startswith("pbkdf2:"):
            try:
                _tag, iters_s, salt_hex, want_hex = spec.split(":", 3)
                dk = hashlib.pbkdf2_hmac(
                    "sha256", password.encode(), bytes.fromhex(salt_hex),
                    int(iters_s))
            except (ValueError, TypeError):
                return False
            return hmac.compare_digest(dk.hex(), want_hex.lower())
        # legacy single-round sha256 hex
        return hmac.compare_digest(
            hashlib.sha256(password.encode()).hexdigest(), spec.lower())


class LdapBackend(LoginBackend):
    """Simple-bind LDAP auth (LoginType.LDAP / ldaploginmodule).

    ``bind_template`` receives the username, e.g.
    ``uid={},ou=people,dc=example,dc=org``. A successful bind IS the
    authentication, exactly like the JAAS ldaploginmodule's
    authIdentity."""

    name = "ldap"

    def __init__(self, url: str, bind_template: str,
                 _ldap3_module=None) -> None:
        if _ldap3_module is None:
            try:
                import ldap3 as _ldap3_module  # noqa: F811
            except ImportError as e:
                raise RuntimeError(
                    "LDAP login needs the 'ldap3' package, which this "
                    "image does not ship; install it or use "
                    "--hash-login-file") from e
        self._ldap3 = _ldap3_module
        self._url = url
        self._template = bind_template

    def authenticate(self, user: str, password: str) -> bool:
        if not password or any(c in user for c in ",=\0"):
            return False  # no anonymous binds, no DN injection
        dn = self._template.format(user)
        try:
            server = self._ldap3.Server(self._url)
            conn = self._ldap3.Connection(server, user=dn,
                                          password=password)
            ok = bool(conn.bind())
            conn.unbind()
            return ok
        except Exception:
            return False


def make_backend(login_type: str, *, auth_file: Optional[str] = None,
                 ldap_url: Optional[str] = None,
                 ldap_bind_template: Optional[str] = None) -> LoginBackend:
    """Factory keyed on LoginType names (lowercased)."""
    lt = (login_type or "none").lower()
    if lt in ("none", ""):
        raise ValueError("no backend for login_type=none")
    if lt in ("hash", "hash_file"):
        if not auth_file:
            raise ValueError("hash login needs an auth file")
        return HashFileBackend(auth_file)
    if lt == "ldap":
        if not (ldap_url and ldap_bind_template):
            raise ValueError("ldap login needs --ldap-url and "
                             "--ldap-bind-template")
        return LdapBackend(ldap_url, ldap_bind_template)
    if lt in ("kerberos", "spnego", "pam"):
        raise ValueError(
            f"login_type={lt} needs system daemons (JAAS "
            f"{lt}loginmodule) this runtime does not ship; supported: "
            "hash_file, ldap")
    raise ValueError(f"unknown login_type {login_type!r}")
