"""User-defined functions: custom model metrics.

Reference: ``water/udf/`` — ``CFuncRef``/``CMetricFunc``: users upload
metric code that runs in-cluster during scoring (``CFuncTask``); the
jython-cfunc extension loads Python sources the same way.

TPU-native/single-process: a custom metric is a plain Python callable
``fn(actual, predicted) -> float`` over numpy arrays. In-process callers
pass the callable directly; the REST route accepts SOURCE TEXT and is
gated behind ``H2O3_TPU_ENABLE_UDF=1`` because compiling uploaded code is
arbitrary code execution — the same trust model as the reference's
uploaded Jython, but opt-in instead of default-on.
"""

from __future__ import annotations

import os
from typing import Callable, Dict

import numpy as np

from h2o3_tpu.util.log import get_logger

MetricFunc = Callable[[np.ndarray, np.ndarray], float]

#: registered custom metrics by name (CFuncRef's DKV-backed registry)
_REGISTRY: Dict[str, MetricFunc] = {}


def register_metric(name: str, fn: MetricFunc) -> str:
    """Register a callable metric under a name (in-process API)."""
    _REGISTRY[name] = fn
    return name


def get_metric(name: str) -> MetricFunc:
    if name not in _REGISTRY:
        raise KeyError(f"no custom metric {name!r} registered")
    return _REGISTRY[name]


def compile_metric(name: str, source: str) -> str:
    """Compile uploaded metric SOURCE (a module defining ``metric(actual,
    predicted)``) and register it. Gated: uploaded code is code execution.

    Reference: water/udf/CFuncRef + jython-cfunc — the reference runs
    uploaded code by default; here the operator must opt in."""
    if os.environ.get("H2O3_TPU_ENABLE_UDF") != "1":
        raise PermissionError(
            "uploaded UDFs are disabled; set H2O3_TPU_ENABLE_UDF=1 to allow "
            "compiling user metric code on this node"
        )
    namespace: Dict[str, object] = {"np": np, "numpy": np}
    exec(compile(source, f"<udf:{name}>", "exec"), namespace)
    fn = namespace.get("metric")
    if not callable(fn):
        raise ValueError("UDF source must define a callable `metric(actual, predicted)`")
    get_logger("udf").info("registered uploaded metric %r", name)
    _REGISTRY[name] = fn  # type: ignore[assignment]
    return name


def custom_metric(model, frame, fn_or_name) -> float:
    """Evaluate a custom metric for a model on a frame
    (ModelMetrics.CustomMetric analogue): actual response vs the model's
    primary prediction (positive-class probability for binomial, class
    index for multinomial, value for regression)."""
    from h2o3_tpu.models.data_info import response_vector

    fn = get_metric(fn_or_name) if isinstance(fn_or_name, str) else fn_or_name
    frame = model._apply_preprocessors(frame)
    raw = model._predict_raw(frame)
    y = response_vector(model.data_info, frame)
    if model.is_classifier:
        pred = raw[:, 1] if model.nclasses == 2 else raw.argmax(axis=1)
    else:
        pred = raw
    keep = ~np.isnan(y)
    return float(fn(y[keep], np.asarray(pred)[keep]))


# ---------------------------------------------------------------------------
# custom distributions (water/udf/CDistributionFunc.java)


#: registered custom distributions by name: dicts with grad_hess / init /
#: link_inv entries (CDistributionFunc's link/init/gradient/gamma quartet)
_DISTRIBUTIONS: Dict[str, dict] = {}


def register_distribution(name: str, grad_hess, init=None,
                          link_inv=None) -> str:
    """Register a custom boosting objective for GBM's distribution family.

    Reference: ``water/udf/CDistributionFunc.java:12`` — a user-supplied
    (link, init, gradient, gamma) quartet plugged into SharedTree. The
    TPU-native contract: ``grad_hess(y, margin)`` is written with
    **jax.numpy ops** over 1-D arrays and returns ``(g, h)`` — it is
    traced INTO the device training program, so a custom objective runs
    at native kernel speed instead of a per-row host callback.

    ``init(y, weights) -> float`` seeds the starting margin (default:
    weighted mean). ``link_inv(margin) -> mu`` maps margins to the
    response scale at predict time (default: identity).

    Compiled training programs are cached by the objective string
    (``custom:<name>``): re-registering different code under a USED name
    will not recompile already-traced programs — pick a fresh name.
    """
    _DISTRIBUTIONS[name] = {
        "grad_hess": grad_hess, "init": init, "link_inv": link_inv,
    }
    return name


def get_distribution(name: str) -> dict:
    if name not in _DISTRIBUTIONS:
        raise KeyError(
            f"no custom distribution {name!r} registered "
            f"(udf.register_distribution)")
    return _DISTRIBUTIONS[name]
