"""MOJO reader + per-algo numpy scorers.

Reference: ``h2o-genmodel/src/main/java/hex/genmodel/MojoModel.java`` and
the per-algo readers under ``hex/genmodel/algos/{tree,glm,deeplearning,
kmeans,naivebayes,isofor,pca}``.  Scoring semantics mirror the in-cluster
models bit-for-bit (same design-matrix expansion, same tree routing, same
link inverses) so "same answer everywhere" holds — the reference's
cross-language consistency guarantee (SURVEY.md §4 tier 6).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

RowLike = Union[Dict[str, Any], Sequence[Any]]


# ---------------------------------------------------------------------------
# design-matrix expansion from serialized DataInfo (numpy re-implementation of
# h2o3_tpu/models/data_info.py:expand_matrix — kept in sync by parity tests)


class _Columns:
    """Column-major batch input: a dict of equal-length column arrays.

    The batch scoring fast path — ``_Layout._columns`` converts each column
    in one vectorized pass instead of materializing N per-row dicts."""

    def __init__(self, data: Dict[str, Any]) -> None:
        self._data = data
        self._n = len(next(iter(data.values()))) if data else 0

    def __len__(self) -> int:
        return self._n

    def column(self, name: str):
        return self._data.get(name)


class _Layout:
    def __init__(self, info: Dict[str, Any]) -> None:
        self.predictor_names: List[str] = info["predictor_names"]
        self.response_name: Optional[str] = info.get("response_name")
        self.use_all_factor_levels: bool = info["use_all_factor_levels"]
        self.standardize: bool = info["standardize"]
        self.missing_values_handling: str = info["missing_values_handling"]
        self.num_means: Dict[str, float] = info.get("num_means", {})
        self.num_sds: Dict[str, float] = info.get("num_sds", {})
        self.cat_domains: Dict[str, List[str]] = info.get("cat_domains", {})
        self.cat_mode: Dict[str, int] = info.get("cat_mode", {})
        self.coef_names: List[str] = info.get("coef_names", [])
        self.response_domain: Optional[List[str]] = info.get("response_domain")

    def _columns(self, rows):
        """Per-predictor raw columns: float array (num) or int codes (cat).
        Accepts a list of row dicts (streaming path) or a _Columns column
        dict (batch path — one vectorized pass per column, no per-row
        Python objects)."""
        if isinstance(rows, _Columns):
            out = {}
            for name in self.predictor_names:
                v = rows.column(name)
                if v is None:
                    out[name] = (
                        np.full(len(rows), -1, np.int64)
                        if name in self.cat_domains
                        else np.full(len(rows), np.nan)
                    )
                elif name in self.cat_domains:
                    index = {lv: i for i, lv in enumerate(self.cat_domains[name])}
                    codes = np.fromiter(
                        (
                            -1
                            if x is None or (isinstance(x, float) and np.isnan(x))
                            else index.get(str(x), -1)
                            for x in v
                        ),
                        dtype=np.int64,
                        count=len(rows),
                    )
                    out[name] = codes
                else:
                    try:
                        x = np.asarray(v, dtype=np.float64)
                    except (TypeError, ValueError):
                        # element-wise with the row path's semantics:
                        # non-numeric values become NA, never an exception
                        def _f(e):
                            if e is None or e == "":
                                return np.nan
                            try:
                                return float(e)
                            except (TypeError, ValueError):
                                return np.nan
                        x = np.fromiter(
                            (_f(e) for e in v), dtype=np.float64, count=len(rows)
                        )
                    out[name] = x
            return out
        n = len(rows)
        out = {}
        for name in self.predictor_names:
            if name in self.cat_domains:
                dom = self.cat_domains[name]
                index = {lv: i for i, lv in enumerate(dom)}
                codes = np.full(n, -1, dtype=np.int64)
                for i, r in enumerate(rows):
                    v = r.get(name)
                    if v is None or (isinstance(v, float) and np.isnan(v)):
                        continue
                    codes[i] = index.get(str(v), -1)  # unseen level -> NA
                out[name] = codes
            else:
                x = np.full(n, np.nan, dtype=np.float64)
                for i, r in enumerate(rows):
                    v = r.get(name)
                    if v is None or v == "":
                        continue
                    try:
                        x[i] = float(v)
                    except (TypeError, ValueError):
                        pass  # non-numeric in a numeric col -> NA
                out[name] = x
        return out

    def expand(self, rows: List[Dict[str, Any]]) -> np.ndarray:
        """Standardized one-hot design matrix [N, P] (GLM/KMeans/DL layout)."""
        n = len(rows)
        cols = self._columns(rows)
        blocks = []
        for name in self.predictor_names:
            if name in self.cat_domains:
                dom = self.cat_domains[name]
                codes = cols[name]
                na = codes < 0
                if self.missing_values_handling == "mean_imputation":
                    codes = np.where(na, self.cat_mode.get(name, 0), codes)
                start = 0 if self.use_all_factor_levels else 1
                width = len(dom) - start
                block = np.zeros((n, width), dtype=np.float64)
                sel = codes - start
                rows_ix = np.nonzero(sel >= 0)[0]
                block[rows_ix, sel[rows_ix]] = 1.0
                blocks.append(block)
            else:
                x = cols[name]
                x = np.where(np.isnan(x), self.num_means.get(name, 0.0), x)
                if self.standardize:
                    x = (x - self.num_means[name]) / self.num_sds[name]
                blocks.append(x[:, None])
        return (
            np.concatenate(blocks, axis=1)
            if blocks
            else np.zeros((n, 0), dtype=np.float64)
        )

    def raw_matrix(
        self, rows: List[Dict[str, Any]], one_hot: bool = False
    ) -> np.ndarray:
        """[N, F] raw features, NaN NA (tree layout,
        h2o3_tpu/models/tree/common.py:tree_matrix). one_hot mirrors
        categorical_encoding="one_hot_explicit": one 0/1 column per level,
        NA rows NaN across the block."""
        cols = self._columns(rows)
        out = []
        for name in self.predictor_names:
            c = cols[name]
            if name in self.cat_domains:
                if one_hot:
                    dom = self.cat_domains[name]
                    block = (c[:, None] == np.arange(len(dom))[None, :]).astype(
                        np.float64
                    )
                    block[c < 0] = np.nan
                    out.append(block)
                else:
                    out.append(np.where(c >= 0, c.astype(np.float64), np.nan)[:, None])
            else:
                out.append(c[:, None])
        return np.concatenate(out, axis=1).astype(np.float32)


def _as_rows(data: Union[RowLike, List[RowLike]], names: List[str]):
    """Accept a single row dict, a list of row dicts, or a dict of columns."""
    if isinstance(data, dict):
        if data and all(np.iterable(v) and not isinstance(v, str) for v in data.values()):
            n = len(next(iter(data.values())))
            return [{k: data[k][i] for k in data} for i in range(n)], True
        return [data], False
    if isinstance(data, (list, tuple)) and data and isinstance(data[0], dict):
        return list(data), True
    raise TypeError("rows must be a dict row, list of dict rows, or column dict")


# ---------------------------------------------------------------------------
# base


class MojoModel:
    """Loaded offline model (hex/genmodel/MojoModel.java)."""

    algo: str = "?"

    def __init__(self, meta: Dict[str, Any], layout: _Layout, arrays) -> None:
        self.meta = meta
        self.layout = layout
        self._arrays = arrays

    # -- java-GenModel-like surface ------------------------------------------
    @property
    def nclasses(self) -> int:
        dom = self.layout.response_domain
        return len(dom) if dom else 1

    @property
    def is_classifier(self) -> bool:
        return self.nclasses > 1

    @property
    def names(self) -> List[str]:
        return list(self.layout.predictor_names)

    @property
    def domain_values(self) -> Optional[List[str]]:
        return self.layout.response_domain

    def score(self, data) -> np.ndarray:
        """Batch scores: [N] regression / [N, K] class probabilities.
        A dict of column arrays takes the vectorized column path (no
        per-row dict materialization)."""
        if isinstance(data, dict) and data and all(
            np.iterable(v) and not isinstance(v, str) for v in data.values()
        ):
            return self._score_rows(_Columns(data))
        rows, _ = _as_rows(data, self.names)
        return self._score_rows(rows)

    def score0(self, row: RowLike) -> np.ndarray:
        """Single-row score (GenModel.score0)."""
        rows, _ = _as_rows(row, self.names)
        out = self._score_rows(rows)
        return out[0]

    def _score_rows(self, rows: List[Dict[str, Any]]) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def load(path: str) -> "MojoModel":
        return load_mojo(path)

    def __repr__(self) -> str:
        return f"<MojoModel algo={self.algo} nclasses={self.nclasses}>"


# ---------------------------------------------------------------------------
# per-algo scorers


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(m):
    z = m - m.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


class GlmMojoModel(MojoModel):
    """hex/genmodel/algos/glm/GlmMojoModel.java."""

    algo = "glm"

    def _score_rows(self, rows):
        X = self.layout.expand(rows)
        off_col = self.meta.get("offset_column")
        off = 0.0
        if off_col:  # GLMModel._eta adds the per-row offset
            if isinstance(rows, _Columns):
                v = rows.column(off_col)
                if v is None:
                    off = 0.0
                else:
                    # match the row path exactly: None entries are a zero
                    # offset; NaN values propagate
                    off = np.fromiter(
                        (0.0 if e is None else float(e) for e in v),
                        dtype=np.float64, count=len(rows),
                    )
            else:
                off = np.array(
                    [float(r.get(off_col) or 0.0) for r in rows], dtype=np.float64
                )
        family = self.meta["family"]
        if family == "multinomial":  # softmax over per-class etas
            B = self._arrays["beta_multi"]
            eta = X @ B[:-1] + B[-1]
            z = eta - eta.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        if family == "ordinal":  # P(y<=k) = sigmoid(t_k - eta), diffs
            b = self._arrays["beta_std"]  # [P], no intercept slot
            t = self._arrays["thresholds"]
            eta = X @ b + off
            cum = _sigmoid(t[None, :] - eta[:, None])
            full = np.concatenate([cum, np.ones((len(eta), 1))], axis=1)
            lower = np.concatenate([np.zeros((len(eta), 1)), cum], axis=1)
            return np.maximum(full - lower, 1e-15)
        b = self._arrays["beta_std"]
        eta = X @ b[:-1] + b[-1] + off
        link = self.meta["link"]
        if link == "identity":
            mu = eta
        elif link == "logit":
            mu = _sigmoid(eta)
        elif link == "log":
            mu = np.exp(eta)
        elif link == "inverse":
            mu = 1.0 / np.where(np.abs(eta) < 1e-10, np.sign(eta + 1e-30) * 1e-10, eta)
        elif link == "tweedie":
            lp = float(self.meta.get("tweedie_link_power", 0.0))
            mu = np.exp(eta) if lp == 0 else np.power(np.maximum(eta, 1e-10), 1.0 / lp)
        else:
            raise ValueError(f"unknown link {link!r}")
        if self.meta["family"] in ("binomial", "quasibinomial"):
            return np.stack([1 - mu, mu], axis=1)
        return mu


class TreeMojoModel(MojoModel):
    """hex/genmodel/algos/tree/SharedTreeMojoModel.java — heap-layout walk
    identical to models/tree/booster.py:_predict_stacked."""

    algo = "tree"

    def _score_rows(self, rows):
        m = self.meta
        X = self.layout.raw_matrix(
            rows, one_hot=m.get("tree_encoding") == "one_hot_explicit"
        )
        edges = self._arrays["edges"]  # [F, B-1]
        n_bins1 = int(m["n_bins1"])
        nbins = n_bins1 - 1
        # apply_bins (ops/histogram.py): searchsorted right, NA -> nbins
        n, F = X.shape
        bins = np.empty((n, F), dtype=np.int64)
        for f in range(F):
            bins[:, f] = np.searchsorted(edges[f], X[:, f], side="right")
            bins[np.isnan(X[:, f]), f] = nbins
        init_margin = self._arrays["init_margin"]
        C = len(init_margin)
        offset_col = m.get("offset_column")
        offset = None
        if offset_col:
            if isinstance(rows, _Columns):
                v = rows.column(offset_col)
                offset = (
                    np.asarray(v, dtype=np.float64)
                    if v is not None
                    else np.full(len(rows), np.nan)
                )
            else:
                offset = np.full(len(rows), np.nan)
                for i, row in enumerate(rows):
                    v = row.get(offset_col)
                    if v is not None and v != "":
                        offset[i] = float(v)
            if np.isnan(offset).any():
                raise ValueError(
                    f"offset column {offset_col!r} must be present and "
                    f"numeric in every scored row"
                )
        max_depth = int(m["max_depth"])
        average = bool(m.get("average", False))
        margins = np.empty((n, C), dtype=np.float64)
        for c in range(C):
            feat = self._arrays[f"feat_{c}"]
            split_bin = self._arrays[f"split_bin_{c}"]
            default_left = self._arrays[f"default_left_{c}"]
            is_split = self._arrays[f"is_split_{c}"]
            leaf = self._arrays[f"leaf_{c}"]
            T = feat.shape[0]
            total = np.zeros(n, dtype=np.float64)
            for t in range(T):
                idx = np.zeros(n, dtype=np.int64)
                for _ in range(max_depth):
                    f_ = feat[t][idx]
                    b = bins[np.arange(n), f_]
                    is_na = b >= n_bins1 - 1
                    go_left = np.where(is_na, default_left[t][idx], b <= split_bin[t][idx])
                    nxt = 2 * idx + np.where(go_left, 1, 2)
                    idx = np.where(is_split[t][idx], nxt, idx)
                total += leaf[t][idx]
            if average and T > 0:
                total /= T
            margins[:, c] = init_margin[c] + total
            if offset is not None:
                margins[:, c] += offset
        transform = m.get("transform", m["distribution"])
        if transform == "bernoulli":
            p = _sigmoid(margins[:, 0])
            return np.stack([1 - p, p], axis=1)
        if transform == "multinomial":
            return _softmax(margins)
        if transform == "drf_votes":  # DRFModel._predict_raw vote averaging
            if margins.shape[1] == 1:
                p1 = np.clip(margins[:, 0], 0.0, 1.0)
                return np.stack([1 - p1, p1], axis=1)
            p = np.clip(margins, 1e-9, None)
            return p / p.sum(axis=1, keepdims=True)
        if transform == "exp":  # log-link regression (poisson/gamma/tweedie)
            return np.exp(margins[:, 0])
        return margins[:, 0]


class KMeansMojoModel(MojoModel):
    """hex/genmodel/algos/kmeans/KMeansMojoModel.java."""

    algo = "kmeans"

    def _score_rows(self, rows):
        X = self.layout.expand(rows)
        C = self._arrays["centers_std"]
        d2 = (X * X).sum(1, keepdims=True) - 2 * X @ C.T + (C * C).sum(1)[None, :]
        return d2.argmin(axis=1).astype(np.float64)

    def distances(self, data) -> np.ndarray:
        rows, _ = _as_rows(data, self.names)
        X = self.layout.expand(rows)
        C = self._arrays["centers_std"]
        d2 = (X * X).sum(1, keepdims=True) - 2 * X @ C.T + (C * C).sum(1)[None, :]
        return np.sqrt(np.maximum(d2, 0.0))


class DeepLearningMojoModel(MojoModel):
    """hex/genmodel/algos/deeplearning/DeeplearningMojoModel.java."""

    algo = "deeplearning"

    def _score_rows(self, rows):
        X = self.layout.expand(rows).astype(np.float32)
        act = self.meta["activation"]
        n_layers = int(self.meta["n_layers"])
        h = X
        for i in range(n_layers):
            W = self._arrays[f"W_{i}"]
            b = self._arrays[f"b_{i}"]
            h = h @ W + b
            if i < n_layers - 1:
                if act in ("rectifier", "rectifier_with_dropout"):
                    h = np.maximum(h, 0.0)
                elif act in ("tanh", "tanh_with_dropout"):
                    h = np.tanh(h)
                elif act in ("maxout", "maxout_with_dropout"):
                    h = np.maximum(h, 0.0)  # training side uses relu for maxout
                else:
                    raise ValueError(f"unknown activation {act!r}")
        if self.meta.get("autoencoder"):
            return h
        if self.is_classifier:
            return _softmax(h.astype(np.float64))
        return h[:, 0].astype(np.float64)


class NaiveBayesMojoModel(MojoModel):
    """hex/genmodel/algos/naivebayes (reference scores via pojo utils)."""

    algo = "naivebayes"

    def _score_rows(self, rows):
        lay = self.layout
        cols = lay._columns(rows)
        n = len(rows)
        priors = self._arrays["priors"]
        C = len(priors)
        logp = np.tile(np.log(np.maximum(priors, 1e-300)), (n, 1))
        for name in lay.predictor_names:
            if name in lay.cat_domains:
                probs = self._arrays[f"cat_{name}"]  # [C, L]
                codes = cols[name]
                ok = codes >= 0
                contrib = np.zeros((n, C))
                contrib[ok] = np.log(np.maximum(probs[:, codes[ok]].T, 1e-300))
                logp += contrib
            else:
                mean = self._arrays[f"mean_{name}"]  # [C]
                sd = self._arrays[f"sd_{name}"]
                x = cols[name]
                ok = ~np.isnan(x)
                z = (x[ok, None] - mean[None, :]) / sd[None, :]
                contrib = np.zeros((n, C))
                contrib[ok] = -0.5 * z * z - np.log(sd[None, :] * np.sqrt(2 * np.pi))
                logp += contrib
        z = logp - logp.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


class IsolationForestMojoModel(MojoModel):
    """hex/genmodel/algos/isofor/IsolationForestMojoModel.java."""

    algo = "isolation_forest"

    def _score_rows(self, rows):
        X = self.layout.raw_matrix(rows).astype(np.float64)
        feat = self._arrays["feat"]  # [T, M]
        thresh = self._arrays["thresh"]
        is_split = self._arrays["is_split"]
        path_len = self._arrays["path_len"]
        max_depth = int(self.meta["max_depth"])
        cn = float(self.meta["c_norm"])
        n = X.shape[0]
        T = feat.shape[0]
        total = np.zeros(n)
        for t in range(T):
            idx = np.zeros(n, dtype=np.int64)
            for _ in range(max_depth):
                f_ = feat[t][idx]
                x = X[np.arange(n), f_]
                go_left = np.where(np.isnan(x), True, x <= thresh[t][idx])
                nxt = 2 * idx + np.where(go_left, 1, 2)
                idx = np.where(is_split[t][idx], nxt, idx)
            total += path_len[t][idx]
        mean_path = total / max(T, 1)
        return np.power(2.0, -mean_path / max(cn, 1e-9))


class PcaMojoModel(MojoModel):
    """hex/genmodel/algos/pca/PCAMojoModel.java — projection scores."""

    algo = "pca"

    def _score_rows(self, rows):
        X = self.layout.expand(rows)
        # training-time demean/descale statistics (absent for
        # standardize/none, which the layout expansion already applies)
        sub = self._arrays.get("transform_sub")
        mul = self._arrays.get("transform_mul")
        if sub is not None:
            X = X - sub
        if mul is not None:
            X = X * mul
        return X @ self._arrays["eigenvectors"]


_ALGOS = {
    cls.algo: cls
    for cls in (
        GlmMojoModel,
        TreeMojoModel,
        KMeansMojoModel,
        DeepLearningMojoModel,
        NaiveBayesMojoModel,
        IsolationForestMojoModel,
        PcaMojoModel,
    )
}
# tree family shares one scorer
for _name in ("gbm", "drf", "xgboost"):
    _ALGOS[_name] = TreeMojoModel


def load_mojo(path: str) -> MojoModel:
    """hex/genmodel/MojoModel.load — open the zip, dispatch on algo."""
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("meta.json"))
        info = json.loads(z.read("data_info.json"))
        with z.open("arrays.npz") as f:
            arrays = dict(np.load(io.BytesIO(f.read()), allow_pickle=False))
    algo = meta["algo"]
    cls = _ALGOS.get(algo)
    if cls is None:
        raise ValueError(f"no MOJO reader for algo {algo!r}")
    return cls(meta, _Layout(info), arrays)
