"""h2o3_tpu.genmodel — dependency-light offline scoring (numpy only).

Reference: ``h2o-genmodel/`` (21.5k LoC, SURVEY.md §2.6) — the
standalone-jar scoring path: ``MojoModel.load``, per-algo readers in
``h2o-genmodel/.../algos/``, and the row-wise
``EasyPredictModelWrapper`` API.

This package deliberately does NOT import jax or the training stack: a
production scorer needs numpy alone, mirroring the reference's
"dependency-light" genmodel jar. MOJO files written by
``h2o3_tpu.models.mojo_export`` (zip of model.ini + data_info.json +
meta.json + arrays.npz — same structure as the reference's
``model.ini`` + per-algo binary blobs, hex/ModelMojoWriter.java:65-77,
though not byte-compatible with Java H2O).
"""

from h2o3_tpu.genmodel.mojo_model import MojoModel, load_mojo
from h2o3_tpu.genmodel.easy import EasyPredictModelWrapper

__all__ = ["MojoModel", "load_mojo", "EasyPredictModelWrapper"]
