"""Row-wise prediction wrapper with typed results.

Reference: ``h2o-genmodel/.../easy/EasyPredictModelWrapper.java`` — wraps a
GenModel, takes a RowData (map of column name -> value), returns typed
prediction objects (BinomialModelPrediction, RegressionModelPrediction,
MultinomialModelPrediction, ClusteringModelPrediction,
AnomalyDetectionPrediction, DimReductionModelPrediction).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.genmodel.mojo_model import (
    IsolationForestMojoModel,
    KMeansMojoModel,
    MojoModel,
    PcaMojoModel,
)


@dataclass
class BinomialModelPrediction:
    label: str
    label_index: int
    class_probabilities: List[float]


@dataclass
class MultinomialModelPrediction:
    label: str
    label_index: int
    class_probabilities: List[float]


@dataclass
class RegressionModelPrediction:
    value: float


@dataclass
class ClusteringModelPrediction:
    cluster: int
    distances: List[float] = field(default_factory=list)


@dataclass
class AnomalyDetectionPrediction:
    score: float
    normalized_score: float


@dataclass
class DimReductionModelPrediction:
    dimensions: List[float]


@dataclass
class AutoEncoderModelPrediction:
    reconstructed: List[float]
    reconstruction_error: Optional[float] = None


class EasyPredictModelWrapper:
    """easy/EasyPredictModelWrapper.java — one wrapper, typed predict_*."""

    def __init__(self, model: MojoModel, threshold: Optional[float] = None) -> None:
        self.model = model
        self.threshold = threshold

    def predict(self, row: Dict[str, Any]):
        """Dispatch on model category (EasyPredictModelWrapper.predict)."""
        m = self.model
        if isinstance(m, KMeansMojoModel):
            return self.predict_clustering(row)
        if isinstance(m, IsolationForestMojoModel):
            return self.predict_anomaly_detection(row)
        if isinstance(m, PcaMojoModel):
            return self.predict_dim_reduction(row)
        if m.meta.get("autoencoder"):
            return self.predict_autoencoder(row)
        if not m.is_classifier:
            return self.predict_regression(row)
        if m.nclasses == 2:
            return self.predict_binomial(row)
        return self.predict_multinomial(row)

    def predict_binomial(self, row: Dict[str, Any]) -> BinomialModelPrediction:
        probs = np.asarray(self.model.score0(row), dtype=np.float64)
        # label threshold priority: wrapper override > exported training
        # max-F1 threshold (matches in-cluster Model.predict) > 0.5
        thr = self.threshold
        if thr is None:
            thr = self.model.meta.get("default_threshold", 0.5)
        idx = int(probs[1] >= thr)
        dom = self.model.domain_values or ["0", "1"]
        return BinomialModelPrediction(
            label=dom[idx], label_index=idx, class_probabilities=probs.tolist()
        )

    def predict_multinomial(self, row: Dict[str, Any]) -> MultinomialModelPrediction:
        probs = np.asarray(self.model.score0(row), dtype=np.float64)
        idx = int(probs.argmax())
        dom = self.model.domain_values or [str(i) for i in range(len(probs))]
        return MultinomialModelPrediction(
            label=dom[idx], label_index=idx, class_probabilities=probs.tolist()
        )

    def predict_regression(self, row: Dict[str, Any]) -> RegressionModelPrediction:
        return RegressionModelPrediction(value=float(self.model.score0(row)))

    def predict_clustering(self, row: Dict[str, Any]) -> ClusteringModelPrediction:
        m = self.model
        cluster = int(m.score0(row))
        dists = m.distances(row)[0].tolist() if isinstance(m, KMeansMojoModel) else []
        return ClusteringModelPrediction(cluster=cluster, distances=dists)

    def predict_anomaly_detection(self, row: Dict[str, Any]) -> AnomalyDetectionPrediction:
        s = float(self.model.score0(row))
        return AnomalyDetectionPrediction(score=s, normalized_score=s)

    def predict_dim_reduction(self, row: Dict[str, Any]) -> DimReductionModelPrediction:
        return DimReductionModelPrediction(
            dimensions=np.asarray(self.model.score0(row), dtype=np.float64).tolist()
        )

    def predict_autoencoder(self, row: Dict[str, Any]) -> AutoEncoderModelPrediction:
        recon = np.asarray(self.model.score0(row), dtype=np.float64)
        X = self.model.layout.expand([row])[0]
        err = float(np.mean((recon - X) ** 2)) if recon.shape == X.shape else None
        return AutoEncoderModelPrediction(
            reconstructed=recon.tolist(), reconstruction_error=err
        )
