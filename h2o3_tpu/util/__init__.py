"""Host-side utilities: logging, timeline tracing, diagnostics."""
