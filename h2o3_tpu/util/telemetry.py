"""Process-wide telemetry: metrics registry + span-correlated tracing.

Reference: H2O-3 ships first-class self-observability — ``/3/Timeline``,
``/3/Profiler``, ``/3/Logs`` and the WaterMeter CPU/IO gauges (``water/api/
WaterMeterCpuTicksHandler.java``) — but no *quantitative* layer: nothing in
the seed counted REST requests, jit compile-cache misses, map_reduce
dispatches, bytes ingested or store churn.  This module is that layer:

* a lock-protected process-wide :class:`Registry` of :class:`Counter` /
  :class:`Gauge` / :class:`Histogram` families with labels, snapshot-able as
  JSON (``GET /3/Metrics``) and as Prometheus text exposition format v0.0.4
  (``GET /3/Metrics/prometheus``);
* a :class:`Span` context that threads a ``trace_id``/``parent_id`` through
  nested work (REST request -> model fit -> map_reduce dispatch) and records
  enriched events into the existing :mod:`h2o3_tpu.util.timeline` ring, so
  ``/3/Timeline`` becomes correlatable — every plain ``timeline.record``
  under an open span inherits the span's trace ids via the trace provider
  hook installed below;
* a ``jax.monitoring`` listener that counts XLA backend compiles process-wide
  (``jit_compiles_total`` / ``jit_compile_seconds_total``), the substrate for
  per-dispatch jit cache hit/miss accounting in ``compute/mapreduce.py``.

The TPU-native story (SURVEY.md §5): ``jax.profiler`` owns the device-side
trace; this registry owns the host-side control-plane numbers that DrJAX-style
per-primitive accounting needs before any hot path can be called "measurably
faster".
"""

from __future__ import annotations

import math
import random
import re
import threading
import time
from typing import (
    Any,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from h2o3_tpu.util import timeline

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "Span",
    "counter",
    "gauge",
    "histogram",
    "current_span",
    "current_trace_id",
    "current_trace_context",
    "install_jax_compile_listener",
    "jit_compile_count",
    "merge_snapshots",
    "node_name",
    "node_scope",
    "set_node_name",
    "snapshot_prometheus",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets (seconds-flavored; jit compiles and model fits
#: span sub-ms REST pings to multi-minute training blocks)
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _escape_label(v: Any) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(h: str) -> str:
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Bound:
    """A pre-validated handle on ONE series of a metric family: label
    checking and key construction happen once at :meth:`Metric.bind` time,
    so the per-event cost on a hot path (the RPC in-flight gauge ticks
    twice per call) drops to a lock plus a dict op.  The update logic
    stays on the metric class (``_inc_key``/``_set_key``/``_observe_key``),
    so a handle keeps its metric's type discipline — ``observe`` on a
    gauge-bound handle is an AttributeError, not silent corruption."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Metric", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc_key(self._key, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._metric._inc_key(self._key, -amount)

    def set(self, value: float) -> None:
        self._metric._set_key(self._key, value)

    def observe(self, value: float) -> None:
        self._metric._observe_key(self._key, value)


class Metric:
    """One metric family: a name + help + fixed label names, holding one
    series per distinct label-value tuple. All mutation is lock-protected
    (REST handler threads, training threads and the compile listener all
    write concurrently)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} for metric {name!r}")
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Mapping[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def bind(self, **labels: Any) -> _Bound:
        """Pre-resolve a label set into a cheap single-series handle
        (validates the labels now, never again)."""
        return _Bound(self, self._key(labels))

    def _label_str(self, key: Tuple[str, ...]) -> str:
        if not self.labelnames:
            return ""
        pairs = ",".join(
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.labelnames, key)
        )
        return "{" + pairs + "}"

    # -- shared exposition scaffolding --------------------------------------
    def _header(self) -> List[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out

    def expose(self) -> List[str]:
        raise NotImplementedError

    def snapshot(self) -> Dict[str, Any]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing count (rest_requests_total, ...)."""

    kind = "counter"

    def _inc_key(self, key: Tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self._inc_key(self._key(labels), amount)

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def total(self) -> float:
        """Sum over every label combination (the /3/Cloud summary number)."""
        with self._lock:
            return float(sum(self._series.values()))

    def expose(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{self._label_str(key)} {_fmt_value(v)}")
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            items = sorted(self._series.items())
        return {
            "type": self.kind,
            "help": self.help,
            "series": [
                {"labels": dict(zip(self.labelnames, key)), "value": v}
                for key, v in items
            ],
        }


class _GaugeTrack:
    """with-block in-flight accounting: inc on entry, dec on exit.  Key
    resolution happens once at :meth:`Gauge.track` time, so entering the
    block on a hot path (one per REST request) is a lock plus a dict op."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Gauge", key: Tuple[str, ...]) -> None:
        self._metric = metric
        self._key = key

    def __enter__(self) -> "_GaugeTrack":
        self._metric._inc_key(self._key, 1.0)
        return self

    def __exit__(self, *exc: Any) -> None:
        self._metric._inc_key(self._key, -1.0)


class Gauge(Metric):
    """A value that goes both ways (dkv_keys, mesh_devices, ...)."""

    kind = "gauge"

    def _set_key(self, key: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._series[key] = float(value)

    def _inc_key(self, key: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def set(self, value: float, **labels: Any) -> None:
        self._set_key(self._key(labels), value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self._inc_key(self._key(labels), amount)

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self._inc_key(self._key(labels), -amount)

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def track(self, **labels: Any) -> _GaugeTrack:
        """Context manager: inc on entry, dec on exit — the in-flight
        idiom (http_inflight while a request is admitted, connections
        while open) without the try/finally boilerplate."""
        return _GaugeTrack(self, self._key(labels))

    expose = Counter.expose
    snapshot = Counter.snapshot


class Histogram(Metric):
    """Cumulative-bucket histogram (model_fit_seconds, rest_request_seconds).

    Exposition follows the Prometheus contract: ``_bucket{le=...}`` lines are
    cumulative, the ``+Inf`` bucket equals ``_count``, plus ``_sum``."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        super().__init__(name, help, labels)
        # the +Inf bucket is implicit (it IS _count); an explicit inf here
        # would double the le="+Inf" exposition line and put a non-JSON
        # Infinity token into the /3/Metrics payload
        bs = tuple(sorted(
            b for b in (buckets if buckets is not None else DEFAULT_BUCKETS)
            if not math.isinf(b)
        ))
        if not bs:
            raise ValueError("histogram needs at least one finite bucket")
        self.buckets: Tuple[float, ...] = bs

    def _observe_key(self, key: Tuple[str, ...], value: float) -> None:
        v = float(value)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = {
                    "buckets": [0] * len(self.buckets), "sum": 0.0, "count": 0,
                }
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    st["buckets"][i] += 1
                    break
            st["sum"] += v
            st["count"] += 1

    def observe(self, value: float, **labels: Any) -> None:
        self._observe_key(self._key(labels), value)

    def count(self, **labels: Any) -> int:
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            return int(st["count"]) if st else 0

    def total_count(self) -> int:
        with self._lock:
            return int(sum(st["count"] for st in self._series.values()))

    def expose(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(
                (k, list(st["buckets"]), st["sum"], st["count"])
                for k, st in self._series.items()
            )
        for key, counts, total, n in items:
            cum = 0
            for ub, c in zip(self.buckets, counts):
                cum += c
                le = dict(zip(self.labelnames, key))
                pairs = [f'{k}="{_escape_label(v)}"' for k, v in le.items()]
                pairs.append(f'le="{_fmt_value(ub)}"')
                out.append(
                    f"{self.name}_bucket{{{','.join(pairs)}}} {cum}"
                )
            pairs = [
                f'{k}="{_escape_label(v)}"'
                for k, v in zip(self.labelnames, key)
            ]
            pairs_inf = pairs + ['le="+Inf"']
            out.append(f"{self.name}_bucket{{{','.join(pairs_inf)}}} {n}")
            suffix = "{" + ",".join(pairs) + "}" if pairs else ""
            out.append(f"{self.name}_sum{suffix} {_fmt_value(total)}")
            out.append(f"{self.name}_count{suffix} {n}")
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            items = sorted(
                (k, list(st["buckets"]), st["sum"], st["count"])
                for k, st in self._series.items()
            )
        return {
            "type": self.kind,
            "help": self.help,
            "buckets": list(self.buckets),
            "series": [
                {
                    "labels": dict(zip(self.labelnames, key)),
                    "bucket_counts": counts,
                    "sum": total,
                    "count": n,
                }
                for key, counts, total, n in items
            ],
        }


class Registry:
    """Process-wide metric catalog. ``counter/gauge/histogram`` are
    get-or-create: re-registration with matching type+labels returns the
    existing family (instrumented modules declare their metrics at import
    time, in any order), a mismatch raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Sequence[str], **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.labelnames}"
                    )
                want = kw.get("buckets")
                if want is not None and tuple(sorted(
                    b for b in want if not math.isinf(b)
                )) != m.buckets:
                    # silently handing back different buckets would skew
                    # the second caller's quantiles with no error
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}"
                    )
                return m
            m = cls(name, help, labels, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every family (the /3/Metrics payload)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in metrics}

    def prometheus(self) -> str:
        """Text exposition format v0.0.4 (one family block per metric)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for _, m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n" if lines else ""

    def summary(self) -> Dict[str, float]:
        """Compact totals for /3/Cloud and the bench artifact: every counter
        and histogram collapsed over labels, gauges as-is when unlabeled."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: Dict[str, float] = {}
        for name, m in metrics:
            if isinstance(m, Histogram):
                out[name + "_count"] = m.total_count()
            elif isinstance(m, Counter):
                out[name] = m.total()
            elif isinstance(m, Gauge) and not m.labelnames:
                out[name] = m.value()
        return out

def merge_snapshots(
    per_node: Mapping[str, Mapping[str, Any]]
) -> Dict[str, Any]:
    """Merge per-node :meth:`Registry.snapshot` payloads into one cluster
    view (the ``GET /3/Metrics?cluster=true`` body).

    Every series gains a ``node=`` label so per-member numbers stay
    visible.  Counters and histograms additionally get a ``node="_cluster"``
    aggregate per distinct label set — counters sum across nodes; histogram
    bucket counts, sums and counts add (one codebase per cloud, so bucket
    bounds match; a family whose bucket layout disagrees across nodes keeps
    only the per-node series).  Gauges stay strictly per-node: summing one
    member's free memory into another's means nothing.
    """
    merged: Dict[str, Dict[str, Any]] = {}
    for node in sorted(per_node):
        snap = per_node[node] or {}
        for name, fam in snap.items():
            slot = merged.setdefault(name, {
                "type": fam.get("type", "untyped"),
                "help": fam.get("help", ""),
                "series": [],
            })
            if "buckets" in fam and "buckets" not in slot:
                slot["buckets"] = list(fam["buckets"])
            for s in fam.get("series", []):
                entry = dict(s)
                entry["labels"] = {**s.get("labels", {}), "node": node}
                slot["series"].append(entry)
    for name, fam in merged.items():
        base_keys = [
            tuple(sorted(
                (k, v) for k, v in s["labels"].items() if k != "node"))
            for s in fam["series"]
        ]
        if fam["type"] == "counter":
            agg: Dict[Tuple, float] = {}
            for key, s in zip(base_keys, fam["series"]):
                agg[key] = agg.get(key, 0.0) + float(s.get("value", 0.0))
            for key in sorted(agg):
                fam["series"].append({
                    "labels": {**dict(key), "node": "_cluster"},
                    "value": agg[key],
                })
        elif fam["type"] == "histogram":
            nb = len(fam.get("buckets", ()))
            if any(len(s.get("bucket_counts", ())) != nb
                   for s in fam["series"]):
                continue  # bucket-layout skew: per-node series only
            hagg: Dict[Tuple, Dict[str, Any]] = {}
            for key, s in zip(base_keys, fam["series"]):
                st = hagg.setdefault(key, {
                    "bucket_counts": [0] * nb, "sum": 0.0, "count": 0})
                st["bucket_counts"] = [
                    a + b for a, b in
                    zip(st["bucket_counts"], s["bucket_counts"])]
                st["sum"] += float(s.get("sum", 0.0))
                st["count"] += int(s.get("count", 0))
            for key in sorted(hagg):
                fam["series"].append({
                    "labels": {**dict(key), "node": "_cluster"},
                    **hagg[key],
                })
    return merged


def snapshot_prometheus(snapshot: Mapping[str, Any]) -> str:
    """Render a snapshot dict (one node's :meth:`Registry.snapshot` or a
    :func:`merge_snapshots` result) as Prometheus text exposition v0.0.4 —
    the federation path cannot use :meth:`Registry.prometheus` because the
    merged series exist only as JSON, never as live Metric objects."""
    lines: List[str] = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        kind = fam.get("type", "untyped")
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for s in fam.get("series", []):
            pairs = [
                '%s="%s"' % (k, _escape_label(v))
                for k, v in s.get("labels", {}).items()
            ]

            def _suffixed(extra_pair: Optional[str] = None) -> str:
                ps = pairs + ([extra_pair] if extra_pair else [])
                return "{" + ",".join(ps) + "}" if ps else ""

            if kind == "histogram":
                cum = 0
                for ub, c in zip(fam.get("buckets", ()),
                                 s.get("bucket_counts", ())):
                    cum += c
                    le = 'le="%s"' % _fmt_value(ub)
                    lines.append(f"{name}_bucket{_suffixed(le)} {cum}")
                n = int(s.get("count", 0))
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_suffixed(inf)} {n}")
                lines.append(
                    f"{name}_sum{_suffixed()} "
                    f"{_fmt_value(float(s.get('sum', 0.0)))}")
                lines.append(f"{name}_count{_suffixed()} {n}")
            else:
                lines.append(
                    f"{name}{_suffixed()} "
                    f"{_fmt_value(float(s.get('value', 0.0)))}")
    return "\n".join(lines) + "\n" if lines else ""


#: The process-wide registry — the analogue of the one WaterMeter per node.
#: Deliberately no reset(): instrumented modules hold direct references to
#: their families, so clearing the catalog would split-brain the process
#: (stale objects still incremented, fresh ones exposed). Tests wanting
#: isolation construct their own Registry.
REGISTRY = Registry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets)


# ---------------------------------------------------------------------------
# Span-correlated tracing


_tls = threading.local()

#: span/trace id minting: a process-seeded PRNG formatted as 16 hex chars.
#: uuid4 costs ~2.5us per id; at three spans per traced RPC that is real
#: money against a ~100us loopback round trip — getrandbits is ~5x cheaper
#: and 64 random bits is ample for correlating events inside one ring
_ids = random.Random()


def _new_id() -> str:
    return "%016x" % _ids.getrandbits(64)


#: process-global node identity (set by the cluster bootstrap); every
#: timeline event and span records it so a merged cluster timeline can
#: attribute events to the member that emitted them
_node_name: Optional[str] = None


def set_node_name(name: Optional[str]) -> None:
    """Declare this process's cluster node name (``boot_node`` calls it);
    every subsequently recorded timeline event carries ``node=<name>``."""
    global _node_name
    _node_name = name


def node_name() -> Optional[str]:
    """The effective node identity: a thread-local :class:`node_scope`
    override (the RPC serving path) wins over the process-global name."""
    override = getattr(_tls, "node", None)
    return override if override is not None else _node_name


class node_scope:
    """Thread-local node-identity override: the RPC server dispatches a
    remote call under the *serving* cloud's name so events recorded during
    the call attribute correctly even with several in-process Clouds (the
    single-process test harness)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._prev: Optional[str] = None

    def __enter__(self) -> "node_scope":
        self._prev = getattr(_tls, "node", None)
        _tls.node = self.name
        return self

    def __exit__(self, *exc) -> None:
        _tls.node = self._prev


def _span_stack() -> List["Span"]:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


def current_span() -> Optional["Span"]:
    stack = _span_stack()
    return stack[-1] if stack else None


def current_trace_id() -> Optional[str]:
    sp = current_span()
    return sp.trace_id if sp else None


def current_trace_context() -> Optional[Dict[str, str]]:
    """``{"trace_id", "span_id"}`` of the calling thread's open span, or
    None — the envelope the RPC client injects so a remote child span can
    join the caller's trace."""
    sp = current_span()
    if sp is None or sp.trace_id is None:
        return None
    return {"trace_id": sp.trace_id, "span_id": sp.span_id}


def _trace_fields() -> Optional[Dict[str, Any]]:
    """Trace context injected into plain ``timeline.record`` calls made under
    an open span (the provider hook; the recording code stays span-unaware).
    Also stamps the recording node's identity when one is declared, so every
    event in a merged cluster timeline names its origin."""
    out: Dict[str, Any] = {}
    node = node_name()
    if node:
        out["node"] = node
    sp = current_span()
    if sp is not None:
        out["trace_id"] = sp.trace_id
        out["span_id"] = sp.span_id
    return out or None


timeline.set_trace_provider(_trace_fields)

# the log ring gets the same correlation: lines emitted under an open span
# carry its trace/span ids, so /3/Logs lines line up with /3/Timeline traces
from h2o3_tpu.util import log as _log  # noqa: E402  (import-light, no cycle)

_log.set_trace_provider(current_trace_context)


class Span:
    """Context manager: a unit of traced work.

    The outermost span mints a fresh ``trace_id``; nested spans inherit it and
    point at their parent via ``parent_id``. On exit one enriched event lands
    in the timeline ring (kind + duration_ms + ok + ids + node + fields) — the
    same shape ``timeline.timed`` wrote, now correlatable across layers. Spans
    are thread-local: a REST handler thread's trace does not leak into a
    concurrently training thread.

    ``trace_id``/``parent_id`` may be passed explicitly to continue a trace
    that started somewhere else — another thread (a fan-out worker joining
    its caller's trace) or another *node* (the RPC server parenting its
    dispatch span under the caller's envelope context). An explicit context
    wins over the thread-local parent."""

    __slots__ = ("kind", "fields", "span_id", "trace_id", "parent_id",
                 "_explicit", "t0")

    def __init__(self, kind: str, *, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None, **fields: Any) -> None:
        self.kind = kind
        self.fields = fields
        self.span_id = _new_id()
        self.trace_id: Optional[str] = trace_id
        self.parent_id: Optional[str] = parent_id
        self._explicit = trace_id is not None
        self.t0 = 0.0

    def set(self, **fields: Any) -> "Span":
        """Attach fields discovered mid-span (iterations, rows, ...)."""
        self.fields.update(fields)
        return self

    def __enter__(self) -> "Span":
        if not self._explicit:
            parent = current_span()
            if parent is not None:
                self.trace_id = parent.trace_id
                self.parent_id = parent.span_id
            else:
                self.trace_id = _new_id()
                # a parent_id passed WITHOUT a trace_id would dangle into
                # no trace (e.g. a proxy dropped the trace header but kept
                # the span header) — a fresh trace starts at a root
                self.parent_id = None
        _span_stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        duration_ms = round((time.perf_counter() - self.t0) * 1e3, 3)
        stack = _span_stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # tolerate exotic unwinding, never corrupt peers
            stack.remove(self)
        evt = {
            "kind": self.kind,
            "duration_ms": duration_ms,
            "ok": exc_type is None,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
        }
        # the event carries an explicit trace_id, so the provider hook is
        # bypassed — stamp the node identity here too
        node = node_name()
        if node and "node" not in self.fields:
            evt["node"] = node
        if self.fields:
            evt.update(self.fields)
        timeline.record_event(evt)


# ---------------------------------------------------------------------------
# XLA compile accounting (jax.monitoring)

_JIT_COMPILES = counter(
    "jit_compiles_total",
    "XLA backend compiles observed process-wide (jax.monitoring)",
)
_JIT_COMPILE_SECS = counter(
    "jit_compile_seconds_total",
    "total wall seconds spent in XLA backend compiles",
)

_jit_listener_lock = threading.Lock()
_jit_listener_installed = False
#: per-thread compile count: XLA compiles run synchronously on the thread
#: that triggered them, so a thread-local delta attributes cache misses to
#: the right dispatch even when builds run concurrently (a global delta
#: would blame thread A for thread B's compile)
_tls_compiles = threading.local()


def install_jax_compile_listener() -> bool:
    """Register the process-wide compile listener once; idempotent.

    Returns False when jax (or jax.monitoring) is unavailable — telemetry
    must never be the reason a host-only code path imports the backend."""
    global _jit_listener_installed
    with _jit_listener_lock:
        if _jit_listener_installed:
            return True
        try:
            from jax import monitoring
        except Exception:  # pragma: no cover - jax is baked into the image
            return False

        def _on_duration(name: str, secs: float, **kw: Any) -> None:
            if name.endswith("backend_compile_duration"):
                _JIT_COMPILES.inc()
                _JIT_COMPILE_SECS.inc(secs)
                _tls_compiles.count = getattr(_tls_compiles, "count", 0) + 1
                _tls_compiles.seconds = (
                    getattr(_tls_compiles, "seconds", 0.0) + secs)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _jit_listener_installed = True
        return True


def jit_compile_count() -> float:
    """Total compiles observed process-wide (the bench/summary number)."""
    return _JIT_COMPILES.total()


def thread_compile_count() -> int:
    """Compiles observed on the CALLING thread — per-dispatch deltas give
    correct cache hit/miss attribution under concurrent builds."""
    return getattr(_tls_compiles, "count", 0)


def thread_compile_seconds() -> float:
    """Compile wall seconds observed on the CALLING thread; the cost
    ledger charges per-dispatch deltas of this to the open trace."""
    return getattr(_tls_compiles, "seconds", 0.0)
