"""Process-wide logging (water/util/Log.java analogue).

Reference: log4j-backed ``Log`` with per-node files in the ice dir,
buffered early logging before the file location is known, and ``/3/Logs``
download from any node (``water/util/Log.java:26,103,258-269``,
``util/GetLogsFromNode.java``).

TPU-native/single-process: stdlib ``logging`` under the ``h2o3_tpu`` root
logger, with (a) an in-memory ring of recent records that the ``/3/Logs``
route serves without touching disk, and (b) an optional rotating file in
the ice dir (``H2O3_TPU_LOG_DIR`` or init(dir=...)).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, List, Optional

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s: %(message)s"
_ROOT = "h2o3_tpu"

_lock = threading.Lock()
_ring: Deque[str] = collections.deque(maxlen=4096)
_file_path: Optional[str] = None
_initialized = False

#: Optional trace-context provider (installed by util/telemetry.py):
#: returns {"trace_id": ..., "span_id": ...} for the calling thread's open
#: span, or None.  A hook — not an import — so this module stays
#: dependency-free (mirrors util/timeline.py's provider).
_trace_provider = None


def set_trace_provider(fn) -> None:
    """Install a callable returning the current trace context; log lines
    emitted under an open span then carry ``[trace=... span=...]`` so
    ``/3/Logs`` correlates with ``/3/Timeline`` (and the cross-node log
    proxy ships the ids along for free — they are part of the line)."""
    global _trace_provider
    _trace_provider = fn


class _RingHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)  # format outside the lock
            if _trace_provider is not None:
                try:
                    ctx = _trace_provider()
                except Exception:  # tracing must never break logging
                    ctx = None
                if ctx and ctx.get("trace_id"):
                    line += (f" [trace={ctx['trace_id']}"
                             f" span={ctx['span_id']}]")
            with _lock:
                # ring access is consistently lock-protected: recent()
                # copies under _lock, so appends must happen under it too
                # (list(deque) during a concurrent append is a RuntimeError)
                _ring.append(line)
        except Exception:  # pragma: no cover - never raise from logging
            pass


def init(dir: Optional[str] = None, level: int = logging.INFO) -> None:
    """Install the ring (+ optional file) handlers once; idempotent."""
    global _initialized, _file_path
    with _lock:
        if _initialized:
            return
        root = logging.getLogger(_ROOT)
        root.setLevel(level)
        fmt = logging.Formatter(_FORMAT)
        rh = _RingHandler()
        rh.setFormatter(fmt)
        root.addHandler(rh)
        dir = dir or os.environ.get("H2O3_TPU_LOG_DIR")
        if dir:
            os.makedirs(dir, exist_ok=True)
            _file_path = os.path.join(
                dir, f"h2o3_tpu_{os.getpid()}_{int(time.time())}.log"
            )
            fh = logging.FileHandler(_file_path)
            fh.setFormatter(fmt)
            root.addHandler(fh)
        _initialized = True


def get_logger(name: str) -> logging.Logger:
    """Module logger under the package root; auto-initializes the sinks."""
    init()
    if not name.startswith(_ROOT):
        name = f"{_ROOT}.{name}"
    return logging.getLogger(name)


def recent(n: int = 1000) -> List[str]:
    """Last n formatted log lines (the /3/Logs payload)."""
    with _lock:
        return list(_ring)[-n:]


def log_file() -> Optional[str]:
    return _file_path
