"""Node-persistent storage — named blobs that survive the process.

Reference: ``water/init/NodePersistentStorage.java`` + the 8
``/3/NodePersistentStorage`` routes (``RegisterV3Api.java``): a tiny
category/name -> value store Flow uses to save notebooks. Here it is a
directory tree under the ice root (one file per value); names are
sanitised to single path segments so a crafted name can never escape
the root.
"""

from __future__ import annotations

import os
import re
import tempfile
import time
from typing import Dict, List, Optional

_SAFE = re.compile(r"[^A-Za-z0-9._-]")


def _root() -> str:
    return os.environ.get("H2O3_TPU_NPS_ROOT") or os.path.join(
        os.environ.get("H2O3_TPU_ICE_ROOT")
        or os.path.join(tempfile.gettempdir(), "h2o3_tpu_ice"),
        "nps",
    )


def _seg(name: str) -> str:
    s = _SAFE.sub("_", name or "")
    if not s or s in (".", ".."):
        raise ValueError(f"bad NPS name {name!r}")
    return s


def configured() -> bool:
    return True  # always backed by the ice dir (no -flow_dir flag needed)


def put(category: str, name: str, value: bytes) -> Dict[str, object]:
    d = os.path.join(_root(), _seg(category))
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, _seg(name))
    with open(path, "wb") as f:
        f.write(value)
    return {"category": category, "name": name, "total_bytes": len(value)}


def get(category: str, name: str) -> bytes:
    path = os.path.join(_root(), _seg(category), _seg(name))
    with open(path, "rb") as f:
        return f.read()


def exists(category: str, name: Optional[str] = None) -> bool:
    if name is None:
        return os.path.isdir(os.path.join(_root(), _seg(category)))
    return os.path.isfile(os.path.join(_root(), _seg(category), _seg(name)))


def delete(category: str, name: str) -> bool:
    path = os.path.join(_root(), _seg(category), _seg(name))
    try:
        os.unlink(path)
        return True
    except FileNotFoundError:
        return False


def list_entries(category: str) -> List[Dict[str, object]]:
    d = os.path.join(_root(), _seg(category))
    out = []
    if os.path.isdir(d):
        for n in sorted(os.listdir(d)):
            p = os.path.join(d, n)
            st = os.stat(p)
            out.append({"category": category, "name": n,
                        "size": st.st_size,
                        "timestamp_millis": int(st.st_mtime * 1000)})
    return out


def new_name() -> str:
    return f"nps_{int(time.time() * 1000)}"
