"""Per-node flight recorder: the bounded event ring the health plane reads.

The ledger (PR 14) answers "what did that trace COST"; this module answers
"what was this node DOING" — before a wedge, during a stall, after a
crash.  It is H2O-3's water timeline recast for the health plane: a
process-wide, lock-leaf, bounded ring of structured events written at the
same choke points the ledger already charges into:

* RPC client dispatch outcomes + every retry-ladder attempt
  (``cluster/rpc.py``), server-side dispatch faults,
* fan-out range scheduling and recovery-ladder rungs
  (``cluster/tasks.py``, ``cluster/frames.py``, ``cluster/search.py``,
  ``models/tree/dist_hist.py``),
* membership suspicion / tombstone / rejoin transitions
  (``cluster/membership.py``),
* coalescer batch open/close and HTTP shed (``api/coalesce.py``,
  ``api/server.py``), devcache evictions (``frame/devcache.py``),
* watchdog verdict transitions and stack dumps
  (``cluster/health.py``).

Each event is a compact dict: monotonic ``seq``, wall-clock ``ts_ms``,
``category`` (closed vocabulary below), ``severity`` (info/warn/error/
critical), the recording ``node``, the open span's ``trace_id`` when one
exists, and a small payload.  The ring holds the last
``H2O3_TPU_FLIGHT_EVENTS`` (default 2048) events; older events are
overwritten — a flight recorder, not a log.

Crash/stall capture: :func:`install_crash_hooks` wires ``SIGUSR2`` (and
the watchdog's stall escalation calls :func:`dump_stacks` directly) to
dump every thread's stack INTO the ring, arms ``faulthandler`` so fatal
signals append C-level tracebacks to a sidecar file, and registers an
``atexit`` hook persisting the final ring to
``$H2O3_TPU_FLIGHT_CRASH_DIR/flight-<node>-<pid>.json`` (crash files are
written only when that knob names a directory).  ``scripts/diag_view.py``
renders the saved file.

In-flight fan-out state: :class:`FanoutTracker` (module instance
``FANOUTS``) is the registry the ``fanout_stalled`` watchdog rule reads —
``begin()`` at scheduling time, ``progress()`` per completed range,
``end()`` in a finally.  Pure dict work under a leaf lock.

Locking discipline (LOCK001): the ring lock is a LEAF — pure
list/dict/deque work, no RPC, no I/O, no other lock — so any choke point
may record while holding its own lock (devcache eviction does); the
``flight_events_total{category}`` meter ticks after the lock releases.
``H2O3_TPU_FLIGHT=0`` disables recording entirely (the --obs-bench A/B
switch flips the same flag at runtime).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from h2o3_tpu.util import telemetry

__all__ = [
    "FlightRecorder",
    "FanoutTracker",
    "RECORDER",
    "FANOUTS",
    "record",
    "set_enabled",
    "dump_stacks",
    "install_crash_hooks",
    "persist_crash",
    "crash_path",
    "set_crash_extras",
    # event-category constants (the closed vocabulary)
    "RPC",
    "FANOUT",
    "RECOVERY",
    "MEMBERSHIP",
    "COALESCE",
    "DEVCACHE",
    "HEALTH",
    "STACKS",
    "CRASH",
]

#: the closed category vocabulary — one constant per choke-point family,
#: so ``flight_events_total{category}`` and the /3/Diagnostics bundle read
#: the same on every node
RPC = "rpc"
FANOUT = "fanout"
RECOVERY = "recovery"
MEMBERSHIP = "membership"
COALESCE = "coalesce"
DEVCACHE = "devcache"
HEALTH = "health"
STACKS = "stacks"
CRASH = "crash"

#: severities, worst-last (diag_view sorts with this)
SEVERITIES = ("info", "warn", "error", "critical")

_EVENTS = telemetry.counter(
    "flight_events_total",
    "flight-recorder events appended to the ring, by category",
    labels=("category",),
)

#: per-category bound counter handles (categories are a small closed set)
_event_bound: Dict[str, telemetry._Bound] = {}


def _bound_event(category: str) -> telemetry._Bound:
    b = _event_bound.get(category)
    if b is None:
        b = _event_bound[category] = _EVENTS.bind(category=category)
    return b


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_on(name: str, default: bool = True) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


class FlightRecorder:
    """Bounded process-wide ring of structured health-plane events.

    The lock is a leaf: every region is pure deque/dict work, so choke
    points may record while holding their own locks (devcache does)
    without joining the LOCK001/LOCK002 deadlock class."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._capacity = (
            _env_int("H2O3_TPU_FLIGHT_EVENTS", 2048)
            if capacity is None else max(1, int(capacity)))
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self._capacity)
        self._seq = 0
        self._enabled = _env_on("H2O3_TPU_FLIGHT", True)

    # -- switches ------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        """Flip recording on/off (the --obs-bench A/B switch; boot honors
        ``H2O3_TPU_FLIGHT``)."""
        self._enabled = bool(on)

    @property
    def capacity(self) -> int:
        return self._capacity

    # -- the record API ------------------------------------------------------
    def record(self, category: str, severity: str = "info",
               msg: str = "", trace_id: Optional[str] = None,
               **payload: Any) -> None:
        """Append one event.  With no explicit ``trace_id`` the calling
        thread's open span supplies one (same attribution context as the
        ledger, one attribute read when untraced).  Payload values must
        be small and JSON-able — this is a flight recorder, not a log."""
        if not self._enabled:
            return
        if trace_id is None:
            sp = telemetry.current_span()
            if sp is not None:
                trace_id = sp.trace_id
        ev: Dict[str, Any] = {
            "ts_ms": int(time.time() * 1000),
            "category": category,
            "severity": severity,
            "node": telemetry.node_name() or "localhost",
            "msg": msg,
        }
        if trace_id is not None:
            ev["trace_id"] = trace_id
        if payload:
            ev.update(payload)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        # the meter ticks AFTER the leaf lock releases
        _bound_event(category).inc()

    # -- read side -----------------------------------------------------------
    def snapshot(self, count: Optional[int] = None,
                 category: Optional[str] = None,
                 min_seq: int = 0) -> List[Dict[str, Any]]:
        """The last ``count`` events, oldest first.  ``category`` filters;
        ``min_seq`` returns only events recorded after a remembered
        :attr:`seq` (the chaos plane's per-run delta window)."""
        with self._lock:
            events = [dict(e) for e in self._ring]
        if category is not None:
            events = [e for e in events if e["category"] == category]
        if min_seq:
            events = [e for e in events if e["seq"] > min_seq]
        if count is not None and count >= 0:
            events = events[-count:]
        return events

    @property
    def seq(self) -> int:
        """Sequence number of the newest event (0 when empty/fresh)."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class FanoutTracker:
    """In-flight fan-out registry for the ``fanout_stalled`` watchdog.

    ``begin()`` when a fan-out schedules its ranges, ``progress()`` as
    partials land, ``end()`` in a finally.  The watchdog reads
    :meth:`snapshot` — ages computed from ``time.monotonic`` so a wedged
    context shows a growing ``idle_s`` no matter what the wall clock
    does.  The lock is a leaf (pure dict work)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active: Dict[int, Dict[str, Any]] = {}
        self._next = 0

    def begin(self, kind: str, total: int, **meta: Any) -> "_FanoutHandle":
        now = time.monotonic()
        entry = {"kind": kind, "total": int(total), "done": 0,
                 "t0": now, "t_last": now}
        entry.update(meta)
        with self._lock:
            self._next += 1
            fid = self._next
            self._active[fid] = entry
        return _FanoutHandle(self, fid)

    def _progress(self, fid: int, done: Optional[int]) -> None:
        now = time.monotonic()
        with self._lock:
            e = self._active.get(fid)
            if e is None:
                return
            e["done"] = int(done) if done is not None else e["done"] + 1
            e["t_last"] = now

    def _end(self, fid: int) -> None:
        with self._lock:
            self._active.pop(fid, None)

    def snapshot(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            entries = [dict(e) for e in self._active.values()]
        for e in entries:
            e["age_s"] = round(now - e.pop("t0"), 3)
            e["idle_s"] = round(now - e.pop("t_last"), 3)
        return entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._active)


class _FanoutHandle:
    __slots__ = ("_tracker", "_fid")

    def __init__(self, tracker: FanoutTracker, fid: int) -> None:
        self._tracker = tracker
        self._fid = fid

    def progress(self, done: Optional[int] = None) -> None:
        self._tracker._progress(self._fid, done)

    def end(self) -> None:
        self._tracker._end(self._fid)


# ---------------------------------------------------------------------------
# crash / stall capture


def dump_stacks(reason: str = "sigusr2") -> int:
    """Dump every live thread's stack into the ring (one ``stacks`` event
    per thread) and return the thread count.  Called by the SIGUSR2
    handler and by the watchdog's stall escalation — no locks are held
    while formatting."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    n = 0
    for ident, frame in frames.items():
        stack = traceback.format_stack(frame)
        record(STACKS, "warn", "thread stack",
               reason=reason, thread=names.get(ident, str(ident)),
               frames=[ln.rstrip("\n") for ln in stack[-12:]])
        n += 1
    return n


#: optional bundle-enricher installed by cluster/health.py so the crash
#: file carries the final health verdicts without a util->cluster import
_crash_extras: Optional[Callable[[], Dict[str, Any]]] = None


def set_crash_extras(fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
    global _crash_extras
    _crash_extras = fn


def crash_path(node: Optional[str] = None) -> Optional[str]:
    """Where :func:`persist_crash` writes by default, or None when
    ``H2O3_TPU_FLIGHT_CRASH_DIR`` is unset (crash files disabled)."""
    d = os.environ.get("H2O3_TPU_FLIGHT_CRASH_DIR")
    if not d:
        return None
    node = node or telemetry.node_name() or "localhost"
    safe = node.replace("/", "_").replace(":", "_")
    return os.path.join(d, "flight-%s-%d.json" % (safe, os.getpid()))


def persist_crash(path: Optional[str] = None,
                  reason: str = "atexit") -> Optional[str]:
    """Persist the final ring (plus health verdicts when the monitor is
    up) to ``path`` or :func:`crash_path`; returns the path written, or
    None when crash files are disabled.  Best-effort: a failed write
    never raises out of an exit path."""
    path = path or crash_path()
    if path is None:
        return None
    bundle: Dict[str, Any] = {
        "kind": "flight_crash",
        "node": telemetry.node_name() or "localhost",
        "pid": os.getpid(),
        "reason": reason,
        "ts_ms": int(time.time() * 1000),
        "events": RECORDER.snapshot(),
    }
    extras = _crash_extras
    if extras is not None:
        try:
            bundle.update(extras())
        except Exception:  # noqa: BLE001 — exit path stays best-effort
            pass
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, sort_keys=True)
        return path
    except OSError:
        return None


_hooks_lock = threading.Lock()
_hooks_installed = False


def _on_sigusr2(signum, frame) -> None:  # noqa: ANN001 — signal signature
    dump_stacks(reason="sigusr2")


def _atexit_persist() -> None:
    persist_crash(reason="atexit")


def install_crash_hooks() -> bool:
    """Idempotently arm crash/stall capture: SIGUSR2 -> stack dump into
    the ring, ``faulthandler`` -> fatal C-level tracebacks into a sidecar
    next to the crash file, ``atexit`` -> persist the final ring.  Signal
    wiring silently skips off the main thread (REST/boot threads still
    get the atexit hook).  Returns True when hooks are (already) armed."""
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return True
        _hooks_installed = True
    atexit.register(_atexit_persist)
    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, OSError):  # not the main thread / no SIGUSR2
        pass
    cpath = crash_path()
    if cpath is not None:
        try:
            import faulthandler

            os.makedirs(os.path.dirname(cpath) or ".", exist_ok=True)
            f = open(cpath + ".stacks.txt", "w")  # noqa: SIM115 — lives
            faulthandler.enable(file=f)           # for the process
        except OSError:
            pass
    return True


#: process-wide instances (one recorder per node, like the ledger)
RECORDER = FlightRecorder()
FANOUTS = FanoutTracker()

#: the terse choke-point spelling: ``_flight.record(CAT, sev, ...)``
record = RECORDER.record


def set_enabled(on: bool) -> None:
    RECORDER.set_enabled(on)
