"""TimeLine — in-memory event ring for tracing (water/TimeLine.java).

Reference: a lock-free ring of every UDP/TCP send/recv with ns timestamps,
snapshotted over ``/3/Timeline`` (``water/TimeLine.java:22,75-110``,
``init/TimelineSnapshot.java``).

TPU-native: the interesting events are not packets (XLA owns transport)
but the compute lifecycle — jit compiles, training blocks, REST requests,
parse jobs, collectives-bearing steps. Each event is (ns timestamp, kind,
fields); the ring keeps the most recent ``CAPACITY`` events and the
``/3/Timeline`` route serves a snapshot.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List

CAPACITY = 8192

_lock = threading.Lock()
_ring: Deque[Dict[str, Any]] = collections.deque(maxlen=CAPACITY)
_counter = 0


def record(kind: str, **fields: Any) -> None:
    """Append one event; cheap enough for per-block/per-request use."""
    global _counter
    evt = {"ns": time.time_ns(), "kind": kind, **fields}
    with _lock:
        _counter += 1
        evt["seq"] = _counter
        _ring.append(evt)


class timed:
    """Context manager: records kind with duration_ms on exit."""

    def __init__(self, kind: str, **fields: Any) -> None:
        self.kind = kind
        self.fields = fields

    def __enter__(self) -> "timed":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        record(
            self.kind,
            duration_ms=round((time.perf_counter() - self.t0) * 1e3, 3),
            ok=exc[0] is None,
            **self.fields,
        )


def snapshot(n: int = 1000) -> List[Dict[str, Any]]:
    with _lock:
        return list(_ring)[-n:]


def total_events() -> int:
    return _counter


def clear() -> None:
    global _counter
    with _lock:
        _ring.clear()
        _counter = 0
