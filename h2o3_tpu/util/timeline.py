"""TimeLine — in-memory event ring for tracing (water/TimeLine.java).

Reference: a lock-free ring of every UDP/TCP send/recv with ns timestamps,
snapshotted over ``/3/Timeline`` (``water/TimeLine.java:22,75-110``,
``init/TimelineSnapshot.java``).

TPU-native: the interesting events are not packets (XLA owns transport)
but the compute lifecycle — jit compiles, training blocks, REST requests,
parse jobs, collectives-bearing steps. Each event is (ns timestamp, kind,
fields); the ring keeps the most recent ``CAPACITY`` events and the
``/3/Timeline`` route serves a snapshot.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List

CAPACITY = 8192

_lock = threading.Lock()
_ring: Deque[Dict[str, Any]] = collections.deque(maxlen=CAPACITY)
_counter = 0

#: Optional trace-context provider (installed by util/telemetry.py): returns
#: {"trace_id": ..., "span_id": ...} for the calling thread's open span, or
#: None. Kept as a hook so this module stays import-light and dependency-free.
_trace_provider = None


def set_trace_provider(fn) -> None:
    """Install a callable returning trace-context fields to merge into every
    recorded event (telemetry Spans use this to make /3/Timeline
    correlatable); pass None to uninstall."""
    global _trace_provider
    _trace_provider = fn


def record(kind: str, **fields: Any) -> None:
    """Append one event; cheap enough for per-block/per-request use."""
    global _counter
    if _trace_provider is not None and "trace_id" not in fields:
        try:
            ctx = _trace_provider()
        except Exception:  # tracing must never break recording
            ctx = None
        if ctx:
            fields = {**ctx, **fields}
    evt = {"ns": time.time_ns(), "kind": kind, **fields}
    with _lock:
        _counter += 1
        evt["seq"] = _counter
        _ring.append(evt)


def record_event(evt: Dict[str, Any]) -> None:
    """Append a pre-built event dict — the hot-path variant of
    :func:`record` for callers that already carry their trace fields
    (telemetry Spans): no kwargs splat, no provider merge, one dict.
    The caller hands over ownership of ``evt``."""
    global _counter
    evt["ns"] = time.time_ns()
    with _lock:
        _counter += 1
        evt["seq"] = _counter
        _ring.append(evt)


class timed:
    """Context manager: records kind with duration_ms on exit."""

    def __init__(self, kind: str, **fields: Any) -> None:
        self.kind = kind
        self.fields = fields

    def __enter__(self) -> "timed":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        record(
            self.kind,
            duration_ms=round((time.perf_counter() - self.t0) * 1e3, 3),
            ok=exc[0] is None,
            **self.fields,
        )


def snapshot(n: int = 1000) -> List[Dict[str, Any]]:
    if n <= 0:
        return []  # [-0:] would be the WHOLE ring, not zero events
    with _lock:
        return list(_ring)[-n:]


def snapshot_payload(n: int = 1000) -> Dict[str, Any]:
    """Snapshot + ring totals + this node's wall clock at snapshot time —
    the ``timeline_snapshot`` RPC body.  ``now_ns`` lets the merging node
    sanity-check its heartbeat-derived clock-skew estimate against the
    moment the events were actually collected."""
    return {
        "events": snapshot(n),
        "total_events": total_events(),
        "now_ns": time.time_ns(),
    }


def total_events() -> int:
    return _counter


def clear() -> None:
    global _counter
    with _lock:
        _ring.clear()
        _counter = 0
