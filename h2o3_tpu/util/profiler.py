"""Sampling profiler + device trace capture.

Reference: ``water/util/ProfileCollectorTask.java`` (+ the ``/3/Profiler``
route): every node samples its JVM stack traces and returns the collapsed
stacks with counts. Here the same idea runs over ``sys._current_frames``
— and, because the interesting time on a TPU host is spent inside XLA
programs, a second facility wraps ``jax.profiler`` trace capture (the
TPU-native half; SURVEY.md §5 maps ProfileCollector to jax.profiler).
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
import traceback
from collections import Counter
from typing import Any, Dict, List, Optional


def collect(duration_s: float = 0.25, interval_s: float = 0.005,
            depth: int = 10,
            exclude: Optional[str] = None) -> List[Dict[str, Any]]:
    """Sample every live thread's stack for ``duration_s``; return
    collapsed stacks sorted by sample count (ProfileCollectorTask's
    per-node result shape).

    ``exclude`` is an optional regex matched against thread *names*:
    daemon housekeeping threads (the HTTP server accept loop, heartbeat
    timers) otherwise dominate the collapsed stacks of an idle server.
    ``pct`` is the share of sampling sweeps in which a stack was seen, so
    a thread pinned on one line reads 100% regardless of how many other
    threads were live."""
    counts: Counter = Counter()  # thread-samples (two threads on one line
    sweeps: Counter = Counter()  # count twice); sweeps counts presence once
    me = threading.get_ident()
    pat = re.compile(exclude) if exclude else None
    deadline = time.monotonic() + max(duration_s, interval_s)
    n_samples = 0
    while True:
        names = {t.ident: t.name for t in threading.enumerate()} if pat else {}
        seen = set()
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the profiler thread itself is noise
            if pat is not None and pat.search(names.get(tid, "")):
                continue
            stack = traceback.extract_stack(frame)[-depth:]
            sig = ";".join(
                f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
                for f in stack
            )
            counts[sig] += 1
            seen.add(sig)
        for sig in seen:
            sweeps[sig] += 1
        n_samples += 1
        # never overshoot duration_s: sleep only the remaining budget
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(interval_s, remaining))
    return [
        {"stacktrace": sig.split(";"), "count": c,
         "pct": round(100.0 * sweeps[sig] / n_samples, 1) if n_samples else 0.0}
        for sig, c in counts.most_common(50)
    ]


class TraceCapture:
    """jax.profiler trace toggle: POST start/stop over REST, read the
    resulting TensorBoard/Perfetto trace directory off the server."""

    def __init__(self) -> None:
        self._dir: str = ""
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self._dir)

    def start(self, log_dir: str) -> Dict[str, Any]:
        import jax

        with self._lock:
            if self._dir:
                raise RuntimeError(f"trace already running in {self._dir}")
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
            self._dir = log_dir
        return {"trace_dir": log_dir, "active": True}

    def stop(self) -> Dict[str, Any]:
        import jax

        with self._lock:
            if not self._dir:
                raise RuntimeError("no trace running")
            d, self._dir = self._dir, ""
            jax.profiler.stop_trace()
        files = []
        for root, _dirs, names in os.walk(d):
            files += [os.path.relpath(os.path.join(root, n), d)
                      for n in names]
        return {"trace_dir": d, "active": False, "files": sorted(files)[:100]}


TRACE = TraceCapture()
