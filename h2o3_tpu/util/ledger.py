"""Per-trace cost attribution: the ledger every choke point charges into.

PR 6 made one ``trace_id`` thread REST -> distributed map_reduce -> remote
shard execution, but nothing answered "which request paid for that compile,
that devcache upload, those RPC bytes, on which node?".  This module is
that answer — the TensorFlow-paper point that whole-system performance
work lives or dies on per-step cost visibility, with DrJAX's placement
split honored by attributing device work per *node*, not per process:

* :class:`CostLedger` — a process-wide, lock-leaf LRU map
  ``trace_id -> {node: {category: amount}}`` (plus a bounded per-span
  breakdown for the trace viewer).  ``charge(category, amount)`` reads the
  calling thread's open :class:`~h2o3_tpu.util.telemetry.Span`, so the
  existing span/RPC-envelope context is the attribution context: a remote
  dtask executing under an ``rpc_server`` span charges the *caller's*
  trace under the *serving* node's name with zero extra wiring.  Untraced
  work (heartbeats, background sweeps) charges nothing and pays one
  attribute read.
* :class:`SlowOpLog` — a threshold-gated ring of the N worst traces per
  REST route, each record carrying its ledger snapshot: exemplars, not
  averages (``GET /3/SlowOps``).

Charge sites (grep the category constants): jit compile seconds and plan
cache misses (``compute/mapreduce.py``), devcache upload bytes and
evictions (``frame/devcache.py``), RPC wire bytes both directions
(``cluster/rpc.py``), shard walls (``cluster/tasks.py``), chunk reads
(``cluster/frames.py``), coalesced-batch shares (``api/coalesce.py``),
search cell walls (``cluster/search.py``), distributed tree-level
histogram walls per home (``models/tree/dist_hist.py``), and distributed
Rapids partial bytes at the fan-out merge (``rapids/dist_exec.py``).

Surface: ``GET /3/Traces/{trace_id}`` federates per-node ledgers over the
``trace_ledger`` RPC (``cluster/membership.py``); ``GET /3/Timeline``
gains ``?ledgers=true``; ``scripts/trace_view.py`` renders per-span cost
columns when a saved snapshot carries ledger data.

Locking discipline (the ``KeyedStore.get`` lesson): the ledger lock is a
LEAF — nothing blocking, no other lock, runs inside it; meters tick after
it releases.  Both maps are bounded: ``H2O3_TPU_LEDGER_TRACES`` (default
512, LRU) caps tracked traces, ``H2O3_TPU_SLOWOP_PER_ROUTE`` (default 8)
caps exemplars per route.  ``H2O3_TPU_LEDGER=0`` disables charging
entirely; ``H2O3_TPU_SLOWOP_MS`` (default 250) gates the slow-op ring.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

from h2o3_tpu.util import telemetry

__all__ = [
    "CostLedger",
    "LEDGER",
    "SLOWOPS",
    "SlowOpLog",
    "charge",
    "set_enabled",
    # charge-site category constants
    "COMPILE_SECONDS",
    "PLAN_CACHE_MISSES",
    "DEVCACHE_UPLOAD_BYTES",
    "DEVCACHE_EVICTIONS",
    "RPC_SENT_BYTES",
    "RPC_RECV_BYTES",
    "SHARD_WALL_SECONDS",
    "CHUNK_READS",
    "COALESCE_SHARE_SECONDS",
    "SEARCH_CELL_SECONDS",
    "HIST_LEVEL_WALL",
    "RAPIDS_PARTIAL_BYTES",
    "CHUNK_ENCODED_BYTES",
]

#: the closed category vocabulary — one constant per choke point, so the
#: breakdown in ``GET /3/Traces/{id}`` reads the same on every node
COMPILE_SECONDS = "compile_seconds"
PLAN_CACHE_MISSES = "plan_cache_misses"
DEVCACHE_UPLOAD_BYTES = "devcache_upload_bytes"
DEVCACHE_EVICTIONS = "devcache_evictions"
RPC_SENT_BYTES = "rpc_sent_bytes"
RPC_RECV_BYTES = "rpc_recv_bytes"
SHARD_WALL_SECONDS = "shard_wall_seconds"
CHUNK_READS = "chunk_reads"
COALESCE_SHARE_SECONDS = "coalesce_share_seconds"
SEARCH_CELL_SECONDS = "search_cell_seconds"
HIST_LEVEL_WALL = "hist_level_wall"
RAPIDS_PARTIAL_BYTES = "rapids_partial_bytes"
CHUNK_ENCODED_BYTES = "chunk_encoded_bytes"

_CHARGES = telemetry.counter(
    "ledger_charges_total",
    "cost-ledger charge events by category (each event adds its amount "
    "to the charging trace's per-node breakdown)",
    labels=("category",),
)
_EVICTIONS = telemetry.counter(
    "ledger_evictions_total",
    "trace ledgers dropped by the H2O3_TPU_LEDGER_TRACES LRU bound",
)
_ENTRIES = telemetry.gauge(
    "ledger_entries", "trace ledgers currently tracked"
)
_SLOWOPS = telemetry.counter(
    "slowop_records_total",
    "requests recorded into the slow-op exemplar ring",
    labels=("route",),
)

#: per-category bound counter handles (categories are a small closed set)
_charge_bound: Dict[str, telemetry._Bound] = {}


def _bound_charge(category: str) -> telemetry._Bound:
    b = _charge_bound.get(category)
    if b is None:
        b = _charge_bound[category] = _CHARGES.bind(category=category)
    return b


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_on(name: str, default: bool = True) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


#: per-trace span-breakdown bound: enough for any real request tree; a
#: pathological trace folds its tail into one "_overflow" bucket instead
#: of growing without limit
_SPAN_CAP = 128


class _TraceCosts:
    __slots__ = ("nodes", "spans", "meta")

    def __init__(self) -> None:
        self.nodes: Dict[str, Dict[str, float]] = {}
        self.spans: Dict[str, Dict[str, float]] = {}
        self.meta: Dict[str, Any] = {}


class CostLedger:
    """Bounded process-wide map of per-trace resource charges.

    The lock is a leaf: every region is pure dict work — no RPC, no
    dispatch, no I/O, no other lock — so any charge site may call
    :meth:`charge` while holding its own lock (devcache eviction does)
    without joining the LOCK001/LOCK002 deadlock class."""

    def __init__(self, max_traces: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _TraceCosts]" = OrderedDict()
        self._max_traces = (
            _env_int("H2O3_TPU_LEDGER_TRACES", 512)
            if max_traces is None else max(1, int(max_traces)))
        self._enabled = _env_on("H2O3_TPU_LEDGER", True)

    # -- switches ------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        """Flip charging on/off (the --obs-bench A/B switch; boot honors
        ``H2O3_TPU_LEDGER``)."""
        self._enabled = bool(on)

    # -- the charge API ------------------------------------------------------
    def charge(
        self,
        category: str,
        amount: float,
        trace_id: Optional[str] = None,
        node: Optional[str] = None,
        span_id: Optional[str] = None,
    ) -> None:
        """Add ``amount`` of ``category`` to the charging trace's ledger.

        With no explicit ``trace_id`` the calling thread's open span is
        the context — which is exactly the span/RPC-envelope context that
        already threads one trace across nodes, so a remote dispatch
        under an ``rpc_server`` span folds its charges back to the
        originating trace.  No open trace: no-op (heartbeats stay free).
        ``node`` defaults to the effective node identity (the serving
        node inside a ``node_scope``)."""
        if not self._enabled:
            return
        if trace_id is None:
            sp = telemetry.current_span()
            if sp is None or sp.trace_id is None:
                return
            trace_id = sp.trace_id
            if span_id is None:
                span_id = sp.span_id
        if node is None:
            node = telemetry.node_name() or "localhost"
        amount = float(amount)
        evicted = 0
        with self._lock:
            e = self._entries.get(trace_id)
            if e is None:
                e = self._entries[trace_id] = _TraceCosts()
                while len(self._entries) > self._max_traces:
                    self._entries.popitem(last=False)
                    evicted += 1
            else:
                self._entries.move_to_end(trace_id)
            cats = e.nodes.setdefault(node, {})
            cats[category] = cats.get(category, 0.0) + amount
            if span_id is not None:
                sk = span_id if (span_id in e.spans
                                 or len(e.spans) < _SPAN_CAP) else "_overflow"
                scats = e.spans.setdefault(sk, {})
                scats[category] = scats.get(category, 0.0) + amount
            n_entries = len(self._entries)
        # meters tick AFTER the leaf lock releases
        _bound_charge(category).inc()
        if evicted:
            _EVICTIONS.inc(evicted)
        _ENTRIES.set(n_entries)

    def annotate(self, trace_id: Optional[str], **meta: Any) -> None:
        """Attach request metadata (route, wall_ms, status) to an
        EXISTING trace ledger — a request that charged nothing keeps no
        entry, so annotation never grows the map."""
        if not self._enabled or not trace_id:
            return
        with self._lock:
            e = self._entries.get(trace_id)
            if e is None:
                return
            e.meta.update(meta)

    # -- read side -----------------------------------------------------------
    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """JSON-able cost breakdown for one trace, or None: per-node and
        per-span category maps plus a cross-node ``total``."""
        with self._lock:
            e = self._entries.get(trace_id)
            if e is None:
                return None
            nodes = {n: dict(c) for n, c in e.nodes.items()}
            spans = {s: dict(c) for s, c in e.spans.items()}
            meta = dict(e.meta)
        total: Dict[str, float] = {}
        for cats in nodes.values():
            for k, v in cats.items():
                total[k] = total.get(k, 0.0) + v
        out: Dict[str, Any] = {
            "trace_id": trace_id, "nodes": nodes, "spans": spans,
            "total": total,
        }
        out.update(meta)
        return out

    def snapshot_many(
            self, trace_ids: Sequence[str]) -> Dict[str, Dict[str, Any]]:
        """Breakdowns for every known id in ``trace_ids`` (the
        ``/3/Timeline?ledgers=true`` attachment)."""
        out = {}
        for tid in dict.fromkeys(trace_ids):  # de-dup, keep order
            entry = self.get(tid)
            if entry is not None:
                out[tid] = entry
        return out

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
        _ENTRIES.set(0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class SlowOpLog:
    """The N worst traces per route, threshold-gated, ledgers attached.

    A request slower than ``H2O3_TPU_SLOWOP_MS`` lands here with its
    ledger snapshot frozen at record time — the exemplar an operator
    drills into when the p99 moves, instead of an average that hides it.
    The snapshot is taken BEFORE this object's lock so the region stays
    a leaf (no ledger-lock nesting, no blocking work)."""

    def __init__(self, threshold_ms: Optional[float] = None,
                 per_route: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._by_route: Dict[str, List[Dict[str, Any]]] = {}
        self.threshold_ms = (
            _env_float("H2O3_TPU_SLOWOP_MS", 250.0)
            if threshold_ms is None else float(threshold_ms))
        self.per_route = (
            _env_int("H2O3_TPU_SLOWOP_PER_ROUTE", 8)
            if per_route is None else max(1, int(per_route)))

    def record(self, route: str, wall_ms: float,
               trace_id: Optional[str] = None,
               status: Optional[int] = None) -> bool:
        """Consider one finished request; True when it entered the ring."""
        if self.threshold_ms < 0 or wall_ms < self.threshold_ms:
            return False
        entry = LEDGER.get(trace_id) if trace_id else None  # pre-lock
        rec = {
            "route": route,
            "wall_ms": round(float(wall_ms), 3),
            "trace_id": trace_id,
            "status": status,
            "ts_ms": int(time.time() * 1000),
            "ledger": entry,
        }
        kept = False
        with self._lock:
            ring = self._by_route.setdefault(route, [])
            ring.append(rec)
            ring.sort(key=lambda r: -r["wall_ms"])
            del ring[self.per_route:]
            kept = rec in ring
        if kept:
            _SLOWOPS.inc(route=route)
        return kept

    def snapshot(self, route: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            routes = ([route] if route is not None
                      else sorted(self._by_route))
            out = {r: [dict(rec) for rec in self._by_route.get(r, [])]
                   for r in routes}
        return {"threshold_ms": self.threshold_ms,
                "per_route": self.per_route,
                "routes": {r: v for r, v in out.items() if v}}

    def clear(self) -> None:
        with self._lock:
            self._by_route.clear()


#: process-wide instances (one ledger per node, like the WaterMeter)
LEDGER = CostLedger()
SLOWOPS = SlowOpLog()

#: the terse charge-site spelling: ``_ledger.charge(CAT, amount)``
charge = LEDGER.charge


def set_enabled(on: bool) -> None:
    LEDGER.set_enabled(on)
