from h2o3_tpu.compute.mapreduce import FrameTable, map_reduce
from h2o3_tpu.compute.quantile import quantiles

__all__ = ["FrameTable", "map_reduce", "quantiles"]
