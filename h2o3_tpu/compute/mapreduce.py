"""The compute primitive: shard_map + psum ≡ MRTask map + tree-reduce.

Reference: ``new MRTask(){ map(Chunk[]); reduce(T); }.doAll(frame)``
(``water/MRTask.java:15-64,391``) — fan out over the node tree, map each home
chunk, reduce partials pairwise back up the tree (``MRTask.java:96-127``).

TPU-native: the node tree and hand-rolled reduction disappear. A user map
function runs per device shard under ``shard_map`` and partials are combined
with ``lax.psum`` — XLA emits the log-depth reduction over ICI natively.
Everything above this layer (rollups, metrics, GLM Gram, tree histograms,
KMeans assignments, …) is expressed in terms of these two calls, exactly the
way everything in the reference sits on MRTask (SURVEY.md §1).

Two entry points:
  * ``map_reduce(fn, table)``   — fn: (cols, mask) -> pytree of partials; psum'd.
  * ``map_batches(fn, table)``  — fn: (cols, mask) -> per-row outputs; stays sharded
    (the analogue of an MRTask producing NewChunks / outputFrame).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.6 top-level API, older fallback
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.parallel.mesh import DATA_AXIS, default_mesh, row_mask, shard_rows
from h2o3_tpu.util import telemetry

#: per-primitive accounting (DrJAX's point for MapReduce-in-JAX: you cannot
#: place sharded work without counting it) — op is map_reduce | map_batches
_DISPATCHES = telemetry.counter(
    "mapreduce_dispatch_total", "MRTask-analogue dispatches", labels=("op",)
)
_SHARDS = telemetry.gauge(
    "mapreduce_shards", "shard count of the most recent dispatch",
    labels=("op",),
)
_WALL = telemetry.histogram(
    "mapreduce_wall_seconds",
    "dispatch wall time (trace + compile + execute + device sync)",
    labels=("op",),
)
_JIT_CACHE = telemetry.counter(
    "mapreduce_jit_cache_total",
    "XLA compile-cache outcome per dispatch (compile-count delta)",
    labels=("op", "result"),
)


def _dispatch(op: str, table: "FrameTable", call):
    """Shared accounting envelope: count + span + jit hit/miss attribution."""
    telemetry.install_jax_compile_listener()
    n_shards = int(table.mesh.devices.size)
    _DISPATCHES.inc(op=op)
    _SHARDS.set(n_shards, op=op)
    # thread-local delta: compiles run on the dispatching thread, so this
    # stays correct when several builds dispatch concurrently
    compiles_before = telemetry.thread_compile_count()
    t0 = time.perf_counter()
    with telemetry.Span("mapreduce", op=op, shards=n_shards,
                        rows=table.n_valid):
        out = call()
    _WALL.observe(time.perf_counter() - t0, op=op)
    missed = telemetry.thread_compile_count() > compiles_before
    _JIT_CACHE.inc(op=op, result="miss" if missed else "hit")
    return out


class FrameTable:
    """Device-resident, row-sharded view of (a subset of) a Frame.

    Columns are float32 by default (the TPU-native compute dtype; float64 on
    request for e.g. exact Gram accumulation), padded to a multiple of the
    mesh size, with a boolean validity ``mask`` for the pad rows.
    """

    def __init__(
        self,
        arrays: Dict[str, jax.Array],
        mask: jax.Array,
        n_valid: int,
        mesh: Mesh,
    ) -> None:
        self.arrays = arrays
        self.mask = mask
        self.n_valid = n_valid
        self.mesh = mesh

    @staticmethod
    def from_frame(
        frame: Frame,
        columns: Optional[Sequence[str]] = None,
        mesh: Optional[Mesh] = None,
        dtype=jnp.float32,
    ) -> "FrameTable":
        mesh = mesh or default_mesh()
        names = list(columns) if columns is not None else [
            c.name for c in frame.columns if c.type not in (ColType.STR, ColType.UUID)
        ]
        if not names:
            raise ValueError("no device-shardable (numeric/categorical/time) columns")
        arrays: Dict[str, jax.Array] = {}
        n = frame.nrows
        for name in names:
            col = frame.col(name)
            host = col.numeric_view().astype(np.dtype(dtype.dtype if hasattr(dtype, "dtype") else dtype))
            arr, n = shard_rows(host, mesh, fill=np.nan)
            arrays[name] = arr
        some = next(iter(arrays.values()))
        mask = row_mask(n, some.shape[0], mesh)
        return FrameTable(arrays, mask, n, mesh)

    @property
    def n_padded(self) -> int:
        return next(iter(self.arrays.values())).shape[0]

    def matrix(self, columns: Optional[Sequence[str]] = None) -> jax.Array:
        """[N_pad, F] feature matrix (column-stacked, row-sharded)."""
        names = list(columns) if columns is not None else list(self.arrays)
        return jnp.stack([self.arrays[n] for n in names], axis=1)


def map_reduce(
    fn: Callable,
    table: FrameTable,
    *extra_args,
    reduce: str = "sum",
):
    """Run ``fn(cols_dict, mask, *extra)`` per shard; psum/pmax/pmin partials.

    ``fn`` must be jax-traceable and return a pytree of arrays whose shapes do
    not depend on the shard content (static shapes — the SPMD contract).
    The returned pytree is fully reduced and replicated on every device.
    """
    red = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}[reduce]

    def shard_fn(arrays, mask, *extras):
        part = fn(arrays, mask, *extras)
        return jax.tree.map(lambda x: red(x, DATA_AXIS), part)

    mapped = _shard_map(
        shard_fn,
        mesh=table.mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)) + tuple(P() for _ in extra_args),
        out_specs=P(),
    )
    return _dispatch(
        "map_reduce",
        table,
        lambda: jax.jit(mapped)(table.arrays, table.mask, *extra_args),
    )


def map_batches(fn: Callable, table: FrameTable, *extra_args):
    """Run ``fn(cols_dict, mask, *extra)`` per shard, keep outputs row-sharded.

    The analogue of an MRTask writing NewChunks into an output Frame
    (``water/MRTask.java:558-559`` outputFrame)."""

    mapped = _shard_map(
        fn,
        mesh=table.mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)) + tuple(P() for _ in extra_args),
        out_specs=P(DATA_AXIS),
    )
    return _dispatch(
        "map_batches",
        table,
        lambda: jax.jit(mapped)(table.arrays, table.mask, *extra_args),
    )


def gather_rows(x: jax.Array, n_valid: int) -> np.ndarray:
    """Pull a row-sharded device result back to host, dropping pad rows."""
    return np.asarray(jax.device_get(x))[:n_valid]
