"""The compute primitive: shard_map + psum ≡ MRTask map + tree-reduce.

Reference: ``new MRTask(){ map(Chunk[]); reduce(T); }.doAll(frame)``
(``water/MRTask.java:15-64,391``) — fan out over the node tree, map each home
chunk, reduce partials pairwise back up the tree (``MRTask.java:96-127``).

TPU-native: the node tree and hand-rolled reduction disappear. A user map
function runs per device shard under ``shard_map`` and partials are combined
with ``lax.psum`` — XLA emits the log-depth reduction over ICI natively.
Everything above this layer (rollups, metrics, GLM Gram, tree histograms,
KMeans assignments, …) is expressed in terms of these two calls, exactly the
way everything in the reference sits on MRTask (SURVEY.md §1).

Two entry points:
  * ``map_reduce(fn, table)``   — fn: (cols, mask) -> pytree of partials; psum'd.
  * ``map_batches(fn, table)``  — fn: (cols, mask) -> per-row outputs; stays sharded
    (the analogue of an MRTask producing NewChunks / outputFrame).

Caching (the DrJAX accounting gap, PAPERS.md): repeat dispatches must not
pay trace+compile again, and repeat placements must not pay host->mesh
transfer again. Two levels close it:
  * a *dispatch plan cache* memoizes the jitted ``shard_map`` program keyed
    on (fn identity, reduce op, mesh, argument shapes/dtypes/treedef) —
    re-dispatching the same fn over same-shaped data reuses the compiled
    executable instead of rebuilding ``jax.jit(mapped)`` per call;
  * ``FrameTable.from_frame`` memoizes the whole device placement in the
    process-wide :data:`h2o3_tpu.frame.devcache.DEVCACHE`, keyed on column
    version stamps, and ``matrix()`` caches its stacked design matrix.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.6 top-level API, older fallback
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from h2o3_tpu.frame.devcache import (
    DEVCACHE,
    REQUESTS as _DEVCACHE_REQUESTS,
    frame_token,
    mesh_fingerprint,
)
from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.parallel.mesh import DATA_AXIS, default_mesh, row_mask, shard_rows
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

#: per-primitive accounting (DrJAX's point for MapReduce-in-JAX: you cannot
#: place sharded work without counting it) — op is map_reduce | map_batches
_DISPATCHES = telemetry.counter(
    "mapreduce_dispatch_total", "MRTask-analogue dispatches", labels=("op",)
)
_SHARDS = telemetry.gauge(
    "mapreduce_shards", "shard count of the most recent dispatch",
    labels=("op",),
)
_WALL = telemetry.histogram(
    "mapreduce_wall_seconds",
    "dispatch wall time (trace + compile + execute + device sync)",
    labels=("op",),
)
_JIT_CACHE = telemetry.counter(
    "mapreduce_jit_cache_total",
    "XLA compile-cache outcome per dispatch (compile-count delta)",
    labels=("op", "result"),
)
_PLAN_CACHE = telemetry.counter(
    "mapreduce_plan_cache_total",
    "compiled shard_map plan reuse per dispatch",
    labels=("op", "result"),
)
_PLAN_EVICTIONS = telemetry.counter(
    "mapreduce_plan_evictions_total",
    "dispatch plans dropped from the LRU plan cache",
)


# ---------------------------------------------------------------------------
# dispatch plan cache: (fn, reduce, mesh, arg signature) -> jitted program


def _plan_cache_size() -> int:
    try:
        return max(1, int(os.environ.get("H2O3_TPU_PLAN_CACHE_SIZE", 128)))
    except ValueError:
        return 128


_plans: "OrderedDict[Tuple, Callable]" = OrderedDict()
_plans_lock = threading.Lock()


def _leaf_sig(x) -> Tuple:
    """Hashable trace signature of one argument leaf: arrays by
    shape+dtype (jit programs depend on avals, not values), python
    scalars by type (weak-typed scalars trace identically per type)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("arr", tuple(x.shape), str(x.dtype))
    return ("py", type(x).__name__)


def _plan_key(op: str, fn: Callable, reduce: str, table: "FrameTable",
              extra_args: tuple) -> Optional[Tuple]:
    """Cache key for the jitted shard_map program, or None when the
    dispatch is uncacheable (unhashable fn). The entry holds ``fn``
    strongly, so a key can never alias a dead function's identity.

    Deliberately NOT weakref-keyed: the cached plan closes over ``fn``
    (shard_fn wraps it for retracing), so a weak key could never fire —
    the entry itself is what keeps fn alive. The cost is that up to
    H2O3_TPU_PLAN_CACHE_SIZE callables (+ captured closures) stay pinned
    until LRU-evicted; callers dispatching per-call closures over large
    captured arrays should prefer passing those arrays as extra_args."""
    leaves, treedef = jax.tree.flatten(tuple(extra_args))
    key = (
        op, fn, reduce, table.mesh,
        tuple((k, tuple(v.shape), str(v.dtype))
              for k, v in sorted(table.arrays.items())),
        _leaf_sig(table.mask),
        treedef,
        tuple(_leaf_sig(leaf) for leaf in leaves),
    )
    try:
        hash(key)
    except TypeError:
        return None
    return key


def _get_plan(op: str, fn: Callable, reduce: str, table: "FrameTable",
              extra_args: tuple, build: Callable[[], Callable]) -> Callable:
    key = _plan_key(op, fn, reduce, table, extra_args)
    if key is None:
        _PLAN_CACHE.inc(op=op, result="uncacheable")
        return build()
    with _plans_lock:
        plan = _plans.get(key)
        if plan is not None:
            _plans.move_to_end(key)
            _PLAN_CACHE.inc(op=op, result="hit")
            return plan
    _PLAN_CACHE.inc(op=op, result="miss")
    _ledger.charge(_ledger.PLAN_CACHE_MISSES, 1)
    plan = build()
    with _plans_lock:
        existing = _plans.get(key)
        if existing is not None:
            return existing  # lost a build race: converge on one program
        _plans[key] = plan
        limit = _plan_cache_size()
        while len(_plans) > limit:
            _plans.popitem(last=False)
            _PLAN_EVICTIONS.inc()
    return plan


def plan_memo(namespace: str, key: Tuple, build: Callable[[], object]):
    """Generic entry point into the dispatch plan cache for callers that
    assemble their own compiled programs (the rapids fusion pass memoizes
    lowered column-programs here keyed on canonical S-expression + input
    schema). Shares the LRU — and its budget and eviction accounting — with
    the shard_map dispatch plans; evicting a fused plan also retires the
    jitted program it holds, since map_batches keys on the program's
    function identity."""
    full = ("memo", namespace, key)
    with _plans_lock:
        hit = _plans.get(full)
        if hit is not None:
            _plans.move_to_end(full)
            _PLAN_CACHE.inc(op=namespace, result="hit")
            return hit
    _PLAN_CACHE.inc(op=namespace, result="miss")
    _ledger.charge(_ledger.PLAN_CACHE_MISSES, 1)
    value = build()
    with _plans_lock:
        existing = _plans.get(full)
        if existing is not None:
            return existing  # lost a build race: converge on one plan
        _plans[full] = value
        limit = _plan_cache_size()
        while len(_plans) > limit:
            _plans.popitem(last=False)
            _PLAN_EVICTIONS.inc()
    return value


def _dispatch(op: str, table: "FrameTable", call):
    """Shared accounting envelope: count + span + jit hit/miss attribution."""
    telemetry.install_jax_compile_listener()
    n_shards = int(table.mesh.devices.size)
    _DISPATCHES.inc(op=op)
    _SHARDS.set(n_shards, op=op)
    # thread-local delta: compiles run on the dispatching thread, so this
    # stays correct when several builds dispatch concurrently
    compiles_before = telemetry.thread_compile_count()
    compile_secs_before = telemetry.thread_compile_seconds()
    t0 = time.perf_counter()
    with telemetry.Span("mapreduce", op=op, shards=n_shards,
                        rows=table.n_valid):
        out = call()
        # charge inside the span so the delta lands on the mapreduce
        # span_id; compiles run on the dispatching thread, so the
        # thread-local delta is this dispatch's own compile bill
        compile_secs = (telemetry.thread_compile_seconds()
                        - compile_secs_before)
        if compile_secs > 0.0:
            _ledger.charge(_ledger.COMPILE_SECONDS, compile_secs)
    _WALL.observe(time.perf_counter() - t0, op=op)
    missed = telemetry.thread_compile_count() > compiles_before
    _JIT_CACHE.inc(op=op, result="miss" if missed else "hit")
    return out


class FrameTable:
    """Device-resident, row-sharded view of (a subset of) a Frame.

    Columns are float32 by default (the TPU-native compute dtype; float64 on
    request for e.g. exact Gram accumulation), padded to a multiple of the
    mesh size, with a boolean validity ``mask`` for the pad rows.
    """

    def __init__(
        self,
        arrays: Dict[str, jax.Array],
        mask: jax.Array,
        n_valid: int,
        mesh: Mesh,
    ) -> None:
        self.arrays = arrays
        self.mask = mask
        self.n_valid = n_valid
        self.mesh = mesh
        # cached tables are process-shared: concurrent first matrix() calls
        # must not double-build (and double byte-account) the stack
        self._matrix_lock = threading.Lock()
        self._matrix_cache: Dict[Tuple[str, ...], jax.Array] = {}
        #: devcache key when this table is cache-resident — stacked
        #: matrices built on it are byte-attributed to that entry
        self._devcache_key: Optional[Tuple] = None

    @staticmethod
    def from_frame(
        frame: Frame,
        columns: Optional[Sequence[str]] = None,
        mesh: Optional[Mesh] = None,
        dtype=jnp.float32,
        cache: bool = True,
    ) -> "FrameTable":
        """Device-resident view of ``frame``, memoized process-wide.

        Placement is cached in :data:`~h2o3_tpu.frame.devcache.DEVCACHE`
        keyed on (column versions, dtype, mesh), so repeat calls on an
        unmutated frame return the SAME resident table — no re-upload, no
        new ``shard_bytes_total``. ``cache=False`` forces a fresh upload."""
        mesh = mesh or default_mesh()
        np_dtype = np.dtype(dtype)  # normalize jnp/np scalar types once
        names = list(columns) if columns is not None else [
            c.name for c in frame.columns if c.type not in (ColType.STR, ColType.UUID)
        ]
        if not names:
            raise ValueError("no device-shardable (numeric/categorical/time) columns")

        def build() -> "FrameTable":
            arrays: Dict[str, jax.Array] = {}
            n = frame.nrows
            for name in names:
                host = frame.col(name).numeric_view().astype(np_dtype)
                arr, n = shard_rows(host, mesh, fill=np.nan)
                arrays[name] = arr
            some = next(iter(arrays.values()))
            mask = row_mask(n, some.shape[0], mesh)
            return FrameTable(arrays, mask, n, mesh)

        token = frame_token(frame, names) if cache else None
        if token is None:
            return build()
        key = ("frame_table", token, str(np_dtype), mesh_fingerprint(mesh))
        table = DEVCACHE.get_or_put(
            key, build, frame_key=getattr(frame, "key", None),
            kind="frame_table",
        )
        table._devcache_key = key
        return table

    @property
    def n_padded(self) -> int:
        return next(iter(self.arrays.values())).shape[0]

    def matrix(self, columns: Optional[Sequence[str]] = None) -> jax.Array:
        """[N_pad, F] feature matrix (column-stacked, row-sharded).

        The stacked matrix is cached per column tuple: with the table
        itself cached, repeat fits stack (and re-place) nothing."""
        names = tuple(columns) if columns is not None else tuple(self.arrays)
        with self._matrix_lock:
            cached = self._matrix_cache.get(names)
        if cached is not None:
            _DEVCACHE_REQUESTS.inc(kind="table_matrix", result="hit")
            return cached
        _DEVCACHE_REQUESTS.inc(kind="table_matrix", result="miss")
        # stack OUTSIDE the lock: a device dispatch while holding a lock
        # other threads contend is the deadlock class _SHARD_EXEC_LOCK
        # exists to prevent; the insert below re-checks like _get_plan
        m = jnp.stack([self.arrays[n] for n in names], axis=1)
        with self._matrix_lock:
            cur = self._matrix_cache.get(names)
            if cur is not None:
                return cur  # lost the stack race; the winner is cached
            self._matrix_cache[names] = m
            if self._devcache_key is not None:
                # a stacked matrix on a cache-resident table is resident
                # device memory: fold it into the entry so the budget sees it
                DEVCACHE.grow_entry(self._devcache_key, int(m.nbytes))
        return m


#: valid ``map_reduce(reduce=...)`` choices -> the collective combiner
_REDUCERS = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}


def map_reduce(
    fn: Callable,
    table: FrameTable,
    *extra_args,
    reduce: str = "sum",
):
    """Run ``fn(cols_dict, mask, *extra)`` per shard; psum/pmax/pmin partials.

    ``fn`` must be jax-traceable and return a pytree of arrays whose shapes do
    not depend on the shard content (static shapes — the SPMD contract).
    The returned pytree is fully reduced and replicated on every device.
    Repeat dispatches of the same ``fn`` over same-shaped arguments reuse
    the compiled program via the plan cache (zero re-trace/re-compile).
    """
    if reduce not in _REDUCERS:
        raise ValueError(
            f"unknown reduce {reduce!r}; valid choices: {sorted(_REDUCERS)}"
        )

    def build() -> Callable:
        red = _REDUCERS[reduce]

        def shard_fn(arrays, mask, *extras):
            part = fn(arrays, mask, *extras)
            return jax.tree.map(lambda x: red(x, DATA_AXIS), part)

        mapped = _shard_map(
            shard_fn,
            mesh=table.mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)) + tuple(P() for _ in extra_args),
            out_specs=P(),
        )
        return jax.jit(mapped)

    jitted = _get_plan("map_reduce", fn, reduce, table, extra_args, build)
    return _dispatch(
        "map_reduce",
        table,
        lambda: jitted(table.arrays, table.mask, *extra_args),
    )


def map_batches(fn: Callable, table: FrameTable, *extra_args):
    """Run ``fn(cols_dict, mask, *extra)`` per shard, keep outputs row-sharded.

    The analogue of an MRTask writing NewChunks into an output Frame
    (``water/MRTask.java:558-559`` outputFrame)."""

    def build() -> Callable:
        mapped = _shard_map(
            fn,
            mesh=table.mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)) + tuple(P() for _ in extra_args),
            out_specs=P(DATA_AXIS),
        )
        return jax.jit(mapped)

    jitted = _get_plan("map_batches", fn, "shard", table, extra_args, build)
    return _dispatch(
        "map_batches",
        table,
        lambda: jitted(table.arrays, table.mask, *extra_args),
    )


def gather_rows(x: jax.Array, n_valid: int) -> np.ndarray:
    """Pull a row-sharded device result back to host, dropping pad rows."""
    return np.asarray(jax.device_get(x))[:n_valid]


def map_reduce_frame(
    fn: Callable,
    frame: Frame,
    columns: Optional[Sequence[str]] = None,
    reduce: str = "sum",
):
    """Cluster-aware MRTask entry: ``map_reduce`` over a Frame that fans
    contiguous row ranges out to the members of a live multi-node
    application-plane cloud (h2o3_tpu/cluster/tasks.py), each member
    running the local shard_map+psum path over its range.  With no cloud
    — or a cloud of one — this is exactly the local path.  Returns the
    reduced pytree as HOST (numpy) arrays in both cases, so callers see
    one contract regardless of where the shards ran."""
    layout = getattr(frame, "chunk_layout", None)
    if columns is not None:
        names = list(columns)
    elif layout is not None:
        # metadata off the layout: listing a chunk-homed frame's numeric
        # columns must not gather its remote chunks
        names = [n for n, t in zip(layout["column_names"],
                                   layout["column_types"])
                 if t not in (ColType.STR, ColType.UUID)]
    else:
        names = [c.name for c in frame.columns
                 if c.type not in (ColType.STR, ColType.UUID)]
    try:
        from h2o3_tpu.cluster import active_cloud

        cloud = active_cloud()
    except Exception:
        cloud = None
    # span both paths under one kind: a trace reads identically whether the
    # shards ran on this node's mesh or fanned out over the cloud, and the
    # distributed path's member/RPC child spans hang underneath
    with telemetry.Span("map_reduce_frame", rows=int(frame.nrows),
                        columns=len(names), distributed=cloud is not None):
        if cloud is not None and layout is not None:
            # chunk-homed frame: map-side execution on each group's ring
            # home, only partials cross the wire (cluster/frames.py)
            from h2o3_tpu.cluster.frames import map_reduce_chunk_homed

            return map_reduce_chunk_homed(
                fn, frame, reduce=reduce, cloud=cloud, names=names)
        if cloud is None:
            table = FrameTable.from_frame(frame, columns=names)
            out = map_reduce(fn, table, reduce=reduce)
            return jax.tree.map(np.asarray, out)
        from h2o3_tpu.cluster.tasks import distributed_map_reduce

        host = {n: frame.col(n).numeric_view() for n in names}
        return distributed_map_reduce(fn, host, reduce=reduce, cloud=cloud)
