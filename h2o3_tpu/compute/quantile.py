"""Distributed quantiles via iterative histogram refinement.

Reference: ``hex/quantile/Quantile.java`` — build a histogram over the current
[lo, hi] range, find the bin containing the target rank, zoom into that bin,
repeat until exact; used by rapids ``quantile`` and by GBM's
``histogram_type=QuantilesGlobal`` (``h2o-algos/.../tree/GlobalQuantilesCalc.java``).

TPU-native: the histogram per refinement round is one masked bincount that
XLA reduces across shards (sharded-in, replicated-out — the collective is
implicit). All probs refine simultaneously (vectorized over the prob axis);
fixed iteration count keeps shapes/trip counts static for jit. Counts are
int32 (exact to 2^31 rows) regardless of the data dtype; fractional rank
interpolation happens host-side in float64, so results stay exact for row
counts past 2^24 where float32 rank arithmetic would round.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

_NBINS = 1024
_MAX_ITERS = 64  # safety bound; the loop exits on bin convergence first


@jax.jit
def _count_valid(x, mask):
    return jnp.sum((mask & ~jnp.isnan(x)).astype(jnp.int32))


@partial(jax.jit, static_argnames=("nbins",))
def _order_stats_kernel(x, mask, ranks, nbins: int = _NBINS):
    """Exact order statistics at integer ``ranks`` (int32) via histogram zoom.

    Zooms until the target bin narrows below the floating-point resolution of
    its endpoints (all values inside are then one representable number), the
    same run-to-exact contract as the reference's iterative refinement —
    robust to outlier-dominated ranges where a fixed iteration count is not.
    """
    ok = mask & ~jnp.isnan(x)
    gmin = jnp.min(jnp.where(ok, x, jnp.inf))
    gmax = jnp.max(jnp.where(ok, x, -jnp.inf))
    eps = jnp.asarray(1e-7 if x.dtype == jnp.float32 else 1e-15, x.dtype)

    def locate(rank):
        def cond(carry):
            lo, hi, cnt, it = carry
            width_converged = (hi - lo) <= eps * jnp.maximum(
                jnp.maximum(jnp.abs(lo), jnp.abs(hi)), jnp.asarray(1e-30, x.dtype)
            )
            return (cnt > 1) & ~width_converged & (it < _MAX_ITERS)

        def body(carry):
            lo, hi, _, it = carry
            span = jnp.maximum(hi - lo, jnp.asarray(1e-30, x.dtype))
            in_range = ok & (x >= lo) & (x <= hi)
            idx = jnp.clip(((x - lo) / span * nbins).astype(jnp.int32), 0, nbins - 1)
            hist = jnp.zeros(nbins, jnp.int32).at[idx].add(in_range.astype(jnp.int32))
            below = jnp.sum((ok & (x < lo)).astype(jnp.int32))
            cum = below + jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(hist)[:-1]])
            bin_i = jnp.clip(jnp.searchsorted(cum, rank, side="right") - 1, 0, nbins - 1)
            new_lo = lo + bin_i.astype(x.dtype) * span / nbins
            new_hi = lo + (bin_i + 1).astype(x.dtype) * span / nbins
            return new_lo, new_hi, hist[bin_i], it + 1

        lo, hi, _, _ = jax.lax.while_loop(
            cond, body, (gmin, gmax, jnp.asarray(2, jnp.int32), jnp.asarray(0, jnp.int32))
        )
        # the exact order statistic inside the converged sliver
        return jnp.min(jnp.where(ok & (x >= lo), x, jnp.inf))

    return jax.vmap(locate)(ranks)


def quantiles(x, probs: Sequence[float], mask=None) -> np.ndarray:
    """Quantiles (linear interpolation, R type 7 — the reference default) of a
    possibly sharded array; NaNs ignored."""
    x = jnp.asarray(x)
    if mask is None:
        mask = jnp.ones(x.shape, dtype=bool)
    n = int(jax.device_get(_count_valid(x, mask)))
    if n == 0:
        return np.full(len(list(probs)), np.nan)
    # float64 rank arithmetic on host — exact for any row count
    p = np.asarray(probs, dtype=np.float64)
    ranks = p * (n - 1)
    rlo = np.floor(ranks).astype(np.int32)
    rhi = np.minimum(rlo + 1, n - 1).astype(np.int32)
    frac = ranks - rlo
    vals = jax.device_get(
        _order_stats_kernel(x, mask, jnp.asarray(np.concatenate([rlo, rhi])))
    ).astype(np.float64)
    v_lo, v_hi = vals[: len(p)], vals[len(p) :]
    return v_lo + frac * (v_hi - v_lo)


# ---------------------------------------------------------------------------
# mergeable per-partition sketches (GlobalQuantilesCalc over chunk homes)
#
# The distributed booster bins each feature ONCE from per-home sketch
# partials: every chunk home summarizes its local rows (exact uniques for
# low-cardinality columns, a dense quantile grid otherwise), the caller
# merges the partials into global [nbins-1] edges, and only the tiny
# sketches cross the wire — never rows.  The merge is a deterministic
# function of the partials in canonical group order, so every topology
# that sees the same group decomposition produces identical edges.


def sketch_column(col: np.ndarray, nbins: int, grid: int = 8) -> dict:
    """One partition's summary of a feature column (NaNs ignored):
    ``{"n", "uniques"}`` when at most ``nbins`` distinct values exist,
    else ``{"n", "q"}`` with a ``grid * nbins + 1``-point quantile grid."""
    valid = col[~np.isnan(col)]
    n = int(valid.size)
    if n == 0:
        return {"n": 0}
    uniq = np.unique(valid.astype(np.float64))
    if uniq.size <= nbins:
        return {"n": n, "uniques": uniq}
    q = np.quantile(valid.astype(np.float64),
                    np.linspace(0.0, 1.0, grid * nbins + 1))
    return {"n": n, "q": q}


def merge_edges(parts, nbins: int) -> np.ndarray:
    """Global interior bin edges [nbins-1] from per-partition sketches.

    Low-cardinality columns (every partial exact, union still <= nbins)
    get exact midpoint edges with +inf padding — the same low-card rule
    as ``ops.histogram.make_bins``, so categorical codes and indicators
    each keep their own bin.  Otherwise the pooled, count-weighted
    sketch points answer the interior quantile targets."""
    parts = [p for p in parts if p.get("n", 0) > 0]
    if not parts:
        return np.arange(nbins - 1, dtype=np.float64)
    if all("uniques" in p for p in parts):
        uniq = np.unique(np.concatenate([p["uniques"] for p in parts]))
        if uniq.size <= nbins:
            mids = (uniq[:-1] + uniq[1:]) / 2.0
            e = np.full(nbins - 1, np.inf)
            e[: mids.size] = mids
            return e
    pts_l, wts_l = [], []
    for p in parts:
        arr = np.asarray(p.get("q", p.get("uniques")), np.float64)
        pts_l.append(arr)
        wts_l.append(np.full(arr.size, p["n"] / arr.size, np.float64))
    pts = np.concatenate(pts_l)
    wts = np.concatenate(wts_l)
    order = np.argsort(pts, kind="stable")
    pts, wts = pts[order], wts[order]
    cw = np.cumsum(wts)
    qs = np.linspace(0.0, 1.0, nbins + 1)[1:-1]
    idx = np.searchsorted(cw, qs * cw[-1], side="left")
    e = pts[np.clip(idx, 0, pts.size - 1)]
    return np.maximum.accumulate(e)
