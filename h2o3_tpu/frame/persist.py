"""Frame binary persistence — save/load a Frame to one file.

Reference: ``water/fvec/persist/FramePersist.java`` (frame save/load to the
persist layer) with per-chunk compression codecs from ``water/fvec/C*.java``
chosen at write time (``NewChunk.close()``, ``Chunk.java:35-43``).

TPU-native: one file per frame (zip container, no pickle):
  * ``meta.json`` — names, types, domains, row count, format version;
  * ``col_<i>.bin`` — numeric/time/cat payloads through the chunk codec
    (native/codecs.cpp: CONST / biased ints / SCALED16 / SPARSE / RAW64 —
    the C0DChunk..CXFChunk lineup). Encoding uses the native library when
    available and falls back to the RAW64 tag otherwise; DECODING of every
    tag is implemented in pure python too, so a frame written with the
    native codecs loads anywhere.
  * string/uuid columns: ``col_<i>.json`` (list of str/null).

Categorical codes ride the codec as float64 (small ints -> biased-int tags,
so a low-cardinality column stores ~1 byte/row, like C1Chunk).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zipfile
from typing import List, Optional, Union

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame

FORMAT_VERSION = 1

_NA16 = -32768


def codec_encode(x: np.ndarray) -> bytes:
    """Encode float64 array with the chunk codec; native if available, else
    the RAW64 fallback (tag 0) — both decodable by ``codec_decode``."""
    x = np.ascontiguousarray(x, dtype=np.float64)
    try:
        from h2o3_tpu import native

        blob = native.codec_encode(x)
        if blob is not None:
            return blob
    except Exception:
        pass
    return b"\x00" + struct.pack("<q", len(x)) + x.tobytes()


def codec_decode(blob: bytes) -> np.ndarray:
    """Decode any codec tag in pure python (portable read path)."""
    tag = blob[0]
    (n,) = struct.unpack_from("<q", blob, 1)
    if tag == 0:  # RAW64
        return np.frombuffer(blob, dtype=np.float64, count=n, offset=9).copy()
    if tag == 1:  # CONST
        (v,) = struct.unpack_from("<d", blob, 9)
        return np.full(n, v, dtype=np.float64)
    if tag in (2, 3, 4):  # biased ints
        (bias,) = struct.unpack_from("<d", blob, 9)
        dt = {2: np.int8, 3: np.int16, 4: np.int32}[tag]
        p = np.frombuffer(blob, dtype=dt, count=n, offset=17)
        sentinel = np.iinfo(dt).min
        out = bias + p.astype(np.float64)
        out[p == sentinel] = np.nan
        return out
    if tag == 5:  # SCALED16
        (bias,) = struct.unpack_from("<d", blob, 9)
        p = np.frombuffer(blob, dtype=np.int16, count=n, offset=17)
        out = (bias + p.astype(np.float64)) / 100.0
        out[p == _NA16] = np.nan
        return out
    if tag == 6:  # SPARSE
        (nz,) = struct.unpack_from("<q", blob, 9)
        out = np.zeros(n, dtype=np.float64)
        off = 17
        for _ in range(nz):
            (i,) = struct.unpack_from("<i", blob, off)
            (v,) = struct.unpack_from("<d", blob, off + 4)
            out[i] = v
            off += 12
        return out
    raise ValueError(f"unknown codec tag {tag}")


def save_frame(frame: Frame, path: Union[str, os.PathLike]) -> str:
    """Write the frame to ``path`` (.h2f zip container). Returns the path."""
    path = os.fspath(path)
    meta = {
        "version": FORMAT_VERSION,
        "nrows": frame.nrows,
        "key": frame.key,
        "columns": [
            {
                "name": c.name,
                "type": c.type.name,
                "domain": c.domain,
            }
            for c in frame.columns
        ],
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("meta.json", json.dumps(meta))
        for i, c in enumerate(frame.columns):
            if c.type in (ColType.STR, ColType.UUID):
                z.writestr(
                    f"col_{i}.json",
                    json.dumps([None if v is None else str(v) for v in c.data]),
                )
            elif c.type is ColType.CAT:
                z.writestr(f"col_{i}.bin", codec_encode(
                    np.where(c.data < 0, np.nan, c.data.astype(np.float64))
                ))
            else:  # NUM / TIME / BAD: float64 with NaN NAs
                z.writestr(f"col_{i}.bin", codec_encode(c.data))
    return path


def load_frame(path: Union[str, os.PathLike], key: Optional[str] = None) -> Frame:
    """Read a frame written by ``save_frame``."""
    path = os.fspath(path)
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("meta.json"))
        if meta.get("version", 0) > FORMAT_VERSION:
            raise ValueError(f"frame file version {meta['version']} too new")
        cols: List[Column] = []
        for i, cm in enumerate(meta["columns"]):
            ctype = ColType[cm["type"]]
            if ctype in (ColType.STR, ColType.UUID):
                vals = json.loads(z.read(f"col_{i}.json"))
                data = np.array(vals, dtype=object)
            elif ctype is ColType.CAT:
                f = codec_decode(z.read(f"col_{i}.bin"))
                data = np.where(np.isnan(f), -1, f).astype(np.int32)
            else:
                data = codec_decode(z.read(f"col_{i}.bin"))
            cols.append(Column(cm["name"], data, ctype, cm.get("domain")))
    return Frame(cols, key=key or meta.get("key"))
