"""Multi-format ingest + URI-scheme Persist dispatch.

Reference:
  * Persist SPI: ``water/persist/PersistManager.java`` — storage backends
    registered per URI scheme (``PersistNFS``, ``PersistFS``, eager HTTP,
    plus the S3/HDFS/GCS modules); import resolves a path/glob to sources
    through the scheme's backend.
  * Parsers: the ``ParserProvider`` SPI — CSV (``CsvParser``), SVMLight
    (``water/parser/SVMLightParser``), ARFF (``water/parser/ARFFParser``),
    XLS, and the module parsers ``h2o-parsers/h2o-{parquet,orc,avro}-parser``.
  * Decompression: ``water/parser/ZipUtil`` — gzip/zip transparently
    unwrapped before format sniffing.
  * Multi-file import: ``ParseDataset`` parses every source into one frame
    (``ImportFilesHandler`` + ``ParseDataset.java:241 parseAllKeys``).

TPU-native: all of this is host-side IO; the parsed product is dense
columnar numpy that shards onto the mesh. S3/HDFS/GCS backends are not
implementable in this image (no network egress, no SDKs baked in) — the
scheme registry raises a clear error naming the missing backend instead of
silently treating the URI as a local path.
"""

from __future__ import annotations

import glob as _glob
import gzip
import io
import os
import re
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame, NA_CAT
from h2o3_tpu.frame.parse import (
    DEFAULT_NA_STRINGS,
    _build_column,
    parse_csv,
)


# ---------------------------------------------------------------------------
# Persist SPI (PersistManager scheme dispatch)


class Persist:
    """Storage backend for one URI scheme (water/persist/Persist.java)."""

    scheme: str = "?"

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        """Expand a path/glob/directory to concrete source paths."""
        raise NotImplementedError


class PersistFS(Persist):
    """Local filesystem (PersistNFS/PersistFS): globs + directories."""

    scheme = "file"

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def list(self, path: str) -> List[str]:
        path = os.path.expanduser(path)
        if os.path.isdir(path):
            out = sorted(
                os.path.join(path, n)
                for n in os.listdir(path)
                if not n.startswith(".")
                and os.path.isfile(os.path.join(path, n))
            )
        elif _glob.has_magic(path):
            out = sorted(p for p in _glob.glob(path) if os.path.isfile(p))
        elif os.path.exists(path):
            out = [path]
        else:
            raise FileNotFoundError(path)
        if not out:
            raise FileNotFoundError(f"no files match {path!r}")
        return out


class PersistHTTP(Persist):
    """Eager HTTP download (the reference's PersistEagerHTTP)."""

    scheme = "http"

    def read_bytes(self, path: str) -> bytes:
        import urllib.request

        # bounded: an unresponsive host must error, not hang the importing
        # thread forever
        with urllib.request.urlopen(path, timeout=60) as resp:
            return resp.read()

    def list(self, path: str) -> List[str]:
        return [path]  # no listing protocol over plain HTTP


_PERSIST: Dict[str, Persist] = {
    "file": PersistFS(),
    "http": PersistHTTP(),
    "https": PersistHTTP(),
}

#: schemes the reference supports through optional modules that cannot run
#: in this image (no egress / SDKs); named so the error is actionable
_KNOWN_UNAVAILABLE = ("s3", "s3a", "s3n", "hdfs", "gs", "gcs", "jdbc")

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


def resolve_persist(uri: str) -> Tuple[Persist, str]:
    """URI -> (backend, backend-local path). Plain paths map to file."""
    m = _SCHEME_RE.match(uri)
    if not m:
        return _PERSIST["file"], uri
    scheme = m.group(1).lower()
    if scheme in _PERSIST:
        path = uri[len(scheme) + 3 :] if scheme == "file" else uri
        return _PERSIST[scheme], path
    if scheme in _KNOWN_UNAVAILABLE:
        raise ValueError(
            f"persist backend for scheme {scheme!r} is not available in "
            f"this build (reference module: h2o-persist-{scheme})"
        )
    raise ValueError(f"unknown URI scheme {scheme!r}")


def register_persist(backend: Persist) -> None:
    """Register a storage backend (PersistManager plug-in point)."""
    _PERSIST[backend.scheme] = backend


def list_sources(uri: str) -> List[str]:
    backend, path = resolve_persist(uri)
    return backend.list(path)


# ---------------------------------------------------------------------------
# transparent decompression (water/parser/ZipUtil)


def decompress_parts(name: str, data: bytes) -> List[Tuple[str, bytes]]:
    """Unwrap gzip/zip by magic bytes. A multi-entry zip yields one part
    per entry (each recursively unwrapped) — entries are parsed separately
    and row-bound, never byte-concatenated (a join would bury each file's
    header mid-data and corrupt binary formats)."""
    if data[:2] == b"\x1f\x8b":  # gzip
        inner = name[:-3] if name.lower().endswith(".gz") else name
        return decompress_parts(inner, gzip.decompress(data))
    if data[:4] == b"PK\x03\x04":  # zip
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            names = sorted(
                n for n in z.namelist()
                if not n.endswith("/") and not os.path.basename(n).startswith(".")
            )
            if not names:
                raise ValueError(f"{name}: empty zip archive")
            out: List[Tuple[str, bytes]] = []
            for n in names:
                out.extend(decompress_parts(os.path.basename(n), z.read(n)))
            return out
    return [(name, data)]


def _decompress(name: str, data: bytes) -> Tuple[str, bytes]:
    """First decompressed part — for format sniffing only."""
    return decompress_parts(name, data)[0]


# ---------------------------------------------------------------------------
# format sniffing + per-format parsers (ParserProvider.guessSetup)


def sniff_format(name: str, data: bytes) -> str:
    low = name.lower()
    if data[:4] == b"PAR1" or low.endswith(".parquet"):
        return "parquet"
    if low.endswith((".svm", ".svmlight")):
        return "svmlight"
    if low.endswith(".arff"):
        return "arff"
    head = data[:4096].decode("utf-8", errors="replace")
    for line in head.splitlines():
        s = line.strip()
        if not s or s.startswith("%"):  # ARFF comments may lead the file
            continue
        if re.match(r"(?i)^@relation\b", s):
            return "arff"
        break
    # svmlight: every sampled line is "label idx:val ..."
    lines = [l for l in head.splitlines()[:20] if l.strip()]
    if lines and all(
        re.match(r"^[+-]?[\d.eE+-]+(\s+\d+:[+-]?[\d.eE+-]+)*\s*(#.*)?$", l)
        and ":" in l
        for l in lines
    ):
        return "svmlight"
    return "csv"


def parse_svmlight(text: str, dest_ncols: Optional[int] = None) -> Frame:
    """SVMLight/libsvm sparse rows -> dense frame.

    Reference: ``water/parser/SVMLightParser`` — first output column is the
    target, features become C1..Cn by their (1-based) index; absent entries
    are 0 (sparse semantics), not NA. Comments after '#'."""
    targets: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        try:
            targets.append(float(toks[0]))
        except ValueError:
            raise ValueError(f"svmlight line {lineno}: bad label {toks[0]!r}")
        entries: List[Tuple[int, float]] = []
        prev = 0
        for t in toks[1:]:
            if t.startswith("qid:"):  # ranking qid: accepted and ignored
                continue
            try:
                i_s, v_s = t.split(":", 1)
                i, v = int(i_s), float(v_s)
            except ValueError:
                raise ValueError(f"svmlight line {lineno}: bad entry {t!r}")
            if i <= 0 or i <= prev:
                raise ValueError(
                    f"svmlight line {lineno}: indices must be increasing and "
                    f"1-based (got {i} after {prev})"
                )
            prev = i
            entries.append((i, v))
            max_idx = max(max_idx, i)
        rows.append(entries)
    n = len(rows)
    ncols = dest_ncols or max_idx
    X = np.zeros((n, ncols), dtype=np.float64)
    for r, entries in enumerate(rows):
        for i, v in entries:
            X[r, i - 1] = v
    cols = [Column("target", np.asarray(targets, np.float64), ColType.NUM)]
    cols += [Column(f"C{j + 1}", X[:, j], ColType.NUM) for j in range(ncols)]
    return Frame(cols)


_ARFF_ATTR_RE = re.compile(r"(?i)^@attribute\s+('[^']+'|\"[^\"]+\"|\S+)\s+(.+)$")


def parse_arff(text: str, na_strings: Sequence[str] = DEFAULT_NA_STRINGS) -> Frame:
    """ARFF: @relation/@attribute/@data (``water/parser/ARFFParser``).

    numeric/real/integer -> NUM, {a,b,...} nominal -> CAT with the DECLARED
    domain (order preserved, even for levels absent from the data), string
    -> STR, date -> TIME. '?' is NA. Sparse {i v, ...} data rows are not
    supported (explicit error)."""
    names: List[str] = []
    types: List[ColType] = []
    domains: List[Optional[List[str]]] = []
    lines = text.splitlines()
    data_start = None
    for li, line in enumerate(lines):
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        if re.match(r"(?i)^@relation\b", s):
            continue
        if re.match(r"(?i)^@data\b", s):
            data_start = li + 1
            break
        m = _ARFF_ATTR_RE.match(s)
        if m:
            name = m.group(1).strip("'\"")
            spec = m.group(2).strip()
            names.append(name)
            if spec.startswith("{"):
                dom = [v.strip().strip("'\"") for v in spec.strip("{} ").split(",")]
                types.append(ColType.CAT)
                domains.append(dom)
            elif re.match(r"(?i)^(numeric|real|integer)\b", spec):
                types.append(ColType.NUM)
                domains.append(None)
            elif re.match(r"(?i)^string\b", spec):
                types.append(ColType.STR)
                domains.append(None)
            elif re.match(r"(?i)^date\b", spec):
                types.append(ColType.TIME)
                domains.append(None)
            else:
                raise ValueError(f"unsupported ARFF attribute type {spec!r}")
            continue
        raise ValueError(f"unrecognized ARFF header line: {s!r}")
    if data_start is None:
        raise ValueError("ARFF file has no @data section")
    if not names:
        raise ValueError("ARFF file declares no attributes")

    width = len(names)
    cells: List[List[str]] = [[] for _ in range(width)]
    from h2o3_tpu.frame.parse import _tokenize

    for line in lines[data_start:]:
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        if s.startswith("{"):
            raise ValueError("sparse ARFF data rows are not supported")
        toks = _tokenize(s, ",")
        for j in range(width):
            t = toks[j].strip().strip("'\"") if j < len(toks) else "?"
            cells[j].append(t)

    na = frozenset(set(na_strings) | {"?"})
    cols: List[Column] = []
    for j in range(width):
        if types[j] is ColType.CAT:
            dom = domains[j]
            index = {lv: i for i, lv in enumerate(dom)}
            codes = np.fromiter(
                (NA_CAT if t in na else index.get(t, NA_CAT) for t in cells[j]),
                dtype=np.int32,
                count=len(cells[j]),
            )
            cols.append(Column(names[j], codes, ColType.CAT, dom))
        else:
            cols.append(_build_column(names[j], types[j], cells[j], na))
    return Frame(cols)


def parse_parquet(data: bytes) -> Frame:
    """Parquet via pyarrow when available (h2o-parquet-parser analogue)."""
    try:
        import pyarrow.parquet as pq
    except ImportError:
        raise ValueError(
            "parquet ingest needs pyarrow, which is not available in this "
            "build (reference module: h2o-parquet-parser)"
        )
    table = pq.read_table(io.BytesIO(data))
    cols: List[Column] = []
    for name in table.column_names:
        arr = table.column(name).to_pandas()
        vals = np.asarray(arr)
        if vals.dtype.kind in "iuf":
            cols.append(Column(name, vals.astype(np.float64), ColType.NUM))
        elif vals.dtype.kind == "b":
            cols.append(Column(name, vals.astype(np.float64), ColType.NUM))
        elif vals.dtype.kind == "M":
            ms = vals.astype("datetime64[ms]").astype(np.int64).astype(np.float64)
            cols.append(Column(name, ms, ColType.TIME))
        else:
            from h2o3_tpu.frame.parse import column_from_strings

            cols.append(
                column_from_strings(
                    name, [None if v is None else str(v) for v in arr]
                )
            )
    return Frame(cols)


# ---------------------------------------------------------------------------
# top-level import + parse (ImportFilesHandler + ParseDataset)

_SVM_COL_RE = re.compile(r"^C\d+$")


def rbind_all(frames: List[Frame]) -> Frame:
    """Row-bind parsed parts into one frame. Sparse-format parts (svmlight)
    routinely differ in max feature index; a narrower frame whose names are
    a prefix of the widest and whose missing columns are all C<k> is padded
    with zeros (sparse semantics) before binding."""
    if not frames:
        raise ValueError("nothing to bind")
    widest = max(frames, key=lambda f: f.ncols)
    out: Optional[Frame] = None
    for fr in frames:
        if fr.ncols < widest.ncols and fr.names == widest.names[: fr.ncols] and all(
            _SVM_COL_RE.match(n) for n in widest.names[fr.ncols :]
        ):
            pad = [
                Column(n, np.zeros(fr.nrows, np.float64), ColType.NUM)
                for n in widest.names[fr.ncols :]
            ]
            fr = Frame(list(fr.columns) + pad)
        out = fr if out is None else out.rbind(fr)
    return out


def parse_bytes(
    name: str,
    data: bytes,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """One raw blob -> Frame: decompression, per-part format sniff, parse,
    bind. The single format dispatch shared by the library path
    (parse_source/import_parse) and the REST /3/Parse handler."""
    frames: List[Frame] = []
    for part_name, part in decompress_parts(name, data):
        f = fmt or sniff_format(part_name, part)
        if f == "csv":
            frames.append(
                parse_csv(part.decode("utf-8", errors="replace"), **csv_kw)
            )
        elif f == "svmlight":
            frames.append(parse_svmlight(part.decode("utf-8", errors="replace")))
        elif f == "arff":
            frames.append(parse_arff(part.decode("utf-8", errors="replace")))
        elif f == "parquet":
            frames.append(parse_parquet(part))
        else:
            raise ValueError(f"unknown format {f!r}")
    return rbind_all(frames)


def parse_source(
    uri: str,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """One source -> Frame: persist dispatch, decompression, format sniff."""
    backend, path = resolve_persist(uri)
    return parse_bytes(
        os.path.basename(path) or path, backend.read_bytes(path), fmt=fmt, **csv_kw
    )


def import_parse(
    uri: str,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """Path/glob/directory -> ONE frame (multi-file sources are parsed
    independently and row-bound, with categorical domains unified — the
    reference's multi-file ParseDataset)."""
    sources = list_sources(uri)
    return rbind_all(
        [parse_source(src, fmt=fmt, **csv_kw) for src in sources]
    )


# ---------------------------------------------------------------------------
# SQL import (water/jdbc/SQLManager.java)


def import_sql_table(
    connection_url: str,
    table: Optional[str] = None,
    select_query: Optional[str] = None,
    columns: Optional[Sequence[str]] = None,
) -> Frame:
    """Import a SQL table/query result as a Frame.

    Reference: ``water/jdbc/SQLManager.java`` — range-partitioned parallel
    selects over a JDBC driver. This build ships the driver available in a
    pure-Python image: sqlite via the stdlib (``sqlite:/path`` or
    ``jdbc:sqlite:/path`` URLs). Other engines raise an actionable error
    naming the reference module, like the persist scheme registry does.
    """
    import sqlite3

    url = connection_url
    for prefix in ("jdbc:sqlite:", "sqlite://", "sqlite:"):
        if url.lower().startswith(prefix):
            path = url[len(prefix):]
            break
    else:
        raise ValueError(
            f"unsupported SQL connection url {connection_url!r}; this build "
            f"ships sqlite ('sqlite:/path/db'); other engines need the "
            f"reference's JDBC drivers (water/jdbc/SQLManager.java)"
        )
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if select_query is None:
        if not table:
            raise ValueError("either table or select_query is required")
        if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", table):
            raise ValueError(f"invalid table name {table!r}")
        cols_sql = "*"
        if columns:
            for c in columns:
                if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", c):
                    raise ValueError(f"invalid column name {c!r}")
            cols_sql = ", ".join(columns)
        select_query = f"SELECT {cols_sql} FROM {table}"
    conn = sqlite3.connect(path)
    try:
        cur = conn.execute(select_query)
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    from h2o3_tpu.frame.parse import column_from_strings

    out: List[Column] = []
    for j, name in enumerate(names):
        vals = [r[j] for r in rows]
        non_null = [v for v in vals if v is not None]
        if all(isinstance(v, (int, float)) for v in non_null):
            data = np.array(
                [np.nan if v is None else float(v) for v in vals], np.float64
            )
            out.append(Column(name, data, ColType.NUM))
        else:
            out.append(
                column_from_strings(
                    name, [None if v is None else str(v) for v in vals]
                )
            )
    return Frame(out)
