"""Multi-format ingest + URI-scheme Persist dispatch.

Reference:
  * Persist SPI: ``water/persist/PersistManager.java`` — storage backends
    registered per URI scheme (``PersistNFS``, ``PersistFS``, eager HTTP,
    plus the S3/HDFS/GCS modules); import resolves a path/glob to sources
    through the scheme's backend.
  * Parsers: the ``ParserProvider`` SPI — CSV (``CsvParser``), SVMLight
    (``water/parser/SVMLightParser``), ARFF (``water/parser/ARFFParser``),
    XLS, and the module parsers ``h2o-parsers/h2o-{parquet,orc,avro}-parser``.
  * Decompression: ``water/parser/ZipUtil`` — gzip/zip transparently
    unwrapped before format sniffing.
  * Multi-file import: ``ParseDataset`` parses every source into one frame
    (``ImportFilesHandler`` + ``ParseDataset.java:241 parseAllKeys``).

TPU-native: all of this is host-side IO; the parsed product is dense
columnar numpy that shards onto the mesh. The S3/GCS/HDFS schemes are
served by stdlib HTTP backends (``frame/cloud.py`` — SigV4, GCS JSON
API, WebHDFS), registered below exactly like the reference's
h2o-persist-* modules register with the PersistManager.
"""

from __future__ import annotations

import glob as _glob
import gzip
import io
import os
import re
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame, NA_CAT
from h2o3_tpu.frame.parse import (
    DEFAULT_NA_STRINGS,
    _build_column,
    parse_csv,
)
from h2o3_tpu.util import telemetry

#: ingest accounting by wire format. Bytes are counted per decompressed
#: part (parse_bytes runs after decompress_parts), i.e. what the parsers
#: actually chewed through — NOT the compressed on-the-wire size
_INGEST_BYTES = telemetry.counter(
    "ingest_bytes_total", "decompressed bytes parsed per source part",
    labels=("format",),
)
_INGEST_ROWS = telemetry.counter(
    "ingest_rows_total", "rows materialized per source part",
    labels=("format",),
)


# ---------------------------------------------------------------------------
# Persist SPI (PersistManager scheme dispatch)


class Persist:
    """Storage backend for one URI scheme (water/persist/Persist.java)."""

    scheme: str = "?"

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        """Expand a path/glob/directory to concrete source paths."""
        raise NotImplementedError


class PersistFS(Persist):
    """Local filesystem (PersistNFS/PersistFS): globs + directories."""

    scheme = "file"

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def list(self, path: str) -> List[str]:
        path = os.path.expanduser(path)
        if os.path.isdir(path):
            out = sorted(
                os.path.join(path, n)
                for n in os.listdir(path)
                if not n.startswith(".")
                and os.path.isfile(os.path.join(path, n))
            )
        elif _glob.has_magic(path):
            out = sorted(p for p in _glob.glob(path) if os.path.isfile(p))
        elif os.path.exists(path):
            out = [path]
        else:
            raise FileNotFoundError(path)
        if not out:
            raise FileNotFoundError(f"no files match {path!r}")
        return out


class PersistHTTP(Persist):
    """Eager HTTP download (the reference's PersistEagerHTTP)."""

    scheme = "http"

    def read_bytes(self, path: str) -> bytes:
        import urllib.request

        # bounded: an unresponsive host must error, not hang the importing
        # thread forever
        with urllib.request.urlopen(path, timeout=60) as resp:
            return resp.read()

    def list(self, path: str) -> List[str]:
        return [path]  # no listing protocol over plain HTTP


_PERSIST: Dict[str, Persist] = {
    "file": PersistFS(),
    "http": PersistHTTP(),
    "https": PersistHTTP(),
}

#: schemes served elsewhere: jdbc goes through import_sql_table (the
#: SQLManager analogue), not the byte-oriented persist layer
_KNOWN_UNAVAILABLE = ("jdbc",)

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


def resolve_persist(uri: str) -> Tuple[Persist, str]:
    """URI -> (backend, backend-local path). Plain paths map to file."""
    if uri.lower().startswith("jdbc:"):  # JDBC urls have no '//'
        raise ValueError(
            "jdbc sources import through import_sql_table / "
            "/99/ImportSQLTable (water/jdbc/SQLManager.java), not the "
            "byte-oriented persist layer")
    m = _SCHEME_RE.match(uri)
    if not m:
        return _PERSIST["file"], uri
    scheme = m.group(1).lower()
    if scheme in _PERSIST:
        path = uri[len(scheme) + 3 :] if scheme == "file" else uri
        return _PERSIST[scheme], path
    if scheme in _KNOWN_UNAVAILABLE:
        raise ValueError(
            f"persist backend for scheme {scheme!r} is not available in "
            f"this build (reference module: h2o-persist-{scheme})"
        )
    raise ValueError(f"unknown URI scheme {scheme!r}")


def register_persist(backend: Persist) -> None:
    """Register a storage backend (PersistManager plug-in point)."""
    _PERSIST[backend.scheme] = backend


def list_sources(uri: str) -> List[str]:
    backend, path = resolve_persist(uri)
    return backend.list(path)


# ---------------------------------------------------------------------------
# transparent decompression (water/parser/ZipUtil)


def _zip_is_opaque(data: bytes) -> bool:
    """True when a PK-magic blob must reach a parser whole instead of
    being exploded into entries: an .xlsx IS a zip (the XLSX parser needs
    the archive), and an unreadable zip is passed through for the format
    sniffer to reject with a real diagnosis."""
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            return "[Content_Types].xml" in z.namelist()
    except zipfile.BadZipFile:
        return True


def _zip_entry_names(z: zipfile.ZipFile, name: str) -> List[str]:
    """Parseable entries of an archive, sorted: directories and hidden
    dotfiles (e.g. __MACOSX resource forks) are skipped."""
    names = sorted(
        n for n in z.namelist()
        if not n.endswith("/") and not os.path.basename(n).startswith(".")
    )
    if not names:
        raise ValueError(f"{name}: empty zip archive")
    return names


def decompress_parts(name: str, data: bytes) -> List[Tuple[str, bytes]]:
    """Unwrap gzip/zip by magic bytes. A multi-entry zip yields one part
    per entry (each recursively unwrapped) — entries are parsed separately
    and row-bound, never byte-concatenated (a join would bury each file's
    header mid-data and corrupt binary formats)."""
    if data[:2] == b"\x1f\x8b":  # gzip
        inner = name[:-3] if name.lower().endswith(".gz") else name
        return decompress_parts(inner, gzip.decompress(data))
    if data[:4] == b"PK\x03\x04":  # zip
        if _zip_is_opaque(data):
            return [(name, data)]
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            out: List[Tuple[str, bytes]] = []
            for n in _zip_entry_names(z, name):
                out.extend(decompress_parts(os.path.basename(n), z.read(n)))
            return out
    return [(name, data)]


def _decompress(name: str, data: bytes) -> Tuple[str, bytes]:
    """First decompressed part — for format sniffing only."""
    return decompress_parts(name, data)[0]


#: magic prefixes that mean "another archive layer" — nested archives are
#: rare enough to materialize; everything else streams
def _is_archive(head: bytes) -> bool:
    return head[:2] == b"\x1f\x8b" or head[:4] == b"PK\x03\x04"


class _PrefixedReader:
    """File-like serving an already-read prefix, then the wrapped stream —
    lets format sniffing peek without losing streamed decompression."""

    def __init__(self, head: bytes, stream) -> None:
        self._head = head
        self._pos = 0
        self._stream = stream
        #: decompressed bytes handed to the consumer (ingest accounting)
        self.count = 0

    def read(self, n: int = -1) -> bytes:
        out: List[bytes] = []
        if self._pos < len(self._head):
            if n is None or n < 0:
                out.append(self._head[self._pos:])
                self._pos = len(self._head)
            else:
                take = self._head[self._pos:self._pos + n]
                self._pos += len(take)
                out.append(take)
                n -= len(take)
        if n is None or n < 0:
            out.append(self._stream.read())
        elif n > 0:
            out.append(self._stream.read(n))
        b = b"".join(out)
        self.count += len(b)
        return b


def iter_part_streams(name: str, data: bytes):
    """Streamed counterpart of decompress_parts: yields (part_name,
    file-like) with gzip/zip entries decoded INCREMENTALLY as the consumer
    reads, so decompression overlaps the parallel parse's chunk
    tokenization instead of materializing whole decompressed parts first.
    Nested archives (gz-in-zip etc.) recurse, materializing only the
    nested layer."""
    if data[:2] == b"\x1f\x8b":  # gzip
        inner = name[:-3] if name.lower().endswith(".gz") else name
        gf = gzip.GzipFile(fileobj=io.BytesIO(data))
        head = gf.read(4)
        if _is_archive(head):
            yield from iter_part_streams(inner, head + gf.read())
        else:
            yield inner, _PrefixedReader(head, gf)
        return
    if data[:4] == b"PK\x03\x04":  # zip
        if _zip_is_opaque(data):  # xlsx / unreadable: hand over whole
            yield name, io.BytesIO(data)
            return
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            for n in _zip_entry_names(z, name):
                with z.open(n) as probe:
                    head = probe.read(4)
                if _is_archive(head):
                    yield from iter_part_streams(os.path.basename(n), z.read(n))
                else:
                    with z.open(n) as f:
                        yield os.path.basename(n), f
        return
    yield name, io.BytesIO(data)


# ---------------------------------------------------------------------------
# format sniffing + per-format parsers (ParserProvider.guessSetup)


def sniff_format(name: str, data: bytes) -> str:
    low = name.lower()
    if data[:4] == b"PAR1" or low.endswith(".parquet"):
        return "parquet"
    if data[:3] == b"ORC" or low.endswith(".orc"):
        return "orc"
    if data[:4] == b"Obj\x01" or low.endswith(".avro"):
        return "avro"
    if data[:4] == b"PK\x03\x04" or low.endswith(".xlsx"):
        return "xlsx"
    if data[:4] == b"\xd0\xcf\x11\xe0" or low.endswith(".xls"):
        return "xls"
    if low.endswith((".svm", ".svmlight")):
        return "svmlight"
    if low.endswith(".arff"):
        return "arff"
    head = data[:4096].decode("utf-8", errors="replace")
    for line in head.splitlines():
        s = line.strip()
        if not s or s.startswith("%"):  # ARFF comments may lead the file
            continue
        if re.match(r"(?i)^@relation\b", s):
            return "arff"
        break
    # svmlight: every sampled line is "label idx:val ..."
    lines = [l for l in head.splitlines()[:20] if l.strip()]
    if lines and all(
        re.match(r"^[+-]?[\d.eE+-]+(\s+\d+:[+-]?[\d.eE+-]+)*\s*(#.*)?$", l)
        and ":" in l
        for l in lines
    ):
        return "svmlight"
    return "csv"


def parse_svmlight(text: str, dest_ncols: Optional[int] = None) -> Frame:
    """SVMLight/libsvm sparse rows -> dense frame.

    Reference: ``water/parser/SVMLightParser`` — first output column is the
    target, features become C1..Cn by their (1-based) index; absent entries
    are 0 (sparse semantics), not NA. Comments after '#'."""
    targets: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        try:
            targets.append(float(toks[0]))
        except ValueError:
            raise ValueError(f"svmlight line {lineno}: bad label {toks[0]!r}")
        entries: List[Tuple[int, float]] = []
        prev = 0
        for t in toks[1:]:
            if t.startswith("qid:"):  # ranking qid: accepted and ignored
                continue
            try:
                i_s, v_s = t.split(":", 1)
                i, v = int(i_s), float(v_s)
            except ValueError:
                raise ValueError(f"svmlight line {lineno}: bad entry {t!r}")
            if i <= 0 or i <= prev:
                raise ValueError(
                    f"svmlight line {lineno}: indices must be increasing and "
                    f"1-based (got {i} after {prev})"
                )
            prev = i
            entries.append((i, v))
            max_idx = max(max_idx, i)
        rows.append(entries)
    n = len(rows)
    ncols = dest_ncols or max_idx
    X = np.zeros((n, ncols), dtype=np.float64)
    for r, entries in enumerate(rows):
        for i, v in entries:
            X[r, i - 1] = v
    cols = [Column("target", np.asarray(targets, np.float64), ColType.NUM)]
    cols += [Column(f"C{j + 1}", X[:, j], ColType.NUM) for j in range(ncols)]
    return Frame(cols)


_ARFF_ATTR_RE = re.compile(r"(?i)^@attribute\s+('[^']+'|\"[^\"]+\"|\S+)\s+(.+)$")


def parse_arff(text: str, na_strings: Sequence[str] = DEFAULT_NA_STRINGS) -> Frame:
    """ARFF: @relation/@attribute/@data (``water/parser/ARFFParser``).

    numeric/real/integer -> NUM, {a,b,...} nominal -> CAT with the DECLARED
    domain (order preserved, even for levels absent from the data), string
    -> STR, date -> TIME. '?' is NA. Sparse {i v, ...} data rows are not
    supported (explicit error)."""
    names: List[str] = []
    types: List[ColType] = []
    domains: List[Optional[List[str]]] = []
    lines = text.splitlines()
    data_start = None
    for li, line in enumerate(lines):
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        if re.match(r"(?i)^@relation\b", s):
            continue
        if re.match(r"(?i)^@data\b", s):
            data_start = li + 1
            break
        m = _ARFF_ATTR_RE.match(s)
        if m:
            name = m.group(1).strip("'\"")
            spec = m.group(2).strip()
            names.append(name)
            if spec.startswith("{"):
                dom = [v.strip().strip("'\"") for v in spec.strip("{} ").split(",")]
                types.append(ColType.CAT)
                domains.append(dom)
            elif re.match(r"(?i)^(numeric|real|integer)\b", spec):
                types.append(ColType.NUM)
                domains.append(None)
            elif re.match(r"(?i)^string\b", spec):
                types.append(ColType.STR)
                domains.append(None)
            elif re.match(r"(?i)^date\b", spec):
                types.append(ColType.TIME)
                domains.append(None)
            else:
                raise ValueError(f"unsupported ARFF attribute type {spec!r}")
            continue
        raise ValueError(f"unrecognized ARFF header line: {s!r}")
    if data_start is None:
        raise ValueError("ARFF file has no @data section")
    if not names:
        raise ValueError("ARFF file declares no attributes")

    width = len(names)
    cells: List[List[str]] = [[] for _ in range(width)]
    from h2o3_tpu.frame.parse import _tokenize

    for line in lines[data_start:]:
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        if s.startswith("{"):
            raise ValueError("sparse ARFF data rows are not supported")
        toks = _tokenize(s, ",")
        for j in range(width):
            t = toks[j].strip().strip("'\"") if j < len(toks) else "?"
            cells[j].append(t)

    na = frozenset(set(na_strings) | {"?"})
    cols: List[Column] = []
    for j in range(width):
        if types[j] is ColType.CAT:
            dom = domains[j]
            index = {lv: i for i, lv in enumerate(dom)}
            codes = np.fromiter(
                (NA_CAT if t in na else index.get(t, NA_CAT) for t in cells[j]),
                dtype=np.int32,
                count=len(cells[j]),
            )
            cols.append(Column(names[j], codes, ColType.CAT, dom))
        else:
            cols.append(_build_column(names[j], types[j], cells[j], na))
    return Frame(cols)


def _frame_from_arrow(table) -> Frame:
    """Shared pyarrow Table -> Frame conversion (parquet + orc)."""
    cols: List[Column] = []
    for name in table.column_names:
        arr = table.column(name).to_pandas()
        vals = np.asarray(arr)
        if vals.dtype.kind in "iuf":
            cols.append(Column(name, vals.astype(np.float64), ColType.NUM))
        elif vals.dtype.kind == "b":
            cols.append(Column(name, vals.astype(np.float64), ColType.NUM))
        elif vals.dtype.kind == "M":
            ms = vals.astype("datetime64[ms]").astype(np.int64).astype(np.float64)
            cols.append(Column(name, ms, ColType.TIME))
        else:
            from h2o3_tpu.frame.parse import column_from_strings

            cols.append(
                column_from_strings(
                    name, [None if v is None else str(v) for v in arr]
                )
            )
    return Frame(cols)


def parse_parquet(data: bytes) -> Frame:
    """Parquet via pyarrow when available (h2o-parquet-parser analogue)."""
    try:
        import pyarrow.parquet as pq
    except ImportError:
        raise ValueError(
            "parquet ingest needs pyarrow, which is not available in this "
            "build (reference module: h2o-parquet-parser)"
        )
    return _frame_from_arrow(pq.read_table(io.BytesIO(data)))


def parse_orc(data: bytes) -> Frame:
    """ORC via pyarrow (h2o-orc-parser analogue)."""
    try:
        import pyarrow.orc as po
    except ImportError:
        raise ValueError(
            "orc ingest needs pyarrow.orc, which is not available in this "
            "build (reference module: h2o-orc-parser)"
        )
    return _frame_from_arrow(po.ORCFile(io.BytesIO(data)).read())


# ---------------------------------------------------------------------------
# Avro object-container files (h2o-avro-parser analogue, stdlib-only)


class _AvroReader:
    """Minimal Avro binary decoder: primitives, unions with null, enums —
    the flat-record shape tabular Avro files use."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) < n:
            raise ValueError("avro: truncated file")
        self.pos += n
        return b

    def long(self) -> int:
        # zigzag varint
        shift, acc = 0, 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def value(self, schema):
        import struct

        if isinstance(schema, str):
            t = schema
        elif isinstance(schema, dict):
            t = schema["type"]
        elif isinstance(schema, list):  # union: branch index then value
            branch = schema[self.long()]
            return self.value(branch)
        else:
            raise ValueError(f"avro: bad schema node {schema!r}")
        if t == "null":
            return None
        if t == "boolean":
            return bool(self.read(1)[0])
        if t in ("int", "long"):
            return self.long()
        if t == "float":
            return struct.unpack("<f", self.read(4))[0]
        if t == "double":
            return struct.unpack("<d", self.read(8))[0]
        if t == "bytes":
            return self.bytes_()
        if t == "string":
            return self.string()
        if t == "enum":
            return schema["symbols"][self.long()]
        raise ValueError(f"avro: unsupported field type {t!r} "
                         f"(flat tabular records only)")


def parse_avro(data: bytes) -> Frame:
    """Avro object-container file -> Frame.

    Reference: ``h2o-parsers/h2o-avro-parser`` (AvroParser.java): one
    column per record field; null/deflate codecs; unions with null are
    nullable columns."""
    import zlib

    if data[:4] != b"Obj\x01":
        raise ValueError("not an Avro object container file")
    r = _AvroReader(data)
    r.pos = 4
    meta: Dict[str, bytes] = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:  # block with explicit byte size
            r.long()
            n = -n
        for _ in range(n):
            k = r.string()
            meta[k] = r.bytes_()
    sync = r.read(16)
    import json as _json

    schema = _json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise ValueError("avro: top-level schema must be a record")
    fields = schema["fields"]
    names = [f["name"] for f in fields]
    rows: List[list] = []
    while r.pos < len(r.data):
        count = r.long()
        size = r.long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"avro: unsupported codec {codec!r}")
        br = _AvroReader(block)
        for _ in range(count):
            rows.append([br.value(f["type"]) for f in fields])
        if r.read(16) != sync:
            raise ValueError("avro: sync marker mismatch")
    from h2o3_tpu.frame.parse import column_from_strings

    cols: List[Column] = []
    for j, name in enumerate(names):
        vals = [row[j] for row in rows]
        non_null = [v for v in vals if v is not None]
        if all(isinstance(v, (int, float)) for v in non_null):  # incl. bool
            cols.append(Column(name, np.array(
                [np.nan if v is None else float(v) for v in vals]),
                ColType.NUM))
        else:
            cols.append(column_from_strings(
                name,
                [None if v is None else
                 (v.decode("utf-8", "replace") if isinstance(v, bytes)
                  else str(v)) for v in vals]))
    return Frame(cols)


# ---------------------------------------------------------------------------
# XLSX (water/parser/XlsParser analogue; stdlib zip + xml)


def _xlsx_col_index(ref: str) -> int:
    """'BC12' -> zero-based column 54."""
    acc = 0
    for ch in ref:
        if ch.isalpha():
            acc = acc * 26 + (ord(ch.upper()) - ord("A") + 1)
        else:
            break
    return acc - 1


def parse_xlsx(data: bytes) -> Frame:
    """First worksheet of an .xlsx workbook; row 1 is the header."""
    import xml.etree.ElementTree as ET

    ns = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.findall(f"{ns}si"):
                shared.append("".join(t.text or "" for t in si.iter(f"{ns}t")))
        sheet_names = sorted(
            n for n in z.namelist()
            if re.fullmatch(r"xl/worksheets/sheet\d+\.xml", n))
        if not sheet_names:
            raise ValueError("xlsx: no worksheets")
        root = ET.fromstring(z.read(sheet_names[0]))
    grid: List[Dict[int, Optional[str]]] = []
    for row in root.iter(f"{ns}row"):
        cells: Dict[int, Optional[str]] = {}
        for c in row.findall(f"{ns}c"):
            ref = c.get("r", "")
            j = _xlsx_col_index(ref) if ref else len(cells)
            t = c.get("t", "n")
            v = c.find(f"{ns}v")
            if t == "inlineStr":
                is_el = c.find(f"{ns}is")
                cells[j] = "".join(
                    t_.text or "" for t_ in is_el.iter(f"{ns}t")
                ) if is_el is not None else None
            elif v is None or v.text is None:
                cells[j] = None
            elif t == "s":
                cells[j] = shared[int(v.text)]
            elif t == "b":
                cells[j] = "1" if v.text == "1" else "0"
            else:
                cells[j] = v.text
        grid.append(cells)
    if not grid:
        raise ValueError("xlsx: empty sheet")
    width = max(max(r.keys(), default=-1) for r in grid) + 1
    header = [grid[0].get(j) or f"C{j + 1}" for j in range(width)]
    from h2o3_tpu.frame.parse import column_from_strings

    cols = []
    for j in range(width):
        vals = [r.get(j) for r in grid[1:]]
        cols.append(column_from_strings(header[j], vals))
    return Frame(cols)


def parse_xls_legacy(data: bytes) -> Frame:
    """Legacy BIFF .xls via the OLE2+BIFF walker (frame/xls.py;
    water/parser/XlsParser.java)."""
    from h2o3_tpu.frame.xls import parse_xls

    return parse_xls(data)


# ---------------------------------------------------------------------------
# top-level import + parse (ImportFilesHandler + ParseDataset)

_SVM_COL_RE = re.compile(r"^C\d+$")


def rbind_all(frames: List[Frame]) -> Frame:
    """Row-bind parsed parts into one frame. Sparse-format parts (svmlight)
    routinely differ in max feature index; a narrower frame whose names are
    a prefix of the widest and whose missing columns are all C<k> is padded
    with zeros (sparse semantics) before binding."""
    if not frames:
        raise ValueError("nothing to bind")
    widest = max(frames, key=lambda f: f.ncols)
    out: Optional[Frame] = None
    for fr in frames:
        if fr.ncols < widest.ncols and fr.names == widest.names[: fr.ncols] and all(
            _SVM_COL_RE.match(n) for n in widest.names[fr.ncols :]
        ):
            pad = [
                Column(n, np.zeros(fr.nrows, np.float64), ColType.NUM)
                for n in widest.names[fr.ncols :]
            ]
            fr = Frame(list(fr.columns) + pad)
        out = fr if out is None else out.rbind(fr)
    return out


def parse_bytes(
    name: str,
    data: bytes,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """One raw blob -> Frame: decompression, per-part format sniff, parse,
    bind. The single format dispatch shared by the library path
    (parse_source/import_parse) and the REST /3/Parse handler.

    CSV parts parse STREAMED (parse_csv_stream): archive decompression
    stays incremental and overlaps the parallel parse's chunk
    tokenization.  Other formats need their whole part materialized."""
    from h2o3_tpu.frame.parse import parse_csv_stream

    frames: List[Frame] = []
    for part_name, stream in iter_part_streams(name, data):
        head_parts: List[bytes] = []
        got = 0
        while got < 8192:  # sniff prefix (loop: streams may read short)
            b = stream.read(8192 - got)
            if not b:
                break
            head_parts.append(b)
            got += len(b)
        head = b"".join(head_parts)
        rdr = _PrefixedReader(head, stream)
        f = fmt or sniff_format(part_name, head)
        if f == "csv":
            fr = parse_csv_stream(rdr, **csv_kw)
            _INGEST_BYTES.inc(rdr.count, format=f)
            _INGEST_ROWS.inc(fr.nrows, format=f)
            frames.append(fr)
            continue
        part = rdr.read()
        if f == "svmlight":
            fr = parse_svmlight(part.decode("utf-8", errors="replace"))
        elif f == "arff":
            fr = parse_arff(part.decode("utf-8", errors="replace"))
        elif f == "parquet":
            fr = parse_parquet(part)
        elif f == "orc":
            fr = parse_orc(part)
        elif f == "avro":
            fr = parse_avro(part)
        elif f == "xlsx":
            fr = parse_xlsx(part)
        elif f == "xls":
            fr = parse_xls_legacy(part)
        else:
            raise ValueError(f"unknown format {f!r}")
        _INGEST_BYTES.inc(len(part), format=f)
        _INGEST_ROWS.inc(fr.nrows, format=f)
        frames.append(fr)
    return rbind_all(frames)


def parse_source(
    uri: str,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """One source -> Frame: persist dispatch, decompression, format sniff."""
    backend, path = resolve_persist(uri)
    return parse_bytes(
        os.path.basename(path) or path, backend.read_bytes(path), fmt=fmt, **csv_kw
    )


def import_parse(
    uri: str,
    fmt: Optional[str] = None,
    **csv_kw,
) -> Frame:
    """Path/glob/directory -> ONE frame (multi-file sources are parsed
    independently and row-bound, with categorical domains unified — the
    reference's multi-file ParseDataset)."""
    sources = list_sources(uri)
    return rbind_all(
        [parse_source(src, fmt=fmt, **csv_kw) for src in sources]
    )


# ---------------------------------------------------------------------------
# SQL import (water/jdbc/SQLManager.java)


def _db_connect(connection_url: str):
    """connection url -> a fresh DB-API connection.

    sqlite ships with the stdlib; postgresql/mysql connect through their
    conventional python drivers when importable; anything else raises an
    actionable error naming the reference module."""
    url = connection_url
    low = url.lower()
    for prefix in ("jdbc:sqlite:", "sqlite://", "sqlite:"):
        if low.startswith(prefix):
            import sqlite3

            path = url[len(prefix):]
            if not os.path.exists(path):
                raise FileNotFoundError(path)
            # each partition thread opens its own connection; sqlite
            # handles concurrent readers
            return sqlite3.connect(path)
    if low.startswith(("postgresql://", "postgres://", "jdbc:postgresql:")):
        try:
            import psycopg2  # type: ignore

            return psycopg2.connect(url.replace("jdbc:postgresql:",
                                                "postgresql:"))
        except ImportError:
            raise ValueError(
                "postgresql import needs psycopg2, which is not available "
                "in this build (reference: water/jdbc/SQLManager.java)")
    if low.startswith(("mysql://", "jdbc:mysql:")):
        try:
            import pymysql  # type: ignore
            from urllib.parse import urlparse

            p = urlparse(url)
            return pymysql.connect(
                host=p.hostname, port=p.port or 3306, user=p.username,
                password=p.password or "", database=p.path.lstrip("/"))
        except ImportError:
            raise ValueError(
                "mysql import needs pymysql, which is not available in "
                "this build (reference: water/jdbc/SQLManager.java)")
    if low.startswith(("hive2://", "jdbc:hive2:")):
        try:
            from pyhive import hive  # type: ignore
        except ImportError:
            raise ValueError(
                "hive import needs the 'pyhive' package, which is not "
                "available in this build (reference: h2o-ext-hive / "
                "water/hive/HiveTableImporter.java); export the table "
                "to parquet/orc/csv and import that instead")
        from urllib.parse import urlparse

        p = urlparse(url.replace("jdbc:hive2:", "hive2:"))
        return hive.connect(
            host=p.hostname or "localhost", port=p.port or 10000,
            username=p.username, database=p.path.lstrip("/") or "default")
    raise ValueError(
        f"unsupported SQL connection url {connection_url!r}; supported: "
        f"sqlite:/path (stdlib), postgresql:// (psycopg2), mysql:// "
        f"(pymysql), hive2:// (pyhive) — the reference loads arbitrary "
        f"JDBC drivers (water/jdbc/SQLManager.java)")


def _rows_to_frame(names: Sequence[str], rows: List[tuple]) -> Frame:
    from h2o3_tpu.frame.parse import column_from_strings

    out: List[Column] = []
    for j, name in enumerate(names):
        vals = [r[j] for r in rows]
        non_null = [v for v in vals if v is not None]
        if all(isinstance(v, (int, float)) for v in non_null):
            data = np.array(
                [np.nan if v is None else float(v) for v in vals], np.float64
            )
            out.append(Column(name, data, ColType.NUM))
        else:
            out.append(
                column_from_strings(
                    name, [None if v is None else str(v) for v in vals]
                )
            )
    return Frame(out)


_SQL_IDENT = r"[A-Za-z_][A-Za-z0-9_]*"


def import_sql_table(
    connection_url: str,
    table: Optional[str] = None,
    select_query: Optional[str] = None,
    columns: Optional[Sequence[str]] = None,
    partition_column: Optional[str] = None,
    num_partitions: int = 1,
) -> Frame:
    """Import a SQL table/query result as a Frame.

    Reference: ``water/jdbc/SQLManager.java`` — range-partitioned
    parallel selects over a JDBC connection. ``partition_column`` (a
    numeric column) + ``num_partitions`` reproduce that: the range
    [min, max] splits into equal-width slices, each fetched on its own
    connection in its own thread, concatenated in range order."""
    cols_sql = "*"
    if select_query is None:
        if not table:
            raise ValueError("either table or select_query is required")
        if not re.fullmatch(_SQL_IDENT, table):
            raise ValueError(f"invalid table name {table!r}")
        if columns:
            for c in columns:
                if not re.fullmatch(_SQL_IDENT, c):
                    raise ValueError(f"invalid column name {c!r}")
            cols_sql = ", ".join(columns)
        select_query = f"SELECT {cols_sql} FROM {table}"
    else:
        table = None  # an explicit query wins; partitions wrap it

    def fetch(query: str):
        conn = _db_connect(connection_url)
        try:
            cur = conn.cursor()
            try:
                cur.execute(query)
            except Exception as e:  # DB-API Error hierarchies vary
                raise ValueError(
                    f"SQL import query failed: {type(e).__name__}: {e}")
            return [d[0] for d in cur.description], cur.fetchall()
        finally:
            conn.close()

    if partition_column and num_partitions > 1:
        if not re.fullmatch(_SQL_IDENT, partition_column):
            raise ValueError(f"invalid column name {partition_column!r}")
        base = f"({select_query}) AS t" if table is None else table
        _, bounds = fetch(
            f"SELECT MIN({partition_column}), MAX({partition_column}) "
            f"FROM {base}")
        lo, hi = bounds[0]
        if lo is None:
            return _rows_to_frame(*fetch(select_query))
        if isinstance(lo, int) and isinstance(hi, int):
            # integer keys stay integer: float() truncates above 2^53
            # (snowflake-style 64-bit ids) and would drop the max rows
            edges = [lo + (hi - lo) * i // num_partitions
                     for i in range(num_partitions)] + [hi]
        else:
            lo, hi = float(lo), float(hi)
            edges = [lo + (hi - lo) * i / num_partitions
                     for i in range(num_partitions + 1)]
        from concurrent.futures import ThreadPoolExecutor

        def part(i: int):
            cond = (
                f"{partition_column} >= {edges[i]!r} AND "
                + (f"{partition_column} <= {edges[i + 1]!r}" if
                   i == num_partitions - 1 else
                   f"{partition_column} < {edges[i + 1]!r}")
            )
            # NULL partition keys ride with the first slice, so no row
            # is dropped (SQLManager fetches them separately)
            if i == 0:
                cond = f"({cond}) OR {partition_column} IS NULL"
            if table is not None:
                # filter on the TABLE so a column projection that drops
                # the partition column still partitions (SQLManager
                # applies the range on the base table)
                return fetch(f"SELECT {cols_sql} FROM {table} "
                             f"WHERE {cond}")
            return fetch(f"SELECT * FROM ({select_query}) AS q "
                         f"WHERE {cond}")

        with ThreadPoolExecutor(max_workers=min(num_partitions, 8)) as pool:
            results = list(pool.map(part, range(num_partitions)))
        names = results[0][0]
        frames = [_rows_to_frame(names, rows) for _, rows in results
                  if rows]
        if not frames:
            return _rows_to_frame(names, [])
        return rbind_all(frames)

    return _rows_to_frame(*fetch(select_query))


# cloud persist schemes register at import (the PersistManager module
# registration h2o-persist-{s3,gcs,hdfs} performs on the classpath).
# Plain module import — cloud.py self-registers at its bottom, and the
# module-object import (unlike a from-import of a name) is safe in both
# import orders of this circular pair.
from h2o3_tpu.frame import cloud as _cloud  # noqa: E402, F401


def import_hive_table(
    database: str = "default",
    table: str = "",
    partitions: Optional[List[List[str]]] = None,
    connection_url: Optional[str] = None,
) -> Frame:
    """Import a Hive table (ImportHiveTableHandler.HiveTableImporter):
    reads over a HiveServer2 DB-API connection (pyhive when importable)
    instead of the reference's metastore-direct file loads; `partitions`
    (list of [col=value, ...] specs) become a WHERE disjunction — the
    importer's partition filter."""
    if not table:
        raise ValueError("table is required")
    if not re.fullmatch(_SQL_IDENT, table):
        raise ValueError(f"invalid table name {table!r}")
    if database and not re.fullmatch(_SQL_IDENT, database):
        raise ValueError(f"invalid database name {database!r}")
    url = connection_url or f"hive2://localhost:10000/{database}"
    query = f"SELECT * FROM {database}.{table}" if database else \
        f"SELECT * FROM {table}"
    if partitions:
        clauses = []
        for spec in partitions:
            parts = []
            for kv in spec:
                k, _, v = str(kv).partition("=")
                if not re.fullmatch(_SQL_IDENT, k):
                    raise ValueError(f"invalid partition column {k!r}")
                parts.append(f"{k} = '" + v.replace("'", "''") + "'")
            clauses.append("(" + " AND ".join(parts) + ")")
        query += " WHERE " + " OR ".join(clauses)
    return import_sql_table(url, select_query=query)
