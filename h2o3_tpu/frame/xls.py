"""Legacy ``.xls`` (BIFF) reader: OLE2 compound document + BIFF2-8
records, numeric and string cells.

Reference: ``water/parser/XlsParser.java`` (a from-scratch BIFF record
walker over the compound-document "Workbook" stream, same scope: cell
values only — no formulas being evaluated, no formatting). The layout
facts come from the public MS-CFB / MS-XLS specifications.

Structure handled here:

* **OLE2/CFB container**: 512-byte header, FAT sector chains, directory
  entries, and the root's mini-stream with its own miniFAT for streams
  under the 4096-byte cutoff (small workbooks written by some tools).
* **BIFF stream** (directory entry ``Workbook`` or ``Book``): a linear
  record walk collecting the BIFF8 shared-string table (including
  CONTINUE splits, where a string resumes with a fresh flags byte) and
  the cell records of the FIRST worksheet substream: NUMBER, RK, MULRK,
  LABELSST, LABEL (BIFF2-5 inline), INTEGER/old NUMBER/old LABEL
  (BIFF2), and cached numeric FORMULA results.

Row 1 is the header when every populated cell in it is a string
(matching the CSV sniffing convention); otherwise columns are named
C1..Cn.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

_ENDOFCHAIN = 0xFFFFFFFE
_FREESECT = 0xFFFFFFFF
_FATSECT = 0xFFFFFFFD
_DIFSECT = 0xFFFFFFFC


def _u16(b: bytes, o: int) -> int:
    return struct.unpack_from("<H", b, o)[0]


def _u32(b: bytes, o: int) -> int:
    return struct.unpack_from("<I", b, o)[0]


# ---------------------------------------------------------------------------
# OLE2 / CFB container


def _cfb_stream(data: bytes, want_names: Tuple[str, ...]) -> bytes:
    """Extract the named stream from an OLE2 compound file."""
    if data[:8] != b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1":
        raise ValueError("not an OLE2 compound document")
    sec_shift = _u16(data, 30)
    sec_size = 1 << sec_shift
    mini_shift = _u16(data, 32)
    mini_size = 1 << mini_shift
    n_fat = _u32(data, 44)
    dir_start = _u32(data, 48)
    mini_cutoff = _u32(data, 56)
    minifat_start = _u32(data, 60)
    difat_start = _u32(data, 68)
    n_difat = _u32(data, 72)

    def sector(n: int) -> bytes:
        off = 512 + n * sec_size
        return data[off:off + sec_size]

    # FAT sector list: 109 header DIFAT entries + DIFAT sector chain
    fat_sectors: List[int] = []
    for i in range(109):
        s = _u32(data, 76 + 4 * i)
        if s not in (_FREESECT, _ENDOFCHAIN):
            fat_sectors.append(s)
    ds = difat_start
    for _ in range(n_difat):
        if ds in (_ENDOFCHAIN, _FREESECT):
            break
        blk = sector(ds)
        per = sec_size // 4 - 1
        for i in range(per):
            s = _u32(blk, 4 * i)
            if s not in (_FREESECT, _ENDOFCHAIN):
                fat_sectors.append(s)
        ds = _u32(blk, sec_size - 4)
    fat_sectors = fat_sectors[:max(n_fat, len(fat_sectors))]

    fat: List[int] = []
    for s in fat_sectors:
        blk = sector(s)
        fat.extend(struct.unpack(f"<{sec_size // 4}I", blk))

    def chain(start: int, cap: int = 1 << 22) -> bytes:
        out, s, seen = [], start, 0
        while s not in (_ENDOFCHAIN, _FREESECT) and seen < cap:
            out.append(sector(s))
            if s >= len(fat):
                break
            s = fat[s]
            seen += 1
        return b"".join(out)

    # directory entries (128 bytes each)
    dirdata = chain(dir_start)
    root_start = root_size = None
    target: Optional[Tuple[int, int]] = None
    for off in range(0, len(dirdata) - 127, 128):
        name_len = _u16(dirdata, off + 64)
        if name_len < 2:
            continue
        name = dirdata[off:off + name_len - 2].decode("utf-16-le",
                                                      "replace")
        etype = dirdata[off + 66]
        start = _u32(dirdata, off + 116)
        size = _u32(dirdata, off + 120)
        if etype == 5:  # root: its stream is the mini stream
            root_start, root_size = start, size
        elif etype == 2 and name in want_names and target is None:
            target = (start, size)
    if target is None:
        raise ValueError(f"no {'/'.join(want_names)} stream in workbook")
    start, size = target

    if size >= mini_cutoff:
        return chain(start)[:size]

    # small stream: bytes live in the root's mini stream, chained by the
    # miniFAT in mini-sector units
    if root_start is None:
        raise ValueError("xls: mini stream without a root entry")
    mini_stream = chain(root_start)[:root_size]
    minifat_data = chain(minifat_start) if minifat_start not in (
        _ENDOFCHAIN, _FREESECT) else b""
    minifat = list(struct.unpack(f"<{len(minifat_data) // 4}I",
                                 minifat_data[:len(minifat_data) & ~3]))
    out, s, seen = [], start, 0
    while s not in (_ENDOFCHAIN, _FREESECT) and seen < (1 << 20):
        out.append(mini_stream[s * mini_size:(s + 1) * mini_size])
        if s >= len(minifat):
            break
        s = minifat[s]
        seen += 1
    return b"".join(out)[:size]


# ---------------------------------------------------------------------------
# BIFF records


def _rk_value(rk: int) -> float:
    """RK-encoded number: bit0 = /100, bit1 = int30 vs high-30-of-double."""
    cents = rk & 1
    if rk & 2:
        v = float(rk >> 2 if rk >> 2 < (1 << 29) else (rk >> 2) - (1 << 30))
    else:
        v = struct.unpack("<d", struct.pack("<Q",
                                            (rk & 0xFFFFFFFC) << 32))[0]
    return v / 100.0 if cents else v


class _SSTReader:
    """BIFF8 shared strings across SST + CONTINUE records: a string that
    spans a record boundary resumes with a fresh option-flags byte."""

    def __init__(self, parts: List[bytes]) -> None:
        self.parts = parts
        self.pi = 0
        self.off = 0

    def _remaining(self) -> int:
        return len(self.parts[self.pi]) - self.off

    def _advance(self) -> None:
        while self.pi < len(self.parts) and self._remaining() == 0:
            self.pi += 1
            self.off = 0

    def take(self, n: int) -> bytes:
        out = b""
        while n > 0:
            self._advance()
            if self.pi >= len(self.parts):
                raise ValueError("xls: truncated SST")
            chunk = self.parts[self.pi][self.off:self.off + n]
            self.off += len(chunk)
            n -= len(chunk)
            out += chunk
        return out

    def read_string(self) -> str:
        cch = _u16(self.take(2), 0)
        flags = self.take(1)[0]
        wide = flags & 0x01
        ext = flags & 0x04
        rich = flags & 0x08
        c_run = _u16(self.take(2), 0) if rich else 0
        cb_ext = _u32(self.take(4), 0) if ext else 0
        chars: List[str] = []
        left = cch
        while left > 0:
            self._advance()
            avail = self._remaining()
            if avail == 0:
                raise ValueError("xls: truncated SST string")
            n = min(left, avail // 2 if wide else avail)
            if wide:
                if n == 0:  # a lone byte at a boundary cannot happen mid-
                    raise ValueError("xls: split utf-16 unit in SST")
                chars.append(self.take(2 * n).decode("utf-16-le",
                                                     "replace"))
            else:
                chars.append(self.take(n).decode("latin-1"))
            left -= n
            if left > 0:  # string continues in the next CONTINUE record
                flags = self.take(1)[0]
                wide = flags & 0x01
        self.take(4 * c_run)
        self.take(cb_ext)
        return "".join(chars)


def _short_string(payload: bytes, off: int, biff8: bool) -> str:
    """Inline LABEL string: BIFF8 unicode (cch16+flags) or BIFF2-5 bytes."""
    if biff8:
        cch = _u16(payload, off)
        flags = payload[off + 2]
        if flags & 0x01:
            return payload[off + 3:off + 3 + 2 * cch].decode(
                "utf-16-le", "replace")
        return payload[off + 3:off + 3 + cch].decode("latin-1")
    cch = _u16(payload, off)
    return payload[off + 2:off + 2 + cch].decode("latin-1")


def parse_xls(data: bytes):
    """.xls bytes -> Frame (numeric + string cells of the first sheet)."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.parse import column_from_strings

    stream = _cfb_stream(data, ("Workbook", "Book"))

    cells: Dict[Tuple[int, int], object] = {}
    sst: List[str] = []
    sst_parts: List[bytes] = []
    sst_total = 0
    in_sst = False
    biff8 = True
    sheets_seen = 0
    pos = 0
    n = len(stream)
    while pos + 4 <= n:
        rid = _u16(stream, pos)
        rlen = _u16(stream, pos + 2)
        payload = stream[pos + 4:pos + 4 + rlen]
        pos += 4 + rlen
        if rid == 0x0809 or rid in (0x0009, 0x0209, 0x0409):  # BOF
            if rid == 0x0809:
                vers = _u16(payload, 0)
                biff8 = vers >= 0x0600
            else:
                biff8 = False
            stype = _u16(payload, 2) if len(payload) >= 4 else 0x0010
            if stype == 0x0010:  # worksheet substream
                sheets_seen += 1
                if sheets_seen > 1:
                    break  # first sheet only, like the xlsx parser
            in_sst = False
            continue
        if rid == 0x00FC:  # SST (BIFF8)
            sst_total = _u32(payload, 4)
            sst_parts = [payload[8:]]
            in_sst = True
            continue
        if rid == 0x003C and in_sst:  # CONTINUE of the SST
            sst_parts.append(payload)
            continue
        in_sst = False
        if sheets_seen == 0 and rid not in (0x00FC, 0x003C):
            continue  # globals substream: only the SST matters
        if rid == 0x0203 and len(payload) >= 14:  # NUMBER (BIFF5/8)
            r, c = _u16(payload, 0), _u16(payload, 2)
            cells[(r, c)] = struct.unpack_from("<d", payload, 6)[0]
        elif rid == 0x0003 and len(payload) >= 15:  # NUMBER (BIFF2)
            r, c = _u16(payload, 0), _u16(payload, 2)
            cells[(r, c)] = struct.unpack_from("<d", payload, 7)[0]
        elif rid == 0x0002 and len(payload) >= 9:  # INTEGER (BIFF2)
            r, c = _u16(payload, 0), _u16(payload, 2)
            cells[(r, c)] = float(_u16(payload, 7))
        elif rid == 0x027E and len(payload) >= 10:  # RK
            r, c = _u16(payload, 0), _u16(payload, 2)
            cells[(r, c)] = _rk_value(_u32(payload, 6))
        elif rid == 0x00BD and len(payload) >= 12:  # MULRK
            r, c0 = _u16(payload, 0), _u16(payload, 2)
            n_rk = (len(payload) - 6) // 6
            for i in range(n_rk):
                cells[(r, c0 + i)] = _rk_value(_u32(payload, 6 + 6 * i + 2))
        elif rid == 0x00FD and len(payload) >= 10:  # LABELSST
            r, c = _u16(payload, 0), _u16(payload, 2)
            if not sst and sst_parts:
                reader = _SSTReader(sst_parts)
                for _ in range(sst_total):
                    sst.append(reader.read_string())
            idx = _u32(payload, 6)
            cells[(r, c)] = sst[idx] if idx < len(sst) else ""
        elif rid == 0x0204 and len(payload) >= 8:  # LABEL (BIFF5/8 inline)
            r, c = _u16(payload, 0), _u16(payload, 2)
            cells[(r, c)] = _short_string(payload, 6, biff8)
        elif rid == 0x0004 and len(payload) >= 8:  # LABEL (BIFF2)
            r, c = _u16(payload, 0), _u16(payload, 2)
            cch = payload[7]
            cells[(r, c)] = payload[8:8 + cch].decode("latin-1")
        elif rid == 0x0006 and len(payload) >= 14:  # FORMULA: cached num
            r, c = _u16(payload, 0), _u16(payload, 2)
            res = payload[6:14]
            if res[6:8] != b"\xff\xff":  # else string/bool/err result
                cells[(r, c)] = struct.unpack("<d", res)[0]
        elif rid == 0x000A:  # EOF
            if sheets_seen >= 1:
                break

    if not cells:
        raise ValueError("xls: no numeric or string cells found")

    n_rows = max(r for r, _ in cells) + 1
    n_cols = max(c for _, c in cells) + 1
    first = [cells.get((0, j)) for j in range(n_cols)]
    has_header = all(isinstance(v, str) for v in first if v is not None) \
        and any(v is not None for v in first)
    header = ([str(v) if v is not None else f"C{j + 1}"
               for j, v in enumerate(first)] if has_header
              else [f"C{j + 1}" for j in range(n_cols)])
    body_rows = range(1, n_rows) if has_header else range(n_rows)
    cols = []
    for j in range(n_cols):
        vals = [None if (v := cells.get((i, j))) is None else str(v)
                for i in body_rows]
        cols.append(column_from_strings(header[j], vals))
    return Frame(cols)
