"""Data ingest: CSV/ARFF-style parsing with type guessing.

Reference: two-phase distributed parse (``water/parser/ParseDataset.java:127,623,899``)
with a ``ParseSetup.guessSetup`` pre-pass that infers separator / header /
per-column types from a sample, then a cluster-wide MRTask that tokenizes file
chunks into NewChunks and unifies categorical dictionaries
(``water/parser/Categorical.java``).

TPU-native redesign: parsing is host-side work (there is no reason to tokenize
bytes on an MXU); the output is dense columnar numpy, which then shards onto
the mesh. We keep the reference's *semantics*: guessSetup (separator sniffing,
header detection, per-column NUM/CAT/TIME/STR/UUID guessing with the same
precedence), NA-string handling, RFC-4180 quoting (embedded separators,
doubled quotes, quoted newlines), categorical dictionary construction, and a
``parse_setup``/``parse_csv`` two-step API mirroring POST /3/ParseSetup +
POST /3/Parse.
"""

from __future__ import annotations

import io
import math
import os
import re
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame, NA_CAT
from h2o3_tpu.util import telemetry

#: parse accounting — every CSV parse (library call, REST /3/Parse, multi-part
#: archives via ingest.parse_bytes) lands here. ``parser`` labels:
#:   csv             — serial pure-python tokenizer (small inputs)
#:   csv_native      — serial whole-buffer all-numeric native fast path
#:   native-parallel — chunk-parallel pipeline, every chunk tokenized by the
#:                     native (csv.cpp) chunk primitives
#:   python-parallel — chunk-parallel pipeline, every chunk on the python
#:                     tokenizer (quotes/unicode/no native lib)
#:   mixed-parallel  — chunk-parallel pipeline with both kinds of chunks
_PARSE_ROWS = telemetry.counter(
    "parse_rows_total", "rows parsed into frames", labels=("parser",)
)
_PARSE_SECONDS = telemetry.histogram(
    "parse_seconds", "wall seconds per CSV parse", labels=("parser",)
)
_PARSE_CHUNKS = telemetry.counter(
    "parse_chunks_total",
    "byte chunks tokenized by the two-phase parallel CSV parse",
    labels=("parser",),
)
_PARSE_WORKERS = telemetry.gauge(
    "parse_workers",
    "thread workers used by the most recent chunk-parallel CSV parse",
)

#: chunk-parallel pipeline knobs. Workers default to the host's cores (the
#: reference's chunk-parallel MultiFileParseTask shape); chunk size trades
#: scheduling granularity against per-chunk overhead.  On a multi-node
#: cloud each raw chunk additionally ships to its DKV ring home, so
#: ``H2O3_TPU_PARSE_CHUNK_BYTES`` must stay under one transport frame
#: (cluster.transport.MAX_FRAME_BYTES minus envelope slack) — the
#: chunk-home guard (cluster.frames.guard_chunk_payload) refuses typed,
#: naming this knob, before anything hits the wire.
DEFAULT_CHUNK_BYTES = 8 << 20
_SAMPLE_BYTES = 1 << 20


def _env_workers() -> int:
    try:
        w = int(os.environ.get("H2O3_TPU_PARSE_WORKERS", "") or 0)
    except ValueError:
        w = 0
    return max(1, w or (os.cpu_count() or 1))


def _chunk_bytes() -> int:
    try:
        c = int(os.environ.get("H2O3_TPU_PARSE_CHUNK_BYTES", "") or 0)
    except ValueError:
        c = 0
    c = c or DEFAULT_CHUNK_BYTES
    # floor keeps tests free to force many chunks; ceiling keeps the native
    # indexer's int32 cell offsets valid
    return min(max(c, 64), 1 << 28)

#: Default NA tokens (reference: water/parser/ParseSetup + CsvParser NA handling)
DEFAULT_NA_STRINGS = ("", "NA", "N/A", "na", "n/a", "NaN", "nan", "null", "NULL", "?")

_TIME_PATTERNS = (
    # yyyy-MM-dd[ HH:mm:ss[.SSS]] — the reference's ParseTime formats subset
    re.compile(r"^\d{4}-\d{2}-\d{2}([ T]\d{2}:\d{2}:\d{2}(\.\d+)?)?$"),
    re.compile(r"^\d{2}/\d{2}/\d{4}$"),
)
_UUID_RE = re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$"
)
#: record terminators str.splitlines honors beyond \\n / \\r\\n: lone \\r plus
#: \\v \\f \\x1c-\\x1e NEL(U+0085) LS(U+2028) PS(U+2029) — any of these makes
#: a byte-level newline scan split records differently from the python path
_SPLITLINES_DIVERGENT_RE = re.compile(
    "[\v\f\x1c\x1d\x1e\x85\u2028\u2029]|\r(?!\n)"
)
def _has_divergent(buf: bytes, start: int, eof: bool) -> bool:
    """Any record terminator in buf[start:] the \\n scan would miss (lone
    \\r, \\v, \\f, \\x1c-\\x1e, NEL, LS, PS)?  A trailing \\r or an
    incomplete E2 80 prefix on the final bytes is NOT flagged unless eof:
    the caller holds the last byte back and rescans it with overlap once
    the next block arrives.  Vectorized \u2014 this runs on the pipeline's
    main thread over every block, so it must outrun the tokenizers."""
    if len(buf) <= start:
        return False
    # memchr pre-filters: the common LF/ASCII block pays ~nothing, and
    # only \r / \xe2 carriers reach the vectorized context checks.  NEL
    # must match the full utf-8 sequence C2 85 — a bare 0x85 is the
    # continuation byte of ordinary characters (Cyrillic, CJK) and
    # decodes alone to U+FFFD, which splitlines does not split on.
    for hard in (b"\x0b", b"\x0c", b"\x1c", b"\x1d", b"\x1e", b"\xc2\x85"):
        if buf.find(hard, start) >= 0:
            return True
    has_cr = buf.find(b"\r", start) >= 0
    has_e2 = buf.find(b"\xe2", start) >= 0
    if not (has_cr or has_e2):
        return False
    arr = np.frombuffer(buf, dtype=np.uint8)[start:]
    n = int(arr.size)
    cr = np.flatnonzero(arr == 13) if has_cr else np.empty(0, np.int64)
    if cr.size:
        if cr[-1] == n - 1:
            if eof:
                return True  # trailing lone \r (conservative)
            cr = cr[:-1]  # may yet be the CRLF half: held back
        if cr.size and bool((arr[cr + 1] != 10).any()):
            return True
    if not has_e2:
        return False
    e2 = np.flatnonzero(arr == 0xE2)
    e2 = e2[e2 <= n - 3]  # incomplete tail prefixes: held back / harmless
    if e2.size:
        nxt, nxt2 = arr[e2 + 1], arr[e2 + 2]
        if bool(((nxt == 0x80) & ((nxt2 == 0xA8) | (nxt2 == 0xA9))).any()):
            return True
    return False
_PATHLIKE_SUFFIXES = (".csv", ".txt", ".tsv", ".data", ".dat", ".gz", ".zip", ".svm", ".arff")


@dataclass
class ParseSetup:
    """Inferred parse plan (reference: water/parser/ParseSetup.java)."""

    separator: str = ","
    header: bool = True
    column_names: List[str] = field(default_factory=list)
    column_types: List[ColType] = field(default_factory=list)
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS
    skip_blank_lines: bool = True
    quote_char: str = '"'


def parse_setup(
    src: Union[str, os.PathLike],
    separator: Optional[str] = None,
    header: Optional[bool] = None,
    column_types: Optional[Dict[str, str]] = None,
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS,
    sample_rows: int = 1000,
) -> ParseSetup:
    """Guess separator/header/types from a sample (ParseSetup.guessSetup)."""
    records = _sample_records(src, sample_rows + 1)
    return _setup_from_records(records, separator, header, column_types, na_strings)


def _setup_from_records(
    records: List[str],
    separator: Optional[str],
    header: Optional[bool],
    column_types: Optional[Dict[str, str]],
    na_strings: Sequence[str],
) -> ParseSetup:
    if not records:
        raise ValueError("empty input")
    sep = separator or _guess_separator(records)
    rows = [_tokenize(r, sep) for r in records]
    width = max(len(r) for r in rows)
    rows = [r + [""] * (width - len(r)) for r in rows]

    if header is None:
        header = _guess_header(rows, na_strings)
    names = (
        [_clean_name(t, i) for i, t in enumerate(rows[0])]
        if header
        else [f"C{i + 1}" for i in range(width)]
    )
    body = rows[1:] if header else rows
    types: List[ColType] = []
    for j in range(width):
        forced = (column_types or {}).get(names[j])
        if forced:
            types.append(_parse_type_name(forced))
        else:
            types.append(_guess_col_type([r[j] for r in body], na_strings))
    return ParseSetup(
        separator=sep,
        header=bool(header),
        column_names=names,
        column_types=types,
        na_strings=na_strings,
    )


def parse_csv(
    src: Union[str, os.PathLike],
    separator: Optional[str] = None,
    header: Optional[bool] = None,
    column_types: Optional[Dict[str, str]] = None,
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS,
    setup: Optional[ParseSetup] = None,
    workers: Optional[int] = None,
) -> Frame:
    """Parse a CSV file or literal CSV text into a Frame (POST /3/Parse).

    Inputs larger than one chunk (``H2O3_TPU_PARSE_CHUNK_BYTES``, default
    8 MiB) take the chunk-parallel two-phase pipeline
    (``ParseDataset.java:623``): newline/quote-safe byte chunks are
    tokenized concurrently by ``workers`` threads
    (``H2O3_TPU_PARSE_WORKERS``, default host cores), then per-chunk
    categorical dictionaries merge into one sorted global domain — the
    result is bit-identical to the serial path at any worker count."""
    t0 = time.perf_counter()
    s = os.fspath(src) if not isinstance(src, str) else src
    if not s.strip():
        raise ValueError("empty input")
    threshold = _chunk_bytes()
    if "\n" not in s:
        if os.path.exists(s):
            if os.path.getsize(s) > threshold:
                with open(s, "rb") as f:
                    return _parse_csv_stream_impl(
                        f, t0, separator, header, column_types, na_strings,
                        setup, workers,
                    )
            with open(s, "r", encoding="utf-8", errors="replace") as f:
                text = f.read()
            return _parse_csv_text(
                text, t0, separator, header, column_types, na_strings, setup
            )
        if _looks_like_path(s):
            raise FileNotFoundError(s)
    if len(s) > threshold:  # large literal text: pipeline over its bytes
        return _parse_csv_stream_impl(
            io.BytesIO(s.encode("utf-8")), t0, separator, header,
            column_types, na_strings, setup, workers,
        )
    return _parse_csv_text(
        s, t0, separator, header, column_types, na_strings, setup
    )


def parse_csv_stream(
    stream,
    separator: Optional[str] = None,
    header: Optional[bool] = None,
    column_types: Optional[Dict[str, str]] = None,
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS,
    setup: Optional[ParseSetup] = None,
    workers: Optional[int] = None,
) -> Frame:
    """Parse a binary CSV stream (anything with ``.read(n)``) into a Frame.

    This is the streamed-decompression entry (frame/ingest.py): gz/zip
    decoding stays incremental — bytes are pulled block-by-block and
    overlap with chunk tokenization already in flight, instead of
    materializing the whole decompressed text first."""
    t0 = time.perf_counter()
    return _parse_csv_stream_impl(
        stream, t0, separator, header, column_types, na_strings, setup,
        workers,
    )


def _parse_csv_text(
    text: str,
    t0: float,
    separator: Optional[str],
    header: Optional[bool],
    column_types: Optional[Dict[str, str]],
    na_strings: Sequence[str],
    setup: Optional[ParseSetup],
) -> Frame:
    """Serial whole-text parse — the small-input path and the oracle the
    chunk pipeline is pinned bit-identical against (tests/test_parse_parallel)."""
    if setup is None:
        setup = parse_setup(
            text,
            separator=separator,
            header=header,
            column_types=column_types,
            na_strings=na_strings,
        )
    fast = _native_numeric_fast(text, setup)
    if fast is not None:
        _PARSE_ROWS.inc(fast.nrows, parser="csv_native")
        _PARSE_SECONDS.observe(time.perf_counter() - t0, parser="csv_native")
        return fast
    records = _split_records(text)
    if setup.skip_blank_lines:
        records = [r for r in records if r.strip()]
    if setup.header:
        records = records[1:]
    fr = Frame(_records_to_columns(records, setup, frozenset(setup.na_strings)))
    _PARSE_ROWS.inc(fr.nrows, parser="csv")
    _PARSE_SECONDS.observe(time.perf_counter() - t0, parser="csv")
    return fr


def _parse_csv_stream_impl(
    stream,
    t0: float,
    separator: Optional[str],
    header: Optional[bool],
    column_types: Optional[Dict[str, str]],
    na_strings: Sequence[str],
    setup: Optional[ParseSetup],
    workers: Optional[int],
) -> Frame:
    cb = _chunk_bytes()
    target = max(cb + 1, _SAMPLE_BYTES)
    first = _read_block(stream, target)
    if len(first) <= cb:  # fits in one chunk: serial path
        return _parse_csv_text(
            first.decode("utf-8", errors="replace"), t0, separator, header,
            column_types, na_strings, setup,
        )
    if setup is None:
        # guessSetup on a sampled prefix; when the stream continues past
        # the sample, the trailing record may be cut mid-stream and is
        # dropped (same as _sample_records on files)
        sample = first[:_SAMPLE_BYTES]
        complete = len(first) < target and len(first) <= _SAMPLE_BYTES
        recs = _split_records(sample.decode("utf-8", errors="replace"))
        if not complete and recs:
            recs = recs[:-1]
        recs = [r for r in recs if r.strip()][:1001]
        if not recs:
            # no complete record inside the sample window (e.g. one giant
            # quoted record): chunking gains nothing — drain the stream
            # and take the serial whole-text path
            rest = first + _read_block(stream, 1 << 62)
            return _parse_csv_text(
                rest.decode("utf-8", errors="replace"), t0, separator,
                header, column_types, na_strings, None,
            )
        setup = _setup_from_records(
            recs, separator, header, column_types, na_strings
        )

    def blocks() -> Iterator[bytes]:
        yield first
        while True:
            b = stream.read(cb)
            if not b:
                return
            yield b

    return _parse_pipeline(blocks(), setup, t0, workers)


def _read_block(stream, n: int) -> bytes:
    """Read exactly n bytes unless EOF (some streams return short reads)."""
    parts: List[bytes] = []
    got = 0
    while got < n:
        b = stream.read(n - got)
        if not b:
            break
        parts.append(b)
        got += len(b)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# chunk-parallel two-phase pipeline
#
# Phase 1 (map): the byte stream is cut into newline-safe chunks (RFC-4180
# quoted newlines respected via quote parity, so every chunk starts at a
# record boundary), and a ThreadPoolExecutor tokenizes chunks concurrently.
# Eligible chunks run entirely inside GIL-releasing native (csv.cpp) calls —
# cell indexing, float/time parsing, dictionary encoding — so the workers
# scale with host cores; chunks with quotes/unicode take the python
# tokenizer.  Phase 2 (reduce): per-chunk categorical dictionaries merge
# into one lexicographically sorted global domain (Categorical.java
# semantics), per-chunk codes are remapped, columns concatenate.  The
# output is bit-identical to the serial path for any worker count and any
# chunk size.


class _DivergentStream(Exception):
    """Raised by the chunker when a block contains a record terminator the
    byte-level \\n scan cannot honor (lone \\r, \\v, \\f, \\x1c-\\x1e,
    NEL/LS/PS): cutting past it would split records differently from the
    python oracle.  Chunks already cut are clean — a chunk free of these
    bytes (and always starting/ending at record boundaries) tokenizes
    identically under either global record-splitting discipline — so the
    pipeline recovers by parsing the unconsumed remainder with the right
    semantics instead of discarding work.  Carries the unconsumed buffer,
    whether the header was already cut, and whether any consumed byte was
    a quote (which picks the global discipline — _split_records)."""

    def __init__(self, buf: bytes, header_done: bool, seen_quote: bool):
        super().__init__("splitlines-divergent record terminator")
        self.buf = buf
        self.header_done = header_done
        self.seen_quote = seen_quote


def _iter_body_chunks(
    blocks: Iterable[bytes],
    chunk_bytes: int,
    skip_header: bool,
    skip_blanks: bool,
) -> Iterator[bytes]:
    """Cut a byte-block stream into record-aligned body chunks.

    Leading blank records and the header record are consumed here, so
    workers see pure body bytes.  Chunks always end just past a newline at
    even quote parity; a record longer than chunk_bytes simply produces a
    bigger chunk.  Raises _DivergentStream before cutting any region that
    contains a record terminator the \\n scan would miss."""
    it = iter(blocks)
    buf = b""
    eof = False
    scanned = 0  # buf offset below which divergent bytes were ruled out
    header_done = not skip_header
    seen_quote = False

    def fill(target: int) -> None:
        nonlocal buf, eof, scanned, seen_quote
        while not eof and len(buf) < target:
            b = next(it, None)
            if b is None:
                eof = True
            elif b:
                seen_quote = seen_quote or b'"' in b
                buf += b
        # scan the newly arrived region (2-byte back-overlap covers the
        # multi-byte LS/PS patterns and the held-back final byte)
        if len(buf) > scanned:
            if _has_divergent(buf, max(scanned - 2, 0), eof):
                raise _DivergentStream(buf, header_done, seen_quote)
            scanned = len(buf) if eof else len(buf) - 1

    if skip_header:
        while True:
            fill(len(buf) + chunk_bytes + 1)
            cut = _header_end(buf, skip_blanks, eof)
            if cut is not None:
                buf = buf[cut:]
                scanned = max(scanned - cut, 0)
                header_done = True
                break
            if eof:
                return  # the whole input is header (+ blanks): empty body
    target = chunk_bytes
    while True:
        fill(target)
        if eof and len(buf) <= chunk_bytes:
            if buf:
                yield buf
            return
        cut = _quote_safe_cut(buf, chunk_bytes)
        if cut is None:
            if eof:
                yield buf
                return
            target = len(buf) + chunk_bytes  # record spans the chunk: grow
            continue
        yield buf[:cut]
        buf = buf[cut:]
        scanned = max(scanned - cut, 0)
        target = chunk_bytes


def _quote_safe_cut(buf: bytes, target: int) -> Optional[int]:
    """End offset (just past a record-terminating newline) of the largest
    record-aligned prefix near ``target``; None when buf holds no complete
    record.  Chunks start at record boundaries, so quote parity counts
    from zero: a newline is a record boundary iff the quotes before it
    are balanced (every '"' toggles — doubled quotes toggle twice, same
    state machine as _split_records)."""
    if buf.find(b'"') < 0:
        p = buf.rfind(b"\n", 0, target)
        if p < 0:
            p = buf.find(b"\n", target)
        return p + 1 if p >= 0 else None
    arr = np.frombuffer(buf, dtype=np.uint8)
    nl = np.flatnonzero(arr == 10)
    if nl.size == 0:
        return None
    q = np.cumsum(arr == 34)
    ok = nl[q[nl] % 2 == 0]
    if ok.size == 0:
        return None
    cand = ok[ok < target]
    return int(cand[-1] if cand.size else ok[0]) + 1


def _header_end(buf: bytes, skip_blanks: bool, eof: bool) -> Optional[int]:
    """Byte offset just past the header record (plus any leading blank
    records); None when the buffer doesn't yet contain the whole header."""
    pos = 0
    while True:
        rec_end = _record_end(buf, pos)
        if rec_end is None:
            # at EOF the unterminated remainder IS the header; body empty
            return len(buf) if eof else None
        if skip_blanks and not buf[pos:rec_end].strip(b" \t\r"):
            pos = rec_end + 1
            continue
        return rec_end + 1


def _record_end(buf: bytes, pos: int) -> Optional[int]:
    """Index of the newline terminating the record starting at pos (which
    is a record boundary, i.e. quote parity 0), or None."""
    if buf.find(b'"', pos) < 0:
        p = buf.find(b"\n", pos)
        return p if p >= 0 else None
    arr = np.frombuffer(buf, dtype=np.uint8)[pos:]
    nl = np.flatnonzero(arr == 10)
    if nl.size == 0:
        return None
    q = np.cumsum(arr == 34)
    ok = nl[q[nl] % 2 == 0]
    return pos + int(ok[0]) if ok.size else None


def _na_breaks_numeric(na_strings: Sequence[str]) -> bool:
    """True when an NA token parses to a non-NaN number: python maps it to
    NA while a byte-level numeric parse would yield the value.  NaN-valued
    tokens ('NaN', 'nan') are harmless — both paths produce NaN."""
    for t in na_strings:
        if not t:
            continue
        try:
            v = float(t)
        except ValueError:
            continue
        if not math.isnan(v):  # includes +-inf: float('inf') never raises
            return True
    return False


#: bytes whose presence in a chunk routes it to the python tokenizer:
#: quotes (RFC-4180 state machine), NUL (would corrupt the gather join),
#: and str.splitlines' extra record terminators (\v \f \x1c-\x1e) that a
#: byte-level \n scan would miss
_PY_ONLY_BYTES = (b'"', b"\x00", b"\x0b", b"\x0c", b"\x1c", b"\x1d", b"\x1e")


def _chunk_native_ok(chunk: bytes, setup: ParseSetup) -> bool:
    """May this chunk take the native tokenizer and stay bit-identical to
    the python path?  Quotes/unicode/lone-\\r and (for numeric columns)
    tokens only python's float() accepts all force the python tokenizer."""
    if not chunk or len(setup.separator) != 1:
        return False
    if any(b in chunk for b in _PY_ONLY_BYTES):
        return False
    arr = np.frombuffer(chunk, dtype=np.uint8)
    if int(arr.max()) > 127:  # unicode: \x85/  terminators, NBSP strip
        return False
    cr = np.flatnonzero(arr == 13)
    if cr.size and (
        cr[-1] == arr.size - 1 or bool((arr[cr + 1] != 10).any())
    ):
        return False  # lone \r splits records in python, not in a \n scan
    return True


def _pack_na(na_strings: Sequence[str]) -> Tuple[bytes, np.ndarray, np.ndarray]:
    """NA tokens packed as (blob, int32 starts, int32 ends) for the native
    dictionary/gather primitives."""
    toks = [t.encode("utf-8") for t in na_strings]
    starts = np.empty(len(toks), dtype=np.int32)
    ends = np.empty(len(toks), dtype=np.int32)
    pos = 0
    for i, t in enumerate(toks):
        starts[i] = pos
        pos += len(t)
        ends[i] = pos
    return b"".join(toks), starts, ends


#: per-chunk result: (nrows, payloads, used_native) where payloads[j] is a
#: float64 array (NUM/TIME/BAD), (int32 codes, local domain) for CAT, or an
#: object array (STR/UUID)
_ChunkResult = Tuple[int, list, bool]


def _parse_chunk(chunk: bytes, setup: ParseSetup, na: frozenset, napack) -> _ChunkResult:
    if napack is not None and _chunk_native_ok(chunk, setup):
        try:
            res = _parse_chunk_native(chunk, setup, na, napack)
            if res is not None:
                return res
        except Exception:
            pass  # any native surprise falls back to the python oracle
    return _parse_chunk_python(chunk, setup, na)


def _parse_chunk_native(
    chunk: bytes, setup: ParseSetup, na: frozenset, napack
) -> Optional[_ChunkResult]:
    from h2o3_tpu import native

    width = len(setup.column_names)
    idx = native.csv_index_chunk(
        chunk, setup.separator, width, setup.skip_blank_lines
    )
    if idx is None:
        return None
    starts, ends = idx
    n = starts.shape[0]
    na_blob, na_st, na_en = napack
    payloads: list = []
    for j, ctype in enumerate(setup.column_types):
        s = np.ascontiguousarray(starts[:, j])
        e = np.ascontiguousarray(ends[:, j])
        if ctype is ColType.CAT:
            r = native.dict_encode_cells(chunk, s, e, na_blob, na_st, na_en)
            if r is None:
                return None
            codes, ust, uen = r
            domain = [
                chunk[ust[k]:uen[k]].decode("ascii") for k in range(len(ust))
            ]
            payloads.append((codes, domain))
        elif ctype in (ColType.STR, ColType.UUID):
            r = native.gather_cells(chunk, s, e, na_blob, na_st, na_en)
            if r is None:
                return None
            joined, mask = r
            arr = np.empty(n, dtype=object)
            if n:
                arr[:] = joined.decode("ascii").split("\n")
                arr[mask.view(bool)] = None
            payloads.append(arr)
        elif ctype is ColType.TIME:
            r = native.parse_cells_time(chunk, s, e)
            if r is None:
                return None
            out, flags = r
            bad = np.flatnonzero(flags)
            if bad.size:  # NA tokens / nonstandard formats: python oracle
                toks = [
                    chunk[s[i]:e[i]].decode("ascii") for i in bad
                ]
                out[bad] = _parse_times(toks, na)
            payloads.append(out)
        else:  # NUM / BAD
            out = native.parse_cells_f64(chunk, s, e)
            if out is None:
                return None
            # python's float() accepts underscore separators (1_000) the
            # native tokenizer rejects as junk; only NaN cells can hide
            # one, so repair just those
            if b"_" in chunk:
                for i in np.flatnonzero(np.isnan(out)):
                    cell = chunk[s[i]:e[i]]
                    if b"_" in cell:
                        t = cell.decode("ascii")
                        if t not in na:
                            try:
                                out[i] = float(t)
                            except ValueError:
                                pass
            payloads.append(out)
    return n, payloads, True


def _records_to_columns(
    records: List[str], setup: ParseSetup, na: frozenset
) -> List[Column]:
    """Tokenize logical records into built Columns — the ONE record
    loop both the serial path and the python chunk workers share (the
    pipeline's bit-identity contract depends on them never diverging)."""
    width = len(setup.column_names)
    cells: List[List[str]] = [[] for _ in range(width)]
    for rec in records:
        toks = _tokenize(rec, setup.separator)
        for j in range(width):
            cells[j].append(toks[j] if j < len(toks) else "")
    return [
        _build_column(setup.column_names[j], setup.column_types[j], cells[j], na)
        for j in range(width)
    ]


def _parse_chunk_python(
    chunk: bytes, setup: ParseSetup, na: frozenset,
    force_machine: Optional[bool] = None,
) -> _ChunkResult:
    text = chunk.decode("utf-8", errors="replace")
    records = _split_records(text, force_machine)
    if setup.skip_blank_lines:
        records = [r for r in records if r.strip()]
    payloads: list = []
    for j, col in enumerate(_records_to_columns(records, setup, na)):
        if setup.column_types[j] is ColType.CAT:
            payloads.append((col.data, col.domain))
        else:
            payloads.append(col.data)
    return len(records), payloads, False


def _pipeline_napack(setup: ParseSetup):
    """The packed-NA blob chunk workers hand the native tokenizer, or
    None when the native path is unavailable/ineligible.  Shared by the
    in-process pipeline and the cluster's remote parse_chunk task
    (h2o3_tpu/cluster/tasks.py) so both pick the same tokenizer."""
    napack = None
    try:
        from h2o3_tpu import native

        if native.available():
            napack = _pack_na(setup.na_strings)
    except Exception:
        napack = None
    if napack is not None and _na_breaks_numeric(setup.na_strings) and any(
        t in (ColType.NUM, ColType.BAD) for t in setup.column_types
    ):
        napack = None  # a numeric NA token breaks native float parity
    return napack


def _parse_pipeline(
    blocks: Iterable[bytes],
    setup: ParseSetup,
    t0: float,
    workers: Optional[int],
) -> Frame:
    na = frozenset(setup.na_strings)
    w = max(1, int(workers)) if workers else _env_workers()
    napack = _pipeline_napack(setup)

    futures: list = []
    tail_result: Optional[_ChunkResult] = None
    with ThreadPoolExecutor(max_workers=w) as ex:
        inflight: deque = deque()
        try:
            for chunk in _iter_body_chunks(
                blocks, _chunk_bytes(), setup.header, setup.skip_blank_lines
            ):
                fut = ex.submit(_parse_chunk, chunk, setup, na, napack)
                futures.append(fut)
                inflight.append(fut)
                # bound decompress-ahead so memory stays ~W chunks, while the
                # decode of chunk k+1 still overlaps the tokenize of chunk k
                while len(inflight) > w * 4:
                    inflight.popleft().result()
        except _DivergentStream as d:
            # a record terminator the \n chunker cannot honor: drain the
            # rest of the stream and recover (see _DivergentStream)
            tail = b"".join([d.buf] + [b for b in blocks if b])
            if not d.header_done:
                # nothing was cut yet — the whole input takes the serial
                # oracle (its splitlines/state-machine semantics ARE the
                # contract the chunker could not honor here)
                for f in futures:
                    f.result()
                return _parse_csv_text(
                    tail.decode("utf-8", errors="replace"), t0,
                    None, None, None, setup.na_strings, setup,
                )
            machine = d.seen_quote or b'"' in tail
            tail_result = _parse_chunk_python(
                tail, setup, na, force_machine=machine
            )
        results = [f.result() for f in futures]
    if tail_result is not None:
        results.append(tail_result)

    n_native = sum(1 for r in results if r[2])
    if n_native:
        _PARSE_CHUNKS.inc(n_native, parser="native")
    if len(results) - n_native:
        _PARSE_CHUNKS.inc(len(results) - n_native, parser="python")
    _PARSE_WORKERS.set(w)
    label = (
        "native-parallel"
        if results and n_native == len(results)
        else ("python-parallel" if n_native == 0 else "mixed-parallel")
    )
    fr = _reduce_chunks(results, setup)
    _PARSE_ROWS.inc(fr.nrows, parser=label)
    _PARSE_SECONDS.observe(time.perf_counter() - t0, parser=label)
    return fr


def _reduce_chunks(results: List[_ChunkResult], setup: ParseSetup) -> Frame:
    """Phase 2: unify per-chunk dictionaries into sorted global domains
    (reference Categorical.java), remap codes, concatenate columns.

    Codec-aware: chunk results read back off the DKV ring may carry
    ENCODED column payloads (frame/codecs.py — parse lands encoded
    chunks on their homes); each decodes bit-exactly here, so a
    materializing gather over encoded chunks is uint64-view identical
    to a dense local parse."""
    from h2o3_tpu.frame import codecs as _codecs

    results = [_codecs.decode_chunk(r) for r in results]
    cols: List[Column] = []
    for j, name in enumerate(setup.column_names):
        ctype = setup.column_types[j]
        if ctype is ColType.CAT:
            domains = [r[1][j][1] for r in results]
            global_domain = sorted(set().union(*map(set, domains))) if domains else []
            gd = np.array(global_domain) if global_domain else None
            parts = []
            for r in results:
                codes, dom = r[1][j]
                if dom:
                    remap = np.searchsorted(gd, np.array(dom)).astype(np.int32)
                    codes = np.where(
                        codes >= 0, remap[np.clip(codes, 0, None)], NA_CAT
                    ).astype(np.int32)
                parts.append(codes)
            data = (
                np.concatenate(parts) if parts else np.empty(0, dtype=np.int32)
            )
            cols.append(Column(name, data, ColType.CAT, global_domain))
        elif ctype in (ColType.STR, ColType.UUID):
            parts = [r[1][j] for r in results]
            data = (
                np.concatenate(parts) if parts else np.empty(0, dtype=object)
            )
            cols.append(Column(name, data, ctype))
        else:
            parts = [r[1][j] for r in results]
            data = (
                np.concatenate(parts)
                if parts
                else np.empty(0, dtype=np.float64)
            )
            cols.append(Column(name, data, ctype))
    return Frame(cols)


def column_from_strings(
    name: str, tokens: Sequence[Optional[str]], na_strings: Sequence[str] = DEFAULT_NA_STRINGS
) -> Column:
    """Build a typed Column from raw string tokens (type-guessed)."""
    na = frozenset(na_strings)
    toks = ["" if t is None else t for t in tokens]
    ctype = _guess_col_type(toks, na)
    return _build_column(name, ctype, toks, na)


# ---------------------------------------------------------------------------
# internals


def _native_numeric_fast(text: str, setup: ParseSetup) -> Optional[Frame]:
    """All-numeric fast path through the native tokenizer (native/csv.cpp —
    the CsvParser.java hot-loop equivalent, thread-parallel on newline
    boundaries). Returns None whenever the python path's semantics could
    diverge (quotes, blank lines, numeric NA strings, non-NUM columns) or the
    shared library is unavailable; callers then take the pure-python path.
    Parity is pinned by tests/test_native.py."""
    if not setup.column_names or any(t is not ColType.NUM for t in setup.column_types):
        return None
    if len(setup.separator) != 1 or '"' in text:
        return None
    # python float() accepts unicode digits a byte scan never will
    if not text.isascii():
        return None
    # record terminators python honors that a byte-level \n scan does not:
    # lone \r (old-Mac endings) and str.splitlines' extra terminators.
    # CRLF is fine — the native tokenizer strips the \r itself.
    if _SPLITLINES_DIVERGENT_RE.search(text):
        return None
    # native parses every physical line; blank or whitespace-only lines would
    # become all-NaN rows where python (skip_blank_lines) drops them
    if re.search(r"(?m)^[ \t\r]*$", text[:-1] if text.endswith("\n") else text):
        return None
    body_start = 0
    if setup.header:
        nl = text.find("\n")
        if nl < 0:
            return None
        body_start = nl + 1
    # numeric literals only python's float() accepts (underscore separators
    # like 1_000) must take the python path — but scan only the BODY: a
    # header named col_1 must not disable the fast path for the whole file
    if text.find("_", body_start) >= 0:
        return None
    # an NA token that parses to a non-NaN number would be NA in python but
    # numeric here; NaN-valued tokens ('NaN', 'nan' — in the DEFAULT list)
    # produce NaN on both paths and must not disable the fast path
    if _na_breaks_numeric(setup.na_strings):
        return None
    try:
        from h2o3_tpu import native
    except Exception:
        return None
    if not native.available():
        return None
    raw = text.encode("utf-8")
    start = 0
    if setup.header:
        nl = raw.find(b"\n")
        if nl < 0:
            return None
        start = nl + 1
    if start >= len(raw):
        return None
    nrows = raw.count(b"\n", start) + (0 if raw.endswith(b"\n") else 1)
    mat = native.parse_numeric_csv(
        raw, start, setup.separator, len(setup.column_names), nrows
    )
    if mat is None:
        return None
    cols = [
        Column(name, np.ascontiguousarray(mat[:, j]), ColType.NUM)
        for j, name in enumerate(setup.column_names)
    ]
    return Frame(cols)


def _looks_like_path(s: str) -> bool:
    return os.sep in s or s.lower().endswith(_PATHLIKE_SUFFIXES)


def _read_all(src: Union[str, os.PathLike]) -> str:
    s = os.fspath(src) if not isinstance(src, str) else src
    if not s.strip():
        raise ValueError("empty input")
    if "\n" not in s:
        if os.path.exists(s):
            with open(s, "r", encoding="utf-8", errors="replace") as f:
                return f.read()
        if _looks_like_path(s):
            raise FileNotFoundError(s)
    return s  # literal CSV text


def _split_records(text: str, force_machine: Optional[bool] = None) -> List[str]:
    """Split text into logical records: newlines inside double quotes do NOT
    terminate a record (RFC 4180).  Quote-free text takes str.splitlines
    (its richer terminator set is the long-standing serial behavior);
    ``force_machine`` overrides that local choice with the *global* one —
    the chunk pipeline's divergent-tail recovery must split a quote-free
    tail with the quote state machine when the rest of the input had
    quotes, exactly as the serial whole-text pass would."""
    machine = ('"' in text) if force_machine is None else force_machine
    if not machine:
        return text.splitlines()
    out, cur, inq = [], [], False
    for ch in text:
        if ch == '"':
            inq = not inq
            cur.append(ch)
        elif ch == "\n" and not inq:
            rec = "".join(cur)
            out.append(rec[:-1] if rec.endswith("\r") else rec)
            cur = []
        else:
            cur.append(ch)
    if cur:
        rec = "".join(cur)
        out.append(rec[:-1] if rec.endswith("\r") else rec)
    return out


def _sample_records(src: Union[str, os.PathLike], n: int) -> List[str]:
    """First n non-blank records; streams only a prefix for file paths."""
    s = os.fspath(src) if not isinstance(src, str) else src
    if not s.strip():
        raise ValueError("empty input")
    if "\n" not in s and os.path.exists(s):
        chunks: List[str] = []
        with open(s, "r", encoding="utf-8", errors="replace") as f:
            complete = False
            while len(chunks) == 0 or sum(c.count("\n") for c in chunks) < n + 1:
                chunk = f.read(1 << 20)
                if not chunk:
                    complete = True
                    break
                chunks.append(chunk)
        text = "".join(chunks)
        records = _split_records(text)
        if not complete and records:
            records = records[:-1]  # drop possibly-partial trailing record
    else:
        records = _split_records(_read_all(s))
    return [r for r in records if r.strip()][:n]


def _clean_name(tok: str, idx: int) -> str:
    tok = tok.strip().strip('"')
    return tok if tok else f"C{idx + 1}"


def _guess_separator(records: List[str]) -> str:
    """Pick the candidate separator with the most consistent nonzero count
    (reference: CsvParser.guessSeparator heuristic)."""
    best, best_score = ",", -1.0
    for sep in (",", "\t", ";", "|", " "):
        counts = [len(_tokenize(r, sep)) for r in records[:50]]
        if not counts or max(counts) <= 1:
            continue
        consistency = counts.count(counts[0]) / len(counts)
        score = consistency * min(counts[0], 1000)
        if score > best_score:
            best, best_score = sep, score
    return best


def _tokenize(line: str, sep: str) -> List[str]:
    """Split one record, honoring double-quote quoting and doubled quotes."""
    if '"' not in line:
        return [t.strip() for t in line.split(sep)]
    out, cur, inq, i = [], [], False, 0
    while i < len(line):
        ch = line[i]
        if inq:
            if ch == '"':
                if i + 1 < len(line) and line[i + 1] == '"':
                    cur.append('"')
                    i += 1
                else:
                    inq = False
            else:
                cur.append(ch)
        elif ch == '"':
            inq = True
        elif ch == sep:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
        i += 1
    out.append("".join(cur).strip())
    return out


def _guess_header(rows: List[List[str]], na_strings: Sequence[str]) -> bool:
    """Header iff first row is all-string while some body column is non-string."""
    if len(rows) < 2:
        return False
    first, body = rows[0], rows[1:]
    na = frozenset(na_strings)
    first_all_str = all((t in na) or not _is_number(t) for t in first)
    if not first_all_str:
        return False
    for j, tok in enumerate(first):
        colvals = [r[j] for r in body if r[j] not in na]
        if colvals and all(_is_number(v) for v in colvals) and not _is_number(tok) and tok not in na:
            return True
    # all-categorical data: header iff first-row tokens don't reappear in body
    body_tokens = {t for r in body[:100] for t in r}
    return bool(first) and not any(t in body_tokens for t in first if t not in na)


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return tok.lower() in ("inf", "-inf", "+inf")


def _is_time(tok: str) -> bool:
    return any(p.match(tok) for p in _TIME_PATTERNS)


def _guess_col_type(tokens: Sequence[str], na: frozenset) -> ColType:
    """Type precedence on non-NA sample tokens: NUM > TIME > UUID > CAT/STR.
    Reference: ParseSetup column type guessing; CAT unless cardinality is
    'mostly unique' (then STR), matching the reference's categorical-vs-string call."""
    vals = [t for t in tokens if t not in na]
    if not vals:
        return ColType.BAD
    if all(_is_number(t) for t in vals):
        return ColType.NUM
    if all(_is_time(t) for t in vals):
        return ColType.TIME
    if all(_UUID_RE.match(t) for t in vals):
        return ColType.UUID
    if len(set(vals)) > max(256, 0.95 * len(vals)):
        return ColType.STR
    return ColType.CAT


def _parse_type_name(t: Union[str, ColType]) -> ColType:
    if isinstance(t, ColType):
        return t
    alias = {
        "numeric": ColType.NUM,
        "real": ColType.NUM,
        "int": ColType.NUM,
        "enum": ColType.CAT,
        "categorical": ColType.CAT,
        "factor": ColType.CAT,
        "string": ColType.STR,
        "time": ColType.TIME,
        "uuid": ColType.UUID,
    }
    return alias[t.lower()]


def _build_column(name: str, ctype: ColType, tokens: List[str], na: frozenset) -> Column:
    n = len(tokens)
    if ctype in (ColType.NUM, ColType.BAD):
        out = np.empty(n, dtype=np.float64)
        for i, t in enumerate(tokens):
            if t in na:
                out[i] = np.nan
            else:
                try:
                    out[i] = float(t)
                except ValueError:
                    out[i] = np.nan
        return Column(name, out, ColType.NUM if ctype is ColType.NUM else ColType.BAD)
    if ctype is ColType.TIME:
        return Column(name, _parse_times(tokens, na), ColType.TIME)
    if ctype is ColType.CAT:
        levels: Dict[str, int] = {}
        codes = np.empty(n, dtype=np.int32)
        for i, t in enumerate(tokens):
            if t in na:
                codes[i] = NA_CAT
            else:
                codes[i] = levels.setdefault(t, len(levels))
        # reference sorts categorical domains lexicographically at parse end
        order = sorted(levels, key=str)
        remap = np.empty(len(order), dtype=np.int32)
        for newc, lv in enumerate(order):
            remap[levels[lv]] = newc
        codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)], NA_CAT).astype(np.int32)
        return Column(name, codes, ColType.CAT, list(order))
    # STR / UUID
    arr = np.array([None if t in na else t for t in tokens], dtype=object)
    return Column(name, arr, ctype)


def _parse_times(tokens: List[str], na: frozenset) -> np.ndarray:
    import datetime as dt

    out = np.empty(len(tokens), dtype=np.float64)
    fmts = (
        "%Y-%m-%d %H:%M:%S.%f",
        "%Y-%m-%d %H:%M:%S",
        "%Y-%m-%dT%H:%M:%S.%f",
        "%Y-%m-%dT%H:%M:%S",
        "%Y-%m-%d",
        "%m/%d/%Y",
    )
    epoch = dt.datetime(1970, 1, 1)
    for i, t in enumerate(tokens):
        if t in na:
            out[i] = np.nan
            continue
        for f in fmts:
            try:
                out[i] = (dt.datetime.strptime(t, f) - epoch).total_seconds() * 1000.0
                break
            except ValueError:
                continue
        else:
            out[i] = np.nan
    return out
