"""Data ingest: CSV/ARFF-style parsing with type guessing.

Reference: two-phase distributed parse (``water/parser/ParseDataset.java:127,623,899``)
with a ``ParseSetup.guessSetup`` pre-pass that infers separator / header /
per-column types from a sample, then a cluster-wide MRTask that tokenizes file
chunks into NewChunks and unifies categorical dictionaries
(``water/parser/Categorical.java``).

TPU-native redesign: parsing is host-side work (there is no reason to tokenize
bytes on an MXU); the output is dense columnar numpy, which then shards onto
the mesh. We keep the reference's *semantics*: guessSetup (separator sniffing,
header detection, per-column NUM/CAT/TIME/STR/UUID guessing with the same
precedence), NA-string handling, RFC-4180 quoting (embedded separators,
doubled quotes, quoted newlines), categorical dictionary construction, and a
``parse_setup``/``parse_csv`` two-step API mirroring POST /3/ParseSetup +
POST /3/Parse.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame, NA_CAT
from h2o3_tpu.util import telemetry

#: parse accounting — every CSV parse (library call, REST /3/Parse, multi-part
#: archives via ingest.parse_bytes) lands here; labels split the native fast
#: path from the pure-python tokenizer so the hot path's share is measurable
_PARSE_ROWS = telemetry.counter(
    "parse_rows_total", "rows parsed into frames", labels=("parser",)
)
_PARSE_SECONDS = telemetry.histogram(
    "parse_seconds", "wall seconds per CSV parse", labels=("parser",)
)

#: Default NA tokens (reference: water/parser/ParseSetup + CsvParser NA handling)
DEFAULT_NA_STRINGS = ("", "NA", "N/A", "na", "n/a", "NaN", "nan", "null", "NULL", "?")

_TIME_PATTERNS = (
    # yyyy-MM-dd[ HH:mm:ss[.SSS]] — the reference's ParseTime formats subset
    re.compile(r"^\d{4}-\d{2}-\d{2}([ T]\d{2}:\d{2}:\d{2}(\.\d+)?)?$"),
    re.compile(r"^\d{2}/\d{2}/\d{4}$"),
)
_UUID_RE = re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$"
)
_PATHLIKE_SUFFIXES = (".csv", ".txt", ".tsv", ".data", ".dat", ".gz", ".zip", ".svm", ".arff")


@dataclass
class ParseSetup:
    """Inferred parse plan (reference: water/parser/ParseSetup.java)."""

    separator: str = ","
    header: bool = True
    column_names: List[str] = field(default_factory=list)
    column_types: List[ColType] = field(default_factory=list)
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS
    skip_blank_lines: bool = True
    quote_char: str = '"'


def parse_setup(
    src: Union[str, os.PathLike],
    separator: Optional[str] = None,
    header: Optional[bool] = None,
    column_types: Optional[Dict[str, str]] = None,
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS,
    sample_rows: int = 1000,
) -> ParseSetup:
    """Guess separator/header/types from a sample (ParseSetup.guessSetup)."""
    records = _sample_records(src, sample_rows + 1)
    if not records:
        raise ValueError("empty input")
    sep = separator or _guess_separator(records)
    rows = [_tokenize(r, sep) for r in records]
    width = max(len(r) for r in rows)
    rows = [r + [""] * (width - len(r)) for r in rows]

    if header is None:
        header = _guess_header(rows, na_strings)
    names = (
        [_clean_name(t, i) for i, t in enumerate(rows[0])]
        if header
        else [f"C{i + 1}" for i in range(width)]
    )
    body = rows[1:] if header else rows
    types: List[ColType] = []
    for j in range(width):
        forced = (column_types or {}).get(names[j])
        if forced:
            types.append(_parse_type_name(forced))
        else:
            types.append(_guess_col_type([r[j] for r in body], na_strings))
    return ParseSetup(
        separator=sep,
        header=bool(header),
        column_names=names,
        column_types=types,
        na_strings=na_strings,
    )


def parse_csv(
    src: Union[str, os.PathLike],
    separator: Optional[str] = None,
    header: Optional[bool] = None,
    column_types: Optional[Dict[str, str]] = None,
    na_strings: Sequence[str] = DEFAULT_NA_STRINGS,
    setup: Optional[ParseSetup] = None,
) -> Frame:
    """Parse a CSV file or literal CSV text into a Frame (POST /3/Parse)."""
    import time as _time

    t0 = _time.perf_counter()
    text = _read_all(src)  # single read; setup guessing reuses it
    if setup is None:
        setup = parse_setup(
            text,
            separator=separator,
            header=header,
            column_types=column_types,
            na_strings=na_strings,
        )
    fast = _native_numeric_fast(text, setup)
    if fast is not None:
        _PARSE_ROWS.inc(fast.nrows, parser="csv_native")
        _PARSE_SECONDS.observe(_time.perf_counter() - t0, parser="csv_native")
        return fast
    records = _split_records(text)
    if setup.skip_blank_lines:
        records = [r for r in records if r.strip()]
    if setup.header:
        records = records[1:]
    width = len(setup.column_names)
    cells: List[List[str]] = [[] for _ in range(width)]
    for rec in records:
        toks = _tokenize(rec, setup.separator)
        for j in range(width):
            cells[j].append(toks[j] if j < len(toks) else "")
    na = frozenset(setup.na_strings)
    cols = [
        _build_column(setup.column_names[j], setup.column_types[j], cells[j], na)
        for j in range(width)
    ]
    fr = Frame(cols)
    _PARSE_ROWS.inc(fr.nrows, parser="csv")
    _PARSE_SECONDS.observe(_time.perf_counter() - t0, parser="csv")
    return fr


def column_from_strings(
    name: str, tokens: Sequence[Optional[str]], na_strings: Sequence[str] = DEFAULT_NA_STRINGS
) -> Column:
    """Build a typed Column from raw string tokens (type-guessed)."""
    na = frozenset(na_strings)
    toks = ["" if t is None else t for t in tokens]
    ctype = _guess_col_type(toks, na)
    return _build_column(name, ctype, toks, na)


# ---------------------------------------------------------------------------
# internals


def _native_numeric_fast(text: str, setup: ParseSetup) -> Optional[Frame]:
    """All-numeric fast path through the native tokenizer (native/csv.cpp —
    the CsvParser.java hot-loop equivalent, thread-parallel on newline
    boundaries). Returns None whenever the python path's semantics could
    diverge (quotes, blank lines, numeric NA strings, non-NUM columns) or the
    shared library is unavailable; callers then take the pure-python path.
    Parity is pinned by tests/test_native.py."""
    if not setup.column_names or any(t is not ColType.NUM for t in setup.column_types):
        return None
    if len(setup.separator) != 1 or '"' in text:
        return None
    # native parses every physical line; blank or whitespace-only lines would
    # become all-NaN rows where python (skip_blank_lines) drops them
    if re.search(r"(?m)^[ \t\r]*$", text[:-1] if text.endswith("\n") else text):
        return None
    # numeric literals python accepts but the native tokenizer doesn't
    # (underscore separators like 1_000) must take the python path
    if "_" in text:
        return None
    # any NA token that parses as a number would be NA in python, numeric here
    if any(t and _is_number(t) for t in setup.na_strings):
        return None
    try:
        from h2o3_tpu import native
    except Exception:
        return None
    if not native.available():
        return None
    raw = text.encode("utf-8")
    start = 0
    if setup.header:
        nl = raw.find(b"\n")
        if nl < 0:
            return None
        start = nl + 1
    if start >= len(raw):
        return None
    nrows = raw.count(b"\n", start) + (0 if raw.endswith(b"\n") else 1)
    mat = native.parse_numeric_csv(
        raw, start, setup.separator, len(setup.column_names), nrows
    )
    if mat is None:
        return None
    cols = [
        Column(name, np.ascontiguousarray(mat[:, j]), ColType.NUM)
        for j, name in enumerate(setup.column_names)
    ]
    return Frame(cols)


def _looks_like_path(s: str) -> bool:
    return os.sep in s or s.lower().endswith(_PATHLIKE_SUFFIXES)


def _read_all(src: Union[str, os.PathLike]) -> str:
    s = os.fspath(src) if not isinstance(src, str) else src
    if not s.strip():
        raise ValueError("empty input")
    if "\n" not in s:
        if os.path.exists(s):
            with open(s, "r", encoding="utf-8", errors="replace") as f:
                return f.read()
        if _looks_like_path(s):
            raise FileNotFoundError(s)
    return s  # literal CSV text


def _split_records(text: str) -> List[str]:
    """Split text into logical records: newlines inside double quotes do NOT
    terminate a record (RFC 4180)."""
    if '"' not in text:
        return text.splitlines()
    out, cur, inq = [], [], False
    for ch in text:
        if ch == '"':
            inq = not inq
            cur.append(ch)
        elif ch == "\n" and not inq:
            rec = "".join(cur)
            out.append(rec[:-1] if rec.endswith("\r") else rec)
            cur = []
        else:
            cur.append(ch)
    if cur:
        rec = "".join(cur)
        out.append(rec[:-1] if rec.endswith("\r") else rec)
    return out


def _sample_records(src: Union[str, os.PathLike], n: int) -> List[str]:
    """First n non-blank records; streams only a prefix for file paths."""
    s = os.fspath(src) if not isinstance(src, str) else src
    if not s.strip():
        raise ValueError("empty input")
    if "\n" not in s and os.path.exists(s):
        chunks: List[str] = []
        with open(s, "r", encoding="utf-8", errors="replace") as f:
            complete = False
            while len(chunks) == 0 or sum(c.count("\n") for c in chunks) < n + 1:
                chunk = f.read(1 << 20)
                if not chunk:
                    complete = True
                    break
                chunks.append(chunk)
        text = "".join(chunks)
        records = _split_records(text)
        if not complete and records:
            records = records[:-1]  # drop possibly-partial trailing record
    else:
        records = _split_records(_read_all(s))
    return [r for r in records if r.strip()][:n]


def _clean_name(tok: str, idx: int) -> str:
    tok = tok.strip().strip('"')
    return tok if tok else f"C{idx + 1}"


def _guess_separator(records: List[str]) -> str:
    """Pick the candidate separator with the most consistent nonzero count
    (reference: CsvParser.guessSeparator heuristic)."""
    best, best_score = ",", -1.0
    for sep in (",", "\t", ";", "|", " "):
        counts = [len(_tokenize(r, sep)) for r in records[:50]]
        if not counts or max(counts) <= 1:
            continue
        consistency = counts.count(counts[0]) / len(counts)
        score = consistency * min(counts[0], 1000)
        if score > best_score:
            best, best_score = sep, score
    return best


def _tokenize(line: str, sep: str) -> List[str]:
    """Split one record, honoring double-quote quoting and doubled quotes."""
    if '"' not in line:
        return [t.strip() for t in line.split(sep)]
    out, cur, inq, i = [], [], False, 0
    while i < len(line):
        ch = line[i]
        if inq:
            if ch == '"':
                if i + 1 < len(line) and line[i + 1] == '"':
                    cur.append('"')
                    i += 1
                else:
                    inq = False
            else:
                cur.append(ch)
        elif ch == '"':
            inq = True
        elif ch == sep:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
        i += 1
    out.append("".join(cur).strip())
    return out


def _guess_header(rows: List[List[str]], na_strings: Sequence[str]) -> bool:
    """Header iff first row is all-string while some body column is non-string."""
    if len(rows) < 2:
        return False
    first, body = rows[0], rows[1:]
    na = frozenset(na_strings)
    first_all_str = all((t in na) or not _is_number(t) for t in first)
    if not first_all_str:
        return False
    for j, tok in enumerate(first):
        colvals = [r[j] for r in body if r[j] not in na]
        if colvals and all(_is_number(v) for v in colvals) and not _is_number(tok) and tok not in na:
            return True
    # all-categorical data: header iff first-row tokens don't reappear in body
    body_tokens = {t for r in body[:100] for t in r}
    return bool(first) and not any(t in body_tokens for t in first if t not in na)


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return tok.lower() in ("inf", "-inf", "+inf")


def _is_time(tok: str) -> bool:
    return any(p.match(tok) for p in _TIME_PATTERNS)


def _guess_col_type(tokens: Sequence[str], na: frozenset) -> ColType:
    """Type precedence on non-NA sample tokens: NUM > TIME > UUID > CAT/STR.
    Reference: ParseSetup column type guessing; CAT unless cardinality is
    'mostly unique' (then STR), matching the reference's categorical-vs-string call."""
    vals = [t for t in tokens if t not in na]
    if not vals:
        return ColType.BAD
    if all(_is_number(t) for t in vals):
        return ColType.NUM
    if all(_is_time(t) for t in vals):
        return ColType.TIME
    if all(_UUID_RE.match(t) for t in vals):
        return ColType.UUID
    if len(set(vals)) > max(256, 0.95 * len(vals)):
        return ColType.STR
    return ColType.CAT


def _parse_type_name(t: Union[str, ColType]) -> ColType:
    if isinstance(t, ColType):
        return t
    alias = {
        "numeric": ColType.NUM,
        "real": ColType.NUM,
        "int": ColType.NUM,
        "enum": ColType.CAT,
        "categorical": ColType.CAT,
        "factor": ColType.CAT,
        "string": ColType.STR,
        "time": ColType.TIME,
        "uuid": ColType.UUID,
    }
    return alias[t.lower()]


def _build_column(name: str, ctype: ColType, tokens: List[str], na: frozenset) -> Column:
    n = len(tokens)
    if ctype in (ColType.NUM, ColType.BAD):
        out = np.empty(n, dtype=np.float64)
        for i, t in enumerate(tokens):
            if t in na:
                out[i] = np.nan
            else:
                try:
                    out[i] = float(t)
                except ValueError:
                    out[i] = np.nan
        return Column(name, out, ColType.NUM if ctype is ColType.NUM else ColType.BAD)
    if ctype is ColType.TIME:
        return Column(name, _parse_times(tokens, na), ColType.TIME)
    if ctype is ColType.CAT:
        levels: Dict[str, int] = {}
        codes = np.empty(n, dtype=np.int32)
        for i, t in enumerate(tokens):
            if t in na:
                codes[i] = NA_CAT
            else:
                codes[i] = levels.setdefault(t, len(levels))
        # reference sorts categorical domains lexicographically at parse end
        order = sorted(levels, key=str)
        remap = np.empty(len(order), dtype=np.int32)
        for newc, lv in enumerate(order):
            remap[levels[lv]] = newc
        codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)], NA_CAT).astype(np.int32)
        return Column(name, codes, ColType.CAT, list(order))
    # STR / UUID
    arr = np.array([None if t in na else t for t in tokens], dtype=object)
    return Column(name, arr, ctype)


def _parse_times(tokens: List[str], na: frozenset) -> np.ndarray:
    import datetime as dt

    out = np.empty(len(tokens), dtype=np.float64)
    fmts = (
        "%Y-%m-%d %H:%M:%S.%f",
        "%Y-%m-%d %H:%M:%S",
        "%Y-%m-%dT%H:%M:%S.%f",
        "%Y-%m-%dT%H:%M:%S",
        "%Y-%m-%d",
        "%m/%d/%Y",
    )
    epoch = dt.datetime(1970, 1, 1)
    for i, t in enumerate(tokens):
        if t in na:
            out[i] = np.nan
            continue
        for f in fmts:
            try:
                out[i] = (dt.datetime.strptime(t, f) - epoch).total_seconds() * 1000.0
                break
            except ValueError:
                continue
        else:
            out[i] = np.nan
    return out
