"""Compressed chunk codecs — the in-memory codec layer under the data plane.

Reference: the platform ships 20+ compressed Chunk representations under
``water/fvec/`` (C0DChunk constants, C1/C2 biased ints, CXI sparse,
CCSChunk dictionaries, C4FChunk narrow floats), chosen per chunk in
``NewChunk.close()`` — host memory, not compute, caps rows per node, so
the data plane never holds a dense copy it can avoid.  This module is
that layer for the TPU port's tokenized chunk payloads: every chunk a
parse lands on its DKV ring home (``cluster/frames.py``) passes through
:func:`encode_chunk`, and everything downstream — replica fan-out,
read-repair, map-side execution, fused Rapids programs — moves and holds
the *encoded* bytes.

The hard contract (enforced, not assumed): a codec is selected for a
column-chunk only if a literal encode→decode round-trip reproduces the
dense payload **bit-exactly** (uint64 views for float64, exact int codes
for CAT, element equality for STR/UUID).  Anything that fails the
round-trip — NaN payload bits, denormals, values outside a packed range —
stays dense.  Decoding therefore never changes a result anywhere: the
bit-identity guarantees of the distributed frame plane are codec
independent.

Codecs (per column-chunk, numeric unless noted):

======== ==================================================================
codec    representation
======== ==================================================================
const    one 8-byte value broadcast to ``n`` rows (C0DChunk)
sparse   (int32 index, float64 value) pairs over a +0.0 background (CXI)
affine   uint8/uint16 codes with ``offset + code * scale`` decode and a
         reserved NA sentinel (C1Chunk/C2Chunk biased ints, scaled)
dict     uint8/uint16 codes into a table of unique 64-bit patterns — the
         decode is a pure gather, bit-exact by construction (CCSChunk)
f32      float32 storage where the f64→f32→f64 round-trip is exact (C4F)
catpack  CAT codes biased +1 into uint8/uint16 (NA_CAT → 0)
strdict  STR/UUID values dictionary-coded into uint32 codes + unique list
dense    the unencoded payload (fallback; always correct)
======== ==================================================================

Selection: candidates are generated in the order above, each verified by
an actual round-trip, and the smallest verified encoding wins — but only
when its size is at most ``H2O3_TPU_CODEC_MIN_RATIO`` (default 0.75) of
the dense size; marginal wins are not worth the decode arithmetic.
``H2O3_TPU_CODECS=0`` disables the layer entirely (every chunk ships and
lands dense — the pre-codec data plane, byte for byte).

Encoded chunk values keep the store's ``[n, payloads, used_native]``
shape; an encoded column payload is a plain dict (``{"c": <codec>, ...}``
holding only python scalars, lists and numpy arrays) so it stays DKV
routable (``dkv.ROUTABLE_VALUE_TYPES``) and rides replica walk,
read-repair and anti-entropy sweeps unchanged.

Device decode: the fused-program paths (``rapids/fusion.py`` /
``rapids/dist_exec.py``) do not decode host-side — a group's column is
homogenized to one :func:`group_rep` (const / affine / dict / f32) whose
decode arithmetic is emitted INTO the jitted program (offset/scale as
traced runtime scalars — never baked constants, which XLA's algebraic
simplifier could fold through; see fusion._externalize_lits for the
signed-zero precedent — and dictionary decode as a device gather).
Homogenizing across chunks re-verifies bit-exactness against the
per-chunk decode and falls back to dense on any mismatch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import NA_CAT
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

__all__ = [
    "codecs_enabled",
    "min_ratio",
    "encode_chunk",
    "decode_chunk",
    "decode_column",
    "is_encoded_chunk",
    "is_encoded",
    "encoded_nbytes",
    "group_rep",
]

#: per column-chunk encode decision at land time (dense = fallback kept)
_CODEC_TOTAL = telemetry.counter(
    "chunk_codec_total",
    "column-chunk codec selections at encode time (dense = the chunk "
    "stayed uncompressed: round-trip failed or the win was marginal)",
    labels=("codec",),
)
#: running resident footprint of encoded payloads by codec
_RESIDENT_BYTES = telemetry.gauge(
    "chunk_resident_bytes",
    "cumulative bytes of column-chunk payloads landed per codec (the "
    "resident/replicated footprint the codec layer actually stores)",
    labels=("codec",),
)

#: NA sentinel per packed-int dtype (the all-ones code is reserved)
_SENTINEL = {np.dtype(np.uint8): 255, np.dtype(np.uint16): 65535}


def codecs_enabled() -> bool:
    """Kill switch: ``H2O3_TPU_CODECS=0`` lands every chunk dense —
    byte-for-byte the pre-codec data plane."""
    return os.environ.get("H2O3_TPU_CODECS", "1").lower() not in (
        "0", "false", "off")


def min_ratio() -> float:
    """Maximum encoded/dense size ratio worth the decode arithmetic
    (``H2O3_TPU_CODEC_MIN_RATIO``, default 0.75)."""
    try:
        r = float(os.environ.get("H2O3_TPU_CODEC_MIN_RATIO", "0.75"))
    except ValueError:
        r = 0.75
    return min(max(r, 0.0), 1.0)


# ---------------------------------------------------------------------------
# sizing — structural bytes (what the pickled store value is dominated by)


def _list_nbytes(vals: Sequence[Any]) -> int:
    return sum(
        (len(v) if isinstance(v, str) else 8) + 8 for v in vals)


def _payload_nbytes(p: Any) -> int:
    """Structural bytes of one column payload (dense or encoded)."""
    if isinstance(p, dict):  # encoded
        total = 0
        for v in p.values():
            if isinstance(v, np.ndarray):
                if v.dtype == object:
                    total += _list_nbytes(list(v))
                else:
                    total += int(v.nbytes)
            elif isinstance(v, (list, tuple)):
                total += _list_nbytes(v)
            else:
                total += 8
        return total
    if isinstance(p, tuple):  # CAT (codes, domain)
        return int(p[0].nbytes) + _list_nbytes(p[1])
    if isinstance(p, np.ndarray):
        if p.dtype == object:
            return _list_nbytes([v if v is not None else "" for v in p])
        return int(p.nbytes)
    return 8


def encoded_nbytes(value: Sequence[Any]) -> int:
    """Structural bytes of a chunk value ([n, payloads, native]) as the
    codec layer accounts it — encoded columns at their packed size."""
    return sum(_payload_nbytes(p) for p in value[1])


# ---------------------------------------------------------------------------
# numeric candidates (float64 payloads: NUM / TIME / BAD)


def _bits(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x).view(np.uint64)


def _bit_identical(a: np.ndarray, b: np.ndarray) -> bool:
    return a.shape == b.shape and bool(np.all(_bits(a) == _bits(b)))


def _cand_const(x: np.ndarray) -> Optional[Dict[str, Any]]:
    if np.unique(_bits(x)).size != 1:
        return None
    return {"c": "const", "n": int(x.size), "v": x[:1].copy()}


def _cand_sparse(x: np.ndarray) -> Optional[Dict[str, Any]]:
    nz = np.flatnonzero(_bits(x) != 0)
    # 12 bytes per stored pair; anything denser than ~1/2 never wins
    if nz.size * 12 >= x.size * 8:
        return None
    return {"c": "sparse", "n": int(x.size),
            "idx": nz.astype(np.int32), "vals": x[nz].copy()}


def _cand_affine(x: np.ndarray) -> Optional[Dict[str, Any]]:
    finite = np.isfinite(x)
    na = np.isnan(x)
    if not finite.any() or bool(np.any(~finite & ~na)):
        return None  # all-NA is const's business; ±inf cannot pack
    v = x[finite]
    offset = float(v.min())
    d = v - offset
    with np.errstate(invalid="ignore"):
        if np.all(d == np.floor(d)):
            scale = 1.0
        else:
            u = np.unique(d)
            u = u[u > 0]
            if u.size == 0:
                return None
            scale = float(u[0])
            q = d / scale
            if not np.all(q == np.floor(q)):
                return None
        kmax = d.max() / scale
    if not np.isfinite(kmax):
        return None
    for dt in (np.uint8, np.uint16):
        sent = _SENTINEL[np.dtype(dt)]
        if kmax < sent:  # the sentinel code itself stays reserved
            codes = np.full(x.size, sent, dtype=dt)
            codes[finite] = np.rint(d / scale).astype(dt)
            return {"c": "affine", "n": int(x.size), "codes": codes,
                    "offset": offset, "scale": float(scale)}
    return None


def _cand_dict(x: np.ndarray) -> Optional[Dict[str, Any]]:
    b = _bits(x)
    uniq_bits, inv = np.unique(b, return_inverse=True)
    for dt, cap in ((np.uint8, 256), (np.uint16, 65536)):
        if uniq_bits.size <= cap:
            return {"c": "dict", "n": int(x.size),
                    "codes": inv.astype(dt),
                    "uniq": uniq_bits.view(np.float64).copy()}
    return None


def _cand_f32(x: np.ndarray) -> Optional[Dict[str, Any]]:
    with np.errstate(over="ignore"):
        f = x.astype(np.float32)
    if not _bit_identical(f.astype(np.float64), x):
        return None
    return {"c": "f32", "n": int(x.size), "data": f}


_NUM_CANDIDATES = (_cand_const, _cand_sparse, _cand_affine, _cand_dict,
                   _cand_f32)


def _decode_numeric(p: Dict[str, Any]) -> np.ndarray:
    c = p["c"]
    n = int(p["n"])
    if c == "const":
        return np.repeat(np.asarray(p["v"], dtype=np.float64), n)
    if c == "sparse":
        out = np.zeros(n, dtype=np.float64)
        out[np.asarray(p["idx"])] = np.asarray(p["vals"])
        return out
    if c == "affine":
        codes = np.asarray(p["codes"])
        sent = _SENTINEL[codes.dtype]
        # the EXACT formula the fused device program emits (offset and
        # scale as runtime scalars): bit parity host/device rests on both
        # sides running the same two IEEE f64 ops in the same order
        out = p["offset"] + codes.astype(np.float64) * p["scale"]
        out[codes == sent] = np.nan
        return out
    if c == "dict":
        return np.asarray(p["uniq"])[np.asarray(p["codes"])]
    if c == "f32":
        return np.asarray(p["data"]).astype(np.float64)
    raise ValueError(f"unknown numeric codec {c!r}")


def _encode_numeric(x: np.ndarray, ratio: float) -> Tuple[Any, str]:
    x = np.ascontiguousarray(x, dtype=np.float64)
    if x.size == 0:
        return x, "dense"
    dense_nb = int(x.nbytes)
    best: Optional[Dict[str, Any]] = None
    best_nb = dense_nb
    for gen in _NUM_CANDIDATES:
        try:
            p = gen(x)
        except (ValueError, FloatingPointError):
            p = None
        if p is None:
            continue
        nb = _payload_nbytes(p)
        if nb < best_nb and _bit_identical(_decode_numeric(p), x):
            best, best_nb = p, nb
    if best is None or best_nb > ratio * dense_nb:
        return x, "dense"
    return best, best["c"]


# ---------------------------------------------------------------------------
# CAT / STR candidates


def _encode_cat(codes: np.ndarray, domain: list,
                ratio: float) -> Tuple[Any, str]:
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    if codes.size == 0:
        return (codes, domain), "dense"
    cmax = int(codes.max()) if codes.size else -1
    if int(codes.min()) < -1:
        return (codes, domain), "dense"
    packed = None
    for dt, cap in ((np.uint8, 255), (np.uint16, 65535)):
        if cmax + 1 <= cap:
            packed = (codes + 1).astype(dt)  # NA_CAT (-1) biases to 0
            break
    if packed is None or packed.nbytes > ratio * codes.nbytes:
        return (codes, domain), "dense"
    p = {"c": "catpack", "n": int(codes.size), "codes": packed,
         "domain": list(domain)}
    back = _decode_cat(p)
    if not (np.array_equal(back[0], codes) and back[1] == list(domain)):
        return (codes, domain), "dense"
    return p, "catpack"


def _decode_cat(p: Dict[str, Any]) -> Tuple[np.ndarray, list]:
    codes = np.asarray(p["codes"]).astype(np.int32) - 1
    codes[codes < 0] = NA_CAT
    return codes, list(p["domain"])


def _encode_str(arr: np.ndarray, ratio: float) -> Tuple[Any, str]:
    if arr.size == 0:
        return arr, "dense"
    table: Dict[Any, int] = {}
    codes = np.empty(arr.size, dtype=np.uint32)
    for i, v in enumerate(arr):
        k = table.get(v)
        if k is None:
            k = table[v] = len(table)
        codes[i] = k
    uniq = list(table)
    p = {"c": "strdict", "n": int(arr.size), "codes": codes, "uniq": uniq}
    if _payload_nbytes(p) > ratio * _payload_nbytes(arr):
        return arr, "dense"
    back = _decode_str(p)
    if not all(a == b for a, b in zip(back, arr)):
        return arr, "dense"
    return p, "strdict"


def _decode_str(p: Dict[str, Any]) -> np.ndarray:
    uniq = np.empty(len(p["uniq"]), dtype=object)
    uniq[:] = list(p["uniq"])
    return uniq[np.asarray(p["codes"])]


# ---------------------------------------------------------------------------
# chunk-level entry points


def is_encoded(payload: Any) -> bool:
    """True for an encoded column payload (a codec dict)."""
    return isinstance(payload, dict) and "c" in payload


def is_encoded_chunk(value: Sequence[Any]) -> bool:
    """True when any column payload of a chunk value is encoded."""
    return any(is_encoded(p) for p in value[1])


def encode_chunk(value: Sequence[Any]) -> List[Any]:
    """Encode a tokenized chunk value ``[n, payloads, used_native]`` per
    column; meters ``chunk_codec_total{codec}`` and
    ``chunk_resident_bytes{codec}`` and charges the ledger for the bytes
    actually landed.  Idempotent: already-encoded payloads pass through
    unmetered; with codecs disabled the value returns unchanged."""
    if not codecs_enabled():
        return list(value)
    n, payloads, used_native = value[0], value[1], value[2]
    ratio = min_ratio()
    out: List[Any] = []
    for p in payloads:
        if is_encoded(p):
            out.append(p)
            continue
        if isinstance(p, tuple):  # CAT (codes, domain)
            enc, codec = _encode_cat(p[0], p[1], ratio)
        elif isinstance(p, np.ndarray) and p.dtype == object:
            enc, codec = _encode_str(p, ratio)
        elif isinstance(p, np.ndarray):
            enc, codec = _encode_numeric(p, ratio)
        else:
            enc, codec = p, "dense"
        _CODEC_TOTAL.inc(codec=codec)
        nb = _payload_nbytes(enc)
        _RESIDENT_BYTES.inc(nb, codec=codec)
        _ledger.charge(_ledger.CHUNK_ENCODED_BYTES, nb)
        out.append(enc)
    return [int(n), out, bool(used_native)]


def decode_column(payload: Any) -> Any:
    """Dense payload from any column payload — encoded dicts decode,
    plain payloads pass through untouched."""
    if not is_encoded(payload):
        return payload
    c = payload["c"]
    if c == "catpack":
        return _decode_cat(payload)
    if c == "strdict":
        return _decode_str(payload)
    return _decode_numeric(payload)


def decode_chunk(value: Sequence[Any]) -> List[Any]:
    """Dense chunk value from a possibly-encoded one (idempotent)."""
    if not is_encoded_chunk(value):
        return list(value)
    return [int(value[0]), [decode_column(p) for p in value[1]],
            bool(value[2])]


# ---------------------------------------------------------------------------
# group homogenization — ONE decodable rep per (group, column) so the
# fused executor can emit the decode into the jitted program


def _as_affine(p: Dict[str, Any]):
    """(codes u8/u16, offset, scale, sentinel) view of one encoded
    payload, or None when the codec has no affine reading."""
    c = p["c"]
    if c == "affine":
        codes = np.asarray(p["codes"])
        return codes, float(p["offset"]), float(p["scale"]), \
            _SENTINEL[codes.dtype]
    return None


def group_rep(payloads: Sequence[Any]) -> Tuple:
    """Homogenize one column's per-chunk payloads (dense float64 arrays
    and/or numeric codec dicts) into a single group-level rep:

    - ``("const", value_f64_scalar_array, n)`` — every chunk constant on
      the same bits;
    - ``("affine", codes_u16, offset, scale, 65535)`` — every chunk
      affine on one shared scale, codes rebased to a group offset;
    - ``("dict", codes_u16, uniq_f64)`` — unique 64-bit patterns across
      the group fit 16-bit codes (pure-gather decode);
    - ``("f32", data_f32)`` — every chunk stored f32;
    - ``("dense", data_f64)`` — anything else (including mixed codecs).

    Every non-dense rep is RE-VERIFIED bit-exactly against the per-chunk
    decode before it is returned — regrouping arithmetic (code rebasing,
    table unions) must never weaken the chunk-level contract."""
    dense = [np.ascontiguousarray(decode_column(p), dtype=np.float64)
             for p in payloads]
    full = (np.concatenate(dense) if dense
            else np.empty(0, dtype=np.float64))

    def fallback() -> Tuple:
        return ("dense", full)

    encs = [p for p in payloads if is_encoded(p)]
    if len(encs) != len(payloads) or not encs or full.size == 0:
        return fallback()
    kinds = {p["c"] for p in encs}

    if kinds == {"const"}:
        v0 = _bits(np.asarray(encs[0]["v"], dtype=np.float64))
        if all(np.all(_bits(np.asarray(p["v"], dtype=np.float64)) == v0)
               for p in encs):
            rep = ("const", np.asarray(encs[0]["v"], dtype=np.float64),
                   int(full.size))
            back = np.repeat(rep[1], full.size)
            if _bit_identical(back, full):
                return rep
        return fallback()

    if kinds == {"f32"}:
        data = np.concatenate([np.asarray(p["data"], dtype=np.float32)
                               for p in encs])
        if _bit_identical(data.astype(np.float64), full):
            return ("f32", data)
        return fallback()

    if kinds == {"affine"}:
        views = [_as_affine(p) for p in encs]
        scales = {v[2] for v in views}
        if len(scales) == 1:
            scale = scales.pop()
            off_g = min(v[1] for v in views)
            parts: List[np.ndarray] = []
            ok = True
            for codes, off_c, _s, sent in views:
                shift = (off_c - off_g) / scale if scale else 0.0
                if shift != np.floor(shift):
                    ok = False
                    break
                c16 = codes.astype(np.uint32) + np.uint32(int(shift))
                c16[codes == sent] = 65535
                if c16.max(initial=0) > 65535 or \
                        bool(np.any(c16[codes != sent] >= 65535)):
                    ok = False
                    break
                parts.append(c16.astype(np.uint16))
            if ok:
                codes_g = np.concatenate(parts)
                out = off_g + codes_g.astype(np.float64) * scale
                out[codes_g == 65535] = np.nan
                if _bit_identical(out, full):
                    return ("affine", codes_g, float(off_g), float(scale),
                            65535)
        # fall through: heterogeneous offsets/scales often still share a
        # small value set — try the dict union below
        kinds = {"dict"}

    if kinds <= {"dict", "affine", "const", "sparse", "f32"}:
        uniq_bits = np.unique(_bits(full))
        if uniq_bits.size <= 65536:
            codes_g = np.searchsorted(
                uniq_bits, _bits(full)).astype(np.uint16)
            uniq = uniq_bits.view(np.float64).copy()
            if _bit_identical(uniq[codes_g], full):
                return ("dict", codes_g, uniq)
    return fallback()
