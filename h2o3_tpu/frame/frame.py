"""Columnar distributed Frame — the TPU-native Frame/Vec/Chunk.

Reference design (water/fvec/): a ``Frame`` is a named list of ``Vec``s; each
``Vec`` is one distributed column cut into ``Chunk``s by row ranges with ~23
per-chunk compression codecs chosen at write time (``water/fvec/Chunk.java:35-43``),
plus lazy cached ``RollupStats`` (``water/fvec/RollupStats.java``).

TPU-native redesign:

  * The host-canonical representation of a column is ONE dense numpy array
    (float64 for NUM/TIME with NaN as the NA sentinel — same sentinel the
    reference uses for numeric NAs — int32 codes with -1 for CAT, object array
    for STR). Chunk codecs are pointless on TPU: XLA wants dense, statically
    shaped, contiguous arrays, and HBM is fed by the host in bulk. The
    "compression" that matters (uint8 bin codes for tree training, bfloat16
    activations) happens at the *compute* layer instead.
  * The device representation is produced on demand: columns are padded to a
    multiple of the mesh's data-axis size and sharded row-wise with
    ``NamedSharding(P("data"))`` — a shard is the moral equivalent of a home
    node's chunks (compute moves to data: SURVEY.md §1 invariant).
  * RollupStats stay: lazily computed min/max/mean/sigma/NA-count/isint plus a
    fixed-width histogram, in one jitted reduction, cached per column and
    invalidated on mutation (h2o3_tpu/frame/rollups.py).
"""

from __future__ import annotations

import enum
import itertools
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

#: process-wide monotonic column-version source. Every Column state —
#: fresh construction or in-place mutation (the invalidate_rollups paths) —
#: draws a new number, so a (name, version) pair identifies column DATA
#: uniquely across the process lifetime. The device frame cache
#: (h2o3_tpu/frame/devcache.py) keys host->mesh placements on these stamps.
_COLUMN_VERSIONS = itertools.count(1)


class ColType(enum.Enum):
    """Column types — mirrors the reference's Vec type ids (water/fvec/Vec.java:207-212:
    T_BAD, T_UUID, T_STR, T_NUM, T_CAT, T_TIME)."""

    NUM = "numeric"
    CAT = "categorical"
    TIME = "time"
    STR = "string"
    UUID = "uuid"
    BAD = "bad"  # all-NA column


NA_CAT = np.int32(-1)  # categorical NA sentinel (codes); numeric NA is NaN


class Column:
    """One named, typed column. Host-canonical numpy storage.

    ``data`` dtype by type:
      NUM  -> float64 (NaN = NA)
      CAT  -> int32 codes into ``domain`` (-1 = NA)
      TIME -> float64 milliseconds since epoch (NaN = NA; reference stores int64
              ms, water/fvec/Vec.java — float64 keeps exact ms until year ~287k)
      STR  -> object ndarray of python str / None
      UUID -> object ndarray of str / None
      BAD  -> float64 all-NaN
    """

    __slots__ = ("name", "type", "data", "domain", "_rollups", "version")

    def __init__(
        self,
        name: str,
        data: np.ndarray,
        type: Optional[ColType] = None,
        domain: Optional[List[str]] = None,
    ) -> None:
        if type is None:
            type = _infer_type(data)
        data = _canonicalize(data, type)
        self.name = name
        self.type = type
        self.data = data
        self.domain = list(domain) if domain is not None else None
        self._rollups = None
        self.version = next(_COLUMN_VERSIONS)
        if self.type is ColType.CAT and self.domain is None:
            raise ValueError(f"CAT column {name!r} requires a domain")

    # -- basic shape ---------------------------------------------------------
    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def nrows(self) -> int:
        return len(self)

    # -- type predicates (mirrors Vec.isNumeric/isCategorical/...) -----------
    def is_numeric(self) -> bool:
        return self.type in (ColType.NUM, ColType.TIME)

    def is_categorical(self) -> bool:
        return self.type is ColType.CAT

    def is_string(self) -> bool:
        return self.type is ColType.STR

    def is_time(self) -> bool:
        return self.type is ColType.TIME

    def is_bad(self) -> bool:
        return self.type is ColType.BAD

    def cardinality(self) -> int:
        """Domain size for CAT columns, -1 otherwise (Vec.cardinality())."""
        return len(self.domain) if self.domain is not None else -1

    # -- NA handling ---------------------------------------------------------
    def isna(self) -> np.ndarray:
        if self.type is ColType.CAT:
            return self.data < 0
        if self.type in (ColType.STR, ColType.UUID):
            return np.array([v is None for v in self.data], dtype=bool)
        return np.isnan(self.data)

    def na_count(self) -> int:
        return int(self.isna().sum())

    # -- rollups (lazy cached stats; water/fvec/RollupStats.java) ------------
    @property
    def rollups(self):
        if self._rollups is None:
            from h2o3_tpu.frame.rollups import compute_rollups

            self._rollups = compute_rollups(self)
        return self._rollups

    def invalidate_rollups(self) -> None:
        """Mutation notification: drops cached rollups AND bumps the version
        stamp, so device placements keyed on the old state can never be
        served for the mutated data (devcache invariant)."""
        self._rollups = None
        self.version = next(_COLUMN_VERSIONS)

    def min(self) -> float:
        return self.rollups.min

    def max(self) -> float:
        return self.rollups.max

    def mean(self) -> float:
        return self.rollups.mean

    def sigma(self) -> float:
        return self.rollups.sigma

    def is_int(self) -> bool:
        return self.rollups.is_int

    # -- conversions ---------------------------------------------------------
    def numeric_view(self) -> np.ndarray:
        """float64 view used for device transfer: CAT codes as floats with NaN NAs."""
        if self.type is ColType.CAT:
            out = self.data.astype(np.float64)
            out[self.data < 0] = np.nan
            return out
        if self.type in (ColType.STR, ColType.UUID):
            raise TypeError(f"column {self.name!r} of type {self.type} has no numeric view")
        return self.data

    def as_factor(self) -> "Column":
        """NUM/STR -> CAT conversion (rapids AstAsFactor)."""
        if self.type is ColType.CAT:
            return self
        if self.type in (ColType.STR, ColType.UUID):
            mask = np.array([v is not None for v in self.data], dtype=bool)
            uniq = sorted({str(v) for v in self.data[mask]})
            index = {lv: i for i, lv in enumerate(uniq)}
            codes = np.full(len(self.data), NA_CAT, dtype=np.int32)
            codes[mask] = [index[str(v)] for v in self.data[mask]]
            return Column(self.name, codes, ColType.CAT, uniq)
        vals = self.data
        mask = ~np.isnan(vals)
        uniq = np.unique(vals[mask])
        domain = [_format_level(v) for v in uniq]
        codes = np.full(len(vals), NA_CAT, dtype=np.int32)
        codes[mask] = np.searchsorted(uniq, vals[mask]).astype(np.int32)
        return Column(self.name, codes, ColType.CAT, domain)

    def as_numeric(self) -> "Column":
        """CAT -> NUM conversion (rapids AstAsNumeric): parse levels, else codes."""
        if self.type is not ColType.CAT:
            return Column(self.name, self.numeric_view(), ColType.NUM)
        try:
            lv = np.array([float(d) for d in self.domain], dtype=np.float64)
            out = np.where(self.data >= 0, lv[np.clip(self.data, 0, None)], np.nan)
        except ValueError:
            out = np.where(self.data >= 0, self.data.astype(np.float64), np.nan)
        return Column(self.name, out, ColType.NUM)

    def copy(self) -> "Column":
        return Column(self.name, self.data.copy(), self.type, self.domain)

    def select(self, idx: np.ndarray) -> "Column":
        return Column(self.name, self.data[idx], self.type, self.domain)

    def __repr__(self) -> str:
        dom = f", card={len(self.domain)}" if self.domain is not None else ""
        return f"<Column {self.name!r} {self.type.value} n={len(self)}{dom}>"


def _infer_type(data: np.ndarray) -> ColType:
    data = np.asarray(data)
    if data.dtype == object or data.dtype.kind in "US":
        return ColType.STR
    return ColType.NUM


def _canonicalize(data: Any, type: ColType) -> np.ndarray:
    data = np.asarray(data)
    if type in (ColType.NUM, ColType.TIME, ColType.BAD):
        return np.ascontiguousarray(data, dtype=np.float64)
    if type is ColType.CAT:
        return np.ascontiguousarray(data, dtype=np.int32)
    if type in (ColType.STR, ColType.UUID):
        if data.dtype != object:
            data = data.astype(object)
        return data
    raise ValueError(f"unknown column type {type}")


def _format_level(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class Frame:
    """A named collection of equal-length Columns (water/fvec/Frame.java).

    Supports the core munging surface the reference exposes through Rapids:
    column/row slicing, boolean filtering, cbind/rbind, renaming, NA ops.
    Heavier relational ops (group-by, merge, sort) live in h2o3_tpu/rapids/.
    """

    #: chunk-home layout when this frame's chunks live distributed on the
    #: DKV ring (h2o3_tpu/cluster/frames.py DistFrame overrides per
    #: instance); None marks an ordinary resident frame, and the cluster
    #: fan-outs key their chunk-homed paths off this attribute
    chunk_layout: Optional[Dict[str, Any]] = None

    def __init__(self, columns: Sequence[Column], key: Optional[str] = None) -> None:
        cols = list(columns)
        if cols:
            n = len(cols[0])
            for c in cols:
                if len(c) != n:
                    raise ValueError(
                        f"column {c.name!r} has {len(c)} rows, expected {n}"
                    )
        names = [c.name for c in cols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        self._cols: List[Column] = cols
        self.key = key

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Frame":
        cols = []
        for name, vals in d.items():
            if isinstance(vals, Column):
                c = vals.copy()
                c.name = name
                cols.append(c)
            else:
                arr = np.asarray(vals)
                if arr.dtype == object or arr.dtype.kind in "US":
                    from h2o3_tpu.frame.parse import column_from_strings

                    cols.append(column_from_strings(name, [None if v is None else str(v) for v in arr]))
                else:
                    cols.append(Column(name, arr.astype(np.float64), ColType.NUM))
        return Frame(cols)

    @staticmethod
    def from_pandas(df) -> "Frame":
        return Frame.from_dict({str(c): df[c].to_numpy() for c in df.columns})

    # -- shape ---------------------------------------------------------------
    @property
    def nrows(self) -> int:
        return len(self._cols[0]) if self._cols else 0

    @property
    def ncols(self) -> int:
        return len(self._cols)

    @property
    def names(self) -> List[str]:
        return [c.name for c in self._cols]

    @property
    def types(self) -> Dict[str, ColType]:
        return {c.name: c.type for c in self._cols}

    def col_types(self) -> List[ColType]:
        """Column types in column order. Metadata-only consumers (Rapids
        type predicates, REST listings) call this instead of ``columns``
        so distributed subclasses can answer from their layout without
        materializing."""
        return [c.type for c in self._cols]

    @property
    def columns(self) -> List[Column]:
        return list(self._cols)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.nrows, self.ncols)

    @property
    def version(self) -> Tuple[int, ...]:
        """Per-column version stamps. Two frames with equal (names, version)
        tuples hold identical data; any mutating path produces a fresh
        Column (new stamp) or bumps in place via invalidate_rollups."""
        return tuple(c.version for c in self._cols)

    def __len__(self) -> int:
        return self.nrows

    # -- selection -----------------------------------------------------------
    def col(self, name_or_idx: Union[str, int]) -> Column:
        if isinstance(name_or_idx, int):
            return self._cols[name_or_idx]
        for c in self._cols:
            if c.name == name_or_idx:
                return c
        raise KeyError(f"no column {name_or_idx!r} in {self.names}")

    def __getitem__(self, sel: Any) -> "Frame":
        # fr[col] / fr[[cols]] / fr[bool-mask] / fr[row-slice] / fr[rows, cols]
        if isinstance(sel, tuple) and len(sel) == 2:
            return self.rows(sel[0]).cols(sel[1])
        if isinstance(sel, str):
            return Frame([self.col(sel)])
        if isinstance(sel, (list,)) and sel and isinstance(sel[0], str):
            return Frame([self.col(n) for n in sel])
        if isinstance(sel, np.ndarray) and sel.dtype == bool:
            return self.rows(sel)
        if isinstance(sel, slice):
            return self.rows(sel)
        raise TypeError(f"unsupported selector {sel!r}")

    def cols(self, sel: Any) -> "Frame":
        if sel is None or (isinstance(sel, slice) and sel == slice(None)):
            return self
        if isinstance(sel, (str, int)):
            sel = [sel]
        return Frame([self.col(s) for s in sel])

    def rows(self, sel: Any) -> "Frame":
        if isinstance(sel, slice) or (
            isinstance(sel, np.ndarray) and sel.dtype in (bool, np.bool_)
        ):
            idx = np.arange(self.nrows)[sel]
        else:
            idx = np.asarray(sel, dtype=np.int64)
        return Frame([c.select(idx) for c in self._cols])

    def drop(self, names: Union[str, Iterable[str]]) -> "Frame":
        if isinstance(names, str):
            names = [names]
        names = set(names)
        return Frame([c for c in self._cols if c.name not in names])

    # -- mutation ------------------------------------------------------------
    def add_column(self, col: Column) -> "Frame":
        if col.name in self.names:
            cols = [col if c.name == col.name else c for c in self._cols]
        else:
            cols = self._cols + [col]
        return Frame(cols)

    def rename(self, mapping: Dict[str, str]) -> "Frame":
        cols = []
        for c in self._cols:
            c2 = c.copy()
            c2.name = mapping.get(c.name, c.name)
            cols.append(c2)
        return Frame(cols)

    def cbind(self, other: "Frame") -> "Frame":
        cols = list(self._cols)
        taken = set(self.names)
        for c in other._cols:
            name, i = c.name, 0
            while name in taken:
                name = f"{c.name}{i}"
                i += 1
            c2 = c.copy()
            c2.name = name
            taken.add(name)
            cols.append(c2)
        return Frame(cols)

    def rbind(self, other: "Frame") -> "Frame":
        if self.names != other.names:
            raise ValueError("rbind requires identical column names")
        out = []
        for a, b in zip(self._cols, other._cols):
            if a.type is ColType.CAT or b.type is ColType.CAT:
                a, b = _unify_cat(a), _unify_cat(b)
                domain, amap = _merge_domains(a.domain, b.domain)
                ad = a.data.copy()
                bd = np.where(b.data >= 0, amap[np.clip(b.data, 0, None)], NA_CAT)
                out.append(
                    Column(a.name, np.concatenate([ad, bd.astype(np.int32)]), ColType.CAT, domain)
                )
            elif a.type in (ColType.STR, ColType.UUID):
                out.append(
                    Column(a.name, np.concatenate([a.data, b.data]), a.type)
                )
            else:
                out.append(
                    Column(a.name, np.concatenate([a.data, b.data]), a.type)
                )
        return Frame(out)

    def na_omit(self) -> "Frame":
        mask = np.zeros(self.nrows, dtype=bool)
        for c in self._cols:
            mask |= c.isna()
        return self.rows(~mask)

    # -- numeric matrix for modeling ----------------------------------------
    def to_numpy(self, columns: Optional[Sequence[str]] = None) -> np.ndarray:
        names = list(columns) if columns is not None else self.names
        return np.stack([self.col(n).numeric_view() for n in names], axis=1)

    def to_pandas(self):
        import pandas as pd

        data = {}
        for c in self._cols:
            if c.type is ColType.CAT:
                dom = np.asarray(c.domain + [None], dtype=object)
                data[c.name] = dom[np.where(c.data >= 0, c.data, len(c.domain))]
            else:
                data[c.name] = c.data
        return pd.DataFrame(data)

    def head(self, n: int = 10) -> "Frame":
        return self.rows(slice(0, n))

    def __repr__(self) -> str:
        return f"<Frame {self.nrows}x{self.ncols} {self.names[:8]}{'...' if self.ncols > 8 else ''}>"


def _unify_cat(c: Column) -> Column:
    return c if c.type is ColType.CAT else c.as_factor()


def _merge_domains(a: List[str], b: List[str]) -> Tuple[List[str], np.ndarray]:
    """Merge categorical domains; returns merged domain and b-code -> merged-code map
    (reference: domain unification during parse, water/parser/Categorical.java)."""
    index = {lv: i for i, lv in enumerate(a)}
    merged = list(a)
    bmap = np.empty(len(b), dtype=np.int32)
    for j, lv in enumerate(b):
        if lv not in index:
            index[lv] = len(merged)
            merged.append(lv)
        bmap[j] = index[lv]
    return merged, bmap
