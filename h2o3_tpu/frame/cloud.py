"""Cloud persist backends: S3, GCS, HDFS(WebHDFS) — pure stdlib.

Reference: ``h2o-persist-s3/.../PersistS3.java``, ``h2o-persist-gcs``,
``h2o-persist-hdfs`` — optional modules registering storage backends
with the PersistManager per URI scheme. This build has no cloud SDKs
baked in, so the backends speak the services' plain HTTP protocols with
the stdlib: AWS Signature V4 is ~40 lines of hmac, GCS is a JSON API,
HDFS is WebHDFS. Endpoints are overridable (``H2O3_TPU_S3_ENDPOINT``
etc.), which is also how the test tier drives them against local fakes
— the wire protocol is identical either way.

Credentials come from the conventional env vars (AWS_ACCESS_KEY_ID /
AWS_SECRET_ACCESS_KEY / AWS_SESSION_TOKEN, GOOGLE_OAUTH_ACCESS_TOKEN).
Anonymous access is attempted when no credentials are set (public
buckets), matching PersistS3's credential-chain fallback.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

from h2o3_tpu.frame.ingest import Persist


def _http(url: str, headers: Optional[dict] = None, timeout: int = 60) -> bytes:
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        # map onto the persist layer's error contract so the REST import
        # handler answers 404/400, not a 500 with a traceback
        if e.code == 404:
            raise FileNotFoundError(url) from e
        raise ValueError(f"cloud storage request failed: HTTP {e.code} "
                         f"for {url}") from e


# ---------------------------------------------------------------------------
# S3 (AWS Signature V4 over the REST API)


def _sigv4_headers(method: str, url: str, region: str, service: str,
                   access_key: str, secret: str,
                   session_token: Optional[str]) -> dict:
    """Minimal SigV4 for GET requests with empty body."""
    parts = urllib.parse.urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(b"").hexdigest()
    headers = {
        "host": parts.netloc,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(
        f"{k}:{headers[k]}\n" for k in sorted(headers))
    # canonical query: sorted, strictly encoded
    q = urllib.parse.parse_qsl(parts.query, keep_blank_values=True)
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(q))
    # the path arrives ALREADY percent-encoded (from _url); S3 forbids
    # double-encoding in the canonical URI
    canonical = "\n".join([
        method, parts.path or "/", cq,
        canonical_headers, signed, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hm(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hm(("AWS4" + secret).encode(), datestamp)
    k = _hm(k, region)
    k = _hm(k, service)
    k = _hm(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    del headers["host"]  # urllib sets it
    return headers


class PersistS3(Persist):
    """s3:// backend over the S3 REST API (PersistS3.java analogue).

    Layout: s3://bucket/key. Endpoint: ``H2O3_TPU_S3_ENDPOINT`` (default
    https://{bucket}.s3.{region}.amazonaws.com); path-style when the
    endpoint is overridden (minio/fakes speak path-style)."""

    scheme = "s3"

    def _endpoint(self, bucket: str) -> Tuple[str, bool]:
        ep = os.environ.get("H2O3_TPU_S3_ENDPOINT")
        if ep:
            return ep.rstrip("/"), True  # path-style
        region = os.environ.get("AWS_REGION", "us-east-1")
        return f"https://{bucket}.s3.{region}.amazonaws.com", False

    def _url(self, bucket: str, key: str, query: str = "") -> str:
        ep, path_style = self._endpoint(bucket)
        base = f"{ep}/{bucket}" if path_style else ep
        url = f"{base}/{urllib.parse.quote(key)}" if key else base
        return url + (f"?{query}" if query else "")

    def _request(self, url: str) -> bytes:
        ak = os.environ.get("AWS_ACCESS_KEY_ID")
        sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
        headers = {}
        if ak and sk:
            region = os.environ.get("AWS_REGION", "us-east-1")
            headers = _sigv4_headers(
                "GET", url, region, "s3", ak, sk,
                os.environ.get("AWS_SESSION_TOKEN"))
        return _http(url, headers)

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        rest = path[len("s3://"):] if path.startswith("s3://") else path
        rest = rest.split("://", 1)[-1] if "://" in rest else rest
        bucket, _, key = rest.partition("/")
        if not bucket:
            raise ValueError(f"s3 path needs a bucket: {path!r}")
        return bucket, key

    def read_bytes(self, path: str) -> bytes:
        bucket, key = self._split(path)
        return self._request(self._url(bucket, key))

    def list(self, path: str) -> List[str]:
        bucket, key = self._split(path)
        if not key or key.endswith("/"):
            keys: List[str] = []
            token = None
            while True:  # follow ListObjectsV2 pagination to the end
                q = ("list-type=2&prefix=" +
                     urllib.parse.quote(key, safe=""))
                if token:
                    q += ("&continuation-token=" +
                          urllib.parse.quote(token, safe=""))
                root = ET.fromstring(self._request(self._url(bucket, "", q)))
                keys += [el.text for el in root.iter()
                         if el.tag.endswith("Key") and el.text
                         and not el.text.endswith("/")]
                token = next(
                    (el.text for el in root.iter()
                     if el.tag.endswith("NextContinuationToken") and el.text),
                    None)
                truncated = next(
                    (el.text for el in root.iter()
                     if el.tag.endswith("IsTruncated")), "false")
                if not token or truncated != "true":
                    break
            if not keys:
                raise FileNotFoundError(f"no objects under {path!r}")
            return [f"s3://{bucket}/{k}" for k in sorted(keys)]
        return [f"s3://{bucket}/{key}"]


class PersistGCS(Persist):
    """gs:// backend over the GCS JSON API (h2o-persist-gcs analogue).
    Endpoint: ``H2O3_TPU_GCS_ENDPOINT`` (default
    https://storage.googleapis.com). Auth: Bearer token from
    ``GOOGLE_OAUTH_ACCESS_TOKEN`` when set, else anonymous."""

    scheme = "gs"

    def _base(self) -> str:
        return os.environ.get(
            "H2O3_TPU_GCS_ENDPOINT", "https://storage.googleapis.com"
        ).rstrip("/")

    def _headers(self) -> dict:
        tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        rest = path.split("://", 1)[-1]
        bucket, _, key = rest.partition("/")
        if not bucket:
            raise ValueError(f"gs path needs a bucket: {path!r}")
        return bucket, key

    def read_bytes(self, path: str) -> bytes:
        bucket, key = self._split(path)
        url = (f"{self._base()}/storage/v1/b/{bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        return _http(url, self._headers())

    def list(self, path: str) -> List[str]:
        bucket, key = self._split(path)
        if not key or key.endswith("/"):
            names: List[str] = []
            token = None
            while True:  # follow nextPageToken pagination to the end
                url = (f"{self._base()}/storage/v1/b/{bucket}/o?prefix="
                       f"{urllib.parse.quote(key, safe='')}")
                if token:
                    url += "&pageToken=" + urllib.parse.quote(token, safe="")
                doc = json.loads(_http(url, self._headers()))
                names += [it["name"] for it in doc.get("items", [])
                          if not it["name"].endswith("/")]
                token = doc.get("nextPageToken")
                if not token:
                    break
            if not names:
                raise FileNotFoundError(f"no objects under {path!r}")
            return [f"gs://{bucket}/{n}" for n in sorted(names)]
        return [f"gs://{bucket}/{key}"]


class PersistHDFS(Persist):
    """hdfs:// backend over WebHDFS (h2o-persist-hdfs analogue).

    hdfs://namenode:port/path is served via the WebHDFS HTTP gateway;
    ``H2O3_TPU_WEBHDFS`` overrides the gateway base URL (default
    http://{namenode}:9870)."""

    scheme = "hdfs"

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        rest = path.split("://", 1)[-1]
        host, _, p = rest.partition("/")
        return host, "/" + p

    def _gateway(self, host: str) -> str:
        gw = os.environ.get("H2O3_TPU_WEBHDFS")
        if gw:
            return gw.rstrip("/")
        name = host.split(":")[0]
        return f"http://{name}:9870"

    def read_bytes(self, path: str) -> bytes:
        host, p = self._split(path)
        url = f"{self._gateway(host)}/webhdfs/v1{urllib.parse.quote(p)}?op=OPEN"
        return _http(url)

    def list(self, path: str) -> List[str]:
        host, p = self._split(path)
        if p.endswith("/"):
            url = (f"{self._gateway(host)}/webhdfs/v1"
                   f"{urllib.parse.quote(p.rstrip('/') or '/')}?op=LISTSTATUS")
            doc = json.loads(_http(url))
            entries = doc["FileStatuses"]["FileStatus"]
            files = [e["pathSuffix"] for e in entries
                     if e.get("type") == "FILE"]
            if not files:
                raise FileNotFoundError(f"no files under {path!r}")
            return [f"hdfs://{host}{p}{n}" for n in sorted(files)]
        return [f"hdfs://{host}{p}"]


def register_cloud_backends() -> None:
    """Install the cloud schemes into the persist registry (the module
    registration PersistManager does for h2o-persist-*)."""
    from h2o3_tpu.frame.ingest import register_persist

    for cls, schemes in ((PersistS3, ("s3", "s3a", "s3n")),
                         (PersistGCS, ("gs", "gcs")),
                         (PersistHDFS, ("hdfs",))):
        for scheme in schemes:
            backend = cls()
            backend.scheme = scheme
            register_persist(backend)


# self-registration at the END of this module: whichever of
# ingest/cloud imports first, the other is far enough along by the time
# this line runs (Persist is defined at ingest's top; everything this
# call needs is above) — so both import orders work
register_cloud_backends()
