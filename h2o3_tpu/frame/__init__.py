from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.frame.parse import parse_csv, parse_setup

__all__ = ["Column", "ColType", "Frame", "parse_csv", "parse_setup"]
