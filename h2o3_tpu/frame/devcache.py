"""Device frame cache — memoized host->mesh placement (the DKV invariant).

The reference platform's performance story rests on data living *in memory,
in place* across jobs: a Frame is parsed once into the DKV and every MRTask
after that touches resident chunks (SURVEY.md §1). The TPU port's analogue
is this cache: the host->mesh transfer of a frame's columns (row-sharded
column dicts, stacked design matrices, tree-booster bin codes, validity
masks) happens ONCE per (data state, layout, mesh), and every later fit or
dispatch on the same unmutated frame reuses the resident device arrays.

Keying: every :class:`~h2o3_tpu.frame.frame.Column` carries a process-wide
monotonic ``version`` stamp, bumped through the same paths that call
``invalidate_rollups`` — so a cache key built from ``(name, version)``
pairs (:func:`frame_token`) identifies column *data*, and any mutation
makes the old key unreachable. Explicit lifecycle eviction rides on the
keyed store: ``KeyedStore.remove/rekey/clear`` (and Cleaner spills) call
:meth:`DeviceFrameCache.invalidate_frame` for the affected frame key.

Memory: entries are LRU in a byte-accounted budget
(``H2O3_TPU_DEVCACHE_BYTES``, default 1 GiB) so device/host pressure
reclaims the least recently used placements first. Hit/miss/evict and
bytes-saved counters flow through the PR 1 telemetry registry.

Chunk codecs (frame/codecs.py) lean on this cache for decode deferral:
chunks rest ENCODED on the DKV ring, and the decoded dense working set
(``group_columns`` host dicts, ``group_rep`` packed-code reps) lives
here — decode is paid at first compute touch and its dense product is
reclaimable under the same byte budget, so at-rest footprint stays at
the encoded size.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

__all__ = [
    "DEVCACHE",
    "DeviceFrameCache",
    "cached",
    "cached_host",
    "device_nbytes",
    "frame_token",
    "mesh_fingerprint",
    "region_token",
]

#: cache traffic by placement kind (frame_table, glm_design, tree_bins, ...)
REQUESTS = telemetry.counter(
    "devcache_requests_total",
    "device frame cache lookups by placement kind",
    labels=("kind", "result"),
)
_EVICTIONS = telemetry.counter(
    "devcache_evictions_total",
    "device frame cache entries dropped",
    labels=("reason",),
)
_BYTES_SAVED = telemetry.counter(
    "devcache_bytes_saved_total",
    "host->device upload bytes avoided by cache hits",
)
_BYTES = telemetry.gauge(
    "devcache_bytes", "device bytes resident in the frame cache"
)
_ENTRIES = telemetry.gauge(
    "devcache_entries", "entries resident in the frame cache"
)

_DEFAULT_BUDGET = 1 << 30  # 1 GiB of device-resident placements


def _env_budget() -> int:
    raw = os.environ.get("H2O3_TPU_DEVCACHE_BYTES")
    if not raw:
        return _DEFAULT_BUDGET
    try:
        return int(raw)
    except ValueError:
        return _DEFAULT_BUDGET


def mesh_fingerprint(mesh) -> Tuple:
    """Hashable identity of a mesh placement: axis layout + device set.

    A placement sharded for one mesh must never be served for another
    (different device count, ids, or platform → different shardings)."""
    devs = tuple(int(d.id) for d in mesh.devices.flat)
    platform = mesh.devices.flat[0].platform if devs else "none"
    return (tuple(mesh.axis_names), mesh.devices.shape, devs, platform)


def frame_token(frame, columns: Optional[Sequence[str]] = None) -> Optional[Tuple]:
    """Data-identity token of (a column subset of) a frame.

    Built from per-column ``(name, version)`` stamps plus the row count;
    versions are globally unique per column state, so equal tokens imply
    byte-identical host data. Returns None for objects without version
    stamps (foreign/duck-typed frames) — callers then skip the cache."""
    if frame is None:
        return None
    try:
        cols = (
            [frame.col(c) for c in columns]
            if columns is not None
            else list(frame.columns)
        )
        token = tuple((c.name, c.version) for c in cols)
        nrows = frame.nrows
    except (AttributeError, KeyError, TypeError):
        return None
    return ("frame", nrows, token)


def region_token(inputs: Sequence[Tuple[Any, Sequence[str]]]) -> Optional[Tuple]:
    """Combined data-identity token over several ``(frame, columns)`` inputs.

    The fusion plan-cache entry point: a fused region reads column subsets of
    one or more frames, and this token — a tuple of per-input
    :func:`frame_token` stamps — identifies the exact device-input state of
    one dispatch. Equal tokens mean every referenced column is byte-identical,
    so per-dispatch input validation (dtype/str checks) can be memoized on
    it. None if any input lacks version stamps (callers then re-validate)."""
    parts = []
    for frame, columns in inputs:
        tok = frame_token(frame, list(columns))
        if tok is None:
            return None
        parts.append(tok)
    return ("region", tuple(parts))


def device_nbytes(value: Any) -> int:
    """Bytes of every array reachable from ``value`` (dict/list/tuple
    nesting and FrameTable-shaped objects with ``arrays`` + ``mask``)."""
    total = 0
    stack = [value]
    while stack:
        v = stack.pop()
        if v is None:
            continue
        if hasattr(v, "nbytes") and hasattr(v, "dtype") and hasattr(v, "shape"):
            total += int(v.nbytes)
        elif isinstance(v, dict):
            stack.extend(v.values())
        elif isinstance(v, (list, tuple)):
            stack.extend(v)
        elif isinstance(getattr(v, "arrays", None), dict):  # FrameTable shape
            stack.extend(v.arrays.values())
            stack.append(getattr(v, "mask", None))
    return total


class _Entry:
    __slots__ = ("value", "nbytes", "kind", "frame_keys")

    def __init__(self, value: Any, nbytes: int, kind: str) -> None:
        self.value = value
        self.nbytes = nbytes
        self.kind = kind
        self.frame_keys: set = set()


class DeviceFrameCache:
    """Process-wide LRU cache of device placements, byte-budgeted.

    ``get_or_put(key, build)`` is the single entry point: the builder runs
    only on a miss, OUTSIDE the lock (a multi-GB device_put must not block
    concurrent lookups); a lost insert race keeps the first entry. Passing
    ``frame_key`` links the entry to a keyed-store frame so DKV
    remove/rekey/clear (and Cleaner spills) can evict it explicitly."""

    def __init__(self, max_bytes: Optional[int] = None) -> None:
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._by_frame_key: Dict[str, set] = {}
        self._bytes = 0
        self._max_bytes = _env_budget() if max_bytes is None else int(max_bytes)

    # -- sizing --------------------------------------------------------------
    def set_max_bytes(self, max_bytes: int) -> None:
        with self._lock:
            self._max_bytes = int(max_bytes)
            self._shrink()
            self._publish()

    @property
    def max_bytes(self) -> int:
        with self._lock:
            return self._max_bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self._max_bytes,
            }

    def kind_bytes(self) -> Dict[str, int]:
        """Resident bytes by placement kind — the chunk-codec bench reads
        this to report the decoded dense working set (``group_columns`` /
        ``group_rep`` entries) separately from device placements."""
        with self._lock:
            out: Dict[str, int] = {}
            for entry in self._entries.values():
                out[entry.kind] = out.get(entry.kind, 0) + entry.nbytes
            return out

    # -- the cache protocol --------------------------------------------------
    def get_or_put(
        self,
        key: Tuple,
        build: Callable[[], Any],
        frame_key: Optional[str] = None,
        kind: str = "table",
    ) -> Any:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._link(entry, key, frame_key)
                REQUESTS.inc(kind=kind, result="hit")
                _BYTES_SAVED.inc(entry.nbytes)
                return entry.value
        REQUESTS.inc(kind=kind, result="miss")
        value = build()  # host->device transfer happens without the lock
        nbytes = device_nbytes(value)
        # the trace whose miss paid the host->device transfer is billed
        # for it (still outside the lock)
        _ledger.charge(_ledger.DEVCACHE_UPLOAD_BYTES, nbytes)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:  # lost a concurrent build race: keep first
                self._entries.move_to_end(key)
                self._link(entry, key, frame_key)  # our lifecycle link still applies
                return entry.value
            entry = _Entry(value, nbytes, kind)
            self._entries[key] = entry
            self._bytes += nbytes
            self._link(entry, key, frame_key)
            self._shrink()
            self._publish()
        return value

    def _link(self, entry: _Entry, key: Tuple, frame_key: Optional[str]) -> None:
        if frame_key:
            entry.frame_keys.add(frame_key)
            self._by_frame_key.setdefault(frame_key, set()).add(key)

    def grow_entry(self, key: Tuple, nbytes: int) -> None:
        """Attribute extra device bytes to a resident entry — e.g. a stacked
        matrix lazily cached ON a resident FrameTable — so the byte budget
        and gauges see the entry's true footprint. No-op once evicted."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            entry.nbytes += int(nbytes)
            self._bytes += int(nbytes)
            self._shrink()
            self._publish()

    # -- eviction ------------------------------------------------------------
    def _drop(self, key: Tuple, reason: str) -> None:
        # caller holds the lock
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._bytes -= entry.nbytes
        for fk in entry.frame_keys:
            keys = self._by_frame_key.get(fk)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_frame_key[fk]
        _EVICTIONS.inc(reason=reason)
        # the trace whose insertion (or invalidation) displaced the entry
        # pays; the ledger and flight-ring locks are leaves, safe under
        # this cache's lock
        _ledger.charge(_ledger.DEVCACHE_EVICTIONS, 1)
        _flight.record(_flight.DEVCACHE, "info", "evict", reason=reason,
                       nbytes=int(entry.nbytes))

    def _shrink(self) -> None:
        # caller holds the lock; never evict the most recent entry — a
        # single over-budget placement must still be usable while resident
        while self._bytes > self._max_bytes and len(self._entries) > 1:
            oldest = next(iter(self._entries))
            self._drop(oldest, reason="lru")

    def invalidate_frame(self, frame_key: str) -> int:
        """Drop every placement linked to a keyed-store frame (DKV
        remove/rekey/clear, Cleaner spill). Returns entries dropped."""
        with self._lock:
            keys = list(self._by_frame_key.get(frame_key, ()))
            for k in keys:
                self._drop(k, reason="invalidate")
            if keys:
                self._publish()
            return len(keys)

    def clear(self) -> None:
        with self._lock:
            for k in list(self._entries):
                self._drop(k, reason="clear")
            self._by_frame_key.clear()
            self._publish()

    def _publish(self) -> None:
        _BYTES.set(self._bytes)
        _ENTRIES.set(len(self._entries))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: The process-wide device frame cache (one per control plane, like the DKV).
DEVCACHE = DeviceFrameCache()


def cached(
    kind: str,
    token: Optional[Tuple],
    extra_key,
    mesh,
    build: Callable[[], Any],
    frame_key: Optional[str] = None,
) -> Any:
    """The one-call memoized-placement pattern every upload site uses:
    bypass (plain build, no counters) when the frame yielded no token,
    else serve from / insert into :data:`DEVCACHE` under
    ``(kind, token, extra_key, mesh fingerprint)``."""
    if token is None:
        return build()
    return DEVCACHE.get_or_put(
        (kind, token, extra_key, mesh_fingerprint(mesh)),
        build,
        frame_key=frame_key,
        kind=kind,
    )


def cached_host(
    kind: str,
    token: Optional[Tuple],
    extra_key,
    build: Callable[[], Any],
    frame_key: Optional[str] = None,
) -> Any:
    """Mesh-free variant of :func:`cached` for host-resident placements —
    e.g. a chunk home's binned-code matrix, which is keyed by data identity
    (layout stamp + bin-edges digest) and never sharded onto a mesh. Same
    store, byte budget, counters, and upload-ledger charging."""
    if token is None:
        return build()
    return DEVCACHE.get_or_put(
        (kind, token, extra_key, "host"),
        build,
        frame_key=frame_key,
        kind=kind,
    )
