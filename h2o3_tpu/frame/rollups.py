"""RollupStats — lazy cached per-column statistics.

Reference: ``water/fvec/RollupStats.java`` computes min/max/mean/sigma/NA
count/isInt plus a histogram in one MRTask on first use and caches the result
under a rollup key; any mutation invalidates it.

TPU-native note: columns are host-canonical float64 numpy, and rollups must be
float64-exact (TIME columns hold epoch-milliseconds ~1.6e12 — float32 would be
off by tens of seconds). JAX here runs with x64 disabled for TPU-native
compute, so the rollup pass runs in numpy on the host where the canonical data
already lives; it is a single streaming pass and is memory-bandwidth bound
either way. Device-side (float32) reductions belong to the compute layer
(h2o3_tpu/compute/mapreduce.py), which always carries explicit masks.
Cached on the Column object, invalidated by ``Column.invalidate_rollups()``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column


@dataclass
class RollupStats:
    min: float
    max: float
    mean: float
    sigma: float
    na_count: int
    zero_count: int
    is_int: bool
    histogram: Optional[np.ndarray] = None  # lazy, via histogram()
    checksum: float = 0.0


def compute_rollups(col: Column) -> RollupStats:
    if col.type in (ColType.STR, ColType.UUID):
        na = col.na_count()
        return RollupStats(np.nan, np.nan, np.nan, np.nan, na, 0, False)
    x = col.numeric_view()
    if x.size == 0:
        return RollupStats(np.nan, np.nan, np.nan, np.nan, 0, 0, True)
    ok = ~np.isnan(x)
    n = int(ok.sum())
    if n == 0:
        return RollupStats(np.nan, np.nan, np.nan, np.nan, x.size, 0, True)
    v = x[ok]
    return RollupStats(
        float(v.min()),
        float(v.max()),
        float(v.mean()),
        float(v.std(ddof=1)) if n > 1 else 0.0,
        x.size - n,
        int((v == 0).sum()),
        bool(np.all(np.floor(v) == v)),
        checksum=float(v.sum()),
    )


def histogram(col: Column, nbins: int = 64) -> np.ndarray:
    """Fixed-width histogram over [min, max] (RollupStats lazy histogram)."""
    r = col.rollups
    x = col.numeric_view()
    ok = ~np.isnan(x)
    if not np.any(ok) or not np.isfinite(r.min):
        return np.zeros(nbins, dtype=np.int64)
    span = max(r.max - r.min, 1e-300)
    idx = np.clip(((x[ok] - r.min) / span * nbins).astype(np.int64), 0, nbins - 1)
    return np.bincount(idx, minlength=nbins)
