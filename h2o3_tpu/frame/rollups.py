"""RollupStats — lazy cached per-column statistics.

Reference: ``water/fvec/RollupStats.java`` computes min/max/mean/sigma/NA
count/isInt plus a histogram in one MRTask on first use and caches the result
under a rollup key; any mutation invalidates it.

TPU-native note: columns are host-canonical float64 numpy, and rollups must be
float64-exact (TIME columns hold epoch-milliseconds ~1.6e12 — float32 would be
off by tens of seconds). JAX here runs with x64 disabled for TPU-native
compute, so the rollup pass runs in numpy on the host where the canonical data
already lives; it is a single streaming pass and is memory-bandwidth bound
either way. Device-side (float32) reductions belong to the compute layer
(h2o3_tpu/compute/mapreduce.py), which always carries explicit masks.
Cached on the Column object, invalidated by ``Column.invalidate_rollups()``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column


@dataclass
class RollupStats:
    min: float
    max: float
    mean: float
    sigma: float
    na_count: int
    zero_count: int
    is_int: bool
    histogram: Optional[np.ndarray] = None  # lazy, via histogram()
    checksum: float = 0.0


def compute_rollups(col: Column) -> RollupStats:
    if col.type in (ColType.STR, ColType.UUID):
        na = col.na_count()
        return RollupStats(np.nan, np.nan, np.nan, np.nan, na, 0, False)
    x = col.numeric_view()
    if x.size == 0:
        return RollupStats(np.nan, np.nan, np.nan, np.nan, 0, 0, True)
    ok = ~np.isnan(x)
    n = int(ok.sum())
    if n == 0:
        return RollupStats(np.nan, np.nan, np.nan, np.nan, x.size, 0, True)
    v = x[ok]
    return RollupStats(
        float(v.min()),
        float(v.max()),
        float(v.mean()),
        float(v.std(ddof=1)) if n > 1 else 0.0,
        x.size - n,
        int((v == 0).sum()),
        bool(np.all(np.floor(v) == v)),
        checksum=float(v.sum()),
    )


# ---------------------------------------------------------------------------
# codec-aware rollups: stats straight off ENCODED chunk payloads
#
# A chunk-homed column rests encoded on the DKV ring (frame/codecs.py);
# computing its rollups must not force the dense working set back into
# host memory.  Each codec yields its moments from its own small tables:
# const is O(1), sparse touches only the stored non-zeros, affine/dict
# reduce a bincount over the (≤64Ki) value table, f32/dense stream one
# transient chunk at a time — the full column is never concatenated.
# min/max/na/zero/is_int are exact; mean/sigma merge per-chunk partial
# moments (Chan et al.) and can differ from the single-pass dense result
# in final-ulp rounding — rollups sit OUTSIDE the codec layer's strict
# bit-identity contract (that covers materialization, map_reduce,
# dist_hist, and Rapids results).


def _weighted_moments(
    vals: np.ndarray, counts: np.ndarray
) -> Tuple[int, int, int, float, float, float, float, bool]:
    """Moments of a value table with multiplicities (affine/dict codecs):
    (n_valid, na, zero, mn, mx, mean, m2, is_int)."""
    vals = np.asarray(vals, dtype=np.float64)
    counts = np.asarray(counts, dtype=np.int64)
    ok = ~np.isnan(vals)
    na = int(counts[~ok].sum())
    v, c = vals[ok], counts[ok]
    live = c > 0
    v, c = v[live], c[live]
    n = int(c.sum())
    if n == 0:
        return 0, na, 0, np.nan, np.nan, np.nan, 0.0, True
    mean = float((v * c).sum() / n)
    m2 = float((c * (v - mean) ** 2).sum())
    return (n, na, int(c[v == 0].sum()), float(v.min()), float(v.max()),
            mean, m2, bool(np.all(np.floor(v) == v)))


def _dense_moments(
    x: np.ndarray,
) -> Tuple[int, int, int, float, float, float, float, bool]:
    ok = ~np.isnan(x)
    n = int(ok.sum())
    if n == 0:
        return 0, int(x.size), 0, np.nan, np.nan, np.nan, 0.0, True
    v = np.asarray(x[ok], dtype=np.float64)
    mean = float(v.mean())
    return (n, int(x.size - n), int((v == 0).sum()), float(v.min()),
            float(v.max()), mean, float(((v - mean) ** 2).sum()),
            bool(np.all(np.floor(v) == v)))


def _payload_moments(payload):
    """Per-chunk moments without a dense copy where the codec allows."""
    if isinstance(payload, dict):
        c = payload.get("c")
        if c == "const":
            v = float(payload["v"][0])
            n = int(payload["n"])
            if np.isnan(v):
                return 0, n, 0, np.nan, np.nan, np.nan, 0.0, True
            return (n, 0, n if v == 0 else 0, v, v, v, 0.0,
                    bool(np.floor(v) == v))
        if c == "sparse":
            n = int(payload["n"])
            vals = np.asarray(payload["vals"], dtype=np.float64)
            nz = n - vals.size  # background +0.0 entries
            tv = np.concatenate([vals, np.zeros(1)])
            tc = np.concatenate(
                [np.ones(vals.size, dtype=np.int64), np.asarray([nz])])
            return _weighted_moments(tv, tc)
        if c == "affine":
            codes = payload["codes"]
            sent = int(np.iinfo(codes.dtype).max)
            counts = np.bincount(codes.astype(np.int64),
                                 minlength=sent + 1)
            vals = (float(payload["offset"])
                    + np.arange(sent + 1, dtype=np.float64)
                    * float(payload["scale"]))
            vals[sent] = np.nan  # the reserved NA sentinel
            return _weighted_moments(vals, counts)
        if c == "dict":
            codes = payload["codes"]
            uniq = np.asarray(payload["uniq"], dtype=np.float64)
            counts = np.bincount(codes.astype(np.int64),
                                 minlength=uniq.size)
            return _weighted_moments(uniq, counts)
        if c == "f32":
            return _dense_moments(
                np.asarray(payload["data"], dtype=np.float64))
        # unknown codec: literal decode, still one chunk at a time
        from h2o3_tpu.frame import codecs as _codecs

        return _dense_moments(
            np.asarray(_codecs.decode_column(payload), dtype=np.float64))
    return _dense_moments(np.asarray(payload, dtype=np.float64))


def payload_rollups(payloads: Sequence) -> RollupStats:
    """RollupStats for one numeric/TIME column from its per-chunk
    payloads (encoded dicts or dense f64 arrays), merging per-chunk
    partial moments — no whole-column dense materialization."""
    n = na = zero = 0
    mn, mx = np.inf, -np.inf
    mean = 0.0
    m2 = 0.0
    is_int = True
    for p in payloads:
        cn, cna, czero, cmn, cmx, cmean, cm2, cint = _payload_moments(p)
        na += cna
        zero += czero
        if cn == 0:
            continue
        mn, mx = min(mn, cmn), max(mx, cmx)
        is_int = is_int and cint
        if n == 0:
            n, mean, m2 = cn, cmean, cm2
        else:
            tot = n + cn
            delta = cmean - mean
            m2 = m2 + cm2 + delta * delta * n * cn / tot
            mean = mean + delta * cn / tot
            n = tot
    if n == 0:
        return RollupStats(np.nan, np.nan, np.nan, np.nan, na, 0, True)
    sigma = float(np.sqrt(m2 / (n - 1))) if n > 1 else 0.0
    return RollupStats(mn, mx, mean, sigma, na, zero, is_int,
                       checksum=mean * n)


def histogram(col: Column, nbins: int = 64) -> np.ndarray:
    """Fixed-width histogram over [min, max] (RollupStats lazy histogram)."""
    r = col.rollups
    x = col.numeric_view()
    ok = ~np.isnan(x)
    if not np.any(ok) or not np.isfinite(r.min):
        return np.zeros(nbins, dtype=np.int64)
    span = max(r.max - r.min, 1e-300)
    idx = np.clip(((x[ok] - r.min) / span * nbins).astype(np.int64), 0, nbins - 1)
    return np.bincount(idx, minlength=nbins)
