"""Rapids runtime: Val types, Session, evaluator.

Reference: ``water/rapids/Val.java`` (NUM/NUMS/STR/STRS/FRAME/ROW/FUN),
``water/rapids/Session.java`` (per-client session with ref-counted temp
frames), ``water/rapids/ast/AstExec`` dispatch.

The evaluator is a small tree-walker: special forms (assignment, lambdas)
are handled here; everything else evaluates its args and dispatches into the
primitive registry (h2o3_tpu/rapids/prims).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Union

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.rapids import parser as P
from h2o3_tpu.rapids.parser import (
    AstExec,
    AstFun,
    AstId,
    AstNode,
    AstNum,
    AstNumList,
    AstStr,
    AstStrList,
)


class Val:
    """Tagged runtime value (water/rapids/Val.java)."""

    NUM, NUMS, STR, STRS, FRAME, ROW, FUN, MODEL, KEYED = range(9)

    __slots__ = ("kind", "value")

    def __init__(self, kind: int, value: Any) -> None:
        self.kind = kind
        self.value = value

    # -- constructors --------------------------------------------------------
    @staticmethod
    def num(x: float) -> "Val":
        return Val(Val.NUM, float(x))

    @staticmethod
    def nums(xs) -> "Val":
        return Val(Val.NUMS, np.asarray(xs, dtype=np.float64))

    @staticmethod
    def str_(s: str) -> "Val":
        return Val(Val.STR, s)

    @staticmethod
    def strs(ss) -> "Val":
        return Val(Val.STRS, list(ss))

    @staticmethod
    def frame(fr: Frame) -> "Val":
        return Val(Val.FRAME, fr)

    @staticmethod
    def row(xs, names=None) -> "Val":
        return Val(Val.ROW, (np.asarray(xs, dtype=np.float64), names))

    @staticmethod
    def fun(f) -> "Val":
        return Val(Val.FUN, f)

    @staticmethod
    def model(m) -> "Val":
        return Val(Val.MODEL, m)

    @staticmethod
    def keyed(obj) -> "Val":
        return Val(Val.KEYED, obj)

    # -- coercions (Val.getNum/getFrame/... semantics) -----------------------
    def as_num(self) -> float:
        if self.kind == Val.NUM:
            return self.value
        if self.kind == Val.FRAME and self.value.ncols == 1 and self.value.nrows == 1:
            return float(self.value.col(0).numeric_view()[0])
        if self.kind == Val.NUMS and len(self.value) == 1:
            return float(self.value[0])
        raise TypeError(f"expected a number, got {self!r}")

    def as_int(self) -> int:
        return int(self.as_num())

    def as_str(self) -> str:
        if self.kind == Val.STR:
            return self.value
        if self.kind == Val.STRS and len(self.value) == 1:
            return self.value[0]
        raise TypeError(f"expected a string, got {self!r}")

    def as_frame(self) -> Frame:
        if self.kind == Val.FRAME:
            return self.value
        if self.kind == Val.NUM:
            return Frame([Column("C1", np.array([self.value]), ColType.NUM)])
        if self.kind == Val.NUMS:
            return Frame([Column("C1", self.value, ColType.NUM)])
        raise TypeError(f"expected a frame, got {self!r}")

    def as_nums(self) -> np.ndarray:
        if self.kind == Val.NUMS:
            return self.value
        if self.kind == Val.NUM:
            return np.array([self.value], dtype=np.float64)
        raise TypeError(f"expected numbers, got {self!r}")

    def as_strs(self) -> List[str]:
        if self.kind == Val.STRS:
            return self.value
        if self.kind == Val.STR:
            return [self.value]
        raise TypeError(f"expected strings, got {self!r}")

    def is_frame(self) -> bool:
        return self.kind == Val.FRAME

    def is_num(self) -> bool:
        return self.kind == Val.NUM

    def is_str(self) -> bool:
        return self.kind == Val.STR

    def is_fun(self) -> bool:
        return self.kind == Val.FUN

    def as_model(self):
        """Val.getModel — a MODEL val, or a str/id naming a model in the
        DKV (h2o-py serializes ModelBase args as bare model ids)."""
        if self.kind == Val.MODEL:
            return self.value
        if self.kind in (Val.STR, Val.KEYED):
            from h2o3_tpu.models.framework import Model

            obj = self.value if self.kind == Val.KEYED else DKV.get(self.value)
            if isinstance(obj, Model):
                return obj
        raise TypeError(f"expected a model, got {self!r}")

    def __repr__(self) -> str:
        names = {0: "num", 1: "nums", 2: "str", 3: "strs", 4: "frame",
                 5: "row", 6: "fun", 7: "model", 8: "keyed"}
        return f"<Val:{names[self.kind]} {self.value!r}>"


class Session:
    """Per-client rapids session with temp-frame lifetime tracking
    (water/rapids/Session.java — ref-counted temps, end() sweeps them)."""

    _ids = itertools.count()

    def __init__(self, session_id: Optional[str] = None) -> None:
        self.id = session_id or f"session_{next(Session._ids)}"
        self.frames: Dict[str, Frame] = {}
        self.temps: List[str] = []

    def lookup(self, key: str) -> Optional[Frame]:
        if key in self.frames:
            return self.frames[key]
        obj = DKV.get(key)
        return obj if isinstance(obj, Frame) else None

    def assign(self, key: str, fr: Frame, temp: bool = False) -> Frame:
        fr.key = key
        self.frames[key] = fr
        DKV.put(key, fr)
        if temp and key not in self.temps:
            self.temps.append(key)
        return fr

    def remove(self, key: str) -> None:
        self.frames.pop(key, None)
        DKV.remove(key)
        if key in self.temps:
            self.temps.remove(key)

    def end(self) -> int:
        """Sweep temps (Session.end). A temp read-locked by a running
        training job is skipped (Lockable) — aborting the sweep on it
        would leak every remaining temp."""
        n = len(self.temps)
        for key in list(self.temps):
            try:
                self.remove(key)
            except ValueError:
                self.frames.pop(key, None)  # in use: leave it in the DKV
        self.temps.clear()
        return n


class Env:
    """Lexical environment for lambda application (water/rapids/Env.java)."""

    def __init__(self, session: Session, parent: Optional["Env"] = None) -> None:
        self.session = session
        self.parent = parent
        self.vars: Dict[str, Val] = {}

    def lookup(self, name: str) -> Optional[Val]:
        env: Optional[Env] = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        return None


class RapidsError(ValueError):
    pass


def parse_rapids(text: str) -> AstNode:
    return P.parse(text)


def exec_rapids(text: str, session: Optional[Session] = None) -> Val:
    """Parse + execute one rapids expression (Rapids.exec, Rapids.java:49)."""
    import time

    from h2o3_tpu.rapids import fusion

    session = session or Session()
    fusion.begin_eval()
    start = time.perf_counter()
    result = eval_ast(parse_rapids(text), Env(session))
    fusion.observe_eval(time.perf_counter() - start)
    return result


def eval_ast(node: AstNode, env: Env) -> Val:
    if isinstance(node, AstNum):
        return Val.num(node.value)
    if isinstance(node, AstStr):
        return Val.str_(node.value)
    if isinstance(node, AstNumList):
        return Val.nums(node.values)
    if isinstance(node, AstStrList):
        return Val.strs(node.values)
    if isinstance(node, AstFun):
        return Val.fun(_Closure(node, env))
    if isinstance(node, AstId):
        return _eval_id(node.name, env)
    if isinstance(node, AstExec):
        return _eval_exec(node, env)
    raise RapidsError(f"cannot evaluate {node!r}")


def _eval_id(name: str, env: Env) -> Val:
    if name == "_":  # placeholder / absent-argument marker used by clients
        return Val.num(float("nan"))
    bound = env.lookup(name)
    if bound is not None:
        return bound
    fr = env.session.lookup(name)
    if fr is not None:
        return Val.frame(fr)
    obj = DKV.get(name)
    if obj is not None:  # DKV ids beyond frames: models, segment models
        from h2o3_tpu.models.framework import Model

        return Val.model(obj) if isinstance(obj, Model) else Val.keyed(obj)
    from h2o3_tpu.rapids.prims import PRIMS

    if name in PRIMS:
        return Val.fun(PRIMS[name])
    raise RapidsError(f"unknown identifier {name!r}")


def _eval_exec(node: AstExec, env: Env) -> Val:
    from h2o3_tpu.rapids.prims import PRIMS

    # resolve the operator
    if isinstance(node.op, AstId):
        op_name = node.op.name
        if op_name in ("tmp=", "=", "assign"):
            # AstAssign registers as "assign"; "=" is the legacy spelling
            return _eval_assign("=" if op_name == "assign" else op_name,
                                node.args, env)
        prim = PRIMS.get(op_name)
        if prim is not None:
            from h2o3_tpu.rapids import fusion

            fused = fusion.try_fuse(node, env)
            if fused is not None:
                return fused
            args = [eval_ast(a, env) for a in node.args]
            return prim(env, args)
        fn_val = env.lookup(op_name) or (
            Val.frame(env.session.lookup(op_name)) if env.session.lookup(op_name) else None
        )
        if fn_val is None:
            raise RapidsError(f"unknown function {op_name!r}")
    else:
        fn_val = eval_ast(node.op, env)
    args = [eval_ast(a, env) for a in node.args]
    if fn_val.is_fun():
        return apply_fun(fn_val, args, env)
    raise RapidsError(f"{fn_val!r} is not callable")


def _eval_assign(op: str, args: List[AstNode], env: Env) -> Val:
    """(tmp= key expr) — session temp; (= key expr) — global assign
    (rapids/ast/prims/assign/AstTmpAssign, AstAssign)."""
    if len(args) != 2 or not isinstance(args[0], AstId):
        raise RapidsError(f"({op} key expr) expects an identifier key")
    key = args[0].name
    val = eval_ast(args[1], env)
    fr = val.as_frame()
    env.session.assign(key, fr, temp=(op == "tmp="))
    return Val.frame(fr)


class _Closure:
    """User lambda (AstFunction): params + body + defining env."""

    def __init__(self, node: AstFun, env: Env) -> None:
        self.node = node
        self.env = env

    def __call__(self, env: Env, args: List[Val]) -> Val:
        if len(args) != len(self.node.params):
            raise RapidsError(
                f"lambda expects {len(self.node.params)} args, got {len(args)}"
            )
        inner = Env(env.session, parent=self.env)
        for name, val in zip(self.node.params, args):
            inner.vars[name] = val
        return eval_ast(self.node.body, inner)


def apply_fun(fn: Val, args: List[Val], env: Env) -> Val:
    return fn.value(env, args)
