"""Rapids — the dataframe munging DSL.

Reference: ``water/rapids/`` — a Lisp-like AST language (``Rapids.java:19-51``)
with ~200 primitives under ``rapids/ast/prims/{mungers,math,reducers,...}``,
interpreted server-side against distributed Frames; Python/R clients compile
dataframe expressions to these ASTs (``h2o-py/h2o/expr.py``).

TPU-native redesign: same wire syntax and primitive inventory (SURVEY.md
Appendix A), interpreted against the host-canonical columnar Frame.  Munging
is host-side, memory-bound work over dense numpy columns (the reference's
MRTask munging is likewise CPU work close to the data); the *device* path is
reserved for the ML compute layer (h2o3_tpu/compute, h2o3_tpu/models) where
the FLOPs are.  Big reducers transparently ride the shard_map/psum primitive
when a mesh is active.
"""

from h2o3_tpu.rapids.runtime import Session, Val, exec_rapids, parse_rapids

__all__ = ["Session", "Val", "exec_rapids", "parse_rapids"]
