"""Rapids AST parser — the Lisp-ish expression syntax.

Reference grammar (``water/rapids/Rapids.java:19-40``)::

    expr  := '(' op arg* ')'            function application
    arg   := expr | num | string | numlist | strlist | id | fun
    num   := [-+0-9.eE]+  | NaN
    string:= "..." | '...'
    numlist := '[' (num | num:count | num:count:stride)* ']'
    strlist := '[' string* ']'
    fun   := '{' id* '.' expr '}'       lambda (AstFunction)
    id    := anything else (frame key / symbol / builtin name)

Produces plain-python AST nodes consumed by h2o3_tpu/rapids/runtime.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple, Union

import numpy as np


@dataclass
class AstNum:
    value: float


@dataclass
class AstStr:
    value: str


@dataclass
class AstId:
    name: str


@dataclass
class AstNumList:
    # expanded host array; ranges like 0:4 / 0:4:2 expand at parse time
    values: np.ndarray


@dataclass
class AstStrList:
    values: List[str]


@dataclass
class AstExec:
    op: "AstNode"
    args: List["AstNode"]


@dataclass
class AstFun:
    params: List[str]
    body: "AstNode"


AstNode = Union[AstNum, AstStr, AstId, AstNumList, AstStrList, AstExec, AstFun]


class RapidsParseError(ValueError):
    pass


class _Scanner:
    def __init__(self, text: str) -> None:
        self.text = text
        self.pos = 0

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def next(self) -> str:
        ch = self.peek()
        self.pos += 1
        return ch

    def skip_ws(self) -> None:
        # note: peek() returns "" at EOF and "" is a substring of anything,
        # so the emptiness check must come first
        while self.peek() and self.peek() in " \t\n\r,":
            self.pos += 1

    def token(self) -> str:
        """Read a bare token (number / id) up to a delimiter."""
        start = self.pos
        while self.peek() and self.peek() not in " \t\n\r,()[]{}\"'":
            self.pos += 1
        return self.text[start : self.pos]

    def string(self) -> str:
        quote = self.next()
        out = []
        while True:
            ch = self.next()
            if not ch:
                raise RapidsParseError("unterminated string literal")
            if ch == "\\":
                nxt = self.next()
                out.append({"n": "\n", "t": "\t", "\\": "\\", quote: quote}.get(nxt, nxt))
            elif ch == quote:
                return "".join(out)
            else:
                out.append(ch)


def _parse_number(tok: str) -> float:
    if tok in ("NaN", "nan", "NA"):
        return float("nan")
    return float(tok)


def _is_number(tok: str) -> bool:
    if tok in ("NaN", "nan", "NA"):
        return True
    try:
        float(tok)
        return True
    except ValueError:
        return False


def parse(text: str) -> AstNode:
    sc = _Scanner(text)
    node = _parse_one(sc)
    sc.skip_ws()
    if sc.peek():
        raise RapidsParseError(f"trailing input at {sc.pos}: {sc.text[sc.pos:sc.pos+20]!r}")
    return node


def _parse_one(sc: _Scanner) -> AstNode:
    sc.skip_ws()
    ch = sc.peek()
    if not ch:
        raise RapidsParseError("unexpected end of input")
    if ch == "(":
        sc.next()
        op = _parse_one(sc)
        args: List[AstNode] = []
        while True:
            sc.skip_ws()
            if sc.peek() == ")":
                sc.next()
                return AstExec(op, args)
            if not sc.peek():
                raise RapidsParseError("unterminated (")
            args.append(_parse_one(sc))
    if ch == "[":
        return _parse_list(sc)
    if ch == "{":
        return _parse_fun(sc)
    if ch in "\"'":
        return AstStr(sc.string())
    tok = sc.token()
    if not tok:
        raise RapidsParseError(f"unexpected char {ch!r} at {sc.pos}")
    if _is_number(tok):
        return AstNum(_parse_number(tok))
    return AstId(tok)


def _parse_list(sc: _Scanner) -> Union[AstNumList, AstStrList]:
    sc.next()  # [
    nums: List[np.ndarray] = []
    strs: List[str] = []
    while True:
        sc.skip_ws()
        ch = sc.peek()
        if ch == "]":
            sc.next()
            break
        if not ch:
            raise RapidsParseError("unterminated [")
        if ch in "\"'":
            strs.append(sc.string())
            continue
        tok = sc.token()
        if not tok:
            raise RapidsParseError(f"bad list element at {sc.pos}")
        nums.append(_expand_range(tok))
    if strs and nums:
        raise RapidsParseError("mixed numeric/string list")
    if strs:
        return AstStrList(strs)
    flat = np.concatenate(nums) if nums else np.empty(0, dtype=np.float64)
    return AstNumList(flat)


def _expand_range(tok: str) -> np.ndarray:
    """``base`` | ``base:count`` | ``base:count:stride`` (AstNumList ranges)."""
    parts = tok.split(":")
    if len(parts) == 1:
        return np.array([_parse_number(parts[0])], dtype=np.float64)
    base = _parse_number(parts[0])
    count = int(_parse_number(parts[1]))
    stride = _parse_number(parts[2]) if len(parts) == 3 else 1.0
    if count < 0:
        raise RapidsParseError(f"negative range count in {tok!r}")
    return base + stride * np.arange(count, dtype=np.float64)


def canonical_sexpr(node: AstNode) -> str:
    """Deterministic S-expression serialization of an AST subtree.

    The fusion pass keys compiled column-programs on this string (plus the
    input schema), so two textually different but structurally identical
    expressions share one compiled plan. Number literals serialize through
    ``repr(float)`` (shortest round-trip form), strings are quoted/escaped,
    lists expand to their parsed elements — whitespace and range-syntax
    differences in the source text cannot split the cache.
    """
    if isinstance(node, AstNum):
        return repr(node.value)
    if isinstance(node, AstStr):
        return '"' + node.value.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(node, AstId):
        return node.name
    if isinstance(node, AstNumList):
        return "[" + " ".join(repr(float(v)) for v in node.values) + "]"
    if isinstance(node, AstStrList):
        return "[" + " ".join(
            '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
            for s in node.values
        ) + "]"
    if isinstance(node, AstExec):
        parts = [canonical_sexpr(node.op)] + [canonical_sexpr(a) for a in node.args]
        return "(" + " ".join(parts) + ")"
    if isinstance(node, AstFun):
        return "{" + " ".join(node.params) + " . " + canonical_sexpr(node.body) + "}"
    raise RapidsParseError(f"cannot serialize {node!r}")


def _parse_fun(sc: _Scanner) -> AstFun:
    sc.next()  # {
    params: List[str] = []
    while True:
        sc.skip_ws()
        if sc.peek() == ".":
            sc.next()
            break
        if not sc.peek() or sc.peek() == "}":
            raise RapidsParseError("lambda missing '.' separator")
        tok = sc.token()
        if not tok:
            raise RapidsParseError("bad lambda parameter")
        params.append(tok)
    body = _parse_one(sc)
    sc.skip_ws()
    if sc.next() != "}":
        raise RapidsParseError("unterminated {")
    return AstFun(params, body)
