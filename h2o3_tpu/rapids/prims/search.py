"""Rapids search prims (5).

Reference: ``water/rapids/ast/prims/search/`` — Match Which WhichMax WhichMin.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val


@prim("match")
def match(env, args):
    """(match fr table nomatch start_index) — positions of values in table
    (AstMatch; R match semantics, 1-based by default via start_index)."""
    fr = args[0].as_frame()
    table = args[1]
    nomatch = args[2].as_num() if len(args) > 2 else float("nan")
    start = int(args[3].as_num()) if len(args) > 3 else 1
    c = fr.col(0)
    if table.kind in (Val.STRS, Val.STR):
        tab = table.as_strs()
        index = {}
        for i, v in enumerate(tab):  # R match: FIRST occurrence wins
            index.setdefault(v, i + start)
        if c.type is ColType.CAT:
            dom_map = np.array(
                [index.get(d, np.nan) for d in c.domain] + [np.nan], dtype=np.float64
            )
            out = dom_map[np.where(c.data >= 0, c.data, len(c.domain))]
        elif c.type in (ColType.STR, ColType.UUID):
            out = np.array([index.get(v, np.nan) if v is not None else np.nan for v in c.data])
        else:
            raise RapidsError("match: string table against numeric column")
    else:
        tab = table.as_nums()
        index = {}
        for i, v in enumerate(tab):
            index.setdefault(v, i + start)
        d = numeric_data(c)
        out = np.array([index.get(v, np.nan) for v in d])
    out = np.where(np.isnan(out), nomatch, out)
    return Val.frame(Frame([Column(c.name, out, ColType.NUM)]))


@prim("which")
def which(env, args):
    """(which fr) — row numbers where the (boolean) column is nonzero."""
    fr = args[0].as_frame()
    d = numeric_data(fr.col(0))
    idx = np.nonzero(~np.isnan(d) & (d != 0))[0].astype(np.float64)
    return Val.frame(Frame([Column("which", idx, ColType.NUM)]))


def _which_extreme(env, args, arg_fn, name):
    fr = args[0].as_frame()
    na_rm = bool(args[1].as_num()) if len(args) > 1 else True
    axis = int(args[2].as_num()) if len(args) > 2 else 0
    mat = np.stack([numeric_data(c) for c in fr.columns], axis=1)
    with np.errstate(all="ignore"):
        if axis == 0:
            out = np.array(
                [
                    np.nan
                    if np.all(np.isnan(mat[:, j]))
                    else float(arg_fn(np.nan_to_num(mat[:, j], nan=-np.inf if name == "max" else np.inf)))
                    for j in range(mat.shape[1])
                ]
            )
            return Val.frame(Frame([Column(c.name, np.array([out[j]]), ColType.NUM) for j, c in enumerate(fr.columns)]))
        filled = np.nan_to_num(mat, nan=-np.inf if name == "max" else np.inf)
        out = arg_fn(filled, axis=1).astype(np.float64)
        all_na = np.all(np.isnan(mat), axis=1)
        out[all_na] = np.nan
        return Val.frame(Frame([Column(f"which.{name}", out, ColType.NUM)]))


@prim("which.max")
def which_max(env, args):
    return _which_extreme(env, args, np.argmax, "max")


@prim("which.min")
def which_min(env, args):
    return _which_extreme(env, args, np.argmin, "min")
