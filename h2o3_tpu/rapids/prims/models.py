"""Model-valued rapids primitives.

Reference: ``water/rapids/ast/prims/models/`` — AstPerfectAUC,
AstModelResetThreshold, AstPermutationVarImp, AstSegmentModelsAsFrame.
These are the reference's rapids-only model operations (no REST route of
their own; clients reach them through ``/99/Rapids``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.runtime import Val


def _single_vec(v: Val, what: str) -> np.ndarray:
    fr = v.as_frame()
    if fr.ncols != 1:
        raise ValueError(
            f"Expected a frame containing a single vector of {what}. "
            f"Instead got {fr.ncols} columns")
    return fr.col(0).numeric_view()


def perfect_auc_values(probs: np.ndarray, acts: np.ndarray) -> float:
    """Exact (non-binned) AUC by sorting the full dataset
    (``hex/AUC2.java:589`` perfectAUC).  The reference walks sorted probs
    accumulating trapezoids with a diagonal across tied-probability runs;
    that is exactly the tie-averaged Mann-Whitney statistic, computed here
    with midranks in vectorized numpy."""
    acts = np.asarray(acts, np.float64)
    probs = np.asarray(probs, np.float64)
    if np.any(np.isnan(acts)) or np.any(acts < 0) or np.any(acts > 1) \
            or np.any(acts != np.floor(acts)):
        raise ValueError("Actuals are either 0 or 1")
    if np.any(np.isnan(probs)) or np.any(probs < 0) or np.any(probs > 1):
        raise ValueError("Probabilities are between 0 and 1")
    pos = acts == 1.0
    n_pos = int(pos.sum())
    n_neg = len(acts) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0 if n_pos == 0 else 1.0
    order = np.argsort(probs, kind="stable")
    sp = probs[order]
    # midranks: average 1-based rank over each tied run
    starts = np.concatenate(([0], np.flatnonzero(sp[1:] != sp[:-1]) + 1))
    ends = np.concatenate((starts[1:], [len(sp)]))
    run_rank = (starts + ends + 1) / 2.0  # mean of ranks start+1..end
    ranks = np.empty(len(sp))
    ranks[order] = np.repeat(run_rank, ends - starts)
    u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


@prim("perfectAUC")
def _perfect_auc(env, args: List[Val]) -> Val:
    """(perfectAUC probs acts) — AstPerfectAUC."""
    probs = _single_vec(args[0], "probabilities")
    acts = _single_vec(args[1], "actuals")
    auc = perfect_auc_values(probs, acts)
    return Val.frame(Frame([Column("C1", np.array([auc]), ColType.NUM)]))


@prim("model.reset.threshold")
def _reset_threshold(env, args: List[Val]) -> Val:
    """(model.reset.threshold model threshold) — AstModelResetThreshold:
    set the model's classification threshold, return the old one."""
    model = args[0].as_model()
    new_thr = args[1].as_num()
    old = model.reset_threshold(new_thr)
    return Val.frame(Frame([Column("C1", np.array([old]), ColType.NUM)]))


@prim("segment_models_as_frame")
def _segment_models_as_frame(env, args: List[Val]) -> Val:
    """(segment_models_as_frame id) — AstSegmentModelsAsFrame."""
    from h2o3_tpu.models.segments import SegmentModels

    v = args[0]
    obj = v.value
    if v.kind == Val.STR:
        from h2o3_tpu.keyed import DKV

        obj = DKV.get(v.value)
    if not isinstance(obj, SegmentModels):
        raise TypeError(f"expected a SegmentModels id, got {v!r}")
    return Val.frame(obj.as_frame())


# ---------------------------------------------------------------------------
# Permutation variable importance (water/rapids/PermutationVarImp.java)

#: metrics getPermutationVarImp accepts, lowercase (ModelMetrics fields)
_PVI_METRICS = {"auc", "pr_auc", "logloss", "mse", "rmse", "mae", "rmsle",
                "mean_per_class_error", "r2"}


def _metric_of(mm, metric: str) -> float:
    v = getattr(mm, metric, None)
    if v is None or (isinstance(v, float) and np.isnan(v)):
        raise ValueError(
            f"Model doesn't support the following metric {metric}")
    return float(v)


def _infer_metric(model, metric: str) -> str:
    """'auto' -> auc (binomial) / rmse (regression) / logloss (multinomial)
    (PermutationVarImp.inferAndValidateMetric)."""
    metric = metric.lower()
    if metric == "auto":
        if not model.is_classifier:
            return "rmse"
        return "auc" if model.nclasses == 2 else "logloss"
    if metric not in _PVI_METRICS:
        raise ValueError(
            f"Permutation Variable Importance doesn't support {metric}")
    return metric


def permutation_var_imp(
    model, fr: Frame, metric: str = "auto", n_samples: int = -1,
    n_repeats: int = 1, features: Optional[List[str]] = None,
    seed: int = -1,
) -> Frame:
    """One-feature-at-a-time shuffle importance
    (``water/rapids/PermutationVarImp.java:98`` calculatePermutationVarImp):
    score the frame, then for each predictor shuffle its column, rescore,
    and record |metric - baseline|.  n_repeats=1 yields the
    relative/scaled/percentage table (ModelMetrics.calcVarImp); >1 yields
    one column per run, rows ordered by the first run's importance."""
    metric = _infer_metric(model, metric)
    if n_samples < -1 or n_samples in (0, 1) or n_samples > fr.nrows:
        raise ValueError(
            "Argument n_samples has to be either -1 to use the whole frame "
            "or greater than 2 and lower than or equal to the number of "
            "rows of the provided frame!")
    if n_repeats < 1:
        raise ValueError("Argument n_repeats must be greater than 0!")

    names = fr.names
    non_pred = {model.params.response_column,
                getattr(model.params, "weights_column", None),
                getattr(model.params, "offset_column", None),
                getattr(model.params, "fold_column", None)}
    non_pred |= set(getattr(model.params, "ignored_columns", None) or [])
    if features:
        missing = [f for f in features if f not in names]
        if missing:
            raise ValueError(
                "Features " + ", ".join(missing) +
                " are not present in the provided frame!")
        not_used = [f for f in features
                    if f not in model.data_info.predictor_names]
        if not_used:
            raise ValueError(
                "Features " + ", ".join(not_used) +
                " weren't used for training!")
        todo = set(features) - non_pred
    else:
        # the model's predictors, not the frame's columns: an extra
        # non-predictor column (id/join key) must not be shuffled and
        # rescored (PermutationVarImp iterates the model's features)
        todo = (set(model.data_info.predictor_names) & set(names)) - non_pred

    runs: List[Dict[str, float]] = []
    full_base: Optional[float] = None
    for rep in range(n_repeats):
        rep_seed = None if seed == -1 else seed + rep
        rng = np.random.default_rng(rep_seed)
        if n_samples > 1:
            # without replacement, like MRUtils.sampleFrame — a duplicated
            # row would double-weight its metric contribution
            idx = rng.choice(fr.nrows, size=n_samples, replace=False)
            sub = fr.rows(idx)
            base = _metric_of(model.model_performance(sub), metric)
        else:
            sub = fr
            if full_base is None:  # same frame every repeat: score once
                full_base = _metric_of(
                    model.model_performance(sub), metric)
            base = full_base
        result: Dict[str, float] = {}
        cols = list(sub.columns)
        for j, name in enumerate(sub.names):
            if name not in todo:
                continue
            orig = cols[j]
            shuf = orig.copy()
            shuf.data = shuf.data[rng.permutation(len(shuf.data))]
            cols[j] = shuf
            mm = model.model_performance(Frame(cols))
            result[name] = abs(_metric_of(mm, metric) - base)
            cols[j] = orig
        runs.append(result)

    feats = sorted(runs[0], key=runs[0].get, reverse=True)
    var_col = Column("Variable", np.asarray(feats, dtype=object), ColType.STR)
    if n_repeats == 1:
        imp = np.array([runs[0][f] for f in feats])
        mx, tot = imp.max() if len(imp) else 1.0, imp.sum()
        return Frame([
            var_col,
            Column("Relative Importance", imp, ColType.NUM),
            Column("Scaled Importance",
                   imp / mx if mx else imp, ColType.NUM),
            Column("Percentage", imp / tot if tot else imp, ColType.NUM),
        ])
    cols = [var_col]
    for rep in range(n_repeats):
        cols.append(Column(f"Run {rep + 1}",
                           np.array([runs[rep][f] for f in feats]),
                           ColType.NUM))
    return Frame(cols)


@prim("PermutationVarImp")
def _permutation_var_imp(env, args: List[Val]) -> Val:
    """(PermutationVarImp model frame metric n_samples n_repeats features
    seed) — AstPermutationVarImp."""
    model = args[0].as_model()
    fr = args[1].as_frame()
    metric = args[2].as_str()
    n_samples = args[3].as_int()
    n_repeats = args[4].as_int()
    features = None
    if args[5].kind == Val.STRS and args[5].value:
        features = args[5].as_strs()
    elif args[5].kind == Val.STR and args[5].value:
        features = [args[5].as_str()]
    seed = args[6].as_int()
    return Val.frame(permutation_var_imp(
        model, fr, metric, n_samples, n_repeats, features, seed))
