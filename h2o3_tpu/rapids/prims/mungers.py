"""Rapids mungers (40): slicing, binding, factors, group-by, reshape.

Reference: ``water/rapids/ast/prims/mungers/`` (SURVEY.md App. A list).
"""

from __future__ import annotations

from typing import List

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame, NA_CAT
from h2o3_tpu.rapids import groupby as G
from h2o3_tpu.rapids import merge as MG
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import col_indices, numeric_data, row_indices
from h2o3_tpu.rapids.runtime import RapidsError, Val, apply_fun


# -- shape / names -----------------------------------------------------------
@prim("nrow")
def nrow(env, args):
    return Val.num(args[0].as_frame().nrows)


@prim("ncol")
def ncol(env, args):
    return Val.num(args[0].as_frame().ncols)


@prim("colnames")
def colnames(env, args):
    return Val.strs(args[0].as_frame().names)


@prim("colnames=")
def colnames_set(env, args):
    """(colnames= fr [idxs] [names]) — AstColNames assignment form."""
    fr = args[0].as_frame()
    idxs = col_indices(fr, args[1])
    names = args[2].as_strs()
    mapping = {fr.names[i]: n for i, n in zip(idxs, names)}
    return Val.frame(fr.rename(mapping))


@prim("rename")
def rename(env, args):
    fr = args[0].as_frame()
    return Val.frame(fr.rename({args[1].as_str(): args[2].as_str()}))


# -- slicing -----------------------------------------------------------------
def _cols_fuse_args(ast_args):
    # a literal selector re-indexes columns statically inside a fused
    # program; computed selectors (frames, expressions) fall back
    from h2o3_tpu.rapids.parser import AstNum, AstNumList, AstStr, AstStrList

    return len(ast_args) == 2 and isinstance(
        ast_args[1], (AstNum, AstNumList, AstStr, AstStrList))


@prim("cols", "cols_py", fusible=True, kind="select",
      fuse_args=_cols_fuse_args)
def cols(env, args):
    fr = args[0].as_frame()
    return Val.frame(fr.cols([fr.names[i] for i in col_indices(fr, args[1])]))


@prim("rows")
def rows(env, args):
    if args[0].is_frame() and \
            getattr(args[0].value, "chunk_layout", None) is not None:
        from h2o3_tpu.rapids import dist_exec

        out = dist_exec.try_rows_dist(env, args)
        if out is not None:
            return out
    fr = args[0].as_frame()
    return Val.frame(fr.rows(row_indices(fr, args[1])))


@prim("flatten")
def flatten(env, args):
    """1x1 frame -> scalar (AstFlatten)."""
    fr = args[0].as_frame()
    if fr.nrows != 1 or fr.ncols != 1:
        return Val.frame(fr)
    c = fr.col(0)
    if c.type in (ColType.STR, ColType.UUID):
        return Val.str_(c.data[0] if c.data[0] is not None else "")
    if c.type is ColType.CAT:
        code = int(c.data[0])
        return Val.str_(c.domain[code]) if code >= 0 else Val.num(float("nan"))
    return Val.num(float(c.data[0]))


@prim("getrow")
def getrow(env, args):
    """Single-row frame -> ROW val (AstGetrow)."""
    fr = args[0].as_frame()
    if fr.nrows != 1:
        raise RapidsError(f"getrow: frame has {fr.nrows} rows, want 1")
    vals = [float(c.numeric_view()[0]) if c.type not in (ColType.STR, ColType.UUID) else float("nan") for c in fr.columns]
    return Val.row(vals, fr.names)


@prim("columnsByType")
def columns_by_type(env, args):
    """(columnsByType fr type) -> indices; type in numeric|categorical|string|
    time|uuid|bad (AstColumnsByType)."""
    fr = args[0].as_frame()
    want = args[1].as_str().lower()
    sel = {
        "numeric": lambda c: c.type is ColType.NUM,
        "categorical": lambda c: c.type is ColType.CAT,
        "string": lambda c: c.type is ColType.STR,
        "time": lambda c: c.type is ColType.TIME,
        "uuid": lambda c: c.type is ColType.UUID,
        "bad": lambda c: c.type is ColType.BAD,
    }.get(want)
    if sel is None:
        raise RapidsError(f"columnsByType: unknown type {want!r}")
    return Val.nums([float(i) for i, c in enumerate(fr.columns) if sel(c)])


# -- bind --------------------------------------------------------------------
@prim("cbind")
def cbind(env, args):
    out = args[0].as_frame()
    for v in args[1:]:
        f = v.as_frame()
        if f.nrows == 1 and out.nrows > 1:  # scalar recycle
            f = Frame([Column(c.name, np.repeat(c.data, out.nrows), c.type, c.domain) for c in f.columns])
        out = out.cbind(f)
    return Val.frame(out)


@prim("rbind")
def rbind(env, args):
    out = args[0].as_frame()
    for v in args[1:]:
        out = out.rbind(v.as_frame())
    return Val.frame(out)


# -- factor / type predicates ------------------------------------------------
# metadata-only prims go through col_types() (the layout on a DistFrame)
# so a types query over a chunk-homed frame never gathers its chunks
@prim("is.factor")
def is_factor(env, args):
    fr = args[0].as_frame()
    return Val.nums([float(t is ColType.CAT) for t in fr.col_types()])


@prim("is.numeric")
def is_numeric(env, args):
    fr = args[0].as_frame()
    return Val.nums([float(t in (ColType.NUM, ColType.TIME))
                     for t in fr.col_types()])


@prim("is.character")
def is_character(env, args):
    fr = args[0].as_frame()
    return Val.nums([float(t is ColType.STR) for t in fr.col_types()])


@prim("anyfactor")
def anyfactor(env, args):
    fr = args[0].as_frame()
    return Val.num(float(any(t is ColType.CAT for t in fr.col_types())))


@prim("as.factor")
def as_factor(env, args):
    fr = args[0].as_frame()
    return Val.frame(Frame([c.as_factor() for c in fr.columns]))


@prim("as.numeric")
def as_numeric(env, args):
    fr = args[0].as_frame()
    return Val.frame(Frame([c.as_numeric() for c in fr.columns]))


@prim("as.character")
def as_character(env, args):
    fr = args[0].as_frame()
    cols = []
    for c in fr.columns:
        if c.type is ColType.CAT:
            dom = np.asarray(c.domain + [None], dtype=object)
            data = dom[np.where(c.data >= 0, c.data, len(c.domain))]
        elif c.type in (ColType.STR, ColType.UUID):
            data = c.data.copy()
        else:
            data = np.array(
                [None if np.isnan(v) else (str(int(v)) if float(v).is_integer() else repr(v)) for v in c.data],
                dtype=object,
            )
        cols.append(Column(c.name, data, ColType.STR))
    return Val.frame(Frame(cols))


@prim("levels")
def levels(env, args):
    fr = args[0].as_frame()
    doms = [c.domain or [] for c in fr.columns]
    return Val.strs(doms[0]) if fr.ncols == 1 else Val(Val.STRS, [lv for d in doms for lv in d])


@prim("nlevels")
def nlevels(env, args):
    fr = args[0].as_frame()
    return Val.nums([float(c.cardinality() if c.type is ColType.CAT else 0) for c in fr.columns])


@prim("setLevel")
def set_level(env, args):
    """(setLevel fr level) — set all rows of a CAT col to one level (AstSetLevel)."""
    fr = args[0].as_frame()
    lvl = args[1].as_str()
    c = fr.col(0)
    if c.type is not ColType.CAT or lvl not in c.domain:
        raise RapidsError(f"setLevel: {lvl!r} not a level of {c.name!r}")
    code = c.domain.index(lvl)
    return Val.frame(
        Frame([Column(c.name, np.full(len(c), code, dtype=np.int32), ColType.CAT, c.domain)])
    )


@prim("setDomain")
def set_domain(env, args):
    """(setDomain fr inPlace [levels]) — replace the CAT domain (AstSetDomain)."""
    fr = args[0].as_frame()
    new_dom = args[-1].as_strs()
    c = fr.col(0)
    if c.type is not ColType.CAT:
        raise RapidsError("setDomain: not a categorical column")
    if len(new_dom) < c.cardinality():
        raise RapidsError("setDomain: fewer levels than existing domain")
    return Val.frame(Frame([Column(c.name, c.data.copy(), ColType.CAT, list(new_dom))]))


@prim("relevel")
def relevel(env, args):
    """(relevel fr level) — move level to front (AstReLevel)."""
    fr = args[0].as_frame()
    lvl = args[1].as_str()
    c = fr.col(0)
    if c.type is not ColType.CAT or lvl not in c.domain:
        raise RapidsError(f"relevel: {lvl!r} not a level")
    old = c.domain
    new_dom = [lvl] + [d for d in old if d != lvl]
    remap = np.array([new_dom.index(d) for d in old], dtype=np.int32)
    codes = np.where(c.data >= 0, remap[np.clip(c.data, 0, None)], NA_CAT).astype(np.int32)
    return Val.frame(Frame([Column(c.name, codes, ColType.CAT, new_dom)]))


# -- NA handling -------------------------------------------------------------
@prim("is.na", fusible=True, kind="uniop",
      emit=lambda jnp, x: jnp.isnan(x).astype(jnp.float64))
def is_na(env, args):
    fr = args[0].as_frame()
    return Val.frame(
        Frame([Column(c.name, c.isna().astype(np.float64), ColType.NUM) for c in fr.columns])
    )


@prim("na.omit")
def na_omit(env, args):
    return Val.frame(args[0].as_frame().na_omit())


@prim("filterNACols")
def filter_na_cols(env, args):
    """(filterNACols fr frac) -> indices of columns with <= frac NAs."""
    fr = args[0].as_frame()
    frac = args[1].as_num()
    keep = [
        float(i)
        for i, c in enumerate(fr.columns)
        if c.na_count() <= frac * fr.nrows
    ]
    return Val.nums(keep)


@prim("h2o.fillna")
def fillna(env, args):
    """(h2o.fillna fr method axis maxlen) — forward/backward fill (AstFillNA)."""
    fr = args[0].as_frame()
    method = args[1].as_str().lower() if len(args) > 1 else "forward"
    axis = int(args[2].as_num()) if len(args) > 2 else 0
    maxlen = int(args[3].as_num()) if len(args) > 3 else 1
    if axis != 0:
        # axis=1 fills across columns within each row: mat is [N, C] and
        # _fill_along fills along its second axis, so no transpose
        mat = np.stack([numeric_data(c) for c in fr.columns], axis=1)
        filled = _fill_along(mat, method, maxlen)
        return Val.frame(
            Frame([Column(c.name, filled[:, j], ColType.NUM) for j, c in enumerate(fr.columns)])
        )
    cols = []
    for c in fr.columns:
        d = numeric_data(c).copy()
        filled = _fill_along(d[None, :], method, maxlen)[0]
        if c.type is ColType.CAT:
            codes = np.where(np.isnan(filled), -1, filled).astype(np.int32)
            cols.append(Column(c.name, codes, ColType.CAT, c.domain))
        else:
            cols.append(Column(c.name, filled, c.type if c.type is ColType.TIME else ColType.NUM))
    return Val.frame(Frame(cols))


def _fill_along(mat: np.ndarray, method: str, maxlen: int) -> np.ndarray:
    out = mat.astype(np.float64).copy()
    rng = range(1, out.shape[1])
    backward = method.startswith("b")
    if backward:
        out = out[:, ::-1]
    run = np.zeros(out.shape[0], dtype=np.int64)
    for j in range(1, out.shape[1]):
        nan = np.isnan(out[:, j])
        run = np.where(nan, run + 1, 0)
        can = nan & (run <= maxlen)
        out[can, j] = out[can, j - 1]
    return out[:, ::-1] if backward else out


# -- cut / scale -------------------------------------------------------------
@prim("cut")
def cut(env, args):
    """(cut fr [breaks] [labels] include_lowest right digits) (AstCut)."""
    fr = args[0].as_frame()
    breaks = args[1].as_nums()
    labels = args[2].as_strs() if len(args) > 2 and args[2].kind in (Val.STRS, Val.STR) else []
    include_lowest = bool(args[3].as_num()) if len(args) > 3 else False
    right = bool(args[4].as_num()) if len(args) > 4 else True
    digits = int(args[5].as_num()) if len(args) > 5 else 3
    c = fr.col(0)
    d = numeric_data(c)
    if right:
        codes = np.searchsorted(breaks, d, side="left") - 1
        if include_lowest:
            codes[d == breaks[0]] = 0
    else:
        codes = np.searchsorted(breaks, d, side="right") - 1
    codes = codes.astype(np.int32)
    bad = np.isnan(d) | (codes < 0) | (codes >= len(breaks) - 1)
    codes[bad] = NA_CAT
    if not labels:
        fmt = lambda v: f"{round(float(v), digits):g}"
        lb, rb = ("(", "]") if right else ("[", ")")
        labels = [f"{lb}{fmt(breaks[i])},{fmt(breaks[i+1])}{rb}" for i in range(len(breaks) - 1)]
        if include_lowest and right:
            labels[0] = "[" + labels[0][1:]
    return Val.frame(Frame([Column(c.name, codes, ColType.CAT, list(labels))]))


@prim("scale")
def scale(env, args):
    """(scale fr center scale) — center/scale numeric columns (AstScale);
    center/scale may be booleans or per-column number lists."""
    fr = args[0].as_frame()

    def resolve(v, default_fn):
        if v.kind == Val.NUMS:
            return v.value
        flag = bool(v.as_num())
        return default_fn() if flag else None

    cols = [c for c in fr.columns]
    num_idx = [i for i, c in enumerate(cols) if c.type is ColType.NUM]
    if not num_idx:
        return Val.frame(fr)
    mat = np.stack([numeric_data(cols[i]) for i in num_idx], axis=1)
    center = resolve(args[1], lambda: np.nanmean(mat, axis=0))
    scl = resolve(args[2], lambda: np.nanstd(mat, axis=0, ddof=1))
    out = list(cols)
    if mat is not None:
        m = mat
        if center is not None:
            m = m - np.asarray(center)[None, :]
        if scl is not None:
            s = np.asarray(scl, dtype=np.float64).copy()
            s[s == 0] = 1.0
            m = m / s[None, :]
        for k, i in enumerate(num_idx):
            out[i] = Column(cols[i].name, m[:, k], ColType.NUM)
    return Val.frame(Frame(out))


# -- group-by ----------------------------------------------------------------
_AGG_NAMES = set(G.AGGS)


@prim("GB")
def gb(env, args):
    """(GB fr [by] agg col na agg col na ...) (AstGroup)."""
    fr = args[0].as_frame()
    by = [int(i) for i in args[1].as_nums()]
    aggs = []
    i = 2
    while i < len(args):
        agg = args[i].as_str()
        col = int(args[i + 1].as_num()) if not args[i + 1].is_str() else fr.names.index(args[i + 1].as_str())
        na = args[i + 2].as_str() if i + 2 < len(args) and args[i + 2].is_str() else "all"
        aggs.append((agg, col, na))
        i += 3
    grouped = G.group_by(fr, by, aggs)
    # reference returns groups sorted by key — group_by already emits sorted
    return Val.frame(grouped)


@prim("ddply")
def ddply(env, args):
    """(ddply fr [by] fun) — split-apply-combine with a lambda per group."""
    fr = args[0].as_frame()
    by = [int(i) for i in args[1].as_nums()]
    fun = args[2]
    if not fun.is_fun():
        raise RapidsError("ddply: third arg must be a lambda")
    order, starts, _ = G.group_keys(fr, by)
    bounds = np.append(starts, fr.nrows)
    key_cols = [fr.col(j) for j in by]
    out_rows: List[List[float]] = []
    for g in range(len(starts)):
        rows_g = order[bounds[g] : bounds[g + 1]]
        sub = fr.rows(rows_g)
        res = apply_fun(fun, [Val.frame(sub)], env)
        if res.is_frame():
            vals = [float(c.numeric_view()[0]) for c in res.value.columns]
        elif res.kind == Val.NUMS:
            vals = [float(x) for x in res.value]
        elif res.kind == Val.ROW:
            vals = [float(x) for x in res.value[0]]
        else:
            vals = [res.as_num()]
        keys = [c.numeric_view()[rows_g[0]] for c in key_cols]
        out_rows.append(keys + vals)
    arr = np.asarray(out_rows, dtype=np.float64)
    names = [c.name for c in key_cols] + [f"ddply_C{i+1}" for i in range(arr.shape[1] - len(by))]
    return Val.frame(Frame([Column(n, arr[:, j], ColType.NUM) for j, n in enumerate(names)]))


@prim("rankWithinGroupBy", "rank_within_groupby")
def rank_within(env, args):
    fr = args[0].as_frame()
    by = [int(i) for i in args[1].as_nums()]
    sort_cols = [int(i) for i in args[2].as_nums()]
    asc = [bool(b) for b in args[3].as_nums()] if len(args) > 3 else [True] * len(sort_cols)
    new_col = args[4].as_str() if len(args) > 4 else "New_Rank_column"
    return Val.frame(G.rank_within_group_by(fr, by, sort_cols, asc, new_col))


# -- merge / sort ------------------------------------------------------------
@prim("merge")
def merge(env, args):
    """(merge left right all_left all_right [by_left] [by_right] method)."""
    left, right = args[0].as_frame(), args[1].as_frame()
    all_left = bool(args[2].as_num()) if len(args) > 2 else False
    all_right = bool(args[3].as_num()) if len(args) > 3 else False
    if len(args) > 4 and len(args[4].as_nums()):
        by_left = [int(i) for i in args[4].as_nums()]
        by_right = [int(i) for i in args[5].as_nums()]
    else:  # default: join on identically named columns
        common = [n for n in left.names if n in right.names]
        if not common:
            raise RapidsError("merge: no common columns")
        by_left = [left.names.index(n) for n in common]
        by_right = [right.names.index(n) for n in common]
    return Val.frame(MG.merge_frames(left, right, by_left, by_right, all_left, all_right))


@prim("sort")
def sort_(env, args):
    fr = args[0].as_frame()
    by = [int(i) for i in args[1].as_nums()]
    asc = [bool(b) for b in args[2].as_nums()] if len(args) > 2 else [True] * len(by)
    return Val.frame(MG.sort_frame(fr, by, asc))


# -- reshape -----------------------------------------------------------------
@prim("melt")
def melt(env, args):
    """(melt fr [id_idx] [value_idx] var_name value_name skipna) (AstMelt)."""
    fr = args[0].as_frame()
    id_idx = [int(i) for i in args[1].as_nums()]
    val_idx = [int(i) for i in args[2].as_nums()] if len(args) > 2 and len(args[2].as_nums()) else [
        i for i in range(fr.ncols) if i not in id_idx
    ]
    var_name = args[3].as_str() if len(args) > 3 else "variable"
    value_name = args[4].as_str() if len(args) > 4 else "value"
    skipna = bool(args[5].as_num()) if len(args) > 5 else False
    n, k = fr.nrows, len(val_idx)
    id_cols = []
    for j in id_idx:
        c = fr.col(j)
        id_cols.append(Column(c.name, np.tile(c.data, k), c.type, c.domain))
    var_domain = [fr.names[j] for j in val_idx]
    var_codes = np.repeat(np.arange(k, dtype=np.int32), n)
    vals = np.concatenate([numeric_data(fr.col(j)) for j in val_idx])
    out = Frame(
        id_cols
        + [
            Column(var_name, var_codes, ColType.CAT, var_domain),
            Column(value_name, vals, ColType.NUM),
        ]
    )
    if skipna:
        out = out.rows(~np.isnan(vals))
    return Val.frame(out)


@prim("pivot")
def pivot(env, args):
    """(pivot fr index column value) (AstPivot)."""
    fr = args[0].as_frame()
    def _col(v):
        return fr.names.index(v.as_str()) if v.is_str() else int(v.as_num())
    ji, jc, jv = _col(args[1]), _col(args[2]), _col(args[3])
    index_c, col_c, val_c = fr.col(ji), fr.col(jc), fr.col(jv)
    idx_vals = index_c.numeric_view()
    uniq_idx, idx_codes = np.unique(idx_vals, return_inverse=True)
    if col_c.type is ColType.CAT:
        col_names = list(col_c.domain)
        col_codes = col_c.data.astype(np.int64)
    else:
        u, col_codes = np.unique(col_c.numeric_view(), return_inverse=True)
        col_names = [f"{v:g}" for v in u]
    out = np.full((len(uniq_idx), len(col_names)), np.nan)
    vals = val_c.numeric_view()
    ok = col_codes >= 0
    out[idx_codes[ok], col_codes[ok]] = vals[ok]
    cols = [Column(index_c.name, uniq_idx, ColType.NUM)]
    for j, name in enumerate(col_names):
        cols.append(Column(name, out[:, j], ColType.NUM))
    return Val.frame(Frame(cols))


@prim("apply")
def apply_(env, args):
    """(apply fr margin fun) — margin 1=rows, 2=cols (AstApply)."""
    fr = args[0].as_frame()
    margin = int(args[1].as_num())
    fun = args[2]
    if not fun.is_fun():
        raise RapidsError("apply: third arg must be a function")
    if margin == 2:
        out_cols = []
        for c in fr.columns:
            res = apply_fun(fun, [Val.frame(Frame([c]))], env)
            rf = res.as_frame()
            rc = rf.col(0)
            out_cols.append(Column(c.name, rc.data, rc.type, rc.domain))
        return Val.frame(Frame(out_cols))
    # margin 1: per-row apply. The row binds as ONE COLUMN of its values
    # (the reference's AstApply row binding): reducers then collapse
    # across the row to a scalar, and elementwise arithmetic yields the
    # transformed row values
    mat = np.stack([numeric_data(c) for c in fr.columns], axis=1)
    out_rows = []
    for i in range(fr.nrows):
        row_fr = Frame([Column("C1", mat[i].astype(np.float64), ColType.NUM)])
        res = apply_fun(fun, [Val.frame(row_fr)], env)
        if res.is_frame():
            rf = res.as_frame()
            out_rows.append([float(v) for v in rf.col(0).numeric_view()])
        elif res.kind == Val.NUMS:
            out_rows.append([float(v) for v in res.as_nums()])
        else:
            out_rows.append([res.as_num()])
    arr = np.asarray(out_rows)
    return Val.frame(
        Frame([Column(f"C{j+1}", arr[:, j], ColType.NUM) for j in range(arr.shape[1])])
    )


@prim("dropdup", "dropduplicates")
def dropdup(env, args):
    """(dropdup fr [cols] keep) — drop duplicate rows (AstDropDuplicates)."""
    fr = args[0].as_frame()
    by = [int(i) for i in args[1].as_nums()] if len(args) > 1 else list(range(fr.ncols))
    keep = args[2].as_str() if len(args) > 2 else "first"
    order, starts, _ = G.group_keys(fr, by)
    bounds = np.append(starts, fr.nrows)
    picks = order[starts] if keep == "first" else order[bounds[1:] - 1]
    return Val.frame(fr.rows(np.sort(picks)))


@prim("mojo.pipeline.transform")
def mojo_pipeline_transform(env, args):
    """(mojo.pipeline.transform pipeline frame allowTimestamps) — score a
    frame through a ScoringPipeline (rapids/AstPipelineTransform.java; the
    allowTimestamps flag is accepted for signature parity — this build's
    pipelines carry time columns as numerics, so nothing is gated on it)."""
    from h2o3_tpu.keyed import DKV
    from h2o3_tpu.models.pipeline import ScoringPipeline

    key = args[0].as_str()
    pipe = DKV.get(key)
    if not isinstance(pipe, ScoringPipeline):
        raise RapidsError(f"no pipeline {key!r}")
    fr = args[1].as_frame()
    try:
        return Val.frame(pipe.transform(fr))
    except ValueError as e:
        raise RapidsError(str(e))


@prim("grouped_permute")
def grouped_permute(env, args):
    """(grouped_permute fr permCol groupBy permuteBy keepCol)
    (AstGroupedPermute): within each group (first groupBy column), rows
    split by whether the permuteBy categorical's level is "D"; the two
    sides' (permCol id -> summed keepCol amount) maps are crossed into
    [group, In, Out, InAmnt, OutAmnt] rows — all D-side x other-side
    combinations, first-seen id order, duplicate ids merging amounts."""
    fr = args[0].as_frame()
    perm_col = int(args[1].as_num())
    by = [int(i) for i in args[2].as_nums()]
    permute_by = int(args[3].as_num())
    keep_col = int(args[4].as_num())

    gb_col = fr.col(by[0])
    gid = numeric_data(gb_col)
    pb = fr.col(permute_by)
    if pb.domain is None:
        raise RapidsError("grouped_permute: permuteBy must be categorical")
    is_d = np.array([
        pb.domain[int(c)] == "D" if c >= 0 else False for c in pb.data
    ])
    rid = numeric_data(fr.col(perm_col))
    amt = numeric_data(fr.col(keep_col))

    # per group, per side: insertion-ordered rid -> summed amount.
    # NaN keys canonicalize to one sentinel: the reference's
    # HashMap<Double> treats NaN as equal to itself, so NA groups merge
    def canon(v: float):
        return "__nan__" if np.isnan(v) else float(v)

    groups: dict = {}
    for i in range(fr.nrows):
        sides = groups.setdefault(canon(gid[i]), ({}, {}))
        side = sides[0] if is_d[i] else sides[1]
        side[canon(rid[i])] = side.get(canon(rid[i]), 0.0) + amt[i]

    rows = []
    for key, (d_side, c_side) in groups.items():
        k = np.nan if key == "__nan__" else key
        for r0, a0 in d_side.items():
            for r1, a1 in c_side.items():
                rows.append((k,
                             np.nan if r0 == "__nan__" else r0,
                             np.nan if r1 == "__nan__" else r1,
                             a0, a1))
    out = np.array(rows, dtype=np.float64).reshape(-1, 5)

    def col(name, vals, src):
        if src.domain is not None:
            codes = np.where(np.isnan(vals), -1, vals).astype(np.int32)
            return Column(name, codes, ColType.CAT, list(src.domain))
        return Column(name, vals, ColType.NUM)

    return Val.frame(Frame([
        col(fr.names[by[0]], out[:, 0], gb_col),
        col("In", out[:, 1], fr.col(perm_col)),
        col("Out", out[:, 2], fr.col(perm_col)),
        Column("InAmnt", out[:, 3], ColType.NUM),
        Column("OutAmnt", out[:, 4], ColType.NUM),
    ]))
