"""Rapids assignment prims.

Reference: ``water/rapids/ast/prims/assign/`` — Append Assign RectangleAssign
Rm TmpAssign (+RecAsgnHelper).  ``tmp=`` and ``=`` are special forms handled
by the evaluator (h2o3_tpu/rapids/runtime.py); the rest live here.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame, NA_CAT
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import col_indices, numeric_data, row_indices
from h2o3_tpu.rapids.runtime import RapidsError, Val


@prim("append")
def append(env, args):
    """(append fr col name) — add/replace a column (AstAppend)."""
    fr = args[0].as_frame()
    src = args[1]
    name = args[2].as_str()
    if src.is_frame():
        c = src.value.col(0).copy()
        if len(c) == 1 and fr.nrows > 1:
            c = Column(name, np.repeat(c.data, fr.nrows), c.type, c.domain)
    else:
        c = Column(name, np.full(max(fr.nrows, 1), src.as_num()), ColType.NUM)
    c.name = name
    return Val.frame(fr.add_column(c))


@prim("rm")
def rm(env, args):
    """(rm key) — delete from the session/DKV (AstRm)."""
    from h2o3_tpu.keyed import DKV

    key = args[0].as_str() if args[0].is_str() else None
    if key is None and args[0].is_frame():
        key = args[0].value.key
    if key:
        env.session.remove(key)
    return Val.num(0)


@prim(":=")
def rectangle_assign(env, args):
    """(:= dst src [col_idxs] [row_idxs]) — rectangle assign into a copy of
    dst (AstRecAsgn; rapids frames are immutable-by-copy here, the reference
    does copy-on-write at the chunk level)."""
    if args[0].is_frame() and \
            getattr(args[0].value, "chunk_layout", None) is not None:
        from h2o3_tpu.rapids import dist_exec

        out = dist_exec.try_assign_dist(env, args)
        if out is not None:
            return out
    dst = args[0].as_frame()
    src = args[1]
    cidx = col_indices(dst, args[2])
    rsel = args[3]
    all_rows = rsel.is_num() and np.isnan(rsel.as_num())
    ridx = np.arange(dst.nrows) if all_rows else row_indices(dst, rsel)
    out_cols = [c.copy() for c in dst.columns]
    for k, j in enumerate(cidx):
        c = out_cols[j]
        if src.is_frame():
            s = src.value.col(k if src.value.ncols > 1 else 0)
            svals = s.data if len(s.data) != 1 else np.repeat(s.data, len(ridx))
            if c.type is ColType.CAT and s.type is ColType.CAT:
                if c.domain == s.domain:
                    c.data[ridx] = svals
                else:
                    remap = {lv: i for i, lv in enumerate(c.domain)}
                    mapped = np.array(
                        [remap.get(s.domain[v], NA_CAT) if v >= 0 else NA_CAT for v in svals],
                        dtype=np.int32,
                    )
                    c.data[ridx] = mapped
            elif c.type in (ColType.STR, ColType.UUID):
                c.data[ridx] = svals
            else:
                out_cols[j] = Column(c.name, _assign_num(c, ridx, np.asarray(svals, dtype=np.float64)), ColType.NUM)
        elif src.is_str():
            if c.type is ColType.CAT:
                s = src.as_str()
                if s not in c.domain:
                    c.domain = c.domain + [s]
                c.data[ridx] = c.domain.index(s)
            elif c.type in (ColType.STR, ColType.UUID):
                c.data[ridx] = src.as_str()
            else:
                raise RapidsError("cannot assign string into numeric column")
        else:
            v = src.as_num()
            if c.type is ColType.CAT:
                c.data[ridx] = NA_CAT if np.isnan(v) else np.int32(v)
            else:
                out_cols[j] = Column(c.name, _assign_num(c, ridx, v), ColType.NUM)
        out_cols[j].invalidate_rollups()
    return Val.frame(Frame(out_cols))


def _assign_num(c: Column, ridx, vals) -> np.ndarray:
    d = numeric_data(c).copy()
    d[ridx] = vals
    return d
